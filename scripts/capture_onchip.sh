#!/usr/bin/env bash
# capture_onchip.sh — run the bench suite against a REAL accelerator and
# refuse to publish anything measured on the CPU fallback.
#
# The axon tunnel fails soft: when the backend is down, jax silently hands
# back CpuDevice and every "TPU" number in the artifact is actually a Xeon.
# `bench.py --require-onchip` turns that into a hard exit(3); this wrapper
# adds round bookkeeping so a capture lands as BENCH_<round>.json plus the
# per-stage checkpoint under benches/.
#
# Usage:
#   scripts/capture_onchip.sh [round] [extra bench.py args...]
#   PILOSA_BENCH_STAGES=kernels scripts/capture_onchip.sh r09
#
# Env (all optional, forwarded to bench.py):
#   PILOSA_BENCH_STAGES      comma list to filter stages (e.g. kernels)
#   PILOSA_BENCH_DEADLINE_S  overall budget (default 1800)
#   PILOSA_BENCH_COMPARE     prior BENCH_*.json to gate against
set -euo pipefail

cd "$(dirname "$0")/.."

ROUND="${1:-${PILOSA_BENCH_ROUND:-}}"
if [ -n "${ROUND}" ]; then
    shift || true
    export PILOSA_BENCH_ROUND="${ROUND}"
fi

ARGS=(--require-onchip)
if [ -n "${PILOSA_BENCH_COMPARE:-}" ]; then
    ARGS+=(--compare "${PILOSA_BENCH_COMPARE}")
fi

echo "[capture] round=${PILOSA_BENCH_ROUND:-r08} stages=${PILOSA_BENCH_STAGES:-all}" >&2
if python bench.py "${ARGS[@]}" "$@"; then
    echo "[capture] on-chip artifact written" >&2
else
    rc=$?
    if [ "$rc" -eq 3 ]; then
        echo "[capture] FAILED: no accelerator (CpuDevice only) — nothing published" >&2
    else
        echo "[capture] FAILED: bench exited rc=$rc" >&2
    fi
    exit "$rc"
fi
