"""ICI-native slice-local serving (executor._ici_route; ROADMAP item 1).

When a query's full shard set is co-resident on the coordinator's slice
(the node holds a live, un-fenced replica of every shard), the executor
answers it as ONE sharded program over the mesh — shard_map + lax.psum on
the interconnect (parallel/mesh.py eval_count_mesh/eval_row_mesh) —
instead of HTTP scatter-gather. These tests cover:

  * the serving-mode kernels themselves (parity with the GSPMD jit forms,
    program-cache hit accounting, sharded-not-replicated results),
  * the multislice-mesh builder's silence on CPU/simulated topologies
    (the old noisy create_hybrid_device_mesh UserWarning),
  * routing decisions (off / write / no-mesh / partial residency / fence),
  * a LIVE mesh-backed cluster: slice-local queries answer the tier-1
    query mix with ZERO /internal/query-batch envelopes (netCoalesce
    counters), bit-identical to ici-serving=off, with the `route` node on
    ?profile=true and /debug/query-history,
  * a routing-parity fuzz: the tier-1 mix with interleaved writes
    churning generations, ici on vs off, byte-identical JSON results.
"""

import json
import time
import urllib.request
import warnings

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH, WORDS_PER_SHARD

SW = SHARD_WIDTH


def jpost(uri, path, raw=b"{}"):
    req = urllib.request.Request(uri + path, data=raw, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def jget(uri, path):
    with urllib.request.urlopen(uri + path, timeout=30) as r:
        return json.loads(r.read())


# ------------------------------------------------- serving-mode kernels


def test_serving_kernels_match_gspmd_forms():
    """eval_count_mesh / eval_row_mesh (explicit shard_map + psum) are
    bit-identical to the jit GSPMD forms, and the program cache counts
    hits/misses."""
    import jax

    from pilosa_tpu.parallel import mesh as pmesh

    mesh = pmesh.make_mesh(jax.devices())
    runner = pmesh.DeviceRunner(mesh)
    assert runner.ici_serving  # default-on with a mesh (PILOSA_TPU_ICI)
    rng = np.random.default_rng(7)
    a = rng.integers(0, 2**32, size=(8, WORDS_PER_SHARD), dtype=np.uint32)
    b = rng.integers(0, 2**32, size=(8, WORDS_PER_SHARD), dtype=np.uint32)
    la, lb = runner.put_leaf(a), runner.put_leaf(b)
    program = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 1))

    s0 = pmesh.ici_program_cache_stats()
    n = int(pmesh.eval_count_mesh(mesh, (la, lb), program))
    expect = int(np.bitwise_count((a | b) & ~b).sum())
    assert n == expect
    assert n == int(pmesh.eval_count_total((la, lb), program))

    row = np.asarray(pmesh.eval_row_mesh(mesh, (la, lb), program))
    assert (row == ((a | b) & ~b)).all()
    s1 = pmesh.ici_program_cache_stats()
    assert s1["misses"] >= s0["misses"] + 2  # count + row programs built
    int(pmesh.eval_count_mesh(mesh, (la, lb), program))  # repeat: a hit
    s2 = pmesh.ici_program_cache_stats()
    assert s2["hits"] >= s1["hits"] + 1
    assert s2["misses"] == s1["misses"]


def test_runner_routes_through_serving_kernels():
    """DeviceRunner with a mesh + ici_serving answers count/row via the
    shard_map forms; results stay sharded across the slice (never
    per-device-replicated) and parity holds against a non-serving
    runner."""
    import jax

    from pilosa_tpu.parallel import mesh as pmesh

    mesh = pmesh.make_mesh(jax.devices())
    on = pmesh.DeviceRunner(mesh)
    off = pmesh.DeviceRunner(mesh, ici_serving=False)
    assert not off.ici_serving
    rng = np.random.default_rng(11)
    a = rng.integers(0, 2**32, size=(6, WORDS_PER_SHARD), dtype=np.uint32)
    b = rng.integers(0, 2**32, size=(6, WORDS_PER_SHARD), dtype=np.uint32)
    program = ("xor", ("leaf", 0), ("not", ("leaf", 1)))
    leaves_on = [on.put_leaf(a), on.put_leaf(b)]
    leaves_off = [off.put_leaf(a), off.put_leaf(b)]
    assert on.count_total_leaves(leaves_on, program) == \
        off.count_total_leaves(leaves_off, program)
    dev = on.row_leaves_dev(leaves_on, program)
    spec = tuple(getattr(dev.sharding, "spec", ()))
    assert pmesh.SHARD_AXIS in spec, \
        f"serving-mode result not sharded across the slice: {spec}"
    assert (on.row_leaves(leaves_on, program, 6)
            == off.row_leaves(leaves_off, program, 6)).all()


def test_multislice_mesh_builds_silently_on_simulated_topology(monkeypatch):
    """Satellite: CPU devices carry no slice_index, so the hybrid-mesh
    attempt was GUARANTEED to fail — the builder now skips it up front
    instead of warning on every mesh build (the old noisy
    `create_hybrid_device_mesh failed ... TFRT_CPU_0 does not have
    attribute slice_index` UserWarning)."""
    import jax

    from pilosa_tpu.parallel import mesh as pmesh

    devs = jax.devices()
    monkeypatch.setattr(pmesh, "group_by_slice",
                        lambda ds: [list(ds[:4]), list(ds[4:])])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        m = pmesh.make_multislice_mesh(devs)
    assert m.axis_names == (pmesh.REPLICA_AXIS, pmesh.SHARD_AXIS)
    assert m.devices.shape == (2, 4)
    multislice = [w for w in caught if "multislice" in str(w.message)]
    assert multislice == [], [str(w.message) for w in multislice]


# ------------------------------------------------------- live cluster


@pytest.fixture(scope="module")
def ici_cluster(tmp_path_factory):
    """2-node replica-2 cluster — every shard co-resident on BOTH nodes —
    with a 4-device mesh on node 0 (the promoted MULTICHIP dryrun
    topology: a mesh-backed executor answering the tier-1 query mix in
    the real serving path, not the bench harness)."""
    import jax

    from pilosa_tpu.parallel.mesh import make_mesh
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("ici")
    mesh = make_mesh(jax.devices()[:4])
    servers = [
        Server(str(tmp / "n0"), port=0, replica_n=2, mesh=mesh,
               long_query_time=1e-9).open(),
        Server(str(tmp / "n1"), port=0, replica_n=2).open(),
    ]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    jpost(uris[0], "/index/i")
    jpost(uris[0], "/index/i/field/f")
    jpost(uris[0], "/index/i/field/g")
    jpost(uris[0], "/index/i/field/v",
          raw=json.dumps({"options": {"type": "int", "min": 0,
                                      "max": 1023}}).encode())
    rng = np.random.default_rng(13)
    n_shards, n_per = 6, 128
    sets: dict = {}
    row_ids, col_ids = [], []
    for shard in range(n_shards):
        for row in range(4):
            cols = (rng.choice(SW, size=n_per, replace=False)
                    .astype(np.int64) + shard * SW)
            sets[(row, shard)] = set(int(c) for c in cols)
            row_ids += [row] * n_per
            col_ids += cols.tolist()
    jpost(uris[0], "/index/i/field/f/import", raw=json.dumps(
        {"rowIDs": row_ids, "columnIDs": col_ids}).encode())
    jpost(uris[0], "/index/i/field/g/import", raw=json.dumps(
        {"rowIDs": [r % 2 for r in row_ids],
         "columnIDs": col_ids}).encode())
    vcols = [s * SW + k for s in range(n_shards) for k in range(48)]
    vvals = [int(rng.integers(0, 1024)) for _ in vcols]
    jpost(uris[0], "/index/i/field/v/import", raw=json.dumps(
        {"columnIDs": vcols, "values": vvals}).encode())

    # wait until node 1 (and the coordinator's view) converged on every
    # shard's availability — the same eventual visibility the cluster
    # tests poll for
    deadline = time.monotonic() + 30
    want = sum(len(sets[(0, s)] & sets[(1, s)]) for s in range(n_shards))
    for u in uris:
        while True:
            got = jpost(u, "/index/i/query",
                        raw=b"Count(Intersect(Row(f=0), Row(f=1)))")
            if got["results"][0] == want:
                break
            assert time.monotonic() < deadline, (u, got, want)
            time.sleep(0.2)
    data = {"sets": sets, "n_shards": n_shards, "vcols": vcols,
            "vvals": vvals}
    yield servers, uris, data
    for s in servers:
        s.close()


def _envelopes(ex) -> int:
    coal = ex.coalescer
    if coal is None:
        return 0
    s = coal.snapshot()
    return s["batches"] + s["fallback_queries"]


TIER1_MIX = [
    b"Count(Intersect(Row(f=0), Row(f=1)))",
    b"Count(Union(Row(f=2), Row(f=3)))",
    b"Intersect(Row(f=0), Row(f=2))",
    b"Union(Row(f=1), Difference(Row(f=3), Row(f=0)))",
    b"TopN(f, n=3)",
    b"TopN(f, Row(g=1), n=2)",
    b"Sum(Range(v > 511), field=v)",
    b"Min(field=v)",
    b"Max(field=v)",
    b"Rows(field=f)",
    b"GroupBy(Rows(field=g), Rows(field=f))",
    b"GroupBy(Rows(field=f), limit=3)",
]


def test_slice_local_serves_tier1_mix_with_zero_envelopes(ici_cluster):
    """THE acceptance path: on the mesh-backed coordinator every tier-1
    query whose shard set is co-resident executes as one sharded program
    — zero /internal/query-batch envelopes (netCoalesce counters), while
    ici-serving=off answers bit-identically over the HTTP plane."""
    servers, uris, data = ici_cluster
    ex = servers[0].executor
    assert ex.runner.mesh is not None and ex.runner.ici_serving
    ex.ici_mode = "auto"  # mesh present: auto routes slice-local

    results_on = {}
    env0 = _envelopes(ex)
    local0 = ex.ici_slice_local
    for q in TIER1_MIX:
        results_on[q] = jpost(uris[0], "/index/i/query", raw=q)["results"]
    assert _envelopes(ex) == env0, \
        "slice-local queries produced internal HTTP envelopes"
    assert ex.ici_slice_local >= local0 + len(TIER1_MIX)

    ex.ici_mode = "off"
    try:
        cross0 = ex.ici_fallback
        for q in TIER1_MIX:
            off = jpost(uris[0], "/index/i/query", raw=q)["results"]
            assert off == results_on[q], (q, off, results_on[q])
        assert ex.ici_fallback >= cross0 + len(TIER1_MIX)
        # the off-path actually exercised the wire (otherwise the
        # zero-envelope assertion above proves nothing)
        assert _envelopes(ex) > env0
    finally:
        ex.ici_mode = "auto"

    # spot-check correctness against host set algebra, not just parity
    sets, n_shards = data["sets"], data["n_shards"]
    want = sum(len(sets[(0, s)] & sets[(1, s)]) for s in range(n_shards))
    assert results_on[TIER1_MIX[0]][0] == want


def test_route_node_on_profile_and_history(ici_cluster):
    """The routing decision is part of the plan: a `route` node on
    ?profile=true and visible in /debug/query-history."""
    servers, uris, _ = ici_cluster
    servers[0].executor.ici_mode = "auto"
    out = jpost(uris[0], "/index/i/query?profile=true",
                raw=b"Count(Intersect(Row(f=0), Row(f=1)))")
    prof = out["profile"]
    assert prof["route"], prof.keys()
    node = prof["route"][0]
    assert node["route"] == "slice_local"
    assert node["reason"] == "co-resident"
    assert node["call"] == "Count"
    # the planner's plan node carries the same decision (plan.route)
    plan = prof["plan"][0]
    assert plan["route"]["route"] == "slice_local"
    # and the slow-query history (long_query_time=1e-9 records every
    # query on node 0) serializes the same tree
    hist = jget(uris[0], "/debug/query-history")["queries"]
    with_route = [h for h in hist
                  if h.get("profile") and h["profile"].get("route")]
    assert with_route, "no history entry carries a route node"


def test_observability_counters(ici_cluster):
    """/debug/vars iciServing block + unconditional /metrics families +
    telemetry gauges."""
    servers, uris, _ = ici_cluster
    servers[0].executor.ici_mode = "auto"
    jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=0))")
    dv = jget(uris[0], "/debug/vars")
    blk = dv["iciServing"]
    assert blk["sliceLocal"] > 0
    assert blk["mode"] == "auto"
    assert blk["programCache"]["misses"] > 0
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'pilosa_iciServing_total{route="slice_local"}' in text
    assert 'pilosa_iciServing_total{route="cross_slice"}' in text
    assert 'pilosa_iciServing_total{route="fallback"}' in text
    assert 'pilosa_iciProgramCache_total{key="hits"}' in text
    g = servers[0].sample_gauges()
    assert "ici.slice_local_per_s" in g
    assert 0.0 <= g["ici.slice_local_share"] <= 1.0


def test_routing_decisions(ici_cluster):
    """_ici_route unit coverage on the live cluster's executors: mode
    off, writes, single-device auto, fenced shards, kill switch."""
    from pilosa_tpu.pql import parse_string_cached

    servers, uris, _ = ici_cluster
    ex0 = servers[0].executor  # mesh-backed
    ex1 = servers[1].executor  # single-device
    idx0 = servers[0].holder.index("i")
    idx1 = servers[1].holder.index("i")
    count = parse_string_cached("Count(Row(f=0))").calls[0]
    setq = parse_string_cached("Set(5, f=0)").calls[0]
    shards = idx0.available_shards_list()
    assert shards

    ex0.ici_mode = "auto"
    assert ex0._ici_route(idx0, count, shards) == \
        ("slice_local", "co-resident")
    # writes never route slice-local (they must reach every replica)
    assert ex0._ici_route(idx0, setq, shards)[0] == "fallback"
    # empty shard set: nothing to route
    assert ex0._ici_route(idx0, count, [])[0] == "fallback"
    # mode off / env kill switch
    ex0.ici_mode = "off"
    assert ex0._ici_route(idx0, count, shards)[0] == "fallback"
    ex0.ici_mode = "auto"
    old_env = ex0._ici_env
    ex0._ici_env = False  # what PILOSA_TPU_ICI=0 sets at construction
    assert ex0._ici_route(idx0, count, shards)[0] == "fallback"
    ex0._ici_env = old_env
    # single-device runner: auto falls back to the HTTP plane, "on"
    # overrides (removing the RTTs is worth it without a mesh too)
    ex1.ici_mode = "auto"
    assert ex1._ici_route(idx1, count, shards) == \
        ("cross_slice", "no mesh")
    ex1.ici_mode = "on"
    assert ex1._ici_route(idx1, count, shards)[0] == "slice_local"
    ex1.ici_mode = "auto"
    # a read-fenced local shard routes to the HTTP plane's fence re-route
    ex0.fence_reads([("i", shards[0])])
    try:
        assert ex0._ici_route(idx0, count, shards) == \
            ("cross_slice", "read-fenced")
    finally:
        ex0.unfence_reads(("i", shards[0]))
    assert ex0._ici_route(idx0, count, shards)[0] == "slice_local"
    # a shard nobody co-resides: unknown shard id far outside placement
    # is still "owned" by some replica set; instead drop node0 from the
    # owners by marking it... ownership is ring-based, so instead assert
    # the memo invalidates on topology change: marking the peer down
    # changes the fingerprint and flushes the memo
    ex0._ici_route(idx0, count, shards)
    assert ex0._ici_route_memo
    servers[0].cluster.down_ids.add("zz-not-a-node")
    try:
        ex0._ici_route(idx0, count, shards)
        assert ex0._ici_topo_fp[2] == frozenset({"zz-not-a-node"})
    finally:
        servers[0].cluster.down_ids.discard("zz-not-a-node")


def _assert_parity(q: bytes, on, off, ctx) -> None:
    """Bit-identical answers — except TopN, whose winner SELECTION is
    approximate by design (per-node rank-cache candidates, the
    reference's cache.go semantics): under churn the scatter-gather
    fan-out can pick a different same-length winner set than the
    single-program path. Counts are exact phase-2 recounts on both
    routes, so any id BOTH paths return must carry the same count."""
    if q.startswith(b"TopN"):
        a = {p["id"]: p["count"] for p in on[0]}
        b = {p["id"]: p["count"] for p in off[0]}
        assert len(a) == len(b), (ctx, q, on, off)
        for rid in a.keys() & b.keys():
            assert a[rid] == b[rid], (ctx, q, on, off)
        return
    assert on == off, (ctx, q, on, off)


def test_routing_parity_fuzz_with_generation_churn(ici_cluster):
    """The tier-1 query mix through ici-serving on vs off with
    interleaved writes churning row generations: every pair of answers
    bit-identical (TopN: see _assert_parity), every slice-local round
    envelope-free."""
    servers, uris, data = ici_cluster
    ex = servers[0].executor
    rng = np.random.default_rng(17)
    n_shards = data["n_shards"]
    try:
        for rnd in range(10):
            # churn: writes through BOTH nodes (replica fan-out bumps
            # generations everywhere; plan-cache keys roll over)
            for _ in range(3):
                row = int(rng.integers(0, 4))
                col = int(rng.integers(0, n_shards * SW))
                u = uris[rnd % 2]
                if rng.random() < 0.25:
                    jpost(u, "/index/i/query",
                          raw=f"Clear({col}, f={row})".encode())
                else:
                    jpost(u, "/index/i/query",
                          raw=f"Set({col}, f={row})".encode())
            qs = [TIER1_MIX[int(i)] for i in
                  rng.choice(len(TIER1_MIX), size=4, replace=False)]
            for q in qs:
                ex.ici_mode = "on"
                env0 = _envelopes(ex)
                on = jpost(uris[0], "/index/i/query", raw=q)["results"]
                assert _envelopes(ex) == env0, (rnd, q)
                ex.ici_mode = "off"
                off = jpost(uris[0], "/index/i/query", raw=q)["results"]
                _assert_parity(q, on, off, rnd)
    finally:
        ex.ici_mode = "auto"
