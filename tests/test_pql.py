"""PQL parser tests.

Case matrix modeled on the reference's pqlpeg_test.go / parser_test.go:
special forms, nesting, conditions, conditionals, lists, strings, timestamps.
"""

from datetime import datetime

import pytest

from pilosa_tpu.pql import Call, Condition, PQLError, parse_string
from pilosa_tpu.pql.ast import BETWEEN


def one(src: str) -> Call:
    q = parse_string(src)
    assert len(q.calls) == 1, q.calls
    return q.calls[0]


def test_row():
    c = one("Row(f=10)")
    assert c == Call("Row", {"f": 10})


def test_nested_bitmap_ops():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert c.name == "Count"
    inter = c.children[0]
    assert inter.name == "Intersect"
    assert inter.children == [Call("Row", {"a": 1}), Call("Row", {"b": 2})]


def test_union_many_and_not():
    c = one("Union(Row(a=1), Row(b=2), Row(c=3))")
    assert len(c.children) == 3
    c = one("Not(Row(a=1))")
    assert c.children[0].name == "Row"


def test_whitespace_and_multiple_calls():
    q = parse_string("  Row(a=1)\n\tRow(b = 2) ")
    assert len(q.calls) == 2
    assert q.calls[1] == Call("Row", {"b": 2})


def test_set():
    c = one("Set(100, f=5)")
    assert c == Call("Set", {"_col": 100, "f": 5})


def test_set_with_timestamp():
    c = one("Set(100, f=5, 2018-01-02T03:04)")
    assert c.args["_timestamp"] == datetime(2018, 1, 2, 3, 4)


def test_set_with_keys():
    c = one("Set('col-key', f='row-key')")
    assert c.args["_col"] == "col-key"
    assert c.args["f"] == "row-key"


def test_clear_and_clearrow():
    assert one("Clear(7, f=1)") == Call("Clear", {"_col": 7, "f": 1})
    assert one("ClearRow(f=1)") == Call("ClearRow", {"f": 1})


def test_store():
    c = one("Store(Row(a=1), f=9)")
    assert c.name == "Store"
    assert c.children[0] == Call("Row", {"a": 1})
    assert c.args["f"] == 9


def test_setrowattrs_setcolumnattrs():
    c = one('SetRowAttrs(f, 10, color="blue", weight=1.5, active=true, gone=null)')
    assert c.args["_field"] == "f"
    assert c.args["_row"] == 10
    assert c.args["color"] == "blue"
    assert c.args["weight"] == 1.5
    assert c.args["active"] is True
    assert c.args["gone"] is None
    c = one("SetColumnAttrs(3, happy=false)")
    assert c.args == {"_col": 3, "happy": False}


def test_topn():
    assert one("TopN(f)").args == {"_field": "f"}
    c = one("TopN(f, n=5)")
    assert c.args == {"_field": "f", "n": 5}
    c = one("TopN(f, Row(g=1), n=10, attrName=\"a\", attrValues=[1,2])")
    assert c.children[0] == Call("Row", {"g": 1})
    assert c.args["n"] == 10
    assert c.args["attrValues"] == [1, 2]


def test_range_condition_ops():
    for op in ("<", "<=", ">", ">=", "==", "!="):
        c = one(f"Range(f {op} 10)")
        assert c.args["f"] == Condition(op, 10), op


def test_range_between():
    c = one("Range(f >< [4, 8])")
    assert c.args["f"] == Condition("><", [4, 8])


def test_range_conditional():
    # intended semantics: 4 < f < 8 -> inclusive [5, 7]
    assert one("Range(4 < f < 8)").args["f"] == Condition(BETWEEN, [5, 7])
    assert one("Range(4 <= f <= 8)").args["f"] == Condition(BETWEEN, [4, 8])
    assert one("Range(-10 <= f < 0)").args["f"] == Condition(BETWEEN, [-10, -1])


def test_range_timerange():
    c = one("Range(f=1, 2018-01-01T00:00, 2018-02-01T00:00)")
    assert c.args["f"] == 1
    assert c.args["_start"] == datetime(2018, 1, 1)
    assert c.args["_end"] == datetime(2018, 2, 1)
    c = one("Range(f=1, '2018-01-01T00:00', \"2018-02-01T00:00\")")
    assert c.args["_start"] == datetime(2018, 1, 1)


def test_row_with_list_and_strings():
    c = one('Row(f=[1, 2, 3])')
    assert c.args["f"] == [1, 2, 3]
    c = one('Row(f="hello world")')
    assert c.args["f"] == "hello world"
    c = one("Row(f=bare-string_1:x)")
    assert c.args["f"] == "bare-string_1:x"


def test_quoted_escapes():
    c = one(r'Row(f="a\"b")')
    assert c.args["f"] == 'a"b'


def test_negative_and_float():
    assert one("Range(f > -5)").args["f"] == Condition(">", -5)
    assert one("Row(f=1.25)").args["f"] == 1.25


def test_field_names_with_underscore_dash():
    c = one("Row(my_field-2=1)")
    assert c.args["my_field-2"] == 1


def test_groupby_rows():
    c = one("GroupBy(Rows(field=a), Rows(field=b), limit=10)")
    assert [ch.name for ch in c.children] == ["Rows", "Rows"]
    assert c.args["limit"] == 10
    assert c.children[0].args["field"] == "a"


def test_options_call():
    c = one("Options(Row(f=10), excludeColumns=true, shards=[0, 2])")
    assert c.children[0] == Call("Row", {"f": 10})
    assert c.args["excludeColumns"] is True
    assert c.args["shards"] == [0, 2]


def test_errors():
    for bad in ("Row(", "Row)", "Set(1,)", "Row(f=)", "(", "Row(f==)"):
        with pytest.raises(PQLError):
            parse_string(bad)


def test_write_call_count():
    q = parse_string("Set(1, f=1)Row(f=1)Clear(1, f=1)")
    assert q.write_call_count() == 2


def test_empty_args_call():
    assert one("Schema()") == Call("Schema")
