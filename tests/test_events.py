"""Cluster flight recorder: HLC causality, journal ring discipline,
crash forensics, and the merged /cluster/events timeline.

The acceptance contract (ISSUE 14): a 3-node rolling restart under
artificially SKEWED node wall clocks reconstructs as ONE merged cluster
timeline with drain → hint append → replay → fence → parity-lift events
in causal order and zero HLC inversions — wall-clock order would shuffle
them, the hybrid logical clock must not.
"""

import json
import os
import signal
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server import Server
from pilosa_tpu.utils import events as ev
from pilosa_tpu.utils.events import (
    EventJournal,
    HybridLogicalClock,
    decode_hlc,
    encode_hlc,
    hlc_sort_key,
    merge_events,
)


def http(method, uri, path, body=None, timeout=20):
    req = urllib.request.Request(uri + path, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else (
        json.dumps(payload).encode() if payload is not None else b"")
    status, headers, out = http("POST", uri, path, body)
    return status, headers, json.loads(out) if out else {}


def jget(uri, path):
    status, headers, out = http("GET", uri, path)
    return status, headers, json.loads(out) if out else {}


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except Exception:  # noqa: BLE001 — condition not ready yet
            pass
        time.sleep(interval)
    return False


# -- hybrid logical clock ----------------------------------------------------


def test_hlc_monotonic_under_backward_wall_step():
    """A stepped-back wall clock stalls the physical half; the logical
    half keeps every stamp strictly increasing."""
    walls = iter([1000, 2000, 1500, 1500, 900, 3000])
    clock = HybridLogicalClock(wall_ms=lambda: next(walls))
    stamps = [clock.now() for _ in range(6)]
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == len(stamps)  # strictly increasing
    assert stamps[1] == (2000, 0)
    assert stamps[2] == (2000, 1)  # wall went backwards: logical ticks
    assert stamps[5] == (3000, 0)  # wall caught up: logical resets


def test_hlc_update_merges_remote_ahead_and_behind():
    clock = HybridLogicalClock(wall_ms=lambda: 1000)
    local = clock.now()
    # remote far ahead (fast peer clock): adopt physical, logical+1
    got = clock.update((999_999, 7))
    assert got == (999_999, 8) and got > local
    # remote behind: keep physical, logical ticks past both
    got2 = clock.update((500, 3))
    assert got2 > got and got2[0] == 999_999
    # garbage merges as a plain local tick, never raises
    got3 = clock.update("garbage")
    assert got3 > got2


def test_hlc_encode_decode_roundtrip_and_garbage():
    assert decode_hlc(encode_hlc((123, 4))) == (123, 4)
    assert decode_hlc(encode_hlc((123, 0))) == (123, 0)
    assert decode_hlc(None) is None
    assert decode_hlc("") is None
    assert decode_hlc("not-a-stamp") is None
    assert decode_hlc("1.2.3") is None
    assert decode_hlc(12) is None


def test_hlc_causal_chain_survives_hours_of_skew():
    """Three nodes with wall clocks hours apart exchange messages; every
    receive-side event must sort after the send-side event that caused
    it (ZERO inversions) — wall-clock order would interleave them."""
    import random
    rng = random.Random(7)
    offsets = {"a": -7200_000, "b": 0, "c": +7200_000}
    base = [1_000_000_000_000]

    def wall(node):
        return lambda: base[0] + offsets[node]

    clocks = {n: HybridLogicalClock(wall_ms=wall(n)) for n in offsets}
    events = []  # (stamp, node, kind, chain-id)
    for i in range(200):
        base[0] += rng.randint(0, 50)  # real time creeps forward
        src, dst = rng.sample(list(clocks), 2)
        sent = clocks[src].now()
        events.append((sent, src, "send", i))
        recv = clocks[dst].update(sent)
        events.append((recv, dst, "recv", i))
        assert recv > sent, (sent, recv, src, dst)
    # the merged order (hlc, node tiebreak) keeps every send before its
    # receive — the acceptance "zero HLC inversions" property
    merged = sorted(events, key=lambda e: (e[0], e[1]))
    for i in range(200):
        s = merged.index(next(e for e in merged
                              if e[3] == i and e[2] == "send"))
        r = merged.index(next(e for e in merged
                              if e[3] == i and e[2] == "recv"))
        assert s < r


# -- journal ring ------------------------------------------------------------


def test_emit_unregistered_type_raises():
    j = EventJournal(node_id="n")
    with pytest.raises(ValueError, match="unregistered event type"):
        j.emit("made.up.type")


def test_ring_bounds_and_since_cursor():
    j = EventJournal(node_id="n", ring_size=8)
    for i in range(20):
        j.emit("scrub.pass", blocksMerged=i)
    assert len(j) == 8  # bounded
    doc = j.since(0)
    assert doc["seq"] == 20
    assert [e["blocksMerged"] for e in doc["events"]] == list(range(12, 20))
    # cursor: nothing new -> empty, seq still advances the poller
    again = j.since(doc["seq"])
    assert again["events"] == [] and again["seq"] == 20
    j.emit("scrub.pass", blocksMerged=99)
    assert [e["blocksMerged"]
            for e in j.since(doc["seq"])["events"]] == [99]
    # limit keeps the newest
    assert [e["blocksMerged"]
            for e in j.since(0, limit=2)["events"]] == [20 - 1, 99]
    snap = j.snapshot()
    assert snap["emitted"] == 21
    assert snap["evicted"]["lifecycle"] == 13
    assert snap["byType"]["scrub.pass"] == 21


def test_log_storm_cannot_evict_lifecycle_events():
    """Separate severity lanes: a log.warn storm fills only the log
    lane; the lifecycle events an incident reconstruction needs stay."""
    j = EventJournal(node_id="n", ring_size=16)
    j.emit("drain.start")
    j.emit("hint.append", target="x")
    for i in range(500):
        j.emit("log.warn", msg=f"storm {i}")
    types = [e["type"] for e in j.events(0)]
    assert "drain.start" in types and "hint.append" in types
    # the log lane stayed at its own (quarter) bound
    assert types.count("log.warn") == 4
    assert j.snapshot()["evicted"]["log"] == 496
    # severity filter separates the lanes on the feed
    assert all(e["type"] in ("drain.start", "hint.append")
               for e in j.since(0, severity="lifecycle")["events"])
    assert all(e["type"] == "log.warn"
               for e in j.since(0, severity="log")["events"])


def test_kill_switch_stops_recording(monkeypatch):
    j = EventJournal(node_id="n")
    monkeypatch.setenv("PILOSA_TPU_EVENTS", "0")
    assert j.emit("drain.start") is None
    assert len(j) == 0 and j.snapshot()["droppedDisabled"] == 1
    monkeypatch.setenv("PILOSA_TPU_EVENTS", "1")
    assert j.emit("drain.start") is not None
    assert len(j) == 1


def test_spool_is_bounded_with_one_rotation(tmp_path):
    spool = str(tmp_path / "events.spool.jsonl")
    j = EventJournal(node_id="n", spool_path=spool, spool_max_bytes=2000)
    for i in range(200):
        j.emit("scrub.pass", blocksMerged=i)
    assert os.path.getsize(spool) <= 2000
    assert os.path.exists(spool + ".1")
    assert os.path.getsize(spool + ".1") <= 2200  # cap + one record
    # spooled lines are valid JSONL carrying the stamp
    with open(spool) as f:
        recs = [json.loads(line) for line in f]
    assert recs and all(r["type"] == "scrub.pass" and "hlc" in r
                        for r in recs)
    assert j.snapshot()["spoolErrors"] == 0
    # a new journal on the same spool reloads the tail at boot (the
    # restarted-node contract: pre-restart lifecycle stays on the
    # timeline) and new events sort after every reloaded one
    j2 = EventJournal(node_id="n", spool_path=spool,
                      spool_max_bytes=2000)
    reloaded = j2.events(0)
    assert reloaded and j2.snapshot()["reloaded"] == len(reloaded)
    assert reloaded[-1]["blocksMerged"] == 199
    fresh = j2.emit("drain.start")
    assert hlc_sort_key(fresh) > hlc_sort_key(reloaded[-1])


def test_dump_and_merge_events(tmp_path):
    a = EventJournal(node_id="a",
                     clock=HybridLogicalClock(wall_ms=lambda: 1000))
    b = EventJournal(node_id="b",
                     clock=HybridLogicalClock(wall_ms=lambda: 2000))
    a.emit("drain.start")
    b.clock.update(a.clock.peek())
    b.emit("peer.draining", peer="a")
    merged = merge_events({"a": a.events(0), "b": b.events(0)})
    assert [e["type"] for e in merged] == ["drain.start", "peer.draining"]
    assert merged == sorted(merged, key=hlc_sort_key)
    path = str(tmp_path / "dump.jsonl")
    assert a.dump(path) == 1
    with open(path) as f:
        assert json.loads(f.readline())["type"] == "drain.start"


def test_crash_dump_spills_on_sigquit(tmp_path):
    """The crash-forensics contract: SIGQUIT spills every registered
    journal's ring to events.crash-<ts>.jsonl next to its data dir."""
    j = EventJournal(node_id="crashy")
    j.emit("drain.start")
    j.emit("log.error", msg="about to die")
    prev = signal.getsignal(signal.SIGQUIT)
    ev.register_crash_dump(j, str(tmp_path))
    try:
        os.kill(os.getpid(), signal.SIGQUIT)
        assert wait_until(lambda: any(
            n.startswith("events.crash-") for n in os.listdir(tmp_path)),
            timeout=10)
        name = next(n for n in os.listdir(tmp_path)
                    if n.startswith("events.crash-"))
        with open(tmp_path / name) as f:
            types = [json.loads(line)["type"] for line in f]
        assert types == ["drain.start", "log.error"]
    finally:
        ev.unregister_crash_dump(j)
        signal.signal(signal.SIGQUIT, prev)
        ev._CRASH_INSTALLED = False


# -- live cluster ------------------------------------------------------------


SKEWS_MS = {0: -7_200_000, 1: 0, 2: +7_200_000}  # ±2h of wall skew


def _skew(server, offset_ms):
    """Give a server's flight-recorder clock a deliberately wrong wall
    (every stamp it mints from now on leans by offset_ms)."""
    server.clock._wall_ms = (
        lambda off=offset_ms: int(time.time() * 1000) + off)  # wall-clock: test skew injection


@pytest.fixture
def trio(tmp_path):
    """3-node replica-2 cluster with ±2h wall skew; node index 2 runs
    with the flight-recorder route 404ing like a legacy build."""
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2,
                   node_id=chr(ord("a") + i), events_spool=1 << 20)
        _skew(s, SKEWS_MS[i])
        s.open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    yield servers
    for s in servers:
        try:
            s.close()
        except Exception:  # noqa: BLE001 — some were restarted/closed
            pass


def test_debug_events_feed_and_hlc_response_header(trio):
    s0 = trio[0]
    st, headers, doc = jget(s0.uri, "/debug/events")
    assert st == 200 and doc["enabled"] is True
    types = [e["type"] for e in doc["events"]]
    assert "node.start" in types
    assert all(e["node"] == s0.node_id for e in doc["events"])
    # every response piggybacks the node's HLC stamp
    stamp = decode_hlc(headers.get("X-Pilosa-HLC"))
    assert stamp is not None and stamp[0] > 0
    # cursor discipline: nothing new after the reported seq
    st, _h, doc2 = jget(s0.uri, f"/debug/events?since={doc['seq']}")
    assert st == 200 and doc2["events"] == []
    # filters validate
    st, _h, _ = jget(s0.uri, "/debug/events?severity=bogus")
    assert st == 400
    st, _h, _ = jget(s0.uri, "/debug/events?type=not.registered")
    assert st == 400


def test_cluster_events_merges_and_degrades_legacy_peer(trio):
    s0, s1, s2 = trio

    def _legacy_404(params, query, body):
        return 404, "application/json", b'{"error": "not found"}'

    s2.handler.get_debug_events = _legacy_404
    st, _h, doc = jget(s0.uri, "/cluster/events")
    assert st == 200
    by_id = {n["id"]: n["status"] for n in doc["nodes"]}
    assert by_id == {"a": "ok", "b": "ok", "c": "legacy"}
    nodes_seen = {e["node"] for e in doc["events"]}
    assert nodes_seen == {"a", "b"}  # the legacy peer contributes none
    # merged stream is HLC-sorted (causal order, node-id tiebreak)
    keys = [hlc_sort_key(e) for e in doc["events"]]
    assert keys == sorted(keys)


def test_events_observability_surfaces(trio):
    s0 = trio[0]
    st, _h, dv = jget(s0.uri, "/debug/vars")
    assert st == 200
    assert dv["events"]["emitted"] >= 1
    assert "node.start" in dv["events"]["byType"]
    st, _h2, text = http("GET", s0.uri, "/metrics")
    assert st == 200
    body = text.decode()
    assert 'pilosa_events_total{type="node.start"} 1' in body
    # the full registered keyspace exists, zeros included
    assert 'pilosa_events_total{type="qos.quota_debt"} 0' in body
    # the dashboard panel rides the same feed (air-gapped page)
    st, _h3, page = http("GET", s0.uri, "/debug/dashboard")
    assert st == 200 and b"/debug/events?since=" in page


def test_rolling_restart_reconstructs_one_causal_timeline(trio, tmp_path):
    """THE acceptance criterion: a rolling restart (drain → writes acked
    while the replica is away → rejoin → hint replay → fence lift) under
    ±2h wall skew reconstructs as ONE merged timeline with
    drain.start → hint.append → fence.armed → hint.replay →
    fence.lifted in causal order, zero HLC inversions."""
    s0, s1, s2 = trio
    uris = [s.uri for s in trio]
    # seed a few shards so the restarted node has fragments to fence
    jpost(s0.uri, "/index/rr", {})
    jpost(s0.uri, "/index/rr/field/f", {})
    for shard in range(3):
        for k in range(4):
            col = shard * SHARD_WIDTH + 50 + k
            st, _h, out = jpost(s0.uri, "/index/rr/query",
                                raw=f"Set({col}, f=7)".encode())
            assert st == 200 and out["results"] == [True]

    # drain node c (the +2h fast clock), then the process goes away
    port = s2.http.port
    st, _h, out = jpost(s2.uri, "/cluster/drain")
    assert st == 200
    assert wait_until(lambda: s2.drained, timeout=20)
    s2.close()

    # writes acked while c is away ride the hint path
    acked = []
    for k in range(9):
        col = (k % 3) * SHARD_WIDTH + 900 + k
        st, _h, out = jpost(trio[k % 2].uri, "/index/rr/query",
                            raw=f"Set({col}, f=9)".encode())
        assert st == 200 and out["results"] == [True]
        acked.append(col)
    assert (s0.hints.snapshot()["queued"]
            + s1.hints.snapshot()["queued"]) >= 1

    # restart on the same port/data (skewed again): rejoin broadcast →
    # hint replay from peers → read fence verifies and lifts
    # the durable spool reloads at boot, so the restarted process still
    # carries its pre-restart drain.start/drain.complete on the timeline
    s2b = Server(str(tmp_path / "n2"), port=port, replica_n=2,
                 node_id="c", events_spool=1 << 20)
    _skew(s2b, SKEWS_MS[2])
    s2b.cluster_hosts = uris
    s2b.open()
    trio[2] = s2b  # fixture teardown closes the restarted instance
    assert wait_until(
        lambda: (s0.hints.snapshot()["pendingBytes"] == 0
                 and s1.hints.snapshot()["pendingBytes"] == 0
                 and s2b.executor.fence_snapshot()["fencedShards"] == 0),
        timeout=30)

    # ONE merged cluster timeline from any node
    st, _h, doc = jget(s0.uri, "/cluster/events")
    assert st == 200
    assert {n["id"]: n["status"] for n in doc["nodes"]} == {
        "a": "ok", "b": "ok", "c": "ok"}
    merged = doc["events"]
    keys = [hlc_sort_key(e) for e in merged]
    assert keys == sorted(keys)

    # zero HLC inversions, part 1: each node's own events keep their
    # local (seq) order under the HLC sort — the clock never ran
    # backwards on any node despite the skew
    for nid in ("a", "b", "c"):
        own = [e for e in merged if e["node"] == nid]
        assert [e["seq"] for e in own] == sorted(e["seq"] for e in own)

    # zero HLC inversions, part 2: the causal chain of the restart
    # appears in order even though the wall clocks disagree by hours
    def first_idx(etype, **match):
        for i, e in enumerate(merged):
            if e["type"] == etype and all(e.get(k) == v
                                          for k, v in match.items()):
                return i
        raise AssertionError(
            f"event {etype} {match} missing from merged timeline: "
            f"{[(e['type'], e.get('node')) for e in merged]}")

    i_drain = first_idx("drain.start", node="c")
    i_draining = first_idx("peer.draining", peer="c")
    i_append = first_idx("hint.append", target="c")
    i_complete = first_idx("drain.complete", node="c")
    i_fence = first_idx("fence.armed", node="c")
    i_rejoined = first_idx("peer.rejoined", peer="c")
    i_replay = first_idx("hint.replay", target="c")
    i_lift = first_idx("fence.lifted", node="c")
    # the message-driven chain: the drain broadcast precedes the peers'
    # routing-around and their hint appends; the rejoin (fence armed on
    # the restarted node, READY broadcast) precedes the peers' replays;
    # every parity-lift follows the fence arming. hint.replay and the
    # per-shard lifts are genuinely CONCURRENT (a lift can ride the
    # block-majority heal while a peer is still streaming its log), so
    # no order is asserted between them — that's the HLC telling the
    # truth, not a gap in it.
    assert i_drain < i_draining < i_append, (i_drain, i_draining,
                                             i_append)
    assert i_append < i_fence < i_lift, (i_append, i_fence, i_lift)
    assert i_drain < i_complete < i_fence, (i_drain, i_complete, i_fence)
    assert i_fence < i_rejoined < i_replay, (i_fence, i_rejoined,
                                             i_replay)
    lifts = [i for i, e in enumerate(merged)
             if e["type"] == "fence.lifted"]
    assert len(lifts) == 3 and all(i > i_fence for i in lifts)

    # the acked writes actually survived (the PR-9 contract still holds
    # with the recorder on)
    st, _h, out = jpost(s2b.uri, "/index/rr/query", raw=b"Row(f=9)")
    assert st == 200
    assert set(out["results"][0]["columns"]) == set(acked)

    # `pilosa-tpu timeline` renders the same merged document
    from pilosa_tpu.cli.main import render_timeline
    text = render_timeline(doc)
    assert "drain.start" in text and "hint.replay" in text
    assert "3 node(s)" in text
