"""Executor tests: PQL strings through the full local pipeline — parse ->
leaf materialization -> device program -> reduce.

Mirrors executor_test.go's style: build an index, run PQL, assert results.
Runs on the CPU backend (8 virtual devices) with and without a mesh runner.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor, ValCount
from pilosa_tpu.models import FieldOptions, FieldType, Holder
from pilosa_tpu.models.row import Row
from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh


@pytest.fixture(params=["single", "mesh", "replica_mesh"])
def ex(tmp_path, request):
    h = Holder(str(tmp_path / "data")).open()
    mesh = None
    if request.param == "mesh":
        mesh = make_mesh()
    elif request.param == "replica_mesh":
        # 2x4 replica×shard: leaves replicated per slice, sharded within
        mesh = make_mesh(replicas=2)
    runner = DeviceRunner(mesh)
    e = Executor(h, runner=runner)
    yield e
    h.close()


@pytest.fixture
def populated(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    # rows spanning 3 shards
    f.import_bits([10] * 4, [1, 2, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5])
    f.import_bits([11] * 3, [2, 3, SHARD_WIDTH + 1])
    g.import_bits([20] * 2, [2, SHARD_WIDTH + 1])
    for c in [1, 2, 3, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5]:
        idx.mark_exists(c)
    return ex


def cols(row: Row) -> list[int]:
    return row.columns().tolist()


def test_row(populated):
    (r,) = populated.execute("i", "Row(f=10)")
    assert cols(r) == [1, 2, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5]


def test_intersect_union_difference_xor(populated):
    (r,) = populated.execute("i", "Intersect(Row(f=10), Row(f=11))")
    assert cols(r) == [2, SHARD_WIDTH + 1]
    (r,) = populated.execute("i", "Union(Row(f=10), Row(f=11))")
    assert cols(r) == [1, 2, 3, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5]
    (r,) = populated.execute("i", "Difference(Row(f=10), Row(f=11))")
    assert cols(r) == [1, 2 * SHARD_WIDTH + 5]
    (r,) = populated.execute("i", "Xor(Row(f=10), Row(f=11))")
    assert cols(r) == [1, 3, 2 * SHARD_WIDTH + 5]


def test_nested_and_cross_field(populated):
    (r,) = populated.execute("i", "Intersect(Union(Row(f=10), Row(f=11)), Row(g=20))")
    assert cols(r) == [2, SHARD_WIDTH + 1]


def test_count(populated):
    (c,) = populated.execute("i", "Count(Row(f=10))")
    assert c == 4
    (c,) = populated.execute("i", "Count(Intersect(Row(f=10), Row(g=20)))")
    assert c == 2


def test_not(populated):
    (r,) = populated.execute("i", "Not(Row(f=10))")
    # existence = {1,2,3,SW+1,2SW+5}; minus row 10 -> {3}
    assert cols(r) == [3]


def test_row_missing_field(populated):
    with pytest.raises(ExecutionError):
        populated.execute("i", "Row(nope=1)")


def test_multiple_calls(populated):
    r1, c1 = populated.execute("i", "Row(f=11) Count(Row(f=11))")
    assert cols(r1) == [2, 3, SHARD_WIDTH + 1]
    assert c1 == 3


def test_set_clear(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("f")
    (changed,) = ex.execute("i", "Set(100, f=1)")
    assert changed is True
    (changed,) = ex.execute("i", "Set(100, f=1)")
    assert changed is False
    (r,) = ex.execute("i", "Row(f=1)")
    assert cols(r) == [100]
    # existence tracked
    (r,) = ex.execute("i", "Not(Row(f=99))")
    assert cols(r) == [100]
    (changed,) = ex.execute("i", "Clear(100, f=1)")
    assert changed is True
    (r,) = ex.execute("i", "Row(f=1)")
    assert cols(r) == []


def test_device_cache_invalidation(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("f")
    ex.execute("i", "Set(1, f=1)")
    (c,) = ex.execute("i", "Count(Row(f=1))")
    assert c == 1
    ex.execute("i", "Set(2, f=1)")
    (c,) = ex.execute("i", "Count(Row(f=1))")  # must not serve stale slab
    assert c == 2


def test_clear_row_and_store(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1, 1, 2], [5, 6, 7])
    (changed,) = ex.execute("i", "ClearRow(f=1)")
    assert changed is True
    (r,) = ex.execute("i", "Row(f=1)")
    assert cols(r) == []
    # Store: copy row 2 into a new row of a new field
    ex.execute("i", "Store(Row(f=2), t=9)")
    (r,) = ex.execute("i", "Row(t=9)")
    assert cols(r) == [7]


def test_bsi_sum_min_max(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=-10, max=1000))
    idx.create_field("f")
    ex.execute("i", "Set(1, v=10) Set(2, v=-10) Set(3, v=1000) Set(4, v=0)")
    ex.execute("i", "Set(1, f=7) Set(2, f=7)")
    (vc,) = ex.execute("i", "Sum(field=v)")
    assert vc == ValCount(1000, 4)
    (vc,) = ex.execute("i", "Sum(Row(f=7), field=v)")
    assert vc == ValCount(0, 2)
    (vc,) = ex.execute("i", "Min(field=v)")
    assert vc == ValCount(-10, 1)
    (vc,) = ex.execute("i", "Max(field=v)")
    assert vc == ValCount(1000, 1)
    (vc,) = ex.execute("i", "Max(Row(f=7), field=v)")
    assert vc == ValCount(10, 1)


def test_bsi_range_ops(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=100))
    vals = {1: 5, 2: 50, 3: 100, SHARD_WIDTH + 4: 50}
    for c, v in vals.items():
        ex.execute("i", f"Set({c}, v={v})")
    cases = {
        "Range(v < 50)": [1],
        "Range(v <= 50)": [1, 2, SHARD_WIDTH + 4],
        "Range(v > 50)": [3],
        "Range(v >= 50)": [2, 3, SHARD_WIDTH + 4],
        "Range(v == 50)": [2, SHARD_WIDTH + 4],
        "Range(v != 50)": [1, 3],
        "Range(v >< [5, 50])": [1, 2, SHARD_WIDTH + 4],
        "Range(0 < v < 100)": [1, 2, SHARD_WIDTH + 4],
        "Range(v != null)": [1, 2, 3, SHARD_WIDTH + 4],
        # out-of-range clamps
        "Range(v > 1000)": [],
        "Range(v < -5)": [],
        "Range(v >= -5)": [1, 2, 3, SHARD_WIDTH + 4],
    }
    for q, expect in cases.items():
        (r,) = ex.execute("i", q)
        assert cols(r) == expect, q


def test_topn(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=100))
    # row 1: 5 cols, row 2: 3 cols, row 3: 1 col; spanning shards
    f.import_bits([1] * 5, [0, 1, 2, SHARD_WIDTH, SHARD_WIDTH + 1])
    f.import_bits([2] * 3, [0, 5, SHARD_WIDTH + 2])
    f.import_bits([3] * 1, [9])
    (pairs,) = ex.execute("i", "TopN(f, n=2)")
    assert pairs == [(1, 5), (2, 3)]
    (pairs,) = ex.execute("i", "TopN(f)")
    assert pairs == [(1, 5), (2, 3), (3, 1)]
    # with Src filter: ranked by intersection with Row(f=2)
    (pairs,) = ex.execute("i", "TopN(f, Row(f=2), n=3)")
    assert pairs[0] == (2, 3)
    # threshold
    (pairs,) = ex.execute("i", "TopN(f, n=10, threshold=3)")
    assert pairs == [(1, 5), (2, 3)]


def test_rows(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([5, 7, 9], [1, SHARD_WIDTH + 2, 3])
    (rows,) = ex.execute("i", "Rows(field=f)")
    assert rows == [5, 7, 9]
    (rows,) = ex.execute("i", "Rows(field=f, limit=2)")
    assert rows == [5, 7]
    (rows,) = ex.execute("i", "Rows(field=f, previous=5)")
    assert rows == [7, 9]
    (rows,) = ex.execute("i", f"Rows(field=f, column={SHARD_WIDTH + 2})")
    assert rows == [7]


def test_group_by(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    # f rows: 1 -> {0,1,2}; 2 -> {2,3}
    f.import_bits([1, 1, 1, 2, 2], [0, 1, 2, 2, 3])
    # g rows: 10 -> {1,2,3}
    g.import_bits([10, 10, 10], [1, 2, 3])
    (groups,) = ex.execute("i", "GroupBy(Rows(field=f), Rows(field=g))")
    assert groups == [
        {"group": [{"field": "f", "rowID": 1}, {"field": "g", "rowID": 10}], "count": 2},
        {"group": [{"field": "f", "rowID": 2}, {"field": "g", "rowID": 10}], "count": 2},
    ]
    (groups,) = ex.execute("i", "GroupBy(Rows(field=f), limit=1)")
    assert groups == [{"group": [{"field": "f", "rowID": 1}], "count": 3}]


def test_group_by_three_axes_filter_and_scale(ex):
    """Device-batched GroupBy: 3 axes with a filter, verified against a
    brute-force numpy cross product; then a 40x40 two-axis product to
    exercise the chunked [P, R] dispatch path (P > P_CHUNK)."""
    rng = np.random.default_rng(7)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    h = idx.create_field("h")
    n_cols = 500
    sets = {}
    for field, rows in ((f, [1, 2, 3]), (g, [5, 6]), (h, [9, 10])):
        rids, cids = [], []
        for r in rows:
            cols_ = rng.choice(n_cols, size=120, replace=False)
            sets[(field.name, r)] = set(int(c) for c in cols_)
            rids += [r] * len(cols_)
            cids += list(cols_)
        field.import_bits(rids, cids)
    filt = sets[("f", 1)] | sets[("f", 2)]

    (groups,) = ex.execute(
        "i", "GroupBy(Rows(field=f), Rows(field=g), Rows(field=h), "
             "Union(Row(f=1), Row(f=2)))")
    expect = []
    for fr in (1, 2, 3):
        for gr in (5, 6):
            for hr in (9, 10):
                c = len(sets[("f", fr)] & sets[("g", gr)]
                        & sets[("h", hr)] & filt)
                if c > 0:
                    expect.append(
                        {"group": [{"field": "f", "rowID": fr},
                                   {"field": "g", "rowID": gr},
                                   {"field": "h", "rowID": hr}],
                         "count": c})
    assert groups == expect

    # 40x40 = 1600 combinations: crosses the P_CHUNK=64 boundary many times
    big1 = idx.create_field("b1")
    big2 = idx.create_field("b2")
    r1, c1, r2, c2 = [], [], [], []
    for r in range(40):
        cols_ = rng.choice(n_cols, size=30, replace=False)
        sets[("b1", r)] = set(int(c) for c in cols_)
        r1 += [r] * 30
        c1 += list(cols_)
        cols_ = rng.choice(n_cols, size=30, replace=False)
        sets[("b2", r)] = set(int(c) for c in cols_)
        r2 += [r] * 30
        c2 += list(cols_)
    big1.import_bits(r1, c1)
    big2.import_bits(r2, c2)
    (groups,) = ex.execute("i", "GroupBy(Rows(field=b1), Rows(field=b2))")
    got = {(d["group"][0]["rowID"], d["group"][1]["rowID"]): d["count"]
           for d in groups}
    expect_big = {}
    for a in range(40):
        for b in range(40):
            c = len(sets[("b1", a)] & sets[("b2", b)])
            if c > 0:
                expect_big[(a, b)] = c
    assert got == expect_big


def test_attrs(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("f")
    ex.execute("i", 'SetRowAttrs(f, 1, color="red", weight=10)')
    assert idx.field("f").row_attrs.attrs(1) == {"color": "red", "weight": 10}
    ex.execute("i", 'SetColumnAttrs(5, active=true)')
    assert idx.column_attrs.attrs(5) == {"active": True}
    # None deletes
    ex.execute("i", 'SetRowAttrs(f, 1, color=null)')
    assert idx.field("f").row_attrs.attrs(1) == {"weight": 10}


def test_options(populated):
    (r,) = populated.execute("i", "Options(Row(f=10), excludeColumns=true)")
    assert cols(r) == []
    (r,) = populated.execute("i", "Options(Row(f=10), shards=[0, 2])")
    assert cols(r) == [1, 2, 2 * SHARD_WIDTH + 5]


def test_bool_field_query(ex):
    idx = ex.holder.create_index("i")
    idx.create_field("b", FieldOptions(type=FieldType.BOOL))
    ex.execute("i", "Set(1, b=true) Set(2, b=false) Set(3, b=true)")
    (r,) = ex.execute("i", "Row(b=true)")
    assert cols(r) == [1, 3]
    (r,) = ex.execute("i", "Row(b=false)")
    assert cols(r) == [2]


def test_topn_attr_filter(ex):
    """TopN(f, attrName=, attrValues=) keeps only candidate rows whose row
    attrs match (topOptions.AttrName/AttrValues, fragment.go:1056-1076)."""
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1] * 3, [1, 2, 3])
    f.import_bits([2] * 2, [1, 2])
    f.import_bits([3] * 1, [1])
    ex.execute("i", 'SetRowAttrs(f, 1, category="x")')
    ex.execute("i", 'SetRowAttrs(f, 2, category="y")')
    top = ex.execute("i", 'TopN(f, n=10, attrName="category", attrValues=["x"])')[0]
    assert list(top) == [(1, 3)]
    # attrName WITHOUT attrValues is a no-op (fragment.go:1029 builds the
    # filter only when both are present) — row 3 (no attrs) stays in
    top = ex.execute("i", 'TopN(f, n=10, attrName="category")')[0]
    assert list(top) == [(1, 3), (2, 2), (3, 1)]


def test_residency_cache_hits_and_invalidation(ex):
    """Repeat queries hit HBM-resident leaves; a write bumps the fragment row
    generation and forces re-upload (the rowCache invalidation analog,
    fragment.go:435-440). Plan cache off: it would answer the repeat from
    the cached scalar before the residency layer is ever consulted — this
    test targets the layer underneath."""
    ex.plan_cache.enabled = False
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1] * 3, [1, 2, 3])
    assert ex.execute("i", "Count(Row(f=1))")[0] == 3
    m0 = ex.residency.snapshot()
    assert m0["misses"] >= 1 and m0["entries"] >= 1
    assert ex.execute("i", "Count(Row(f=1))")[0] == 3
    m1 = ex.residency.snapshot()
    assert m1["hits"] > m0["hits"]
    assert m1["misses"] == m0["misses"]
    # batched write -> resident leaf patched IN PLACE (new generation in
    # the key, net masks applied on-device): the next read hits, with the
    # correct new count
    ex.execute("i", "Set(9, f=1)")
    assert ex.execute("i", "Count(Row(f=1))")[0] == 4
    m2 = ex.residency.snapshot()
    snap = ex.ingest_snapshot()
    assert snap["patchedDense"] + snap["patchedSparse"] >= 1
    assert m2["misses"] == m1["misses"]
    # per-bit write path (ingest kill switch): generation bump with no
    # patch -> the stranded entry forces a re-upload miss, correct count
    monkey = pytest.MonkeyPatch()
    try:
        monkey.setenv("PILOSA_TPU_INGEST", "0")
        ex.execute("i", "Set(10, f=1)")
    finally:
        monkey.undo()
    assert ex.execute("i", "Count(Row(f=1))")[0] == 5
    m3 = ex.residency.snapshot()
    assert m3["misses"] > m2["misses"]


def test_residency_eviction():
    from pilosa_tpu.parallel.mesh import DeviceRunner
    from pilosa_tpu.parallel.residency import DeviceResidency

    r = DeviceResidency(DeviceRunner(), budget_bytes=4 * 128 * 1024)
    mk = lambda: np.zeros((1, SHARD_WIDTH // 32), dtype=np.uint32)  # 128KiB
    for i in range(8):
        r.leaf(("k", i), mk)
    snap = r.snapshot()
    assert snap["evictions"] >= 4
    assert snap["bytes"] <= 4 * 128 * 1024
    # most-recent keys still resident
    r.leaf(("k", 7), mk)
    assert r.snapshot()["hits"] == 1


def test_residency_inflight_miss_vs_clear():
    """A miss whose make() completes after clear() must not re-insert the
    stale entry: a recreated field reaching an identical generation tuple
    would otherwise be served deleted data (the collision clear() prevents)."""
    from pilosa_tpu.parallel.mesh import DeviceRunner
    from pilosa_tpu.parallel.residency import DeviceResidency

    r = DeviceResidency(DeviceRunner())
    arr = np.ones((1, SHARD_WIDTH // 32), dtype=np.uint32)

    def make_and_race():
        r.clear()  # clear() lands while this miss is in flight
        return arr

    out = r.leaf(("i", "f", 0, 0), make_and_race)
    assert out is not None  # caller still gets the data...
    assert r.snapshot()["entries"] == 0  # ...but it was not cached
    # a normal miss after the clear caches fine
    r.leaf(("i", "f", 0, 0), lambda: arr)
    assert r.snapshot()["entries"] == 1


def test_residency_bulk_import_invalidates(ex):
    """import_roaring resets per-row generations; the bulk-generation floor
    must still invalidate cached device leaves."""
    from pilosa_tpu.storage.roaring import Bitmap

    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1], [5])
    assert ex.execute("i", "Count(Row(f=1))")[0] == 1
    # bulk roaring import adds column 9 to row 1 (absolute bit positions)
    b = Bitmap(np.array([1 * SHARD_WIDTH + 9], dtype=np.uint64))
    frag = f.view().fragment(0)
    frag.import_roaring(b.to_bytes())
    assert ex.execute("i", "Count(Row(f=1))")[0] == 2


def test_residency_delete_recreate_invalidates(tmp_path):
    """Deleting and recreating an index restarts generation counters; the
    delete must drop cached leaves or the old data would be served."""
    from pilosa_tpu.api import API
    from pilosa_tpu.models import Holder
    from pilosa_tpu.parallel.cluster import Cluster, Node

    h = Holder(str(tmp_path / "d")).open()
    cluster = Cluster("n1")
    cluster.set_static([Node(id="n1", uri="http://localhost:0")])
    api = API(h, cluster)
    api.create_index("i")
    from pilosa_tpu.models.field import FieldOptions
    api.create_field("i", "f", FieldOptions())
    api.query_results("i", "Set(5, f=1)")
    assert api.query_results("i", "Count(Row(f=1))")[0] == 1
    api.delete_index("i")
    api.create_index("i")
    api.create_field("i", "f", FieldOptions())
    api.query_results("i", "Set(9, f=1)")
    row = api.query_results("i", "Row(f=1)")[0]
    assert row.columns().tolist() == [9]
    h.close()


def test_topn_result_is_dictable():
    """Pairs/RowIdentifiers must behave as plain lists: a `keys` attribute
    would make dict() take the mapping branch and call it (regression: the
    key-translation attribute was named `keys` and dict(pairs) raised
    \"'NoneType' object is not callable\")."""
    from pilosa_tpu.executor import Pairs, RowIdentifiers

    p = Pairs([(1, 10), (2, 5)])
    assert dict(p) == {1: 10, 2: 5}
    p.row_keys = ["a", "b"]
    assert dict(p) == {1: 10, 2: 5}  # still a list, even when keyed
    r = RowIdentifiers([3, 1])
    assert list(r) == [3, 1] and not hasattr(r, "keys")


def test_groupby_rows_paging_and_limit(executor_world=None, tmp_path=None):
    """GroupBy children accept the Rows paging args (previous/limit) —
    the reference's GroupBy paging shape (executor.go:897-1090)."""
    import tempfile

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder

    tmp = tempfile.mkdtemp()
    h = Holder(tmp).open()
    ex = Executor(h)
    idx = h.create_index("gp", track_existence=False)
    g1 = idx.create_field("g1")
    g2 = idx.create_field("g2")
    # rows 0..4 in g1, rows 0..1 in g2; all share columns 0..9
    cols = list(range(10))
    for r in range(5):
        g1.import_bits([r] * 10, cols)
    for r in range(2):
        g2.import_bits([r] * 10, cols)
    (all_groups,) = ex.execute("gp", "GroupBy(Rows(field=g1), Rows(field=g2))")
    assert len(all_groups) == 10  # 5 x 2
    (paged,) = ex.execute(
        "gp", "GroupBy(Rows(field=g1, previous=2), Rows(field=g2))")
    assert [g["group"][0]["rowID"] for g in paged] == [3, 3, 4, 4]
    (limited,) = ex.execute(
        "gp", "GroupBy(Rows(field=g1, limit=2), Rows(field=g2), limit=3)")
    assert len(limited) == 3
    assert all(g["group"][0]["rowID"] <= 1 for g in limited)
    assert all(g["count"] == 10 for g in all_groups)
    h.close()


def test_residency_eviction_pressure(tmp_path):
    """Working set > HBM budget (VERDICT r3 weak #4): queries stay correct
    while the LRU thrashes — evictions are visible in the snapshot, resident
    bytes stay within budget (+ at most one entry: the loop never evicts
    the last one), and a hot row re-uploads instead of erroring."""
    h = Holder(str(tmp_path / "data")).open()
    try:
        e = Executor(h, runner=DeviceRunner(None))
        # plan cache off: repeat sweeps would be answered from cached
        # scalars without ever touching the residency LRU under test.
        # Hybrid off too: 300-bit rows upload as ~1 KiB sparse leaves,
        # and the whole 24-row working set then FITS the 4-plane budget
        # (exactly the capacity win tests/test_hybrid.py asserts) — this
        # test needs dense planes to create eviction pressure.
        e.plan_cache.enabled = False
        e.hybrid.threshold = 0
        idx = h.create_index("ev", track_existence=False)
        f = idx.create_field("f")
        n_rows, per_row = 24, 300
        rng = np.random.default_rng(41)
        sets = {}
        rows_l, cols_l = [], []
        for r in range(n_rows):
            c = np.unique(rng.integers(0, SHARD_WIDTH, per_row))
            sets[r] = c
            rows_l += [r] * c.size
            cols_l += c.tolist()
        f.import_bits(rows_l, cols_l)
        # leaf = one shard slab [1, W] = 128 KiB; budget fits only ~4 rows
        leaf_bytes = SHARD_WIDTH // 8
        e.residency.budget = 4 * leaf_bytes
        for sweep in range(3):  # 24-row working set >> 4-row budget
            for r in range(n_rows):
                (cnt,) = e.execute("ev", f"Count(Row(f={r}))")
                assert cnt == sets[r].size, (sweep, r)
        snap = e.residency.snapshot()
        assert snap["evictions"] > n_rows, snap  # thrash is visible
        assert snap["bytes"] <= e.residency.budget + leaf_bytes, snap
        assert snap["misses"] > n_rows  # re-uploads happened (bounded...
        # ...by sweeps * rows: every miss re-uploaded at most one leaf)
        assert snap["misses"] <= 3 * n_rows + 1, snap
    finally:
        h.close()
