"""Pluggable container stores: B+Tree vs dict (roaring/roaring.go:67
`Containers`; enterprise/b/btree.go B+Tree impl)."""

import numpy as np
import pytest

from pilosa_tpu.storage.containers import (
    BTreeContainers,
    make_container_store,
)
from pilosa_tpu.storage.roaring import Bitmap


def test_btree_basic_mapping():
    t = BTreeContainers()
    assert len(t) == 0 and not list(t)
    t[5] = "a"
    t[1] = "b"
    t[9] = "c"
    assert list(t) == [1, 5, 9]
    assert t[5] == "a" and t.get(7) is None and 9 in t and 7 not in t
    t[5] = "a2"  # overwrite: no growth
    assert len(t) == 3 and t[5] == "a2"
    del t[5]
    assert list(t) == [1, 9] and 5 not in t
    with pytest.raises(KeyError):
        _ = t[5]
    with pytest.raises(KeyError):
        del t[5]
    assert t.pop(1) == "b"
    assert list(t.items()) == [(9, "c")]


def test_btree_fuzz_vs_dict():
    rng = np.random.default_rng(42)
    t, model = BTreeContainers(), {}
    for step in range(20_000):
        op = rng.integers(0, 10)
        key = int(rng.integers(0, 500))
        if op < 5:  # insert/overwrite
            t[key] = step
            model[key] = step
        elif op < 8:  # delete if present
            if key in model:
                del t[key]
                del model[key]
            else:
                assert key not in t
        else:  # point lookup
            assert t.get(key) == model.get(key)
        if step % 2500 == 0:
            assert list(t) == sorted(model)
            assert len(t) == len(model)
    assert list(t) == sorted(model)
    assert [t[k] for k in sorted(model)] == [model[k] for k in sorted(model)]


def test_btree_many_keys_ordered_iteration():
    """Force multiple levels of splits (order 64 → 3 levels at 100k keys)."""
    rng = np.random.default_rng(7)
    keys = rng.permutation(100_000)[:30_000]
    t = BTreeContainers()
    for k in keys:
        t[int(k)] = int(k) * 2
    expect = sorted(int(k) for k in keys)
    assert list(t) == expect
    assert len(t) == len(expect)
    # delete every third key (exercises emptied-leaf unlinking en masse)
    for k in expect[::3]:
        del t[k]
    remaining = [k for i, k in enumerate(expect) if i % 3]
    assert list(t) == remaining
    assert all(t[k] == k * 2 for k in remaining[:100])


def test_btree_irange():
    t = BTreeContainers((k, k) for k in range(0, 1000, 7))
    lo, hi = 100, 300
    assert list(t.irange(lo, hi)) == [k for k in range(0, 1000, 7)
                                      if lo <= k <= hi]
    assert list(t.irange(2000, 3000)) == []
    assert list(t.irange(0, 0)) == [0]


def test_make_container_store(monkeypatch):
    assert isinstance(make_container_store("dict"), dict)
    assert isinstance(make_container_store("btree"), BTreeContainers)
    monkeypatch.setenv("PILOSA_TPU_CONTAINER_STORE", "btree")
    assert isinstance(make_container_store(), BTreeContainers)
    monkeypatch.delenv("PILOSA_TPU_CONTAINER_STORE")
    assert isinstance(make_container_store(), dict)
    with pytest.raises(ValueError):
        make_container_store("bogus")


# --- Bitmap behavior parity over both stores --------------------------------


@pytest.fixture(params=["dict", "btree"])
def store(request):
    return request.param


def test_bitmap_ops_parity(store):
    rng = np.random.default_rng(3)
    a_vals = rng.choice(1 << 22, size=5000, replace=False).astype(np.uint64)
    b_vals = rng.choice(1 << 22, size=5000, replace=False).astype(np.uint64)
    a = Bitmap(a_vals, store=store)
    b = Bitmap(b_vals, store=store)
    sa, sb = set(map(int, a_vals)), set(map(int, b_vals))
    assert a.count() == len(sa)
    assert set(a) == sa
    assert a.intersection_count(b) == len(sa & sb)
    assert set(a.intersect(b)) == sa & sb
    assert set(a.union(b)) == sa | sb
    assert set(a.difference(b)) == sa - sb
    assert set(a.xor(b)) == sa ^ sb
    assert a.min() == min(sa) and a.max() == max(sa)


def test_bitmap_mutation_and_serialization_parity(store):
    rng = np.random.default_rng(5)
    vals = rng.choice(1 << 20, size=3000, replace=False).astype(np.uint64)
    bm = Bitmap(vals, store=store)
    model = set(map(int, vals))
    for v in (0, 1, 12345, 1 << 19):
        assert bm.add(v) == (v not in model)
        model.add(v)
    for v in list(model)[:50]:
        assert bm.remove(v)
        model.discard(v)
    assert set(bm) == model
    # Pilosa-format round trip lands in the *default* store; parity is on
    # content, not store type
    rt = Bitmap.from_bytes(bm.to_bytes())
    assert set(rt) == model
    # run-heavy data to exercise run-container encode under the btree store
    dense = Bitmap(np.arange(100_000, dtype=np.uint64), store=store)
    rt2 = Bitmap.from_bytes(dense.to_bytes())
    assert rt2.count() == 100_000


def test_btree_numpy_integer_keys():
    """np.uint64 keys must behave exactly like ints (the dict store's hash
    equality) — add() paths historically produced numpy container keys."""
    t = BTreeContainers()
    t[np.uint64(5)] = "a"
    assert np.uint64(5) in t and 5 in t
    assert t[5] == "a" and t[np.uint64(5)] == "a"
    t[5] = "b"  # same key, not a sibling
    assert len(t) == 1 and t[np.uint64(5)] == "b"
    del t[np.uint64(5)]
    assert len(t) == 0
    assert "not-a-key" not in t  # uncomparable types: absent, not a crash


def test_btree_items_values_leaf_walk():
    t = BTreeContainers((k, -k) for k in range(1000))
    assert list(t.items()) == [(k, -k) for k in range(1000)]
    assert list(t.values()) == [-k for k in range(1000)]
    assert t.first_key() == 0 and t.last_key() == 999


def test_btree_descending_drain_bounded_walks():
    """Emptied-leaf unlink must be O(depth) via the descent path — a full
    leaf-chain rescan makes descending drains quadratic. Counted (not
    timed): _prev_leaf_via_path touches O(depth) nodes per call."""
    calls = {"nodes": 0}
    orig = BTreeContainers._prev_leaf_via_path  # plain function (Py3 staticmethod access)

    def counting(path, parent, ci):
        # rightmost-spine walk depth is bounded by tree height; count the
        # invocation, then measure the spine length it traverses
        calls["nodes"] += 1 + len(path)
        return orig(path, parent, ci)

    n = 40_000
    t = BTreeContainers((k, k) for k in range(n))
    try:
        BTreeContainers._prev_leaf_via_path = staticmethod(counting)
        for k in reversed(range(n)):
            del t[k]
    finally:
        BTreeContainers._prev_leaf_via_path = staticmethod(orig)
    assert len(t) == 0
    # one unlink per emptied leaf (~n/ORDER overall), each O(depth<=4):
    # far below even one full leaf-chain rescan per unlink (~(n/64)^2)
    assert calls["nodes"] < 4 * (n // 32), calls["nodes"]


def test_bitmap_derived_results_inherit_store():
    a = Bitmap(np.array([1, 2, 3], dtype=np.uint64), store="btree")
    b = Bitmap(np.array([2, 3, 4], dtype=np.uint64), store="btree")
    for derived in (a.intersect(b), a.union(b), a.difference(b), a.xor(b)):
        assert isinstance(derived.containers, BTreeContainers), derived
        assert derived.store_kind == "btree"


def test_bitmap_min_max_keys_in_btree_paths():
    vals = np.array([7, 1 << 17, (5 << 16) + 9, 1 << 21], dtype=np.uint64)
    bm = Bitmap(vals, store="btree")
    assert bm.min() == 7 and bm.max() == 1 << 21
    # _keys_in via irange
    assert bm._keys_in(0, 1 << 18) == [0, 2]
    assert bm._keys_in(5 << 16, (5 << 16) + 10) == [5]
    assert bm._keys_in(10, 10) == []


def test_fragment_lifecycle_under_btree_store(tmp_path, monkeypatch):
    """Full fragment lifecycle (open -> import -> single-bit WAL writes ->
    reopen-without-close replay) with the btree store selected process-wide
    — the enterprise-build-tag usage shape."""
    monkeypatch.setenv("PILOSA_TPU_CONTAINER_STORE", "btree")
    from pilosa_tpu.storage.fragment import Fragment

    f = Fragment(str(tmp_path / "bt"), "i", "f", "standard", 0).open()
    assert isinstance(f.storage.containers, BTreeContainers)
    f.bulk_import([0, 0, 1], [5, 9, 9])
    f.set_bit(2, 123)
    f.set_bit(2, 124)
    assert f.row_counts([0, 1, 2]).tolist() == [2, 1, 2]
    f.close()  # crash-shaped reopen is covered by test_fragment's WAL tests
    f2 = Fragment(str(tmp_path / "bt"), "i", "f", "standard", 0)
    f2.open()
    assert isinstance(f2.storage.containers, BTreeContainers)
    assert f2.row_counts([0, 1, 2]).tolist() == [2, 1, 2]
    assert sorted(f2.row_columns(2)) == [123, 124]
    f2.close()


def test_bitmap_btree_store_env(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_CONTAINER_STORE", "btree")
    bm = Bitmap(np.array([1, 2, 3], dtype=np.uint64))
    assert isinstance(bm.containers, BTreeContainers)
    assert set(bm) == {1, 2, 3}


def test_btree_drain_heavy_delete():
    """Drain-style stress (ADVICE r3): a multi-level tree loses contiguous
    runs and then ~99% of keys in random order; iteration / irange /
    first_key / last_key track a dict model throughout, exercising
    cascade leaf-unlink and _prev_leaf_via_path for real (deleting 1/3 of
    a dense range never empties an order-64 leaf)."""
    rng = np.random.default_rng(41)
    keys = list(range(10_000))
    t = BTreeContainers()
    model = {}
    for k in keys:
        t[k] = k * 3
        model[k] = k * 3

    def check():
        ms = sorted(model)
        assert len(t) == len(model)
        assert list(t) == ms
        if ms:
            assert t.first_key() == ms[0]
            assert t.last_key() == ms[-1]
            lo, hi = ms[0], ms[len(ms) // 2]
            assert list(t.irange(lo, hi)) == [k for k in ms if lo <= k <= hi]

    # contiguous runs: empties whole leaves and their parents
    for lo in (0, 3000, 9000):
        for k in range(lo, lo + 800):
            if k in model:
                del t[k]
                del model[k]
    check()
    # random-order drain down to ~1%
    remaining = list(model)
    rng.shuffle(remaining)
    for i, k in enumerate(remaining[:-80]):
        del t[k]
        del model[k]
        if i % 1500 == 0:
            check()
    check()
    # survivors still readable, then full drain to empty
    for k in sorted(model):
        assert t[k] == k * 3
        del t[k]
    assert len(t) == 0 and list(t) == []
