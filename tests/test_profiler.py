"""Distributed query profiler (utils/profile.py): the ?profile=true tree,
cross-node fragment assembly over QueryResponse.Profile, per-entry trace
propagation through coalesced envelopes, the structured slow-query history,
and the profile_mode / kill-switch gates.

Unit tests drive QueryProfile and the coalescer entry encoding directly;
the integration tests run a REAL 3-node cluster (pinned node ids, the
test_coalesce fixture recipe) and assert the acceptance shape: per-node
RPC timings for every remote shard group, a device-dispatch record with
batch_size >= 1, residency hit/miss counts, and remote fragments — plus
mixed-version degradation to a coordinator-only tree."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.utils import profile as qprofile

SW = SHARD_WIDTH


# ------------------------------------------------------------------- unit


def test_query_profile_records_and_serializes():
    p = qprofile.QueryProfile(trace_id="t1", node_id="a", index="i",
                              pql="Count(Row(f=1))")
    p.record_call("Count", 12.5)
    p.record_fanout("b", 3, 9.5, "coalesced")
    p.record_hedge("b", "c", won=True)
    p.record_retry("d", 2, "ConnectionError: boom")
    p.record_dispatch("CountBatcher", 7, 4, 8.0)
    p.record_residency(hit=True)
    p.record_residency(hit=False, nbytes=1024)
    p.add_remote_fragment("http://b:1", {"node": "b", "calls": []})
    p.finish()
    d = p.to_dict()
    assert d["traceId"] == "t1" and d["node"] == "a"
    assert d["calls"] == [{"call": "Count", "ms": 12.5}]
    assert d["fanout"][0]["transport"] == "coalesced"
    assert d["fanout"][1] == {"node": "b", "hedgeNode": "c",
                              "kind": "hedge", "hedgeWon": True}
    assert d["fanout"][2]["kind"] == "failover"
    disp = d["dispatches"][0]
    assert disp["batchSize"] == 4 and disp["shareMs"] == 2.0
    assert d["residency"] == {"hits": 1, "misses": 1,
                              "hostToDeviceBytes": 1024}
    assert d["remoteProfiles"][0]["node"] == "http://b:1"
    assert d["elapsedMs"] >= 0
    json.dumps(d)  # the tree must be JSON-clean as-is


def test_truncate_pql_and_history_ring():
    assert qprofile.truncate_pql("short") == "short"
    long = "Set(" + "1" * 500 + ")"
    out = qprofile.truncate_pql(long, limit=64)
    assert len(out) == 64 and out.endswith("...")
    h = qprofile.QueryHistory(size=3)
    for i in range(5):
        h.append({"i": i})
    snap = h.snapshot()
    assert [e["i"] for e in snap] == [4, 3, 2]  # newest first, bounded


def test_invalid_profile_mode_fails_boot(tmp_path):
    from pilosa_tpu.server import Server
    with pytest.raises(ValueError, match="profile mode"):
        Server(str(tmp_path / "bad"), port=0, profile_mode="On")


def test_nop_fast_path_default():
    # with no profile installed, every instrumentation site reads None
    assert qprofile.current_profile.get() is None
    assert qprofile.current() is None


def test_finish_seals_against_late_records():
    """A discarded hedge loser's RPC can land AFTER the response was
    serialized; finish() seals the profile so every surface (response,
    history, wire fragment) sees one deterministic tree."""
    p = qprofile.QueryProfile(trace_id="t", node_id="a")
    p.record_fanout("b", 2, 5.0, "coalesced")
    p.finish()
    d1 = p.to_dict()
    p.record_fanout("c", 1, 99.0, "proto")  # late loser: dropped
    p.record_call("Count", 1.0)
    p.record_dispatch("CountBatcher", 1, 1, 1.0)
    p.record_residency(hit=True)
    p.add_remote_fragment("http://c:1", {})
    d2 = p.to_dict()
    assert d1 is d2  # sealed tree memoizes: one serialization, identical
    assert len(d2["fanout"]) == 1 and d2["fanout"][0]["node"] == "b"
    assert d2["calls"] == [] and d2["dispatches"] == []
    assert d2["remoteProfiles"] == []


def test_coalescer_entries_carry_trace_and_profile_flags():
    """Per-entry trace id mirrors the per-entry deadline: the envelope
    must carry each caller's OWN trace id and profile request, and
    deduped followers must not erase the first caller's trace."""
    from tests.test_coalesce import FakeClient
    from pilosa_tpu.net.coalesce import NodeCoalescer

    fc = FakeClient()
    co = NodeCoalescer(fc, window_s=0.0)
    co._compute(("http://n1:1",), [
        ("idx", "q1", None, None, "trace-A", True, "key:a", "batch"),
        ("idx", "q2", None, 1.5, None, False, None, None),
        # dedup of q1: later caller must not erase the first trace, and
        # its more urgent class upgrades the shared execution
        ("idx", "q1", None, None, "trace-B", False, "key:b",
         "interactive"),
    ])
    entries = fc.batch_calls[0]
    assert len(entries) == 2  # q1 deduped
    e1 = next(e for e in entries if e["query"] == "q1")
    e2 = next(e for e in entries if e["query"] == "q2")
    assert e1["traceId"] == "trace-A"  # first caller's trace wins
    assert e1["profile"] is True  # any profiled dup profiles the execution
    assert e1["priority"] == "interactive"  # most urgent dup wins
    assert "traceId" not in e2 and "profile" not in e2
    assert "priority" not in e2
    assert e2["timeout"] == 1.5


def test_query_batch_installs_per_entry_trace(tmp_path):
    """The remote side of satellite 1: api.query_batch installs each
    entry's traceId via tracing.current_trace_id before executing, so
    remote spans join the coordinator's trace instead of minting one."""
    from pilosa_tpu.server import Server

    s = Server(str(tmp_path / "n"), port=0).open()
    try:
        jpost(s.uri, "/index/i", {})
        jpost(s.uri, "/index/i/field/f", {})
        jpost(s.uri, "/index/i/query", raw=b"Set(5, f=1)")
        out = s.api.query_batch([
            {"index": "i", "query": "Count(Row(f=1))", "remote": True,
             "traceId": "envelope-trace-1"},
            {"index": "i", "query": "Count(Row(f=1))", "remote": True,
             "traceId": "envelope-trace-2"},
        ])
        assert [r for r, *_ in out] == [[1], [1]]
        got = {sp.trace_id for sp in s.tracer.finished("executor.Count")}
        # BOTH entries' spans carry their own caller's trace id — the
        # pre-fix behavior gave every entry the envelope leader's trace
        assert {"envelope-trace-1", "envelope-trace-2"} <= got, got
    finally:
        s.close()


# ------------------------------------------------------------ integration


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def jget(uri, path):
    with urllib.request.urlopen(uri + path, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """3-node cluster with PINNED node ids (the test_coalesce recipe): the
    jump-hash placement is deterministic, so fan-out from node 0 reaches
    both remote nodes on every run."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("profcluster")
    servers = [Server(str(tmp / f"n{i}"), port=0,
                      node_id=chr(ord("a") + i)).open()
               for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    u = uris[0]
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/f", {})
    rng = np.random.default_rng(61)
    cols = np.unique(rng.choice(6 * SW, 3000))
    half = cols.size // 2
    jpost(u, "/index/i/field/f/import",
          {"rowIDs": [0] * half + [1] * (cols.size - half),
           "columnIDs": cols.tolist()})
    # wait for cross-node shard visibility (async create-shard announce)
    q = b"Count(Union(Row(f=0), Row(f=1)))"
    deadline = time.monotonic() + 30
    for uri in uris:
        while jpost(uri, "/index/i/query", raw=q)["results"][0] != cols.size:
            assert time.monotonic() < deadline
            time.sleep(0.2)
    yield servers, uris
    for s in servers:
        s.close()


def test_distributed_profile_tree_acceptance_shape(cluster):
    """The acceptance query: ?profile=true on a 3-node cluster returns a
    tree with per-node RPC timings for every remote shard group, a device
    dispatch record with batch_size >= 1 + residency counts, and remote
    fragments assembled from the QueryResponse.Profile protobuf field."""
    servers, uris = cluster
    # plan cache off for this test: a warm repeat would be served from the
    # cached Count scalar with (correctly) zero dispatches and zero
    # residency lookups — this test asserts the attribution plumbing
    # underneath the cache
    for s in servers:
        s.executor.plan_cache.enabled = False
    try:
        # run twice: the second profile sees warm residency (hits) while
        # the assertions stay valid for both
        jpost(uris[0], "/index/i/query?profile=true", raw=b"Count(Row(f=0))")
        out = jpost(uris[0], "/index/i/query?profile=true",
                    raw=b"Count(Row(f=0))")
    finally:
        for s in servers:
            s.executor.plan_cache.enabled = True
    prof = out["profile"]
    assert prof["traceId"] and prof["node"] == "a"
    assert prof["calls"] and prof["calls"][0]["call"] == "Count"

    # per-node RPC timings for every remote shard group the planner built
    groups = servers[0].cluster.shards_by_node(
        "i", servers[0].executor._query_shards(
            servers[0].holder.index("i"), None))
    remote_ids = {nid for nid in groups if nid != "a"}
    assert remote_ids  # pinned ids split ownership — fan-out must exist
    timed = {f["node"] for f in prof["fanout"]
             if "ms" in f and f.get("transport") != "local"}
    assert remote_ids <= timed, (remote_ids, prof["fanout"])
    for f in prof["fanout"]:
        if "ms" in f:
            assert f["ms"] >= 0 and f["shards"] >= 1

    # device dispatch attribution with the batch size this query shared
    assert any(d["batchSize"] >= 1 and d["wallMs"] >= 0
               for d in prof["dispatches"]), prof["dispatches"]
    # residency hit/miss counts (warm run: the leaf is HBM-resident)
    res = prof["residency"]
    assert res["hits"] + res["misses"] >= 1

    # remote fragments: one per remote node, carried in the protobuf
    # field (through the coalesced envelope's per-entry slots here)
    frag_nodes = {r["profile"]["node"] for r in prof["remoteProfiles"]}
    assert remote_ids <= frag_nodes, (remote_ids, frag_nodes)
    # remote spans of this query joined the coordinator's trace
    for r in prof["remoteProfiles"]:
        assert r["profile"]["traceId"] == prof["traceId"]
        assert r["profile"]["calls"]
        # batch entries profile the RAW PQL, not a parsed Query repr
        assert r["profile"]["pql"] == "Count(Row(f=0))", r["profile"]["pql"]


def test_remote_spans_join_coordinator_trace_through_envelope(cluster):
    """Satellite 1 end-to-end: remote executor spans of a coalesced
    distributed query carry the coordinator's trace id."""
    servers, uris = cluster
    req = urllib.request.Request(
        uris[0] + "/index/i/query", data=b"Count(Row(f=1))", method="POST",
        headers={"X-Pilosa-Trace-Id": "prof-trace-join"})
    with urllib.request.urlopen(req, timeout=30) as r:
        json.loads(r.read())
    remote_hits = [
        s.node_id for s in servers[1:]
        if any(sp.trace_id == "prof-trace-join"
               for sp in s.tracer.finished("executor.Count"))]
    assert remote_hits, "no remote span joined the coordinator's trace"


def test_mixed_version_legacy_peer_degrades_to_coordinator_only(cluster):
    """A peer that sends no Profile fragment (legacy binary / profiling
    off) must degrade the tree, not the query: results stay correct, the
    coordinator's own attribution is intact, and only that node's child
    is missing."""
    servers, uris = cluster
    old_mode = servers[1].api.profile_mode
    servers[1].api.profile_mode = "off"  # behaves like a legacy peer:
    # QueryRequest.Profile is ignored, QueryResponse.Profile stays absent
    try:
        out = jpost(uris[0], "/index/i/query?profile=true",
                    raw=b"Count(Row(f=0))")
        prof = out["profile"]
        assert out["results"][0] > 0
        frag_nodes = {r["profile"]["node"] for r in prof["remoteProfiles"]}
        assert "b" not in frag_nodes  # the legacy peer contributed nothing
        # the coordinator still timed node b's RPC (attribution survives)
        assert any(f.get("node") == "b" and "ms" in f
                   for f in prof["fanout"]), prof["fanout"]
    finally:
        servers[1].api.profile_mode = old_mode


def test_profile_mode_off_and_kill_switch(cluster):
    servers, uris = cluster
    api = servers[0].api
    old = api.profile_mode
    try:
        api.profile_mode = "off"
        out = jpost(uris[0], "/index/i/query?profile=true",
                    raw=b"Count(Row(f=0))")
        assert "profile" not in out
        api.profile_mode = "auto"
        api._profile_killed = True  # PILOSA_TPU_PROFILE=0 at boot
        out = jpost(uris[0], "/index/i/query?profile=true",
                    raw=b"Count(Row(f=0))")
        assert "profile" not in out
    finally:
        api.profile_mode = old
        api._profile_killed = False
    # and without the flag, no profile rides the response
    out = jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=0))")
    assert "profile" not in out


def test_proto_query_path_carries_profile(cluster):
    """The protobuf codec path: QueryRequest.Profile in,
    QueryResponse.Profile out (what remote nodes speak)."""
    from pilosa_tpu.encoding.protobuf import CONTENT_TYPE, Serializer
    servers, uris = cluster
    s = Serializer()
    body = s.encode_query_request("Count(Row(f=0))", profile=True)
    req = urllib.request.Request(
        uris[0] + "/index/i/query", data=body, method="POST",
        headers={"Content-Type": CONTENT_TYPE, "Accept": CONTENT_TYPE})
    with urllib.request.urlopen(req, timeout=30) as r:
        resp = s.decode_query_response(r.read())
    assert resp["err"] == ""
    assert resp["profile"] is not None
    assert resp["profile"]["calls"][0]["call"] == "Count"


def test_slow_query_history_and_truncated_log_line(cluster):
    """Satellite 2 + the history surface: queries over long-query-time
    land in /debug/query-history with trace id, truncated PQL, elapsed
    and profile; the log line truncates the PQL and appends trace=<id>."""
    import io
    from pilosa_tpu.utils.logger import Logger

    servers, uris = cluster
    api = servers[0].api
    buf = io.StringIO()
    old_logger, old_lqt = api.logger, api.long_query_time
    api.logger = Logger(out=buf)
    api.long_query_time = 1e-9  # everything is slow
    try:
        # a PQL long enough to need truncation (batched Sets pad it)
        pql = "Count(Union(" + ", ".join(
            f"Row(f={i})" for i in range(60)) + "))"
        assert len(pql) > 256
        jpost(uris[0], "/index/i/query", raw=pql.encode())
        hist = jget(uris[0], "/debug/query-history")["queries"]
        assert hist, "slow query never reached the history ring"
        e = hist[0]
        assert e["pql"].endswith("...") and len(e["pql"]) <= 256
        assert e["traceId"] and e["traceId"] != "-"
        assert e["elapsed"] > 0
        # auto mode + long-query-time set => the entry carries a profile
        assert e["profile"] is not None
        assert e["profile"]["traceId"] == e["traceId"]
        line = buf.getvalue()
        assert "SLOW QUERY" in line
        assert f"trace={e['traceId']}" in line
        assert pql not in line  # raw unbounded PQL never hits the log
    finally:
        api.logger, api.long_query_time = old_logger, old_lqt


def test_history_ring_is_bounded(cluster):
    servers, uris = cluster
    api = servers[0].api
    old_size, old_lqt = api.query_history.size, api.long_query_time
    api.query_history.size = 3
    api.long_query_time = 1e-9
    try:
        for i in range(6):
            jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=0))")
        hist = jget(uris[0], "/debug/query-history")["queries"]
        assert len(hist) == 3
    finally:
        api.query_history.size = old_size
        api.long_query_time = old_lqt


def test_profiled_queries_answer_identically_under_concurrency(cluster):
    """Profiling must be an observer: concurrent profiled + unprofiled
    queries (coalescing + device batching active) return identical
    results, and each profiled response carries its own tree."""
    servers, uris = cluster
    expect = jpost(uris[0], "/index/i/query",
                   raw=b"Count(Row(f=0))")["results"][0]
    errs = []

    def go(i):
        try:
            path = "/index/i/query" + ("?profile=true" if i % 2 else "")
            out = jpost(uris[0], path, raw=b"Count(Row(f=0))")
            assert out["results"][0] == expect
            if i % 2:
                assert out["profile"]["calls"][0]["call"] == "Count"
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go, args=(i,)) for i in range(10)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
