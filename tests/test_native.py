"""Native C++ kernel tests: parity with the Python/numpy implementations."""

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.parallel.placement import fnv64a as py_fnv64a
from pilosa_tpu.storage.roaring import fnv1a32 as py_fnv1a32

RNG = np.random.default_rng(13)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native build unavailable")


def test_build_succeeded():
    assert native.lib() is not None


def test_hashes_match_python():
    for data in (b"", b"a", b"foobar", bytes(RNG.integers(0, 256, 100, dtype=np.uint8))):
        assert native.fnv1a32(data) == py_fnv1a32(data)
        assert native.fnv64a(data) == py_fnv64a(data)


def test_popcounts():
    words = RNG.integers(0, 2**64, 4096, dtype=np.uint64)
    other = RNG.integers(0, 2**64, 4096, dtype=np.uint64)
    assert native.popcount64(words) == int(np.sum(np.bitwise_count(words)))
    assert native.and_count(words, other) == int(np.sum(np.bitwise_count(words & other)))


@pytest.mark.parametrize("kind,npop", [
    ("and", lambda a, b: np.intersect1d(a, b)),
    ("or", lambda a, b: np.union1d(a, b)),
    ("andnot", lambda a, b: np.setdiff1d(a, b)),
    ("xor", lambda a, b: np.setxor1d(a, b)),
])
def test_array_ops(kind, npop):
    a = np.unique(RNG.integers(0, 1 << 16, 3000)).astype(np.uint16)
    b = np.unique(RNG.integers(0, 1 << 16, 5000)).astype(np.uint16)
    got = native.array_op(a, b, kind)
    np.testing.assert_array_equal(got, npop(a, b).astype(np.uint16))
    # empties
    empty = np.empty(0, dtype=np.uint16)
    np.testing.assert_array_equal(native.array_op(a, empty, kind),
                                  npop(a, empty).astype(np.uint16))


def test_bits_roundtrip():
    vals = np.unique(RNG.integers(0, 1 << 16, 9000)).astype(np.uint16)
    words = native.array_to_bits(vals)
    assert native.popcount64(words) == vals.size
    back = native.bits_to_array(words)
    np.testing.assert_array_equal(back, vals)
    # edges
    edge = np.array([0, 63, 64, 65535], dtype=np.uint16)
    np.testing.assert_array_equal(native.bits_to_array(native.array_to_bits(edge)), edge)


def test_positions_to_dense():
    width = 1 << 20
    start = 5 * width
    offs = np.unique(RNG.integers(0, width, 5000)).astype(np.uint64)
    positions = offs + np.uint64(start)
    # plus out-of-range noise that must be ignored
    noise = np.array([0, start - 1, start + width, 2**63], dtype=np.uint64)
    dense = native.positions_to_dense(np.concatenate([positions, noise]), start, width)
    from pilosa_tpu.ops.bitvector import columns_from_dense
    np.testing.assert_array_equal(columns_from_dense(dense), offs.astype(np.int64))


def test_oplog_parse():
    import struct
    from pilosa_tpu.storage.roaring import OP_ADD, OP_REMOVE
    recs = []
    for typ, val in [(OP_ADD, 5), (OP_ADD, 2**40), (OP_REMOVE, 5)]:
        body = struct.pack("<BQ", typ, val)
        recs.append(body + struct.pack("<I", py_fnv1a32(body)))
    data = b"".join(recs)
    types, values = native.oplog_parse(data)
    assert types.tolist() == [OP_ADD, OP_ADD, OP_REMOVE]
    assert values.tolist() == [5, 2**40, 5]
    # corruption detected
    assert native.oplog_parse(data[:-1]) is None
    bad = bytearray(data)
    bad[9] ^= 0xFF
    assert native.oplog_parse(bytes(bad)) is None
