"""GroupBy parity: the device cross-count path vs a host brute-force oracle.

The single-program GroupBy (executor._execute_group_by over the
cross_count_matrix kernel family) must agree bit-for-bit with a naive
host-side set walk on randomized multi-axis schemas — across filter, limit
(including limit=0), single-axis, empty-axis, and mesh vs single-device
runners — and must pay at most ONE host sync per cross-product level
(the groupby_host_syncs dispatch-count contract, analogous to the
topn_recount_rows assertion in test_topn.py).
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import Holder
from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh


def build_random_index(holder, rng, axes, n_cols, bits_per_row,
                       name="gpar"):
    """Create fields with random rows; returns {(field, row): set(cols)}."""
    idx = holder.create_index(name, track_existence=False)
    sets = {}
    for fname, row_ids in axes:
        f = idx.create_field(fname)
        rids, cids = [], []
        for r in row_ids:
            cols = rng.choice(n_cols, size=bits_per_row, replace=False)
            sets[(fname, r)] = set(int(c) for c in cols)
            rids += [r] * len(cols)
            cids += [int(c) for c in cols]
        f.import_bits(rids, cids)
    return sets


def oracle_groups(sets, axes, filter_cols=None, limit=None):
    """Brute-force lexicographic cross product with intersection counts."""
    out = []

    def rec(level, acc_cols, group):
        if limit is not None and len(out) >= limit:
            return
        if level == len(axes):
            if acc_cols:
                out.append({"group": list(group), "count": len(acc_cols)})
            return
        fname, row_ids = axes[level]
        for r in sorted(row_ids):
            cols = sets[(fname, r)]
            nxt = acc_cols & cols if acc_cols is not None else set(cols)
            rec(level + 1, nxt,
                group + [{"field": fname, "rowID": r}])

    base = set(filter_cols) if filter_cols is not None else None
    rec(0, base, [])
    return out


@pytest.fixture(params=["single", "mesh"])
def gex(tmp_path, request):
    h = Holder(str(tmp_path / "data")).open()
    mesh = make_mesh() if request.param == "mesh" else None
    e = Executor(h, runner=DeviceRunner(mesh))
    yield e
    h.close()


def test_randomized_two_axis_parity(gex):
    rng = np.random.default_rng(31)
    axes = [("a", list(range(12))), ("b", list(range(9)))]
    sets = build_random_index(gex.holder, rng, axes, 3000, 150)
    (groups,) = gex.execute("gpar", "GroupBy(Rows(field=a), Rows(field=b))")
    assert list(groups) == oracle_groups(sets, axes)


def test_randomized_three_axis_filter_parity(gex):
    rng = np.random.default_rng(33)
    axes = [("a", [0, 2, 5, 7]), ("b", [1, 3, 4]), ("c", [0, 1, 2])]
    # span two shards so per-shard reduction is exercised
    sets = build_random_index(gex.holder, rng, axes,
                              SHARD_WIDTH + 5000, 400)
    filt = sets[("a", 0)] | sets[("a", 5)]
    (groups,) = gex.execute(
        "gpar", "GroupBy(Rows(field=a), Rows(field=b), Rows(field=c), "
                "filter=Union(Row(a=0), Row(a=5)))")
    assert list(groups) == oracle_groups(sets, axes, filter_cols=filt)


def test_limit_zero_and_limit_parity(gex):
    rng = np.random.default_rng(35)
    axes = [("a", list(range(6))), ("b", list(range(6)))]
    sets = build_random_index(gex.holder, rng, axes, 2000, 200)
    (zero,) = gex.execute("gpar",
                          "GroupBy(Rows(field=a), Rows(field=b), limit=0)")
    assert list(zero) == []
    for limit in (1, 5, 17):
        (got,) = gex.execute(
            "gpar", f"GroupBy(Rows(field=a), Rows(field=b), limit={limit})")
        assert list(got) == oracle_groups(sets, axes, limit=limit)


def test_single_axis_and_empty_axis(gex):
    rng = np.random.default_rng(37)
    axes = [("a", [1, 4, 9])]
    sets = build_random_index(gex.holder, rng, axes, 1500, 80)
    gex.holder.index("gpar").create_field("empty")
    (groups,) = gex.execute("gpar", "GroupBy(Rows(field=a))")
    assert list(groups) == oracle_groups(sets, axes)
    # an axis with no rows short-circuits to no groups (and no device work)
    before = gex.groupby_host_syncs
    (none,) = gex.execute("gpar",
                          "GroupBy(Rows(field=a), Rows(field=empty))")
    assert list(none) == []
    assert gex.groupby_host_syncs == before


def test_one_host_sync_per_level(gex):
    """The pipelined device path's dispatch contract: every chunk of a
    level is enqueued before one batched fetch — multi-axis GroupBy pays
    exactly len(axes)-1 syncs, single-axis exactly 1, warm or cold."""
    rng = np.random.default_rng(39)
    axes = [("a", list(range(10))), ("b", list(range(8))),
            ("c", list(range(5)))]
    build_random_index(gex.holder, rng, axes, 4000, 120)
    for _ in range(2):  # cold (slab upload) and warm (residency hit)
        before = gex.groupby_host_syncs
        gex.execute("gpar",
                    "GroupBy(Rows(field=a), Rows(field=b), Rows(field=c))")
        assert gex.groupby_host_syncs - before == 2
    before = gex.groupby_host_syncs
    gex.execute("gpar", "GroupBy(Rows(field=a))")
    assert gex.groupby_host_syncs - before == 1


def test_live_bound_overflow_fallback(gex):
    """A chunk whose live combinations exceed the static prune bound must
    fall back to the full count-matrix fetch — exact results, extra sync
    counted, no group silently dropped."""
    rng = np.random.default_rng(41)
    axes = [("a", list(range(7))), ("b", list(range(7)))]
    sets = build_random_index(gex.holder, rng, axes, 800, 300)
    (expect,) = gex.execute("gpar", "GroupBy(Rows(field=a), Rows(field=b))")
    gex._groupby_live_cap = 1  # force overflow on every chunk
    before = gex.groupby_host_syncs
    (got,) = gex.execute("gpar", "GroupBy(Rows(field=a), Rows(field=b))")
    assert list(got) == list(expect) == oracle_groups(sets, axes)
    assert gex.groupby_host_syncs - before > 1  # fallback syncs recorded


def test_limited_final_level_waves(tmp_path):
    """A limited final level spanning multiple chunks: the lex-first-chunk
    probe satisfies a small limit in one sync; a limit beyond the probe's
    yield pays exactly one extra sync for the remaining chunks and still
    returns the full lexicographic prefix."""
    h = Holder(str(tmp_path / "data")).open()
    ex = Executor(h, runner=DeviceRunner())
    try:
        rng = np.random.default_rng(47)
        # 40x26 live prefixes = 1040 > the 512-prefix chunk cap, so the
        # final (c) level runs 3 chunks; a shared core column block keeps
        # every combination nonzero
        axes = [("a", list(range(40))), ("b", list(range(26))),
                ("c", list(range(5)))]
        core = list(range(20))
        sets = {}
        idx = h.create_index("gw", track_existence=False)
        for fname, rows in axes:
            f = idx.create_field(fname)
            rids, cids = [], []
            for r in rows:
                cols = set(core) | set(
                    int(c) for c in rng.choice(480, size=40, replace=False))
                sets[(fname, r)] = cols
                rids += [r] * len(cols)
                cids += list(cols)
            f.import_bits(rids, cids)
        q = "GroupBy(Rows(field=a), Rows(field=b), Rows(field=c))"
        before = ex.groupby_host_syncs
        (unlimited,) = ex.execute("gw", q)
        assert ex.groupby_host_syncs - before == 2  # one per level
        assert list(unlimited) == oracle_groups(sets, axes)
        # small limit: probe chunk alone satisfies it — still 2 syncs
        before = ex.groupby_host_syncs
        (small,) = ex.execute("gw", q[:-1] + ", limit=100)")
        assert ex.groupby_host_syncs - before == 2
        assert list(small) == list(unlimited)[:100]
        # limit beyond the whole result: the probe misses, the second
        # wave covers the remaining chunks — exactly one extra sync
        before = ex.groupby_host_syncs
        (huge,) = ex.execute("gw", q[:-1] + ", limit=100000)")
        assert ex.groupby_host_syncs - before == 3
        assert list(huge) == list(unlimited)
    finally:
        h.close()


def test_mesh_vs_single_device_agreement(tmp_path):
    """The sharded shard_map form and the single-device form must produce
    identical groups on identical data — including with a filter and a
    limit in play."""
    rng_bits = np.random.default_rng(43)
    cols = {}
    axes = [("a", list(range(9))), ("b", list(range(7)))]
    for fname, rows in axes:
        for r in rows:
            cols[(fname, r)] = rng_bits.choice(
                2 * SHARD_WIDTH, size=250, replace=False)
    results = {}
    for mode in ("single", "mesh", "replica_mesh"):
        h = Holder(str(tmp_path / mode)).open()
        mesh = None
        if mode == "mesh":
            mesh = make_mesh()
        elif mode == "replica_mesh":
            mesh = make_mesh(replicas=2)
        ex = Executor(h, runner=DeviceRunner(mesh))
        idx = h.create_index("gm", track_existence=False)
        for fname, rows in axes:
            f = idx.create_field(fname)
            rids, cids = [], []
            for r in rows:
                rids += [r] * len(cols[(fname, r)])
                cids += [int(c) for c in cols[(fname, r)]]
            f.import_bits(rids, cids)
        out = {}
        (out["plain"],) = ex.execute(
            "gm", "GroupBy(Rows(field=a), Rows(field=b))")
        (out["filtered"],) = ex.execute(
            "gm", "GroupBy(Rows(field=a), Rows(field=b), filter=Row(a=3))")
        (out["limited"],) = ex.execute(
            "gm", "GroupBy(Rows(field=a), Rows(field=b), limit=11)")
        results[mode] = {k: list(v) for k, v in out.items()}
        h.close()
    assert results["single"] == results["mesh"] == results["replica_mesh"]
