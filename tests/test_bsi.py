"""BSI kernel tests vs. numpy integer ground truth.

Mirrors the reference's fragment BSI tests (fragment_internal_test.go:
setValue/sum/min/max/range cases) with randomized values.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitvector as bv
from pilosa_tpu.ops import bsi

WIDTH = 1 << 16  # small shard width for test speed
DEPTH = 12
RNG = np.random.default_rng(7)


def make_planes(values: dict[int, int], depth=DEPTH, width=WIDTH):
    """Build dense bit planes + existence row from {column: value}."""
    planes = np.zeros((depth, width // 32), dtype=np.uint32)
    exists_cols = np.array(sorted(values), dtype=np.int64)
    for i in range(depth):
        cols = [c for c, v in values.items() if (v >> i) & 1]
        planes[i] = bv.dense_from_columns(np.array(cols, dtype=np.int64), width)
    exists = bv.dense_from_columns(exists_cols, width)
    return planes, exists


@pytest.fixture(scope="module")
def data():
    cols = np.unique(RNG.integers(0, WIDTH, size=800))
    values = {int(c): int(RNG.integers(0, 1 << DEPTH)) for c in cols}
    planes, exists = make_planes(values)
    return values, planes, exists


def test_sum(data):
    values, planes, exists = data
    counts = np.asarray(bsi.plane_counts(planes, exists))
    assert bsi.counts_to_sum(counts) == sum(values.values())
    assert int(bv.popcount(exists)) == len(values)


def test_sum_with_filter(data):
    values, planes, exists = data
    keep = [c for c in values if c % 3 == 0]
    filt = bv.dense_from_columns(np.array(keep, dtype=np.int64), WIDTH)
    filt = np.asarray(bv.band(filt, exists))
    counts = np.asarray(bsi.plane_counts(planes, filt))
    assert bsi.counts_to_sum(counts) == sum(values[c] for c in keep)


def test_min_max(data):
    values, planes, exists = data
    bits, cnt = bsi.bsi_min(planes, exists)
    vmin = min(values.values())
    assert bsi.bits_to_value(np.asarray(bits)) == vmin
    assert int(cnt) == sum(1 for v in values.values() if v == vmin)

    bits, cnt = bsi.bsi_max(planes, exists)
    vmax = max(values.values())
    assert bsi.bits_to_value(np.asarray(bits)) == vmax
    assert int(cnt) == sum(1 for v in values.values() if v == vmax)


def test_min_max_empty_candidate(data):
    _, planes, _ = data
    empty = np.zeros(WIDTH // 32, dtype=np.uint32)
    _, cnt = bsi.bsi_min(planes, empty)
    assert int(cnt) == 0
    _, cnt = bsi.bsi_max(planes, empty)
    assert int(cnt) == 0


@pytest.mark.parametrize("op,pyop", [
    (bsi.LT, lambda v, p: v < p),
    (bsi.LTE, lambda v, p: v <= p),
    (bsi.GT, lambda v, p: v > p),
    (bsi.GTE, lambda v, p: v >= p),
    (bsi.EQ, lambda v, p: v == p),
    (bsi.NEQ, lambda v, p: v != p),
])
@pytest.mark.parametrize("pred", [0, 1, 1000, (1 << DEPTH) - 1, 2048])
def test_compare(data, op, pyop, pred):
    values, planes, exists = data
    pred_bits = bsi.value_to_bits(pred, DEPTH)
    got = set(bv.columns_from_dense(np.asarray(bsi.compare(planes, exists, pred_bits, op))).tolist())
    expect = {c for c, v in values.items() if pyop(v, pred)}
    assert got == expect


def test_between(data):
    values, planes, exists = data
    a, b = 500, 3000
    lo = bsi.compare(planes, exists, bsi.value_to_bits(a, DEPTH), bsi.GTE)
    hi = bsi.compare(planes, exists, bsi.value_to_bits(b, DEPTH), bsi.LTE)
    got = set(bv.columns_from_dense(np.asarray(bv.band(lo, hi))).tolist())
    expect = {c for c, v in values.items() if a <= v <= b}
    assert got == expect


def test_value_bits_roundtrip():
    for v in (0, 1, 12345, (1 << 40) + 17):
        assert bsi.bits_to_value(bsi.value_to_bits(v, 48)) == v
    with pytest.raises(ValueError):
        bsi.value_to_bits(-1, 8)


def test_plane_slab_residency_reuse(tmp_path):
    """The stacked [depth, S', W] plane slab is residency-cached by plane
    generations: repeat aggregations must not re-miss, and a write must
    invalidate (new key -> one new miss)."""
    import numpy as np

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    h = Holder(str(tmp_path)).open()
    ex = Executor(h)
    idx = h.create_index("ps", track_existence=False)
    v = idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=63))
    v.import_values(np.arange(100, dtype=np.uint64),
                    np.arange(100, dtype=np.int64) % 64)
    ex.execute("ps", "Sum(field=v)")
    misses0 = ex.residency.misses
    for _ in range(3):
        ex.execute("ps", "Sum(field=v)")
        ex.execute("ps", "Min(field=v)")
    assert ex.residency.misses == misses0  # warm: no new uploads or stacks
    (vc,) = ex.execute("ps", "Sum(field=v)")
    assert vc.count == 100
    ex.execute("ps", "Set(7, v=5)")  # mutation bumps plane generations
    (vc2,) = ex.execute("ps", "Sum(field=v)")
    assert vc2.val == vc.val - (7 % 64) + 5
    assert ex.residency.misses > misses0  # slab re-keyed and rebuilt
    h.close()
