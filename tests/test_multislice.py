"""The REAL Executor over a simulated two-slice multislice mesh.

make_multislice_mesh maps one replica slice per TPU slice (DCN between
slices, ICI within — SURVEY §5.8). The shared harness
(__graft_entry__.run_multislice_dryrun) substitutes the slice bucketer
on CPU test devices (which carry no slice topology) and drives the
production path end-to-end: mesh construction, DeviceRunner,
CountBatcher replica scatter, executor dispatch — Count(Intersect),
32 concurrent batched counts, TopN, BSI Sum(Range), GroupBy, query
stream, all asserted against host set algebra, plus the check that the
data plane never shards over the replica axis (the DCN carries queries,
not corpus).
"""

import pytest

import __graft_entry__ as graft


def test_executor_on_two_slice_multislice_mesh():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device test mesh")
    graft.run_multislice_dryrun(devs[:8])
