"""Distributed executor corpus: PQL scenario tables through a REAL 3-node
cluster over HTTP (replica_n=2), checked against the same Python set
models as the single-node corpus — and asserted IDENTICAL from every
node (the remote re-parse / mapReduce fan-out path, executor.go:2183,
2142 remoteExec)."""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server import Server

SW = SHARD_WIDTH


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dcorpus")
    servers = [Server(str(tmp / f"n{i}"), port=0, replica_n=2).open()
               for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    rng = np.random.default_rng(83)
    sets: dict = {}
    u = uris[0]
    jpost(u, "/index/d", {})
    jpost(u, "/index/d/field/f", {})
    jpost(u, "/index/d/field/g", {})
    jpost(u, "/index/d/field/v",
          {"options": {"type": "int", "min": -50, "max": 1000}})
    for fname, n_rows in (("f", 4), ("g", 3)):
        for r in range(n_rows):
            cols = np.unique(rng.integers(0, 3 * SW, 120 + 31 * r))
            sets[(fname, r)] = set(int(c) for c in cols)
            st, _ = jpost(u, f"/index/d/field/{fname}/import",
                          {"rowIDs": [r] * cols.size,
                           "columnIDs": cols.tolist()})
            assert st == 200
    vals = {}
    vcols = rng.choice(2 * SW, 400, replace=False)
    vvals = rng.integers(-50, 1000, 400)
    for c, v in zip(vcols.tolist(), vvals.tolist()):
        vals[c] = v
    jpost(u, "/index/d/field/v/import",
          {"columnIDs": vcols.tolist(), "values": vvals.tolist()})
    jpost(u, "/recalculate-caches")
    yield uris, sets, vals
    for s in servers:
        s.close()


def q_all_nodes(uris, pql):
    outs = []
    for u in uris:
        st, out = jpost(u, "/index/d/query", raw=pql.encode())
        assert st == 200, (u, pql, out)
        outs.append(out["results"][0])
    assert outs[0] == outs[1] == outs[2], (pql, outs)
    return outs[0]


def test_distributed_algebra(cluster):
    uris, sets, _ = cluster
    cases = [
        ("Count(Intersect(Row(f=0), Row(f=1)))",
         len(sets[("f", 0)] & sets[("f", 1)])),
        ("Count(Union(Row(f=0), Row(g=0), Row(g=2)))",
         len(sets[("f", 0)] | sets[("g", 0)] | sets[("g", 2)])),
        ("Count(Difference(Row(f=3), Row(g=1)))",
         len(sets[("f", 3)] - sets[("g", 1)])),
        ("Count(Xor(Row(f=2), Row(g=2)))",
         len(sets[("f", 2)] ^ sets[("g", 2)])),
        ("Count(Row(f=99))", 0),
    ]
    for pql, expect in cases:
        assert q_all_nodes(uris, pql) == expect, pql


def test_distributed_row_columns(cluster):
    uris, sets, _ = cluster
    got = q_all_nodes(uris, "Intersect(Row(f=1), Row(g=1))")
    assert got["columns"] == sorted(sets[("f", 1)] & sets[("g", 1)])


def test_distributed_topn(cluster):
    uris, sets, _ = cluster
    pairs = q_all_nodes(uris, "TopN(f, n=2)")
    brute = sorted(((len(cs), -r) for (fn, r), cs in sets.items()
                    if fn == "f"), reverse=True)
    assert [(p["id"], p["count"]) for p in pairs] == \
        [(-nr, c) for c, nr in brute[:2]]


def test_distributed_bsi(cluster):
    uris, _, vals = cluster
    out = q_all_nodes(uris, "Sum(Range(v > 100), field=v)")
    keep = [v for v in vals.values() if v > 100]
    assert out == {"value": sum(keep), "count": len(keep)}
    out = q_all_nodes(uris, "Min(field=v)")
    mn = min(vals.values())
    assert out == {"value": mn,
                   "count": sum(1 for v in vals.values() if v == mn)}


def test_distributed_groupby(cluster):
    uris, sets, _ = cluster
    groups = q_all_nodes(uris, "GroupBy(Rows(field=f), Rows(field=g))")
    got = {(d["group"][0]["rowID"], d["group"][1]["rowID"]): d["count"]
           for d in groups}
    for (fn, fr), fcs in sets.items():
        if fn != "f":
            continue
        for (gn, gr), gcs in sets.items():
            if gn != "g":
                continue
            inter = len(fcs & gcs)
            if inter:
                assert got.get((fr, gr)) == inter, (fr, gr)


def test_distributed_writes_visible_everywhere(cluster):
    uris, _, _ = cluster
    col = 2 * SW + 12345
    st, out = jpost(uris[1], "/index/d/query", raw=f"Set({col}, f=0)".encode())
    assert st == 200
    for u in uris:
        st, out = jpost(u, "/index/d/query",
                        raw=f"Count(Intersect(Row(f=0), Row(f=0)))".encode())
        assert st == 200
    got = q_all_nodes(uris, f"Count(Row(f=0))")
    # the new bit is counted exactly once, from every node
    st, out0 = jpost(uris[0], "/index/d/query", raw=b"Row(f=0)")
    assert col in out0["results"][0]["columns"]
