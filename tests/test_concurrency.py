"""Concurrency stress: writes + queries + snapshots racing on shared state.

The reference leans on Go's race detector (SURVEY §5.2); the analog here is
a set of stress tests that hammer the real thread-shared surfaces — the
HTTP server is a ThreadingHTTPServer, so fragments, rank caches, and the
executor's residency/row caches all see concurrent access in production.
Assertions are about invariants surviving the race, not exact interleaving:
no exceptions escape, final state converges, and every read returns an
internally-consistent value (never a torn/corrupt structure).
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import Holder


N_WRITER_OPS = 300
N_READER_OPS = 200


def run_threads(*fns, timeout=120.0):
    """Run fns concurrently; re-raise the first exception from any."""
    errors = []

    def wrap(fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,), daemon=True)
               for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "stress thread wedged"
    if errors:
        raise errors[0]


def test_fragment_writes_vs_snapshot(tmp_path):
    """set_bit racing snapshot(): the WAL-compaction path swaps the backing
    file + mmap under live writers; nothing may be lost or corrupted."""
    from pilosa_tpu.storage.fragment import Fragment

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    lock = threading.Lock()  # storage mutation is lock-protected in prod
    written = []

    def writer(base):
        for k in range(N_WRITER_OPS):
            with lock:
                frag.set_bit(base, k * 7 + base)
            written.append((base, k * 7 + base))

    def snapshotter():
        for _ in range(10):
            with lock:
                frag.snapshot()

    run_threads(lambda: writer(1), lambda: writer(2), snapshotter)
    with lock:
        frag.snapshot()
    for r, c in written:
        assert frag.contains(r, c), (r, c)
    frag.close()
    # reopen: everything durable
    g = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    try:
        for r, c in written:
            assert g.contains(r, c), (r, c)
    finally:
        g.close()


def test_executor_queries_vs_writes(tmp_path):
    """Executor.execute racing Set() writes through the same executor —
    the production server shape (ThreadingHTTPServer worker threads).
    Counts must be internally consistent (monotonic for append-only
    writes) and the row/residency caches must never serve a torn row."""
    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    idx = holder.create_index("i", track_existence=False)
    idx.create_field("f")
    ex.execute("i", "Set(0, f=1) Set(1, f=1)")

    seen = []

    def writer():
        for k in range(N_WRITER_OPS):
            ex.execute("i", f"Set({(k * 13) % SHARD_WIDTH}, f=1)")

    def reader():
        last = 0
        for _ in range(N_READER_OPS):
            (c,) = ex.execute("i", "Count(Row(f=1))")
            # append-only writes: the count can never go backwards
            assert c >= last, (c, last)
            last = c
            seen.append(c)

    def topn_reader():
        for _ in range(N_READER_OPS // 2):
            (pairs,) = ex.execute("i", "TopN(f, n=5)")
            for rid, cnt in pairs:
                assert cnt > 0

    run_threads(writer, reader, topn_reader)
    (final,) = ex.execute("i", "Count(Row(f=1))")
    distinct = len({(k * 13) % SHARD_WIDTH for k in range(N_WRITER_OPS)})
    assert final == len({0, 1} | {(k * 13) % SHARD_WIDTH
                                  for k in range(N_WRITER_OPS)})
    assert seen[-1] <= final
    assert distinct > 0
    holder.close()


def test_rank_cache_reads_vs_writes():
    """top()/top_arrays racing add(): the version-tagged memo must never
    pin a stale snapshot (a read after a completed write sees it) and
    never return torn arrays (ids/counts always same length)."""
    from pilosa_tpu.models.cache import RankCache

    cache = RankCache(cache_size=1000)
    for r in range(500):
        cache.add(r, 500 - r)
    stop = threading.Event()

    def writer():
        for k in range(2000):
            cache.add(k % 1500, (k * 31) % 997 + 1)
        stop.set()

    def reader():
        while not stop.is_set():
            ids, counts = cache.top_arrays()
            assert ids.size == counts.size
            if counts.size > 1:
                assert (np.diff(counts) <= 0).all()  # rank order holds

    run_threads(writer, reader, reader)
    # a read AFTER the last completed write must reflect it (no sticky
    # stale memo — the round-3 regression this guards)
    cache.add(99999, 12345)
    ids, counts = cache.top_arrays()
    assert 99999 in ids
    assert counts[list(ids).index(99999)] == 12345


def test_http_server_concurrent_clients(tmp_path):
    """Real threaded HTTP server: concurrent write + query clients, no
    5xx responses, correct final count."""
    import json
    import urllib.request

    from pilosa_tpu.server import Server

    srv = Server(str(tmp_path / "s"), port=0).open()
    try:
        u = srv.uri

        def post(path, body):
            req = urllib.request.Request(u + path, data=body, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                raise AssertionError(
                    f"{path}: {e.code}: {e.read().decode()[:400]}") from e

        post("/index/i", b"{}")
        post("/index/i/field/f", b"{}")

        def client_writer(base):
            for k in range(60):
                post("/index/i/query",
                     f"Set({base * 1000 + k}, f=1)".encode())

        def client_reader():
            for _ in range(60):
                out = post("/index/i/query", b"Count(Row(f=1))")
                assert isinstance(out["results"][0], int)

        run_threads(lambda: client_writer(1), lambda: client_writer(2),
                    client_reader, client_reader)
        out = post("/index/i/query", b"Count(Row(f=1))")
        assert out["results"] == [120]
    finally:
        srv.close()


def test_executor_sums_vs_value_writes(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_BATCH", "1")  # exercises the batched Sum path
    """Batched BSI Sums racing SetValue writes on fresh columns: sums are
    append-only so both val and count must be monotone, and the plane-slab
    residency cache must never serve a torn slab."""
    from pilosa_tpu.models import FieldOptions, FieldType

    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    idx = holder.create_index("sv", track_existence=False)
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=15))
    ex.execute("sv", "Set(0, v=3)")

    def writer():
        for k in range(N_WRITER_OPS):
            ex.execute("sv", f"Set({k + 1}, v={(k % 15) + 1})")

    def sum_reader():
        last_val = last_n = 0
        for _ in range(N_READER_OPS):
            (vc,) = ex.execute("sv", "Sum(field=v)")
            assert vc.val >= last_val and vc.count >= last_n, \
                (vc, last_val, last_n)
            last_val, last_n = vc.val, vc.count

    run_threads(writer, sum_reader, sum_reader, sum_reader)
    (vc,) = ex.execute("sv", "Sum(field=v)")
    assert vc.count == N_WRITER_OPS + 1
    assert vc.val == 3 + sum((k % 15) + 1 for k in range(N_WRITER_OPS))
    assert ex.sum_batcher.snapshot()["batched_queries"] >= 3
    holder.close()
