"""Frozen (array-backed) container store: the bulk-load path for
BASELINE-scale imports (storage/frozen.py). Behavior parity with the dict
store, COW overlay semantics, and the vectorized fragment/rank-cache
integration."""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.storage.frozen import FrozenContainers
from pilosa_tpu.storage.roaring import Bitmap, Container


def _positions(seed=3, n=5000, span=50):
    rng = np.random.default_rng(seed)
    return np.unique(rng.integers(0, span * (1 << 16), n).astype(np.uint64))


def test_from_positions_matches_dict_store():
    pos = _positions()
    fz = FrozenContainers.from_positions(pos)
    ref = Bitmap(pos)  # dict store
    assert sorted(fz) == sorted(ref.containers)
    for k in ref.containers:
        a, b = fz[k], ref.containers[k]
        assert np.array_equal(a.values(), b.values()), k
    assert fz.total_count() == pos.size
    assert len(fz) == len(ref.containers)
    assert fz.first_key() == min(ref.containers)
    assert fz.last_key() == max(ref.containers)


def test_large_container_materializes_as_bitmap():
    # >4096 members in one keyspace, NOT runny -> bitmap-kind container
    pos = np.arange(0, 10000, 2, dtype=np.uint64)  # every other bit
    fz = FrozenContainers.from_positions(pos)
    assert fz[0].kind == "bitmap" and fz[0].n == 5000


def test_runny_container_becomes_run_overlay():
    """Sequential/fully-set shapes (existence rows, time views) run-encode
    instead of inflating the flat lows (countRuns optimize heuristic,
    roaring/roaring.go:1261,1594)."""
    # one full container + one sequential stretch + sparse tail
    full = np.arange(65536, dtype=np.uint64)                    # key 0
    seq = np.arange(65536, 65536 + 5000, dtype=np.uint64)       # key 1
    sparse = np.uint64(2) << np.uint64(16) | np.arange(
        0, 60000, 13, dtype=np.uint64)                          # key 2
    fz = FrozenContainers.from_positions(
        np.concatenate([full, seq, sparse]))
    assert fz[0].kind == "run" and fz[0].n == 65536
    assert np.array_equal(fz[0].data, np.array([[0, 65535]], np.uint16))
    assert fz[1].kind == "run" and fz[1].n == 5000
    assert fz[2].kind == "bitmap"  # big but not runny: stays in base form
    # the flat lows no longer hold the runny containers' members
    assert fz._lows.size == sparse.size
    assert fz.total_count() == 65536 + 5000 + sparse.size
    # membership + positions round-trip through the run overlay
    probe = np.array([5, 65535, 65536 + 4999, 65536 + 5000,
                      (2 << 16) | 13], dtype=np.uint64)
    assert fz.contains_positions(probe).tolist() == [
        True, True, True, False, True]
    assert fz.all_positions().size == fz.total_count()
    # fully-runny store (EMPTY base): probing an absent key must return
    # False, not crash on the empty key array
    fz2 = FrozenContainers.from_positions(np.arange(65536, dtype=np.uint64))
    assert fz2._keys.size == 0
    assert fz2.contains_positions(
        np.array([70000, 5], dtype=np.uint64)).tolist() == [False, True]


def test_runny_snapshot_keeps_run_encoding(tmp_path):
    """write_pilosa serializes overlay runs as TYPE_RUN and the frozen
    parser restores them as run containers — the existence-shaped corpus
    stays KBs on disk and in RAM across the round trip."""
    import io as _io

    from pilosa_tpu.storage.roaring import Bitmap

    full = np.arange(4 * 65536, dtype=np.uint64)  # 4 fully-set containers
    sparse = (np.uint64(9) << np.uint64(16)) | np.arange(
        0, 60000, 17, dtype=np.uint64)
    pos = np.concatenate([full, sparse])
    b = Bitmap.frozen(pos)
    buf = _io.BytesIO()
    b.containers.write_pilosa(buf)
    data = buf.getvalue()
    # 4 run containers a 4+2 bytes each, not 4 x 8 KiB of bitmaps
    assert len(data) < 2 * sparse.size + 1024
    b2 = Bitmap.from_bytes(data)
    assert b2.count() == pos.size
    store = b2.containers
    if isinstance(store, FrozenContainers):
        assert store[0].kind == "run"
    assert np.array_equal(b2.positions(), pos)


def test_overlay_cow_and_delete():
    pos = _positions(n=2000, span=10)
    fz = FrozenContainers.from_positions(pos)
    base_total = fz.total_count()
    k0 = int(next(iter(fz)))
    # replace one container via the overlay
    fz[k0] = Container.from_values(np.array([1, 2, 3], dtype=np.uint16))
    assert fz[k0].n == 3
    # brand-new key beyond the base
    fz[10_000] = Container.from_values(np.array([7], dtype=np.uint16))
    assert 10_000 in fz and fz.last_key() == 10_000
    # delete a base key
    keys = list(fz)
    kdel = keys[1]
    del fz[kdel]
    assert kdel not in fz
    with pytest.raises(KeyError):
        _ = fz[kdel]
    # iteration stays sorted and consistent
    ks = list(fz)
    assert ks == sorted(ks) and 10_000 in ks and kdel not in ks
    # vectorized arrays reflect the overlay
    ka, na = fz.key_and_count_arrays()
    assert ka.tolist() == ks
    total = fz.total_count()
    assert total == int(na.sum()) != base_total
    # irange with overlay-only and deleted keys
    got = list(fz.irange(k0, 10_000))
    assert got[0] == k0 and got[-1] == 10_000 and kdel not in got


def test_pop_and_bool_and_len_empty():
    fz = FrozenContainers.empty()
    assert not fz and len(fz) == 0
    assert fz.pop(5) is None
    with pytest.raises(KeyError):
        fz.first_key()
    fz[1] = Container.from_values(np.array([4], dtype=np.uint16))
    assert fz and len(fz) == 1
    c = fz.pop(1)
    assert c.n == 1 and not fz


def test_bitmap_frozen_read_paths():
    pos = _positions(seed=9, n=8000, span=64)
    b = Bitmap.frozen(pos)
    ref = Bitmap(pos)
    assert b.count() == ref.count() == pos.size
    lo, hi = 3 << 16, 40 << 16
    assert b.count_range(lo, hi) == ref.count_range(lo, hi)
    assert np.array_equal(b.slice(lo, hi), ref.slice(lo, hi))
    assert np.array_equal(b.to_dense_words(0, 1 << 20),
                          ref.to_dense_words(0, 1 << 20))
    assert b.min() == ref.min() and b.max() == ref.max()
    # mutation after freeze: COW overlay keeps reads exact
    b.add(int(pos[0]) + 1) if int(pos[0]) + 1 not in pos else None
    b.remove_many(pos[:10])
    ref.remove_many(pos[:10])
    got = set(b.slice(0, int(pos[20]) + 1).tolist())
    assert int(pos[5]) not in got


def test_fragment_import_frozen_and_queries(tmp_path):
    from pilosa_tpu.storage.fragment import Fragment

    rng = np.random.default_rng(11)
    n_rows = 500
    rows = rng.integers(0, n_rows, 20_000).astype(np.uint64)
    cols = rng.integers(0, SHARD_WIDTH, 20_000).astype(np.uint64)
    positions = np.unique(rows * np.uint64(SHARD_WIDTH) + cols)
    frag = Fragment(str(tmp_path / "f0"), "i", "f", "standard", 0).open()
    try:
        frag.import_frozen(np.sort(positions))
        model_rows = positions // np.uint64(SHARD_WIDTH)
        uids, counts = np.unique(model_rows, return_counts=True)
        assert frag.bit_count() == positions.size
        # vectorized row_counts against the model
        some = uids[::7]
        got = frag.row_counts(some.tolist())
        assert np.array_equal(got, counts[::7])
        assert frag.row_ids()[:10] == uids[:10].tolist()
        assert frag.row_ids(start=int(uids[13]), limit=3) == \
            uids[13:16].tolist()
        # dense row parity
        r = int(uids[3])
        dense = frag.row_dense(r)
        expect_cols = positions[model_rows == r] % np.uint64(SHARD_WIDTH)
        got_cols = np.flatnonzero(
            np.unpackbits(dense.view(np.uint8), bitorder="little"))
        assert np.array_equal(got_cols, expect_cols.astype(np.int64))
        # post-freeze single-bit writes still work (COW overlay)
        newcol = int(expect_cols[0]) + 1
        changed = frag.set_bit(r, newcol)
        assert frag.row_count(r) == int(counts[3]) + int(changed)
        # double-freeze refused
        with pytest.raises(ValueError):
            frag.import_frozen(positions)
    finally:
        frag.close()


def test_field_import_rows_frozen_topn_parity(tmp_path):
    """End to end: frozen bulk load -> rank cache -> executor TopN matches
    the mutating import path's answer."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder

    rng = np.random.default_rng(23)
    n_rows, n_bits = 2000, 60_000
    rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
    cols = rng.integers(0, 3 * SHARD_WIDTH, n_bits).astype(np.uint64)
    # heavy head so TopN is decisive
    rows[: n_bits // 4] = rng.integers(0, 20, n_bits // 4)

    h = Holder(str(tmp_path / "d")).open()
    try:
        idx = h.create_index("fz", track_existence=False)
        f1 = idx.create_field("mut")
        f1.import_bits(rows.tolist(), cols.tolist())
        f2 = idx.create_field("frz")
        f2.import_rows_frozen(rows, cols)
        ex = Executor(h)
        (a,) = ex.execute("fz", "TopN(mut, n=50)")
        (b,) = ex.execute("fz", "TopN(frz, n=50)")
        assert [tuple(p) for p in a] == [tuple(p) for p in b]
        (ra,) = ex.execute("fz", "Row(mut=7)")
        (rb,) = ex.execute("fz", "Row(frz=7)")
        assert ra.columns().tolist() == rb.columns().tolist()
        (ca,) = ex.execute("fz", "Count(Intersect(Row(frz=3), Row(frz=5)))")
        (cb,) = ex.execute("fz", "Count(Intersect(Row(mut=3), Row(mut=5)))")
        assert ca == cb
    finally:
        h.close()


def test_import_values_vectorized_parity(tmp_path):
    """The numpy-array fast path of import_values matches the list path."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    rng = np.random.default_rng(29)
    n = 30_000
    cols = rng.choice(2 * SHARD_WIDTH, n, replace=False).astype(np.uint64)
    vals = rng.integers(0, 512, n).astype(np.int64)
    h = Holder(str(tmp_path / "d")).open()
    try:
        idx = h.create_index("bv", track_existence=False)
        va = idx.create_field("a", FieldOptions(type=FieldType.INT,
                                                min=0, max=511))
        vb = idx.create_field("b", FieldOptions(type=FieldType.INT,
                                                min=0, max=511))
        va.import_values(cols, vals)  # numpy arrays
        vb.import_values(cols.tolist(), vals.tolist())  # lists
        ex = Executor(h)
        (x,) = ex.execute("bv", "Sum(Range(a > 100), field=a)")
        (y,) = ex.execute("bv", "Sum(Range(b > 100), field=b)")
        assert (x.val, x.count) == (y.val, y.count)
        mask = vals > 100
        assert x.val == int(vals[mask].sum()) and x.count == int(mask.sum())
    finally:
        h.close()


def test_frozen_volatility_contract(tmp_path):
    """A frozen fragment is volatile until snapshot(): post-freeze writes
    are NOT op-logged (a WAL op against the un-persisted base would replay
    into an empty fragment after restart and silently serve one op's worth
    of a billion-row corpus), and reopening yields an EMPTY fragment that
    accepts a fresh import_frozen. snapshot() makes it durable."""
    from pilosa_tpu.storage.fragment import Fragment

    path = str(tmp_path / "vf")
    pos = np.arange(0, 3000, 3, dtype=np.uint64)
    frag = Fragment(path, "i", "f", "standard", 0).open()
    frag.import_frozen(pos)
    frag.set_bit(0, 1)  # volatile too — must not op-log
    assert frag.bit_count() == pos.size + 1
    frag.close()
    # restart: clean empty state, not a one-op corpse
    frag2 = Fragment(path, "i", "f", "standard", 0).open()
    assert frag2.bit_count() == 0
    frag2.import_frozen(pos)  # re-import allowed
    frag2.snapshot()  # opt-in durability
    frag2.set_bit(0, 1)  # WAL re-attached by snapshot: this op persists
    frag2.close()
    frag3 = Fragment(path, "i", "f", "standard", 0).open()
    assert frag3.bit_count() == pos.size + 1
    frag3.close()


def test_frozen_clear_roaring_in_place(tmp_path):
    """clear=True roaring import against frozen storage removes bits
    through the COW overlay (touching only incoming containers) instead of
    materializing the whole corpus via difference()."""
    from pilosa_tpu.storage.fragment import Fragment

    pos = np.arange(0, 200_000, 2, dtype=np.uint64)
    frag = Fragment(str(tmp_path / "cf"), "i", "f", "standard", 0).open()
    try:
        frag.import_frozen(pos)
        store = frag.storage.containers
        clear = Bitmap(np.arange(0, 1000, 2, dtype=np.uint64))
        frag.import_roaring(clear.to_bytes(), clear=True)
        assert frag.storage.containers is store  # same store object (COW)
        assert frag.bit_count() == pos.size - 500
        assert not frag.storage.contains(0) and frag.storage.contains(1000)
    finally:
        frag.close()


def test_import_values_last_write_wins(tmp_path):
    """Duplicate columns in one import_values call: the LAST value wins
    (importValue semantics, fragment.go:1624) — not the bitwise OR."""
    from pilosa_tpu.executor import Executor, ValCount
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    h = Holder(str(tmp_path / "d")).open()
    try:
        idx = h.create_index("lw", track_existence=False)
        v = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                               min=0, max=100))
        v.import_values([5, 5, 9], [2, 1, 7])  # col 5: 2 then 1
        ex = Executor(h)
        (vc,) = ex.execute("lw", "Sum(field=v)")
        assert vc == ValCount(8, 2)  # 1 + 7, NOT 3 + 7
        (r,) = ex.execute("lw", "Range(v == 1)")
        assert r.columns().tolist() == [5]
    finally:
        h.close()


def test_frozen_mutation_fuzz():
    """Randomized mutation/read fuzz vs the dict-store model: set/delete/
    get/irange interleave across base and overlay keys."""
    rng = np.random.default_rng(7)
    pos = np.unique(rng.integers(0, 40 << 16, 10_000).astype(np.uint64))
    fz = FrozenContainers.from_positions(pos)
    ref = Bitmap(pos)
    for i in range(600):
        op = int(rng.integers(0, 4))
        key = int(rng.integers(0, 42))
        if op == 0:
            vals = np.unique(rng.integers(0, 65536, 20)).astype(np.uint16)
            c = Container.from_values(vals)
            fz[key] = c
            ref.containers[key] = c
        elif op == 1:
            a, b = fz.get(key), ref.containers.get(key)
            assert (a is None) == (b is None), (i, key)
            if a is not None:
                assert np.array_equal(a.values(), b.values()), (i, key)
        elif op == 2 and key in fz:
            del fz[key]
            ref.containers.pop(key, None)
        else:
            assert list(fz.irange(key, key + 5)) == sorted(
                k for k in ref.containers if key <= k <= key + 5), (i, key)
    assert list(fz) == sorted(ref.containers)
    assert fz.total_count() == ref.count()


# -- serialization round trip (the 1B-scale snapshot/reopen path) ----------


def test_frozen_write_pilosa_matches_dict_store():
    """Frozen vectorized serialization produces a file the standard reader
    parses to identical contents (incl. dense bitmap-encoded containers),
    and the dict-store writer's output parses identically too."""
    import io

    rng = np.random.default_rng(51)
    sparse = rng.integers(0, 30 << 16, 20_000).astype(np.uint64)
    dense = (np.uint64(31 << 16) + rng.integers(0, 30_000, 20_000)
             .astype(np.uint64))  # >4096 in one keyspace -> bitmap kind
    pos = np.unique(np.concatenate([sparse, dense]))
    fz = Bitmap.frozen(pos)
    ref = Bitmap(pos)
    buf = io.BytesIO()
    n = fz.write_to(buf)
    assert n == len(buf.getvalue())
    back = Bitmap.from_bytes(buf.getvalue())
    assert back.count() == ref.count() == pos.size
    assert np.array_equal(back.slice(), ref.slice())


def test_frozen_write_with_overlay_and_deletes():
    import io

    pos = np.arange(0, 100_000, 3, dtype=np.uint64)
    fz = Bitmap.frozen(pos)
    fz.add_many(np.array([7, 9, (50 << 16) + 5], dtype=np.uint64))
    fz.remove_many(pos[:100])  # note: removes the just-added 9 (9 in pos)
    model = (set(pos.tolist()) | {7, 9, (50 << 16) + 5}) \
        - set(pos[:100].tolist())
    buf = io.BytesIO()
    fz.write_to(buf)
    back = Bitmap.from_bytes(buf.getvalue())
    assert set(back.slice().tolist()) == model


def test_frozen_parse_roundtrip(monkeypatch):
    """from_bytes(lazy=True) above the threshold parses into a frozen
    store (zero-copy views) with identical read behavior, op-log replay
    landing in the COW overlay."""
    import io

    import pilosa_tpu.storage.frozen as fzmod
    import pilosa_tpu.storage.roaring as rmod

    monkeypatch.setattr(fzmod, "FROZEN_PARSE_MIN", 4)
    rng = np.random.default_rng(53)
    pos = np.unique(rng.integers(0, 20 << 16, 30_000).astype(np.uint64))
    src = Bitmap(pos)
    data = src.to_bytes()
    b = Bitmap.from_bytes(data, lazy=True)
    assert isinstance(b.containers, fzmod.FrozenContainers)
    assert b.count() == pos.size
    assert np.array_equal(b.slice(3 << 16, 9 << 16),
                          src.slice(3 << 16, 9 << 16))
    # mutation goes to the overlay; serialize again and re-read
    b.add(int(pos[0]) + 1 if int(pos[0]) + 1 not in set(pos[:3].tolist())
          else 999_999)
    out = io.BytesIO()
    b.write_to(out)
    again = Bitmap.from_bytes(out.getvalue())
    assert again.count() == b.count()


def test_fragment_frozen_snapshot_reopen(tmp_path, monkeypatch):
    """import_frozen -> snapshot() -> close -> reopen: durable round trip
    through the vectorized writer and (above threshold) frozen parser;
    WAL re-attached ops survive too."""
    import pilosa_tpu.storage.frozen as fzmod
    from pilosa_tpu.storage.fragment import Fragment

    monkeypatch.setattr(fzmod, "FROZEN_PARSE_MIN", 4)
    rng = np.random.default_rng(59)
    rows = rng.integers(0, 3000, 50_000).astype(np.uint64)
    cols = rng.integers(0, SHARD_WIDTH, 50_000).astype(np.uint64)
    pos = np.unique(rows * np.uint64(SHARD_WIDTH) + cols)
    path = str(tmp_path / "fs")
    frag = Fragment(path, "i", "f", "standard", 0).open()
    frag.import_frozen(pos)
    frag.snapshot()  # durable now; WAL re-attached
    frag.set_bit(1, 77)  # op-logged post-snapshot
    n = frag.bit_count()
    frag.close()
    frag2 = Fragment(path, "i", "f", "standard", 0).open()
    try:
        assert isinstance(frag2.storage.containers, fzmod.FrozenContainers)
        assert frag2.bit_count() == n
        r = int(rows[0])
        expect = np.unique(cols[rows == r])
        got = frag2.row_columns(r)
        assert np.array_equal(np.sort(got), np.sort(expect.astype(np.int64)))
        # re-snapshot of a FILE-PARSED frozen store (the gather path)
        frag2.set_bit(2, 99)
        frag2.snapshot()
        assert frag2.bit_count() == n + 1
    finally:
        frag2.close()


def test_mutex_write_scale_against_frozen(tmp_path):
    """VERDICT r4 weak #2: mutex probes and bulk mutex imports must cost
    candidate-container work, not full key-space walks. A frozen mutex-
    shaped fragment with ~1M bits across 100k distinct rows (the shape of
    a 100M-row corpus shard) must serve a single rows_for_column probe and
    a large mutex batch in interactive time."""
    import time

    from pilosa_tpu.storage.fragment import Fragment

    rng = np.random.default_rng(5)
    n_bits = 1_000_000
    cols = np.arange(n_bits, dtype=np.uint64)  # mutex: one bit per column
    rows = rng.integers(0, 100_000, n_bits).astype(np.uint64)
    pos = np.sort(rows * np.uint64(SHARD_WIDTH) + cols)
    frag = Fragment(str(tmp_path / "m0"), "i", "m", "standard", 0).open()
    try:
        frag.import_frozen(pos)
        # single probe: vectorized candidate mask, no per-key Python walk
        t0 = time.monotonic()
        got = frag.rows_for_column(12345)
        probe_s = time.monotonic() - t0
        assert got == [int(rows[12345])]
        assert probe_s < 0.5, f"probe took {probe_s:.3f}s"
        # bulk mutex rewrite of 100k columns: set algebra over all bits
        bcols = np.arange(0, 200_000, 2, dtype=np.uint64)
        brows = rng.integers(100_000, 100_010, bcols.size).astype(np.uint64)
        t0 = time.monotonic()
        frag.bulk_import_mutex(brows.tolist(), bcols.tolist())
        bulk_s = time.monotonic() - t0
        assert bulk_s < 5.0, f"bulk mutex took {bulk_s:.3f}s"
        # invariant: every written column holds exactly its new row
        probe = frag.rows_for_column(int(bcols[7]))
        assert probe == [int(brows[7])]
        # untouched columns keep their original row
        assert frag.rows_for_column(12345) == [int(rows[12345])]
        # total bits unchanged: one bit per column, still
        assert frag.bit_count() == n_bits
    finally:
        frag.close()


def test_bulk_import_mutex_last_write_wins_parity(tmp_path):
    """Duplicate columns in one batch: the LAST (row, col) pair wins,
    matching the reference's per-bit processing order
    (bulkImportMutex, fragment.go:1553-1588)."""
    from pilosa_tpu.storage.fragment import Fragment

    frag = Fragment(str(tmp_path / "m1"), "i", "m", "standard", 0).open()
    try:
        frag.bulk_import_mutex([1, 2, 3], [10, 10, 10])
        assert frag.rows_for_column(10) == [3]
        assert frag.bit_count() == 1
        # rewrite across rows, mixed with fresh columns
        frag.bulk_import_mutex([7, 8], [10, 11])
        assert frag.rows_for_column(10) == [7]
        assert frag.rows_for_column(11) == [8]
        assert frag.bit_count() == 2
    finally:
        frag.close()


def test_import_values_frozen_parity(tmp_path):
    """import_values_frozen (deferred-durability BSI bulk load) produces
    bit-identical planes to the mutating import path, and executor
    Sum/Range answers match host arithmetic (importValue,
    fragment.go:1624-1658)."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    rng = np.random.default_rng(41)
    n = 2 * SHARD_WIDTH + 999  # 3 shards, ragged tail
    cols = np.sort(rng.choice(3 * SHARD_WIDTH, n, replace=False)
                   ).astype(np.uint64)
    vals = rng.integers(-50, 200, n).astype(np.int64)

    h1 = Holder(str(tmp_path / "mut")).open()
    f1 = h1.create_index("a", track_existence=False).create_field(
        "v", FieldOptions(type=FieldType.INT, min=-50, max=199))
    f1.import_values(cols, vals)
    h2 = Holder(str(tmp_path / "fz")).open()
    f2 = h2.create_index("a", track_existence=False).create_field(
        "v", FieldOptions(type=FieldType.INT, min=-50, max=199))
    f2.import_values_frozen(cols, vals)
    v1, v2 = f1.views[f1.bsi_view_name], f2.views[f2.bsi_view_name]
    assert v1.shards() == v2.shards()
    for shard in v1.shards():
        assert np.array_equal(v1.fragment(shard).storage.positions(),
                              v2.fragment(shard).storage.positions()), shard
    # executor agreement with host math
    thr = 100
    m = vals > thr
    ex = Executor(h2)
    (res,) = ex.execute("a", f"Sum(Range(v > {thr}), field=v)")
    assert res.val == int(vals[m].sum()) and res.count == int(m.sum())
    # non-int fields refuse the frozen value path
    f3 = h2.index("a").create_field("s")
    with pytest.raises(ValueError):
        f3.import_values_frozen([1], [2])
    h1.close()
    h2.close()


def test_bulk_import_values_empty_fast_path_parity(tmp_path):
    """Fresh-fragment BSI import skips the zero-plane clears; a second
    import over the same columns still clears stale plane bits."""
    from pilosa_tpu.storage.fragment import Fragment

    frag = Fragment(str(tmp_path / "b0"), "i", "v", "bsig_v", 0).open()
    try:
        frag.bulk_import_values(np.array([5, 9], np.uint64),
                                np.array([3, 7], np.int64), 4)
        assert frag.contains(0, 5) and frag.contains(1, 5)
        assert not frag.contains(2, 5)
        # overwrite col 5: 3 (0b011) -> 4 (0b100): bits 0,1 must CLEAR
        frag.bulk_import_values(np.array([5], np.uint64),
                                np.array([4], np.int64), 4)
        assert not frag.contains(0, 5) and not frag.contains(1, 5)
        assert frag.contains(2, 5)
        assert frag.contains(0, 9) and frag.contains(1, 9)  # untouched
    finally:
        frag.close()
