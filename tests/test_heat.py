"""Fragment heat maps + placement advisor (utils/heat.py,
analysis/advisor.py) and the surfaces that ride them: EWMA decay math,
bounded-table spill with exact totals, every charge site (row reads,
writes, plan-cache hits, residency transitions, remote attribution in a
live 3-node cluster), the /debug/heat and /cluster/heat endpoints
(legacy-peer degradation), advisor determinism on a replayed trace, the
kill switch + runtime toggle, heat-steered eviction parity with the
residency invariants, and the query-history shed-entry satellite."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import FieldOptions, Holder
from pilosa_tpu.utils import heat as heat_mod
from pilosa_tpu.utils.heat import (
    HALF_LIVES,
    HOT_SCORE,
    HeatTracker,
    leaf_frag_keys,
    merge_heat_docs,
)

# ----------------------------------------------------------------- tracker


def test_ewma_decay_math():
    """The documented decay contract: after exactly one half-life with no
    touches, each decayed access count halves (so the score derived from
    it halves too), and the per-window rates derive as count/half-life."""
    t = HeatTracker()
    t0 = 1000.0
    t.touch("i", "f", "standard", 0, reads=4, now=t0)
    key = [("i", "f", "standard", 0)]
    # snapshot rates first (decay is applied in place at each read's
    # `now`, so probes must move forward in time like a real clock):
    # short-window decayed count / short half-life
    snap = t.snapshot(top=1, now=t0)
    assert snap["hot"][0]["readsPerS"] == pytest.approx(
        4.0 / HALF_LIVES[0], abs=1e-6)
    s0 = t.scores_for(key, now=t0)[0]
    assert s0 == pytest.approx(sum(4.0 / hl for hl in HALF_LIVES))
    # one short half-life later: the 1m window halved, the long windows
    # barely moved — the score sits between half and full
    s1 = t.scores_for(key, now=t0 + HALF_LIVES[0])[0]
    expected = sum(4.0 * 0.5 ** (HALF_LIVES[0] / hl) / hl
                   for hl in HALF_LIVES)
    assert s1 == pytest.approx(expected)
    # after one LONG half-life every window halved at least once
    s2 = t.scores_for(key, now=t0 + HALF_LIVES[-1])[0]
    assert s2 < s0 / 2 + 1e-12
    # touching again re-heats monotonically
    t.touch("i", "f", "standard", 0, reads=1, now=t0 + HALF_LIVES[-1])
    assert t.scores_for(key, now=t0 + HALF_LIVES[-1])[0] > s2


def test_bounded_spill_exact_totals():
    """At capacity the coldest entry merges into the ~other aggregate:
    per-fragment resolution of the tail is lost, totals never are."""
    t = HeatTracker(max_fragments=4)
    t0 = 50.0
    # one clearly-hot fragment, then a parade of cold strangers
    t.touch("i", "hot", "standard", 0, reads=100, device_ms=7.5, now=t0)
    for s in range(10):
        t.touch("i", "cold", "standard", s, reads=1, h2d_bytes=10,
                now=t0 + 1 + s * 0.001)
    snap = t.snapshot(top=0, now=t0 + 2)
    assert snap["trackedFragments"] == 4
    assert snap["spilledFragments"] == 7
    # exact totals survive the spill
    assert snap["totals"]["reads"] == 110.0
    assert snap["totals"]["deviceMs"] == 7.5
    assert snap["totals"]["h2dBytes"] == 100.0
    # the hot fragment was never the victim
    assert snap["hot"][0]["field"] == "hot"
    # runtime toggle: a disabled tracker charges nothing
    t.enabled = False
    t.touch("i", "hot", "standard", 0, reads=50, now=t0 + 3)
    t.enabled = True
    assert t.totals()["reads"] == 110.0


def test_leaf_frag_keys_shapes():
    """The residency-key -> fragment-coordinate bridge parses every leaf
    kind the executor mints and ignores synthetic/unknown keys."""
    assert leaf_frag_keys(
        ("row", "i", "f", "standard", 7, (0, 2), (1, 1))) == \
        [("i", "f", "standard", 0), ("i", "f", "standard", 2)]
    assert leaf_frag_keys(
        ("timerange", "i", "f", 7, ("std_2020", "std_2021"), (1,),
         ((0,), (0,)))) == \
        [("i", "f", "std_2020", 1), ("i", "f", "std_2021", 1)]
    assert leaf_frag_keys(
        ("bsicmp", "i", "v", "==", 3, 4, (0,), ())) == \
        [("i", "v", "bsig_v", 0)]
    assert leaf_frag_keys(
        ("bsiplanes", "i", "v", 4, (0, 1), ())) == \
        [("i", "v", "bsig_v", 0), ("i", "v", "bsig_v", 1)]
    assert leaf_frag_keys(
        ("rows_slab", "i", "f", "standard", (3,), (1, 2), ())) == \
        [("i", "f", "standard", 3)]
    assert leaf_frag_keys(("zeros", 4)) == []
    assert leaf_frag_keys(("mystery", 1, 2)) == []
    assert leaf_frag_keys(None) == []


# ----------------------------------------------------------- charge sites


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    e = Executor(h)
    yield e
    h.close()


def _heat_keys(tracker):
    return set((e["index"], e["field"], e["view"], e["shard"])
               for e in tracker.snapshot(top=0)["hot"])


def test_executor_read_write_charge_sites(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([0] * 3, [1, 2, SHARD_WIDTH + 1])
    assert ex.heat is not None  # default-on
    ex.execute("i", "Count(Row(f=0))")
    keys = _heat_keys(ex.heat)
    assert ("i", "f", "standard", 0) in keys
    assert ("i", "f", "standard", 1) in keys
    reads0 = ex.heat.totals()["reads"]
    assert reads0 > 0
    # write heat lands at the written column's shard
    ex.execute("i", f"Set({SHARD_WIDTH + 5}, f=9)")
    snap = ex.heat.snapshot(top=0)
    by_key = {(e["index"], e["field"], e["view"], e["shard"]): e
              for e in snap["hot"]}
    assert by_key[("i", "f", "standard", 1)]["writes"] == 1.0
    assert by_key[("i", "f", "standard", 0)]["writes"] == 0.0
    # BSI reads charge at the bsig_ view coordinate
    v = idx.create_field("v", FieldOptions(type="int", min=0, max=100))
    v.set_value(3, 42)
    ex.execute("i", "Sum(field=v)")
    assert any(k[2] == "bsig_v" for k in _heat_keys(ex.heat))
    # device-ms attribution accumulated somewhere along the way
    assert ex.heat.totals()["deviceMs"] >= 0.0
    # residency transitions: uploads were charged by the leaf misses
    assert ex.heat.totals()["uploads"] > 0


def test_plan_cache_hit_still_heats(ex):
    """A cached read never reaches _row_leaf_dev, but its operands must
    still heat — reuse is the strongest pin signal the advisor has."""
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([0] * 2 + [1] * 2, [1, 2, 2, 3])
    assert ex.plan_cache is not None
    ex.execute("i", "Intersect(Row(f=0), Row(f=1))")
    reads1 = ex.heat.totals()["reads"]
    hits1 = ex.plan_cache.hits
    ex.execute("i", "Intersect(Row(f=0), Row(f=1))")
    assert ex.plan_cache.hits > hits1  # really a cache hit...
    assert ex.heat.totals()["reads"] > reads1  # ...that still heated
    # the cached-Count path heats too
    ex.execute("i", "Count(Intersect(Row(f=0), Row(f=1)))")
    reads2 = ex.heat.totals()["reads"]
    ex.execute("i", "Count(Intersect(Row(f=0), Row(f=1)))")
    assert ex.heat.totals()["reads"] > reads2


def test_kill_switch_and_runtime_toggle(tmp_path, monkeypatch):
    """PILOSA_TPU_HEAT=0 builds no tracker and forces lru eviction
    regardless of the [storage] eviction knob; the runtime toggle stops
    charging without tearing the tracker down (the bench A/B path)."""
    monkeypatch.setenv("PILOSA_TPU_HEAT", "0")
    h = Holder(str(tmp_path / "killed")).open()
    try:
        e = Executor(h)
        assert e.heat is None
        assert e.residency.heat is None
        idx = h.create_index("i")
        idx.create_field("f").import_bits([0], [1])
        e.execute("i", "Count(Row(f=0))")  # charge sites are nops
        # eviction=heat cannot engage without a tracker: victims are LRU
        e.residency.eviction = "heat"
        e.residency.budget = 1  # force eviction on every insert
        e.execute("i", "Row(f=0)")
        assert e.residency.heat_evictions == 0
    finally:
        h.close()
    monkeypatch.delenv("PILOSA_TPU_HEAT")
    h2 = Holder(str(tmp_path / "alive")).open()
    try:
        e2 = Executor(h2)
        assert e2.heat is not None
        idx = h2.create_index("i")
        idx.create_field("f").import_bits([0], [1])
        e2.heat.enabled = False  # runtime toggle
        e2.execute("i", "Count(Row(f=0))")
        assert e2.heat.totals()["reads"] == 0.0
        e2.heat.enabled = True
        e2.execute("i", "Count(Row(f=0))")
        assert e2.heat.totals()["reads"] > 0.0
    finally:
        h2.close()


# ----------------------------------------------- heat-steered eviction


class _FakeRunner:
    """Minimal runner: leaves are numpy arrays (nbytes-bearing), no
    device round trips — eviction mechanics only."""

    def put_leaf(self, host):
        return host


def test_heat_eviction_prefers_cold_and_keeps_invariants():
    from pilosa_tpu.parallel.residency import DeviceResidency

    tracker = HeatTracker()
    nbytes = 1024
    res = DeviceResidency(_FakeRunner(), budget_bytes=3 * nbytes)
    res.heat = tracker
    res.eviction = "heat"
    now = 10.0

    def make(i):
        return lambda: np.zeros(nbytes // 4, dtype=np.uint32)

    def key(i):
        return ("row", "i", "f", "standard", i, (i,), (0,))

    # heat fragments 0 and 1; fragment 2 stays stone cold
    tracker.touch_many([("i", "f", "standard", 0)], reads=50, now=now)
    tracker.touch_many([("i", "f", "standard", 1)], reads=30, now=now)
    for i in range(3):
        res.leaf(key(i), make(i))
    assert res.bytes == 3 * nbytes
    # inserting a 4th (warm) leaf must evict the COLD entry (2), not the
    # LRU-oldest (0 — which is the hottest)
    tracker.touch_many([("i", "f", "standard", 3)], reads=10, now=now)
    res.leaf(key(3), make(3))
    assert key(2) not in res._lru
    assert key(0) in res._lru and key(1) in res._lru
    assert res.heat_evictions == 1 and res.evictions == 1
    # parity with the residency invariants: bytes exact, hits still hit
    assert res.bytes == sum(a.nbytes for a in res._lru.values())
    res.leaf(key(0), lambda: (_ for _ in ()).throw(AssertionError))
    assert res.hits == 1
    # eviction transitions were charged back into the tracker
    snap = tracker.snapshot(top=0)
    ev = {(e["index"], e["field"], e["view"], e["shard"]): e["evictions"]
          for e in snap["hot"]}
    assert ev[("i", "f", "standard", 2)] == 1.0
    # epoch fence: a clear() mid-make still serves without caching
    res.clear()
    assert res.bytes == 0 and len(res._lru) == 0

    # lru mode on the same struct: the oldest goes, heat ignored
    res2 = DeviceResidency(_FakeRunner(), budget_bytes=2 * nbytes)
    res2.heat = tracker
    res2.eviction = "lru"
    for i in range(3):
        res2.leaf(key(i), make(i))
    assert key(0) not in res2._lru  # LRU victim despite being hottest
    assert res2.heat_evictions == 0


# -------------------------------------------------------- live cluster


def _get(uri, path, timeout=15):
    with urllib.request.urlopen(uri + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post(uri, path, payload=None, raw=None, headers=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def trio(tmp_path_factory):
    """3-node cluster (replica 1 — ownership is unambiguous), one peer
    speaking the legacy protocol for /debug/heat."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("heat")
    servers = [Server(str(tmp / f"n{i}"), port=0,
                      node_id=chr(ord("a") + i),
                      telemetry_interval=0.05).open() for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    def _legacy_404(params, query, body):
        return 404, "application/json", b'{"error": "not found"}'

    servers[2].handler.get_debug_heat = _legacy_404

    _post(uris[0], "/index/h", {})
    _post(uris[0], "/index/h/field/f", {})
    cols = list(range(0, 3 * SHARD_WIDTH, 4099))
    _post(uris[0], "/index/h/field/f/import",
          {"rowIDs": [0] * len(cols), "columnIDs": cols})
    for _ in range(2):
        _post(uris[0], "/index/h/query", raw=b"Count(Row(f=0))")
    yield servers, uris
    for s in servers:
        s.close()


def test_remote_attribution_charges_owner_not_coordinator(trio):
    """A distributed query heats each OWNING node's tracker for the
    shards it served; the coordinator never absorbs remote heat."""
    servers, uris = trio
    tracked = {}
    for s in servers:
        snap = s.executor.heat.snapshot(top=0)
        tracked[s.node_id] = {e["shard"] for e in snap["hot"]
                              if e["field"] == "f"}
    # every shard of the query is heated SOMEWHERE...
    assert set().union(*tracked.values()) == {0, 1, 2}
    # ...and each node's heated shards are exactly the ones it owns
    for s in servers:
        owns = {shard for shard in (0, 1, 2)
                if any(n.id == s.node_id
                       for n in s.cluster.shard_nodes("h", shard))}
        assert tracked[s.node_id] == owns, s.node_id
    # distributed write: heat lands on the written shard's owner
    col = 2 * SHARD_WIDTH + 123
    _post(uris[0], "/index/h/query", raw=f"Set({col}, f=7)".encode())
    for s in servers:
        owns = any(n.id == s.node_id
                   for n in s.cluster.shard_nodes("h", 2))
        snap = s.executor.heat.snapshot(top=0)
        wrote = any(e["shard"] == 2 and e["writes"] > 0
                    for e in snap["hot"] if e["field"] == "f")
        assert wrote == owns, s.node_id


def test_debug_heat_endpoint_and_cursor(trio):
    servers, uris = trio
    # with replica 1 the coordinator may own no shard of the index at
    # all — probe a node whose tracker actually holds fragments
    i = next(i for i, s in enumerate(servers[:2])
             if s.executor.heat.snapshot(top=1)["trackedFragments"])
    st, doc = _get(uris[i], "/debug/heat")
    assert st == 200
    assert doc["enabled"] and doc["trackedFragments"] >= 1
    assert doc["hot"] and doc["hot"][0]["score"] > 0
    assert doc["cold"]  # top-K form carries both ends
    assert "distribution" in doc and "+Inf" in doc["distribution"]
    # the since-cursor summary ring (driven by the telemetry sampler)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        _, doc = _get(uris[i], "/debug/heat")
        if doc["samples"]:
            break
        time.sleep(0.05)
    assert doc["samples"] and "skew" in doc["samples"][-1]["gauges"]
    cur = doc["seq"]
    _, nxt = _get(uris[i], f"/debug/heat?since={cur}")
    assert all(s["seq"] > cur for s in nxt["samples"])
    # ?advice=true appends the advisor document
    _, adv = _get(uris[i], "/debug/heat?advice=true")
    assert adv["advice"]["dryRun"] is True
    assert adv["advice"]["hbmPinSet"]
    # unknown query args 400 (validation spec)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(uris[i], "/debug/heat?hot=1")
    assert e.value.code == 400


def test_cluster_heat_federation_with_legacy_peer(trio):
    servers, uris = trio
    st, doc = _get(uris[0], "/cluster/heat")
    assert st == 200
    status = {n["id"]: n["status"] for n in doc["nodes"]}
    assert status["a"] == "ok" and status["b"] == "ok"
    assert status["c"] == "legacy"  # 404 degrades, never an error
    # the merge carries every live node's fragments
    merged = {(e["index"], e["field"], e["shard"]) for e in doc["hot"]}
    for s in servers[:2]:
        for e in s.executor.heat.snapshot(top=0)["hot"]:
            assert (e["index"], e["field"], e["shard"]) in merged
    assert doc["generatedBy"] == "a"
    # node summaries ride along (the advisor's per-node skew input)
    assert "skew" in next(n for n in doc["nodes"] if n["id"] == "a")


def test_query_history_records_sheds(trio):
    """Satellite: rejected queries no longer vanish — a drain shed lands
    in /debug/query-history with principal, priority and reason."""
    servers, uris = trio
    servers[1].handler.draining = True
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(uris[1], "/index/h/query", raw=b"Count(Row(f=0))",
                  headers={"X-API-Key": "shed-witness"})
        assert e.value.code == 503
    finally:
        servers[1].handler.draining = False
    _, hist = _get(uris[1], "/debug/query-history")
    shed = [q for q in hist["queries"] if q.get("shed")]
    assert shed, hist
    entry = shed[0]
    assert entry["shed"] == "draining"
    assert entry["status"] == 503
    assert entry["principal"] == "key:shed-witness"
    assert entry["index"] == "h"
    assert "Count(Row(f=0))" in entry["pql"]


# --------------------------------------------------------------- advisor


def _fixed_trace_tracker():
    """Replay one fixed access trace with pinned timestamps."""
    t = HeatTracker()
    base = 100.0
    trace = [
        ("i", "a", "standard", 0, 50, 0),   # hot reader
        ("i", "a", "standard", 1, 20, 2),
        ("i", "b", "standard", 0, 1, 0),    # barely warm
        ("i", "c", "standard", 0, 0, 1),    # write-only
    ]
    for step, (ix, f, v, s, r, w) in enumerate(trace):
        t.touch(ix, f, v, s, reads=r, writes=w, h2d_bytes=64,
                uploads=1, now=base + step)
    # one fragment has gone fully cold (touched, then aged out)
    t.touch("i", "z", "standard", 9, reads=1, uploads=1, now=base - 50000)
    return t, base + 10


def test_advisor_deterministic_on_fixed_trace():
    from pilosa_tpu.analysis.advisor import advise

    t1, now1 = _fixed_trace_tracker()
    t2, now2 = _fixed_trace_tracker()
    a1 = advise(t1.snapshot(top=0, now=now1))
    a2 = advise(t2.snapshot(top=0, now=now2))
    assert a1 == a2  # byte-identical on a replayed trace
    assert a1["dryRun"] is True
    pins = [(e["index"], e["field"], e["shard"]) for e in a1["hbmPinSet"]]
    assert pins[0] == ("i", "a", 0)  # hottest first
    # the aged-out fragment with HBM history is an eviction candidate
    assert any(e["field"] == "z" for e in a1["evictionCandidates"])
    tiers = a1["tiers"]
    assert tiers["hbm"] >= 2 and tiers["hbm"] + tiers["host"] \
        + tiers["cold"] == 5
    # every assignment is deterministic and tier-consistent with score
    for e in tiers["assignments"]:
        if e["tier"] == "hbm":
            assert e["score"] >= HOT_SCORE


def test_advisor_node_skew_recommendations():
    from pilosa_tpu.analysis.advisor import advise

    t, now = _fixed_trace_tracker()
    doc = t.snapshot(top=0, now=now)
    nodes = [
        {"id": "a", "skew": 1.0, "hotFragments": 2, "health": "green"},
        {"id": "b", "skew": 9.0, "hotFragments": 7, "health": "green"},
        {"id": "c", "skew": 9.0, "hotFragments": 7, "health": "red"},
    ]
    adv = advise(doc, nodes=nodes)
    rec = {n["id"]: n["recommendation"] for n in adv["nodes"]}
    assert rec["a"] == "ok"
    assert rec["b"] == "rebalance-candidate"  # hot but healthy
    assert rec["c"] == "investigate-health"   # hot AND sick: page first


def test_merge_heat_docs_sums_replica_heat():
    t1, now = _fixed_trace_tracker()
    d = t1.snapshot(top=0, now=now)
    merged = merge_heat_docs({"a": d, "b": d})
    by = {(e["index"], e["field"], e["shard"]): e for e in merged["hot"]}
    one = {(e["index"], e["field"], e["shard"]): e for e in d["hot"]}
    for k, e in one.items():
        assert by[k]["reads"] == pytest.approx(2 * e["reads"])
        assert by[k]["score"] == pytest.approx(2 * e["score"], abs=1e-5)
        assert by[k]["nodes"] == 2
    assert merged["totals"]["reads"] == pytest.approx(
        2 * d["totals"]["reads"])


def test_render_advice_is_printable():
    from pilosa_tpu.analysis.advisor import advise, render_advice

    t, now = _fixed_trace_tracker()
    out = render_advice(advise(t.snapshot(top=0, now=now)))
    assert "HBM pin set" in out and "projected tiers" in out
