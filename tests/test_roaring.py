"""Roaring bitmap storage tests: set semantics, dense materialization,
Pilosa-format serialization round-trips and op-log replay.

Mirrors the reference's roaring_internal_test.go container-op matrix and
serialization round-trip coverage with randomized corpora.
"""

import io
import struct

import numpy as np
import pytest

from pilosa_tpu.storage.roaring import (
    ARRAY_MAX_SIZE,
    OP_ADD,
    OP_REMOVE,
    Bitmap,
    Container,
    fnv1a32,
)

RNG = np.random.default_rng(5)


def random_bitmap(n, lo=0, hi=1 << 22):
    vals = np.unique(RNG.integers(lo, hi, size=n).astype(np.uint64))
    return Bitmap(vals), set(vals.tolist())


def test_add_remove_contains():
    b = Bitmap()
    assert not b.any()
    assert b.add(100)
    assert not b.add(100)
    assert b.contains(100)
    assert b.count() == 1
    assert b.remove(100)
    assert not b.remove(100)
    assert not b.contains(100)
    assert b.count() == 0


def test_bulk_and_iteration():
    b, s = random_bitmap(10000)
    assert b.count() == len(s)
    assert set(b.slice().tolist()) == s
    assert b.min() == min(s)
    assert b.max() == max(s)
    # spot check membership
    for v in list(s)[:50]:
        assert b.contains(v)


def test_container_promotion_demotion():
    # Force a container across the array->bitmap threshold and back.
    vals = np.arange(0, ARRAY_MAX_SIZE + 10, dtype=np.uint64)
    b = Bitmap(vals)
    assert b.containers[0].kind == "bitmap"
    b.remove_many(vals[: 20])
    assert b.containers[0].kind == "array"
    assert b.count() == ARRAY_MAX_SIZE + 10 - 20


def test_slice_and_count_range():
    b, s = random_bitmap(5000, hi=1 << 20)
    lo, hi = 1000, 700000
    expect = sorted(v for v in s if lo <= v < hi)
    assert b.slice(lo, hi).tolist() == expect
    assert b.count_range(lo, hi) == len(expect)


def test_set_algebra():
    a, sa = random_bitmap(4000)
    b, sb = random_bitmap(6000)
    assert set(a.intersect(b).slice().tolist()) == sa & sb
    assert set(a.union(b).slice().tolist()) == sa | sb
    assert set(a.difference(b).slice().tolist()) == sa - sb
    assert set(a.xor(b).slice().tolist()) == sa ^ sb
    assert a.intersection_count(b) == len(sa & sb)


def test_dense_roundtrip():
    b, s = random_bitmap(3000, hi=1 << 20)
    words = b.to_dense_words(0, 1 << 20)
    assert words.dtype == np.uint32
    back = Bitmap.from_dense_words(words)
    assert set(back.slice().tolist()) == s
    # offset materialization: row 3 of a 2^20-wide shard
    base = 3 << 20
    b2 = Bitmap((np.array(sorted(s), dtype=np.uint64) + base))
    words2 = b2.to_dense_words(base, base + (1 << 20))
    np.testing.assert_array_equal(words2, words)
    back2 = Bitmap.from_dense_words(words2, base=base)
    assert set(back2.slice().tolist()) == {v + base for v in s}


@pytest.mark.parametrize("shape", ["array", "bitmap", "run", "mixed"])
def test_serialization_roundtrip(shape):
    if shape == "array":
        vals = np.unique(RNG.integers(0, 1 << 16, 100).astype(np.uint64))
    elif shape == "bitmap":
        vals = np.unique(RNG.integers(0, 1 << 16, 20000).astype(np.uint64))
    elif shape == "run":
        vals = np.arange(5, 30000, dtype=np.uint64)  # one long run
    else:
        vals = np.concatenate([
            np.unique(RNG.integers(0, 1 << 16, 50)).astype(np.uint64),
            np.arange(1 << 16, (1 << 16) + 5000, dtype=np.uint64),
            np.unique(RNG.integers(1 << 17, 1 << 18, 30000)).astype(np.uint64),
            np.array([1 << 40, (1 << 40) + 1], dtype=np.uint64),  # 64-bit keys
        ])
    b = Bitmap(vals)
    data = b.to_bytes()
    # header sanity: magic + version + count
    magic, version, count = struct.unpack_from("<HHI", data, 0)
    assert magic == 12348 and version == 0
    assert count == len(b.containers)
    back = Bitmap.from_bytes(data)
    assert set(back.slice().tolist()) == set(vals.tolist())


def test_run_encoding_chosen_for_runs():
    b = Bitmap(np.arange(0, 60000, dtype=np.uint64))
    code, payload = b.containers[0].best_encoding()
    assert code == 3  # run
    assert len(payload) == 2 + 4  # one run


def test_oplog_append_and_replay():
    b, s = random_bitmap(1000)
    snapshot = b.to_bytes()
    log = io.BytesIO()
    b.op_writer = log
    b.add(42)
    b.add(99)
    b.remove(42)
    assert b.op_n == 3
    data = snapshot + log.getvalue()
    back = Bitmap.from_bytes(data)
    assert back.op_n == 3
    expect = (s | {99}) - ({42} - s)
    if 42 in s:
        expect -= {42}
    assert set(back.slice().tolist()) == expect
    assert back.contains(99)
    assert not back.contains(42)


def test_oplog_checksum_rejected():
    b = Bitmap(np.array([1, 2, 3], dtype=np.uint64))
    data = b.to_bytes()
    bad_op = struct.pack("<BQ", OP_ADD, 7) + struct.pack("<I", 0xDEADBEEF)
    with pytest.raises(ValueError, match="checksum"):
        Bitmap.from_bytes(data + bad_op)


def test_fnv1a32_vector():
    # FNV-1a reference vectors
    assert fnv1a32(b"") == 2166136261
    assert fnv1a32(b"a") == 0xE40C292C
    assert fnv1a32(b"foobar") == 0xBF9CF968


def test_check():
    b, _ = random_bitmap(500)
    b.check()
    b.containers[0] = Container("array", np.array([5, 4], dtype=np.uint16))
    with pytest.raises(ValueError):
        b.check()


def test_union_in_place_kway():
    rng = np.random.default_rng(3)
    parts = [np.unique(rng.integers(0, 1 << 22, size=n).astype(np.uint64))
             for n in (5000, 300, 9000, 1)]
    dst = Bitmap(parts[0])
    dst.union_in_place(*(Bitmap(p) for p in parts[1:]))
    expect = np.unique(np.concatenate(parts))
    assert dst.count() == expect.size
    assert np.array_equal(dst.slice(), expect)
    # k=0 is a no-op
    before = dst.count()
    dst.union_in_place()
    assert dst.count() == before


def test_repair():
    b = Bitmap(np.arange(5000, dtype=np.uint64))
    # simulate external mutation leaving a stale encoding + an empty container
    big = b.containers[0]
    assert big.kind == "bitmap"
    big.data[:] = 0
    big.data[0] = 3  # now only 2 bits: should re-encode to array
    b.containers[7] = Container("array", np.empty(0, dtype=np.uint16))
    changed = b.repair()
    assert changed == 2
    assert b.containers[0].kind == "array"
    assert 7 not in b.containers
    b.check()


def test_contains_many():
    rng = np.random.default_rng(5)
    vals = np.unique(rng.integers(0, 1 << 21, size=6000).astype(np.uint64))
    b = Bitmap(vals)
    probe = np.concatenate([vals[:100], vals[:100] + np.uint64(1 << 40)])
    mask = b.contains_many(probe)
    assert mask[:100].all() and not mask[100:].any()
    assert not b.contains_many(np.array([], dtype=np.uint64)).any()


# ---------------------------------------------------------------------------
# in-memory run containers (roaring/roaring.go:56-62,1594; VERDICT r1 item 6)
# ---------------------------------------------------------------------------


def test_run_container_memory_and_roundtrip():
    """A fully-set container costs bytes as one run, not 8 KiB inflated."""
    from pilosa_tpu.storage.roaring import Container

    b = Bitmap(np.arange(1 << 16, dtype=np.uint64))  # one full container
    assert b.containers[0].kind == "bitmap"  # built dense
    b.optimize()
    c = b.containers[0]
    assert c.kind == "run" and c.data.nbytes == 4, (c.kind, c.data.nbytes)
    assert c.n == 1 << 16
    # round-trips through the Pilosa format AND stays a run on read
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert b2.containers[0].kind == "run"
    assert b2.count() == 1 << 16
    assert list(b2.slice(0, 10)) == list(range(10))
    assert b2.contains(0) and b2.contains(65535)


def test_run_container_algebra_and_mutation():
    rng = np.random.default_rng(9)
    b = Bitmap(np.arange(1000, 60000, dtype=np.uint64))
    b.optimize()
    assert b.containers[0].kind == "run"
    other_vals = np.unique(rng.integers(0, 1 << 16, 5000)).astype(np.uint64)
    other = Bitmap(other_vals)
    inter = b.intersect(other)
    sother = set(other_vals.tolist())
    expect = {v for v in sother if 1000 <= v < 60000}
    assert set(inter.slice().tolist()) == expect
    assert b.intersection_count(other) == len(expect)
    uni = b.union(other)
    assert uni.count() == len(set(range(1000, 60000)) | sother)
    # mutation re-encodes away from run, correctly
    assert b.add(5) and b.contains(5)
    assert b.remove(1000) and not b.contains(1000)
    assert b.count() == 59000 + 1 - 1 + 1 - 1  # +5, -1000... recompute:
    assert b.count() == len((set(range(1000, 60000)) | {5}) - {1000})
    b.check()


def test_run_container_contains_many_and_dense():
    b = Bitmap(np.concatenate([
        np.arange(10, 20, dtype=np.uint64),
        np.arange(100, 4000, dtype=np.uint64),
        np.arange(65000, 65536, dtype=np.uint64),
    ]))
    b.optimize()
    assert b.containers[0].kind == "run"
    probe = np.array([0, 10, 19, 20, 99, 100, 3999, 4000, 64999, 65000, 65535],
                     dtype=np.uint64)
    got = b.contains_many(probe)
    expect = [b.contains(int(v)) for v in probe]
    assert got.tolist() == expect
    words = b.to_dense_words(0, 1 << 16)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    assert np.flatnonzero(bits).tolist() == sorted(b.slice().tolist())
    b.check()


def test_time_view_row_rss_kb_not_mb(tmp_path):
    """The VERDICT scenario: a dense time-view row (all 2^20 bits of a
    shard row set) costs KB as runs in memory, not MB inflated."""
    from pilosa_tpu.constants import SHARD_WIDTH
    from pilosa_tpu.storage.fragment import Fragment

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard_2024", 0).open()
    frag.bulk_import([3] * SHARD_WIDTH, list(range(SHARD_WIDTH)))
    frag.close()
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard_2024", 0).open()
    # materialize the whole row; runs survive materialization
    assert frag.row_count(3) == SHARD_WIDTH
    dense = frag.row_dense(3)
    assert int(np.bitwise_count(dense).sum()) == SHARD_WIDTH
    total_bytes = sum(c.data.nbytes for c in frag.storage.containers.values())
    assert total_bytes < 1024, total_bytes  # 16 runs x 4B, not 16 x 8KiB
    frag.close()


def test_container_op_matrix_all_kind_pairs():
    """Exhaustive op parity over every encoding pair — the analog of the
    reference's 45 hand-specialized kernels (roaring.go:2162-3771): for
    each (kind_a, kind_b) in {array, bitmap, run}^2 and each op, `op` and
    `op_count` must agree with python-set algebra, the result's encoding
    must be consistent with its cardinality (array iff <= ARRAY_MAX_SIZE,
    unless run-encoded), and the inputs must be left untouched."""
    from pilosa_tpu.storage.roaring import Container

    rng = np.random.default_rng(77)
    shapes = {
        # sparse values -> array kind
        "array": np.unique(rng.integers(0, 1 << 16, 700)).astype(np.uint16),
        # dense scatter -> bitmap kind
        "bitmap": np.unique(rng.integers(0, 1 << 16, 20000)).astype(np.uint16),
        # few long intervals -> run kind
        "run": np.concatenate([
            np.arange(50, 9000, dtype=np.uint16),
            np.arange(20000, 41000, dtype=np.uint16),
            np.arange(65500, 65536, dtype=np.uint16),
        ]),
    }
    conts, models = {}, {}
    for want_kind, vals in shapes.items():
        c = Container.from_values(vals).optimize()
        assert c.kind == want_kind, (want_kind, c.kind)
        conts[want_kind] = c
        models[want_kind] = set(vals.tolist())

    op_model = {
        "and": lambda a, b: a & b,
        "or": lambda a, b: a | b,
        "xor": lambda a, b: a ^ b,
        "andnot": lambda a, b: a - b,
    }
    for ka, a in conts.items():
        for kb, b in conts.items():
            for opname, fn in op_model.items():
                expect = fn(models[ka], models[kb])
                out = a.op(b, opname)
                assert set(out.values().tolist()) == expect, \
                    (ka, kb, opname)
                assert out.n == len(expect)
                if out.kind != "run":  # encoding/cardinality consistency
                    from pilosa_tpu.storage.roaring import ARRAY_MAX_SIZE
                    assert out.kind == (
                        "array" if out.n <= ARRAY_MAX_SIZE else "bitmap"), \
                        (ka, kb, opname, out.kind, out.n)
                assert a.op_count(b, opname) == len(expect), \
                    (ka, kb, opname)
                # inputs must be untouched (ops are pure)
                assert set(a.values().tolist()) == models[ka]
                assert set(b.values().tolist()) == models[kb]


def test_run_mutation_fuzz_vs_set_model():
    """Interleaved mutation fuzz with optimize() forced between steps so
    run encodings keep appearing mid-stream (the new native run kernels'
    adversarial workout): add/remove batches, container ops, serialization
    round-trips — every step checked against a python-set model."""
    import io

    rng = np.random.default_rng(123)
    b = Bitmap()
    model = set()
    # clustered value space: long runs + scattered points, 2 containers
    def draw(n):
        if rng.integers(0, 2):
            s = int(rng.integers(0, 2 << 16))
            return np.arange(s, min(s + int(rng.integers(1, 4000)),
                                    2 << 16), dtype=np.uint64)
        return rng.integers(0, 2 << 16, size=n).astype(np.uint64)

    for step in range(60):
        vals = draw(int(rng.integers(1, 500)))
        if rng.integers(0, 3) == 0:
            for v in np.unique(vals):
                if b.remove(int(v)):
                    model.discard(int(v))
                else:
                    assert int(v) not in model
        else:
            for v in np.unique(vals):
                added = b.add(int(v))
                assert added == (int(v) not in model)
                model.add(int(v))
        if step % 5 == 0:
            b.optimize()  # re-pick encodings (runs appear here)
        if step % 7 == 0:
            other_vals = draw(300)
            other = Bitmap(np.unique(other_vals))
            other.optimize()
            omodel = set(np.unique(other_vals).tolist())
            assert b.intersection_count(other) == len(model & omodel)
            assert b.intersect(other).count() == len(model & omodel)
            assert b.union(other).count() == len(model | omodel)
        if step % 11 == 0:
            buf = io.BytesIO()
            b.write_to(buf)
            b = Bitmap.from_bytes(buf.getvalue())
        assert b.count() == len(model), step
        b.check()
    assert set(b.slice().tolist()) == model
