"""Hybrid sparse/dense device containers (ISSUE 15 tentpole).

Three layers under test:

* the sparse kernel family (ops/bitvector.py): padded sorted-index
  algebra vs a numpy set-algebra oracle, including sentinel padding,
  empty rows, the galloping orientation, and the Pallas blocked
  sparse∩dense variant's parity;
* the HybridManager (parallel/residency.py): threshold choice,
  promote/demote hysteresis, heat-informed demotion, kill switches;
* the executor integration: sparse leaves in the residency manager with
  real padded byte accounting, on-device materialization for dense
  consumers, the /debug/vars-shaped snapshot, and equal-budget capacity
  (the ≥4x resident-rows claim, asserted at test scale).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.ops import bitvector as bv
from pilosa_tpu.parallel.residency import (
    DEFAULT_SPARSE_THRESHOLD,
    HybridManager,
)

W = SHARD_WIDTH // 32
SENT = bv.SPARSE_SENTINEL


def _sparse(cols, slots):
    return jnp.asarray(bv.sparse_from_columns(
        np.asarray(sorted(cols), dtype=np.int64), slots)[None])


def _as_set(sp_row):
    arr = np.asarray(sp_row)[0]
    return set(arr[arr < SENT].tolist())


# ------------------------------------------------------------- kernels


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sparse_kernel_algebra_matches_set_oracle(seed):
    rng = np.random.default_rng(seed)
    na, nb = int(rng.integers(0, 400)), int(rng.integers(1, 2000))
    sa = set(rng.choice(SHARD_WIDTH, size=na, replace=False).tolist())
    sb = set(rng.choice(SHARD_WIDTH, size=nb, replace=False).tolist())
    a = _sparse(sa, HybridManager.pad_slots(max(na, 1)))
    b = _sparse(sb, HybridManager.pad_slots(max(nb, 1)))
    assert _as_set(bv.sparse_intersect(a, b)) == sa & sb
    assert _as_set(bv.sparse_union(a, b)) == sa | sb
    assert _as_set(bv.sparse_xor(a, b)) == sa ^ sb
    assert _as_set(bv.sparse_difference(a, b)) == sa - sb
    assert int(np.asarray(bv.sparse_count(a))[0]) == len(sa)
    dense_b = jnp.asarray(
        bv.dense_from_columns(np.asarray(sorted(sb)))[None])
    assert _as_set(bv.sparse_intersect_dense(a, dense_b)) == sa & sb
    assert _as_set(bv.sparse_difference_dense(a, dense_b)) == sa - sb
    assert int(np.asarray(bv.sparse_dense_count(a, dense_b))[0]) \
        == len(sa & sb)
    # round trip through the materializer
    md = np.asarray(bv.sparse_to_dense(a, W))[0]
    assert set(bv.columns_from_dense(md).tolist()) == sa


def test_sparse_kernels_sorted_sentinel_contract():
    """Every kernel's output is sorted with sentinel padding at the tail
    — the invariant that lets compositions chain without re-normalizing."""
    rng = np.random.default_rng(7)
    sa = set(rng.choice(SHARD_WIDTH, 100, replace=False).tolist())
    sb = set(rng.choice(SHARD_WIDTH, 300, replace=False).tolist())
    a, b = _sparse(sa, 128), _sparse(sb, 512)
    for out in (bv.sparse_intersect(a, b), bv.sparse_union(a, b),
                bv.sparse_xor(a, b), bv.sparse_difference(a, b)):
        row = np.asarray(out)[0]
        assert (np.diff(row) >= 0).all()
        live = row[row < SENT]
        assert live.size == np.unique(live).size


def test_sparse_kernels_empty_rows():
    empty = _sparse([], 8)
    full = _sparse([1, 5, 9], 8)
    assert _as_set(bv.sparse_intersect(empty, full)) == set()
    assert _as_set(bv.sparse_union(empty, full)) == {1, 5, 9}
    assert _as_set(bv.sparse_difference(full, empty)) == {1, 5, 9}
    assert int(np.asarray(bv.sparse_count(empty))[0]) == 0
    assert np.asarray(bv.sparse_to_dense(empty, W)).sum() == 0


def test_pallas_sparse_dense_parity():
    """The blocked Pallas gather-and-test variant returns bit-identical
    sorted sentinel-padded output (the PILOSA_TPU_PALLAS contract)."""
    from pilosa_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(3)
    sa = set(rng.choice(SHARD_WIDTH, 500, replace=False).tolist())
    sb = set(rng.choice(SHARD_WIDTH, 5000, replace=False).tolist())
    sp = jnp.asarray(np.stack(
        [bv.sparse_from_columns(np.asarray(sorted(sa)), 512)] * 3))
    dense = jnp.asarray(np.stack(
        [bv.dense_from_columns(np.asarray(sorted(sb)))] * 3))
    want = np.asarray(bv.sparse_intersect_dense(sp, dense))
    got = np.asarray(pk.sparse_intersect_dense(sp, dense))
    assert (want == got).all()


def test_eval_hybrid_mixed_tree():
    rng = np.random.default_rng(11)
    sets = [set(rng.choice(SHARD_WIDTH, n, replace=False).tolist())
            for n in (120, 350, 7000)]
    leaves = [_sparse(sets[0], 128), _sparse(sets[1], 512),
              jnp.asarray(bv.dense_from_columns(
                  np.asarray(sorted(sets[2])))[None])]
    kinds = ["sparse", "sparse", "dense"]
    prog = ("andnot", ("or", ("leaf", 0), ("leaf", 1)),
            ("and", ("leaf", 1), ("leaf", 2)))
    expect = (sets[0] | sets[1]) - (sets[1] & sets[2])
    kind, arr = bv.eval_hybrid(prog, leaves, kinds, W)
    dense = np.asarray(bv.sparse_to_dense(arr, W)
                       if kind == "sparse" else arr)[0]
    assert set(bv.columns_from_dense(dense).tolist()) == expect
    assert bv.hybrid_count(prog, leaves, kinds) == len(expect)


def test_eval_hybrid_union_cap_densifies():
    """A union whose combined slot count would exceed SPARSE_UNION_CAP
    falls back to a dense plane instead of growing index arrays toward
    plane size."""
    rng = np.random.default_rng(13)
    sa = set(rng.choice(SHARD_WIDTH, 12000, replace=False).tolist())
    sb = set(rng.choice(SHARD_WIDTH, 12000, replace=False).tolist())
    # 16384 + 16384 slots > SPARSE_UNION_CAP -> the union densifies
    leaves = [_sparse(sa, 1 << 14), _sparse(sb, 1 << 14)]
    kind, arr = bv.eval_hybrid(("or", ("leaf", 0), ("leaf", 1)),
                               leaves, ["sparse", "sparse"], W)
    assert kind == "dense"
    assert set(bv.columns_from_dense(np.asarray(arr)[0]).tolist()) \
        == sa | sb


# ------------------------------------------------------------- manager


def test_manager_threshold_and_slots():
    m = HybridManager(threshold=1000)
    rep, slots = m.choose(("i", "f", "standard", 1), 100)
    assert rep == "sparse" and slots == 128
    rep, slots = m.choose(("i", "f", "standard", 2), 1001)
    assert rep == "dense"
    assert m.pad_slots(0) == 8 and m.pad_slots(8) == 8
    assert m.pad_slots(9) == 16 and m.pad_slots(4096) == 4096


def test_manager_hysteresis_band():
    """Promote at threshold crossing; inside the band a dense row stays
    dense (no heat tracker = never cold), demote below the band floor."""
    m = HybridManager(threshold=1000, hysteresis=0.25)
    key = ("i", "f", "standard", 7)
    assert m.choose(key, 900)[0] == "sparse"   # first sight, under thr
    assert m.choose(key, 1200)[0] == "dense"   # promoted
    assert m.promoted == 1
    assert m.choose(key, 900)[0] == "dense"    # band [750, 1000]: sticky
    assert m.choose(key, 700)[0] == "sparse"   # below band floor: demoted
    assert m.demoted == 1
    assert m.choose(key, 900)[0] == "sparse"   # band is one-sided: only a
    assert m.demoted == 1                      # DENSE row is sticky in it


def test_manager_heat_informed_demotion():
    """A band-resident dense row demotes when every covered fragment is
    cold — the 'cold dense rows re-enter as sparse' rule."""

    class FakeTracker:
        enabled = True

        def __init__(self):
            self.score = 1.0

        def scores_for(self, keys):
            return [self.score] * len(keys)

    t = FakeTracker()
    m = HybridManager(threshold=1000, hysteresis=0.25, heat=t)
    key = ("i", "f", "standard", 9)
    fkeys = [("i", "f", "standard", 0)]
    m.choose(key, 1200, fkeys)                      # dense
    assert m.choose(key, 900, fkeys)[0] == "dense"  # band + hot: sticky
    t.score = 0.0                                   # fragment went cold
    assert m.choose(key, 900, fkeys)[0] == "sparse"
    assert m.demoted == 1


def test_manager_kill_switches(monkeypatch):
    m = HybridManager(threshold=1000)
    monkeypatch.setenv("PILOSA_TPU_HYBRID", "0")
    assert not m.active()
    assert m.choose(("i", "f", "standard", 1), 10) == ("dense", 0)
    monkeypatch.delenv("PILOSA_TPU_HYBRID")
    assert m.active()
    m.threshold = 0
    assert not m.active()


# ------------------------------------------------- executor integration


@pytest.fixture()
def holder_ex(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    idx = h.create_index("hy", track_existence=False)
    f = idx.create_field("f")
    rng = np.random.default_rng(5)
    sets = {}
    for rid, n in ((0, 150), (1, 800), (2, 6000)):
        cols = rng.choice(2 * SHARD_WIDTH, size=n, replace=False)
        f.import_bits([rid] * n, cols.tolist())
        sets[rid] = set(cols.tolist())
    ex = Executor(h)
    yield h, ex, sets
    h.close()


def test_executor_sparse_residency_accounting(holder_ex):
    """Sparse leaves land in the residency LRU under the 'sparse' kind at
    their real padded byte cost — a 150-bit row over 2 shards is a
    2x256-slot int32 array (2 KiB), not two 128 KiB planes."""
    h, ex, sets = holder_ex
    (n,) = ex.execute("hy", "Count(Intersect(Row(f=0), Row(f=1)))")
    assert n == len(sets[0] & sets[1])
    # slots bucket by the LARGEST per-shard cardinality, not the total
    slots = {}
    for rid in (0, 1):
        per_shard = max(
            sum(1 for c in sets[rid] if c // SHARD_WIDTH == s)
            for s in (0, 1))
        slots[rid] = HybridManager.pad_slots(per_shard)
    by_kind = ex.residency.snapshot()["by_kind"]
    assert by_kind["sparse"]["entries"] == 2
    assert by_kind["sparse"]["bytes"] == 2 * 4 * (slots[0] + slots[1])
    snap = ex.hybrid_snapshot()
    assert snap["sparseUploads"] == 2
    assert snap["residentSparseLeaves"] == 2
    plan_reps = None  # representation rides the plan node
    from pilosa_tpu import planner as _planner
    call = __import__("pilosa_tpu.pql", fromlist=["parse_string_cached"]) \
        .parse_string_cached("Count(Intersect(Row(f=0), Row(f=1)))")
    planned, info = ex.planner.plan_call(
        h.index("hy"), call.calls[0], [0, 1])
    # plan info carries no hybrid entries yet (recorded at compile), but
    # executing under a profile does — assert via current_plan
    tok = _planner.current_plan.set(info)
    try:
        ex._compile(h.index("hy"), planned.children[0], [0, 1])
    finally:
        _planner.current_plan.reset(tok)
    plan_reps = info.get("hybrid")
    assert plan_reps and all(r["rep"] == "sparse" for r in plan_reps)
    assert {r["slots"] for r in plan_reps} == {slots[0], slots[1]}


def test_executor_dense_consumer_materializes_on_device(holder_ex):
    """A dense consumer (TopN recount path: _row_leaf_dev) of a row that
    is sparse-resident gets its plane by on-device materialization — no
    second host upload of the row."""
    h, ex, sets = holder_ex
    idx = h.index("hy")
    ex.execute("hy", "Count(Row(f=0))")  # sparse-resident now
    before = ex.hybrid.snapshot()
    dense = ex._row_leaf_dev(idx, "f", "standard", [0, 1], 0)
    after = ex.hybrid.snapshot()
    assert after["materialized"] == before["materialized"] + 1
    assert after["denseUploads"] == before["denseUploads"]  # no upload
    cols = set()
    host = np.asarray(dense)
    for s in (0, 1):
        cols |= {int(c) + s * SHARD_WIDTH
                 for c in bv.columns_from_dense(host[s]).tolist()}
    assert cols == sets[0]


def test_executor_kill_switch_restores_pure_dense(holder_ex, monkeypatch):
    h, ex, sets = holder_ex
    monkeypatch.setenv("PILOSA_TPU_HYBRID", "0")
    (n,) = ex.execute("hy", "Count(Row(f=0))")
    assert n == len(sets[0])
    assert ex.residency.snapshot()["by_kind"].get("sparse") is None
    assert ex.hybrid_snapshot()["sparseUploads"] == 0


def test_equal_budget_capacity_multiplier(tmp_path):
    """The headline claim at test scale: at an HBM budget that holds only
    4 dense planes, hybrid keeps the WHOLE 32-row sparse working set
    resident — ≥4x the resident-row capacity, with zero evictions."""
    h = Holder(str(tmp_path / "cap")).open()
    try:
        idx = h.create_index("cap", track_existence=False)
        f = idx.create_field("f")
        rng = np.random.default_rng(9)
        n_rows = 32
        for r in range(n_rows):
            cols = rng.choice(SHARD_WIDTH, size=300, replace=False)
            f.import_bits([r] * cols.size, cols.tolist())

        def sweep(ex):
            ex.plan_cache.enabled = False
            ex.residency.budget = 4 * (SHARD_WIDTH // 8)
            for _ in range(2):
                for r in range(n_rows):
                    ex.execute("cap", f"Count(Row(f={r}))")
            bk = ex.residency.snapshot()["by_kind"]
            return (bk.get("sparse", {}).get("entries", 0)
                    + bk.get("row", {}).get("entries", 0))

        hybrid_ex = Executor(h)
        assert hybrid_ex.hybrid.active()
        resident_hybrid = sweep(hybrid_ex)
        dense_ex = Executor(h)
        dense_ex.hybrid.threshold = 0
        resident_dense = sweep(dense_ex)
        assert resident_dense <= 4
        assert resident_hybrid >= 4 * resident_dense
        assert resident_hybrid == n_rows  # everything stayed resident
    finally:
        h.close()
