"""Hybrid sparse/dense parity fuzz (ISSUE 15 satellite).

Two executors share one holder: `hybrid` runs with the default sparse
threshold AND the plan cache deliberately left warm (the interleaved
writes must invalidate it through generation keys even as rows change
representation), `plain` runs with sparse-threshold 0 — pure dense.
Rounds interleave randomized nested PQL trees with set/clear churn that
drives rows across the threshold in BOTH directions (a sparse row bulks
up past it, a dense row is cleared below it), so the promote/demote
hysteresis, the generation-keyed residency entries of both kinds, and
the mixed-representation kernels are all exercised against the dense
oracle. Any divergence — results, or error-vs-result behavior — is a
hybrid bug.

A final phase flips the PILOSA_TPU_HYBRID=0 kill switch at runtime and
asserts the hybrid executor immediately behaves purely dense.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor, Pairs
from pilosa_tpu.models.holder import Holder

FIELDS = ("f", "g")
N_ROWS = 6
SHARDS = 2
# the hybrid executor's threshold for this test: small enough that churn
# rounds can push rows across it both ways quickly
THRESHOLD = 512


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hybridfuzz")
    h = Holder(str(tmp / "data")).open()
    rng = np.random.default_rng(23)
    idx = h.create_index("z")
    for fname in FIELDS:
        f = idx.create_field(fname)
        for rid in range(N_ROWS - 1):  # last row starts empty
            # rows straddle the threshold: some well under, some over
            n = int(rng.integers(16, 96) * (8 ** (rid % 3)))
            cols = rng.choice(SHARDS * SHARD_WIDTH,
                              size=min(n, 6000), replace=False)
            f.import_bits([rid] * len(cols), cols.tolist())
            for c in cols[:32]:
                idx.mark_exists(int(c))
    hybrid = Executor(h)
    hybrid.hybrid.threshold = THRESHOLD
    assert hybrid.hybrid.active() and hybrid.plan_cache is not None
    plain = Executor(h)
    plain.hybrid.threshold = 0
    assert not plain.hybrid.active()
    yield h, hybrid, plain, rng
    h.close()


def _rand_bitmap(rng, depth: int) -> str:
    if depth <= 0 or rng.random() < 0.35:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        rid = int(rng.integers(N_ROWS))
        return f"Row({fname}={rid})"
    op = ("Intersect", "Union", "Difference", "Xor",
          "Not")[int(rng.integers(5))]
    if op == "Not":
        return f"Not({_rand_bitmap(rng, depth - 1)})"
    n = int(rng.integers(2, 4))
    kids = ", ".join(_rand_bitmap(rng, depth - 1) for _ in range(n))
    return f"{op}({kids})"


def _rand_query(rng) -> str:
    inner = _rand_bitmap(rng, int(rng.integers(1, 4)))
    shape = rng.random()
    if shape < 0.5:
        return f"Count({inner})"
    if shape < 0.65:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        return f"TopN({fname}, {inner}, n=4)"
    return inner


def _canon(result):
    if isinstance(result, Pairs):
        return ("pairs", tuple(result))
    if isinstance(result, list):
        return ("list", tuple(
            tuple(sorted(r.items())) if isinstance(r, dict) else r
            for r in result))
    if hasattr(result, "columns"):
        return ("row", tuple(int(c) for c in result.columns()))
    return ("val", result)


def _both(hybrid, plain, pql):
    outs = []
    for e in (hybrid, plain):
        try:
            (res,) = e.execute("z", pql)
            outs.append(("ok", _canon(res)))
        except ExecutionError as err:
            outs.append(("err", type(err).__name__, str(err)[:80]))
    assert outs[0] == outs[1], f"divergence on {pql}: {outs}"


def _churn(h, hybrid, plain, rng):
    """Interleaved writes through BOTH executors' shared holder — chosen
    to cross the threshold in both directions: bulk imports fatten a
    sparse row past it, clears thin a dense row below it."""
    idx = h.index("z")
    fname = FIELDS[int(rng.integers(len(FIELDS)))]
    f = idx.field(fname)
    rid = int(rng.integers(N_ROWS))
    action = rng.random()
    if action < 0.45:
        # fatten: push toward/past the threshold
        cols = rng.choice(SHARDS * SHARD_WIDTH,
                          size=int(rng.integers(64, 2 * THRESHOLD)),
                          replace=False)
        f.import_bits([rid] * len(cols), cols.tolist())
    elif action < 0.55:
        # empty the row outright: the decisive downward crossing (a
        # dense row's next upload must come back sparse — demotion)
        from pilosa_tpu.pql import Call
        hybrid._execute_clear_row(idx, Call("ClearRow", {fname: rid}),
                                  None)
    elif action < 0.8:
        # thin: single-bit clears through the write path
        cols = rng.integers(0, SHARDS * SHARD_WIDTH,
                            size=int(rng.integers(8, 64)))
        for c in cols.tolist():
            hybrid._execute_clear(
                idx, __import__("pilosa_tpu.pql",
                                fromlist=["Call"]).Call(
                    "Clear", {"_col": int(c), fname: rid}), None)
    else:
        # single sets through the executor write path
        cols = rng.integers(0, SHARDS * SHARD_WIDTH,
                            size=int(rng.integers(8, 64)))
        for c in cols.tolist():
            hybrid._execute_set(
                idx, __import__("pilosa_tpu.pql",
                                fromlist=["Call"]).Call(
                    "Set", {"_col": int(c), fname: rid}), None)


def test_hybrid_parity_under_threshold_churn(setup):
    h, hybrid, plain, rng = setup
    for round_no in range(40):
        for _ in range(4):
            _both(hybrid, plain, _rand_query(rng))
        _churn(h, hybrid, plain, rng)
    snap = hybrid.hybrid.snapshot()
    # the churn really drove representation both ways
    assert snap["sparseUploads"] > 0 and snap["denseUploads"] > 0
    assert snap["promoted"] > 0, snap
    assert snap["demoted"] > 0, snap


def test_hybrid_kill_switch_parity(setup, monkeypatch):
    """PILOSA_TPU_HYBRID=0 flips the hybrid executor to pure dense at
    runtime — same results, no new sparse uploads."""
    h, hybrid, plain, rng = setup
    monkeypatch.setenv("PILOSA_TPU_HYBRID", "0")
    before = hybrid.hybrid.snapshot()["sparseUploads"]
    for _ in range(12):
        _both(hybrid, plain, _rand_query(rng))
    assert hybrid.hybrid.snapshot()["sparseUploads"] == before


def test_zero_threshold_restores_pure_dense(setup):
    """[query] sparse-threshold = 0 is the config-side off switch."""
    h, hybrid, plain, rng = setup
    old = hybrid.hybrid.threshold
    hybrid.hybrid.threshold = 0
    try:
        before = hybrid.hybrid.snapshot()["sparseUploads"]
        for _ in range(12):
            _both(hybrid, plain, _rand_query(rng))
        assert hybrid.hybrid.snapshot()["sparseUploads"] == before
    finally:
        hybrid.hybrid.threshold = old
