"""Hybrid sparse/run/dense parity fuzz (ISSUE 15 satellite; ISSUE 17
extended it three-way).

Two executors share one holder: `hybrid` runs with the default sparse
threshold AND the plan cache deliberately left warm (the interleaved
writes must invalidate it through generation keys even as rows change
representation), `plain` runs with sparse-threshold 0 — pure dense.
Rounds interleave randomized nested PQL trees with set/clear churn that
drives rows across BOTH thresholds in BOTH directions: a sparse row
bulks up past the cardinality threshold, a dense row is cleared below
it, a runny row's runs are SPLIT by mid-run clears (interval count
crossing the run threshold promotes it dense) and MERGED back by
adjacent contiguous sets (demoting it to runs again). The promote/
demote hysteresis, the generation-keyed residency entries of all three
kinds, and the mixed-representation kernels are all exercised against
the dense oracle. Any divergence — results, or error-vs-result
behavior — is a hybrid bug.

A final phase flips the PILOSA_TPU_HYBRID=0 kill switch at runtime and
asserts the hybrid executor immediately behaves purely dense; a Pallas
phase re-runs the parity with PILOSA_TPU_PALLAS-style kernels on
(interpret mode off-TPU).
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor, Pairs
from pilosa_tpu.models.holder import Holder

FIELDS = ("f", "g")
N_ROWS = 6
SHARDS = 2
# the hybrid executor's threshold for this test: small enough that churn
# rounds can push rows across it both ways quickly
THRESHOLD = 512
# interval-count threshold for the run representation — small so a few
# dozen mid-run clears (splits) push a runny row across it
RUN_THRESHOLD = 48
# row N_ROWS-2 is the dedicated RUNNY row: seeded as contiguous blocks
# (cardinality above THRESHOLD, interval count far below RUN_THRESHOLD)
RUNNY_ROW = N_ROWS - 2
RUNNY_BASE = {"f": 70_000, "g": SHARD_WIDTH + 90_000}
RUNNY_LEN = 1500


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hybridfuzz")
    h = Holder(str(tmp / "data")).open()
    rng = np.random.default_rng(23)
    idx = h.create_index("z")
    for fname in FIELDS:
        f = idx.create_field(fname)
        for rid in range(N_ROWS - 1):  # last row starts empty
            if rid == RUNNY_ROW:
                # the runny row: two contiguous blocks — cardinality
                # well past THRESHOLD but only 2 intervals, so the
                # three-way planner picks the run representation
                base = RUNNY_BASE[fname]
                cols = np.concatenate([
                    np.arange(base, base + RUNNY_LEN),
                    np.arange(base + 50_000, base + 50_000 + RUNNY_LEN),
                ])
            else:
                # rows straddle the threshold: some well under, some over
                n = int(rng.integers(16, 96) * (8 ** (rid % 3)))
                cols = rng.choice(SHARDS * SHARD_WIDTH,
                                  size=min(n, 6000), replace=False)
            f.import_bits([rid] * len(cols), cols.tolist())
            for c in cols[:32]:
                idx.mark_exists(int(c))
    hybrid = Executor(h)
    hybrid.hybrid.threshold = THRESHOLD
    hybrid.hybrid.run_threshold = RUN_THRESHOLD
    assert hybrid.hybrid.active() and hybrid.plan_cache is not None
    plain = Executor(h)
    plain.hybrid.threshold = 0
    assert not plain.hybrid.active()
    yield h, hybrid, plain, rng
    h.close()


def _rand_bitmap(rng, depth: int) -> str:
    if depth <= 0 or rng.random() < 0.35:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        rid = int(rng.integers(N_ROWS))
        return f"Row({fname}={rid})"
    op = ("Intersect", "Union", "Difference", "Xor",
          "Not")[int(rng.integers(5))]
    if op == "Not":
        return f"Not({_rand_bitmap(rng, depth - 1)})"
    n = int(rng.integers(2, 4))
    kids = ", ".join(_rand_bitmap(rng, depth - 1) for _ in range(n))
    return f"{op}({kids})"


def _rand_query(rng) -> str:
    inner = _rand_bitmap(rng, int(rng.integers(1, 4)))
    shape = rng.random()
    if shape < 0.5:
        return f"Count({inner})"
    if shape < 0.65:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        return f"TopN({fname}, {inner}, n=4)"
    return inner


def _canon(result):
    if isinstance(result, Pairs):
        return ("pairs", tuple(result))
    if isinstance(result, list):
        return ("list", tuple(
            tuple(sorted(r.items())) if isinstance(r, dict) else r
            for r in result))
    if hasattr(result, "columns"):
        return ("row", tuple(int(c) for c in result.columns()))
    return ("val", result)


def _both(hybrid, plain, pql):
    outs = []
    for e in (hybrid, plain):
        try:
            (res,) = e.execute("z", pql)
            outs.append(("ok", _canon(res)))
        except ExecutionError as err:
            outs.append(("err", type(err).__name__, str(err)[:80]))
    assert outs[0] == outs[1], f"divergence on {pql}: {outs}"


def _churn(h, hybrid, plain, rng):
    """Interleaved writes through BOTH executors' shared holder — chosen
    to cross BOTH thresholds in both directions: bulk imports fatten a
    sparse row past the cardinality threshold, clears thin a dense row
    below it, mid-run single-bit clears SPLIT the runny row's intervals
    past the run threshold (run -> dense), and a contiguous re-import
    MERGES them back under it (dense -> run)."""
    idx = h.index("z")
    fname = FIELDS[int(rng.integers(len(FIELDS)))]
    f = idx.field(fname)
    rid = int(rng.integers(N_ROWS))
    if rid == RUNNY_ROW:
        # keep scattered writes off the runny row: its interval count
        # is owned by the split/merge arms below, and random scatter
        # would inflate it past RUN_THRESHOLD permanently
        rid = N_ROWS - 1
    action = rng.random()
    if action < 0.35:
        # fatten: push toward/past the threshold
        cols = rng.choice(SHARDS * SHARD_WIDTH,
                          size=int(rng.integers(64, 2 * THRESHOLD)),
                          replace=False)
        f.import_bits([rid] * len(cols), cols.tolist())
    elif action < 0.45:
        # empty the row outright: the decisive downward crossing (a
        # dense row's next upload must come back sparse — demotion)
        from pilosa_tpu.pql import Call
        hybrid._execute_clear_row(idx, Call("ClearRow", {fname: rid}),
                                  None)
    elif action < 0.6:
        # run SPLIT: scattered single-bit clears inside the runny row's
        # contiguous block — each interior clear splits an interval, a
        # couple of these actions push the count past RUN_THRESHOLD
        from pilosa_tpu.pql import Call
        base = RUNNY_BASE[fname]
        offs = rng.choice(RUNNY_LEN, size=int(rng.integers(16, 48)),
                          replace=False)
        for o in offs.tolist():
            hybrid._execute_clear(
                idx, Call("Clear", {"_col": int(base + o),
                                    fname: RUNNY_ROW}), None)
    elif action < 0.7:
        # run MERGE: contiguous re-import heals the splits back to one
        # interval (and restores cardinality a ClearRow may have zeroed)
        base = RUNNY_BASE[fname]
        cols = np.arange(base, base + RUNNY_LEN)
        f.import_bits([RUNNY_ROW] * len(cols), cols.tolist())
    elif action < 0.85:
        # thin: single-bit clears through the write path
        cols = rng.integers(0, SHARDS * SHARD_WIDTH,
                            size=int(rng.integers(8, 64)))
        for c in cols.tolist():
            hybrid._execute_clear(
                idx, __import__("pilosa_tpu.pql",
                                fromlist=["Call"]).Call(
                    "Clear", {"_col": int(c), fname: rid}), None)
    else:
        # single sets through the executor write path
        cols = rng.integers(0, SHARDS * SHARD_WIDTH,
                            size=int(rng.integers(8, 64)))
        for c in cols.tolist():
            hybrid._execute_set(
                idx, __import__("pilosa_tpu.pql",
                                fromlist=["Call"]).Call(
                    "Set", {"_col": int(c), fname: rid}), None)


def test_hybrid_parity_under_threshold_churn(setup):
    h, hybrid, plain, rng = setup
    for round_no in range(40):
        for _ in range(4):
            _both(hybrid, plain, _rand_query(rng))
        _churn(h, hybrid, plain, rng)
    snap = hybrid.hybrid.snapshot()
    # the churn really drove representation across all three kinds
    assert snap["sparseUploads"] > 0 and snap["denseUploads"] > 0
    assert snap["runUploads"] > 0, snap
    assert snap["promoted"] > 0, snap
    assert snap["demoted"] > 0, snap
    assert snap["runTransitions"] > 0, snap


def test_hybrid_kill_switch_parity(setup, monkeypatch):
    """PILOSA_TPU_HYBRID=0 flips the hybrid executor to pure dense at
    runtime — same results, no new sparse uploads."""
    h, hybrid, plain, rng = setup
    monkeypatch.setenv("PILOSA_TPU_HYBRID", "0")
    before = hybrid.hybrid.snapshot()["sparseUploads"]
    for _ in range(12):
        _both(hybrid, plain, _rand_query(rng))
    assert hybrid.hybrid.snapshot()["sparseUploads"] == before


def test_zero_threshold_restores_pure_dense(setup):
    """[query] sparse-threshold = 0 is the config-side off switch."""
    h, hybrid, plain, rng = setup
    old = hybrid.hybrid.threshold
    hybrid.hybrid.threshold = 0
    try:
        before = hybrid.hybrid.snapshot()["sparseUploads"]
        for _ in range(12):
            _both(hybrid, plain, _rand_query(rng))
        assert hybrid.hybrid.snapshot()["sparseUploads"] == before
    finally:
        hybrid.hybrid.threshold = old


def test_pallas_executor_threeway_parity(setup):
    """The Pallas kernel family (interpret mode off-TPU) under the same
    three-way hybrid config: a fresh Pallas-on executor against the
    plain dense XLA oracle, with the runny rows healed first so the run
    representation is actually in play. Rounds are short — interpret
    mode runs the kernel body in Python."""
    from pilosa_tpu.parallel.mesh import DeviceRunner

    h, hybrid, plain, rng = setup
    idx = h.index("z")
    for fname in FIELDS:  # heal: contiguous block -> few intervals
        base = RUNNY_BASE[fname]
        cols = np.arange(base, base + RUNNY_LEN)
        idx.field(fname).import_bits([RUNNY_ROW] * len(cols),
                                     cols.tolist())
    hp = Executor(h, runner=DeviceRunner(use_pallas=True))
    hp.hybrid.threshold = THRESHOLD
    hp.hybrid.run_threshold = RUN_THRESHOLD
    assert hp.hybrid.active()
    # force run-leaf traffic, then randomized trees + a TopN (the
    # fused popcount-rank Pallas path)
    _both(hp, plain,
          f"Count(Intersect(Row(f={RUNNY_ROW}), Row(g={RUNNY_ROW})))")
    _both(hp, plain, f"Union(Row(f={RUNNY_ROW}), Row(g=0))")
    for _ in range(6):
        _both(hp, plain, _rand_query(rng))
    _both(hp, plain, f"TopN(f, Row(f={RUNNY_ROW}), n=4)")
    _both(hp, plain, "TopN(g, Union(Row(g=0), Row(g=1)), n=4)")
    assert hp.hybrid.snapshot()["runUploads"] > 0
