"""Query deadline propagation: contextvar primitives, executor abort
between calls, the X-Pilosa-Deadline fan-out header on the internal
client, and the HTTP layer's ?timeout= / 504 mapping.

Reference: executor.go:2591-2608 (validateQueryContext between shard
batches) and net/http context deadlines; here the deadline rides a
contextvar locally and an explicit header across nodes (utils/qctx.py).
"""

import time

import numpy as np
import pytest

from pilosa_tpu.utils import qctx


def test_qctx_primitives():
    assert qctx.remaining() is None
    qctx.check()  # no deadline: never raises
    token = qctx.deadline.set(time.monotonic() + 0.5)
    try:
        rem = qctx.remaining()
        assert rem is not None and 0.3 < rem <= 0.5
        qctx.check()
    finally:
        qctx.deadline.reset(token)
    token = qctx.deadline.set(time.monotonic() - 0.01)
    try:
        with pytest.raises(qctx.QueryTimeoutError):
            qctx.check()
    finally:
        qctx.deadline.reset(token)


def test_executor_timeout_aborts_between_calls(tmp_path):
    """execute(timeout=) aborts the query stream once the deadline passes:
    the first call runs long (monkeypatched), the second must raise instead
    of executing."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("q")
    f = idx.create_field("f")
    f.import_bits(np.zeros(10, dtype=np.uint64),
                  np.arange(10, dtype=np.uint64))
    ex = Executor(h)
    (n,) = ex.execute("q", "Count(Row(f=0))")
    assert n == 10

    real = ex._execute_count
    calls = []

    def slow_count(index, call, shards):
        calls.append(1)
        if len(calls) == 1:
            time.sleep(0.08)  # overruns the 0.02 s budget
        return real(index, call, shards)

    ex._execute_count = slow_count
    with pytest.raises(qctx.QueryTimeoutError):
        ex.execute("q", "Count(Row(f=0)) Count(Row(f=0))", timeout=0.02)
    assert len(calls) == 1  # second call never executed
    # the deadline must not leak into subsequent queries
    ex._execute_count = real
    (n,) = ex.execute("q", "Count(Row(f=0))")
    assert n == 10
    h.close()


def test_client_fans_out_remaining_deadline():
    """With a deadline set, every outgoing RPC carries X-Pilosa-Deadline
    with the REMAINING seconds (the remote re-applies it locally), and an
    already-expired deadline fails fast without touching the network."""
    from tests.test_client import ScriptedServer
    from pilosa_tpu.net.client import InternalClient

    seen = []
    orig = ScriptedServer._read_request

    def read_and_record(self, conn):
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                return False
            data += chunk
        seen.append(data.split(b"\r\n\r\n", 1)[0].decode())
        # delegate body drain to the original reader semantics: the head
        # captured above is enough for the header assertion; the body may
        # already be in `data`
        return True

    ScriptedServer._read_request = read_and_record
    try:
        srv = ScriptedServer(["ok"])
        try:
            c = InternalClient(timeout=30)
            token = qctx.deadline.set(time.monotonic() + 5.0)
            try:
                c._json("POST", srv.uri, "/x", None)  # no body: head-only
            finally:
                qctx.deadline.reset(token)
            head = seen[-1]
            line = next(l for l in head.split("\r\n")
                        if l.lower().startswith("x-pilosa-deadline:"))
            rem = float(line.split(":", 1)[1])
            assert 4.0 < rem <= 5.0
            # expired deadline: fail fast, no request on the wire
            n_before = len(seen)
            token = qctx.deadline.set(time.monotonic() - 1.0)
            try:
                with pytest.raises(qctx.QueryTimeoutError):
                    c._json("POST", srv.uri, "/x", None)
            finally:
                qctx.deadline.reset(token)
            assert len(seen) == n_before
        finally:
            srv.close()
    finally:
        ScriptedServer._read_request = orig


def test_http_timeout_arg_maps_to_504(tmp_path):
    """?timeout= on /query parses as a duration; an overrun surfaces as
    504 with the deadline message."""
    from pilosa_tpu.net.http_server import Handler
    from pilosa_tpu.api import API
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.parallel.cluster import Cluster, Node

    h = Holder(str(tmp_path))
    h.open()
    cluster = Cluster("n1")
    cluster.set_static([Node(id="n1", uri="http://localhost:0")])
    api = API(h, cluster)
    handler = Handler(api)
    status, _, _ = handler.dispatch("POST", "/index/q", {}, b"{}")
    assert status == 200
    status, _, _ = handler.dispatch("POST", "/index/q/field/f", {}, b"{}")
    assert status == 200
    status, _, _ = handler.dispatch(
        "POST", "/index/q/query", {"timeout": ["5s"]}, b"Count(Row(f=0))")
    assert status == 200
    # invalid duration -> 400 (query args are parse_qs-style lists)
    status, _, payload = handler.dispatch(
        "POST", "/index/q/query", {"timeout": ["not-a-duration"]},
        b"Set(1, f=0)")
    assert status == 400, payload
    # expired adopted deadline (fan-out header) -> 504
    status, _, payload = handler.dispatch(
        "POST", "/index/q/query", {}, b"Count(Row(f=0))",
        headers={qctx.DEADLINE_HEADER: "-1"})
    assert status == 504, payload
    h.close()


def test_server_query_timeout_is_a_cap(tmp_path):
    """[cluster] query-timeout is an operator CAP: it bounds bare queries,
    cannot be lengthened by ?timeout= or a forged/malformed fan-out
    header, and ?timeout=0 means no client-side timeout (the cap still
    applies)."""
    from pilosa_tpu.net.http_server import Handler
    from pilosa_tpu.api import API
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.parallel.cluster import Cluster, Node

    h = Holder(str(tmp_path))
    h.open()
    cluster = Cluster("n1")
    cluster.set_static([Node(id="n1", uri="http://localhost:0")])
    api = API(h, cluster)
    # an (absurdly) tiny cap: expired by the time the executor checks
    handler = Handler(api, query_timeout=1e-9)
    handler.dispatch("POST", "/index/q", {}, b"{}")
    handler.dispatch("POST", "/index/q/field/f", {}, b"{}")
    status, _, payload = handler.dispatch(
        "POST", "/index/q/query", {}, b"Count(Row(f=0))")
    assert status == 504, payload
    # a larger ?timeout= cannot lift the cap
    status, _, _ = handler.dispatch(
        "POST", "/index/q/query", {"timeout": ["30s"]}, b"Count(Row(f=0))")
    assert status == 504
    # neither can a forged or malformed deadline header
    status, _, _ = handler.dispatch(
        "POST", "/index/q/query", {}, b"Count(Row(f=0))",
        headers={qctx.DEADLINE_HEADER: "999999"})
    assert status == 504
    status, _, _ = handler.dispatch(
        "POST", "/index/q/query", {}, b"Count(Row(f=0))",
        headers={qctx.DEADLINE_HEADER: "garbage"})
    assert status == 504
    # with no cap, ?timeout=0 = unbounded (documented convention), and a
    # malformed header alone leaves the query deadline-free
    unbounded = Handler(api)
    status, _, _ = unbounded.dispatch(
        "POST", "/index/q/query", {"timeout": ["0"]}, b"Count(Row(f=0))")
    assert status == 200
    status, _, _ = unbounded.dispatch(
        "POST", "/index/q/query", {}, b"Count(Row(f=0))",
        headers={qctx.DEADLINE_HEADER: "garbage"})
    assert status == 200
    h.close()
