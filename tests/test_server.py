"""Server + HTTP API tests: full in-process servers on random ports.

Mirrors the reference's test harness (test/pilosa.go:38-128 Command,
test/pilosa.go:297-352 MustRunCluster): black-box HTTP against real servers,
including a 3-node in-process cluster with distributed queries, replicated
writes and anti-entropy.
"""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server import Server


def http(method, uri, path, body=None):
    req = urllib.request.Request(uri + path, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else (json.dumps(payload).encode() if payload is not None else b"")
    status, out = http("POST", uri, path, body)
    return status, json.loads(out) if out else {}


@pytest.fixture
def server(tmp_path):
    s = Server(str(tmp_path / "node"), port=0).open()
    yield s
    s.close()


def test_home_version_status(server):
    status, out = http("GET", server.uri, "/")
    assert status == 200
    assert json.loads(out)["name"] == "pilosa-tpu"
    status, out = http("GET", server.uri, "/version")
    assert json.loads(out)["version"]
    status, out = http("GET", server.uri, "/status")
    d = json.loads(out)
    assert d["state"] == "NORMAL"
    assert len(d["nodes"]) == 1


def test_schema_ddl_and_query(server):
    u = server.uri
    status, _ = jpost(u, "/index/i", {"options": {}})
    assert status == 200
    status, _ = jpost(u, "/index/i/field/f", {"options": {"type": "set"}})
    assert status == 200
    # duplicate -> 409
    status, out = jpost(u, "/index/i", {"options": {}})
    assert status == 409
    # write + read through PQL over HTTP
    status, out = jpost(u, "/index/i/query", raw=b"Set(100, f=1)")
    assert status == 200 and out["results"] == [True]
    status, out = jpost(u, "/index/i/query", raw=f"Set({SHARD_WIDTH+5}, f=1)".encode())
    status, out = jpost(u, "/index/i/query", raw=b"Row(f=1)")
    assert out["results"][0]["columns"] == [100, SHARD_WIDTH + 5]
    status, out = jpost(u, "/index/i/query", raw=b"Count(Row(f=1))")
    assert out["results"] == [2]
    # schema reflects everything
    status, out = http("GET", u, "/schema")
    schema = json.loads(out)
    assert schema["indexes"][0]["name"] == "i"
    assert schema["indexes"][0]["fields"][0]["name"] == "f"
    # bad pql -> 400 with error
    status, out = jpost(u, "/index/i/query", raw=b"Row(")
    assert status == 400 and "error" in out
    # missing index -> 404
    status, out = jpost(u, "/index/nope/query", raw=b"Row(f=1)")
    assert status == 404


def test_import_and_export(server):
    u = server.uri
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/f", {})
    status, _ = jpost(u, "/index/i/field/f/import",
                      {"rowIDs": [1, 1, 2], "columnIDs": [3, 4, 5]})
    assert status == 200
    _, out = jpost(u, "/index/i/query", raw=b"Row(f=1)")
    assert out["results"][0]["columns"] == [3, 4]
    status, out = http("GET", u, "/export?index=i&field=f&shard=0")
    assert status == 200
    lines = sorted(out.decode().strip().splitlines())
    assert lines == ["1,3", "1,4", "2,5"]


def test_import_values_and_bsi_query(server):
    u = server.uri
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/v", {"options": {"type": "int", "min": 0, "max": 1000}})
    status, _ = jpost(u, "/index/i/field/v/import",
                      {"columnIDs": [1, 2, 3], "values": [10, 20, 30]})
    assert status == 200
    _, out = jpost(u, "/index/i/query", raw=b"Sum(field=v)")
    assert out["results"][0] == {"value": 60, "count": 3}
    _, out = jpost(u, "/index/i/query", raw=b"Range(v > 15)")
    assert out["results"][0]["columns"] == [2, 3]


def test_keyed_index(server):
    u = server.uri
    jpost(u, "/index/ki", {"options": {"keys": True}})
    jpost(u, "/index/ki/field/f", {"options": {"keys": True}})
    status, out = jpost(u, "/index/ki/query", raw=b"Set('col-a', f='row-x')")
    assert status == 200 and out["results"] == [True]
    jpost(u, "/index/ki/query", raw=b"Set('col-b', f='row-x')")
    _, out = jpost(u, "/index/ki/query", raw=b"Row(f='row-x')")
    assert sorted(out["results"][0]["keys"]) == ["col-a", "col-b"]
    # translate endpoint
    status, out = jpost(u, "/internal/translate/keys",
                        {"index": "ki", "field": None, "keys": ["col-a", "col-new"]})
    assert status == 200
    assert out["ids"][0] == 1 and out["ids"][1] >= 2


def test_keyed_result_translation(server):
    """TopN pairs, Rows identifiers, and GroupBy groups come back as keys
    on keyed fields (translateResult, executor.go:2497-2590)."""
    u = server.uri
    jpost(u, "/index/kt", {"options": {"keys": True}})
    jpost(u, "/index/kt/field/f", {"options": {"keys": True}})
    jpost(u, "/index/kt/field/g", {"options": {"keys": True}})
    for col in ("a", "b", "c"):
        jpost(u, "/index/kt/query", raw=f"Set('{col}', f='hot')".encode())
    jpost(u, "/index/kt/query", raw=b"Set('a', f='cold')")
    jpost(u, "/index/kt/query", raw=b"Set('a', g='left')")
    jpost(u, "/index/kt/query", raw=b"Set('b', g='left')")

    _, out = jpost(u, "/index/kt/query", raw=b"TopN(f, n=2)")
    pairs = out["results"][0]
    assert [p["key"] for p in pairs] == ["hot", "cold"]
    assert [p["count"] for p in pairs] == [3, 1]

    _, out = jpost(u, "/index/kt/query", raw=b"Rows(field=f)")
    assert sorted(out["results"][0]["keys"]) == ["cold", "hot"]
    assert out["results"][0]["rows"] is None

    _, out = jpost(u, "/index/kt/query", raw=b"GroupBy(Rows(field=f), Rows(field=g))")
    groups = out["results"][0]
    assert groups, "GroupBy returned no groups"
    for gc in groups:
        for fr in gc["group"]:
            assert "rowKey" in fr and "rowID" not in fr
    flat = {tuple(fr["rowKey"] for fr in gc["group"]): gc["count"]
            for gc in groups}
    assert flat[("hot", "left")] == 2
    assert flat[("cold", "left")] == 1


def test_fragment_internals_and_misc(server):
    u = server.uri
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/f", {})
    jpost(u, "/index/i/query", raw=b"Set(1, f=1)")
    status, out = http("GET", u, "/internal/fragment/blocks?index=i&field=f&view=standard&shard=0")
    assert status == 200 and json.loads(out)["blocks"]
    status, out = http("GET", u, "/internal/fragment/data?index=i&field=f&view=standard&shard=0")
    assert status == 200 and out[:2] == (12348).to_bytes(2, "little")
    status, out = http("GET", u, "/internal/shards/max")
    assert json.loads(out)["standard"]["i"] == 0
    status, out = http("GET", u, "/internal/nodes")
    assert len(json.loads(out)) == 1
    status, out = http("GET", u, "/info")
    assert json.loads(out)["shardWidth"] == SHARD_WIDTH
    status, _ = jpost(u, "/recalculate-caches")
    assert status == 200
    # unknown route / bad method
    status, _ = http("GET", u, "/nope")
    assert status == 404
    status, _ = http("DELETE", u, "/schema")
    assert status in (404, 405)


def test_persistence_across_restart(tmp_path):
    s = Server(str(tmp_path / "n"), port=0).open()
    jpost(s.uri, "/index/i", {})
    jpost(s.uri, "/index/i/field/f", {})
    jpost(s.uri, "/index/i/query", raw=b"Set(7, f=3)")
    node_id = s.node_id
    s.close()
    s2 = Server(str(tmp_path / "n"), port=0).open()
    assert s2.node_id == node_id  # .id file persisted
    _, out = jpost(s2.uri, "/index/i/query", raw=b"Row(f=3)")
    assert out["results"][0]["columns"] == [7]
    s2.close()


# ---------------------------------------------------------------------------
# multi-node cluster (MustRunCluster analog)
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster3(tmp_path):
    servers = []
    # boot 3 servers, then point them at each other and refresh membership
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    yield servers
    for s in servers:
        s.close()


def test_cluster_membership(cluster3):
    for s in cluster3:
        assert len(s.cluster.nodes) == 3
        assert s.cluster.state == "NORMAL"
    # same coordinator everywhere
    coords = {s.cluster.coordinator_id for s in cluster3}
    assert len(coords) == 1


def test_cluster_ddl_broadcast_and_distributed_query(cluster3):
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    # DDL must have propagated
    for s in cluster3:
        assert s.holder.index("i") is not None
        assert s.holder.index("i").field("f") is not None
    # writes route to shard owners (with replication)
    cols = [5, SHARD_WIDTH + 9, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 1]
    for c in cols:
        status, out = jpost(s0.uri, "/index/i/query", raw=f"Set({c}, f=1)".encode())
        assert status == 200, out
    # distributed read from any node sees all columns; a node hosting no
    # replica of a new shard learns of it via the async create-shard
    # announcement, so poll for convergence (eventual visibility, like the
    # reference's gossiped CreateShardMessage)
    for s in cluster3:
        assert wait_until(lambda s=s: jpost(
            s.uri, "/index/i/query", raw=b"Row(f=1)"
        )[1]["results"][0]["columns"] == cols), s.uri
        _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
        assert out["results"] == [4]
    # each shard is stored on exactly replica_n nodes
    for c in cols:
        shard = c // SHARD_WIDTH
        holders = sum(
            1 for s in cluster3
            if s.holder.index("i").field("f").view("standard")
            and s.holder.index("i").field("f").view("standard").fragment(shard)
            and s.holder.index("i").field("f").view("standard").fragment(shard).bit_count() > 0
        )
        assert holders == 2, f"shard {shard} on {holders} nodes"


def test_cluster_distributed_topn_and_sum(cluster3):
    s0 = cluster3[0]
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/field/v", {"options": {"type": "int", "min": 0, "max": 100}})
    for c in range(6):
        jpost(s0.uri, "/index/i/query", raw=f"Set({c * SHARD_WIDTH}, f=1)".encode())
    for c in range(3):
        jpost(s0.uri, "/index/i/query", raw=f"Set({c * SHARD_WIDTH + 1}, f=2)".encode())
        jpost(s0.uri, "/index/i/query", raw=f"Set({c * SHARD_WIDTH + 1}, v=10)".encode())
    # nodes 1/2 are not replicas of every shard: they learn of the new
    # shards via the async create-shard announcement, so poll for
    # convergence (the cross-node visibility contract is eventual, like
    # the reference's gossiped CreateShardMessage)
    assert wait_until(lambda: jpost(
        cluster3[1].uri, "/index/i/query", raw=b"TopN(f, n=2)"
    )[1]["results"][0] == [{"id": 1, "count": 6}, {"id": 2, "count": 3}])
    assert wait_until(lambda: jpost(
        cluster3[2].uri, "/index/i/query", raw=b"Sum(field=v)"
    )[1]["results"][0] == {"value": 30, "count": 3})


def test_liveness_detects_crashed_node(cluster3):
    """A crashed (not gracefully removed) node is detected by liveness
    probing: after `liveness_threshold` failed probes the cluster enters
    DEGRADED, placement routes around the dead node (no per-query
    ClientError churn), and queries stay correct (gossip probe ->
    NodeLeave -> ReceiveEvent, gossip/gossip.go:488-519,
    cluster.go:1690-1703; determineClusterState :522-533)."""
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    cols = [5, SHARD_WIDTH + 9, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 1]
    for c in cols:
        jpost(s0.uri, "/index/i/query", raw=f"Set({c}, f=1)".encode())

    # crash s2's HTTP plane (SIGKILL analog: sockets die, no leave message)
    s2.http.close()
    for s in (s0, s1):
        s.probe_timeout = 0.5
        for _ in range(s.liveness_threshold):
            s._probe_peers()
        assert s.cluster.is_down(s2.node_id)
        # 1 lost < replica_n=2 -> every shard still has a live replica
        assert s.cluster.state == "DEGRADED"

    # placement no longer routes primaries to the dead node
    shards = [c // SHARD_WIDTH for c in cols]
    groups = s0.cluster.shards_by_node("i", shards)
    assert s2.node_id not in groups

    # queries from the survivors are correct, with zero failover retries
    calls = {"n": 0}
    orig = s0.executor.client.query_proto

    def counting(uri, *a, **kw):
        calls["n"] += 1
        assert uri != s2.uri, "query routed to a known-dead node"
        return orig(uri, *a, **kw)

    s0.executor.client.query_proto = counting
    _, out = jpost(s0.uri, "/index/i/query", raw=b"Count(Row(f=1))")
    assert out["results"] == [4]
    s0.executor.client.query_proto = orig

    # writes succeed while a replica is down (it heals via anti-entropy)
    status, out = jpost(s0.uri, "/index/i/query",
                        raw=f"Set({4 * SHARD_WIDTH + 7}, f=1)".encode())
    assert status == 200 and out["results"] == [True]

    # a successful probe marks the node back up -> NORMAL
    s0.cluster.mark_up(s2.node_id)
    assert s0.cluster.state == "NORMAL"
    assert s0.cluster.node_by_id(s2.node_id).state == "READY"


def test_suspect_refuted_by_indirect_probe(cluster3):
    """A peer WE can't reach but other nodes can is NOT marked down: the
    suspicion is refuted by an indirect probe through a live peer
    (memberlist indirect ping — a broken link must not evict a healthy
    node)."""
    s0, s1, s2 = cluster3
    orig = s0.client.status

    def broken_link(uri, timeout=None):
        if uri == s2.uri:
            raise OSError("simulated one-way link failure")
        return orig(uri, timeout=timeout)

    s0.client.status = broken_link
    try:
        s0.probe_timeout = 1.0
        for _ in range(s0.liveness_threshold + 2):
            s0._probe_peers()
        # s1 vouched for s2 over /internal/probe: still up, counter reset
        assert not s0.cluster.is_down(s2.node_id)
        assert s0._probe_failures.get(s2.node_id, 0) < s0.liveness_threshold
        assert s0.cluster.state == "NORMAL"
    finally:
        s0.client.status = orig


def test_down_node_revives_only_after_consecutive_successes(cluster3):
    """Anti-flap hysteresis: a down node needs revive_threshold
    CONSECUTIVE successful probes to re-enter placement; one lucky probe
    between failures does not flap it up."""
    s0, s1, s2 = cluster3
    orig = s0.client.status
    fail = {"on": True}

    def flaky(uri, timeout=None):
        if uri == s2.uri and fail["on"]:
            raise OSError("down")
        return orig(uri, timeout=timeout)

    s0.client.status = flaky
    # also break s1's view of s2 so the indirect probe can't refute
    orig1 = s1.client.status

    def down_for_s1(uri, timeout=None):
        if uri == s2.uri:
            raise OSError("down")
        return orig1(uri, timeout=timeout)

    s1.client.status = down_for_s1
    # s0's indirect helper is s1, whose probe_peer_fn uses s1.client.status
    try:
        s0.probe_timeout = 1.0
        for _ in range(s0.liveness_threshold):
            s0._probe_peers()
        assert s0.cluster.is_down(s2.node_id)
        # one good probe: NOT yet revived (hysteresis)
        fail["on"] = False
        s0._probe_peers()
        assert s0.cluster.is_down(s2.node_id)
        # a failure in between resets the success streak
        fail["on"] = True
        s0._probe_peers()
        fail["on"] = False
        s0._probe_peers()
        assert s0.cluster.is_down(s2.node_id)
        # second consecutive success: revived
        s0._probe_peers()
        assert not s0.cluster.is_down(s2.node_id)
    finally:
        s0.client.status = orig
        s1.client.status = orig1


@pytest.fixture
def cluster3_r3(tmp_path):
    """3 nodes, ReplicaN=3: every node owns every shard — the consensus
    configuration (fragment.go:1366 majorityN kicks in at 3 replicas)."""
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"r3n{i}"), port=0, replica_n=3).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    yield servers
    for s in servers:
        s.close()


def _frag(server, index="i", field="f", view="standard", shard=0):
    return server.holder.index(index).field(field).view(view).fragment(shard)


def test_majority_sync_clear_stays_cleared(cluster3_r3):
    """A bit cleared on 2 of 3 replicas must STAY cleared after anti-entropy
    — the stale replica adopts the clear instead of resurrecting the bit
    cluster-wide (mergeBlock majority + clear deltas,
    fragment.go:1366, 1407-1417)."""
    s0, s1, s2 = cluster3_r3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(5, f=1)")
    jpost(s0.uri, "/index/i/query", raw=b"Set(6, f=1)")  # keeps block nonempty
    for s in cluster3_r3:
        assert _frag(s).contains(1, 5), "replication should reach all 3"
    # two replicas clear the bit directly (simulating a clear the third
    # replica missed while down)
    _frag(s0).clear_bit(1, 5)
    _frag(s1).clear_bit(1, 5)
    # sync FROM the stale node — the worst case: union semantics would push
    # its stale bit back onto the two cleared replicas
    assert s2.sync_holder() > 0
    for s in cluster3_r3:
        assert not _frag(s).contains(1, 5), f"bit resurrected on {s.uri}"
        assert _frag(s).contains(1, 6), f"innocent bit lost on {s.uri}"
    # steady state: another pass from any node moves nothing
    assert _frag(s0).contains(1, 6)


def test_majority_sync_removes_minority_stray(cluster3_r3):
    """A bit present on only 1 of 3 replicas (below majority) is removed
    from that replica by its own sync pass, not propagated."""
    s0, s1, s2 = cluster3_r3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(10, f=2)")
    _frag(s0).set_bit(2, 77)  # local-only stray, bypassing replication
    assert s0.sync_holder() > 0
    for s in cluster3_r3:
        assert not _frag(s).contains(2, 77), f"stray bit spread to {s.uri}"
        assert _frag(s).contains(2, 10)


def test_majority_sync_union_with_two_replicas(cluster3_r3):
    """With one reachable peer (one replica down), the majority threshold is
    1 — union semantics, no clears on partial evidence."""
    s0, s1, s2 = cluster3_r3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(3, f=4)")
    _frag(s0).clear_bit(4, 3)  # s0 cleared; s1 holds the bit; s2 marked down
    s0.cluster.mark_down(s2.node_id)
    assert s0.sync_holder() > 0
    # only 2 voters -> union: the bit comes BACK to s0 rather than being
    # cleared on s1 off partial evidence
    assert _frag(s0).contains(4, 3)
    assert _frag(s1).contains(4, 3)


def test_anti_entropy_heals_divergence(cluster3):
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(1, f=1)")
    # find the two owners of shard 0 and diverge one replica manually
    owners = [s for s in cluster3
              if s.cluster.owns_shard(s.node_id, "i", 0)]
    assert len(owners) == 2
    frag = owners[0].holder.index("i").field("f").view("standard").fragment(0)
    frag.set_bit(1, 99)  # local-only write, bypassing replication
    # peer doesn't have it yet
    peer_frag = owners[1].holder.index("i").field("f").view("standard").fragment(0)
    assert not peer_frag.contains(1, 99)
    merged = owners[0].sync_holder()
    assert merged > 0
    assert peer_frag.contains(1, 99)


def test_cluster_empty_partials_and_options(cluster3):
    s0 = cluster3[0]
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(1, f=1)")
    # TopN/GroupBy where remote nodes have empty partials must not crash
    _, out = jpost(cluster3[1].uri, "/index/i/query", raw=b"TopN(f, n=5)")
    assert out["results"][0] == [{"id": 1, "count": 1}]
    _, out = jpost(cluster3[1].uri, "/index/i/query", raw=b"GroupBy(Rows(field=f))")
    assert out["results"][0] == [
        {"group": [{"field": "f", "rowID": 1}], "count": 1}]
    _, out = jpost(cluster3[1].uri, "/index/i/query", raw=b"Rows(field=f)")
    assert out["results"][0] == {"rows": [1]}
    # Options() must reduce over ALL nodes' shards, not just the first
    jpost(s0.uri, "/index/i/query", raw=f"Set({SHARD_WIDTH * 3 + 7}, f=1)".encode())
    for s in cluster3:
        _, out = jpost(s.uri, "/index/i/query", raw=b"Options(Count(Row(f=1)))")
        assert out["results"] == [2], s.uri


def test_cluster_groupby_limit_correctness(cluster3):
    s0 = cluster3[0]
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    # row 1 sparse on an early shard; row 2 heavy across shards: a per-node
    # limit would truncate differently per node
    jpost(s0.uri, "/index/i/query", raw=b"Set(1, f=1)")
    for k in range(4):
        jpost(s0.uri, "/index/i/query", raw=f"Set({k * SHARD_WIDTH + 2}, f=2)".encode())
    # node 2 learns of the new shards via the async create-shard
    # announcement — poll for convergence (eventual visibility, like the
    # reference's gossiped CreateShardMessage)
    assert wait_until(lambda: jpost(
        cluster3[2].uri, "/index/i/query",
        raw=b"GroupBy(Rows(field=f), limit=2)",
    )[1]["results"][0] == [
        {"group": [{"field": "f", "rowID": 1}], "count": 1},
        {"group": [{"field": "f", "rowID": 2}], "count": 4},
    ])


def test_cluster_keyed_index_consistent_ids(cluster3):
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/ki", {"options": {"keys": True}})
    jpost(s0.uri, "/index/ki/field/f", {"options": {"keys": True}})
    # writes through different nodes must agree on the key -> id mapping
    jpost(s1.uri, "/index/ki/query", raw=b"Set('a', f='x')")
    jpost(s2.uri, "/index/ki/query", raw=b"Set('b', f='x')")
    jpost(s0.uri, "/index/ki/query", raw=b"Set('c', f='y')")
    for s in cluster3:
        _, out = jpost(s.uri, "/index/ki/query", raw=b"Row(f='x')")
        assert sorted(out["results"][0]["keys"]) == ["a", "b"], s.uri
    # id mappings agree across nodes (single-writer primary): a node may hold
    # only a subset of keys, but never a conflicting id for the same key
    combined: dict[str, int] = {}
    for srv in cluster3:
        for k, v in srv.translate.column_items("ki"):
            assert combined.setdefault(k, v) == v, (k, v, combined)


def test_cluster_failover_per_shard_remap(cluster3):
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    cols = [k * SHARD_WIDTH for k in range(6)]
    for c in cols:
        jpost(s0.uri, "/index/i/query", raw=f"Set({c}, f=1)".encode())
    # kill one server's HTTP abruptly; others must failover per shard
    victim = s2
    victim.http.close()
    survivors = [s for s in cluster3 if s is not victim]
    for s in survivors:
        _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
        assert out["results"] == [6], s.uri


def test_read_does_not_mint_keys(server):
    u = server.uri
    jpost(u, "/index/ki", {"options": {"keys": True}})
    jpost(u, "/index/ki/field/f", {"options": {"keys": True}})
    jpost(u, "/index/ki/query", raw=b"Set('a', f='x')")
    size_before = server.translate.log_size()
    # reads with unknown keys return empty, and must not grow the key log
    _, out = jpost(u, "/index/ki/query", raw=b"Row(f='typo-key')")
    assert out["results"][0]["keys"] == []
    _, out = jpost(u, "/index/ki/query", raw=b"Count(Row(f='typo-key'))")
    assert out["results"] == [0]
    jpost(u, "/index/ki/query", raw=b"Clear('nope', f='x')")
    assert server.translate.log_size() == size_before


# ---------------------------------------------------------------------------
# resize: dynamic node join / remove with fragment migration
# ---------------------------------------------------------------------------


def wait_until(fn, timeout=15.0, interval=0.05):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def _fragment_count(server):
    n = 0
    for idx in server.holder.indexes.values():
        for f in idx.fields.values():
            for v in f.views.values():
                n += len(v.shards())
    return n


def test_resize_join_migrates_fragments(tmp_path):
    # 1-node cluster with data spanning 8 shards
    a = Server(str(tmp_path / "a"), port=0, membership_interval=0.2).open()
    jpost(a.uri, "/index/i", {})
    jpost(a.uri, "/index/i/field/f", {})
    cols = [k * SHARD_WIDTH + 5 for k in range(8)]
    jpost(a.uri, "/index/i/field/f/import",
          {"rowIDs": [1] * 8, "columnIDs": cols})
    _, out = jpost(a.uri, "/index/i/query", raw=b"Count(Row(f=1))")
    assert out["results"] == [8]

    # dynamic join (gossip-seed analog): B knocks, coordinator A resizes
    b = Server(str(tmp_path / "b"), port=0, cluster_hosts=[a.uri],
               membership_interval=0.2, join=True).open()
    try:
        assert wait_until(lambda: b.cluster.state == "NORMAL"
                          and len(b.cluster.nodes) == 2
                          and len(a.cluster.nodes) == 2)
        # schema arrived on B
        assert b.holder.index("i") is not None
        assert b.holder.index("i").field("f") is not None
        # B owns some shards and received their fragments
        owned_b = [s for s in range(8) if b.cluster.owns_shard(b.node_id, "i", s)]
        assert owned_b, "placement should give the new node shards"
        # migration: B holds data for its owned shards
        assert wait_until(lambda: _fragment_count(b) > 0)
        # cleaner: A dropped what it no longer owns (replica_n=1)
        assert wait_until(lambda: all(
            a.holder.index("i").field("f").views["standard"].fragment(s) is None
            for s in owned_b))
        # the data is still fully queryable from BOTH nodes
        for s in (a, b):
            _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
            assert out["results"] == [8], s.uri
            _, out = jpost(s.uri, "/index/i/query", raw=b"Row(f=1)")
            assert sorted(out["results"][0]["columns"]) == sorted(cols)
    finally:
        b.close()
        a.close()


def test_resize_remove_node(tmp_path):
    # 3 nodes, replica_n=2 so a removed node's shards have surviving donors
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2,
                   membership_interval=0.2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    try:
        s0 = servers[0]
        jpost(s0.uri, "/index/i", {})
        jpost(s0.uri, "/index/i/field/f", {})
        cols = [k * SHARD_WIDTH + 9 for k in range(6)]
        jpost(s0.uri, "/index/i/field/f/import",
              {"rowIDs": [2] * 6, "columnIDs": cols})

        # remove the last node (by id) via the public endpoint on any node
        victim = max(servers, key=lambda s: s.node_id)
        survivors = [s for s in servers if s is not victim]
        jpost(s0.uri, "/cluster/resize/remove-node", {"id": victim.node_id})
        assert wait_until(lambda: all(
            s.cluster.state == "NORMAL" and len(s.cluster.nodes) == 2
            for s in survivors))
        # data remains fully queryable on the survivors
        for s in survivors:
            _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=2))")
            assert out["results"] == [6], s.uri
    finally:
        for s in servers:
            s.close()


def test_resize_join_queues_while_resizing(tmp_path):
    # two nodes joining in quick succession both end up admitted
    a = Server(str(tmp_path / "a"), port=0, membership_interval=0.2).open()
    jpost(a.uri, "/index/i", {})
    jpost(a.uri, "/index/i/field/f", {})
    jpost(a.uri, "/index/i/field/f/import",
          {"rowIDs": [1] * 4, "columnIDs": [k * SHARD_WIDTH for k in range(4)]})
    b = Server(str(tmp_path / "b"), port=0, cluster_hosts=[a.uri],
               membership_interval=0.2, join=True).open()
    c = Server(str(tmp_path / "c"), port=0, cluster_hosts=[a.uri],
               membership_interval=0.2, join=True).open()
    try:
        assert wait_until(lambda: all(
            s.cluster.state == "NORMAL" and len(s.cluster.nodes) == 3
            for s in (a, b, c)), timeout=30)
        for s in (a, b, c):
            _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
            assert out["results"] == [4], s.uri
    finally:
        c.close()
        b.close()
        a.close()


def test_cluster_state_broadcast_blocks_writes(server):
    # a "cluster-state" RESIZING message must gate writes on every node
    # (methodsNormal excludes Import during RESIZING, api.go:1247-1278)
    server.receive_message({"type": "cluster-state", "state": "RESIZING"})
    st, out = jpost(server.uri, "/index/i2", {})
    assert st == 503
    st, _ = jpost(server.uri, "/cluster/resize/abort")
    assert st == 200
    st, out = jpost(server.uri, "/index/i2", {})
    assert st == 200


def test_remove_node_refuses_without_replicas(tmp_path):
    # replica_n=1: removing a node would drop its shards' only copy — the
    # request must be refused (fragSources error, cluster.go:806-811)
    servers = []
    for i in range(2):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=1,
                   membership_interval=0.2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    try:
        s0 = servers[0]
        jpost(s0.uri, "/index/i", {})
        jpost(s0.uri, "/index/i/field/f", {})
        jpost(s0.uri, "/index/i/field/f/import",
              {"rowIDs": [1] * 4, "columnIDs": [k * SHARD_WIDTH for k in range(4)]})
        victim = max(servers, key=lambda s: s.node_id)
        coordinator = min(servers, key=lambda s: s.node_id)
        st, out = jpost(coordinator.uri, "/cluster/resize/remove-node",
                        {"id": victim.node_id})
        assert st == 400
        assert "replica factor" in out["error"]
        # membership unchanged, data intact
        assert len(coordinator.cluster.nodes) == 2
        _, out = jpost(coordinator.uri, "/index/i/query", raw=b"Count(Row(f=1))")
        assert out["results"] == [4]
    finally:
        for s in servers:
            s.close()


def test_remove_then_rejoin(tmp_path):
    # a removed node must be able to rejoin: peers' tombstones are replaced
    # by the coordinator's authoritative removed-set on each topology
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2,
                   membership_interval=0.2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    rejoined = None
    try:
        jpost(servers[0].uri, "/index/i", {})
        jpost(servers[0].uri, "/index/i/field/f", {})
        jpost(servers[0].uri, "/index/i/query", raw=b"Set(5, f=1)")
        victim = max(servers, key=lambda s: s.node_id)
        survivors = [s for s in servers if s is not victim]
        jpost(servers[0].uri, "/cluster/resize/remove-node",
              {"id": victim.node_id})
        assert wait_until(lambda: all(
            s.cluster.state == "NORMAL" and len(s.cluster.nodes) == 2
            for s in survivors))
        victim.close()
        # rejoin with the same identity (same data dir -> same .id file)
        rejoined = Server(str(tmp_path / f"n{servers.index(victim)}"), port=0,
                          replica_n=2, cluster_hosts=[survivors[0].uri],
                          membership_interval=0.2, join=True).open()
        assert rejoined.node_id == victim.node_id
        assert wait_until(lambda: all(
            s.cluster.state == "NORMAL" and len(s.cluster.nodes) == 3
            for s in survivors + [rejoined]), timeout=30)
        for s in survivors + [rejoined]:
            _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
            assert out["results"] == [1], s.uri
    finally:
        if rejoined is not None:
            rejoined.close()
        for s in servers:
            if s.http._thread is not None:
                s.close()


def test_attr_anti_entropy_sync(cluster3):
    """Attr blocks diff + pull heals diverged row/column attr stores
    (holderSyncer.syncIndex/syncField, holder.go:726,772)."""
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    # write attrs only on s0's stores, bypassing broadcast
    s0.holder.index("i").column_attrs.set_attrs(7, {"city": "x", "n": 3})
    s0.holder.index("i").field("f").row_attrs.set_attrs(2, {"label": "two"})
    assert s1.holder.index("i").column_attrs.attrs(7) == {}
    # s1 pulls the diff on its own anti-entropy pass
    merged = s1.sync_holder()
    assert merged >= 2
    assert s1.holder.index("i").column_attrs.attrs(7) == {"city": "x", "n": 3}
    assert s1.holder.index("i").field("f").row_attrs.attrs(2) == {"label": "two"}
    # converged stores stop reporting diffs
    assert s1.sync_holder() == 0


def test_attr_diff_endpoint(server):
    u = server.uri
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/f", {})
    server.holder.index("i").column_attrs.set_attrs(1, {"a": 1})
    status, out = jpost(u, "/internal/index/i/attr/diff", {"blocks": []})
    assert status == 200
    assert out["attrs"] == {"1": {"a": 1}}
    # matching checksum -> empty diff
    blocks = [{"id": b, "checksum": c.hex()}
              for b, c in server.holder.index("i").column_attrs.blocks()]
    _, out = jpost(u, "/internal/index/i/attr/diff", {"blocks": blocks})
    assert out["attrs"] == {}


def test_tls_server_roundtrip(tmp_path):
    """HTTPS serving (getListener, server/server.go:375-393) + skip-verify
    internal client (server/config.go:31)."""
    import ssl
    import subprocess

    crt, key = str(tmp_path / "crt.pem"), str(tmp_path / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-subj", "/CN=localhost", "-keyout", key, "-out", crt, "-days", "1"],
        check=True, capture_output=True)
    s = Server(str(tmp_path / "node"), port=0,
               tls_certificate=crt, tls_key=key, tls_skip_verify=True).open()
    try:
        assert s.uri.startswith("https://")
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        req = urllib.request.Request(s.uri + "/version")
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            assert json.loads(resp.read())["version"]
        # the internal client with skip_verify reaches it too
        assert s.client.status(s.uri)["state"]
    finally:
        s.close()


def test_cache_flush(tmp_path):
    """holder.flush_caches persists rank caches in place
    (holder.monitorCacheFlush, holder.go:483-526)."""
    import os
    s = Server(str(tmp_path / "node"), port=0).open()
    try:
        jpost(s.uri, "/index/i", {})
        jpost(s.uri, "/index/i/field/f", {})
        jpost(s.uri, "/index/i/query", raw=b"Set(5, f=1)")
        assert s.holder.flush_caches() >= 1
        frag = s.holder.index("i").field("f").view().fragment(0)
        assert os.path.exists(frag.path + ".cache")
    finally:
        s.close()


def test_trace_header_propagates_into_spans(server):
    """X-Pilosa-Trace-Id on an incoming query is adopted by executor spans
    (extractTracing, http/handler.go:226-234)."""
    from pilosa_tpu.utils.tracing import TRACE_HEADER
    jpost(server.uri, "/index/i", {})
    jpost(server.uri, "/index/i/field/f", {})
    req = urllib.request.Request(server.uri + "/index/i/query",
                                 data=b"Count(Row(f=1))", method="POST",
                                 headers={TRACE_HEADER: "cafef00d"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
    spans = server.tracer.finished()
    assert any(sp.trace_id == "cafef00d" for sp in spans)


def test_trace_propagates_across_concurrent_fanout(cluster3):
    """The trace id must reach REMOTE nodes through the concurrent per-node
    fan-out: pool threads don't inherit contextvars, so the executor copies
    the context per submit (InjectHTTPHeaders analog, tracing.go:22-26)."""
    from pilosa_tpu.utils.tracing import TRACE_HEADER

    s0 = cluster3[0]
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    # bits across enough shards that >1 node group participates
    for c in [5, SHARD_WIDTH + 9, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 1]:
        jpost(s0.uri, "/index/i/query", raw=f"Set({c}, f=1)".encode())
    req = urllib.request.Request(s0.uri + "/index/i/query",
                                 data=b"Count(Row(f=1))", method="POST",
                                 headers={TRACE_HEADER: "feedc0de"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
    remote_hits = [
        s for s in cluster3[1:]
        if any(sp.trace_id == "feedc0de" for sp in s.tracer.finished())
    ]
    assert remote_hits, "trace id never reached any remote node"


def test_debug_pprof(server):
    """/debug/pprof analog (http/handler.go:242): index, thread stacks, and
    a short sampling profile."""
    status, out = http("GET", server.uri, "/debug/pprof")
    assert status == 200 and "goroutine" in json.loads(out)["profiles"]
    status, out = http("GET", server.uri, "/debug/pprof/goroutine")
    body = json.loads(out)
    assert status == 200 and body["threads"] >= 1
    status, out = http("GET", server.uri, "/debug/pprof/profile?seconds=0.05")
    assert status == 200 and "samples" in json.loads(out)
    status, _ = http("GET", server.uri, "/debug/pprof/heapz")
    assert status == 404


def test_cluster_node_pause_and_convergence(cluster3):
    """The clustertests fault-injection scenario (internal/clustertests
    TestClusterStuff: pumba-paused node misses writes mid-stream, anti-entropy
    converges it after it returns). Pause = handler returns 503."""
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(1, f=1)")

    # find a non-coordinator owner of shard 0 to pause
    owners = [s for s in cluster3
              if s.cluster.owns_shard(s.node_id, "i", 0)]
    victim = next(s for s in owners if not s.cluster.is_coordinator())
    healthy = next(s for s in owners if s is not victim)

    real_dispatch = victim.handler.dispatch
    victim.handler.dispatch = lambda *a, **k: (
        503, "application/json", b'{"error": "paused"}')
    try:
        # a write needing the paused replica fails cleanly, not silently
        status, out = jpost(healthy.uri, "/index/i/query", raw=b"Set(2, f=1)")
        assert status >= 400
        assert "error" in out
        # write the bit into the healthy owner only (the divergence the
        # paused node accumulates while down)
        healthy.holder.index("i").field("f").set_bit(1, 2)
    finally:
        victim.handler.dispatch = real_dispatch

    # victim is back: anti-entropy pass on the healthy node pushes the delta
    assert healthy.sync_holder() >= 1
    vfrag = victim.holder.index("i").field("f").view().fragment(0)
    assert vfrag.contains(1, 2)
    # and queries agree everywhere
    for s in cluster3:
        _, out = jpost(s.uri, "/index/i/query", raw=b"Count(Row(f=1))")
        assert out["results"] == [2], s.uri


def test_max_writes_per_request(tmp_path):
    """Oversized write batches are rejected up front (MaxWritesPerRequest,
    server/config.go:47)."""
    s = Server(str(tmp_path / "node"), port=0, max_writes_per_request=2).open()
    try:
        jpost(s.uri, "/index/i", {})
        jpost(s.uri, "/index/i/field/f", {})
        status, out = jpost(s.uri, "/index/i/query",
                            raw=b"Set(1, f=1) Set(2, f=1) Set(3, f=1)")
        assert status == 400 and "too many writes" in out["error"]
        # reads aren't counted
        status, _ = jpost(s.uri, "/index/i/query",
                          raw=b"Count(Row(f=1)) Count(Row(f=2)) Count(Row(f=3))")
        assert status == 200
        status, _ = jpost(s.uri, "/index/i/query", raw=b"Set(1, f=1) Set(2, f=1)")
        assert status == 200
    finally:
        s.close()


def test_max_writes_counts_options_wrapped(tmp_path):
    s = Server(str(tmp_path / "node"), port=0, max_writes_per_request=2).open()
    try:
        jpost(s.uri, "/index/i", {})
        jpost(s.uri, "/index/i/field/f", {})
        status, out = jpost(
            s.uri, "/index/i/query",
            raw=b"Options(Set(1, f=1)) Options(Set(2, f=1)) Options(Set(3, f=1))")
        assert status == 400 and "too many writes" in out["error"]
    finally:
        s.close()


def test_unknown_query_args_rejected(server):
    """Per-endpoint query-arg validation (queryValidationSpec,
    http/handler.go:171-224)."""
    jpost(server.uri, "/index/i", {})
    jpost(server.uri, "/index/i/field/f", {})
    status, out = jpost(server.uri, "/index/i/query?shard=0", raw=b"Count(Row(f=1))")
    assert status == 400 and "invalid query argument" in out["error"]
    status, _ = jpost(server.uri, "/index/i/query?shards=0", raw=b"Count(Row(f=1))")
    assert status == 200
    status, out = http("GET", server.uri, "/internal/translate/data?offst=3")
    assert status == 400


def test_column_attrs_in_query_response(server):
    """QueryRequest.ColumnAttrs attaches attrs of result columns
    (internal/public.proto:70 ColumnAttrSets)."""
    jpost(server.uri, "/index/i", {})
    jpost(server.uri, "/index/i/field/f", {})
    jpost(server.uri, "/index/i/query", raw=b"Set(5, f=1) Set(6, f=1)")
    jpost(server.uri, "/index/i/query", raw=b'SetColumnAttrs(5, city="ankh")')
    _, out = jpost(server.uri, "/index/i/query?columnAttrs=true", raw=b"Row(f=1)")
    assert out["columnAttrSets"] == [{"id": 5, "attrs": {"city": "ankh"}}]
    # excludeRowAttrs strips attrs, excludeColumns strips columns
    jpost(server.uri, "/index/i/query", raw=b'SetRowAttrs(f, 1, name="row1")')
    _, out = jpost(server.uri, "/index/i/query",
                   raw=b"Options(Row(f=1), excludeRowAttrs=true)")
    assert out["results"][0]["attrs"] == {}
    _, out = jpost(server.uri, "/index/i/query",
                   raw=b"Options(Row(f=1), excludeColumns=true)")
    assert out["results"][0]["columns"] == []


def test_request_level_exclude_flags_and_open_endpoints(server):
    jpost(server.uri, "/index/i", {})
    jpost(server.uri, "/index/i/field/f", {})
    jpost(server.uri, "/index/i/query", raw=b"Set(5, f=1)")
    jpost(server.uri, "/index/i/query", raw=b'SetRowAttrs(f, 1, name="n")')
    _, out = jpost(server.uri, "/index/i/query?excludeRowAttrs=true", raw=b"Row(f=1)")
    assert out["results"][0] == {"columns": [5], "attrs": {}}
    _, out = jpost(server.uri, "/index/i/query?excludeColumns=true", raw=b"Row(f=1)")
    assert out["results"][0] == {"columns": [], "attrs": {"name": "n"}}
    # unlisted endpoints stay open to stray args (cache busters etc.)
    status, _ = http("GET", server.uri, "/version?cb=123")
    assert status == 200


def test_cluster_options_exclude_flags(cluster3):
    s0 = cluster3[0]
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    jpost(s0.uri, "/index/i/query", raw=b"Set(5, f=1)")
    jpost(s0.uri, "/index/i/query", raw=b'SetRowAttrs(f, 1, name="n")')
    _, out = jpost(cluster3[1].uri, "/index/i/query",
                   raw=b"Options(Row(f=1), excludeColumns=true)")
    assert out["results"][0] == {"columns": [], "attrs": {"name": "n"}}
    _, out = jpost(cluster3[2].uri, "/index/i/query",
                   raw=b"Options(Row(f=1), excludeRowAttrs=true)")
    assert out["results"][0] == {"columns": [5], "attrs": {}}


def test_debug_vars_surfaces_engine_stats(server):
    """/debug/vars carries residency, TopN, and batcher observability
    (stats/stats.go Expvar analog, http/handler.go:243). Batcher keys
    appear only when batching is on (the server fixture inherits the
    ambient PILOSA_TPU_BATCH)."""
    jpost(server.uri, "/index/dv", {})
    jpost(server.uri, "/index/dv/field/f", {})
    jpost(server.uri, "/index/dv/field/v",
          {"options": {"type": "int", "min": 0, "max": 100}})
    jpost(server.uri, "/index/dv/query", raw=b"Set(1, f=0)")
    jpost(server.uri, "/index/dv/query", raw=b"Set(2, f=1)")
    jpost(server.uri, "/index/dv/query", raw=b"Set(1, v=7)")
    # one-bit rows ride the hybrid sparse path, which bypasses the count
    # batcher by design — force dense for this query so the batcher
    # surface under test sees traffic, then restore
    old_thr = server.executor.hybrid.threshold
    server.executor.hybrid.threshold = 0
    try:
        _, out = jpost(server.uri, "/index/dv/query",
                       raw=b"Count(Intersect(Row(f=0), Row(f=1)))")
    finally:
        server.executor.hybrid.threshold = old_thr
    assert out["results"] == [0]
    # and one hybrid-path query so the `hybrid` block is visibly live
    _, out = jpost(server.uri, "/index/dv/query", raw=b"Count(Row(f=0))")
    assert out["results"] == [1]
    _, out = jpost(server.uri, "/index/dv/query", raw=b"Sum(field=v)")
    assert out["results"][0] == {"value": 7, "count": 1}
    status, body = http("GET", server.uri, "/debug/vars")
    assert status == 200
    d = json.loads(body)
    assert d["deviceResidency"]["entries"] > 0
    assert d["hybrid"]["sparseUploads"] >= 1
    assert d["hybrid"]["threshold"] == 4096
    if server.executor.batcher is not None:
        assert d["countBatcher"]["batched_queries"] >= 1
        assert d["planeSumBatcher"]["batched_queries"] >= 1
    assert "topnRecountRows" in d


def test_import_clear_mode(server):
    """clear=true on the import endpoint removes bits instead of setting
    them (PostImport Optional clear, handler.go:184, :1002-1004)."""
    jpost(server.uri, "/index/ic", {})
    jpost(server.uri, "/index/ic/field/f", {})
    jpost(server.uri, "/index/ic/field/f/import",
          {"rowIDs": [1, 1, 1, 2], "columnIDs": [10, 11, 12, 10]})
    _, out = jpost(server.uri, "/index/ic/query", raw=b"Count(Row(f=1))")
    assert out["results"] == [3]
    # clear two of row 1's bits via the query param, one via the body flag
    jpost(server.uri, "/index/ic/field/f/import?clear=true",
          {"rowIDs": [1, 1], "columnIDs": [10, 11]})
    jpost(server.uri, "/index/ic/field/f/import",
          {"rowIDs": [1], "columnIDs": [12], "clear": True})
    _, out = jpost(server.uri, "/index/ic/query", raw=b"Row(f=1)")
    assert out["results"][0]["columns"] == []
    _, out = jpost(server.uri, "/index/ic/query", raw=b"Count(Row(f=2))")
    assert out["results"] == [1]  # untouched row survives


# -- async broadcast plane (SendAsync, broadcast.go:30-36) -------------------


def test_hung_peer_adds_no_write_latency(server, tmp_path):
    """The create-shard announcement fired from inside the first write to a
    new shard rides the async broadcast queue: a peer that accepts TCP but
    never answers must add ZERO latency to Set() (the reference sends this
    over gossip SendAsync; the old sync path added peer-timeout per write)."""
    import socket
    import time as _time

    from pilosa_tpu.parallel.cluster import Node

    u = server.uri
    jpost(u, "/index/hp", {})
    jpost(u, "/index/hp/field/f", {})

    # a peer that accepts connections and then hangs forever
    hung = socket.socket()
    hung.bind(("127.0.0.1", 0))
    hung.listen(8)
    hport = hung.getsockname()[1]
    try:
        server.cluster.nodes.append(
            Node(id="hung-node", uri=f"http://127.0.0.1:{hport}"))
        # write to a shard THIS node owns (adding a peer moved ownership of
        # ~half the shards to it; a write routed to the hung owner would
        # legitimately block on forwarding, which is not what's under test)
        shard = next(
            s for s in range(64)
            if all(n.id == server.node_id
                   for n in server.cluster.shard_nodes("hp", s)))
        col = shard * SHARD_WIDTH + 3
        t0 = _time.perf_counter()
        status, out = jpost(u, "/index/hp/query",
                            raw=f"Set({col}, f=1)".encode())
        elapsed = _time.perf_counter() - t0
        assert status == 200 and out["results"] == [True]
        # sync-broadcast behavior would block ~30s (client timeout); the
        # async queue returns immediately — generous bound for slow CI
        assert elapsed < 2.0, f"Set took {elapsed:.1f}s with a hung peer"
        # the announcement was not dropped: the broadcast worker actually
        # dialed the (hung) peer off the write path
        hung.settimeout(10)
        conn, _ = hung.accept()
        conn.close()
    finally:
        server.cluster.nodes[:] = [n for n in server.cluster.nodes
                                   if n.id != "hung-node"]
        hung.close()


def test_broadcast_async_delivers(cluster3):
    """broadcast_async reaches every healthy peer (delivery happens off the
    caller's thread; convergence within the wait window)."""
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/ba", {})
    jpost(s0.uri, "/index/ba/field/f", {})
    s0.broadcast_async({"type": "create-shard", "index": "ba",
                        "field": "f", "shard": 7})
    assert wait_until(lambda: all(
        7 in {int(x) for x in
              s.holder.index("ba").field("f").available_shards.slice()}
        for s in (s1, s2)), timeout=10)


def test_attr_sync_paginates(cluster3):
    """_sync_attrs pages block diffs: with a 1-block page size every local
    chunk carries a tiling [lo, hi) range, so peer-only blocks in the gaps
    and beyond the last local block are still pulled exactly once
    (holder.go:726-820 attr-block paging analog)."""
    s0, s1, s2 = cluster3
    jpost(s0.uri, "/index/pg", {})
    # peer (s0) attrs spread over blocks 0, 1, 2 and 100 (block = id//100)
    ca0 = s0.holder.index("pg").column_attrs
    for cid, val in ((7, "a"), (105, "b"), (250, "c"), (10_050, "d")):
        ca0.set_attrs(cid, {"v": val})
    # puller (s1) has its OWN blocks 1 and 3 -> multi-page with gaps: pages
    # are [0,106)@blk1, [106,None)@blk3; blocks 0/2/100 ride range gaps
    ca1 = s1.holder.index("pg").column_attrs
    ca1.set_attrs(199, {"mine": 1})
    ca1.set_attrs(399, {"mine": 2})
    s1.ATTR_SYNC_PAGE = 1  # force one block per request
    merged = s1.sync_holder()
    assert merged >= 1
    for cid, val in ((7, "a"), (105, "b"), (250, "c"), (10_050, "d")):
        assert ca1.attrs(cid) == {"v": val}, cid
    assert ca1.attrs(199) == {"mine": 1} and ca1.attrs(399) == {"mine": 2}


def test_import_iso_timestamps(server):
    """Import accepts ISO-8601 timestamp strings (convenience superset of
    the reference's epoch numbers) and lands bits in time views; junk
    timestamps fail loudly instead of silently dropping the time views."""
    u = server.uri
    jpost(u, "/index/ts", {})
    jpost(u, "/index/ts/field/t",
          {"options": {"type": "time", "timeQuantum": "YMD"}})
    status, _ = jpost(u, "/index/ts/field/t/import", {
        "rowIDs": [1, 1], "columnIDs": [5, 6],
        "timestamps": ["2019-03-02T00:00", 1551744000]})  # str + epoch
    assert status == 200
    _, out = jpost(u, "/index/ts/query",
                   raw=b"Count(Range(t=1, 2019-03-01T00:00, 2019-03-10T00:00))")
    assert out["results"] == [2], out
    status, out = jpost(u, "/index/ts/field/t/import", {
        "rowIDs": [1], "columnIDs": [7], "timestamps": ["not-a-time"]})
    assert status >= 400 and "invalid import timestamp" in json.dumps(out)


def test_debug_vars_surfaces_volatile_fragments(server, tmp_path):
    """ADVICE r4: frozen-loaded (volatile) fragments and their at-risk
    mutation counts are visible in /debug/vars until a snapshot makes
    them durable."""
    import numpy as np

    idx = server.holder.create_index("vi", track_existence=False)
    f = idx.create_field("f")
    rows = np.repeat(np.arange(50, dtype=np.uint64), 20)
    cols = np.tile(np.arange(20, dtype=np.uint64), 50)
    f.import_rows_frozen(rows, cols)
    frag = f.view("standard").fragment(0)
    frag.set_bit(5, 999)  # acknowledged write that is NOT yet durable
    _, dv = http("GET", server.uri, "/debug/vars")
    vf = json.loads(dv).get("volatileFragments")
    assert vf == [{"index": "vi", "field": "f", "view": "standard",
                   "shard": 0, "mutations": 1}]
    frag.snapshot()
    _, dv = http("GET", server.uri, "/debug/vars")
    assert "volatileFragments" not in json.loads(dv)


def test_import_roaring_endpoint_and_set_coordinator(cluster3):
    """HTTP surface coverage for the two previously-untested routes:
    /index/{i}/field/{f}/import-roaring/{shard} (base64 views in JSON,
    clear= arg) and /cluster/resize/set-coordinator."""
    import base64

    from pilosa_tpu.storage.roaring import Bitmap

    s0 = cluster3[0]
    jpost(s0.uri, "/index/ir", {})
    jpost(s0.uri, "/index/ir/field/f", {})
    # rows 0 and 1 in shard 0 of the standard view: positions row*2^20+col
    bm = Bitmap(np.array([5, 9, (1 << 20) + 5], dtype=np.uint64))
    payload = {"views": {"": base64.b64encode(bm.to_bytes()).decode()}}
    status, out = jpost(s0.uri, "/index/ir/field/f/import-roaring/0", payload)
    assert status == 200, out
    _, out = jpost(s0.uri, "/index/ir/query", raw=b"Row(f=0)")
    assert out["results"][0]["columns"] == [5, 9]
    _, out = jpost(s0.uri, "/index/ir/query", raw=b"Row(f=1)")
    assert out["results"][0]["columns"] == [5]
    # clear path removes presented bits only
    clr = Bitmap(np.array([9], dtype=np.uint64))
    status, out = jpost(
        s0.uri, "/index/ir/field/f/import-roaring/0?clear=true",
        {"views": {"": base64.b64encode(clr.to_bytes()).decode()}})
    assert status == 200, out
    _, out = jpost(s0.uri, "/index/ir/query", raw=b"Row(f=0)")
    assert out["results"][0]["columns"] == [5]

    # set-coordinator: every node must adopt the new coordinator
    # (SetCoordinatorMessage broadcast)
    target = cluster3[1]
    status, out = jpost(s0.uri, "/cluster/resize/set-coordinator",
                        {"id": target.cluster.local_id})
    assert status == 200, out
    assert wait_until(lambda: all(
        s.cluster.coordinator_id == target.cluster.local_id
        for s in cluster3))
    # missing id is a clean 400
    status, out = jpost(s0.uri, "/cluster/resize/set-coordinator", {})
    assert status == 400, out


def test_internal_fragment_views_nodes_and_shard_tombstone(server):
    """Coverage for the three previously-untested internal routes:
    /internal/fragment/views, /internal/fragment/nodes, and DELETE
    /internal/.../remote-available-shards/{s}."""
    u = server.uri
    jpost(u, "/index/iv", {})
    jpost(u, "/index/iv/field/f", {"options": {"type": "time",
                                               "timeQuantum": "YMD"}})
    status, _ = jpost(u, "/index/iv/field/f/import", {
        "rowIDs": [1, 1], "columnIDs": [3, SHARD_WIDTH + 4],
        "timestamps": ["2026-07-15T00:00:00Z"] * 2})
    assert status == 200
    status, out = http("GET", u,
                       "/internal/fragment/views?index=iv&field=f&shard=0")
    views = json.loads(out)["views"]
    assert status == 200 and "standard" in views
    assert any(v.startswith("standard_2026") for v in views), views
    status, out = http("GET", u, "/internal/fragment/nodes?index=iv&shard=1")
    nodes = json.loads(out)
    assert status == 200 and len(nodes) == 1 and nodes[0]["id"]

    # remote-available-shards tombstone: mark a remote shard available,
    # then DELETE must retract it from the availability view
    f = server.holder.index("iv").field("f")
    f.add_available_shard(7, quiet=True)
    assert 7 in set(f.available_shards)
    status, _ = http("DELETE", u,
                     "/internal/index/iv/field/f/remote-available-shards/7")
    assert status == 200
    assert 7 not in set(f.available_shards)
    # shards with local data survive the retraction path
    status, _ = http("DELETE", u,
                     "/internal/index/iv/field/f/remote-available-shards/0")
    assert status == 200
    _, out = jpost(u, "/index/iv/query", raw=b"Count(Row(f=1))")
    assert out["results"] == [2]


def test_pending_coordinator_claim_semantics():
    """adopt_coordinator is sticky across the claimed node being unknown:
    the claim waits for the node to materialize, takes effect on admission,
    and is retired by explicit removal (not by transient unknown-ness)."""
    from pilosa_tpu.parallel.cluster import Cluster, Node

    c = Cluster("n1")
    c.set_static([Node(id="n1"), Node(id="n2")])
    c.elect_coordinator()
    assert c.coordinator_id == "n1"  # default: lowest id
    c.adopt_coordinator("n9")  # unknown node: claim pends, default holds
    assert c.coordinator_id == "n1"
    c.add_node(Node(id="n9"))  # claim takes effect on admission
    assert c.coordinator_id == "n9"
    c.remove_node("n9")  # explicit removal retires the claim
    assert c.coordinator_id == "n1"
    c.add_node(Node(id="n9"))  # re-admission does NOT resurrect it
    assert c.coordinator_id == "n1"


def test_return_heal_repushes_explicit_coordinator(cluster3):
    """A node that was down during set-coordinator learns the explicit
    choice from the return-heal push (the convergence path gossip mode
    relies on)."""
    s0, s1, s2 = cluster3
    target = s2.cluster.local_id
    # s0 holds an explicit claim; simulate s1 having missed the broadcast
    s0.cluster.adopt_coordinator(target)
    s1.cluster._explicit_claim = None
    s1.cluster.elect_coordinator()
    assert s1.cluster.coordinator_id != target or True  # may equal by luck
    node_s1 = s0.cluster.node_by_id(s1.cluster.local_id)
    s0._on_node_return(node_s1)  # the heal thread pushes the claim
    assert wait_until(
        lambda: s1.cluster.coordinator_id == target
        and s1.cluster._explicit_claim == target)
