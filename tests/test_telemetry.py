"""Fleet telemetry (utils/telemetry.py + the HTTP surfaces it feeds):
ring-buffer bounds and `since` cursors, the sampler kill switch, XLA
compile-vs-cached counters with induced-recompile storm warnings, the
shared health-score definition, structured JSON logging, mixed-version
`/cluster/stats` federation (legacy 404 peers degrade, never error), and
the air-gap guarantee of the self-contained dashboard."""

import json
import re
import time
import urllib.request

import jax.numpy as jnp
import pytest

from pilosa_tpu.utils import telemetry as T


# --------------------------------------------------------------------- ring


def test_ring_bounded_and_since_cursor():
    r = T.Ring(4)
    for i in range(10):
        r.append({"v": float(i)})
    assert len(r) == 4  # bounded memory regardless of appends
    out = r.since(0)
    assert out["seq"] == 10
    assert [s["gauges"]["v"] for s in out["samples"]] == [6.0, 7.0, 8.0, 9.0]
    # cursor: nothing new -> empty, but seq still advances the poller
    again = r.since(out["seq"])
    assert again["samples"] == [] and again["seq"] == 10
    r.append({"v": 10.0})
    assert [s["gauges"]["v"]
            for s in r.since(out["seq"])["samples"]] == [10.0]
    assert len(r.since(0, limit=2)["samples"]) == 2


def test_sampler_lifecycle_and_kill_switch(monkeypatch):
    s = T.TelemetrySampler(interval=0.01, ring_size=8,
                           source=lambda: {"x": 1.0})
    monkeypatch.setenv("PILOSA_TPU_TELEMETRY", "0")
    s.start()
    assert not s.running  # kill switch wins over start()
    monkeypatch.delenv("PILOSA_TPU_TELEMETRY")
    s.start()
    assert s.running
    deadline = time.monotonic() + 2.0
    while len(s.ring) == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    s.stop()  # restartable pause (the bench A/B uses this)
    assert len(s.ring) >= 1
    s.close()
    assert not s.running


def test_sampler_survives_source_errors():
    def bad():
        raise RuntimeError("boom")

    s = T.TelemetrySampler(interval=0, source=bad)
    assert s.sample_once() is None
    assert s.sample_errors == 1


# ------------------------------------------------------------- XLA counters


def test_xla_counters_compile_cached_and_storm():
    c = T.XLACounters(storm_n=3, storm_window_s=60)
    msgs = []
    c.log_fn = lambda fmt, *a: msgs.append(fmt % a)
    assert c.record("fam", ("k1",)) is True  # new signature = compile
    assert c.record("fam", ("k1",)) is False  # repeat = cached dispatch
    c.record("fam", ("k2",))
    assert not msgs
    c.record("fam", ("k3",))  # 3rd new key in window -> storm
    snap = c.snapshot()
    fam = snap["families"]["fam"]
    assert (fam["compiles"], fam["cached"], fam["storms"]) == (3, 1, 1)
    assert "lastSignatureDiff" in fam  # old-vs-new diff rides the snapshot
    assert c.storms == 1 and c.storm_active()
    assert len(msgs) == 1 and "recompile storm" in msgs[0]
    # a second storm inside the same window does not re-warn (rate limit)
    c.record("fam", ("k4",))
    assert len(msgs) == 1


def test_induced_recompile_trips_counter_and_storm(monkeypatch):
    """A real jit dispatch-site test: shape churn on a wrapped kernel
    bumps the compile counter and fires the storm warning."""
    fresh = T.XLACounters(storm_n=3, storm_window_s=60)
    msgs = []
    fresh.log_fn = lambda fmt, *a: msgs.append(fmt % a)
    monkeypatch.setattr(T, "xla", fresh)
    from pilosa_tpu.ops import bitvector as bv

    for n in (33, 65, 129):  # three distinct shapes = three compiles
        bv.popcount(jnp.zeros((n,), jnp.uint32))
    snap = fresh.snapshot()
    assert snap["families"]["count"]["compiles"] == 3
    assert fresh.storms == 1
    assert any("recompile storm" in m for m in msgs)
    bv.popcount(jnp.zeros((33,), jnp.uint32))  # repeat shape: cached
    assert fresh.snapshot()["families"]["count"]["cached"] == 1


def test_kill_switch_disables_dispatch_counting(monkeypatch):
    fresh = T.XLACounters()
    monkeypatch.setattr(T, "xla", fresh)
    monkeypatch.setenv("PILOSA_TPU_TELEMETRY", "0")
    from pilosa_tpu.ops import bitvector as bv

    bv.popcount(jnp.zeros((47,), jnp.uint32))
    assert fresh.snapshot()["compiles"] == 0


def test_device_memory_stats_graceful_on_cpu():
    out = T.device_memory_stats()
    assert out, "device list should not be empty"
    for d in out:
        assert "memoryStats" in d  # None on CPU is the graceful null
        assert d["platform"] == "cpu"


# -------------------------------------------------------------- health score


def test_health_score_levels():
    assert T.health_score({}) == {"score": "green", "reasons": []}
    assert T.health_score({"walPoisoned": True})["score"] == "red"
    assert T.health_score({"needsRebuild": 2})["score"] == "yellow"
    assert T.health_score({"damagedFragments": 1})["score"] == "yellow"
    assert T.health_score({"errorRate": 0.5})["score"] == "yellow"
    assert T.health_score({"errorRate": 5.0})["score"] == "red"
    assert T.health_score({"queueSaturation": 3.0})["score"] == "yellow"
    assert T.health_score({"recompileStormActive": True})["score"] == "yellow"
    # worst input wins; every reason is reported
    both = T.health_score({"walPoisoned": True, "needsRebuild": 1})
    assert both["score"] == "red" and len(both["reasons"]) == 2


# ---------------------------------------------------------------- json logs


def test_json_log_format_carries_trace_field():
    import io

    from pilosa_tpu.utils import tracing
    from pilosa_tpu.utils.logger import Logger

    buf = io.StringIO()
    log = Logger(out=buf, fmt="json")
    tok = tracing.current_trace_id.set("abc123")
    try:
        log.printf("%.3fs SLOW QUERY %s", 1.5, "Count(Row(f=0))")
    finally:
        tracing.current_trace_id.reset(tok)
    log.printf("plain message")
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert lines[0]["level"] == "INFO"
    assert lines[0]["msg"].endswith("Count(Row(f=0))")
    assert lines[0]["trace"] == "abc123"  # a FIELD, not a suffix
    assert "trace" not in lines[1]
    with pytest.raises(ValueError):
        Logger(fmt="xml")


# ------------------------------------------------------------- live cluster


def _get(uri, path, timeout=15):
    with urllib.request.urlopen(uri + path, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def _post(uri, path, payload=None, raw=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def trio(tmp_path_factory):
    """3-node cluster, one node speaking the legacy protocol (its
    /internal/stats route 404s like a build that predates it)."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("telemetry")
    servers = [Server(str(tmp / f"n{i}"), port=0,
                      node_id=chr(ord("a") + i),
                      telemetry_interval=0.05).open() for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    def _legacy_404(params, query, body):
        return 404, "application/json", b'{"error": "not found"}'

    servers[2].handler.get_internal_stats = _legacy_404

    _post(uris[0], "/index/t", {})
    _post(uris[0], "/index/t/field/f", {})
    cols = list(range(0, 3 * 2 ** 20, 4099))
    _post(uris[0], "/index/t/field/f/import",
          {"rowIDs": [0] * len(cols), "columnIDs": cols})
    for _ in range(2):
        _post(uris[0], "/index/t/query", raw=b"Count(Row(f=0))")
    # the XLA counters are process-global and earlier test files churn
    # shapes by design — drop any active storm window so the "fleet is
    # green" assertions below are deterministic under full-suite order
    T.xla.reset()
    yield servers, uris
    for s in servers:
        s.close()


def test_status_gains_uptime_version_health(trio):
    servers, uris = trio
    _, _, body = _get(uris[0], "/status")
    st = json.loads(body)
    assert st["uptimeSeconds"] >= 0
    from pilosa_tpu import __version__
    assert st["version"] == __version__
    assert st["health"]["score"] == "green"
    # one health definition: /status agrees with the node's own score
    assert st["health"] == servers[0].node_health()


def test_timeseries_incremental_cursor(trio):
    servers, uris = trio
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        _, _, body = _get(uris[0], "/debug/timeseries")
        first = json.loads(body)
        if len(first["samples"]) >= 2:
            break
        time.sleep(0.05)
    assert len(first["samples"]) >= 2
    assert first["enabled"] and first["ringSize"] == 720
    g = first["samples"][-1]["gauges"]
    for key in ("residency.bytes", "batcher.queue_depth", "fanout.queued",
                "wal.bytes", "process.rss_bytes", "xla.compiles",
                "residency.hit_rate"):
        assert key in g, sorted(g)
    # incremental: polling with the returned cursor transfers each
    # sample exactly once
    cur = first["seq"]
    _, _, body = _get(uris[0], f"/debug/timeseries?since={cur}")
    nxt = json.loads(body)
    assert all(s["seq"] > cur for s in nxt["samples"])
    _, _, body = _get(uris[0], f"/debug/timeseries?since={10**9}")
    assert json.loads(body)["samples"] == []
    # unknown query args still 400 (validation spec)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(uris[0], "/debug/timeseries?cursor=1")
    assert e.value.code == 400


def test_timeseries_ring_stays_bounded(tmp_path):
    from pilosa_tpu.server import Server

    srv = Server(str(tmp_path / "ringy"), port=0, telemetry_interval=0.01,
                 telemetry_ring=5).open()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            _, _, body = _get(srv.uri, "/debug/timeseries")
            out = json.loads(body)
            if out["seq"] > 8:
                break
            time.sleep(0.02)
        assert out["seq"] > 8  # many samples taken...
        assert len(out["samples"]) <= 5  # ...bounded by the ring
    finally:
        srv.close()


def test_cluster_stats_mixed_version_federation(trio):
    servers, uris = trio
    _, _, body = _get(uris[0], "/cluster/stats")
    doc = json.loads(body)
    fleet = doc["fleet"]
    assert len(fleet["nodes"]) == 3
    by_id = {n["id"]: n for n in fleet["nodes"]}
    assert by_id["a"]["health"]["score"] == "green"
    assert by_id["b"]["health"]["score"] == "green"
    # the legacy peer 404s /internal/stats -> marked legacy, NOT an error
    assert by_id["c"]["health"]["score"] == "legacy"
    # ...and the fleet stays green
    assert fleet["health"] == "green"
    assert fleet["counts"] == {"green": 2, "legacy": 1}
    assert doc["generatedBy"] == "a"
    # live peers carry real documents: gauges + a sparkline tail
    assert "residency.bytes" in by_id["b"]["gauges"]
    assert by_id["b"]["timeseries"]["samples"]


def test_cluster_stats_down_peer_is_red(trio):
    servers, uris = trio
    servers[0].cluster.mark_down("b")
    try:
        _, _, body = _get(uris[0], "/cluster/stats")
        fleet = json.loads(body)["fleet"]
        by_id = {n["id"]: n for n in fleet["nodes"]}
        assert by_id["b"]["health"]["score"] == "red"
        assert fleet["health"] == "red"
    finally:
        servers[0].cluster.mark_up("b")


def test_internal_stats_document(trio):
    servers, uris = trio
    _, _, body = _get(uris[1], "/internal/stats")
    doc = json.loads(body)
    assert doc["id"] == "b" and doc["uri"] == uris[1]
    assert doc["health"]["score"] == "green"
    assert "healthInputs" in doc and "gauges" in doc
    assert doc["xla"]["compiles"] >= 0
    for dev in doc["deviceMemory"]:
        assert "memoryStats" in dev  # null on CPU, stats dict on TPU


# tier-1 air-gap guarantee: the dashboard must reference NOTHING external
_EXTERNAL_REF = re.compile(
    r"https?://|href\s*=|src\s*=|url\s*\(|@import|<link|<iframe|"
    r"integrity=|crossorigin", re.IGNORECASE)


def test_dashboard_is_self_contained(trio):
    servers, uris = trio
    status, ctype, body = _get(uris[0], "/debug/dashboard")
    html = body.decode()
    assert status == 200 and ctype.startswith("text/html")
    assert "<svg" in html or "spark" in html  # inline sparkline machinery
    hits = _EXTERNAL_REF.findall(html)
    assert not hits, f"dashboard references external assets: {hits}"
    # the same guarantee at the source level (catches routes the handler
    # might add around the template)
    from pilosa_tpu.net.dashboard import render_dashboard
    assert not _EXTERNAL_REF.findall(render_dashboard())


def test_debug_vars_and_metrics_still_work(trio):
    """The new series ride the existing surfaces without breaking them."""
    servers, uris = trio
    _, _, body = _get(uris[0], "/debug/vars")
    json.loads(body)
    _, ctype, body = _get(uris[0], "/metrics")
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "pilosa_residency" in text
    assert "pilosa_nodeHealth" in text
