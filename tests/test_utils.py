"""Aux subsystem tests: stats, tracing, logger, attr store, translate store."""

import io

import pytest

from pilosa_tpu.utils.attrstore import AttrStore, NopAttrStore
from pilosa_tpu.utils.logger import Logger, NopLogger
from pilosa_tpu.utils.stats import NopStatsClient, StatsClient, new_stats_client
from pilosa_tpu.utils.tracing import NopTracer, Tracer
from pilosa_tpu.utils.translate import TranslateStore


def test_stats_counts_gauges_timings():
    s = StatsClient()
    s.count("queries")
    s.count("queries", 2)
    s.gauge("goroutines", 5)
    s.timing("latency", 1.5)
    s.timing("latency", 0.5)
    s.set("indexes", "i")
    snap = s.snapshot()
    assert snap["counts"]["queries"] == 3
    assert snap["gauges"]["goroutines"] == 5
    assert snap["timings"]["latency"]["count"] == 2
    assert snap["timings"]["latency"]["min"] == 0.5
    assert snap["sets"]["indexes"] == ["i"]
    # tags namespace, shared store
    s.with_tags("index:i").count("queries")
    assert s.snapshot()["counts"]["queries,index:i"] == 1
    assert new_stats_client("nop").snapshot() == {}
    NopStatsClient().count("x")  # no-op


def test_tracer_spans_and_propagation():
    t = Tracer()
    with t.start_span("executor.Count") as span:
        span.set_tag("index", "i")
    spans = t.finished("executor.Count")
    assert len(spans) == 1
    assert spans[0].tags == {"index": "i"}
    assert spans[0].duration() >= 0
    headers = {}
    t.inject_headers(spans[0], headers)
    assert t.extract_trace_id(headers) == spans[0].trace_id
    assert NopTracer().finished() == []


def test_logger():
    buf = io.StringIO()
    log = Logger(verbose=False, out=buf)
    log.printf("hello %s", "world")
    log.debugf("hidden")
    out = buf.getvalue()
    assert "hello world" in out and "hidden" not in out
    Logger(verbose=True, out=buf).debugf("shown")
    assert "shown" in buf.getvalue()
    NopLogger().printf("x")


def test_attrstore(tmp_path):
    s = AttrStore(str(tmp_path / "a.db")).open()
    s.set_attrs(1, {"color": "red", "n": 5})
    s.set_attrs(1, {"n": None, "x": True})  # merge + delete
    assert s.attrs(1) == {"color": "red", "x": True}
    s.set_attrs(250, {"y": 1})
    assert s.ids() == [1, 250]
    blocks = dict(s.blocks())
    assert set(blocks) == {0, 2}
    assert s.block_data(2) == [(250, {"y": 1})]
    s.close()
    # persistence
    s2 = AttrStore(str(tmp_path / "a.db")).open()
    assert s2.attrs(1) == {"color": "red", "x": True}
    s2.close()
    assert NopAttrStore().open().attrs(1) == {}


def test_translate_store_persistence(tmp_path):
    path = str(tmp_path / "keys")
    t = TranslateStore(path).open()
    a = t.translate_column("i", "alpha")
    b = t.translate_column("i", "beta")
    assert (a, b) == (1, 2)
    assert t.translate_column("i", "alpha") == 1  # stable
    r = t.translate_row("i", "f", "row-key")
    assert r == 1  # row namespace separate from columns
    assert t.translate_column_to_string("i", 1) == "alpha"
    assert t.translate_row_to_string("i", "f", 1) == "row-key"
    t.close()
    t2 = TranslateStore(path).open()
    assert t2.translate_column("i", "alpha", create=False) == 1
    assert t2.translate_column("i", "gamma") == 3
    t2.close()


def test_translate_replication(tmp_path):
    primary = TranslateStore(str(tmp_path / "p")).open()
    primary.translate_column("i", "k1")
    primary.translate_column("i", "k2")
    replica = TranslateStore(str(tmp_path / "r")).open()
    replica.read_only = True
    replica.apply_log(primary.log_bytes(0))
    assert replica.translate_column("i", "k1", create=False) == 1
    with pytest.raises(ValueError):
        replica.translate_column("i", "new-key")
    # incremental tail
    off = primary.log_size()
    primary.translate_column("i", "k3")
    replica.apply_log(primary.log_bytes(off))
    assert replica.translate_column("i", "k3", create=False) == 3
    primary.close()
    replica.close()
