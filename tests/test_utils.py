"""Aux subsystem tests: stats, tracing, logger, attr store, translate store."""

import io
import os
import time

import pytest

from pilosa_tpu.utils.attrstore import AttrStore, NopAttrStore
from pilosa_tpu.utils.logger import Logger, NopLogger
from pilosa_tpu.utils.stats import NopStatsClient, StatsClient, new_stats_client
from pilosa_tpu.utils.tracing import NopTracer, Tracer
from pilosa_tpu.utils.translate import TranslateStore


def test_stats_counts_gauges_timings():
    s = StatsClient()
    s.count("queries")
    s.count("queries", 2)
    s.gauge("goroutines", 5)
    s.timing("latency", 1.5)
    s.timing("latency", 0.5)
    s.set("indexes", "i")
    snap = s.snapshot()
    assert snap["counts"]["queries"] == 3
    assert snap["gauges"]["goroutines"] == 5
    assert snap["timings"]["latency"]["count"] == 2
    assert snap["timings"]["latency"]["min"] == 0.5
    assert snap["sets"]["indexes"] == ["i"]
    # tags namespace, shared store
    s.with_tags("index:i").count("queries")
    assert s.snapshot()["counts"]["queries,index:i"] == 1
    assert new_stats_client("nop").snapshot() == {}
    NopStatsClient().count("x")  # no-op


def test_tracer_spans_and_propagation():
    t = Tracer()
    with t.start_span("executor.Count") as span:
        span.set_tag("index", "i")
    spans = t.finished("executor.Count")
    assert len(spans) == 1
    assert spans[0].tags == {"index": "i"}
    assert spans[0].duration() >= 0
    headers = {}
    t.inject_headers(spans[0], headers)
    assert t.extract_trace_id(headers) == spans[0].trace_id
    assert NopTracer().finished() == []


def test_logger():
    buf = io.StringIO()
    log = Logger(verbose=False, out=buf)
    log.printf("hello %s", "world")
    log.debugf("hidden")
    out = buf.getvalue()
    assert "hello world" in out and "hidden" not in out
    Logger(verbose=True, out=buf).debugf("shown")
    assert "shown" in buf.getvalue()
    NopLogger().printf("x")


def test_attrstore(tmp_path):
    s = AttrStore(str(tmp_path / "a.db")).open()
    s.set_attrs(1, {"color": "red", "n": 5})
    s.set_attrs(1, {"n": None, "x": True})  # merge + delete
    assert s.attrs(1) == {"color": "red", "x": True}
    s.set_attrs(250, {"y": 1})
    assert s.ids() == [1, 250]
    blocks = dict(s.blocks())
    assert set(blocks) == {0, 2}
    assert s.block_data(2) == [(250, {"y": 1})]
    s.close()
    # persistence
    s2 = AttrStore(str(tmp_path / "a.db")).open()
    assert s2.attrs(1) == {"color": "red", "x": True}
    s2.close()
    assert NopAttrStore().open().attrs(1) == {}


def test_translate_store_persistence(tmp_path):
    path = str(tmp_path / "keys")
    t = TranslateStore(path).open()
    a = t.translate_column("i", "alpha")
    b = t.translate_column("i", "beta")
    assert (a, b) == (1, 2)
    assert t.translate_column("i", "alpha") == 1  # stable
    r = t.translate_row("i", "f", "row-key")
    assert r == 1  # row namespace separate from columns
    assert t.translate_column_to_string("i", 1) == "alpha"
    assert t.translate_row_to_string("i", "f", 1) == "row-key"
    t.close()
    t2 = TranslateStore(path).open()
    assert t2.translate_column("i", "alpha", create=False) == 1
    assert t2.translate_column("i", "gamma") == 3
    t2.close()


def test_translate_replication(tmp_path):
    primary = TranslateStore(str(tmp_path / "p")).open()
    primary.translate_column("i", "k1")
    primary.translate_column("i", "k2")
    replica = TranslateStore(str(tmp_path / "r")).open()
    replica.read_only = True
    replica.apply_log(primary.log_bytes(0))
    assert replica.translate_column("i", "k1", create=False) == 1
    with pytest.raises(ValueError):
        replica.translate_column("i", "new-key")
    # incremental tail
    off = primary.log_size()
    primary.translate_column("i", "k3")
    replica.apply_log(primary.log_bytes(off))
    assert replica.translate_column("i", "k3", create=False) == 3
    primary.close()
    replica.close()


# -- statsd client (statsd/statsd.go) ----------------------------------------

def test_statsd_client_datagrams():
    import socket
    from pilosa_tpu.utils.stats import StatsDClient, new_stats_client

    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5)
    port = rx.getsockname()[1]
    c = StatsDClient("127.0.0.1", port, prefix="pilosa.")
    c.count("queries", 2)
    assert rx.recvfrom(1024)[0] == b"pilosa.queries:2|c"
    c.gauge("heap", 12.5)
    assert rx.recvfrom(1024)[0] == b"pilosa.heap:12.5|g"
    c.with_tags("index:i").timing("latency", 3)
    assert rx.recvfrom(1024)[0] == b"pilosa.latency:3|ms|#index:i"
    # factory selection
    s = new_stats_client("statsd", f"127.0.0.1:{port}")
    s.count("x")
    assert rx.recvfrom(1024)[0] == b"pilosa.x:1|c"
    rx.close()
    # unreachable agent must not raise
    dead = StatsDClient("127.0.0.1", 1)
    dead.count("x")


# -- system info / diagnostics / runtime monitor (diagnostics.go) ------------

def test_system_info_proc():
    from pilosa_tpu.utils.diagnostics import SystemInfo
    si = SystemInfo()
    assert si.uptime() > 0
    assert si.platform() == "Linux"
    assert si.mem_total() > si.mem_used() > 0
    assert si.cpu_count() >= 1


def test_diagnostics_collect_and_flush():
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer
    from pilosa_tpu.utils.diagnostics import DiagnosticsCollector

    received = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/diagnostics"
    d = DiagnosticsCollector("1.0.0", url=url)
    info = d.collect()
    assert info["Version"] == "1.0.0" and info["OS"] == "Linux"
    assert d.flush() is True
    assert received[0]["NumCPU"] >= 1
    srv.shutdown()
    # no URL -> disabled, flush is a no-op
    assert DiagnosticsCollector("1.0.0").flush() is False


def test_span_exporter_ships_batches():
    """Config-enabled span export to a collector (the reference's Jaeger
    wiring, tracing/opentracing/opentracing.go:21-39): spans buffer, flush
    in batches, and sampler type/param gate what ships."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer
    from pilosa_tpu.utils.tracing import SpanExporter

    received = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/api/traces"

    exp = SpanExporter(url, batch_size=2, flush_interval=0)  # manual flush
    tr = Tracer(exporter=exp, sampler_type="const", sampler_param=1.0)
    with tr.start_span("executor.Count") as s:
        s.set_tag("index", "i")
    assert exp.exported == 0  # buffered below batch_size
    with tr.start_span("executor.TopN"):
        pass  # second span hits batch_size -> background flush
    deadline = time.time() + 5
    while exp.exported < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert exp.exported == 2
    batch = received[0]
    assert batch["process"]["serviceName"] == "pilosa-tpu"
    ops = [s["operationName"] for s in batch["spans"]]
    assert ops == ["executor.Count", "executor.TopN"]
    assert batch["spans"][0]["tags"] == {"index": "i"}
    assert batch["spans"][0]["durationMicros"] >= 0

    # sampler off -> recorded locally, never exported
    tr_off = Tracer(exporter=exp, sampler_type="off")
    with tr_off.start_span("x"):
        pass
    exp.flush()
    assert exp.exported == 2
    assert len(tr_off.finished("x")) == 1

    # probabilistic is deterministic per trace id
    tr_p = Tracer(exporter=exp, sampler_type="probabilistic",
                  sampler_param=0.5)
    v1 = tr_p._sampled(tr_p.start_span("y", trace_id="abc"))
    v2 = tr_p._sampled(tr_p.start_span("y", trace_id="abc"))
    assert v1 == v2

    # export failure (collector gone) drops the batch, never raises
    srv.shutdown()
    with tr.start_span("a"):
        pass
    with tr.start_span("b"):
        pass
    assert exp.exported == 2
    exp.close()


def test_runtime_monitor_gauges():
    from pilosa_tpu.utils.diagnostics import RuntimeMonitor
    from pilosa_tpu.utils.stats import StatsClient
    stats = StatsClient()
    RuntimeMonitor(stats).sample()
    snap = stats.snapshot()["gauges"]
    assert snap["memory/rss"] > 0
    assert snap["threads"] >= 1


def test_long_query_logging(tmp_path):
    import io
    from pilosa_tpu.server import Server
    from pilosa_tpu.utils.logger import Logger

    s = Server(str(tmp_path / "n"), port=0, long_query_time=0.0000001).open()
    try:
        buf = io.StringIO()
        s.api.logger = Logger(out=buf)
        s.api.create_index("i")
        from pilosa_tpu.models.field import FieldOptions
        s.api.create_field("i", "f", FieldOptions())
        s.api.query("i", "Count(Row(f=1))")
        assert "SLOW QUERY i Count(Row(f=1))" in buf.getvalue()
    finally:
        s.close()


def test_duration_strings():
    from pilosa_tpu.utils.duration import parse_duration
    assert parse_duration(5) == 5.0
    assert parse_duration("2.5") == 2.5
    assert parse_duration("250ms") == 0.25
    assert parse_duration("10s") == 10.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("") == 0.0
    with pytest.raises(ValueError):
        parse_duration("10 parsecs")
    with pytest.raises(ValueError):
        parse_duration("s10")


def test_uri_parse():
    from pilosa_tpu.net.uri import URI, URIError
    assert URI.parse("").normalize() == "http://localhost:10101"
    assert URI.parse("example.com").normalize() == "http://example.com:10101"
    assert URI.parse(":8080") == URI("http", "localhost", 8080)
    assert URI.parse("https://db1:444").normalize() == "https://db1:444"
    assert URI.parse("10.0.0.1:10101").host_port == "10.0.0.1:10101"
    with pytest.raises(URIError):
        URI.parse("ftp://x:1")
    with pytest.raises(URIError):
        URI.parse("http://host:99999")


def test_trace_id_propagation_context():
    """Incoming trace ids flow into spans opened while serving
    (extractTracing middleware + GlobalTracer), and onto outgoing internal
    requests (InjectHTTPHeaders)."""
    from pilosa_tpu.utils import tracing

    t = Tracer()
    token = tracing.current_trace_id.set("deadbeef")
    try:
        with t.start_span("executor.Execute") as span:
            assert span.trace_id == "deadbeef"
    finally:
        tracing.current_trace_id.reset(token)
    # outside the request context ids are fresh
    assert t.start_span("x").trace_id != "deadbeef"


def test_config_durations_and_tls(tmp_path):
    from pilosa_tpu.cli.config import load_config
    p = tmp_path / "c.toml"
    p.write_text(
        '[anti-entropy]\ninterval = "10m"\n'
        '[tls]\ncertificate = "crt.pem"\nkey = "key.pem"\nskip-verify = true\n')
    cfg = load_config(str(p))
    assert cfg.anti_entropy.interval == 600.0
    assert cfg.tls.enabled and cfg.tls.skip_verify
    cfg2 = load_config(None, environ={"PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "90s",
                                      "PILOSA_TPU_TLS_CERTIFICATE": "x"})
    assert cfg2.anti_entropy.interval == 90.0
    assert cfg2.tls.certificate == "x" and not cfg2.tls.enabled
    assert "[tls]" in cfg2.to_toml()


def test_translate_sqlite_index_no_replay_on_reopen(tmp_path, monkeypatch):
    """The sqlite index absorbs the log incrementally: a clean reopen
    replays NOTHING (meta.log_pos == log size), so opening a 100M-key
    store is O(1), not O(keys) (the non-resident index of
    translate.go:359-433)."""
    import pilosa_tpu.utils.translate as tr

    path = str(tmp_path / "keys")
    t = TranslateStore(path, index_kind="sqlite").open()
    for i in range(500):
        t.translate_column("i", f"k{i}")
    t.close()

    def boom(self, data):
        raise AssertionError("clean reopen must not replay the log")

    monkeypatch.setattr(tr.TranslateStore, "_replay", boom)
    t2 = TranslateStore(path, index_kind="sqlite").open()
    assert t2.translate_column("i", "k250", create=False) == 251
    assert t2.translate_column_to_string("i", 251) == "k250"
    monkeypatch.undo()
    # minting continues from the persisted max id
    assert t2.translate_column("i", "fresh") == 501
    t2.close()


def test_translate_sqlite_index_heals_from_log_tail(tmp_path):
    """Crash between log append and index commit: the next open replays
    only the un-absorbed tail from meta.log_pos."""
    path = str(tmp_path / "keys")
    t = TranslateStore(path, index_kind="sqlite").open()
    t.translate_column("i", "a")
    t.close()
    # simulate a lost index commit: rewind log_pos to 0 (index empty-ish is
    # fine too — INSERT OR IGNORE dedups on replay)
    import sqlite3

    db = sqlite3.connect(path + ".idx")
    db.execute("UPDATE meta SET v=0 WHERE k='log_pos'")
    db.commit()
    db.close()
    t2 = TranslateStore(path, index_kind="sqlite").open()
    assert t2.translate_column("i", "a", create=False) == 1
    assert t2.translate_column("i", "b") == 2
    t2.close()


def test_translate_index_ahead_of_log_rebuilds(tmp_path):
    """Index ahead of the log (crash wrote the index before the log hit
    disk, or the log was replaced): the LOG is the source of truth — the
    index rebuilds from it instead of serving mappings the cluster never
    minted or refusing to open."""
    from pilosa_tpu.utils.translate import _record_end

    path = str(tmp_path / "keys")
    t = TranslateStore(path, index_kind="sqlite").open()
    for i in range(10):
        t.translate_column("i", f"k{i}")
    t.close()
    # truncate the log at a record boundary, behind the absorbed offset
    data = open(path, "rb").read()
    pos = 0
    for _ in range(4):
        pos = _record_end(data, pos)
    with open(path, "r+b") as f:
        f.truncate(pos)
    t2 = TranslateStore(path, index_kind="sqlite").open()
    assert t2.translate_column("i", "k3", create=False) == 4
    assert t2.translate_column("i", "k7", create=False) is None  # truncated away
    assert t2.translate_column("i", "fresh") == 5  # minting resumes from log truth
    t2.close()
    # log deleted entirely but index left behind: same rule
    os.remove(path)
    t3 = TranslateStore(path, index_kind="sqlite").open()
    assert t3.translate_column("i", "k3", create=False) is None
    assert t3.translate_column("i", "first") == 1
    t3.close()


def test_translate_sqlite_replication_parity(tmp_path):
    """Replica tailing works identically over the sqlite index."""
    primary = TranslateStore(str(tmp_path / "p"), index_kind="sqlite").open()
    for i in range(50):
        primary.translate_column("i", f"c{i}")
        primary.translate_row("i", "f", f"r{i}")
    replica = TranslateStore(str(tmp_path / "r"), index_kind="sqlite").open()
    replica.read_only = True
    replica.apply_log(primary.log_bytes(0))
    assert replica.translate_column("i", "c7", create=False) == 8
    assert replica.translate_row_to_string("i", "f", 8) == "r7"
    assert replica.log_size() == primary.log_size()
    # ensure_mapping installs without touching the log (byte-prefix rule)
    before = replica.log_size()
    replica.ensure_mapping(0, "i", "", "minted-elsewhere", 999)
    assert replica.log_size() == before
    assert replica.translate_column("i", "minted-elsewhere",
                                    create=False) == 999
    primary.close()
    replica.close()
