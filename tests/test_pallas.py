"""Pallas kernel tests (interpret mode on the CPU backend)."""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk

RNG = np.random.default_rng(21)
W = 1024  # small lane count for interpret-mode speed (multiple of 128)


def test_intersect_count_matches_numpy():
    for s in (1, 8, 16):
        a = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        b = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        got = np.asarray(pk.intersect_count(a, b))
        expect = np.bitwise_count(a & b).sum(axis=1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_program_count_nested():
    leaves = RNG.integers(0, 2**32, size=(3, 8, W), dtype=np.uint32)
    prog = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    got = np.asarray(pk.program_count(leaves, prog))
    ref = (leaves[0] | leaves[1]) & ~leaves[2]
    expect = np.bitwise_count(ref).sum(axis=1).astype(np.int32)
    np.testing.assert_array_equal(got, expect)


def test_available():
    assert pk.available()
