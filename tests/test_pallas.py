"""Pallas kernel tests (interpret mode on the CPU backend)."""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk

RNG = np.random.default_rng(21)
W = 1024  # small lane count for interpret-mode speed (multiple of 128)


def test_intersect_count_matches_numpy():
    for s in (1, 8, 16):
        a = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        b = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        got = np.asarray(pk.intersect_count(a, b))
        expect = np.bitwise_count(a & b).sum(axis=1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_program_count_nested():
    leaves = RNG.integers(0, 2**32, size=(3, 8, W), dtype=np.uint32)
    prog = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    got = np.asarray(pk.program_count(leaves, prog))
    ref = (leaves[0] | leaves[1]) & ~leaves[2]
    expect = np.bitwise_count(ref).sum(axis=1).astype(np.int32)
    np.testing.assert_array_equal(got, expect)


def test_program_count_not_with_shard_padding():
    """Not-rooted programs complement the zero padding to all-ones; the
    padded shards' counts must be sliced off, never summed in."""
    for s in (3, 5):  # forces _pad_shards
        leaves = RNG.integers(0, 2**32, size=(1, s, W), dtype=np.uint32)
        got = np.asarray(pk.program_count(leaves, ("not", ("leaf", 0))))
        assert got.shape == (s,)
        expect = np.bitwise_count(~leaves[0]).sum(axis=1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_pair_stream_counts_matches_numpy():
    """Scalar-prefetch query stream: data-dependent row gathers via
    PrefetchScalarGridSpec, per-query accumulation over shard blocks."""
    import jax.numpy as jnp

    for s in (3, 16):  # non-multiple of SHARD_BLOCK exercises blk=1
        rows = RNG.integers(0, 2**32, size=(5, s, W), dtype=np.uint32)
        ii = np.array([0, 4, 2, 2], dtype=np.int32)
        jj = np.array([1, 4, 0, 3], dtype=np.int32)
        got = np.asarray(pk.pair_stream_counts(
            jnp.asarray(rows), jnp.asarray(ii), jnp.asarray(jj)))
        expect = np.array([np.bitwise_count(rows[i] & rows[j]).sum()
                           for i, j in zip(ii, jj)], dtype=np.int32)
        np.testing.assert_array_equal(got, expect)


def test_available():
    assert pk.available()


# -- mesh composition (shard_map wrappers; interpret mode on the 8-device
#    CPU mesh — VERDICT r3: PILOSA_TPU_PALLAS must compose with multi-device)


@pytest.mark.parametrize("replicas", [1, 2])
def test_program_count_mesh_parity(replicas):
    import jax

    from pilosa_tpu.parallel.mesh import DeviceRunner, eval_count_total, make_mesh

    mesh = make_mesh(replicas=replicas)
    runner = DeviceRunner(mesh, use_pallas=True)
    assert runner.use_pallas  # no longer forced off under a mesh
    rng = np.random.default_rng(17)
    host = [rng.integers(0, 2**32, size=(5, 256), dtype=np.uint32)
            for _ in range(3)]
    leaves = [runner.put_leaf(h) for h in host]
    program = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    got = runner.count_total_leaves(leaves, program)
    expect = int(np.bitwise_count((host[0] | host[1]) & ~host[2]).sum())
    assert got == expect
    # and parity with the XLA mesh path on the same device arrays
    assert got == int(eval_count_total(tuple(leaves), program))


@pytest.mark.parametrize("replicas", [1, 2])
def test_pair_stream_counts_mesh_parity(replicas):
    import jax

    from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

    mesh = make_mesh(replicas=replicas)
    runner = DeviceRunner(mesh)
    rng = np.random.default_rng(19)
    host = rng.integers(0, 2**32, size=(6, 4, 256), dtype=np.uint32)
    rows = runner.put_plane_slab(host)  # [R, S(padded), W] sharded
    k = 10
    ii = rng.integers(0, 6, size=k).astype(np.int32)
    jj = rng.integers(0, 6, size=k).astype(np.int32)
    got = pk.pair_stream_counts_mesh(mesh, rows, ii, jj)
    for q in range(k):
        expect = int(np.bitwise_count(host[ii[q]] & host[jj[q]]).sum())
        assert got[q] == expect, (q, got[q], expect)
