"""Pallas kernel tests (interpret mode on the CPU backend)."""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk

RNG = np.random.default_rng(21)
W = 1024  # small lane count for interpret-mode speed (multiple of 128)


def test_intersect_count_matches_numpy():
    for s in (1, 8, 16):
        a = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        b = RNG.integers(0, 2**32, size=(s, W), dtype=np.uint32)
        got = np.asarray(pk.intersect_count(a, b))
        expect = np.bitwise_count(a & b).sum(axis=1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_program_count_nested():
    leaves = RNG.integers(0, 2**32, size=(3, 8, W), dtype=np.uint32)
    prog = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    got = np.asarray(pk.program_count(leaves, prog))
    ref = (leaves[0] | leaves[1]) & ~leaves[2]
    expect = np.bitwise_count(ref).sum(axis=1).astype(np.int32)
    np.testing.assert_array_equal(got, expect)


def test_program_count_not_with_shard_padding():
    """Not-rooted programs complement the zero padding to all-ones; the
    padded shards' counts must be sliced off, never summed in."""
    for s in (3, 5):  # forces _pad_shards
        leaves = RNG.integers(0, 2**32, size=(1, s, W), dtype=np.uint32)
        got = np.asarray(pk.program_count(leaves, ("not", ("leaf", 0))))
        assert got.shape == (s,)
        expect = np.bitwise_count(~leaves[0]).sum(axis=1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_pair_stream_counts_matches_numpy():
    """Scalar-prefetch query stream: data-dependent row gathers via
    PrefetchScalarGridSpec, per-query accumulation over shard blocks."""
    import jax.numpy as jnp

    for s in (3, 16):  # non-multiple of SHARD_BLOCK exercises blk=1
        rows = RNG.integers(0, 2**32, size=(5, s, W), dtype=np.uint32)
        ii = np.array([0, 4, 2, 2], dtype=np.int32)
        jj = np.array([1, 4, 0, 3], dtype=np.int32)
        got = np.asarray(pk.pair_stream_counts(
            jnp.asarray(rows), jnp.asarray(ii), jnp.asarray(jj)))
        expect = np.array([np.bitwise_count(rows[i] & rows[j]).sum()
                           for i, j in zip(ii, jj)], dtype=np.int32)
        np.testing.assert_array_equal(got, expect)


def test_cross_count_matrix_matches_numpy():
    """Blocked GroupBy cross-count kernel: counts[P, R] over ragged shapes
    that force prefix/row/word padding in every combination."""
    for p, r, w in ((1, 1, 512), (5, 7, 512), (8, 128, 1024), (9, 130, 512)):
        a = RNG.integers(0, 2**32, size=(p, w), dtype=np.uint32)
        b = RNG.integers(0, 2**32, size=(r, w), dtype=np.uint32)
        got = np.asarray(pk.cross_count_matrix(a, b))
        expect = np.bitwise_count(
            a[:, None, :] & b[None, :, :]).sum(axis=-1).astype(np.int32)
        np.testing.assert_array_equal(got, expect)


def test_cross_count_matrix_parity_with_xla():
    """PILOSA_TPU_PALLAS routes GroupBy levels through this kernel; it must
    agree with the XLA fused form on [*, S, W] slab operands."""
    from pilosa_tpu.ops.bitvector import cross_count_matrix as xla_ccm

    pref = RNG.integers(0, 2**32, size=(6, 3, 512), dtype=np.uint32)
    axis = RNG.integers(0, 2**32, size=(9, 3, 512), dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(pk.cross_count_matrix(pref, axis)),
                                  np.asarray(xla_ccm(pref, axis)))


def test_groupby_chunk_live_parity():
    """Full chunk contract (gather + AND + cross count + on-device prune):
    the shared composition with the Pallas kernel plugged in as cross_fn
    returns identical (n_live, indices, counts) to the XLA form."""
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.ops import bitvector as bv

    slab_a = jnp.asarray(
        RNG.integers(0, 2**32, size=(5, 2, 512), dtype=np.uint32))
    slab_b = jnp.asarray(
        RNG.integers(0, 2**32, size=(4, 2, 512), dtype=np.uint32))
    idx = (jnp.asarray(np.array([0, 3, 4, 0], dtype=np.int32)),
           jnp.asarray(np.array([2, 0, 1, 0], dtype=np.int32)))
    args = ((slab_a, slab_b), idx, slab_b, jnp.int32(3), 32)
    got = jax.device_get(
        bv.groupby_chunk_live(*args, cross_fn=pk.cross_count_matrix))
    expect = jax.device_get(bv.groupby_chunk_live(*args))
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(g, e)


def test_available():
    assert pk.available()


# -- mesh composition (shard_map wrappers; interpret mode on the 8-device
#    CPU mesh — VERDICT r3: PILOSA_TPU_PALLAS must compose with multi-device)


@pytest.mark.parametrize("replicas", [1, 2])
def test_program_count_mesh_parity(replicas):
    import jax

    from pilosa_tpu.parallel.mesh import DeviceRunner, eval_count_total, make_mesh

    mesh = make_mesh(replicas=replicas)
    runner = DeviceRunner(mesh, use_pallas=True)
    assert runner.use_pallas  # no longer forced off under a mesh
    rng = np.random.default_rng(17)
    host = [rng.integers(0, 2**32, size=(5, 256), dtype=np.uint32)
            for _ in range(3)]
    leaves = [runner.put_leaf(h) for h in host]
    program = ("andnot", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    got = runner.count_total_leaves(leaves, program)
    expect = int(np.bitwise_count((host[0] | host[1]) & ~host[2]).sum())
    assert got == expect
    # and parity with the XLA mesh path on the same device arrays
    assert got == int(eval_count_total(tuple(leaves), program))


@pytest.mark.parametrize("replicas", [1, 2])
def test_groupby_chunk_mesh_pallas_parity(replicas):
    """GroupBy level chunks under PILOSA_TPU_PALLAS on a mesh: the blocked
    kernel runs per-device inside shard_map with an ICI psum over the shard
    axis, and must agree with the XLA mesh path and a numpy oracle."""
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

    mesh = make_mesh(replicas=replicas)
    xla = DeviceRunner(mesh, use_pallas=False)
    pallas = DeviceRunner(mesh, use_pallas=True)
    assert pallas.use_pallas
    rng = np.random.default_rng(23)
    host_a = rng.integers(0, 2**32, size=(6, 4, 512), dtype=np.uint32)
    host_b = rng.integers(0, 2**32, size=(5, 4, 512), dtype=np.uint32)
    idx = (jnp.asarray(np.array([0, 2, 5, 0], dtype=np.int32)),)
    n_valid, bound = jnp.int32(3), 30
    outs = []
    for runner in (xla, pallas):
        slab_a = runner.put_plane_slab(host_a)
        slab_b = runner.put_plane_slab(host_b)
        outs.append(jax.device_get(runner.groupby_chunk(
            (slab_a,), idx, slab_b, n_valid, bound)))
    for g, e in zip(outs[0], outs[1]):
        np.testing.assert_array_equal(g, e)
    cmat = np.bitwise_count(
        host_a[np.asarray(idx[0][:3])][:, None] & host_b[None]).reshape(
            3, 5, -1).sum(axis=-1)
    lp, lr = np.nonzero(cmat)
    n_live, flat_idx, counts = outs[1]
    assert int(n_live) == lp.size
    np.testing.assert_array_equal(flat_idx[:lp.size] // 5, lp)
    np.testing.assert_array_equal(counts[:lp.size], cmat[lp, lr])


def test_executor_groupby_pallas_parity(tmp_path):
    """End to end: PILOSA_TPU_PALLAS GroupBy through the executor matches
    the XLA path's groups, still at one host sync per level."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder
    from pilosa_tpu.parallel.mesh import DeviceRunner

    rng = np.random.default_rng(27)
    results = {}
    for mode, use_pallas in (("xla", False), ("pallas", True)):
        h = Holder(str(tmp_path / mode)).open()
        ex = Executor(h, runner=DeviceRunner(use_pallas=use_pallas))
        idx = h.create_index("gp", track_existence=False)
        rng = np.random.default_rng(27)  # identical data both runs
        for fname in ("a", "b"):
            f = idx.create_field(fname)
            rids, cids = [], []
            for r in range(8):
                cols = rng.choice(2000, size=120, replace=False)
                rids += [r] * len(cols)
                cids += [int(c) for c in cols]
            f.import_bits(rids, cids)
        before = ex.groupby_host_syncs
        (groups,) = ex.execute("gp", "GroupBy(Rows(field=a), Rows(field=b))")
        assert ex.groupby_host_syncs - before == 1
        results[mode] = list(groups)
        h.close()
    assert results["pallas"] == results["xla"]


@pytest.mark.parametrize("replicas", [1, 2])
def test_pair_stream_counts_mesh_parity(replicas):
    import jax

    from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

    mesh = make_mesh(replicas=replicas)
    runner = DeviceRunner(mesh)
    rng = np.random.default_rng(19)
    host = rng.integers(0, 2**32, size=(6, 4, 256), dtype=np.uint32)
    rows = runner.put_plane_slab(host)  # [R, S(padded), W] sharded
    k = 10
    ii = rng.integers(0, 6, size=k).astype(np.int32)
    jj = rng.integers(0, 6, size=k).astype(np.int32)
    got = pk.pair_stream_counts_mesh(mesh, rows, ii, jj)
    for q in range(k):
        expect = int(np.bitwise_count(host[ii[q]] & host[jj[q]]).sum())
        assert got[q] == expect, (q, got[q], expect)


# -- run-container PR kernels (ISSUE 17): fused TopN counts, BSI sweeps


def test_topn_counts_packed_parity():
    """Packed [3, R] = (|row∩src|, |row|, |src|) against numpy and the
    XLA twin, across shapes that force row AND word padding."""
    from pilosa_tpu.ops.topn import tanimoto_counts_packed as xla_packed

    for r, w in ((1, 512), (8, 2048), (100, 2048), (130, 4096)):
        rows = RNG.integers(0, 2**32, size=(r, w), dtype=np.uint32)
        src = RNG.integers(0, 2**32, size=(w,), dtype=np.uint32)
        got = np.asarray(pk.topn_counts_packed(rows, src))
        assert got.shape == (3, r)
        np.testing.assert_array_equal(
            got[0], np.bitwise_count(rows & src).sum(axis=1))
        np.testing.assert_array_equal(
            got[1], np.bitwise_count(rows).sum(axis=1))
        assert np.all(got[2] == np.bitwise_count(src).sum())
        np.testing.assert_array_equal(got, np.asarray(xla_packed(rows, src)))


def test_top_rows_pallas_matches_xla():
    from pilosa_tpu.ops.topn import top_rows as xla_top_rows

    rows = RNG.integers(0, 2**32, size=(12, 512), dtype=np.uint32)
    for k in (1, 5, 50):
        gc, gi = pk.top_rows(rows, k)
        ec, ei = xla_top_rows(rows, k)
        np.testing.assert_array_equal(np.asarray(gc), np.asarray(ec))
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(ei))


def test_bsi_compare_all_ops_parity():
    """Blocked VMEM sweep vs the XLA unrolled form: every op, values that
    exercise strict/equal boundaries, ragged shard/word padding."""
    from pilosa_tpu.ops import bsi as bsiops

    depth, s, w = 6, 3, 640  # pads S->8 and W->1024
    rng = np.random.default_rng(11)
    vals = rng.integers(0, 2**depth, size=(s, w * 32), dtype=np.int64)
    planes = np.stack([
        np.packbits(((vals >> i) & 1).astype(np.uint8), axis=-1,
                    bitorder="little").view(np.uint32).reshape(s, w)
        for i in range(depth)]).astype(np.uint32)
    exists = np.full((s, w), 0xFFFFFFFF, dtype=np.uint32)
    for op in ("lt", "lte", "gt", "gte", "eq", "neq"):
        for pred in (0, 1, 17, 2**depth - 1):
            bits = bsiops.value_to_bits(pred, depth)
            got = np.asarray(pk.bsi_compare(planes, exists, bits, op))
            expect = np.asarray(bsiops.compare(planes, exists, bits, op))
            np.testing.assert_array_equal(got, expect, err_msg=f"{op} {pred}")


def test_bsi_compare_respects_exists():
    """Columns outside the existence row never match, whatever the op."""
    from pilosa_tpu.ops import bsi as bsiops

    depth, s, w = 4, 2, 512
    planes = RNG.integers(0, 2**32, size=(depth, s, w), dtype=np.uint32)
    exists = RNG.integers(0, 2**32, size=(s, w), dtype=np.uint32)
    bits = bsiops.value_to_bits(5, depth)
    for op in ("lt", "gte", "neq"):
        got = np.asarray(pk.bsi_compare(planes, exists, bits, op))
        assert not np.any(got & ~exists)
        np.testing.assert_array_equal(
            got, np.asarray(bsiops.compare(planes, exists, bits, op)))


def test_bsi_sum_counts_parity():
    """Packed [depth+1, S] per-plane counts + filter count in one kernel
    vs the XLA sum_counts row layout."""
    from pilosa_tpu.ops import bsi as bsiops

    for depth, s, w in ((1, 1, 512), (8, 3, 640), (24, 9, 512)):
        planes = RNG.integers(0, 2**32, size=(depth, s, w), dtype=np.uint32)
        filt = RNG.integers(0, 2**32, size=(s, w), dtype=np.uint32)
        got = np.asarray(pk.bsi_sum_counts(planes, filt))
        expect = np.asarray(bsiops.sum_counts(planes, filt))
        np.testing.assert_array_equal(got, expect)


def test_bsi_sum_counts_depth_cap():
    planes = RNG.integers(0, 2**32, size=(128, 1, 512), dtype=np.uint32)
    filt = RNG.integers(0, 2**32, size=(1, 512), dtype=np.uint32)
    with pytest.raises(ValueError):
        pk.bsi_sum_counts(planes, filt)
