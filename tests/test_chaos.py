"""Chaos suite: seeded failpoint schedules + true crash durability.

The acceptance contract (ISSUE 4): under torn WAL writes, snapshot
corruption, node kills and partial RPC reads across a 3-node cluster, every
query either succeeds with CORRECT results or fails with a clean error
(never silently-wrong data); every fsync-acked write survives SIGKILL; and
once faults stop, the anti-entropy scrubber converges all replicas to
identical block checksums with zero manual intervention.

All tests here are marked `chaos` (tests/conftest.py prints the seed and the
exact fired-failpoint schedule on failure, so any run replays); the long
randomized storm is additionally `slow` and excluded from tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server import Server
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.utils import failpoints

pytestmark = pytest.mark.chaos


def http(method, uri, path, body=None, timeout=20):
    req = urllib.request.Request(uri + path, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else (
        json.dumps(payload).encode() if payload is not None else b"")
    status, out = http("POST", uri, path, body)
    return status, json.loads(out) if out else {}


def wait_until(fn, timeout=20.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except Exception:  # noqa: BLE001 — condition not ready yet
            pass
        time.sleep(interval)
    return False


# -- true crash durability (SIGKILL mid-write, wal-fsync=always) ------------

CRASH_WRITER = r"""
import sys
from pilosa_tpu.storage.fragment import Fragment

# wal_fsync comes from PILOSA_TPU_WAL_FSYNC=always in the environment —
# the documented override path, exactly what an operator would set
f = Fragment(sys.argv[1], "i", "f", "standard", 0).open()
assert f.wal_fsync is True
col = 0
while True:  # parent SIGKILLs us mid-stream
    f.set_bit(col % 7, col)
    # the ACK line prints ONLY after set_bit returned, i.e. after the
    # framed record was written AND fsynced: everything acked must survive
    print(f"ACK {col % 7} {col}", flush=True)
    col += 1
"""


def test_sigkill_mid_write_loses_no_acked_writes(tmp_path):
    """Subprocess crash-durability: SIGKILL a writer mid-stream with
    wal-fsync=always; every acked mutation must be present after reopen,
    and any torn tail damage is truncated, never fatal."""
    script = tmp_path / "writer.py"
    script.write_text(CRASH_WRITER)
    frag_path = str(tmp_path / "data" / "frag")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PILOSA_TPU_WAL_FSYNC="always",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen([sys.executable, str(script), frag_path],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=env)
    acked = []
    try:
        for line in proc.stdout:
            parts = line.split()
            assert parts[0] == b"ACK", line
            acked.append((int(parts[1]), int(parts[2])))
            if len(acked) >= 150:
                # kill mid-write: no shutdown, no flush, no lock release
                os.kill(proc.pid, signal.SIGKILL)
                break
        # drain: lines already in the pipe were also acked pre-kill
        rest, err = proc.communicate(timeout=30)
        for line in rest.splitlines():
            parts = line.split()
            if len(parts) == 3 and parts[0] == b"ACK":
                acked.append((int(parts[1]), int(parts[2])))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert len(acked) >= 150, (acked, err)

    # the dead process released the flock; reopen recovers in-place
    g = Fragment(frag_path, "i", "f", "standard", 0).open()
    missing = [(r, c) for r, c in acked if not g.contains(r, c)]
    assert not missing, f"{len(missing)} acked writes lost: {missing[:5]}"
    # un-acked tail damage (a record torn by the kill) was truncated, not
    # fatal — and at most ONE op can sit past the last ack
    extra = g.bit_count() - len(acked)
    assert 0 <= extra <= 1
    # the store is immediately writable and reopenable again
    g.set_bit(6, 123456)
    g.close()
    h = Fragment(frag_path, "i", "f", "standard", 0).open()
    assert h.wal_truncated_bytes == 0 and h.contains(6, 123456)
    h.close()


CRASH_WRITER_BATCHED = r"""
import sys, threading
from pilosa_tpu.models import Holder
from pilosa_tpu.executor import Executor

h = Holder(sys.argv[1]).open()
idx = h.create_index("i")
idx.create_field("f")
ex = Executor(h)
plock = threading.Lock()
acks = 0

def writer(tid):
    global acks
    col = tid * 1000000
    while True:  # parent SIGKILLs us mid-stream
        cols = list(range(col, col + 5))
        pql = "".join(f"Set({c}, f={tid})" for c in cols)
        ex.execute("i", pql)
        # the ACKs print ONLY after execute() returned, i.e. after the
        # mutations' batch was group-committed AND fsynced (wal-fsync=
        # always): everything acked must survive the kill
        with plock:
            for c in cols:
                print(f"ACK {tid} {c}", flush=True)
            acks += len(cols)
            if 120 <= acks < 125:
                s = ex.ingest_snapshot()
                print(f"STATS {s['mutations']} {s['walAppends']}",
                      flush=True)
        col += 5

# concurrent writers so the batcher actually coalesces under the
# fragment-lock-serialized applies (the self-clocked group commit)
ts = [threading.Thread(target=writer, args=(t,), daemon=True)
      for t in range(4)]
for t in ts:
    t.start()
for t in ts:
    t.join()
"""


def test_sigkill_mid_batched_ingest_loses_no_acked_writes(tmp_path):
    """Batched-ingest crash durability (ISSUE 16): SIGKILL a process
    running 4 concurrent writers through the coalesced executor write
    path with wal-fsync=always. Every fsync-acked mutation must be
    present after reopen — the group commit is all-or-nothing per batch,
    and torn tails truncate like any per-bit append."""
    script = tmp_path / "writer.py"
    script.write_text(CRASH_WRITER_BATCHED)
    data_dir = str(tmp_path / "data")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PILOSA_TPU_WAL_FSYNC="always",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("PILOSA_TPU_INGEST", None)  # batched path on
    proc = subprocess.Popen([sys.executable, str(script), data_dir],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=env)
    acked = []
    stats = None
    err = b""
    try:
        for line in proc.stdout:
            parts = line.split()
            if parts[0] == b"STATS":
                stats = (int(parts[1]), int(parts[2]))
                continue
            assert parts[0] == b"ACK", line
            acked.append((int(parts[1]), int(parts[2])))
            if len(acked) >= 200:
                os.kill(proc.pid, signal.SIGKILL)
                break
        rest, err = proc.communicate(timeout=30)
        for line in rest.splitlines():
            parts = line.split()
            if len(parts) == 3 and parts[0] == b"ACK":
                acked.append((int(parts[1]), int(parts[2])))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert len(acked) >= 200, (acked, err)
    # the batched plane really served the acks, and group commit really
    # coalesced: strictly fewer fsync-able WAL appends than mutations
    # (per-bit would pay 2x mutations, counting mark_exists)
    assert stats is not None and stats[0] >= 120 and stats[1] < stats[0]

    from pilosa_tpu.models import Holder

    h = Holder(data_dir).open()
    from pilosa_tpu.executor import Executor
    ex = Executor(h)
    present = {tid: set(ex.execute("i", f"Row(f={tid})")[0].columns())
               for tid in range(4)}
    missing = [(r, c) for r, c in acked if c not in present[r]]
    assert not missing, f"{len(missing)} acked writes lost: {missing[:5]}"
    # acked columns are also existence-tracked (mark_exists rode the
    # same group commit)
    exist = set(ex.execute("i", "Not(Row(f=99))")[0].columns())
    assert all(c in exist for _r, c in acked)
    # immediately writable and durable again after recovery
    assert ex.execute("i", "Set(999999, f=0)") == [True]
    h.close()
    h2 = Holder(data_dir).open()
    ex2 = Executor(h2)
    assert 999999 in set(ex2.execute("i", "Row(f=0)")[0].columns())
    h2.close()


# -- 3-node cluster chaos ---------------------------------------------------


@pytest.fixture
def cluster3(tmp_path):
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    yield servers
    failpoints.reset()  # never tear servers down with faults still armed
    for s in servers:
        s.close()


def _seed_corpus(s0):
    """Rows 1..3 x 10 cols in each of shards 0..3, written cleanly before
    any fault is armed. Returns the per-row expected count."""
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    for shard in range(4):
        for row in (1, 2, 3):
            for k in range(10):
                col = shard * SHARD_WIDTH + row * 100 + k
                status, out = jpost(s0.uri, "/index/i/query",
                                    raw=f"Set({col}, f={row})".encode())
                assert status == 200 and out["results"] == [True], out
    return 40  # 4 shards x 10 cols per row


def _converged(servers) -> bool:
    """All co-owned fragments carry identical block checksums."""
    sums = []
    for s in servers:
        m = {}
        for iname, fname, vname, shard, frag in s.holder.walk_fragments():
            if not s.cluster.owns_shard(s.node_id, iname, shard):
                continue
            m[(iname, fname, vname, shard)] = \
                {b: c.hex() for b, c in frag.blocks()}
        sums.append(m)
    shared_any = False
    for i in range(len(sums)):
        for j in range(i + 1, len(sums)):
            shared = set(sums[i]) & set(sums[j])
            shared_any |= bool(shared)
            for key in shared:
                if sums[i][key] != sums[j][key]:
                    return False
    return shared_any


def _chaos_storm(cluster3, seed, rate, n_queries, n_writes,
                 kill_node=True):
    s0, s1, s2 = cluster3
    expected = _seed_corpus(s0)
    live = [s0, s1]

    failpoints.arm_chaos(seed, rate=rate, points={
        "storage.wal.append",   # torn WAL writes
        "net.client.send",      # fan-out RPC failures
        "net.client.read",      # partial RPC reads
        "executor.fanout",      # injected remote-shard failures
        "http.server.dispatch",  # server-side 500s
    })

    acked_writes = []
    bad = []
    for i in range(max(n_queries, n_writes)):
        src = live[i % 2]
        if i < n_writes:
            # writes target row 9 in the EXISTING shards (no new-shard
            # announcements in play: eventual shard visibility is a
            # separate, documented semantic)
            col = (i % 4) * SHARD_WIDTH + 900 + i
            status, out = jpost(src.uri, "/index/i/query",
                                raw=f"Set({col}, f=9)".encode())
            if status == 200 and out.get("results") == [True]:
                acked_writes.append(col)
            elif status == 200:
                bad.append(("write-200-notrue", out))
            elif "error" not in out:
                bad.append(("write-error-shape", status, out))
        if i < n_queries:
            row = 1 + (i % 3)
            status, out = jpost(src.uri, "/index/i/query",
                                raw=f"Count(Row(f={row}))".encode())
            if status == 200:
                # THE invariant: a successful answer is never wrong data
                if out["results"] != [expected]:
                    bad.append(("wrong-count", row, out["results"]))
            elif "error" not in out:
                bad.append(("error-shape", status, out))
        if kill_node and i == max(n_queries, n_writes) // 2:
            # mid-storm node crash (SIGKILL analog: sockets die, no
            # goodbye); queries keep routing to the surviving replica
            s2.http.close()
    assert not bad, bad

    # faults stop; the scrubber converges the survivors with zero manual
    # intervention (paced scrub passes, exactly what the ticker would run)
    failpoints.reset()
    for s in live:
        s.anti_entropy_pace = 0.0

    def settle():
        for s in live:
            s.scrub_pass()
        return _converged(live)

    assert wait_until(settle, timeout=60.0, interval=0.2), \
        "replicas did not converge to identical block checksums"

    # every acked write survived the storm, on every surviving node
    for s in live:
        status, out = jpost(s.uri, "/index/i/query", raw=b"Row(f=9)")
        assert status == 200
        cols = set(out["results"][0]["columns"])
        missing = [c for c in acked_writes if c not in cols]
        assert not missing, f"acked writes lost on {s.node_id}: {missing}"
        for row in (1, 2, 3):
            status, out = jpost(s.uri, "/index/i/query",
                                raw=f"Count(Row(f={row}))".encode())
            assert status == 200 and out["results"] == [expected]


def test_chaos_storm_3node_seeded(cluster3):
    """Tier-1 fast storm: fixed seed, moderate rate, ~40 operations."""
    _chaos_storm(cluster3, seed=20250804, rate=0.08,
                 n_queries=40, n_writes=24)


@pytest.mark.slow
def test_chaos_storm_3node_long(cluster3):
    """Long randomized schedule (still seeded — CI can override via
    PILOSA_TPU_CHAOS_SEED for exploratory runs; failures print the seed)."""
    seed = int(os.environ.get("PILOSA_TPU_CHAOS_SEED", "987654321"))
    _chaos_storm(cluster3, seed=seed, rate=0.2,
                 n_queries=200, n_writes=120)


def test_corrupt_snapshot_rebuilt_from_replica(cluster3):
    """Bit-rot on one replica's snapshot: reopen quarantines the file and
    comes up empty; one scrubber pass rebuilds the fragment from a live
    replica over the full-snapshot retrieval path and re-persists it."""
    s0, s1, s2 = cluster3
    _seed_corpus(s0)
    # pick a node+shard it owns, with a replica elsewhere
    victim, frag = None, None
    for s in cluster3:
        v = s.holder.index("i").field("f").view("standard")
        for shard, fr in (v.fragments.items() if v else []):
            owners = {n.id for n in s.cluster.shard_nodes("i", shard)}
            if s.node_id in owners and len(owners) > 1 and fr.bit_count():
                victim, frag = s, fr
                break
        if frag is not None:
            break
    assert frag is not None
    before = frag.bit_count()

    frag.snapshot()  # persist, then rot a payload byte on disk
    frag.close()
    size = os.path.getsize(frag.path)
    with open(frag.path, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))
    frag.open()

    # quarantined, emptied, flagged — the node is up, data awaits rebuild
    assert frag.quarantine_path and os.path.exists(frag.quarantine_path)
    assert frag.needs_rebuild and frag.bit_count() == 0
    assert victim.holder.damaged_fragments()[0]["needsRebuild"]

    rebuilt = victim.repair_quarantined()
    assert rebuilt == 1
    assert frag.rebuilt_from and not frag.needs_rebuild
    assert frag.bit_count() == before
    # durable again: the rebuilt fragment reopens clean with its trailer
    frag.close()
    frag.open()
    assert frag.quarantine_path is None and frag.bit_count() == before
    # and the corrupt original is preserved for forensics
    assert any(p.startswith(os.path.basename(frag.path) + ".corrupt-")
               for p in os.listdir(os.path.dirname(frag.path)))


def test_scrub_pass_counters_and_debug_vars(cluster3):
    """The scrubber surfaces its work: antiEntropy counters on /debug/vars
    + /metrics, and failpoint counters appear once armed."""
    s0, _, _ = cluster3
    _seed_corpus(s0)
    s0.scrub_pass()
    status, out = http("GET", s0.uri, "/debug/vars")
    assert status == 200
    snap = json.loads(out)
    assert snap["counts"]["antiEntropy/passes"] >= 1
    assert "antiEntropy/lastPassSeconds" in snap["gauges"]
    # fire a failpoint, then check both surfaces
    with failpoints.failpoint("executor.fanout", "raise", times=1):
        try:
            failpoints.hit("executor.fanout")
        except failpoints.FailpointError:
            pass
    status, out = http("GET", s0.uri, "/debug/vars")
    snap = json.loads(out)
    assert snap["failpoints"]["points"]["executor.fanout"]["fired"] == 1
    status, out = http("GET", s0.uri, "/metrics")
    assert status == 200
    assert b"failpoints" in out and b"antiEntropy" in out
