"""Data model tests: holder/index/field/view tree, field types, time views,
Row algebra, persistence across reopen.

Mirrors holder_test.go / index_test.go / field_test.go / view tests.
"""

from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu.constants import EXISTENCE_FIELD_NAME, SHARD_WIDTH
from pilosa_tpu.models import Field, FieldOptions, FieldType, Holder, Row
from pilosa_tpu.models.timequantum import views_by_time, views_by_time_range


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    yield h
    h.close()


def test_holder_index_lifecycle(holder):
    idx = holder.create_index("i")
    assert holder.index("i") is idx
    with pytest.raises(ValueError):
        holder.create_index("i")
    with pytest.raises(ValueError):
        holder.create_index("Bad Name!")
    holder.delete_index("i")
    assert holder.index("i") is None


def test_set_field_write_read(holder):
    idx = holder.create_index("i")
    f = idx.create_field("f")
    assert f.set_bit(10, 100)
    assert not f.set_bit(10, 100)
    f.set_bit(10, SHARD_WIDTH + 5)  # second shard
    row = f.row(10)
    assert row.columns().tolist() == [100, SHARD_WIDTH + 5]
    assert f.shards() == [0, 1]
    assert idx.available_shards().slice().tolist() == [0, 1]


def test_persistence_across_reopen(tmp_path):
    h = Holder(str(tmp_path / "d")).open()
    idx = h.create_index("i", keys=False)
    f = idx.create_field("f", FieldOptions(type=FieldType.SET, cache_size=100))
    f.set_bit(3, 7)
    g = idx.create_field("n", FieldOptions(type=FieldType.INT, min=-10, max=100))
    g.set_value(5, 42)
    h.close()

    h2 = Holder(str(tmp_path / "d")).open()
    idx2 = h2.index("i")
    assert idx2 is not None
    f2 = idx2.field("f")
    assert f2.options.cache_size == 100
    assert f2.row(3).columns().tolist() == [7]
    g2 = idx2.field("n")
    assert g2.options.min == -10 and g2.options.max == 100
    assert g2.value(5) == (42, True)
    h2.close()


def test_int_field_bsi(holder):
    idx = holder.create_index("i")
    f = idx.create_field("v", FieldOptions(type=FieldType.INT, min=-100, max=1000))
    assert f.bit_depth == (1100).bit_length()
    f.set_value(1, -100)
    f.set_value(2, 0)
    f.set_value(3, 1000)
    assert f.value(1) == (-100, True)
    assert f.value(2) == (0, True)
    assert f.value(3) == (1000, True)
    assert f.value(4) == (0, False)
    with pytest.raises(ValueError):
        f.set_value(1, 1001)
    f.clear_value(3)
    assert f.value(3) == (0, False)
    with pytest.raises(ValueError):
        f.set_bit(0, 0)  # set_bit invalid on int fields


def test_mutex_field(holder):
    idx = holder.create_index("i")
    f = idx.create_field("m", FieldOptions(type=FieldType.MUTEX))
    f.set_bit(1, 50)
    f.set_bit(2, 50)  # must clear row 1 for column 50
    assert f.row(1).columns().size == 0
    assert f.row(2).columns().tolist() == [50]


def test_mutex_write_cost_independent_of_row_count(holder):
    """Mutex clear-other-rows is a column probe (fragment.go:2446-2455
    rowsVector.Get), not a per-row scan: only containers in the written
    column's 64K chunk are membership-tested, so rows whose bits live in
    other chunks cost nothing."""
    idx = holder.create_index("i")
    f = idx.create_field("m", FieldOptions(type=FieldType.MUTEX))
    # 200 rows with bits ONLY in column chunk 1 (columns >= 65536)
    f.import_bits(list(range(200)), [70_000] * 200)
    frag = f.views["standard"].fragment(0)
    probes = 0
    orig = frag.storage.contains

    def counting(v):
        nonlocal probes
        probes += 1
        return orig(v)

    frag.storage.contains = counting
    f.set_bit(5, 10)     # column chunk 0: none of the 200 containers match
    # exactly one probe: add()'s own changed-check — zero column-probe work
    assert probes == 1
    f.set_bit(6, 70_000)  # chunk 1: probes candidates, clears all 200
    frag.storage.contains = orig
    assert f.row(6).columns().tolist() == [70_000]
    for rid in range(200):
        if rid not in (5, 6):
            assert f.row(rid).columns().size == 0
    assert f.row(5).columns().tolist() == [10]


def test_bool_field(holder):
    idx = holder.create_index("i")
    f = idx.create_field("b", FieldOptions(type=FieldType.BOOL))
    f.set_bit(1, 9)
    f.set_bit(0, 9)
    assert f.row(1).columns().size == 0
    assert f.row(0).columns().tolist() == [9]
    with pytest.raises(ValueError):
        f.set_bit(2, 9)


def test_time_field_views_and_range(holder):
    idx = holder.create_index("i")
    f = idx.create_field("t", FieldOptions(type=FieldType.TIME, time_quantum="YMD"))
    t1 = datetime(2018, 1, 2)
    t2 = datetime(2018, 2, 3)
    f.set_bit(1, 10, timestamp=t1)
    f.set_bit(1, 20, timestamp=t2)
    # standard view has both
    assert f.row(1).columns().tolist() == [10, 20]
    # range covering only January
    r = f.row_time(1, datetime(2018, 1, 1), datetime(2018, 2, 1))
    assert r.columns().tolist() == [10]
    r = f.row_time(1, datetime(2018, 1, 1), datetime(2018, 3, 1))
    assert r.columns().tolist() == [10, 20]


def test_views_by_time():
    t = datetime(2018, 1, 2, 3)
    assert views_by_time("standard", t, "YMDH") == [
        "standard_2018", "standard_201801", "standard_20180102", "standard_2018010203"]


def test_views_by_time_range_minimal_cover():
    # feb..april exactly = 2 monthly views + partial via days
    got = views_by_time_range("standard", datetime(2018, 2, 1), datetime(2018, 4, 1), "YMD")
    assert got == ["standard_201802", "standard_201803"]
    # full year plus one day each side
    got = views_by_time_range("standard", datetime(2017, 12, 31), datetime(2019, 1, 2), "YMD")
    assert "standard_2018" in got
    assert "standard_20171231" in got and "standard_20190101" in got
    assert len(got) == 3
    # sub-day ranges need H
    got = views_by_time_range("standard", datetime(2018, 1, 1, 5), datetime(2018, 1, 1, 7), "YMDH")
    assert got == ["standard_2018010105", "standard_2018010106"]


def test_existence_field(holder):
    idx = holder.create_index("i", track_existence=True)
    assert idx.existence_field() is not None
    idx.mark_exists(42)
    assert idx.existence_field().row(0).columns().tolist() == [42]
    # existence field hidden from schema
    names = [f["name"] for f in idx.schema_dict()["fields"]]
    assert EXISTENCE_FIELD_NAME not in names


def test_import_bits_and_values(holder):
    idx = holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1, 1, 2], [5, SHARD_WIDTH + 1, 9])
    assert f.row(1).columns().tolist() == [5, SHARD_WIDTH + 1]
    assert f.row(2).columns().tolist() == [9]
    g = idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=1000))
    g.import_values([1, 2, 3], [10, 20, 30])
    assert g.value(2) == (20, True)


def test_row_algebra():
    a = Row(np.array([1, 5, SHARD_WIDTH + 3]))
    b = Row(np.array([5, 9, SHARD_WIDTH + 3, 2 * SHARD_WIDTH]))
    assert a.intersect(b).columns().tolist() == [5, SHARD_WIDTH + 3]
    assert a.union(b).columns().tolist() == [1, 5, 9, SHARD_WIDTH + 3, 2 * SHARD_WIDTH]
    assert a.difference(b).columns().tolist() == [1]
    assert sorted(a.xor(b).columns().tolist()) == [1, 9, 2 * SHARD_WIDTH]
    assert a.intersection_count(b) == 2
    assert a.includes(5) and not a.includes(9)
    assert a.count() == 3
    m = Row.from_segment(0, np.array([1])).merge(Row.from_segment(1, np.array([SHARD_WIDTH + 1])))
    assert m.columns().tolist() == [1, SHARD_WIDTH + 1]


def test_rank_cache_update(holder):
    idx = holder.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=10))
    for c in range(20):
        f.set_bit(1, c)
    f.set_bit(2, 0)
    v = f.view()
    cache = v.rank_caches[0]
    top = cache.top(2)
    assert top[0] == (1, 20)
    assert top[1] == (2, 1)


# -- cache types (cache.go:58-130 lru, :461 nop; field option cacheType) -----

def test_lru_cache_evicts_by_recency():
    from pilosa_tpu.models.cache import LRUCache
    c = LRUCache(cache_size=3)
    c.add(1, 10)
    c.add(2, 20)
    c.add(3, 30)
    c.add(1, 11)      # touch 1 -> 2 is now least recent
    c.add(4, 40)
    assert sorted(c.ids()) == [1, 3, 4]
    assert c.top() == [(4, 40), (3, 30), (1, 11)]


def test_nop_cache_tracks_nothing():
    from pilosa_tpu.models.cache import NopCache
    c = NopCache(cache_size=3)
    c.add(1, 10)
    c.bulk_add([(2, 5)])
    assert len(c) == 0 and c.ids() == [] and c.top() == []


def test_cache_persistence_dispatches_on_type(tmp_path):
    from pilosa_tpu.models.cache import LRUCache, load_cache
    c = LRUCache(cache_size=5)
    c.add(7, 70)
    p = str(tmp_path / "x.cache")
    c.save(p)
    loaded = load_cache(p)
    assert isinstance(loaded, LRUCache)
    assert loaded.top() == [(7, 70)]


def test_field_cache_type_options(tmp_path):
    from pilosa_tpu.models.cache import LRUCache, NopCache
    from pilosa_tpu.models.field import Field, FieldOptions
    import pytest as _pytest

    f = Field(str(tmp_path / "f"), "i", "f",
              FieldOptions(cache_type="lru", cache_size=10)).open()
    f.set_bit(1, 5)
    v = f.view("standard")
    assert isinstance(v.rank_caches[0], LRUCache)
    f.close()

    g = Field(str(tmp_path / "g"), "i", "g",
              FieldOptions(cache_type="none")).open()
    g.set_bit(1, 5)
    assert g.view("standard").rank_caches == {}
    g.close()

    with _pytest.raises(ValueError):
        FieldOptions(cache_type="bogus").validate()


def test_mutex_bulk_import_one_row_per_column(tmp_path):
    """Bulk import on a mutex field keeps the one-row-per-column invariant:
    last write wins and prior rows' bits are cleared (bulkImportMutex,
    fragment.go:1535-1622)."""
    h = Holder(str(tmp_path / "d")).open()
    idx = h.create_index("i")
    f = idx.create_field("m", FieldOptions(type=FieldType.MUTEX))
    f.set_bit(1, 5)
    f.set_bit(2, 6)
    # bulk: col 5 -> row 3 (must clear row 1's bit), col 6 -> row 2 twice,
    # col 7 -> row 1 then row 2 in the same batch (last wins)
    f.import_bits([3, 2, 1, 2], [5, 6, 7, 7])
    assert f.row(1).columns().tolist() == []
    assert f.row(2).columns().tolist() == [6, 7]
    assert f.row(3).columns().tolist() == [5]
    h.close()


def test_available_shards_memo_field_recreate(tmp_path):
    """The shard-fanout memo must not serve a deleted field's shard list
    after delete+recreate (a fresh Field restarts shards_version at 0,
    colliding with the old version without the schema epoch)."""
    from pilosa_tpu.models import Holder

    h = Holder(str(tmp_path / "d")).open()
    try:
        idx = h.create_index("m", track_existence=False)
        f = idx.create_field("f")
        f.import_bits([1], [5 * 1048576 + 3])  # shard 5
        assert idx.available_shards_list() == [5]
        idx.delete_field("f")
        f2 = idx.create_field("f")
        f2.import_bits([1], [9 * 1048576 + 3])  # shard 9
        assert idx.available_shards_list() == [9]
    finally:
        h.close()
