"""Protobuf wire codec tests: serializer roundtrips + HTTP content
negotiation end-to-end (reference: encoding/proto/proto.go,
http/handler.go:915-988)."""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.encoding.protobuf import CONTENT_TYPE, Serializer
from pilosa_tpu.executor import GroupCounts, Pairs, RowIdentifiers, ValCount
from pilosa_tpu.models.row import Row
from pilosa_tpu.server import Server


@pytest.fixture(scope="module")
def ser():
    return Serializer()


def test_query_request_roundtrip(ser):
    data = ser.encode_query_request("Count(Row(f=1))", shards=[0, 3], remote=True)
    req = ser.decode_query_request(data)
    assert req["query"] == "Count(Row(f=1))"
    assert req["shards"] == [0, 3]
    assert req["remote"] is True


def test_result_roundtrip_all_types(ser):
    row = Row(np.array([1, 5, 2**20 + 3], dtype=np.uint64))
    row.attrs = {"name": "x", "n": 7, "ok": True, "score": 1.5}
    results = [
        row,
        Pairs([(10, 100), (20, 50)]),
        ValCount(42, 3),
        7,               # Count
        True,            # Set
        RowIdentifiers([1, 2, 3]),
        GroupCounts([{"group": [{"field": "f", "rowID": 4}], "count": 9}]),
        None,
    ]
    data = ser.encode_query_response(results)
    out = ser.decode_query_response(data)
    assert out["err"] == ""
    dec = out["results"]
    assert list(dec[0].columns()) == [1, 5, 2**20 + 3]
    assert dec[0].attrs == {"name": "x", "n": 7, "ok": True, "score": 1.5}
    assert dec[1] == [(10, 100), (20, 50)]
    assert dec[2] == ValCount(42, 3)
    assert dec[3] == 7
    assert dec[4] is True
    assert dec[5] == [1, 2, 3]
    assert dec[6] == [{"group": [{"field": "f", "rowID": 4}], "count": 9}]
    assert dec[7] is None


def test_keyed_result_roundtrip(ser):
    """Pair.Key / RowIdentifiers.Keys / FieldRow.RowKey survive the wire
    (internal/public.proto Pair; executor.go:2497-2590 translateResult)."""
    pairs = Pairs([(10, 100), (20, 50)])
    pairs.row_keys = ["hot", "cold"]
    rows = RowIdentifiers()
    rows.row_keys = ["a", "b"]
    gcs = GroupCounts([
        {"group": [{"field": "f", "rowKey": "hot"},
                   {"field": "g", "rowID": 2}], "count": 9}])
    data = ser.encode_query_response([pairs, rows, gcs])
    dec = ser.decode_query_response(data)["results"]
    assert dec[0] == [(10, 100), (20, 50)]
    assert dec[0].row_keys == ["hot", "cold"]
    assert dec[1] == [] and dec[1].row_keys == ["a", "b"]
    assert dec[2] == [{"group": [{"field": "f", "rowKey": "hot"},
                                 {"field": "g", "rowID": 2}], "count": 9}]


def test_import_request_roundtrip(ser):
    data = ser.encode_import_request("i", "f", shard=2, row_ids=[1, 2],
                                     column_ids=[10, 20], timestamps=[0, 5])
    req = ser.decode_import_request(data)
    assert req["index"] == "i" and req["field"] == "f" and req["shard"] == 2
    assert req["rowIDs"] == [1, 2]
    assert req["columnIDs"] == [10, 20]
    assert req["timestamps"] == [0, 5]

    data = ser.encode_import_value_request("i", "v", column_ids=[3], values=[-7])
    req = ser.decode_import_value_request(data)
    assert req["columnIDs"] == [3] and req["values"] == [-7]

    data = ser.encode_import_roaring_request({"standard": b"\x01\x02"}, clear=True)
    req = ser.decode_import_roaring_request(data)
    assert req["clear"] is True and req["views"] == {"standard": b"\x01\x02"}


def test_translate_keys_roundtrip(ser):
    data = ser.encode_translate_keys_request("i", None, ["a", "b"])
    req = ser.decode_translate_keys_request(data)
    assert req == {"index": "i", "field": None, "keys": ["a", "b"]}
    ids = ser.decode_translate_keys_response(
        ser.encode_translate_keys_response([4, 5]))
    assert ids == [4, 5]


# ---------------------------------------------------------------- end-to-end


@pytest.fixture
def server(tmp_path):
    s = Server(str(tmp_path / "node"), port=0).open()
    yield s
    s.close()


def _req(uri, path, body=None, method="POST", headers=None):
    req = urllib.request.Request(uri + path, data=body, method=method,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_http_protobuf_negotiation(server, ser):
    u = server.uri
    _req(u, "/index/i", json.dumps({"options": {}}).encode())
    _req(u, "/index/i/field/f",
         json.dumps({"options": {"type": "set"}}).encode())

    # import over protobuf
    body = ser.encode_import_request("i", "f", row_ids=[1, 1, 2],
                                     column_ids=[10, 20, 10])
    status, _, _ = _req(u, "/index/i/field/f/import", body,
                        headers={"Content-Type": CONTENT_TYPE})
    assert status == 200

    # protobuf request + protobuf response
    qbody = ser.encode_query_request("Count(Row(f=1))")
    status, ctype, out = _req(u, "/index/i/query", qbody,
                              headers={"Content-Type": CONTENT_TYPE,
                                       "Accept": CONTENT_TYPE})
    assert status == 200 and ctype == CONTENT_TYPE
    resp = ser.decode_query_response(out)
    assert resp["results"] == [2]

    # JSON request + protobuf response (Accept only)
    status, ctype, out = _req(u, "/index/i/query", b"Row(f=1)",
                              headers={"Accept": CONTENT_TYPE})
    assert ctype == CONTENT_TYPE
    resp = ser.decode_query_response(out)
    assert list(resp["results"][0].columns()) == [10, 20]

    # JSON path still default
    status, ctype, out = _req(u, "/index/i/query", b"Count(Row(f=2))")
    assert ctype == "application/json"
    assert json.loads(out)["results"] == [1]


def test_http_protobuf_value_import(server, ser):
    u = server.uri
    _req(u, "/index/i", json.dumps({"options": {}}).encode())
    _req(u, "/index/i/field/v",
         json.dumps({"options": {"type": "int", "min": -100, "max": 100}}).encode())
    body = ser.encode_import_value_request("i", "v", column_ids=[1, 2, 3],
                                           values=[5, -7, 30])
    status, _, _ = _req(u, "/index/i/field/v/import", body,
                        headers={"Content-Type": CONTENT_TYPE})
    assert status == 200
    _, _, out = _req(u, "/index/i/query", b"Sum(field=v)")
    assert json.loads(out)["results"][0] == {"value": 28, "count": 3}


def test_protobuf_error_response(server, ser):
    """Errors reach protobuf clients as QueryResponse{Err}, not JSON
    (proto.go error encoding; handler negotiation)."""
    import urllib.error
    u = server.uri
    _req(u, "/index/i", json.dumps({"options": {}}).encode())
    qbody = ser.encode_query_request("Bogus(")
    try:
        _req(u, "/index/i/query", qbody,
             headers={"Content-Type": CONTENT_TYPE, "Accept": CONTENT_TYPE})
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as e:
        assert e.headers.get("Content-Type") == CONTENT_TYPE
        resp = ser.decode_query_response(e.read())
        assert resp["err"]


def test_column_attr_sets_roundtrip_with_keys():
    ser = Serializer()
    cas = [{"id": 5, "attrs": {"city": "ankh"}, "key": "alice"},
           {"id": 6, "attrs": {"n": 2}}]
    blob = ser.encode_query_response([], column_attr_sets=cas)
    dec = ser.decode_query_response(blob)
    assert dec["columnAttrSets"] == [
        {"id": 5, "attrs": {"city": "ankh"}, "key": "alice"},
        {"id": 6, "attrs": {"n": 2}},
    ]
