"""SWIM gossip transport: convergence, failure detection, refutation.

Mirrors the memberlist behaviors the reference relies on
(gossip/gossip.go:42-541): join via seed push-pull, probe/ack liveness,
suspect -> dead expiry, incarnation-bump refutation, piggyback spread.
Timings are shrunk ~20x; assertions poll with generous deadlines so load
spikes don't flake them.
"""

import json
import socket
import time

import pytest

from pilosa_tpu.parallel.gossip import (
    ALIVE,
    DEAD,
    SUSPECT,
    Gossip,
    GossipConfig,
    Member,
)

FAST = dict(period=0.05, probe_timeout=0.05, push_pull_interval=0.3,
            suspicion_mult=3.0)


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def make_cluster(n, **overrides):
    cfg = GossipConfig(**{**FAST, **overrides})
    nodes = [Gossip(f"n{i}", config=GossipConfig(**{**FAST, **overrides}))
             for i in range(n)]
    seed = (nodes[0].host, nodes[0].port)
    for i, g in enumerate(nodes):
        g.open(seeds=[seed] if i else [])
    del cfg
    return nodes


def close_all(nodes):
    for g in nodes:
        try:
            g.close()
        except OSError:
            pass


def alive_ids(g):
    return {m.id for m in g.members(state=ALIVE)}


def test_join_and_full_convergence():
    nodes = make_cluster(4)
    try:
        want = {f"n{i}" for i in range(4)}
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 msg="all 4 nodes alive everywhere")
    finally:
        close_all(nodes)


def test_dead_node_detected_and_spread():
    events = []
    nodes = make_cluster(4)
    nodes[1].on_dead = lambda m: events.append(m.id)
    try:
        want = {f"n{i}" for i in range(4)}
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 msg="initial convergence")
        nodes[3].close()  # hard kill: socket gone, no acks ever again
        wait_for(lambda: all(
            "n3" in {m.id for m in g.members(state=DEAD)}
            for g in nodes[:3]), timeout=20.0,
            msg="n3 marked dead on every survivor")
        assert "n3" in events  # callback fired, not just state flipped
        assert all("n3" not in alive_ids(g) for g in nodes[:3])
    finally:
        close_all(nodes[:3])


def test_refutation_keeps_slow_node_alive():
    """A false suspicion about a LIVE node must be refuted by an
    incarnation bump, not expire to dead (the slow-vs-dead distinction
    that motivates SWIM)."""
    nodes = make_cluster(3)
    try:
        want = {"n0", "n1", "n2"}
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 msg="initial convergence")
        inc0 = nodes[2].incarnation
        # inject a rumor: n2 is suspect (as if a partitioned node said so)
        rumor = {"t": "ping", "seq": 999999, "from": "liar", "updates": [
            {"id": "n2", "host": nodes[2].host, "port": nodes[2].port,
             "state": SUSPECT, "inc": inc0}]}
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for g in nodes:
            s.sendto(json.dumps(rumor).encode(), (g.host, g.port))
        s.close()
        wait_for(lambda: nodes[2].incarnation > inc0,
                 msg="n2 refutes by bumping incarnation")
        # the refutation must win: n2 stays/returns alive everywhere and
        # never expires to dead
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 timeout=20.0, msg="n2 alive everywhere after refutation")
        time.sleep(0.5)  # well past the suspicion window at FAST timings
        assert all("n2" not in {m.id for m in g.members(state=DEAD)}
                   for g in nodes)
    finally:
        close_all(nodes)


def test_meta_broadcast_reaches_all():
    nodes = make_cluster(3)
    try:
        want = {"n0", "n1", "n2"}
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 msg="initial convergence")
        nodes[1].broadcast_meta({"uri": "http://node1:10101"})

        def got_meta(g):
            for m in g.members():
                if m.id == "n1" and m.meta.get("uri") == "http://node1:10101":
                    return True
            return False

        wait_for(lambda: got_meta(nodes[0]) and got_meta(nodes[2]),
                 msg="meta gossiped to non-origin nodes")
    finally:
        close_all(nodes)


class TestOverrideRules:
    """_apply_update implements SWIM's precedence table; drive it directly."""

    def make(self):
        g = Gossip("me")
        g._members["x"] = Member("x", "127.0.0.1", 1, ALIVE, 5)
        return g

    def apply(self, g, state, inc):
        g._apply_update({"id": "x", "host": "127.0.0.1", "port": 1,
                         "state": state, "inc": inc})
        return g._members["x"]

    def test_stale_alive_loses(self):
        g = self.make()
        g._members["x"].state = SUSPECT
        m = self.apply(g, ALIVE, 5)  # same inc: suspicion stands
        assert m.state == SUSPECT
        g._sock.close()

    def test_newer_alive_wins_over_suspect(self):
        g = self.make()
        g._members["x"].state = SUSPECT
        m = self.apply(g, ALIVE, 6)
        assert m.state == ALIVE and m.incarnation == 6
        g._sock.close()

    def test_suspect_beats_alive_at_equal_inc(self):
        g = self.make()
        m = self.apply(g, SUSPECT, 5)
        assert m.state == SUSPECT
        g._sock.close()

    def test_dead_beats_suspect_at_equal_inc(self):
        g = self.make()
        g._members["x"].state = SUSPECT
        m = self.apply(g, DEAD, 5)
        assert m.state == DEAD
        g._sock.close()

    def test_stale_suspect_cannot_displace_dead(self):
        g = self.make()
        g._members["x"].state = DEAD
        m = self.apply(g, SUSPECT, 5)
        assert m.state == DEAD
        g._sock.close()

    def test_unknown_dead_tracked_and_fired(self):
        """A death first heard about via merge (node never seen alive
        locally) must still fire on_dead: the application layer can know
        the node through other membership channels."""
        g = self.make()
        seen = []
        g.on_dead = lambda m: seen.append(m.id)
        g._apply_update({"id": "ghost", "host": "h", "port": 1,
                         "state": DEAD, "inc": 0})
        assert g._members["ghost"].state == DEAD
        assert seen == ["ghost"]
        g._sock.close()

    def test_self_suspicion_refuted(self):
        g = self.make()
        g._apply_update({"id": "me", "host": g.host, "port": g.port,
                         "state": SUSPECT, "inc": 7})
        assert g.incarnation == 8  # outbid the rumor
        q = [json.loads(blob) for blob, _ in g._queue.values()]
        assert any(u["id"] == "me" and u["state"] == ALIVE and u["inc"] == 8
                   for u in q)
        g._sock.close()


# ---------------------------------------------------------------- server glue


def test_server_gossip_membership_and_liveness(tmp_path):
    """Two Servers with NO cluster_hosts discover each other purely via
    gossip (alive-record meta carries the HTTP URI -> NotifyJoin admission,
    gossip/gossip.go:335-342), and a killed node is marked down via
    suspicion expiry instead of the HTTP probe loop."""
    from pilosa_tpu.server import Server

    fast = GossipConfig(**FAST)
    a = Server(str(tmp_path / "a"), port=0, membership_interval=0,
               gossip_port=0, gossip_config=GossipConfig(**FAST)).open()
    try:
        b = Server(str(tmp_path / "b"), port=0, membership_interval=0,
                   gossip_port=0, gossip_config=fast,
                   gossip_seeds=[f"127.0.0.1:{a.gossip.port}"]).open()
        try:
            wait_for(lambda: {n.id for n in a.cluster.nodes} ==
                     {a.node_id, b.node_id} ==
                     {n.id for n in b.cluster.nodes},
                     msg="gossip-discovered membership on both nodes")
            # URIs must come from the gossiped meta, not cluster_hosts
            assert any(n.uri == b.uri for n in a.cluster.nodes)
        finally:
            b.close()
        wait_for(lambda: a.cluster.is_down(b.node_id), timeout=30.0,
                 msg="a marks killed b down via gossip suspicion")
    finally:
        a.close()


def test_parse_seed_forms():
    from pilosa_tpu.parallel.gossip import DEFAULT_PORT, parse_seed
    assert parse_seed("10.0.0.5:7001") == ("10.0.0.5", 7001)
    assert parse_seed("10.0.0.5") == ("10.0.0.5", DEFAULT_PORT)
    assert parse_seed("node-a.local") == ("node-a.local", DEFAULT_PORT)
    assert parse_seed(":7001") == ("127.0.0.1", 7001)
    assert parse_seed("[::1]:7001") == ("::1", 7001)
    assert parse_seed("[fe80::2]") == ("fe80::2", DEFAULT_PORT)
    # unbracketed v6 literals cannot carry a port: whole string is the host
    assert parse_seed("::1") == ("::1", DEFAULT_PORT)
    assert parse_seed("fe80::2") == ("fe80::2", DEFAULT_PORT)
    with pytest.raises(ValueError):
        parse_seed("host:notaport")
    with pytest.raises(ValueError):
        parse_seed("[::1")


def test_falsely_dead_node_heals_via_ack_refutation():
    """A node wrongly marked dead keeps pinging its peers; the peer's ack
    carries the dead rumor back to it, it refutes with an incarnation
    bump, and the peer revives it — no probe of the dead node required
    (dead members are out of the probe ring)."""
    nodes = make_cluster(2)
    a, b = nodes
    try:
        wait_for(lambda: alive_ids(a) == {"n0", "n1"} == alive_ids(b),
                 msg="initial convergence")
        # inject the false rumor into a only: b is dead at inc 0
        a._apply_update({"id": "n1", "host": b.host, "port": b.port,
                         "state": DEAD, "inc": b.incarnation})
        assert "n1" in {m.id for m in a.members(state=DEAD)}
        # b's own pings of a must carry the rumor back and get refuted
        wait_for(lambda: "n1" in alive_ids(a), timeout=15.0,
                 msg="false death healed by ack-carried refutation")
        assert b.incarnation > 0  # the heal was a refutation, not luck
    finally:
        close_all(nodes)


def test_join_retries_after_lost_seed_datagram():
    """The open()-time join is a single UDP datagram; if it is lost the
    protocol loop must re-sync the seeds rather than leave the node a
    permanent gossip island (joinWithRetry, gossip/gossip.go:112-119)."""
    a = Gossip("n0", config=GossipConfig(**FAST))
    a.open()
    b = Gossip("n1", config=GossipConfig(**FAST))
    real_send = b._send
    dropped = []

    def lossy_send(addr, msg):
        if msg.get("t") == "sync" and not dropped:
            dropped.append(msg)  # swallow the first join sync
            return
        real_send(addr, msg)

    b._send = lossy_send
    try:
        b.open(seeds=[(a.host, a.port)])
        assert alive_ids(b) == {"n1"}  # island right after the drop
        wait_for(lambda: alive_ids(a) == {"n0", "n1"} == alive_ids(b),
                 msg="island healed by seed-sync retry")
        assert dropped  # the simulated loss actually happened
    finally:
        close_all([a, b])


def test_gossip_mode_set_coordinator_cluster_wide(tmp_path):
    """set-coordinator under the gossip backend: the broadcast reaches
    gossip-discovered peers over the HTTP control plane, the choice is
    sticky, and a node admitted AFTER the adoption converges via the
    pending-claim + return-heal paths."""
    import json
    import urllib.request

    from pilosa_tpu.server import Server

    fast = GossipConfig(**FAST)
    a = Server(str(tmp_path / "a"), port=0, membership_interval=0,
               gossip_port=0, gossip_config=GossipConfig(**FAST)).open()
    b = Server(str(tmp_path / "b"), port=0, membership_interval=0,
               gossip_port=0, gossip_config=fast,
               gossip_seeds=[f"127.0.0.1:{a.gossip.port}"]).open()
    try:
        wait_for(lambda: {n.id for n in a.cluster.nodes} ==
                 {a.node_id, b.node_id} ==
                 {n.id for n in b.cluster.nodes},
                 msg="gossip-discovered membership")
        # explicitly adopt the NON-default coordinator (highest id)
        target = max(a.node_id, b.node_id)
        req = urllib.request.Request(
            a.uri + "/cluster/resize/set-coordinator",
            data=json.dumps({"id": target}).encode(), method="POST")
        urllib.request.urlopen(req, timeout=10)
        wait_for(lambda: a.cluster.coordinator_id == target
                 and b.cluster.coordinator_id == target,
                 msg="both gossip nodes adopt the explicit coordinator")
        # a third node joins AFTER adoption: it must converge too (it gets
        # the claim via the observers' return-heal push on admission, or
        # adopts on its first membership contact)
        c = Server(str(tmp_path / "c"), port=0, membership_interval=0,
                   gossip_port=0, gossip_config=GossipConfig(**FAST),
                   gossip_seeds=[f"127.0.0.1:{a.gossip.port}"]).open()
        try:
            wait_for(lambda: len(c.cluster.nodes) == 3,
                     msg="third node admitted")
            # push the claim to the newcomer the way a heal would
            a._on_node_return(a.cluster.node_by_id(c.node_id))
            wait_for(lambda: c.cluster.coordinator_id == target,
                     msg="newcomer adopts the explicit coordinator")
        finally:
            c.close()
    finally:
        b.close()
        a.close()


# -- shared-key AES-GCM transport encryption (utils/aesgcm.py) -------------


def test_aesgcm_known_answer_vectors():
    """FIPS-197 / NIST SP 800-38D known answers pin the pure-stdlib
    implementation (the image has no `cryptography` wheel): AES-128 and
    AES-256 single blocks, the GHASH key, and two full GCM cases."""
    from pilosa_tpu.utils.aesgcm import AESGCM, _encrypt_block, _expand_key

    w, nr = _encrypt_block, None  # noqa: F841 — readability
    w, nr = _expand_key(bytes.fromhex("000102030405060708090a0b0c0d0e0f"))
    assert _encrypt_block(
        w, nr, bytes.fromhex("00112233445566778899aabbccddeeff")).hex() \
        == "69c4e0d86a7b0430d8cdb78070b4c55a"  # FIPS-197 C.1
    w, nr = _expand_key(bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f"
        "101112131415161718191a1b1c1d1e1f"))
    assert _encrypt_block(
        w, nr, bytes.fromhex("00112233445566778899aabbccddeeff")).hex() \
        == "8ea2b7ca516745bfeafc49904b496089"  # FIPS-197 C.3
    w, nr = _expand_key(b"\x00" * 16)
    assert _encrypt_block(w, nr, b"\x00" * 16).hex() \
        == "66e94bd4ef8a2c3b884cfa59ca342b2e"  # the GHASH key H
    # GCM test case 2: zero key/IV, one zero block
    g = AESGCM(b"\x00" * 16)
    assert g.encrypt(b"\x00" * 12, b"\x00" * 16).hex() == (
        "0388dace60b6a392f328c2b971b2fe78"
        "ab6e47d42cec13bdf53a67b21257bddf")
    # GCM test case 3: the classic 64-byte message
    key = bytes.fromhex("feffe9928665731c6d6a8f9467308308")
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    pt = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d8a318a72"
        "1c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657ba637b391aafd255")
    assert AESGCM(key).encrypt(iv, pt).hex() == (
        "42831ec2217774244b7221b784d0d49ce3aa212f2c02a4e035c17e2329aca12e"
        "21d514b25466931c7d8f6a5aac84aa051ba30b396a0aac973d58e091473f5985"
        "4d5c2af327cd64a62cf35abd2ba6fab4")


def test_aesgcm_roundtrip_aad_and_tamper():
    from pilosa_tpu.utils.aesgcm import AESGCM, derive_key, open_sealed, seal
    g = AESGCM(derive_key("hush"))
    ct = g.encrypt(b"n" * 12, b"membership state", b"aad")
    assert g.decrypt(b"n" * 12, ct, b"aad") == b"membership state"
    with pytest.raises(ValueError):  # flipped tag bit
        g.decrypt(b"n" * 12, ct[:-1] + bytes([ct[-1] ^ 1]), b"aad")
    with pytest.raises(ValueError):  # wrong AAD
        g.decrypt(b"n" * 12, ct, b"other")
    with pytest.raises(ValueError):  # wrong key
        AESGCM(derive_key("loud")).decrypt(b"n" * 12, ct, b"aad")
    # seal/open datagram framing (version + nonce + ct/tag)
    dg = seal(g, b'{"t": "ping"}')
    assert open_sealed(g, dg) == b'{"t": "ping"}'
    with pytest.raises(ValueError):  # cleartext is never admitted
        open_sealed(g, b'{"t": "ping"}')
    # distinct passphrases derive distinct keys
    from pilosa_tpu.utils.aesgcm import derive_key as dk
    assert dk("a") != dk("b") and len(dk("a")) == 16


def test_encrypted_cluster_converges_and_drops_unkeyed():
    """Nodes sharing the secret converge exactly like cleartext gossip;
    a cleartext datagram (unkeyed sender) is dropped and counted, and an
    injected suspicion rumor from an unkeyed sender cannot poison the
    member map — there is no downgrade path."""
    from pilosa_tpu.utils.aesgcm import derive_key
    key = derive_key("cluster-secret")
    nodes = [Gossip(f"e{i}", config=GossipConfig(**FAST), secret_key=key)
             for i in range(3)]
    try:
        seed = (nodes[0].host, nodes[0].port)
        for i, g in enumerate(nodes):
            g.open(seeds=[seed] if i else [])
        want = {"e0", "e1", "e2"}
        wait_for(lambda: all(alive_ids(g) == want for g in nodes),
                 msg="encrypted cluster convergence")
        # cleartext injection: a rumor that would mark e2 suspect
        rumor = {"t": "ping", "seq": 4242, "from": "liar", "updates": [
            {"id": "e2", "host": nodes[2].host, "port": nodes[2].port,
             "state": SUSPECT, "inc": nodes[2].incarnation + 10}]}
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(json.dumps(rumor).encode(), (nodes[0].host, nodes[0].port))
        s.close()
        wait_for(lambda: nodes[0].crypto_drops >= 1,
                 msg="cleartext datagram dropped and counted")
        # the rumor never entered the state machine
        assert "e2" not in {m.id for m in nodes[0].members(state=SUSPECT)}
    finally:
        close_all(nodes)


def test_wrong_key_node_never_joins():
    from pilosa_tpu.utils.aesgcm import derive_key
    right = [Gossip(f"r{i}", config=GossipConfig(**FAST),
                    secret_key=derive_key("right")) for i in range(2)]
    wrong = Gossip("w0", config=GossipConfig(**FAST),
                   secret_key=derive_key("wrong"))
    try:
        seed = (right[0].host, right[0].port)
        right[0].open(seeds=[])
        right[1].open(seeds=[seed])
        wait_for(lambda: alive_ids(right[0]) == {"r0", "r1"},
                 msg="keyed pair converges")
        wrong.open(seeds=[seed])
        time.sleep(0.5)  # several protocol periods
        assert "w0" not in alive_ids(right[0])
        assert "w0" not in alive_ids(right[1])
        assert right[0].crypto_drops >= 1  # its sync datagrams dropped
        # and the wrong-key node learned nothing either
        assert alive_ids(wrong) == {"w0"}
    finally:
        close_all(right)
        wrong.close()


def test_server_gossip_secret_wires_cipher(tmp_path):
    """[gossip] secret on a Server turns the transport cipher on."""
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "enc"), port=0, membership_interval=0,
                 gossip_port=0, gossip_config=GossipConfig(**FAST),
                 gossip_secret="hush").open()
    try:
        assert srv.gossip._cipher is not None
    finally:
        srv.close()
