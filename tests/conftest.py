"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU backend (the analog of the reference's in-process multi-node
harness, test/pilosa.go:297-352 MustRunCluster).

Note: this environment exports JAX_PLATFORMS=axon and the axon plugin wins
over env-var overrides, so the platform is forced via jax.config.update
(must happen before any backend use; conftest imports run first). The
recipe lives in pilosa_tpu.parallel.mesh.force_platform.
"""

from pilosa_tpu.parallel.mesh import force_platform

force_platform("cpu", host_devices=8)

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_sessionstart(session):
    assert jax.devices()[0].platform == "cpu", jax.devices()
    assert len(jax.devices()) == 8, jax.devices()


@pytest.fixture(autouse=True)
def _failpoint_isolation():
    """Failpoint state is process-global (utils/failpoints.py): reset it
    around every test so a leaked activation can never bleed into an
    unrelated test's I/O paths."""
    from pilosa_tpu.utils import failpoints

    failpoints.reset()
    yield
    failpoints.reset()


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On a chaos-marked test failure, print the chaos seed and the exact
    fired-failpoint schedule — the replay recipe (re-arm the same seed, or
    re-fire the logged schedule via explicit configure() calls)."""
    out = yield
    rep = out.get_result()
    if rep.when == "call" and rep.failed \
            and item.get_closest_marker("chaos") is not None:
        from pilosa_tpu.utils import failpoints

        rep.sections.append((
            "chaos replay",
            "deterministic replay recipe (seed + fired schedule):\n"
            + failpoints.describe()))
