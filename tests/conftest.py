"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU backend (the analog of the reference's in-process multi-node
harness, test/pilosa.go:297-352 MustRunCluster).  Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
