"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU backend (the analog of the reference's in-process multi-node
harness, test/pilosa.go:297-352 MustRunCluster).

Note: this environment exports JAX_PLATFORMS=axon and the axon plugin wins
over env-var overrides, so the platform is forced via jax.config.update
(must happen before any backend use; conftest imports run first). The
recipe lives in pilosa_tpu.parallel.mesh.force_platform.
"""

import os

# runtime lock-order witness ON for the whole suite (export
# PILOSA_TPU_LOCKCHECK=0 to opt out): every concurrency test doubles as
# a race regression test — the autouse guard below fails the test that
# first forms a lock-order cycle or holds a lock across RPC/dispatch.
# Armed by direct install() rather than by exporting the env var: the
# subprocess clusters (clusterproc/chaos tests) would inherit the env
# and pay witness overhead whose reports nothing ever reads — pure load
# that erodes the SWIM-clock margins of the liveness tests. Installed
# before the first pilosa_tpu.parallel import so every lock the package
# constructs afterwards is wrapped.
from pilosa_tpu.analysis import lockwitness

if os.environ.get(lockwitness.ENV_GATE, "") != "0":
    lockwitness.install()

from pilosa_tpu.parallel.mesh import force_platform

force_platform("cpu", host_devices=8)

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_sessionstart(session):
    assert jax.devices()[0].platform == "cpu", jax.devices()
    assert len(jax.devices()) == 8, jax.devices()


@pytest.fixture(autouse=True)
def _lockwitness_guard():
    """With the witness active, any lock-order cycle or held-across-
    RPC/dispatch violation fails the test that formed it, with the
    offending stacks."""
    if not lockwitness.ACTIVE:
        yield
        return
    before = lockwitness.violation_count()
    yield
    after = lockwitness.violation_count()
    assert after == before, (
        "lock-order witness recorded new violations during this test:\n"
        + lockwitness.format_violations())


def pytest_sessionfinish(session, exitstatus):
    if lockwitness.ACTIVE:
        rep = lockwitness.report()
        print(f"\nlockwitness: {rep['edges']} lock-order edges, "
              f"{len(rep['cycles'])} cycles, "
              f"{len(rep['heldAcrossBlocking'])} held-across-blocking")


@pytest.fixture(autouse=True)
def _failpoint_isolation():
    """Failpoint state is process-global (utils/failpoints.py): reset it
    around every test so a leaked activation can never bleed into an
    unrelated test's I/O paths."""
    from pilosa_tpu.utils import failpoints

    failpoints.reset()
    yield
    failpoints.reset()


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On a chaos-marked test failure, print the chaos seed and the exact
    fired-failpoint schedule — the replay recipe (re-arm the same seed, or
    re-fire the logged schedule via explicit configure() calls)."""
    out = yield
    rep = out.get_result()
    if rep.when == "call" and rep.failed \
            and item.get_closest_marker("chaos") is not None:
        from pilosa_tpu.utils import failpoints

        rep.sections.append((
            "chaos replay",
            "deterministic replay recipe (seed + fired schedule):\n"
            + failpoints.describe()))
