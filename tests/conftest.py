"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU backend (the analog of the reference's in-process multi-node
harness, test/pilosa.go:297-352 MustRunCluster).

Note: this environment exports JAX_PLATFORMS=axon and the axon plugin wins
over env-var overrides, so the platform is forced via jax.config.update
(must happen before any backend use; conftest imports run first).
"""

import os

import re

_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_sessionstart(session):
    assert jax.devices()[0].platform == "cpu", jax.devices()
    assert len(jax.devices()) == 8, jax.devices()
