"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU backend (the analog of the reference's in-process multi-node
harness, test/pilosa.go:297-352 MustRunCluster).

Note: this environment exports JAX_PLATFORMS=axon and the axon plugin wins
over env-var overrides, so the platform is forced via jax.config.update
(must happen before any backend use; conftest imports run first). The
recipe lives in pilosa_tpu.parallel.mesh.force_platform.
"""

from pilosa_tpu.parallel.mesh import force_platform

force_platform("cpu", host_devices=8)

import jax  # noqa: E402


def pytest_sessionstart(session):
    assert jax.devices()[0].platform == "cpu", jax.devices()
    assert len(jax.devices()) == 8, jax.devices()
