"""Multi-process cluster test with fault injection.

The reference runs this as internal/clustertests/cluster_test.go:14-81: a
real multi-container cluster, pumba pauses one node for 10s mid-run, and the
test asserts the cluster keeps serving and converges afterwards. Here the
three nodes are real `pilosa-tpu server` OS processes on loopback ports
(separate data dirs, real sockets, real flocks); the pause is SIGSTOP — the
process keeps its sockets but answers nothing, exactly a pumba pause.

Covered end to end across process boundaries:
- membership bootstrap to NORMAL over HTTP
- liveness probing marks the SIGSTOP'd node down -> cluster DEGRADED
- writes during the outage succeed on the live replicas
- reads stay correct throughout (placement routes around the dead node)
- SIGCONT -> probes mark it back up -> NORMAL, and anti-entropy heals the
  missed writes (block checksums of every shard's replicas converge)
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from pilosa_tpu.constants import SHARD_WIDTH

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_SHARDS = 6
BITS_PER_SHARD_P1 = 40  # phase 1 (before pause)
BITS_PER_SHARD_P2 = 25  # phase 2 (during pause)


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def http(method, port, path, body=None, timeout=10.0):
    data = None if body is None else (
        body if isinstance(body, bytes) else json.dumps(body).encode())
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read() or b"{}")


def wait_until(fn, timeout=60.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except Exception:
            pass
        time.sleep(interval)
    return False


def sched_stall_factor(samples: int = 40, nap: float = 0.005) -> float:
    """Measured scheduler-stall multiplier for timing-sensitive
    assertions: sample short sleeps and take the worst observed overshoot
    relative to the request. On an idle host this is ~1; under full-suite
    load (every worker pinning a core) sleeps of 5 ms routinely come back
    after 50+ ms, which is exactly the jitter that false-suspects a
    healthy-but-slow SWIM peer. Clamped to [1, 6] so a pathological host
    widens the margins instead of hanging the suite."""
    worst = 0.0
    for _ in range(samples):
        t0 = time.monotonic()
        time.sleep(nap)
        worst = max(worst, time.monotonic() - t0)
    return min(6.0, max(1.0, worst / nap / 3.0))


@pytest.fixture
def cluster_procs(tmp_path):
    ports = free_ports(3)
    hosts = ", ".join(f'"http://127.0.0.1:{p}"' for p in ports)
    procs = []
    for i, port in enumerate(ports):
        cfg = tmp_path / f"n{i}.toml"
        cfg.write_text(
            f'data-dir = "{tmp_path / f"n{i}"}"\n'
            f'bind = "127.0.0.1:{port}"\n'
            "[cluster]\n"
            "disabled = false\n"
            "replicas = 2\n"
            f"hosts = [{hosts}]\n"
            "liveness-threshold = 3\n"
            "probe-timeout = 2.0\n"
            "membership-interval = 0.5\n"
            "[anti-entropy]\n"
            "interval = 1.0\n"
            "[mesh]\n"
            'devices = "none"\n'
            'platform = "cpu"\n')
        env = dict(os.environ)
        # keep the axon plugin importable but force the CPU backend (the
        # subprocess gotcha from round 1: PYTHONPATH must carry .axon_site)
        env["PYTHONPATH"] = f"{REPO}:{os.path.expanduser('~')}/.axon_site"
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "--config", str(cfg)],
            stdout=(tmp_path / f"n{i}.log").open("wb"),
            stderr=subprocess.STDOUT, cwd=REPO, env=env)
        procs.append(p)
    yield ports, procs
    for p in procs:
        try:
            os.kill(p.pid, signal.SIGCONT)  # in case a test left it paused
        except OSError:
            pass
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def cluster_state(port):
    _, st = http("GET", port, "/status", timeout=3.0)
    return st["state"]


def node_ready(port, n_nodes=3):
    """NORMAL alone is not enough: a freshly-booted node is a NORMAL
    1-node cluster before membership merges its peers — DDL issued then
    would never broadcast to them."""
    _, st = http("GET", port, "/status", timeout=3.0)
    return st["state"] == "NORMAL" and len(st["nodes"]) == n_nodes


def shard_blocks(port, shard):
    try:
        _, out = http(
            "GET", port,
            f"/internal/fragment/blocks?index=ci&field=f&view=standard"
            f"&shard={shard}", timeout=5.0)
    except Exception:
        return None  # 404: this node holds no fragment for the shard
    return out.get("blocks")


def test_three_process_cluster_sigstop_convergence(cluster_procs):
    ports, procs = cluster_procs
    p0, p1, p2 = ports

    assert wait_until(
        lambda: all(node_ready(p) for p in ports), 90.0), \
        "cluster never reached NORMAL with full membership"

    http("POST", p0, "/index/ci", {})
    http("POST", p0, "/index/ci/field/f", {})

    # phase 1: bulk import across every shard, verify from every node
    cols = [s * SHARD_WIDTH + k
            for s in range(N_SHARDS) for k in range(BITS_PER_SHARD_P1)]
    http("POST", p0, "/index/ci/field/f/import",
         {"rowIDs": [0] * len(cols), "columnIDs": cols})
    expect1 = len(cols)

    def assert_count(port, expect, timeout=30.0):
        # eventually-consistent: a CPU-starved node can transiently
        # mis-probe its peers (self-healing DEGRADED/STARTING blip) and
        # 400 a query; assert convergence, not instantaneous state
        last = {}

        def check():
            _, out = http("POST", port, "/index/ci/query", b"Count(Row(f=0))")
            last["got"] = out["results"]
            return out["results"] == [expect]

        assert wait_until(check, timeout), (port, last.get("got"), expect)

    for p in ports:
        assert_count(p, expect1)

    # pumba-pause node 2: SIGSTOP keeps sockets alive but nothing answers
    os.kill(procs[2].pid, signal.SIGSTOP)
    try:
        assert wait_until(
            lambda: cluster_state(p0) == "DEGRADED"
            and cluster_state(p1) == "DEGRADED", 30.0), \
            "survivors never detected the paused node"

        # phase 2: writes AND schema DDL during the outage land on the live
        # replicas (broadcasts skip the down node)
        cols2 = [s * SHARD_WIDTH + 1000 + k
                 for s in range(N_SHARDS) for k in range(BITS_PER_SHARD_P2)]

        def write_phase2():
            http("POST", p0, "/index/ci/field/f/import",
                 {"rowIDs": [0] * len(cols2), "columnIDs": cols2},
                 timeout=30.0)
            http("POST", p0, "/index/ci/field/g", {})  # DDL the node misses
            http("POST", p0, "/index/ci/query", b"Set(3, g=7)")
            return True

        assert wait_until(write_phase2, 30.0), \
            "writes during the outage never succeeded"
        expect2 = expect1 + len(cols2)
        for p in (p0, p1):
            assert_count(p, expect2)
    finally:
        os.kill(procs[2].pid, signal.SIGCONT)

    # recovery: probes mark the node back up, cluster returns to NORMAL
    assert wait_until(
        lambda: all(cluster_state(p) == "NORMAL" for p in ports), 30.0), \
        "cluster never returned to NORMAL after SIGCONT"

    # anti-entropy heals the missed writes: every shard's two replicas
    # converge to identical block checksums
    def converged():
        for shard in range(N_SHARDS):
            owners = [p for p in ports if shard_blocks(p, shard) is not None]
            blocks = [shard_blocks(p, shard) for p in owners]
            if len(blocks) < 2 or any(b != blocks[0] for b in blocks[1:]):
                return False
        return True

    assert wait_until(converged, 45.0), "replicas never converged"
    for p in ports:
        assert_count(p, expect2)

    # the returned node received the DDL it missed (coordinator schema-sync
    # on mark-up) and serves the new field correctly
    def has_g():
        _, out = http("GET", p2, "/schema")
        idx = next(i for i in out["indexes"] if i["name"] == "ci")
        return any(f["name"] == "g" for f in idx.get("fields", []))

    assert wait_until(has_g, 30.0), "returned node never learned field g"

    def g_served():
        _, out = http("POST", p2, "/index/ci/query", b"Row(g=7)")
        return out["results"][0]["columns"] == [3]

    assert wait_until(g_served, 30.0), \
        "returned node never served the missed write"


def test_gossip_cluster_sigstop_liveness(tmp_path):
    """Same three-OS-process fault drama, but with the SWIM UDP gossip
    transport as the failure detector ([gossip] section) instead of HTTP
    /status probes: SIGSTOP -> no UDP acks -> suspect -> dead -> cluster
    DEGRADED; SIGCONT -> acks -> alive -> NORMAL. Asserts the optional
    backend drives the same mark_down/mark_up plumbing end to end across
    process boundaries (gossip/gossip.go:488-519 analog).

    Load-deflaked three times (commit-78793c6, the full-suite pass, and
    the ISSUE 15 satellite): the SWIM clock is isolated from suite CPU
    contention — a loaded-but-alive node gets 1.5 s to ack before
    suspicion with a 0.5 s protocol period, BOTH now scaled by the
    MEASURED scheduler stall (sched_stall_factor: on a host where 5 ms
    sleeps overshoot 10x, the protocol clock and every wait deadline
    widen proportionally instead of false-suspecting a descheduled-but-
    healthy peer) — and the subprocesses run with the telemetry sampler
    and planner cache disabled (background CPU they don't need, stolen
    from the prober threads when the whole suite shares the host). Every
    cross-process observation polls until convergence with generous
    deadlines instead of asserting a single snapshot."""
    stall = sched_stall_factor()
    ports = free_ports(3)
    gports = free_ports(3)
    hosts = ", ".join(f'"http://127.0.0.1:{p}"' for p in ports)
    procs = []
    try:
        for i, port in enumerate(ports):
            cfg = tmp_path / f"g{i}.toml"
            cfg.write_text(
                f'data-dir = "{tmp_path / f"g{i}"}"\n'
                f'bind = "127.0.0.1:{port}"\n'
                "[cluster]\n"
                "disabled = false\n"
                "replicas = 2\n"
                f"hosts = [{hosts}]\n"
                "membership-interval = 0.5\n"
                "[gossip]\n"
                f"port = {gports[i]}\n"
                f'seeds = ["127.0.0.1:{gports[0]}"]\n'
                # widened suspicion tolerance: sub-second ack windows
                # false-suspect healthy-but-slow peers whenever the full
                # suite loads the host; 1.5 s ack + 0.5 s period keeps
                # the SWIM clock an order of magnitude above scheduler
                # jitter — and both scale by the MEASURED stall factor,
                # so a heavily oversubscribed host widens the protocol
                # margin instead of flaking the assertion
                f"period = {0.5 * stall}\n"
                f"probe-timeout = {1.5 * stall}\n"
                f"push-pull-interval = {2.0 * stall}\n"
                "[metric]\n"
                # no background sampler burning CPU in the subprocesses:
                # this test is about the failure detector's clock, and
                # suite-load contention was flaking it (ISSUE 8 satellite)
                "telemetry-interval = 0\n"
                "[mesh]\n"
                'devices = "none"\n'
                'platform = "cpu"\n')
            env = dict(os.environ)
            env["PYTHONPATH"] = \
                f"{REPO}:{os.path.expanduser('~')}/.axon_site"
            env["JAX_PLATFORMS"] = "cpu"
            env["PILOSA_TPU_TELEMETRY"] = "0"
            p = subprocess.Popen(
                [sys.executable, "-m", "pilosa_tpu.cli", "server",
                 "--config", str(cfg)],
                stdout=(tmp_path / f"g{i}.log").open("wb"),
                stderr=subprocess.STDOUT, cwd=REPO, env=env)
            procs.append(p)
        p0, p1, p2 = ports
        assert wait_until(lambda: all(node_ready(p) for p in ports),
                          90.0 * stall), \
            "cluster never reached NORMAL/3-node"
        # a write served while everyone is up
        http("POST", p0, "/index/gi", {"options": {}})
        http("POST", p0, "/index/gi/field/f", {"options": {"type": "set"}})
        http("POST", p0, "/index/gi/query", b"Set(1, f=5)")
        os.kill(procs[2].pid, signal.SIGSTOP)
        assert wait_until(
            lambda: cluster_state(p0) == "DEGRADED"
            and cluster_state(p1) == "DEGRADED", 120.0 * stall), \
            "gossip never marked the SIGSTOP'd node down"

        # queries still answer while DEGRADED (placement routes around);
        # poll — routing tables converge asynchronously with the state flip
        def degraded_query_ok():
            _, out = http("POST", p0, "/index/gi/query", b"Count(Row(f=5))")
            return out["results"] == [1]

        assert wait_until(degraded_query_ok, 30.0 * stall), \
            "DEGRADED cluster never served the routed-around query"
        os.kill(procs[2].pid, signal.SIGCONT)
        assert wait_until(
            lambda: cluster_state(p0) == "NORMAL"
            and cluster_state(p1) == "NORMAL", 90.0 * stall), \
            "gossip never revived the resumed node"
    finally:
        for p in procs:
            try:
                os.kill(p.pid, signal.SIGCONT)
            except OSError:
                pass
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
