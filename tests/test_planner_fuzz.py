"""Planner/plan-cache parity fuzz: randomized PQL call trees, with writes
interleaved to churn generations, asserting planned+cached execution is
bit-identical to written-order evaluation.

Two executors share one holder: `planned` runs with the planner and the
cross-query plan cache on (the cache is deliberately left WARM across
rounds — the interleaved writes are exactly what must invalidate it via
generation keys), `plain` runs with both kill switches thrown. Any
divergence — results, or error-vs-result behavior — is a planner bug.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor, Pairs
from pilosa_tpu.models.holder import Holder

FIELDS = ("f", "g", "h")
N_ROWS = 6  # rows 4/5 stay sparse-or-empty so short-circuits exercise
SHARDS = 2


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("planfuzz")
    h = Holder(str(tmp / "data")).open()
    rng = np.random.default_rng(7)
    idx = h.create_index("z")
    for fname in FIELDS:
        f = idx.create_field(fname)
        for rid in range(N_ROWS - 2):
            n = int(rng.integers(1, 400) * (4 ** (rid % 3)))
            cols = rng.choice(SHARDS * SHARD_WIDTH, size=min(n, 5000),
                              replace=False)
            f.import_bits([rid] * len(cols), cols.tolist())
            for c in cols[:64]:
                idx.mark_exists(int(c))
    planned = Executor(h)
    assert planned.planner is not None and planned.plan_cache is not None
    import os
    os.environ["PILOSA_TPU_PLANNER"] = "0"
    os.environ["PILOSA_TPU_PLAN_CACHE"] = "0"
    try:
        plain = Executor(h)
    finally:
        del os.environ["PILOSA_TPU_PLANNER"]
        del os.environ["PILOSA_TPU_PLAN_CACHE"]
    assert plain.planner is None and plain.plan_cache is None
    yield h, planned, plain, rng
    h.close()


def _rand_bitmap(rng, depth: int) -> str:
    if depth <= 0 or rng.random() < 0.35:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        rid = int(rng.integers(N_ROWS))
        return f"Row({fname}={rid})"
    op = ("Intersect", "Union", "Difference", "Xor",
          "Not")[int(rng.integers(5))]
    if op == "Not":
        return f"Not({_rand_bitmap(rng, depth - 1)})"
    n = int(rng.integers(2, 4))
    kids = ", ".join(_rand_bitmap(rng, depth - 1) for _ in range(n))
    return f"{op}({kids})"


def _rand_query(rng) -> str:
    inner = _rand_bitmap(rng, int(rng.integers(1, 4)))
    shape = rng.random()
    if shape < 0.45:
        return f"Count({inner})"
    if shape < 0.6:
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        return f"TopN({fname}, {inner}, n=4)"
    return inner


def _canon(result):
    if isinstance(result, Pairs):
        return ("pairs", list(result))
    if hasattr(result, "segments"):
        return ("row", {int(s): [int(c) for c in cols]
                        for s, cols in result.segments.items()})
    return ("val", result)


def _run(ex, pql):
    try:
        return _canon(ex.execute("z", pql)[0])
    except (ExecutionError, ValueError):
        return ("error",)  # both sides must error; messages may differ
        # (reordering legitimately changes which operand errors first)


def test_parity_randomized_trees_with_interleaved_writes(setup):
    h, planned, plain, rng = setup
    idx = h.index("z")
    mismatches = []
    for round_no in range(60):
        for _ in range(4):
            pql = _rand_query(rng)
            a = _run(planned, pql)
            b = _run(plain, pql)
            if a != b:
                mismatches.append((round_no, pql, a, b))
        # interleave writes to churn generations: the warm cache must
        # never serve a pre-write result
        fname = FIELDS[int(rng.integers(len(FIELDS)))]
        rid = int(rng.integers(N_ROWS))
        col = int(rng.integers(SHARDS * SHARD_WIDTH))
        f = idx.field(fname)
        if rng.random() < 0.75:
            f.set_bit(rid, col)
            idx.mark_exists(col)
        else:
            f.clear_bit(rid, col)
    assert not mismatches, mismatches[:5]
    # the fuzz actually exercised the machinery
    psnap = planned.planner.snapshot()
    csnap = planned.plan_cache.snapshot()
    assert psnap["plans"] > 100
    assert csnap["misses"] > 0


def test_parity_groupby_and_aggregates(setup):
    """GroupBy filters and Sum/Min/Max filter subtrees ride the plan
    cache; results must match written-order evaluation exactly."""
    h, planned, plain, rng = setup
    idx = h.index("z")
    from pilosa_tpu.models.field import FieldOptions, FieldType
    fi = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                            min=0, max=1000))
    cols = rng.choice(SHARDS * SHARD_WIDTH, size=300, replace=False)
    for c in cols:
        fi.set_value(int(c), int(rng.integers(0, 1000)))
    queries = [
        "GroupBy(Rows(f), filter=Intersect(Row(g=0), Row(g=1)))",
        "GroupBy(Rows(f), Rows(g), limit=10)",
        "Sum(Intersect(Row(f=0), Row(f=1)), field=v)",
        "Min(Union(Row(f=0), Row(g=0)), field=v)",
        "Max(Intersect(Row(f=0), Row(f=0)), field=v)",
    ]
    for q in queries * 2:  # second pass: warm plan cache
        a = _run(planned, q)
        b = _run(plain, q)
        assert a == b, q


def test_kill_switch_parity(setup):
    """PILOSA_TPU_PLANNER=0 / PILOSA_TPU_PLAN_CACHE=0 executors produce
    identical results to the planned one on the same live data (the
    kill-switch escape hatch must always be safe to throw)."""
    h, planned, plain, rng = setup
    for _ in range(20):
        pql = _rand_query(rng)
        assert _run(planned, pql) == _run(plain, pql), pql
