"""Continuous count batching (parallel/batcher.py): concurrent simple
Counts coalesce into single device dispatches."""

import threading

import numpy as np
import pytest

from pilosa_tpu.parallel.batcher import ContinuousBatcher, CountBatcher, _pow2


def _leaves(n=4, s=2, w=256, seed=0):
    import jax

    rng = np.random.default_rng(seed)
    return [jax.device_put(rng.integers(0, 2**32, size=(s, w),
                                        dtype=np.uint32))
            for _ in range(n)]


def _expect(op, a, b):
    a, b = np.asarray(a), np.asarray(b)
    if op == "and":
        r = a & b
    elif op == "or":
        r = a | b
    elif op == "xor":
        r = a ^ b
    elif op == "andnot":
        r = a & ~b
    else:
        r = a
    return int(np.bitwise_count(r).sum())


def test_single_query_immediate():
    b = CountBatcher()
    ls = _leaves(2)
    got = b.count("and", ls[0], ls[1])
    assert got == _expect("and", ls[0], ls[1])
    snap = b.snapshot()
    assert snap["batches"] == 1 and snap["batched_queries"] == 1


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_ops_and_leaf_count(op):
    b = CountBatcher()
    ls = _leaves(3, seed=op.__hash__() % 100)
    assert b.count(op, ls[0], ls[1]) == _expect(op, ls[0], ls[1])
    assert b.count("id", ls[2], None) == _expect("id", ls[2], ls[2])


def test_concurrent_batching_correct_and_batched():
    b = CountBatcher()
    ls = _leaves(6)
    n_threads, per = 16, 20
    results = {}
    errors = []
    start = threading.Barrier(n_threads)

    def client(tid):
        try:
            start.wait()
            out = []
            for i in range(per):
                x, y = ls[(tid + i) % 6], ls[(tid * 3 + i * 7) % 6]
                out.append((id(x), id(y), b.count("and", x, y)))
            results[tid] = out
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    by_id = {id(x): x for x in ls}
    for out in results.values():
        for xa, xb, got in out:
            assert got == _expect("and", by_id[xa], by_id[xb])
    snap = b.snapshot()
    assert snap["batched_queries"] == n_threads * per
    # batching must actually have happened (fewer dispatches than queries)
    assert snap["batches"] < n_threads * per, snap
    assert snap["max_batch_seen"] > 1


def test_leadership_handoff_under_load():
    """A leader serves ONE batch then promotes the queue head — no thread
    serves strangers after its own query completes."""
    b = CountBatcher(max_batch=4)
    ls = _leaves(2)
    n = 24
    done = []

    def client(i):
        done.append((i, b.count("and", ls[0], ls[1])))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expect = _expect("and", ls[0], ls[1])
    assert len(done) == n and all(c == expect for _, c in done)
    assert b.snapshot()["batches"] >= n // 4  # max_batch enforced


def test_error_propagates_to_all_waiters(monkeypatch):
    import pilosa_tpu.parallel.batcher as mod

    b = CountBatcher()

    def boom(*a, **k):
        raise RuntimeError("kernel exploded")

    monkeypatch.setattr(mod, "_batched_counts", boom)
    ls = _leaves(2)
    errs = []

    def client():
        try:
            b.count("and", ls[0], ls[1])
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(errs) == 8 and all("kernel exploded" in e for e in errs)
    # batcher stays usable after the failure
    monkeypatch.undo()
    assert b.count("and", ls[0], ls[1]) == _expect("and", ls[0], ls[1])


def test_pow2():
    assert [_pow2(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]


def test_executor_count_uses_batcher(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_BATCH", "1")  # asserts batcher behavior
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder

    holder = Holder(str(tmp_path)).open()
    ex = Executor(holder)
    assert ex.batcher is not None
    # hybrid off: these few-bit rows would ride the sparse path, which
    # bypasses the batcher by design — the batcher layer is under test
    ex.hybrid.threshold = 0
    idx = holder.create_index("bt", track_existence=False)
    f = idx.create_field("f")
    f.import_bits([0, 0, 1, 1, 1], [1, 5, 5, 9, 2_000_000])
    (c,) = ex.execute("bt", "Count(Intersect(Row(f=0), Row(f=1)))")
    assert c == 1
    (c2,) = ex.execute("bt", "Count(Row(f=1))")
    assert c2 == 3
    (c3,) = ex.execute("bt", "Count(Union(Row(f=0), Row(f=1)))")
    assert c3 == 4
    (c4,) = ex.execute("bt", "Count(Difference(Row(f=1), Row(f=0)))")
    assert c4 == 2
    snap = ex.batcher.snapshot()
    assert snap["batched_queries"] == 4
    # Not() compiles to andnot(existence, child) — needs existence tracking;
    # three-way intersect is NOT batchable and must take the general path
    (c5,) = ex.execute(
        "bt", "Count(Intersect(Row(f=0), Row(f=1), Row(f=1)))")
    assert c5 == 1
    assert ex.batcher.snapshot()["batched_queries"] == 4  # unchanged
    holder.close()


def test_plane_sum_batcher_concurrent():
    """Concurrent Sums sharing a plane slab coalesce; per-query totals
    match serial sum_counts exactly."""
    import jax

    from pilosa_tpu.parallel.batcher import PlaneSumBatcher

    rng = np.random.default_rng(31)
    depth, s, w = 5, 4, 256
    planes = jax.device_put(
        rng.integers(0, 2**32, size=(depth, s, w), dtype=np.uint32))
    masks = [jax.device_put(
        rng.integers(0, 2**32, size=(s, w), dtype=np.uint32))
        for _ in range(6)]
    b = PlaneSumBatcher()

    def expect(mask):
        p, m = np.asarray(planes), np.asarray(mask)
        per_plane = [int(np.bitwise_count(p[i] & m).sum())
                     for i in range(depth)]
        return per_plane + [int(np.bitwise_count(m).sum())]

    results = {}
    start = threading.Barrier(24)  # force overlap: coalescing must happen

    def worker(i):
        start.wait()
        results[i] = b.plane_sums(planes, masks[i % 6])

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, got in results.items():
        assert got.tolist() == expect(masks[i % 6]), i
    snap = b.snapshot()
    assert snap["batched_queries"] == 24
    assert snap["batches"] < 24  # coalescing happened


def test_executor_concurrent_sums_batch(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_BATCH", "1")  # asserts batcher behavior
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    holder = Holder(str(tmp_path)).open()
    ex = Executor(holder)
    idx = holder.create_index("sb", track_existence=False)
    v = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=0, max=255))
    rng = np.random.default_rng(7)
    cols = np.arange(5000, dtype=np.uint64)
    vals = rng.integers(0, 256, size=5000, dtype=np.int64)
    v.import_values(cols, vals)
    thresholds = [32 * i for i in range(8)]
    expected = {t: (int(vals[vals > t].sum()), int((vals > t).sum()))
                for t in thresholds}
    ex.execute("sb", "Sum(Range(v > 0), field=v)")  # warm residency
    results = {}
    threads = [threading.Thread(
        target=lambda t=t: results.__setitem__(
            t, ex.execute("sb", f"Sum(Range(v > {t}), field=v)")[0]))
        for t in thresholds for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for t, vc in results.items():
        assert (vc.val, vc.count) == expected[t], t
    assert ex.sum_batcher.snapshot()["batched_queries"] >= 8
    holder.close()


def test_executor_batcher_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_BATCH", "0")
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder

    holder = Holder(str(tmp_path)).open()
    ex = Executor(holder)
    assert ex.batcher is None
    idx = holder.create_index("bt2", track_existence=False)
    f = idx.create_field("f")
    f.import_bits([0, 1], [3, 3])
    (c,) = ex.execute("bt2", "Count(Intersect(Row(f=0), Row(f=1)))")
    assert c == 1
    holder.close()


def test_executor_concurrent_min_max_batch(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_BATCH", "1")  # asserts batcher behavior
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, FieldType, Holder

    holder = Holder(str(tmp_path)).open()
    ex = Executor(holder)
    idx = holder.create_index("mm", track_existence=False)
    v = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-20, max=500))
    rng = np.random.default_rng(9)
    n = 4000
    vals = rng.integers(-20, 501, size=n, dtype=np.int64)
    v.import_values(np.arange(n, dtype=np.uint64), vals)
    ex.execute("mm", "Min(field=v)")  # warm residency
    results = {}
    start = threading.Barrier(12)

    def worker(i):
        start.wait()
        q = "Min(field=v)" if i % 2 == 0 else "Max(field=v)"
        results[i] = ex.execute("mm", q)[0]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mn, mx = int(vals.min()), int(vals.max())
    for i, vc in results.items():
        if i % 2 == 0:
            assert vc.val == mn and vc.count == int((vals == mn).sum()), vc
        else:
            assert vc.val == mx and vc.count == int((vals == mx).sum()), vc
    snap = ex.minmax_batcher.snapshot()
    assert snap["batched_queries"] == 13  # 12 concurrent + the warm-up Min
    holder.close()


def test_compute_length_mismatch_raises_everywhere(monkeypatch):
    """A _compute that returns the wrong number of results must surface as
    an exception on EVERY waiter, never leave unpaired waiters hanging."""
    b = CountBatcher()
    ls = _leaves(2)

    def bad_compute(key, payloads):
        return [0]  # always one result, regardless of batch size

    monkeypatch.setattr(b, "_compute", bad_compute)
    start = threading.Barrier(4)
    errors = []

    def client():
        start.wait()
        try:
            b.count("and", ls[0], ls[1])
        except RuntimeError as e:
            errors.append(e)

    ts = [threading.Thread(target=client) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
        assert not t.is_alive(), "waiter hung on length mismatch"
    # every client either got the single real result (batch of 1) or the
    # mismatch error (batch > 1); none hung. At least the multi-request
    # batches must have errored:
    assert all("returned" in str(e) for e in errors)


def test_leader_death_reclaim(monkeypatch):
    """If the leader thread dies without delivering (thread kill analog),
    a queued follower reclaims leadership after the poll interval instead
    of waiting forever (ADVICE r3: unbounded _Req.event.wait)."""
    import pilosa_tpu.parallel.batcher as batcher_mod

    monkeypatch.setattr(batcher_mod, "_WAIT_POLL_S", 0.1)
    b = CountBatcher()
    ls = _leaves(2)
    key = ("and", tuple(ls[0].shape), str(ls[0].dtype))

    # fabricate a dead leader: a finished thread holds the key
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with b._lock:
        b._leaders.add(key)
        b._leader_threads[key] = dead

    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault("r", b.count("and", ls[0], ls[1])))
    t.start()
    t.join(timeout=20)
    assert not t.is_alive(), "follower never reclaimed dead leadership"
    assert out["r"] == _expect("and", ls[0], ls[1])


def test_leader_death_mid_compute_errors(monkeypatch):
    """A follower whose request was absorbed into a dead leader's batch
    gets an error (the result can never arrive), not a silent hang."""
    import pilosa_tpu.parallel.batcher as batcher_mod

    monkeypatch.setattr(batcher_mod, "_WAIT_POLL_S", 0.1)
    b = CountBatcher()
    ls = _leaves(2)
    key = ("and", tuple(ls[0].shape), str(ls[0].dtype))
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with b._lock:
        b._leaders.add(key)
        b._leader_threads[key] = dead

    errs = []

    def client():
        try:
            b.count("and", ls[0], ls[1])
        except RuntimeError as e:
            errs.append(e)

    t = threading.Thread(target=client)
    t.start()
    # let the request enqueue, then simulate the dead leader having taken
    # it into its batch: drop it from the pending queue
    import time as _time

    _time.sleep(0.03)
    with b._lock:
        q = b._pending.get(key)
        assert q, "request not enqueued yet"
        q.clear()
    t.join(timeout=20)
    assert not t.is_alive(), "absorbed follower hung after leader death"
    assert errs and "leader died" in str(errs[0])


def test_batched_counts_int64_exact_over_2048_shards():
    """Counts past int32 range must come back exact: the device reduction
    is chunked at 2016 shards (int32-safe partials) and finished host-side
    in int64 (ADVICE r3: the old whole-axis int32 sum wrapped at >2047
    dense shards)."""
    import jax

    s, w = 70_000, 1024  # 70k shards x 1024 words x 32 bits = 2.29e9 > 2^31
    ones = jax.device_put(np.full((s, w), 0xFFFFFFFF, dtype=np.uint32))
    b = CountBatcher()
    got = b.count("and", ones, ones)
    assert got == s * w * 32  # would be negative / wrapped under int32


def test_replica_mesh_scatters_batch():
    """Production serving on a replica×shard mesh (VERDICT r3 missing #4):
    a batch of K concurrent Counts scatters K/R queries to each replica
    slice (each holding a full data copy) instead of every replica
    redundantly computing all K. Verifies numpy-exact results AND the
    scatter layout (per-device output rows = K/R, so on real hardware the
    batch costs each chip 1/R of the work -> ~R× batch throughput)."""
    from pilosa_tpu.parallel.batcher import _replica_counts_fn
    from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

    mesh = make_mesh(replicas=2)  # 2 replicas x 4 shard slots
    runner = DeviceRunner(mesh)
    rng = np.random.default_rng(31)
    host = [rng.integers(0, 2**32, size=(6, 64), dtype=np.uint32)
            for _ in range(4)]
    leaves = [runner.put_leaf(h) for h in host]  # padded to 8, sharded
    b = CountBatcher(runner=runner)

    # concurrent clients -> coalesced batches through the replica path
    n_threads, per = 8, 6
    results, errors = {}, []
    start = threading.Barrier(n_threads)

    def client(tid):
        start.wait()
        try:
            for q in range(per):
                i, j = (tid + q) % 4, (tid + q + 1) % 4
                got = b.count("and", leaves[i], leaves[j])
                expect = int(np.bitwise_count(host[i] & host[j]).sum())
                results[(tid, q)] = (got, expect)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    assert len(results) == n_threads * per
    for (tid, q), (got, expect) in results.items():
        assert got == expect, (tid, q, got, expect)

    # scatter layout: each device holds K/2 query rows of the partials
    ii = np.arange(8, dtype=np.int32) % 4
    jj = (np.arange(8, dtype=np.int32) + 1) % 4
    fn = _replica_counts_fn(mesh, "and")
    out = fn(tuple(leaves), ii, jj)
    assert out.shape[0] == 8
    shard_rows = {s.data.shape[0] for s in out.addressable_shards}
    assert shard_rows == {4}, shard_rows  # K/R = 8/2 per replica slice
    got = np.asarray(out).astype(np.int64).sum(axis=-1)
    for k in range(8):
        assert got[k] == int(
            np.bitwise_count(host[ii[k]] & host[jj[k]]).sum())


def test_dispatch_overlaps_inflight_finalize():
    """Leadership hands off BEFORE _dispatch: batch N+1's admission and
    device launch overlap batch N's dispatch and result round trip, so
    _dispatch may run concurrently for the same key (through a ~100 ms
    tunnel this is the difference between ~15 serialized dispatches/s and
    arrival-bound throughput). This test pins the weaker invariant that a
    later batch's dispatch need not wait for an in-flight finalize."""
    dispatched = []
    release = threading.Event()
    overlap_seen = threading.Event()

    class Slow(ContinuousBatcher):
        def _dispatch(self, key, payloads):
            dispatched.append(list(payloads))
            if len(dispatched) >= 2:
                overlap_seen.set()
            return list(payloads)

        def _finalize(self, key, handle, payloads):
            # first batch's fetch blocks until a SECOND dispatch happened
            if handle == dispatched[0] and not release.is_set():
                assert overlap_seen.wait(10.0), \
                    "no second dispatch while first finalize in flight"
                release.set()
            return [p * 2 for p in handle]

    b = Slow(max_batch=1)  # force one payload per batch
    results = {}

    def client(v):
        results[v] = b.submit(("k",), v)

    ts = [threading.Thread(target=client, args=(v,)) for v in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert results == {v: v * 2 for v in range(4)}
    assert len(dispatched) == 4
    assert release.is_set()


def test_dispatches_overlap_for_same_key():
    """The strong invariant of handoff-before-dispatch: a slow _dispatch
    does not serialize the dispatch rate. While batch N's _dispatch is
    still executing, batch N+1's _dispatch starts (each dispatch costs ~a
    link transfer on a tunneled chip; serialized dispatches capped serving
    at ~15 batches/s regardless of chip speed — see module docstring)."""
    both_in = threading.Event()
    n_inside = [0]
    lock = threading.Lock()

    class SlowDispatch(ContinuousBatcher):
        def _dispatch(self, key, payloads):
            with lock:
                n_inside[0] += 1
                if n_inside[0] >= 2:
                    both_in.set()
            # blocks until TWO dispatches are inside concurrently: times
            # out (and fails) if dispatches are serialized per key
            assert both_in.wait(10.0), \
                "second dispatch never started while first was in flight"
            return list(payloads)

        def _finalize(self, key, handle, payloads):
            return [p + 1 for p in handle]

    b = SlowDispatch(max_batch=1)  # force one payload per batch
    results = {}

    def client(v):
        results[v] = b.submit(("k",), v)

    ts = [threading.Thread(target=client, args=(v,)) for v in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert results == {0: 1, 1: 2}
    assert n_inside[0] == 2


def test_dispatch_failure_wakes_batch_and_promotes_next():
    """An exception raised at dispatch time must error that batch's
    waiters immediately and still hand leadership to the next batch."""
    calls = []

    class Flaky(ContinuousBatcher):
        def _dispatch(self, key, payloads):
            calls.append(list(payloads))
            if len(calls) == 1:
                raise RuntimeError("device rejected program")
            return list(payloads)

        def _finalize(self, key, handle, payloads):
            return [p + 100 for p in handle]

    b = Flaky(max_batch=1)
    out = {}

    def client(v):
        try:
            out[v] = b.submit(("k",), v)
        except RuntimeError as e:
            out[v] = e

    ts = [threading.Thread(target=client, args=(v,)) for v in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    vals = list(out.values())
    assert sum(isinstance(v, RuntimeError) for v in vals) == 1
    assert sorted(v for v in vals if isinstance(v, int)) == \
        [v + 100 for v in sorted(out) if isinstance(out[v], int)]
