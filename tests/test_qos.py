"""Multi-tenant QoS plane (pilosa_tpu/qos.py): quotas, priorities,
deadline-aware admission and load shedding.

Unit layers: token-bucket semantics, priority resolution, the priority
pool's ordering, QosPlane verdicts per mode (off/observe/enforce) and
the batcher's priority-ordered cut. Live layers: a single enforce-mode
server throttling one principal with `429 + Retry-After` while a
quota'd VIP sails through, observe-mode counting without rejecting, the
env kill switch, and a 3-node cluster proving the deadline budget
shrinks as it fans out — and that an entry arriving expired is shed
remotely before any device dispatch.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import qos
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.qos import (
    PriorityPool,
    QosPlane,
    Rejection,
    TokenBucket,
)

SW = SHARD_WIDTH


# ------------------------------------------------------------------ buckets


def test_token_bucket_refill_and_debt():
    b = TokenBucket(rate=10.0, burst=20.0)
    t0 = time.monotonic()
    assert b.wait_for(1.0, now=t0) == 0.0
    b.take(20.0, now=t0)  # drain the whole burst
    assert b.wait_for(1.0, now=t0) == pytest.approx(0.1, abs=1e-6)
    # ledger feedback can push into debt; the wait scales with the debt
    b.take(30.0, now=t0)
    assert b.wait_for(0.0, now=t0) == pytest.approx(3.0, abs=1e-6)
    # refill is linear in elapsed time and capped at burst
    assert b.wait_for(0.0, now=t0 + 3.0) == 0.0
    b2 = TokenBucket(rate=10.0, burst=20.0)
    b2.take(1.0, now=t0)
    b2._refill(t0 + 100.0)
    assert b2.tokens == 20.0  # never exceeds burst


def test_zero_rate_bucket_reports_cap_wait():
    b = TokenBucket(rate=0.0, burst=0.0)
    b.take(1.0)
    assert b.wait_for(0.0) == qos.RETRY_AFTER_MAX_S


# ----------------------------------------------------------------- priority


def test_priority_levels_and_defaults():
    assert qos.priority_level("interactive") == 0
    assert qos.priority_level("batch") == 1
    assert qos.priority_level("internal") == 2
    # unknown / untagged sorts as internal: background work must never
    # queue ahead of tagged user traffic
    assert qos.priority_level(None) == 2
    assert qos.priority_level("garbage") == 2
    assert qos.current_level() == 2  # no contextvar installed


def test_priority_for_header_override_default():
    plane = QosPlane(mode="off", default_priority="interactive",
                     principals={"key:etl": {"priority": "batch"}})
    assert plane.priority_for("batch", "key:x") == "batch"
    assert plane.priority_for(" Interactive ", "key:etl") == "interactive"
    assert plane.priority_for(None, "key:etl") == "batch"  # override
    assert plane.priority_for("nonsense", "key:x") == "interactive"
    assert plane.priority_for(None, "key:x") == "interactive"


def test_plane_validates_config():
    with pytest.raises(ValueError):
        QosPlane(mode="enfroce")
    with pytest.raises(ValueError):
        QosPlane(default_priority="vip")
    with pytest.raises(ValueError):
        QosPlane(principals={"k": {"priority": "vip"}})
    with pytest.raises(ValueError):
        QosPlane(principals={"k": {"queries-per-sec": 1}})  # typo'd key
    # hyphenated TOML keys normalize
    p = QosPlane(principals={"k": {"queries-per-s": 5, "priority": "batch"}})
    assert p.overrides["k"] == {"queries_per_s": 5, "priority": "batch"}


def test_priority_pool_orders_by_class_under_saturation():
    import threading
    pool = PriorityPool(1, "t")
    release = threading.Event()
    order = []
    try:
        blocker = pool.submit(release.wait, 5.0)  # occupies the worker
        # queue three classes in reverse-priority submit order
        futs = []
        for name in ("internal", "batch", "interactive"):
            tok = qos.current_priority.set(name)
            try:
                futs.append(pool.submit(order.append, name))
            finally:
                qos.current_priority.reset(tok)
        release.set()
        for f in futs:
            f.result(timeout=5)
        assert blocker.result(timeout=5)
        assert order == ["interactive", "batch", "internal"]
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def test_priority_pool_delivers_exceptions_and_shutdown_cancels():
    pool = PriorityPool(2, "t")
    def boom():
        raise RuntimeError("boom")
    f = pool.submit(boom)
    with pytest.raises(RuntimeError):
        f.result(timeout=5)
    pool.shutdown(wait=True, cancel_futures=True)
    with pytest.raises(RuntimeError):
        pool.submit(lambda: 1)


def test_batcher_cut_is_priority_ordered():
    """When the pending queue overflows one batch, the cut takes the
    most urgent requests first (stable within a class)."""
    from pilosa_tpu.parallel.batcher import ContinuousBatcher, _Req

    seen = []

    class Rec(ContinuousBatcher):
        def _compute(self, key, payloads):
            seen.append(list(payloads))
            return payloads

    b = Rec(max_batch=2)
    b.admission_s = 0.0
    key = ("k",)
    reqs = []
    for payload, prio in (("bat1", 1), ("int1", 0), ("bat2", 1),
                          ("int2", 0)):
        r = _Req(payload)
        r.priority = prio
        reqs.append(r)
    b._pending[key] = list(reqs)
    b._serve_one_batch(key)
    assert seen[0] == ["int1", "int2"]  # interactive rode the first cut
    b._serve_one_batch(key)
    assert seen[1] == ["bat1", "bat2"]
    assert all(r.done for r in reqs)


# -------------------------------------------------------------- plane logic


class _FakeLedger:
    def __init__(self):
        self.entries = {}

    def peek(self, principal):
        return self.entries.get(principal)


def test_plane_mode_off_admits_everything():
    plane = QosPlane(mode="off", queries_per_s=0.001)
    for _ in range(50):
        assert plane.admit("p", "interactive", None) is None
    assert plane.totals()["admitted"] == 0  # off = not even counted


def test_plane_enforce_qps_quota_and_observe_mode():
    plane = QosPlane(mode="enforce", queries_per_s=2.0, burst_s=1.0)
    verdicts = [plane.admit("key:a", "interactive", None)
                for _ in range(5)]
    rejected = [v for v in verdicts if v is not None]
    assert len(rejected) == 3
    assert all(v.status == 429 and v.reason == "queriesPerS"
               and v.retry_after > 0 for v in rejected)
    assert plane.admitted["interactive"] == 2
    assert plane.throttled["queriesPerS"] == 3
    # a different principal has its own bucket
    assert plane.admit("key:b", "interactive", None) is None
    # observe mode: same decision, nothing rejected
    obs = QosPlane(mode="observe", queries_per_s=2.0, burst_s=1.0)
    assert all(obs.admit("key:a", "interactive", None) is None
               for _ in range(5))
    assert obs.would_throttled["queriesPerS"] == 3
    assert obs.throttled["queriesPerS"] == 0


def test_plane_ledger_feedback_throttles_device_spend():
    """Device-ms quota charges the ledger's MEASURED spend between
    requests — a principal that burned device time goes into debt and is
    throttled until the bucket refills."""
    ledger = _FakeLedger()
    plane = QosPlane(mode="enforce", device_ms_per_s=10.0, burst_s=1.0,
                     ledger=ledger)
    ledger.entries["key:a"] = {"deviceMs": 0.0, "rpcBytes": 0,
                               "hbmBytes": 0}
    assert plane.admit("key:a", "interactive", None) is None
    # the principal's queries burned 500 device-ms since admission
    ledger.entries["key:a"]["deviceMs"] = 500.0
    v = plane.admit("key:a", "interactive", None)
    assert v is not None and v.status == 429
    assert v.reason == "deviceMsPerS"
    # debt of ~490ms at 10ms/s -> long wait, capped at the ceiling
    assert v.retry_after == pytest.approx(qos.RETRY_AFTER_MAX_S)


def test_plane_health_red_sheds():
    plane = QosPlane(mode="enforce",
                     health_fn=lambda: {"score": "red", "reasons": []})
    v = plane.admit("p", "interactive", None)
    assert v is not None and v.status == 503 and v.reason == "healthRed"
    assert plane.shed["healthRed"] == 1


def test_plane_estimated_wait_sheds_against_deadline():
    plane = QosPlane(mode="enforce")
    plane.wait_ewma_ms = 500.0
    plane._sig_t = time.monotonic() + 3600  # pin the injected signal
    # 100 ms of budget against a 500 ms estimated wait: shed early
    v = plane.admit("p", "interactive", 0.1)
    assert v is not None and v.status == 503
    assert v.reason == "estimatedWait"
    assert 0 < v.retry_after <= qos.RETRY_AFTER_MAX_S
    # plenty of budget: admitted
    assert plane.admit("p", "interactive", 10.0) is None
    # already expired: shed, not executed
    v = plane.admit("p", "interactive", -0.1)
    assert v is not None and v.reason == "deadline"


def test_plane_bounded_principal_tables():
    plane = QosPlane(mode="enforce", queries_per_s=1000.0,
                     max_principals=4)
    for i in range(50):
        plane.admit(f"key:{i}", "interactive", None)
    assert len(plane._principals) <= 4
    assert len(plane._per_principal) <= 4
    snap = plane.snapshot()
    assert snap["mode"] == "enforce"
    assert sum(snap["admitted"].values()) == 50


def test_rejection_retry_after_is_capped():
    r = Rejection(429, 1e9, "queriesPerS", "m")
    assert r.retry_after == qos.RETRY_AFTER_MAX_S
    assert qos.retry_after_header(0.2) == "1"
    assert qos.retry_after_header(2.4) == "3"


# ------------------------------------------------------------ config plumb


def test_qos_config_toml_roundtrip(tmp_path):
    from pilosa_tpu.cli.config import Config, load_config
    toml = tmp_path / "c.toml"
    toml.write_text(
        '[qos]\nmode = "observe"\ndefault-priority = "batch"\n'
        'default-deadline = "500ms"\nqueries-per-s = 25.0\n'
        '[qos.principals."key:etl"]\npriority = "internal"\n'
        "queries-per-s = 5\n"
        '[gossip]\nsecret = "hush"\n')
    cfg = load_config(str(toml))
    assert cfg.qos.mode == "observe"
    assert cfg.qos.default_priority == "batch"
    assert cfg.qos.default_deadline == pytest.approx(0.5)
    assert cfg.qos.queries_per_s == 25.0
    assert cfg.qos.principals["key:etl"]["priority"] == "internal"
    assert cfg.gossip.secret == "hush"
    # generated TOML parses back to the same qos section
    rendered = Config()
    rendered.qos.mode = "enforce"
    rendered.qos.principals = {"key:x": {"queries-per-s": 9.0}}
    import tomli as tomllib  # noqa: F401 — py3.10 fallback name
    try:
        import tomllib as tl
    except ModuleNotFoundError:
        import tomli as tl
    back = tl.loads(rendered.to_toml())
    assert back["qos"]["mode"] == "enforce"
    assert back["qos"]["principals"]["key:x"]["queries-per-s"] == 9.0


def test_env_kill_switch_does_not_clobber_config_section():
    """PILOSA_TPU_QOS=0 is the runtime kill switch, NOT a config path:
    the env merge must leave the [qos] section object intact (and the
    dotted forms like PILOSA_TPU_QOS_MODE must still work)."""
    from pilosa_tpu.cli.config import QosConfig, load_config
    cfg = load_config(environ={"PILOSA_TPU_QOS": "0",
                               "PILOSA_TPU_QOS_MODE": "observe"})
    assert isinstance(cfg.qos, QosConfig)
    assert cfg.qos.mode == "observe"


def test_server_rejects_bad_qos_mode(tmp_path):
    from pilosa_tpu.server import Server
    with pytest.raises(ValueError):
        Server(str(tmp_path / "bad"), port=0, qos_mode="enfroce")


# ---------------------------------------------------------------- live HTTP


def _post(uri, path, body, key=None, hdrs=None):
    h = dict(hdrs or {})
    if key:
        h["X-API-Key"] = key
    req = urllib.request.Request(uri + path, data=body, method="POST",
                                 headers=h)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture()
def enforce_server(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "q"), port=0, qos_mode="enforce",
                 qos_queries_per_s=2.0, qos_burst=1.0,
                 qos_principals={
                     "key:vip": {"queries-per-s": 100000},
                     "key:etl": {"priority": "batch"}}).open()
    uri = srv.uri
    _post(uri, "/index/t", b"{}", key="vip")
    _post(uri, "/index/t/field/f", b"{}", key="vip")
    _post(uri, "/index/t/query", b"Set(1, f=1)", key="vip")
    yield srv, uri
    srv.close()


def test_http_quota_throttles_with_retry_after(enforce_server):
    srv, uri = enforce_server
    out = [_post(uri, "/index/t/query", b"Count(Row(f=1))", key="flood")
           for _ in range(6)]
    codes = [st for st, _, _ in out]
    assert codes.count(200) == 2  # rate 2/s, burst 1s -> 2 tokens
    rejected = [(st, h, b) for st, h, b in out if st == 429]
    assert len(rejected) == 4
    for st, h, body in rejected:
        assert int(h["Retry-After"]) >= 1
        assert h["X-Pilosa-Shed-Reason"] == "queriesPerS"
        assert json.loads(body)["code"] == "quota-exhausted"
    # the VIP principal's override keeps it unthrottled through the storm
    assert all(_post(uri, "/index/t/query", b"Count(Row(f=1))",
                     key="vip")[0] == 200 for _ in range(10))
    snap = srv.qos.snapshot()
    assert snap["throttled"]["queriesPerS"] == 4
    assert snap["perPrincipal"]["key:flood"]["throttled"] == 4
    # sheds are deliberate backpressure, not server errors: the health
    # score's 5xx input must not see them
    assert srv.handler.errors_5xx == 0


def test_http_doomed_query_shed_by_class_cost(enforce_server):
    """Enforce mode sheds a query whose class's observed device cost
    already exceeds its remaining deadline — 503 + code=shed, before any
    execution."""
    srv, uri = enforce_server
    srv.qos.observe_service("count", 10_000.0)  # counts "cost" 10s
    st, h, body = _post(uri, "/index/t/query?timeout=200ms",
                        b"Count(Row(f=1))", key="vip")
    assert st == 503
    assert json.loads(body)["code"] == "shed"
    assert srv.qos.shed["estimatedCost"] == 1
    srv.qos._class_cost_ms.clear()


def test_http_priority_rides_profile_and_vars(enforce_server):
    srv, uri = enforce_server
    st, _, body = _post(uri, "/index/t/query?profile=true",
                        b"Count(Row(f=1))", key="etl")
    assert st == 200
    prof = json.loads(body)["profile"]
    # the override (not the default) decided the class, and it shows in
    # the profile tree's qos node
    assert prof["qos"]["priority"] == "batch"
    v = json.loads(urllib.request.urlopen(uri + "/debug/vars",
                                          timeout=10).read())
    assert v["qos"]["mode"] == "enforce"
    assert v["qos"]["admitted"]["batch"] >= 1


def test_kill_switch_disables_enforcement(enforce_server, monkeypatch):
    srv, uri = enforce_server
    monkeypatch.setenv("PILOSA_TPU_QOS", "0")
    codes = [_post(uri, "/index/t/query", b"Count(Row(f=1))",
                   key="killswitch")[0] for _ in range(10)]
    assert codes == [200] * 10  # quota would have allowed only 2


def test_observe_mode_counts_without_rejecting(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "obs"), port=0, qos_mode="observe",
                 qos_queries_per_s=1.0, qos_burst=1.0).open()
    try:
        uri = srv.uri
        _post(uri, "/index/o", b"{}")
        _post(uri, "/index/o/field/f", b"{}")
        _post(uri, "/index/o/query", b"Set(1, f=1)")
        codes = [_post(uri, "/index/o/query", b"Count(Row(f=1))",
                       key="noisy")[0] for _ in range(5)]
        assert codes == [200] * 5  # nothing rejected...
        snap = srv.qos.snapshot()
        assert snap["wouldThrottled"]["queriesPerS"] >= 1  # ...but seen
        assert snap["throttled"]["queriesPerS"] == 0
    finally:
        srv.close()


# ------------------------------------------------------------ 3-node plane


def _jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def trio(tmp_path_factory):
    """3-node cluster with pinned ids (deterministic placement) and a
    6-shard index so node a's queries genuinely fan out."""
    from pilosa_tpu.server import Server
    tmp = tmp_path_factory.mktemp("qos3")
    servers = [Server(str(tmp / f"n{i}"), port=0, replica_n=1,
                      node_id=chr(ord("a") + i)).open() for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    rng = np.random.default_rng(7)
    u = uris[0]
    _jpost(u, "/index/i", {})
    _jpost(u, "/index/i/field/f", {})
    cols = np.unique(rng.choice(6 * SW, 4000))
    _jpost(u, "/index/i/field/f/import",
           {"rowIDs": [0] * cols.size, "columnIDs": cols.tolist()})
    expect = int(cols.size)
    deadline = time.monotonic() + 30
    while True:  # async create-shard announcements must settle
        out = _jpost(u, "/index/i/query", raw=b"Count(Row(f=0))")
        if out["results"][0] == expect:
            break
        assert time.monotonic() < deadline, out
        time.sleep(0.2)
    yield servers, uris, expect
    for s in servers:
        s.close()


def test_remote_deadline_is_coordinator_budget_minus_elapsed(trio):
    """The deadline budget SHRINKS as it crosses nodes: each remote sees
    the coordinator's budget minus wire/queue elapsed, never a fresh
    budget and never more than the coordinator had."""
    servers, uris, expect = trio
    budget = 5.0
    seen = {}  # node_id -> remaining at remote execution entry
    originals = {}
    from pilosa_tpu.utils import qctx

    def wrap(srv):
        orig = srv.api.query_results
        originals[srv.node_id] = orig

        def spy(*a, **k):
            if k.get("remote"):
                seen[srv.node_id] = qctx.remaining()
            return orig(*a, **k)
        srv.api.query_results = spy

    for s in servers[1:]:
        wrap(s)
    try:
        t0 = time.monotonic()
        out = _jpost(uris[0], f"/index/i/query?timeout={budget}s",
                     raw=b"Count(Row(f=0))")
        elapsed = time.monotonic() - t0
        assert out["results"][0] == expect
        assert seen, "query never fanned out to a remote"
        for node, rem in seen.items():
            assert rem is not None, f"{node} executed without a deadline"
            # strictly less than the full budget (time elapsed on the
            # coordinator + wire), strictly positive, and consistent
            # with the observed wall clock
            assert 0 < rem < budget, (node, rem)
            assert rem >= budget - elapsed - 0.5, (node, rem, elapsed)
    finally:
        for s in servers[1:]:
            s.api.query_results = originals[s.node_id]


def test_expired_entry_shed_remotely_without_device_dispatch(trio):
    """An envelope entry whose inherited deadline is already spent is
    rejected at the remote's execution boundary: the error comes back
    per-entry, the remote counts a deadlineRemote shed, and its count
    batcher never dispatched for it."""
    servers, uris, _ = trio
    remote = servers[1]
    before_shed = remote.qos.shed["deadlineRemote"]
    before_batches = remote.executor.batcher.batches
    out = remote.client.query_batch(uris[1], [
        {"index": "i", "query": "Count(Row(f=0))", "remote": True,
         "timeout": 0.0, "principal": "key:doomed"}])
    assert len(out) == 1
    assert "deadline" in out[0]["err"]
    assert remote.qos.shed["deadlineRemote"] == before_shed + 1
    assert remote.executor.batcher.batches == before_batches


def test_priority_header_propagates_to_remote_entries(trio):
    """X-Pilosa-Priority rides the fan-out (envelope field / header) so
    the remote's batchers order the work under the caller's class."""
    servers, uris, expect = trio
    seen = []
    orig = servers[1].api.query_batch
    orig2 = servers[2].api.query_batch

    def spy(entries, _orig=orig):
        seen.extend(e.get("priority") for e in entries)
        return _orig(entries)

    def spy2(entries, _orig=orig2):
        seen.extend(e.get("priority") for e in entries)
        return _orig(entries)

    servers[1].api.query_batch = spy
    servers[2].api.query_batch = spy2
    try:
        st, _, body = _post(uris[0], "/index/i/query",
                            b"Count(Row(f=0))",
                            hdrs={"X-Pilosa-Priority": "batch"})
        assert st == 200
        assert json.loads(body)["results"][0] == expect
        # whichever remotes were hit saw the batch class on every entry
        assert seen and all(p == "batch" for p in seen)
    finally:
        servers[1].api.query_batch = orig
        servers[2].api.query_batch = orig2
