"""L0 kernel tests: dense bitvector algebra vs. numpy ground truth.

Mirrors the role of the reference's roaring container-op matrix tests
(roaring/roaring_internal_test.go) — here the matrix is dense, so the ground
truth is plain numpy set algebra over column lists.
"""

import numpy as np
import pytest

from pilosa_tpu import ops
from pilosa_tpu.constants import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.ops.bitvector import range_mask, set_range, xor_range, zero_range

RNG = np.random.default_rng(42)


def random_columns(n, width=SHARD_WIDTH):
    return np.unique(RNG.integers(0, width, size=n))


def test_dense_roundtrip():
    cols = random_columns(5000)
    dense = ops.dense_from_columns(cols)
    assert dense.shape == (WORDS_PER_SHARD,)
    assert dense.dtype == np.uint32
    back = ops.columns_from_dense(dense)
    np.testing.assert_array_equal(back, cols)


def test_dense_empty_and_bounds():
    dense = ops.dense_from_columns(np.array([], dtype=np.int64))
    assert ops.columns_from_dense(dense).size == 0
    with pytest.raises(ValueError):
        ops.dense_from_columns(np.array([SHARD_WIDTH]))
    with pytest.raises(ValueError):
        ops.dense_from_columns(np.array([-1]))


@pytest.mark.parametrize("na,nb", [(0, 100), (100, 0), (3000, 5000), (1, 1)])
def test_pairwise_ops_match_set_algebra(na, nb):
    a_cols, b_cols = random_columns(na), random_columns(nb)
    a, b = ops.dense_from_columns(a_cols), ops.dense_from_columns(b_cols)
    sa, sb = set(a_cols.tolist()), set(b_cols.tolist())

    cases = {
        "and": (ops.band, sa & sb),
        "or": (ops.bor, sa | sb),
        "xor": (ops.bxor, sa ^ sb),
        "andnot": (ops.bandnot, sa - sb),
    }
    for name, (fn, expect) in cases.items():
        got = set(ops.columns_from_dense(np.asarray(fn(a, b))).tolist())
        assert got == expect, name


def test_counts():
    a_cols, b_cols = random_columns(4000), random_columns(6000)
    a, b = ops.dense_from_columns(a_cols), ops.dense_from_columns(b_cols)
    sa, sb = set(a_cols.tolist()), set(b_cols.tolist())
    assert int(ops.popcount(a)) == len(sa)
    assert int(ops.intersect_count(a, b)) == len(sa & sb)
    assert int(ops.union_count(a, b)) == len(sa | sb)
    assert int(ops.difference_count(a, b)) == len(sa - sb)
    assert int(ops.xor_count(a, b)) == len(sa ^ sb)


def test_batched_broadcasting():
    # Stacked [rows, words] slab: kernels must broadcast over leading axes.
    rows = np.stack([ops.dense_from_columns(random_columns(n)) for n in (10, 500, 4096)])
    counts = np.asarray(ops.row_popcounts(rows))
    expect = [len(ops.columns_from_dense(r)) for r in rows]
    np.testing.assert_array_equal(counts, expect)

    other = ops.dense_from_columns(random_columns(2000))
    inter = np.asarray(ops.intersect_count(rows, other))
    expect = [
        len(set(ops.columns_from_dense(r).tolist()) & set(ops.columns_from_dense(other).tolist()))
        for r in rows
    ]
    np.testing.assert_array_equal(inter, expect)


def test_complement_count():
    cols = random_columns(1234)
    a = ops.dense_from_columns(cols)
    assert int(ops.popcount(ops.bnot(a))) == SHARD_WIDTH - len(cols)


@pytest.mark.parametrize("start,end", [(0, 0), (0, 64), (5, 37), (100, 100000), (0, 1 << 16)])
def test_range_ops(start, end):
    width = 1 << 16
    n_words = width // 32
    mask = np.asarray(range_mask(np.uint32(start), np.uint32(end), n_words))
    expect = set(range(start, min(end, width)))
    assert set(ops.columns_from_dense(mask).tolist()) == expect

    base_cols = random_columns(500, width=1 << 16)
    base = ops.dense_from_columns(base_cols, width=1 << 16)
    sbase = set(base_cols.tolist())
    assert set(ops.columns_from_dense(np.asarray(set_range(base, mask))).tolist()) == sbase | expect
    assert set(ops.columns_from_dense(np.asarray(zero_range(base, mask))).tolist()) == sbase - expect
    assert set(ops.columns_from_dense(np.asarray(xor_range(base, mask))).tolist()) == sbase ^ expect


def test_count_pair_stream_matches_numpy():
    """The batched query-stream kernel (one dispatch, K queries) agrees with
    per-query numpy counts and chains its carry."""
    import jax.numpy as jnp
    from pilosa_tpu.parallel.mesh import count_pair_stream

    rng = np.random.default_rng(11)
    rows = rng.integers(0, 2**32, size=(4, 3, WORDS_PER_SHARD), dtype=np.uint32)
    ii = jnp.array([0, 1, 3], dtype=jnp.int32)
    jj = jnp.array([2, 3, 3], dtype=jnp.int32)
    expect = sum(int(np.bitwise_count(rows[i] & rows[j]).sum())
                 for i, j in [(0, 2), (1, 3), (3, 3)])
    got = int(count_pair_stream(jnp.asarray(rows), ii, jj, jnp.uint32(5)))
    assert got == expect + 5


def test_pair_stream_counts_replica_mesh():
    """Replica-parallel query stream (shard_map: queries sharded over
    "replica", data sharded over "shard" with psum): per-query counts match
    numpy, including the K % replicas padding path."""
    import jax.numpy as jnp
    from pilosa_tpu.parallel.mesh import (DeviceRunner, make_mesh,
                                          pair_stream_counts)

    mesh = make_mesh(replicas=2)  # 2x4 on the 8-device CPU mesh
    runner = DeviceRunner(mesh)
    rng = np.random.default_rng(12)
    rows = rng.integers(0, 2**32, size=(6, 4, WORDS_PER_SHARD), dtype=np.uint32)
    slab = jnp.stack([runner.put_leaf(rows[r]) for r in range(6)])
    k = 7  # odd: exercises padding to a multiple of 2 replicas
    ii = rng.integers(0, 6, size=k).astype(np.int32)
    jj = rng.integers(0, 6, size=k).astype(np.int32)
    counts = pair_stream_counts(mesh, slab, ii, jj)
    assert counts.shape == (k,)
    for q in range(k):
        expect = int(np.bitwise_count(rows[ii[q]] & rows[jj[q]]).sum())
        assert counts[q] == expect


def test_group_by_slice_buckets():
    """Devices bucket by slice_index ascending; missing attr → one bucket."""
    from pilosa_tpu.parallel import mesh as pmesh

    class Dev:
        def __init__(self, s):
            self.slice_index = s

    a, b, c, d = Dev(1), Dev(0), Dev(1), Dev(0)
    assert pmesh.group_by_slice([a, b, c, d]) == [[b, d], [a, c]]
    no_topo = pmesh.group_by_slice([object(), object()])
    assert len(no_topo) == 1 and len(no_topo[0]) == 2


def test_multislice_mesh_single_slice_falls_back():
    """CPU devices carry no slice topology → plain 1-D shard mesh."""
    from pilosa_tpu.parallel import mesh as pmesh

    m = pmesh.make_multislice_mesh()
    assert m.axis_names == (pmesh.SHARD_AXIS,)
    assert m.devices.size == 8


def test_multislice_mesh_two_slices(monkeypatch):
    """Simulated 2-slice topology: bucketed-reshape fallback yields a
    ("replica", "shard") mesh and pair_stream_counts matches numpy —
    the DCN multi-slice form of the reference's ReplicaN node groups."""
    import jax
    import jax.numpy as jnp
    from pilosa_tpu.parallel import mesh as pmesh

    devs = jax.devices()
    monkeypatch.setattr(pmesh, "group_by_slice",
                        lambda ds: [list(ds[:4]), list(ds[4:])])
    m = pmesh.make_multislice_mesh(devs)
    assert m.axis_names == (pmesh.REPLICA_AXIS, pmesh.SHARD_AXIS)
    assert m.devices.shape == (2, 4)

    runner = pmesh.DeviceRunner(m)
    rng = np.random.default_rng(21)
    rows = rng.integers(0, 2**32, size=(4, 4, WORDS_PER_SHARD),
                        dtype=np.uint32)
    slab = jnp.stack([runner.put_leaf(rows[r]) for r in range(4)])
    ii = np.array([0, 1, 2], dtype=np.int32)
    jj = np.array([3, 2, 2], dtype=np.int32)
    counts = pmesh.pair_stream_counts(m, slab, ii, jj)
    for q in range(3):
        expect = int(np.bitwise_count(rows[ii[q]] & rows[jj[q]]).sum())
        assert counts[q] == expect


def test_mesh_from_config_multislice_auto():
    """[mesh] replicas = 0 routes through make_multislice_mesh (single
    CPU slice here → 1-D fallback, still a working mesh)."""
    from pilosa_tpu.parallel.mesh import mesh_from_config

    m = mesh_from_config(devices="auto", replicas=0)
    assert m is not None and m.devices.size == 8
