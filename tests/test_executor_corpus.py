"""Ported executor test corpus: table-driven PQL -> result cases at the
reference's coverage breadth (executor_test.go, 3175 LoC of scenario
tests — VERDICT r3 weak #2).

Three mechanisms give several hundred cases without transliterating Go:

1. A GENERATED algebra corpus: every op tree up to depth 3 over a fixed
   3-shard world, checked against a Python set model (Row + Count per
   tree). This is strictly broader than the reference's hand-picked
   Union/Intersect/Difference/Xor/Not combinations.
2. Curated scenario tables for the semantics the generator can't reach:
   writes (Set/Clear/ClearRow/Store/mutex/bool), BSI (all operators,
   negative values, filters, Min/Max/Sum), time ranges (YMDH quantum
   windows), TopN option cross-products, Rows paging, GroupBy shapes,
   Options, attrs, existence/Not edges.
3. Keyed-index renderers: every result type that can carry keys, with
   translation checked both directions (executor.go translateCall /
   translateResults, :2323-2483).

The module runs its whole corpus twice: single-device and on the 8-device
replica mesh (the fixture param), matching how the reference runs its
executor tests against MustRunCluster sizes.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor, ValCount
from pilosa_tpu.models import FieldOptions, FieldType, Holder
from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

SW = SHARD_WIDTH


# ---------------------------------------------------------------- the world


class World:
    """Deterministic 3-shard dataset + Python set models."""

    F_ROWS = 5
    G_ROWS = 3

    def __init__(self, tmpdir: str, mesh):
        self.holder = Holder(tmpdir).open()
        self.ex = Executor(self.holder, runner=DeviceRunner(mesh))
        idx = self.holder.create_index("w")
        f = idx.create_field("f")
        g = idx.create_field("g")
        rng = np.random.default_rng(71)
        self.f_sets: dict[int, set] = {}
        self.g_sets: dict[int, set] = {}
        self.existence: set = set()
        for r in range(self.F_ROWS):
            cols = rng.choice(3 * SW, size=40 + 13 * r, replace=False)
            self.f_sets[r] = set(int(c) for c in cols)
            f.import_bits([r] * cols.size, cols)
            self.existence |= self.f_sets[r]
        for r in range(self.G_ROWS):
            cols = rng.choice(3 * SW, size=30 + 9 * r, replace=False)
            self.g_sets[r] = set(int(c) for c in cols)
            g.import_bits([r] * cols.size, cols)
            self.existence |= self.g_sets[r]
        for c in sorted(self.existence):
            idx.mark_exists(c)

    def close(self):
        self.holder.close()


@pytest.fixture(scope="module", params=["single", "replica_mesh"])
def world(request, tmp_path_factory):
    mesh = make_mesh(replicas=2) if request.param == "replica_mesh" else None
    w = World(str(tmp_path_factory.mktemp(f"corpus-{request.param}")), mesh)
    yield w
    w.close()


# ------------------------------------------------- generated algebra corpus


def _gen_trees():
    """All op trees to depth 3 over a fixed leaf pool — (pql, model_fn)."""
    leaves = [(f"Row(f={r})", ("f", r)) for r in range(3)] + \
             [(f"Row(g={r})", ("g", r)) for r in range(2)]

    def model(w: World, spec):
        if isinstance(spec, tuple) and spec[0] in ("f", "g"):
            return (w.f_sets if spec[0] == "f" else w.g_sets)[spec[1]]
        op, args = spec
        sets = [model(w, a) for a in args]
        if op == "Union":
            out = set()
            for s in sets:
                out |= s
            return out
        if op == "Intersect":
            out = sets[0].copy()
            for s in sets[1:]:
                out &= s
            return out
        if op == "Difference":
            out = sets[0].copy()
            for s in sets[1:]:
                out -= s
            return out
        if op == "Xor":
            out = sets[0].copy()
            for s in sets[1:]:
                out ^= s
            return out
        if op == "Not":
            return w.existence - sets[0]
        raise AssertionError(op)

    cases = []
    # depth 1: leaves
    pool1 = list(leaves)
    # depth 2: every op over ordered leaf pairs (+ Not over each leaf)
    pool2 = []
    for op in ("Union", "Intersect", "Difference", "Xor"):
        for i, (pa, sa) in enumerate(leaves):
            for pb, sb in leaves[i:i + 2]:  # neighbor pairs bound the count
                pool2.append((f"{op}({pa}, {pb})", (op, [sa, sb])))
    pool2 += [(f"Not({p})", ("Not", [s])) for p, s in leaves[:3]]
    # depth 3: ops combining depth-2 nodes with leaves (sampled grid)
    pool3 = []
    for op in ("Union", "Intersect", "Difference", "Xor"):
        for j, (p2, s2) in enumerate(pool2):
            pl, sl = leaves[j % len(leaves)]
            pool3.append((f"{op}({p2}, {pl})", (op, [s2, sl])))
    pool3 += [(f"Not({p})", ("Not", [s])) for p, s in pool2[:8]]
    # 3-arg variadic forms
    for op in ("Union", "Intersect", "Xor", "Difference"):
        pa, sa = leaves[0]
        pb, sb = leaves[2]
        pc, sc = leaves[3]
        pool3.append((f"{op}({pa}, {pb}, {pc})", (op, [sa, sb, sc])))
    for p, s in pool1 + pool2 + pool3:
        cases.append(pytest.param(p, s, id=p[:60]))
    return cases, model


_ALGEBRA_CASES, _model = _gen_trees()


@pytest.mark.parametrize("pql,spec", _ALGEBRA_CASES)
def test_algebra(world, pql, spec):
    expect = sorted(_model(world, spec))
    (r,) = world.ex.execute("w", pql)
    assert r.columns().tolist() == expect, pql
    (c,) = world.ex.execute("w", f"Count({pql})")
    assert c == len(expect), pql


def test_empty_variants(world):
    """Empty / missing-row forms (Execute_Empty_* in the reference)."""
    for pql, expect in [
        ("Row(f=99)", []),
        ("Union(Row(f=99), Row(g=99))", []),
        ("Intersect(Row(f=0), Row(f=99))", []),
        ("Difference(Row(f=99), Row(f=0))", []),
        ("Xor(Row(f=99), Row(f=99))", []),
        ("Union(Row(f=0))", sorted(world.f_sets[0])),
        ("Intersect(Row(f=0))", sorted(world.f_sets[0])),
        # zero-arg Union/Xor = empty row (executor.go:1446,1468)
        ("Union()", []),
        ("Xor()", []),
    ]:
        (r,) = world.ex.execute("w", pql)
        assert r.columns().tolist() == expect, pql
    # zero-arg Intersect/Difference are errors (executor.go:835,1214)
    for pql in ("Intersect()", "Difference()"):
        with pytest.raises(ExecutionError):
            world.ex.execute("w", pql)


def test_count_forms(world):
    for pql, spec in [("Row(f=1)", ("f", 1)),
                      ("Union(Row(f=0), Row(g=0))",
                       ("Union", [("f", 0), ("g", 0)]))]:
        (c,) = world.ex.execute("w", f"Count({pql})")
        assert c == len(_model(world, spec))


# --------------------------------------------------------- write semantics


@pytest.fixture()
def wex(tmp_path):
    h = Holder(str(tmp_path / "w")).open()
    e = Executor(h)
    yield e
    h.close()


def test_set_semantics(wex):
    wex.holder.create_index("i").create_field("f")
    # new bit -> True; repeat -> False; cross-shard columns
    cases = [(3, 1, True), (3, 1, False), (SW + 3, 1, True),
             (2 * SW + 7, 1, True), (3, 2, True)]
    for col, row, expect in cases:
        (changed,) = wex.execute("i", f"Set({col}, f={row})")
        assert changed is expect, (col, row)
    (r,) = wex.execute("i", "Row(f=1)")
    assert r.columns().tolist() == [3, SW + 3, 2 * SW + 7]
    # multi-call write request: per-call results in order
    out = wex.execute("i", "Set(9, f=1) Set(9, f=1) Clear(9, f=1)")
    assert out == [True, False, True]


def test_clear_semantics(wex):
    f = wex.holder.create_index("i").create_field("f")
    f.import_bits([1, 1, 2], [0, SW, 0])
    assert wex.execute("i", "Clear(0, f=1)") == [True]
    assert wex.execute("i", "Clear(0, f=1)") == [False]  # already clear
    assert wex.execute("i", "Clear(5, f=9)") == [False]  # missing row
    (r,) = wex.execute("i", "Row(f=1)")
    assert r.columns().tolist() == [SW]
    (r,) = wex.execute("i", "Row(f=2)")  # untouched row survives
    assert r.columns().tolist() == [0]


def test_bool_field(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("b", FieldOptions(type=FieldType.BOOL))
    wex.execute("i", "Set(1, b=true) Set(2, b=false) Set(3, b=true)")
    (r,) = wex.execute("i", "Row(b=true)")
    assert r.columns().tolist() == [1, 3]
    # flipping a column moves it between the two rows (bool = 2-row mutex)
    wex.execute("i", "Set(1, b=false)")
    (r,) = wex.execute("i", "Row(b=true)")
    assert r.columns().tolist() == [3]
    (r,) = wex.execute("i", "Row(b=false)")
    assert r.columns().tolist() == [1, 2]


def test_mutex_field(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("m", FieldOptions(type=FieldType.MUTEX))
    wex.execute("i", "Set(7, m=1)")
    wex.execute("i", "Set(7, m=2)")  # replaces row 1's bit
    (r1,) = wex.execute("i", "Row(m=1)")
    (r2,) = wex.execute("i", "Row(m=2)")
    assert r1.columns().tolist() == [] and r2.columns().tolist() == [7]


def test_clear_row_forms(wex):
    f = wex.holder.create_index("i").create_field("f")
    f.import_bits([1, 1, 2], [0, SW + 1, 2])
    (ch,) = wex.execute("i", "ClearRow(f=1)")
    assert ch is True
    (ch,) = wex.execute("i", "ClearRow(f=1)")  # already empty
    assert ch is False
    (r,) = wex.execute("i", "Row(f=2)")
    assert r.columns().tolist() == [2]


def test_store_overwrites(wex):
    f = wex.holder.create_index("i").create_field("f")
    f.import_bits([1, 1, 9, 9], [0, SW, 5, 6])
    # Store REPLACES the target row (SetRow, executor.go:883)
    wex.execute("i", "Store(Row(f=1), f=9)")
    (r,) = wex.execute("i", "Row(f=9)")
    assert r.columns().tolist() == [0, SW]
    # storing an empty source empties the target
    wex.execute("i", "Store(Row(f=42), f=9)")
    (r,) = wex.execute("i", "Row(f=9)")
    assert r.columns().tolist() == []


# ------------------------------------------------------------------- BSI


@pytest.fixture()
def bsi(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("v", FieldOptions(type=FieldType.INT,
                                       min=-100, max=1000))
    idx.create_field("f")
    vals = {0: -100, 1: -3, 2: 0, 3: 7, 4: 500, SW + 1: 7, SW + 2: 1000,
            2 * SW + 3: -50}
    for c, v in vals.items():
        wex.execute("i", f"Set({c}, v={v})")
    wex.execute("i", "Set(1, f=1) Set(3, f=1) Set(" + str(SW + 2) + ", f=1)")
    return wex, vals


_BSI_OPS = [
    ("<", lambda v, a: v < a), ("<=", lambda v, a: v <= a),
    (">", lambda v, a: v > a), (">=", lambda v, a: v >= a),
    ("==", lambda v, a: v == a), ("!=", lambda v, a: v != a),
]
_BSI_OPERANDS = [-100, -50, -3, 0, 7, 500, 1000]


@pytest.mark.parametrize("op,fn", _BSI_OPS)
@pytest.mark.parametrize("operand", _BSI_OPERANDS)
def test_bsi_operator_grid(bsi, op, fn, operand):
    """42-case operator x operand grid incl. negatives and extremes
    (BSIGroupRange, executor_test.go:1621)."""
    wex, vals = bsi
    (r,) = wex.execute("i", f"Range(v {op} {operand})")
    expect = sorted(c for c, v in vals.items() if fn(v, operand))
    assert r.columns().tolist() == expect, (op, operand)


def test_bsi_between_and_null(bsi):
    wex, vals = bsi
    (r,) = wex.execute("i", "Range(-50 < v < 500)")
    assert r.columns().tolist() == sorted(
        c for c, v in vals.items() if -50 < v < 500)
    (r,) = wex.execute("i", "Range(v >< [-3, 7])")
    assert r.columns().tolist() == sorted(
        c for c, v in vals.items() if -3 <= v <= 7)
    (r,) = wex.execute("i", "Range(v != null)")
    assert r.columns().tolist() == sorted(vals)


def test_bsi_aggregates_with_filters(bsi):
    wex, vals = bsi
    (vc,) = wex.execute("i", "Sum(field=v)")
    assert vc == ValCount(sum(vals.values()), len(vals))
    (vc,) = wex.execute("i", "Min(field=v)")
    assert vc == ValCount(-100, 1)
    (vc,) = wex.execute("i", "Max(field=v)")
    assert vc == ValCount(1000, 1)
    fset = {1, 3, SW + 2}
    (vc,) = wex.execute("i", "Sum(Row(f=1), field=v)")
    assert vc == ValCount(sum(vals[c] for c in fset), 3)
    (vc,) = wex.execute("i", "Min(Row(f=1), field=v)")
    assert vc == ValCount(-3, 1)
    (vc,) = wex.execute("i", "Max(Row(f=1), field=v)")
    assert vc == ValCount(1000, 1)
    # aggregate over a Range filter (compose on device)
    (vc,) = wex.execute("i", "Sum(Range(v > 0), field=v)")
    pos = [v for v in vals.values() if v > 0]
    assert vc == ValCount(sum(pos), len(pos))
    # duplicate values: Min/Max count ties
    wex.execute("i", "Set(9, v=-100)")
    (vc,) = wex.execute("i", "Min(field=v)")
    assert vc == ValCount(-100, 2)


def test_bsi_overwrite_and_range_edges(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=100))
    wex.execute("i", "Set(1, v=50)")
    wex.execute("i", "Set(1, v=60)")  # overwrite
    (vc,) = wex.execute("i", "Sum(field=v)")
    assert vc == ValCount(60, 1)
    with pytest.raises(Exception):
        wex.execute("i", "Set(2, v=101)")  # out of range


# ------------------------------------------------------------ time ranges


def test_time_range_windows(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                       time_quantum="YMDH"))
    sets = [
        (1, 10, "2010-01-01T00:00"),
        (1, 11, "2010-01-02T00:00"),
        (1, 12, "2010-02-01T00:00"),
        (1, 13, "2011-01-01T00:00"),
        (1, 14, "2010-01-01T13:00"),
    ]
    for row, col, ts in sets:
        wex.execute("i", f"Set({col}, t={row}, {ts})")
    cases = [
        ("2010-01-01T00:00", "2010-01-01T23:59", [10, 14]),
        ("2010-01-01T00:00", "2010-01-31T23:59", [10, 11, 14]),
        ("2010-01-01T00:00", "2010-12-31T23:59", [10, 11, 12, 14]),
        ("2010-01-01T00:00", "2011-12-31T23:59", [10, 11, 12, 13, 14]),
        # whole units only: [13:00, 14:00) covers hour 13; [13:00, 13:59)
        # contains no complete hour and matches nothing (viewsByTimeRange
        # semantics, time.go)
        ("2010-01-01T13:00", "2010-01-01T14:00", [14]),
        ("2010-01-01T13:00", "2010-01-01T13:59", []),
        ("2012-01-01T00:00", "2013-01-01T00:00", []),
    ]
    for frm, to, expect in cases:
        (r,) = wex.execute("i", f"Range(t=1, {frm}, {to})")
        assert r.columns().tolist() == expect, (frm, to)
    # standard view still answers plain Row across all time
    (r,) = wex.execute("i", "Row(t=1)")
    assert r.columns().tolist() == [10, 11, 12, 13, 14]


# ------------------------------------------------------- TopN cross product


@pytest.fixture()
def topn_world(wex):
    idx = wex.holder.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=100))
    sets = {1: [0, 1, 2, SW, SW + 1], 2: [0, 5, SW + 2], 3: [9],
            4: [0, 1, 5, 9, SW, 2 * SW + 1], 5: [2 * SW + 5]}
    for r, cs in sets.items():
        f.import_bits([r] * len(cs), cs)
    return wex, {r: set(cs) for r, cs in sets.items()}


@pytest.mark.parametrize("n", [1, 2, 3, 10])
def test_topn_n(topn_world, n):
    wex, sets = topn_world
    (pairs,) = wex.execute("i", f"TopN(f, n={n})")
    brute = sorted(((len(cs), -r) for r, cs in sets.items()), reverse=True)
    expect = [(-nr, c) for c, nr in brute[:n]]
    assert [tuple(p) for p in pairs] == expect


@pytest.mark.parametrize("ids,threshold", [
    ("[1, 2]", 0), ("[1, 2]", 4), ("[4]", 0), ("[9]", 0)])
def test_topn_ids_threshold(topn_world, ids, threshold):
    wex, sets = topn_world
    opts = f", ids={ids}" if ids else ""
    if threshold:
        opts += f", threshold={threshold}"
    (pairs,) = wex.execute("i", f"TopN(f, n=10{opts})")
    import json

    want_ids = [r for r in json.loads(ids) if r in sets]
    brute = [(r, len(sets[r])) for r in want_ids]
    if threshold:
        brute = [(r, c) for r, c in brute if c >= threshold]
    brute.sort(key=lambda rc: (-rc[1], rc[0]))
    assert [tuple(p) for p in pairs] == brute


def test_topn_src_and_tanimoto(topn_world):
    wex, sets = topn_world
    (pairs,) = wex.execute("i", "TopN(f, Row(f=4), n=10)")
    brute = [(r, len(cs & sets[4])) for r, cs in sets.items()
             if cs & sets[4]]
    brute.sort(key=lambda rc: (-rc[1], rc[0]))
    assert [tuple(p) for p in pairs] == brute
    # tanimotoThreshold prunes by similarity to the src row
    (pairs,) = wex.execute(
        "i", "TopN(f, Row(f=1), n=10, tanimotoThreshold=50)")
    for r, c in pairs:
        inter = len(sets[r] & sets[1])
        tani = 100 * inter // (len(sets[r]) + len(sets[1]) - inter)
        assert tani >= 50, (r, tani)
    got_rows = {p[0] for p in pairs}
    for r, cs in sets.items():
        inter = len(cs & sets[1])
        if inter:
            tani = 100 * inter // (len(cs) + len(sets[1]) - inter)
            assert (tani >= 50) == (r in got_rows), r


# ------------------------------------------------------------ Rows paging


def test_rows_paging_grid(wex):
    f = wex.holder.create_index("i").create_field("f")
    rows = [2, 3, 5, 8, 13, 21]
    for r in rows:
        f.import_bits([r] * 2, [r, SW + r])
    for prev, limit, expect in [
        (None, None, rows), (None, 3, rows[:3]), (2, None, rows[1:]),
        (5, 2, [8, 13]), (21, None, []), (0, 1, [2]), (22, None, []),
    ]:
        q = "Rows(field=f"
        if prev is not None:
            q += f", previous={prev}"
        if limit is not None:
            q += f", limit={limit}"
        (got,) = wex.execute("i", q + ")")
        assert got == expect, (prev, limit)
    (got,) = wex.execute("i", f"Rows(field=f, column={SW + 8})")
    assert got == [8]
    (got,) = wex.execute("i", "Rows(field=f, column=4)")
    assert got == []


# ------------------------------------------------------------ keyed paths


@pytest.fixture()
def keyed(tmp_path):
    from pilosa_tpu.utils.translate import TranslateStore

    h = Holder(str(tmp_path / "k")).open()
    ts = TranslateStore().open()
    e = Executor(h, translator=ts)
    h.create_index("ki", keys=True).create_field("f", FieldOptions(keys=True))
    yield e, ts
    h.close()


def test_keyed_set_row_topn_rows(keyed):
    e, ts = keyed

    def col_id(k):
        return ts.translate_column("ki", k, create=False)

    for col, row in [("a", "foo"), ("b", "foo"), ("c", "foo"),
                     ("a", "bar"), ("b", "baz")]:
        (ch,) = e.execute("ki", f'Set("{col}", f="{row}")')
        assert ch is True
    # Row column ids map back through the translator (column keys render
    # at the API layer; the executor returns ids — executor.py
    # _translate_result docstring)
    (r,) = e.execute("ki", 'Row(f="foo")')
    assert sorted(r.columns().tolist()) == sorted(
        col_id(k) for k in ("a", "b", "c"))
    (c,) = e.execute("ki", 'Count(Union(Row(f="foo"), Row(f="bar")))')
    assert c == 3
    (pairs,) = e.execute("ki", "TopN(f, n=2)")
    assert pairs.row_keys[0] == "foo" and pairs[0][1] == 3
    (rows,) = e.execute("ki", "Rows(field=f)")
    assert set(rows.row_keys) == {"foo", "bar", "baz"}
    (r,) = e.execute("ki", 'Difference(Row(f="foo"), Row(f="bar"))')
    assert sorted(r.columns().tolist()) == sorted(
        col_id(k) for k in ("b", "c"))
    (r,) = e.execute("ki", 'Row(f="nosuch")')
    assert r.columns().tolist() == []
    # unknown-key reads must not mint ids
    assert ts.translate_row("ki", "f", "nosuch", create=False) is None


def test_keyed_groupby_and_clear(keyed):
    e, _ = keyed
    e.execute("ki", 'Set("a", f="x") Set("b", f="x") Set("a", f="y")')
    (groups,) = e.execute("ki", "GroupBy(Rows(field=f))")
    got = {g["group"][0].get("rowKey"): g["count"] for g in groups}
    assert got == {"x": 2, "y": 1}
    (ch,) = e.execute("ki", 'Clear("a", f="x")')
    assert ch is True
    (c,) = e.execute("ki", 'Count(Row(f="x"))')
    assert c == 1


# ----------------------------------------------------- Options / existence


def test_options_shards_and_exclude(wex):
    f = wex.holder.create_index("i", track_existence=True).create_field("f")
    wex.execute("i", f"Set(1, f=1) Set({SW + 1}, f=1) Set({2 * SW + 2}, f=1)")
    (r,) = wex.execute("i", "Options(Row(f=1), shards=[0, 2])")
    assert r.columns().tolist() == [1, 2 * SW + 2]
    (r,) = wex.execute("i", "Options(Row(f=1), excludeColumns=true)")
    assert r.columns().tolist() == []


def test_existence_not_edges(wex):
    idx = wex.holder.create_index("i", track_existence=True)
    idx.create_field("f")
    idx.create_field("g")
    wex.execute("i", "Set(1, f=1) Set(2, f=1) Set(3, g=1)")
    (r,) = wex.execute("i", "Not(Row(f=1))")
    assert r.columns().tolist() == [3]
    (r,) = wex.execute("i", "Not(Not(Row(f=1)))")
    assert r.columns().tolist() == [1, 2]
    (r,) = wex.execute("i", "Not(Row(g=99))")  # Not of empty = everything
    assert r.columns().tolist() == [1, 2, 3]
    (r,) = wex.execute("i", "Intersect(Not(Row(f=1)), Row(g=1))")
    assert r.columns().tolist() == [3]
    # a cleared column STAYS in existence (reference semantics: existence
    # is append-only until the column is deleted)
    wex.execute("i", "Clear(2, f=1)")
    (r,) = wex.execute("i", "Not(Row(f=1))")
    assert r.columns().tolist() == [2, 3]


def test_attrs_render(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("f")
    wex.execute("i", "Set(1, f=1)")
    wex.execute("i", 'SetRowAttrs(f, 1, color="red", weight=3)')
    assert idx.field("f").row_attrs.attrs(1) == {"color": "red", "weight": 3}
    wex.execute("i", 'SetColumnAttrs(1, city="x")')
    assert idx.column_attrs.attrs(1) == {"city": "x"}


def test_error_cases(wex):
    wex.holder.create_index("i").create_field("f")
    for bad in ["Nope(Row(f=1))", "Count()", "Row(nosuch=1)",
                "Sum(field=nosuch)"]:
        with pytest.raises(Exception):
            wex.execute("i", bad)


def test_keyed_rows_paging(keyed):
    """Rows paging by row KEY (previous="...") on a keyed field
    (executor.go:2693 RowKey paging)."""
    e, _ = keyed
    e.execute("ki", 'Set("a", f="x") Set("b", f="y") Set("c", f="z")')
    (all_rows,) = e.execute("ki", "Rows(field=f)")
    keys = all_rows.row_keys
    assert set(keys) == {"x", "y", "z"}
    (page,) = e.execute("ki", f'Rows(field=f, previous="{keys[0]}")')
    assert page.row_keys == keys[1:]
    # unknown/stale previous key ERRORS (translate-or-error, ADVICE r4):
    # silently restarting from the beginning would re-send the full set
    # to a paging client
    from pilosa_tpu.executor import ExecutionError
    with pytest.raises(ExecutionError, match="nosuch"):
        e.execute("ki", 'Rows(field=f, previous="nosuch")')


def test_rows_previous_validation(wex):
    """Fractional/invalid `previous` fails loudly instead of silently
    shifting the page window."""
    f = wex.holder.create_index("i").create_field("f")
    f.import_bits([3, 4], [0, 1])
    with pytest.raises(Exception):
        wex.execute("i", "Rows(field=f, previous=2.5)")


# ------------------------------------------------ additional scenario depth


def test_time_quantum_variants(wex):
    """Coarser quanta produce coarser covers (YM: whole months only)."""
    idx = wex.holder.create_index("i")
    idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                       time_quantum="YM"))
    wex.execute("i", "Set(1, t=1, 2010-01-15T10:00)")
    wex.execute("i", "Set(2, t=1, 2010-03-02T00:00)")
    # end must reach April for March to be a COMPLETE covered month
    (r,) = wex.execute("i", "Range(t=1, 2010-01-01T00:00, 2010-04-01T00:00)")
    assert r.columns().tolist() == [1, 2]
    (r,) = wex.execute("i", "Range(t=1, 2010-01-01T00:00, 2010-03-31T23:59)")
    assert r.columns().tolist() == [1]  # March incomplete: col 2 excluded
    (r,) = wex.execute("i", "Range(t=1, 2010-02-01T00:00, 2010-04-01T00:00)")
    assert r.columns().tolist() == [2]
    # sub-month window: no complete month covered
    (r,) = wex.execute("i", "Range(t=1, 2010-01-02T00:00, 2010-01-20T00:00)")
    assert r.columns().tolist() == []


def test_not_compositions(wex):
    idx = wex.holder.create_index("i", track_existence=True)
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=50))
    wex.execute("i", "Set(1, f=1) Set(2, f=1) Set(3, f=2)")
    wex.execute("i", "Set(1, v=10) Set(2, v=40) Set(3, v=20)")
    (c,) = wex.execute("i", "Count(Not(Row(f=1)))")
    assert c == 1
    (r,) = wex.execute("i", "Not(Range(v > 15))")
    assert r.columns().tolist() == [1]
    (r,) = wex.execute("i", "Union(Not(Row(f=1)), Row(f=1))")
    assert r.columns().tolist() == [1, 2, 3]  # existence partition
    (c,) = wex.execute("i", "Count(Intersect(Not(Row(f=1)), Not(Row(f=2))))")
    assert c == 0


def test_store_from_arbitrary_sources(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=50))
    wex.execute("i", "Set(1, f=1) Set(2, f=1) Set(5, f=2)")
    wex.execute("i", "Set(1, v=10) Set(2, v=40) Set(5, v=45)")
    # Store a BSI comparison result as a materialized row
    wex.execute("i", "Store(Range(v > 30), f=77)")
    (r,) = wex.execute("i", "Row(f=77)")
    assert r.columns().tolist() == [2, 5]
    # Store a compound expression
    wex.execute("i", "Store(Intersect(Row(f=1), Range(v > 30)), f=78)")
    (r,) = wex.execute("i", "Row(f=78)")
    assert r.columns().tolist() == [2]
    # overwrite the stored row with a different source
    wex.execute("i", "Store(Row(f=2), f=77)")
    (r,) = wex.execute("i", "Row(f=77)")
    assert r.columns().tolist() == [5]


def test_min_max_all_negative(wex):
    idx = wex.holder.create_index("i")
    idx.create_field("v", FieldOptions(type=FieldType.INT,
                                       min=-1000, max=-1))
    wex.execute("i", "Set(1, v=-5) Set(2, v=-1000) Set(3, v=-5)")
    (vc,) = wex.execute("i", "Min(field=v)")
    assert (vc.val, vc.count) == (-1000, 1)
    (vc,) = wex.execute("i", "Max(field=v)")
    assert (vc.val, vc.count) == (-5, 2)
    (vc,) = wex.execute("i", "Sum(field=v)")
    assert (vc.val, vc.count) == (-1010, 3)


def test_groupby_filter_and_limit_interplay(wex):
    idx = wex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    f.import_bits([1, 1, 2, 2, 3], [0, 1, 1, 2, 9])
    g.import_bits([7, 7, 8], [1, 2, 0])
    (groups,) = wex.execute(
        "i", "GroupBy(Rows(field=f), Rows(field=g), filter=Row(f=1))")
    got = {(d["group"][0]["rowID"], d["group"][1]["rowID"]): d["count"]
           for d in groups}
    # counts intersected with Row(f=1) = {0, 1}
    assert got == {(1, 7): 1, (1, 8): 1, (2, 7): 1}
    (groups,) = wex.execute(
        "i", "GroupBy(Rows(field=f), Rows(field=g), limit=2)")
    assert len(groups) == 2  # lexicographic cutoff
    (groups,) = wex.execute(
        "i", "GroupBy(Rows(field=f, previous=1), Rows(field=g))")
    assert all(d["group"][0]["rowID"] > 1 for d in groups)


def test_topn_attr_ids_cross(wex):
    idx = wex.holder.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=50))
    f.import_bits([1] * 4 + [2] * 3 + [3] * 2 + [4] * 1,
                  [0, 1, 2, 3, 0, 1, 2, 0, 1, 0])
    wex.execute("i", 'SetRowAttrs(f, 1, cat="a")')
    wex.execute("i", 'SetRowAttrs(f, 2, cat="b")')
    wex.execute("i", 'SetRowAttrs(f, 3, cat="a")')
    (pairs,) = wex.execute(
        "i", 'TopN(f, n=10, attrName=cat, attrValues=["a"])')
    assert [tuple(p) for p in pairs] == [(1, 4), (3, 2)]
    # attr filter x ids: intersection of both restrictions
    (pairs,) = wex.execute(
        "i", 'TopN(f, n=10, ids=[1, 2], attrName=cat, attrValues=["a"])')
    assert [tuple(p) for p in pairs] == [(1, 4)]


def test_count_distinct_shard_boundaries(wex):
    """Bits on exact shard edges land in the right shard's fan-out."""
    f = wex.holder.create_index("i").create_field("f")
    edge = [0, SW - 1, SW, 2 * SW - 1, 2 * SW, 3 * SW - 1]
    f.import_bits([1] * len(edge), edge)
    (c,) = wex.execute("i", "Count(Row(f=1))")
    assert c == len(edge)
    (r,) = wex.execute("i", "Row(f=1)")
    assert r.columns().tolist() == edge
