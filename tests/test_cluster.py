"""Cluster placement + resize planning tests.

Mirrors cluster_internal_test.go: partition/jump-hash placement vs hand-built
clusters, fragSources resize planning, state machine.
"""

import pytest

from pilosa_tpu.parallel.cluster import (
    EVENT_JOIN,
    EVENT_LEAVE,
    STATE_DEGRADED,
    STATE_NORMAL,
    STATE_RESIZING,
    STATE_STARTING,
    Cluster,
    Node,
)
from pilosa_tpu.parallel.placement import ModHasher, fnv64a, jump_hash, partition


def make_cluster(n, replica_n=1, schema=None, hasher=None):
    c = Cluster("node0", replica_n=replica_n, hasher=hasher,
                schema_fn=(lambda: schema) if schema else None)
    c.set_static([Node(id=f"node{i}", uri=f"http://host{i}:10101") for i in range(n)])
    return c


def test_fnv64a_vectors():
    # published FNV-1a 64 test vectors
    assert fnv64a(b"") == 0xCBF29CE484222325
    assert fnv64a(b"a") == 0xAF63DC4C8601EC8C
    assert fnv64a(b"foobar") == 0x85944171F73967E8


def test_jump_hash_properties():
    # deterministic, in-range, and monotone-consistent: growing n only moves
    # keys INTO the new bucket
    for n in (1, 2, 5, 16):
        for key in range(200):
            b = jump_hash(key, n)
            assert 0 <= b < n
    moved = 0
    for key in range(1000):
        b5, b6 = jump_hash(key, 5), jump_hash(key, 6)
        if b5 != b6:
            assert b6 == 5
            moved += 1
    # ~1/6 of keys move
    assert 100 < moved < 250


def test_partition_stability():
    # partition depends on index name and shard
    assert partition("i", 0) == partition("i", 0)
    spread = {partition("i", s) for s in range(1000)}
    assert len(spread) > 200  # well-spread over 256 partitions


def test_placement_replicas():
    c = make_cluster(4, replica_n=2)
    nodes = c.shard_nodes("i", 7)
    assert len(nodes) == 2
    assert nodes[0].id != nodes[1].id
    # replicas are ring successors
    ids = [n.id for n in c.nodes]
    i0 = ids.index(nodes[0].id)
    assert nodes[1].id == ids[(i0 + 1) % 4]
    # replica_n clamped to cluster size
    c2 = make_cluster(2, replica_n=5)
    assert len(c2.shard_nodes("i", 1)) == 2


def test_owns_and_group_by_node():
    c = make_cluster(3, hasher=ModHasher())
    groups = c.shards_by_node("i", list(range(12)))
    total = sum(len(v) for v in groups.values())
    assert total == 12
    for node_id, shards in groups.items():
        for s in shards:
            assert c.owns_shard(node_id, "i", s)


def test_resize_plan_join():
    schema = {"i": {"f": list(range(8))}}
    c = make_cluster(2, schema=schema)
    job = c.node_join(Node(id="node9", uri="http://host9:10101"))
    assert c.state == STATE_RESIZING
    assert job is not None
    # every fetch instruction targets the new topology and sources an old owner
    old_ids = {"node0", "node1"}
    for target, sources in job.instructions.items():
        for src in sources:
            assert src.from_node in old_ids
            assert target not in (src.from_node,)
    # the new node must appear in the instruction map
    assert "node9" in job.instructions
    # completing all instructions transitions to NORMAL and adds the node
    for node_id in list(job.instructions):
        c.complete_resize(job, node_id)
    assert c.state == STATE_NORMAL
    assert c.node_by_id("node9") is not None


def test_resize_plan_leave():
    schema = {"i": {"f": list(range(8))}}
    c = make_cluster(3, replica_n=2, schema=schema)
    job = c.node_leave("node2")
    assert job is not None and c.state == STATE_RESIZING
    for target, sources in job.instructions.items():
        assert target != "node2"
        for src in sources:
            assert src.from_node != "node2" or True  # donor must survive
            assert src.from_node in {"node0", "node1"}
    for node_id in list(job.instructions):
        c.complete_resize(job, node_id)
    assert c.state == STATE_NORMAL
    assert c.node_by_id("node2") is None


def test_leave_below_replica_degrades():
    c = make_cluster(2, replica_n=2)
    job = c.node_leave("node1")
    assert job is None
    assert c.state == STATE_DEGRADED
    assert c.node_by_id("node1") is None


def test_abort_resize():
    schema = {"i": {"f": [0]}}
    c = make_cluster(2, schema=schema)
    c.node_join(Node(id="nodez"))
    assert c.state == STATE_RESIZING
    c.abort_resize()
    assert c.state == STATE_NORMAL
    assert c.node_by_id("nodez") is None


def test_topology_persistence(tmp_path):
    path = str(tmp_path / ".topology")
    c = Cluster("a", topology_path=path)
    c.add_node(Node(id="a"))
    c.add_node(Node(id="b"))
    c2 = Cluster("a", topology_path=path)
    assert c2.load_topology() == ["a", "b"]


def test_initial_state():
    c = Cluster("x")
    assert c.state == STATE_STARTING
    c.set_static([Node(id="x")])
    assert c.state == STATE_NORMAL
    assert c.is_coordinator()
