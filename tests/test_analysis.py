"""pilosa-lint + runtime lock-order witness (tier-1).

Three layers:

* rule units — each lint rule against synthetic sources, positive and
  negative;
* the tree gate — `run_all(repo root)` must return ZERO findings (the
  committed baseline is empty and stays empty), plus the
  `python -m pilosa_tpu.analysis --check` CLI contract (exit 0 on the
  clean tree, exit 1 on an injected violation);
* the witness — an induced A→B / B→A inversion and a lock held across a
  fake RPC must both be detected with the offending stacks; reentrant
  RLocks, Condition/Event integration and consistent orders must stay
  silent; and the live suite (witnessed via conftest) must stay clean
  through a real server query.

Plus the thread-boundary contextvar regression tests: a profiled query's
trace/principal/deadline/priority must survive every background hop now
that all spawn sites route through utils.threads (enforced by the
`ctx-thread` rule over the tree).
"""

import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.analysis import (config_knob_findings, env_gate_findings,
                                 lockwitness, run_all)
from pilosa_tpu.analysis.lint import lint_source
from pilosa_tpu.utils import accounting, qctx, threads, tracing

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- lint units


def test_lint_flags_raw_thread_and_timer():
    src = ("import threading\n"
           "t = threading.Thread(target=print)\n"
           "threading.Timer(1.0, print).start()\n")
    fs = lint_source("pilosa_tpu/x.py", src)
    assert [f.rule for f in fs] == ["ctx-thread", "ctx-thread"]
    assert fs[0].line == 2 and fs[1].line == 3


def test_lint_flags_from_import_thread_alias():
    src = ("from threading import Thread as T\n"
           "T(target=print).start()\n")
    assert rules(lint_source("pilosa_tpu/x.py", src)) == ["ctx-thread"]


def test_lint_allows_threads_wrapper_module():
    src = "import threading\nt = threading.Thread(target=print)\n"
    assert lint_source("pilosa_tpu/utils/threads.py", src) == []


def test_lint_submit_rule():
    bad = "fut = self._fanout_pool.submit(fn, 1)\n"
    good = ("import contextvars\n"
            "fut = pool.submit(contextvars.copy_context().run, fn, 1)\n")
    not_a_pool = "out = self.submit(key, payload)\n"  # batcher protocol
    assert rules(lint_source("pilosa_tpu/x.py", bad)) == ["ctx-submit"]
    assert lint_source("pilosa_tpu/x.py", good) == []
    assert lint_source("pilosa_tpu/x.py", not_a_pool) == []


def test_lint_swallowed_future():
    bad = "pool.submit(contextvars.copy_context().run, fn)\n"
    good = "fut = pool.submit(contextvars.copy_context().run, fn)\n"
    assert rules(lint_source("pilosa_tpu/x.py",
                             "import contextvars\n" + bad)) \
        == ["swallowed-future"]
    assert lint_source("pilosa_tpu/x.py",
                       "import contextvars\n" + good) == []


def test_lint_wall_clock_rule():
    bad = "import time\ndeadline = time.time() + 5\n"
    same_line = "import time\nts = time.time()  # wall-clock: serialized\n"
    prev_line = ("import time\n"
                 "# wall-clock: export timestamps\n"
                 "ts = time.time()\n")
    monotonic = "import time\nd = time.monotonic() + 5\n"
    assert rules(lint_source("pilosa_tpu/x.py", bad)) == ["wall-clock"]
    assert lint_source("pilosa_tpu/x.py", same_line) == []
    assert lint_source("pilosa_tpu/x.py", prev_line) == []
    assert lint_source("pilosa_tpu/x.py", monotonic) == []


def test_lint_bare_except():
    bad = "try:\n    pass\nexcept:\n    pass\n"
    good = "try:\n    pass\nexcept Exception:\n    pass\n"
    assert rules(lint_source("pilosa_tpu/x.py", bad)) == ["bare-except"]
    assert lint_source("pilosa_tpu/x.py", good) == []


def test_lint_lock_blocking():
    bad = ("import os\n"
           "with self._lock:\n"
           "    os.fsync(fd)\n")
    rpc = ("with self.mu:\n"
           "    client.query_proto(uri, i, q)\n")
    deferred = ("with self._lock:\n"
                "    def later():\n"
                "        os.fsync(fd)\n")
    not_a_lock = "with open(p) as f:\n    os.fsync(f.fileno())\n"
    assert rules(lint_source("pilosa_tpu/x.py", bad)) == ["lock-blocking"]
    assert rules(lint_source("pilosa_tpu/x.py", rpc)) == ["lock-blocking"]
    assert lint_source("pilosa_tpu/x.py", deferred) == []
    assert lint_source("pilosa_tpu/x.py", not_a_lock) == []


def test_lint_stats_registry():
    bad = "s = StatsClient()\n"
    assert rules(lint_source("pilosa_tpu/x.py", bad)) == ["stats-registry"]
    assert lint_source("pilosa_tpu/utils/stats.py", bad) == []
    assert lint_source("pilosa_tpu/server.py", bad) == []


def test_lint_raw_jit():
    bare = "import jax\n@jax.jit\ndef f(a):\n    return a\n"
    configured = ("import jax\n@jax.jit(static_argnames=('k',))\n"
                  "def f(a, k):\n    return a\n")
    call_form = "import jax\ng = jax.jit(lambda a: a)\n"
    aliased = "from jax import jit as J\n@J\ndef f(a):\n    return a\n"
    counted = ("from pilosa_tpu.utils.telemetry import counted_jit\n"
               "@counted_jit('bsi')\ndef f(a):\n    return a\n")
    for bad in (bare, configured, call_form, aliased):
        assert rules(lint_source("pilosa_tpu/ops/x.py", bad)) == ["raw-jit"]
    # counted_jit is the sanctioned wrapper
    assert lint_source("pilosa_tpu/ops/x.py", counted) == []
    # scope is pilosa_tpu/ops/ only — jit elsewhere is someone else's call
    assert lint_source("pilosa_tpu/executor.py", bare) == []


# ------------------------------------------------------------- the tree gate


def test_tree_is_lint_clean():
    """THE gate: zero findings over the real tree — AST rules AND the
    env-gate / config-knob inventory diffs. The committed baseline plays
    no part here; a baselined finding still fails."""
    findings = run_all(ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_committed_baseline_is_empty():
    path = os.path.join(ROOT, "pilosa_tpu", "analysis", "baseline.txt")
    with open(path, encoding="utf-8") as f:
        entries = [ln for ln in (l.strip() for l in f)
                   if ln and not ln.startswith("#")]
    assert entries == [], "the baseline must stay empty; fix, don't suppress"


def test_cli_check_passes_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "pilosa_tpu.analysis", "--check",
         "--root", ROOT],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout


def test_cli_check_fails_on_injected_finding(tmp_path):
    """A mini-tree with one raw-thread violation (docs copied from the
    real tree so the inventory rules stay quiet) must exit 1 and name
    the file:line."""
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    bad = pkg / "bad.py"
    bad.write_text("import threading\n"
                   "threading.Thread(target=print).start()\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    with open(os.path.join(ROOT, "docs", "operations.md"),
              encoding="utf-8") as f:
        (docs / "operations.md").write_text(f.read())
    proc = subprocess.run(
        [sys.executable, "-m", "pilosa_tpu.analysis", "--check",
         "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "bad.py:2: ctx-thread" in proc.stdout


def test_cli_baseline_suppresses_but_check_reports(tmp_path):
    """The incident-branch escape hatch: a baselined finding passes
    --check but still prints (marked), so it cannot vanish silently."""
    pkg = tmp_path / "pilosa_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text("import threading\n"
                                "threading.Thread(target=print).start()\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    with open(os.path.join(ROOT, "docs", "operations.md"),
              encoding="utf-8") as f:
        (docs / "operations.md").write_text(f.read())
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("# incident hotfix\npilosa_tpu/bad.py:ctx-thread\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pilosa_tpu.analysis", "--check",
         "--root", str(tmp_path), "--baseline", str(baseline)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "(baselined)" in proc.stdout


def test_env_gate_inventory_sees_known_gates():
    from pilosa_tpu.analysis.inventories import env_gate_inventory
    inv = env_gate_inventory(ROOT)
    assert "PILOSA_TPU_LOCKCHECK" in inv
    assert "PILOSA_TPU_QOS" in inv
    assert "PILOSA_TPU_WAL_FSYNC" in inv
    assert env_gate_findings(ROOT) == []


def test_config_knob_inventory_complete():
    from pilosa_tpu.analysis.inventories import config_knob_inventory
    knobs = dict.fromkeys(f"{s}.{k}" if s else k
                          for s, k in config_knob_inventory())
    # spot checks incl. the knobs this PR wired into to_toml
    for expect in ("cluster.query-timeout", "cluster.liveness-threshold",
                   "cluster.membership-interval", "log-path",
                   "qos.mode", "slo.burn-red"):
        assert expect in knobs
    assert config_knob_findings(ROOT) == []


# ------------------------------------------------------------- lock witness


def make_locks(witness, *sites):
    # build on the RAW factories: under the suite-wide witness,
    # threading.Lock() here would return an already-wrapped lock whose
    # inner recordings pollute the GLOBAL witness with these tests'
    # intentional inversions (and trip the conftest guard)
    return [lockwitness.WitnessLock(lockwitness._real_lock(), s, witness)
            for s in sites]


def test_witness_detects_ab_ba_inversion_with_stacks():
    w = lockwitness.Witness()
    A, B = make_locks(w, "mod_a.py:10", "mod_b.py:20")
    with A:
        with B:
            pass
    with B:
        with A:  # closes the cycle
            pass
    rep = w.report()
    assert len(rep["cycles"]) == 1
    cyc = rep["cycles"][0]
    assert set(cyc["cycle"]) == {"mod_a.py:10", "mod_b.py:20"}
    # both the closing edge's stack and the prior edge's stack point here
    assert "test_witness_detects_ab_ba_inversion" in cyc["newEdgeStack"]
    prior = list(cyc["priorStacks"].values())
    assert prior and all(
        "test_witness_detects_ab_ba_inversion" in s for s in prior if s)
    assert "LOCK-ORDER CYCLE" in w.format_violations()


def test_witness_transitive_cycle():
    """A→B, B→C, then C→A: the cycle spans three sites."""
    w = lockwitness.Witness()
    A, B, C = make_locks(w, "a.py:1", "b.py:2", "c.py:3")
    with A:
        with B:
            pass
    with B:
        with C:
            pass
    with C:
        with A:
            pass
    rep = w.report()
    assert len(rep["cycles"]) == 1
    assert set(rep["cycles"][0]["cycle"]) == {"a.py:1", "b.py:2", "c.py:3"}


def test_witness_consistent_order_is_silent():
    w = lockwitness.Witness()
    A, B = make_locks(w, "a.py:1", "b.py:2")
    for _ in range(3):
        with A:
            with B:
                pass
    assert w.report()["cycles"] == []
    assert w.violation_count() == 0


def test_witness_held_across_fake_rpc():
    w = lockwitness.Witness()
    L = lockwitness.WitnessRLock(lockwitness._real_rlock(), "srv.py:42", w)
    with L:
        w.note_blocking("rpc", "POST /internal/query-batch")
    rep = w.report()
    assert len(rep["heldAcrossBlocking"]) == 1
    v = rep["heldAcrossBlocking"][0]
    assert v["kind"] == "rpc" and v["held"] == ["srv.py:42"]
    assert "test_witness_held_across_fake_rpc" in v["stack"]
    # identical (kind, held sites) dedup: a hot path reports once
    with L:
        w.note_blocking("rpc", "POST /internal/query-batch")
    assert len(w.report()["heldAcrossBlocking"]) == 1
    # no lock held -> clean
    w2 = lockwitness.Witness()
    w2.note_blocking("rpc", "GET /status")
    assert w2.report()["heldAcrossBlocking"] == []


def test_witness_reentrant_rlock_no_self_noise():
    w = lockwitness.Witness()
    L = lockwitness.WitnessRLock(lockwitness._real_rlock(), "re.py:1", w)
    with L:
        with L:  # reentrant: no edge, no self-edge
            pass
    rep = w.report()
    assert rep["cycles"] == [] and rep["selfEdges"] == []
    # but two DIFFERENT instances from one site nesting -> selfEdges info
    L2 = lockwitness.WitnessRLock(lockwitness._real_rlock(), "re.py:1", w)
    with L:
        with L2:
            pass
    rep = w.report()
    assert rep["selfEdges"] == ["re.py:1"]
    assert rep["cycles"] == []  # info, not a violation


def test_witness_condition_and_event_integration():
    """Condition.wait/notify over a witnessed RLock and Event round trips
    must keep bookkeeping balanced (no phantom held locks)."""
    w = lockwitness.Witness()
    inner = lockwitness.WitnessRLock(lockwitness._real_rlock(), "cv.py:1", w)
    cond = threading.Condition(inner)
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=5)

    t = threads.spawn(waiter)
    time.sleep(0.05)
    with cond:
        ready.append(1)
        cond.notify_all()
    t.join(5)
    assert not t.is_alive()
    # the waiter thread released during wait: nothing held afterwards
    w.note_blocking("rpc", "after")
    assert w.report()["heldAcrossBlocking"] == []
    assert w.report()["cycles"] == []


def test_witness_env_gate_and_passthrough():
    """Without install(), threading.Lock() stays native and
    note_blocking is a no-op even under a held native lock."""
    if lockwitness.ACTIVE:
        lockwitness.uninstall()
        try:
            lk = threading.Lock()
            assert not isinstance(lk, lockwitness.WitnessLock)
        finally:
            lockwitness.install()
    else:
        lk = threading.Lock()
        assert not isinstance(lk, lockwitness.WitnessLock)


def test_suite_runs_witnessed_and_clean():
    """The conftest arms the witness for the whole tier-1 run (the env
    gate opts out); a real server query under it must record no
    violations — the clean-run acceptance in miniature. (The autouse
    guard enforces the same per test; this pins the wiring itself.)"""
    if os.environ.get(lockwitness.ENV_GATE) == "0":
        pytest.skip("witness explicitly disabled")
    assert lockwitness.ACTIVE
    from pilosa_tpu.server import Server
    import tempfile
    before = lockwitness.violation_count()
    with tempfile.TemporaryDirectory() as tmp:
        s = Server(os.path.join(tmp, "n0"), port=0).open()
        try:
            # at least one witnessed lock exists (the server is full of
            # them) and real traffic crossed the choke points
            req = urllib.request.Request(
                s.uri + "/index/w", data=b"{}", method="POST")
            urllib.request.urlopen(req, timeout=30).read()
            req = urllib.request.Request(
                s.uri + "/index/w/query", data=b"Set(1, f=1)",
                method="POST")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req, timeout=30)  # no field: 400
        finally:
            s.close()
    assert lockwitness.violation_count() == before
    assert lockwitness.report()["edges"] > 0


# ---------------------------------------- thread-boundary ctx propagation


def test_spawn_propagates_all_query_contextvars():
    from pilosa_tpu import qos
    seen = {}
    tok_t = tracing.current_trace_id.set("trace-spawn-1")
    acct = accounting.Account(accounting.UsageLedger(), "key:ctx-test")
    tok_a = accounting.current_account.set(acct)
    tok_d = qctx.deadline.set(time.monotonic() + 30)
    tok_p = qos.current_priority.set("batch")
    try:
        t = threads.spawn(lambda: seen.update(
            trace=tracing.current_trace_id.get(),
            acct=accounting.current_account.get(),
            deadline=qctx.deadline.get(),
            prio=qos.current_priority.get()))
        t.join(5)
    finally:
        tracing.current_trace_id.reset(tok_t)
        accounting.current_account.reset(tok_a)
        qctx.deadline.reset(tok_d)
        qos.current_priority.reset(tok_p)
    assert seen["trace"] == "trace-spawn-1"
    assert seen["acct"] is acct
    assert seen["deadline"] is not None and seen["prio"] == "batch"


def test_ctx_thread_and_timer_propagate_trace():
    seen = {}
    tok = tracing.current_trace_id.set("trace-timer-1")
    try:
        t = threads.ctx_thread(
            lambda: seen.__setitem__("t", tracing.current_trace_id.get()))
        t.start()
        t.join(5)
        tm = threads.ctx_timer(0.01, lambda: seen.__setitem__(
            "timer", tracing.current_trace_id.get()))
        tm.start()
        tm.join(5)
    finally:
        tracing.current_trace_id.reset(tok)
    assert seen == {"t": "trace-timer-1", "timer": "trace-timer-1"}


def test_submit_ctx_propagates_through_pool():
    from concurrent.futures import ThreadPoolExecutor
    tok = tracing.current_trace_id.set("trace-pool-1")
    try:
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = threads.submit_ctx(
                pool, lambda: tracing.current_trace_id.get())
            assert fut.result(5) == "trace-pool-1"
    finally:
        tracing.current_trace_id.reset(tok)


def test_telemetry_sampler_tick_keeps_trace():
    """The sampler's background tick chain (one of the paths the lint
    migration covered) runs in the context active at start()."""
    from pilosa_tpu.utils.telemetry import TelemetrySampler
    seen = []

    def source():
        seen.append(tracing.current_trace_id.get())
        return {"g": 1.0}

    tok = tracing.current_trace_id.set("trace-sampler-1")
    try:
        sampler = TelemetrySampler(interval=0.01, ring_size=8,
                                   source=source)
        sampler.start()
        deadline = time.monotonic() + 5
        while not seen and time.monotonic() < deadline:
            time.sleep(0.01)
        sampler.close()
    finally:
        tracing.current_trace_id.reset(tok)
    assert seen and seen[0] == "trace-sampler-1"


def test_hint_replay_from_spawned_heal_keeps_trace(tmp_path):
    """The server's return-heal replays hints on a spawned thread; the
    trace active when the heal was triggered must reach every applied
    hint (the profiled-query-keeps-its-trace regression)."""
    from pilosa_tpu.storage.hints import HintStore
    store = HintStore(str(tmp_path / "hints"))
    store.append("peer-1", "i", "Set(1, f=1)")
    store.append("peer-1", "i", "Set(2, f=1)")
    seen = []

    def apply(doc):
        seen.append((doc["pql"], tracing.current_trace_id.get()))

    tok = tracing.current_trace_id.set("trace-heal-1")
    try:
        t = threads.spawn(lambda: store.replay("peer-1", apply))
        t.join(10)
    finally:
        tracing.current_trace_id.reset(tok)
    assert [p for p, _ in seen] == ["Set(1, f=1)", "Set(2, f=1)"]
    assert all(tid == "trace-heal-1" for _, tid in seen)
    assert store.pending("peer-1") == 0  # replayed prefix retired


def test_hint_replay_concurrent_append_survives(tmp_path):
    """The witness-driven fix (apply outside the per-target lock) must
    not lose hints appended mid-replay: the un-replayed suffix stays for
    the next pass, in order."""
    from pilosa_tpu.storage.hints import HintStore
    store = HintStore(str(tmp_path / "hints"))
    store.append("peer-1", "i", "Set(1, f=1)")
    applied = []

    def apply(doc):
        if not applied:
            # mid-replay, after the snapshot was taken: a new hint lands
            store.append("peer-1", "i", "Set(99, f=1)")
        applied.append(doc["pql"])

    replayed, dropped, complete = store.replay("peer-1", apply)
    assert (replayed, dropped, complete) == (1, 0, True)
    assert applied == ["Set(1, f=1)"]
    assert store.pending("peer-1") > 0  # the mid-replay hint survived
    replayed2, _, _ = store.replay("peer-1", apply)
    assert replayed2 == 1 and applied[-1] == "Set(99, f=1)"
    assert store.pending("peer-1") == 0
