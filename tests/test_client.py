"""InternalClient connection pooling and retry semantics.

The pooled keep-alive client (net/client.py) must reuse connections across
requests, transparently retry exactly the stale-keep-alive failure modes,
surface HTTP error statuses as ClientError, and never retry once response
headers have arrived (side-effect safety). Exercised against a raw-socket
HTTP server whose behavior is scripted per connection.
"""

import socket
import threading

import pytest

from pilosa_tpu.net.client import ClientError, InternalClient


class ScriptedServer:
    """Accepts connections; each connection is handled per `script`, a list
    of per-request actions: "ok" (respond 200, keep alive), "close-after"
    (respond 200 then close), "drop" (close without responding), "400"
    (error status). Tracks connection and request counts."""

    def __init__(self, script):
        self.script = script
        self.sock = socket.create_server(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.connections = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def uri(self):
        return f"http://127.0.0.1:{self.port}"

    def _read_request(self, conn) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                return False
            data += chunk
        head = data.split(b"\r\n\r\n", 1)[0].decode()
        clen = 0
        for line in head.split("\r\n"):
            if line.lower().startswith("content-length:"):
                clen = int(line.split(":", 1)[1])
        body = data.split(b"\r\n\r\n", 1)[1]
        while len(body) < clen:
            chunk = conn.recv(65536)
            if not chunk:
                return False
            body += chunk
        return True

    def _serve(self):
        self.sock.settimeout(10)
        while True:
            try:
                conn, _ = self.sock.accept()
            except (OSError, socket.timeout):
                return
            with self._lock:
                self.connections += 1
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                with self._lock:
                    action = (self.script.pop(0) if self.script else "ok")
                if not self._read_request(conn):
                    return
                with self._lock:
                    self.requests += 1
                if action == "drop":
                    conn.close()
                    return
                body = b'{"ok": true}' if action != "400" \
                    else b'{"error": "bad", "code": "ErrTest"}'
                status = b"200 OK" if action != "400" else b"400 Bad Request"
                conn.sendall(
                    b"HTTP/1.1 " + status + b"\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"\r\n" + body)
                if action == "close-after":
                    conn.close()
                    return
        except OSError:
            pass

    def close(self):
        self.sock.close()


def test_keepalive_reuses_one_connection():
    srv = ScriptedServer(["ok"] * 5)
    try:
        c = InternalClient(timeout=5)
        for _ in range(5):
            assert c._json("POST", srv.uri, "/x", {"a": 1}) == {"ok": True}
        assert srv.requests == 5
        assert srv.connections == 1  # pooled: one TCP connection for all
    finally:
        srv.close()


def test_stale_keepalive_retries_once_transparently():
    # server closes the connection after the first response; the client's
    # second request hits the stale socket and must transparently reconnect
    srv = ScriptedServer(["close-after", "ok"])
    try:
        c = InternalClient(timeout=5)
        assert c._json("POST", srv.uri, "/x", {}) == {"ok": True}
        assert c._json("POST", srv.uri, "/x", {}) == {"ok": True}
        assert srv.connections == 2
    finally:
        srv.close()


def test_fresh_connection_failure_is_an_error_not_a_retry():
    # a connection that dies WITHOUT ever answering is a real peer failure:
    # exactly one reconnect attempt is allowed for the stale case, and a
    # fresh-connection drop must not loop
    srv = ScriptedServer(["drop", "drop", "drop"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError):
            c._json("POST", srv.uri, "/x", {})
        assert srv.connections <= 2  # at most the one stale-style retry
    finally:
        srv.close()


def test_http_error_status_surfaces_code():
    srv = ScriptedServer(["400"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 400
        assert exc.value.code == "ErrTest"
    finally:
        srv.close()


def test_connection_refused_is_clienterror():
    c = InternalClient(timeout=2)
    with pytest.raises(ClientError):
        c._json("POST", "http://127.0.0.1:9", "/x", {})
