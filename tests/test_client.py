"""InternalClient connection pooling and retry semantics.

The pooled keep-alive client (net/client.py) must reuse connections across
requests, transparently retry exactly the stale-keep-alive failure modes,
surface HTTP error statuses as ClientError, and never retry once response
headers have arrived (side-effect safety). Exercised against a raw-socket
HTTP server whose behavior is scripted per connection.
"""

import socket
import threading

import pytest

from pilosa_tpu.net.client import ClientError, InternalClient


class ScriptedServer:
    """Accepts connections; each connection is handled per `script`, a list
    of per-request actions: "ok" (respond 200, keep alive), "close-after"
    (respond 200 then close), "drop" (close without responding), "400"
    (error status). Tracks connection and request counts."""

    def __init__(self, script):
        self.script = script
        self.sock = socket.create_server(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.connections = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def uri(self):
        return f"http://127.0.0.1:{self.port}"

    def _read_request(self, conn) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                return False
            data += chunk
        head = data.split(b"\r\n\r\n", 1)[0].decode()
        clen = 0
        for line in head.split("\r\n"):
            if line.lower().startswith("content-length:"):
                clen = int(line.split(":", 1)[1])
        body = data.split(b"\r\n\r\n", 1)[1]
        while len(body) < clen:
            chunk = conn.recv(65536)
            if not chunk:
                return False
            body += chunk
        return True

    def _serve(self):
        self.sock.settimeout(10)
        while True:
            try:
                conn, _ = self.sock.accept()
            except (OSError, socket.timeout):
                return
            with self._lock:
                self.connections += 1
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                with self._lock:
                    action = (self.script.pop(0) if self.script else "ok")
                if not self._read_request(conn):
                    return
                with self._lock:
                    self.requests += 1
                if action == "drop":
                    conn.close()
                    return
                extra = b""
                if action == "429":
                    body = b'{"error": "quota", "code": "quota-exhausted"}'
                    status = b"429 Too Many Requests"
                    extra = b"Retry-After: 1\r\n"
                elif action == "503-no-retry-after":
                    body = b'{"error": "down"}'
                    status = b"503 Service Unavailable"
                elif action == "503-draining":
                    # the graceful-drain rejection (server.drain): carries
                    # BOTH Retry-After and the shed-reason header
                    body = b'{"error": "node is draining", "code": "shed"}'
                    status = b"503 Service Unavailable"
                    extra = (b"Retry-After: 1\r\n"
                             b"X-Pilosa-Shed-Reason: draining\r\n")
                elif action == "400":
                    body = b'{"error": "bad", "code": "ErrTest"}'
                    status = b"400 Bad Request"
                else:
                    body = b'{"ok": true}'
                    status = b"200 OK"
                conn.sendall(
                    b"HTTP/1.1 " + status + b"\r\n"
                    b"Content-Type: application/json\r\n" + extra +
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"\r\n" + body)
                if action == "close-after":
                    conn.close()
                    return
        except OSError:
            pass

    def close(self):
        self.sock.close()


def test_keepalive_reuses_one_connection():
    srv = ScriptedServer(["ok"] * 5)
    try:
        c = InternalClient(timeout=5)
        for _ in range(5):
            assert c._json("POST", srv.uri, "/x", {"a": 1}) == {"ok": True}
        assert srv.requests == 5
        assert srv.connections == 1  # pooled: one TCP connection for all
    finally:
        srv.close()


def test_stale_keepalive_retries_once_transparently():
    # server closes the connection after the first response; the client's
    # second request hits the stale socket and must transparently reconnect
    srv = ScriptedServer(["close-after", "ok"])
    try:
        c = InternalClient(timeout=5)
        assert c._json("POST", srv.uri, "/x", {}) == {"ok": True}
        assert c._json("POST", srv.uri, "/x", {}) == {"ok": True}
        assert srv.connections == 2
    finally:
        srv.close()


def test_fresh_connection_failure_is_an_error_not_a_retry():
    # a connection that dies WITHOUT ever answering is a real peer failure:
    # exactly one reconnect attempt is allowed for the stale case, and a
    # fresh-connection drop must not loop
    srv = ScriptedServer(["drop", "drop", "drop"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError):
            c._json("POST", srv.uri, "/x", {})
        assert srv.connections <= 2  # at most the one stale-style retry
    finally:
        srv.close()


def test_http_error_status_surfaces_code():
    srv = ScriptedServer(["400"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 400
        assert exc.value.code == "ErrTest"
    finally:
        srv.close()


def test_connection_refused_is_clienterror():
    c = InternalClient(timeout=2)
    with pytest.raises(ClientError):
        c._json("POST", "http://127.0.0.1:9", "/x", {})


# -- 429/503 + Retry-After backpressure (QoS plane contract) ---------------


def test_parse_retry_after_forms():
    from pilosa_tpu.net.client import parse_retry_after
    assert parse_retry_after("3") == 3.0
    assert parse_retry_after(" 1.5 ") == 1.5
    assert parse_retry_after("-2") == 0.0  # negative floors at zero
    assert parse_retry_after(None) is None
    assert parse_retry_after("") is None
    assert parse_retry_after("soon-ish") is None  # garbage: no sleep
    # HTTP-date form -> remaining delta (a past date floors at 0)
    assert parse_retry_after("Wed, 21 Oct 2015 07:28:00 GMT") == 0.0
    from email.utils import format_datetime
    from datetime import datetime, timedelta, timezone
    future = format_datetime(datetime.now(timezone.utc)
                             + timedelta(seconds=40))
    got = parse_retry_after(future)
    assert got is not None and 30 < got <= 41


def test_backoff_delay_is_capped_and_jittered():
    from pilosa_tpu.net.client import RETRY_AFTER_CAP_S, backoff_delay
    # a hostile/huge Retry-After is capped before jitter
    assert backoff_delay(3600.0, rng=lambda: 1.0) == RETRY_AFTER_CAP_S
    assert backoff_delay(3600.0, rng=lambda: 0.0) == RETRY_AFTER_CAP_S / 2
    # jitter spans [0.5, 1.0]x of the (floored) base
    lo = backoff_delay(1.0, rng=lambda: 0.0)
    hi = backoff_delay(1.0, rng=lambda: 1.0)
    assert lo == pytest.approx(0.5) and hi == pytest.approx(1.0)
    # tiny hints floor at 50 ms so the retry isn't a busy-loop
    assert backoff_delay(0.0, rng=lambda: 1.0) == pytest.approx(0.05)


def test_429_with_retry_after_is_retried_then_succeeds(monkeypatch):
    # two rejections then success: the client sleeps the (capped,
    # jittered) hint and re-issues — backpressure honored, not surfaced
    import pilosa_tpu.net.client as client_mod
    sleeps = []
    monkeypatch.setattr(client_mod.time, "sleep", sleeps.append)
    srv = ScriptedServer(["429", "429", "ok"])
    try:
        c = InternalClient(timeout=5)
        assert c._json("POST", srv.uri, "/x", {}) == {"ok": True}
        assert srv.requests == 3
        assert len(sleeps) == 2
        assert all(0.05 <= s <= client_mod.RETRY_AFTER_CAP_S
                   for s in sleeps)
    finally:
        srv.close()


def test_429_retries_are_bounded(monkeypatch):
    import pilosa_tpu.net.client as client_mod
    monkeypatch.setattr(client_mod.time, "sleep", lambda s: None)
    srv = ScriptedServer(["429"] * 10)
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 429
        assert exc.value.retry_after == 1.0
        assert srv.requests == 1 + client_mod.BACKPRESSURE_RETRIES
    finally:
        srv.close()


def test_503_without_retry_after_is_not_retried():
    # a bare 503 (peer crash-looping, proxy error) carries no
    # backpressure contract: fail fast so per-shard failover engages
    srv = ScriptedServer(["503-no-retry-after", "ok"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 503
        assert exc.value.retry_after is None
        assert srv.requests == 1
    finally:
        srv.close()


def test_503_draining_fails_over_immediately_no_backoff(monkeypatch):
    # a 503 carrying X-Pilosa-Shed-Reason: draining means "this node is
    # gracefully restarting — go to another replica": the client must
    # surface it at once (no backoff sleep, no re-issue to the SAME
    # node), even though Retry-After is present — unlike quota 429s,
    # which keep the capped jittered backoff
    import pilosa_tpu.net.client as client_mod
    sleeps = []
    monkeypatch.setattr(client_mod.time, "sleep", sleeps.append)
    srv = ScriptedServer(["503-draining", "ok"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 503
        assert exc.value.shed_reason == "draining"
        assert exc.value.retry_after == 1.0  # parsed, surfaced to caller
        assert srv.requests == 1  # never re-sent to the draining node
        assert sleeps == []  # and never slept
    finally:
        srv.close()


def test_shed_reason_absent_on_plain_errors():
    srv = ScriptedServer(["400"])
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.shed_reason == ""
    finally:
        srv.close()


def test_backpressure_respects_remaining_deadline(monkeypatch):
    # with 10 ms of budget left, a 1 s Retry-After must NOT be slept:
    # the rejection surfaces immediately
    import time as _time

    from pilosa_tpu.utils import qctx
    srv = ScriptedServer(["429", "ok"])
    tok = qctx.deadline.set(_time.monotonic() + 0.01)
    try:
        c = InternalClient(timeout=5)
        with pytest.raises(ClientError) as exc:
            c._json("POST", srv.uri, "/x", {})
        assert exc.value.status == 429
        assert srv.requests == 1
    finally:
        qctx.deadline.reset(tok)
        srv.close()
