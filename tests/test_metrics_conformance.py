"""GET /metrics conformance (tier-1): a minimal Prometheus text-format
parser scrapes a LIVE server and validates every emitted family — legal
metric names from arbitrary stats keys, cumulative non-decreasing
`le` buckets, `_count` == the `+Inf` bucket — so a malformed exposition
can never ship. Unit tests additionally pin the renderer against
adversarial stats keys (slashes, colons, tags, unicode)."""

import json
import re
import urllib.request

import pytest

from pilosa_tpu.utils.stats import StatsClient, prometheus_exposition

METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
SAMPLE_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? (?P<value>[0-9eE+.\-]+|NaN|\+Inf|-Inf)$")
LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """-> (types: {family: type}, samples: [(name, {label: value}, float)]).
    Raises AssertionError on any malformed line — the conformance core."""
    types: dict = {}
    samples: list = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            assert METRIC_NAME.match(fam), f"line {lineno}: bad family {fam!r}"
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), f"line {lineno}: bad type {kind!r}"
            assert fam not in types, f"line {lineno}: duplicate TYPE {fam}"
            types[fam] = kind
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = SAMPLE_LINE.match(line)
        assert m, f"line {lineno}: unparseable sample {line!r}"
        assert METRIC_NAME.match(m["name"]), \
            f"line {lineno}: illegal metric name {m['name']!r}"
        labels = {}
        if m["labels"]:
            consumed = LABEL.findall(m["labels"])
            # every byte of the label block must belong to a legal pair
            rebuilt = ",".join(f'{k}="{v}"' for k, v in consumed)
            assert rebuilt == m["labels"], \
                f"line {lineno}: malformed labels {m['labels']!r}"
            labels = dict(consumed)
        value = float("inf") if m["value"] == "+Inf" else float(m["value"])
        samples.append((m["name"], labels, value))
    return types, samples


def check_conformance(text: str):
    """Full family validation; returns (types, samples) for extra asserts."""
    types, samples = parse_exposition(text)
    # every sample belongs to a declared family (name or name+suffix)
    fams = set(types)
    for name, _, _ in samples:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in fams:
                base = name[: -len(suffix)]
        assert base in fams, f"sample {name} has no # TYPE"
    # histograms: per label-series, le buckets cumulative + capped by +Inf
    hist_fams = [f for f, k in types.items() if k == "histogram"]
    for fam in hist_fams:
        series: dict = {}
        counts: dict = {}
        for name, labels, value in samples:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            if name == fam + "_bucket":
                series.setdefault(key, []).append((labels["le"], value))
            elif name == fam + "_count":
                counts[key] = value
        assert series, f"histogram {fam} emitted no buckets"
        for key, buckets in series.items():
            bounds = [float("inf") if le == "+Inf" else float(le)
                      for le, _ in buckets]
            assert bounds == sorted(bounds), \
                f"{fam}{key}: le bounds out of order: {bounds}"
            assert bounds[-1] == float("inf"), f"{fam}{key}: no +Inf bucket"
            vals = [v for _, v in buckets]
            assert vals == sorted(vals), \
                f"{fam}{key}: buckets not cumulative: {vals}"
            assert key in counts, f"{fam}{key}: missing _count"
            assert counts[key] == vals[-1], \
                f"{fam}{key}: _count {counts[key]} != +Inf bucket {vals[-1]}"
    return types, samples


# ------------------------------------------------------------------- unit


def test_renderer_sanitizes_hostile_keys():
    s = StatsClient()
    s.count("query/Count")  # slash namespacing -> key label
    s.count("weird name!@#")  # illegal chars collapse
    s.with_tags("index:idx-1", "bare").count("tagged/x", 3)
    s.gauge("memory/rss", 123.5)
    s.set("uniq/things", "a")
    s.set("uniq/things", "b")
    s.timing("fanoutLatency/node-id-with-dashes", 0.7)
    s.timing("fanoutLatency/node-id-with-dashes", 3.0)
    s.timing("fanoutLatency/node-id-with-dashes", -1.0)  # le0 bucket
    text = prometheus_exposition(s.snapshot())
    types, samples = check_conformance(text)
    assert types["pilosa_query_total"] == "counter"
    assert ("pilosa_query_total", {"key": "Count"}, 1.0) in samples
    assert types["pilosa_fanoutLatency"] == "histogram"
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["pilosa_uniq_cardinality"][0][1] == 2.0
    # tag forms: colon tags become k="v" labels, bare tags tag="..."
    tagged = by_name["pilosa_tagged_total"][0][0]
    assert tagged["index"] == "idx-1" and tagged["tag"] == "bare"
    # the le0 catch-all renders as le="0" and the cascade stays cumulative
    les = [lbl["le"] for lbl, _ in by_name["pilosa_fanoutLatency_bucket"]]
    assert "0" in les and "+Inf" in les


def test_multiple_bare_tags_fold_into_one_label():
    """Repeating a label name ({tag="a",tag="b"}) is illegal exposition;
    multiple bare tags must fold into one `tag` label."""
    s = StatsClient().with_tags("a", "b")
    s.count("multi", 1)
    s.timing("multi_t", 2.0)
    text = prometheus_exposition(s.snapshot())
    types, samples = check_conformance(text)
    labels = next(lbl for n, lbl, _ in samples
                  if n == "pilosa_multi_total")
    assert labels["tag"] == "a,b"
    # the label block parsed cleanly (no duplicate label names survived
    # check_conformance's full-consumption label check)
    assert types["pilosa_multi_t"] == "histogram"


def test_renderer_empty_snapshot():
    assert prometheus_exposition({}) == ""
    types, samples = parse_exposition(prometheus_exposition({}))
    assert not types and not samples


def test_histogram_count_equals_top_bucket_many_series():
    s = StatsClient()
    for node in ("n1", "n2"):
        for v in (0.3, 1.0, 900.0, 2.5, 2.5):
            s.timing(f"fanoutLatency/{node}", v)
    types, samples = check_conformance(
        prometheus_exposition(s.snapshot()))
    counts = [v for name, labels, v in samples
              if name == "pilosa_fanoutLatency_count"]
    assert counts == [5.0, 5.0]
    sums = [v for name, _, v in samples
            if name == "pilosa_fanoutLatency_sum"]
    assert all(abs(v - 906.3) < 1e-6 for v in sums)


# ------------------------------------------------------------ live server


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    """2-node cluster: distributed traffic populates counter AND
    histogram families (fanoutLatency timings need real fan-out)."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("metrics")
    servers = [Server(str(tmp / f"n{i}"), port=0,
                      node_id=chr(ord("a") + i)).open() for i in range(2)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    def jpost(path, payload=None, raw=None):
        body = raw if raw is not None else json.dumps(payload or {}).encode()
        req = urllib.request.Request(uris[0] + path, data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    jpost("/index/m", {})
    jpost("/index/m/field/f", {})
    cols = list(range(0, 4 * 2 ** 20, 9001))
    jpost("/index/m/field/f/import",
          {"rowIDs": [0] * len(cols), "columnIDs": cols})
    for _ in range(3):
        jpost("/index/m/query", raw=b"Count(Row(f=0))")
    yield servers, uris
    for s in servers:
        s.close()


def test_live_metrics_scrape_conforms(pair):
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    types, samples = check_conformance(text)
    # real traffic produced counters...
    assert any(n == "pilosa_query_total" for n, _, _ in samples), text[:400]
    # ...and, when fan-out happened, the log2 timings render as histograms
    if any(k.startswith("fanoutLatency/") for k in
           servers[0].stats.snapshot().get("timings", {})):
        assert types.get("pilosa_fanoutLatency") == "histogram"
        count = next(v for n, _, v in samples
                     if n == "pilosa_fanoutLatency_count")
        assert count >= 1


def test_live_metrics_fleet_telemetry_series(pair):
    """PR 5 satellite: series that previously lived only in /debug/vars
    (HBM residency, damaged fragments, batcher queues, hedges, XLA
    compile counters, the node health score) are now scrapeable — and
    conform like everything else."""
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    names = {n for n, _, _ in samples}
    # residency gauges keyed under one family
    assert types["pilosa_residency"] == "gauge"
    keys = {l.get("key") for n, l, _ in samples if n == "pilosa_residency"}
    assert {"bytes", "budget", "hitRate", "entries"} <= keys
    # cumulative residency counters (hits/misses/evictions)
    ckeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_residency_total"}
    assert {"hits", "misses", "evictions"} <= ckeys
    assert "pilosa_damagedFragments" in names
    assert "pilosa_walPoisonedFragments" in names
    assert types["pilosa_hedges_total"] == "counter"
    assert types["pilosa_batcher_total"] == "counter"
    assert "pilosa_xlaRecompileStorms_total" in names
    # the node's health score as a numeric gauge (0 green / 1 yellow /
    # 2 red) so the PromQL alert in docs/operations.md works. Asserted
    # against the node's OWN score, not a literal: the XLA counters are
    # process-global, and an earlier test's shape churn can legitimately
    # leave a recompile-storm window active (score yellow) here.
    health = next(v for n, _, v in samples if n == "pilosa_nodeHealth")
    expected = {"green": 0.0, "yellow": 1.0,
                "red": 2.0}[servers[0].node_health()["score"]]
    assert health == expected
    # traffic ran through the count batcher: XLA families show up
    assert any(n == "pilosa_xlaCompiles_total" for n, _, _ in samples)


def test_live_metrics_planner_and_plan_cache_series(pair):
    """Planner PR satellite: the cost-based planner's decision counters
    and the generation-keyed plan cache's hit economics are scrapeable —
    emitted unconditionally (zeros included) so the families always exist,
    and conforming like everything else."""
    servers, uris = pair
    # the fixture already ran Count queries (planned); run one more with a
    # reorderable shape so the counters are visibly live
    req = urllib.request.Request(
        uris[0] + "/index/m/query",
        data=b"Count(Intersect(Row(f=0), Row(f=0)))", method="POST")
    urllib.request.urlopen(req, timeout=30).read()
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_planner_total"] == "counter"
    pkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_planner_total"}
    assert {"plans", "reorders", "pushdowns", "shortCircuits"} <= pkeys
    plans = next(v for n, l, v in samples
                 if n == "pilosa_planner_total" and l.get("key") == "plans")
    assert plans >= 1  # real traffic was planned
    assert types["pilosa_planCache_total"] == "counter"
    ckeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_planCache_total"}
    assert {"hits", "misses", "evictions"} <= ckeys
    gkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_planCache"}
    assert {"bytes", "entries"} <= gkeys


def test_live_metrics_hybrid_families(pair):
    """Hybrid containers PR satellite: the sparse/dense representation
    counters (uploads by rep, promote/demote/materialize transitions)
    and the resident-occupancy gauges are scrapeable — emitted
    unconditionally (zeros included) so a "sparse share collapsed" alert
    never races the first sparse upload — and conform like everything
    else. The fixture's row f=0 (~117 bits per shard) sits far below the
    default sparse-threshold, so real sparse uploads back the counter."""
    servers, uris = pair
    req = urllib.request.Request(
        uris[0] + "/index/m/query", data=b"Count(Row(f=0))",
        method="POST")
    urllib.request.urlopen(req, timeout=30).read()
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_hybrid_total"] == "counter"
    reps = {l.get("rep") for n, l, _ in samples
            if n == "pilosa_hybrid_total" and "rep" in l}
    assert {"sparse", "run", "dense"} <= reps
    transitions = {l.get("transition") for n, l, _ in samples
                   if n == "pilosa_hybrid_total" and "transition" in l}
    assert {"promoted", "demoted", "materialized", "run"} <= transitions
    sparse_ups = next(v for n, l, v in samples
                      if n == "pilosa_hybrid_total"
                      and l.get("rep") == "sparse")
    assert sparse_ups >= 1  # real sparse traffic uploaded
    for fam in ("pilosa_hybridLeaves", "pilosa_hybridBytes"):
        assert types[fam] == "gauge"
        assert {"sparse", "run", "dense"} <= {
            l.get("rep") for n, l, _ in samples if n == fam}
    thr = next(v for n, l, v in samples
               if n == "pilosa_hybrid" and l.get("key") == "threshold")
    assert thr == 4096.0  # the default [query] sparse-threshold
    run_thr = next(v for n, l, v in samples
                   if n == "pilosa_hybrid"
                   and l.get("key") == "runThreshold")
    assert run_thr == 2048.0  # the default [query] run-threshold
    assert any(n == "pilosa_hybrid" and l.get("key") == "enabled"
               and v == 1.0 for n, l, v in samples)


def test_live_metrics_ingest_families(pair):
    """Streaming ingest PR satellite: the coalesced write plane's
    counters (mutations by op, applied batches, WAL group commits,
    resident-leaf patches) are scrapeable — emitted unconditionally
    (zeros included) so an "ingest stalled" alert never races the first
    write — and the /debug/vars `ingest` block carries the full batcher
    snapshot. Real writes through the HTTP plane back the counters."""
    servers, uris = pair
    for pql in (b"Set(77, f=3)", b"Set(78, f=3)", b"Clear(77, f=3)"):
        req = urllib.request.Request(
            uris[0] + "/index/m/query", data=pql, method="POST")
        urllib.request.urlopen(req, timeout=30).read()
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_ingest_total"] == "counter"
    ops = {l.get("op"): v for n, l, v in samples
           if n == "pilosa_ingest_total" and "op" in l}
    assert ops.get("set", 0) >= 2 and ops.get("clear", 0) >= 1
    kinds = {l.get("kind") for n, l, _ in samples
             if n == "pilosa_ingestBatches_total"}
    assert {"applied", "remote"} <= kinds
    patch_kinds = {l.get("kind") for n, l, _ in samples
                   if n == "pilosa_ingestPatch_total"}
    assert {"dense", "sparse", "dropped"} <= patch_kinds
    assert any(n == "pilosa_ingest" and l.get("key") == "enabled"
               and v == 1.0 for n, l, v in samples)
    # the apply lands on whichever replica owns the shard: batch + WAL
    # group-commit evidence is asserted cluster-wide via the expvar
    # blocks, which mirror each executor's full ingest snapshot
    blocks = []
    for uri in uris:
        with urllib.request.urlopen(uri + "/debug/vars", timeout=10) as r:
            blocks.append(json.loads(r.read())["ingest"])
    assert all(b["enabled"] is True for b in blocks)
    assert sum(b["mutations"] for b in blocks) >= 3  # coordinator-side
    assert sum(b["appliedBatches"] for b in blocks) >= 1
    applied_wal = sum(b["walAppends"] for b in blocks)
    assert applied_wal >= 1
    assert sum(b["walOps"] for b in blocks) >= applied_wal


def test_live_metrics_ici_families(pair):
    """ICI serving PR satellite: the slice-local routing decision
    counters and the serving-mode program-cache economics are scrapeable
    — the full route keyspace emitted unconditionally (zeros included)
    so a "slice-local share collapsed" alert never races the first
    routed query — and conform like everything else."""
    servers, uris = pair
    # the fixture's distributed Counts were routed (no mesh on these
    # nodes, so auto sends them down the cross_slice/HTTP plane)
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_iciServing_total"] == "counter"
    routes = {l.get("route") for n, l, _ in samples
              if n == "pilosa_iciServing_total"}
    assert {"slice_local", "cross_slice", "fallback"} <= routes
    crossed = next(v for n, l, v in samples
                   if n == "pilosa_iciServing_total"
                   and l.get("route") == "cross_slice")
    assert crossed >= 1  # real distributed traffic was routed
    assert types["pilosa_iciProgramCache_total"] == "counter"
    ckeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_iciProgramCache_total"}
    assert {"hits", "misses"} <= ckeys
    gkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_iciProgramCache"}
    assert "programs" in gkeys
    # mode gauge: 0 off / 1 auto / 2 on — these servers run the default
    mode = next(v for n, l, v in samples
                if n == "pilosa_iciServing" and l.get("key") == "mode")
    assert mode == 1.0


def test_live_metrics_usage_and_slo_families(pair):
    """Accounting PR satellite: the per-principal usage counters and the
    SLO burn-rate gauges are scrapeable — emitted unconditionally (zeros
    included) so the families always exist — and conform like everything
    else. Per-principal series ride a `principal` label."""
    servers, uris = pair
    req = urllib.request.Request(
        uris[0] + "/index/m/query", data=b"Count(Row(f=0))",
        method="POST", headers={"X-API-Key": "conformance-key"})
    urllib.request.urlopen(req, timeout=30).read()
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_usage_total"] == "counter"
    ukeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_usage_total" and "principal" not in l}
    assert {"deviceMs", "hbmBytes", "rpcBytes", "queueMs", "queries",
            "errors", "planCacheHits"} <= ukeys
    # per-principal rows carry the principal label; the API-key query
    # above guarantees at least one tracked principal exists
    principals = {l.get("principal") for n, l, _ in samples
                  if n == "pilosa_usage_total" and "principal" in l}
    assert "key:conformance-key" in principals
    q = next(v for n, l, v in samples
             if n == "pilosa_usage_total" and l.get("key") == "queries"
             and l.get("principal") == "key:conformance-key")
    assert q >= 1
    assert types["pilosa_usage"] == "gauge"  # tracked/spilled principals
    # SLO burn gauges per objective (the default availability objective
    # exists on every server, so the family is unconditional)
    assert types["pilosa_slo"] == "gauge"
    skeys = {(l.get("key"), l.get("objective")) for n, l, _ in samples
             if n == "pilosa_slo"}
    assert ("burnShort", "availability") in skeys
    assert ("burnLong", "availability") in skeys
    assert ("status", "availability") in skeys
    assert ("worst", None) in skeys


def test_live_metrics_qos_families(pair):
    """QoS PR satellite: the admission plane's counters — admitted per
    priority, shed per reason, throttled per reason, the observe-mode
    would-* twins — and its gauges are scrapeable, emitted
    unconditionally (zeros included; mode off on this server) so a
    shed-rate alert can never race the first shed. Every priority class
    and every shed/throttle reason in the glossary must be present."""
    from pilosa_tpu.qos import PRIORITIES, SHED_REASONS, THROTTLE_REASONS
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_qos_total"] == "counter"
    keyspace = {(l.get("key"), l.get("priority"), l.get("reason"))
                for n, l, _ in samples if n == "pilosa_qos_total"}
    for p in PRIORITIES:
        assert ("admitted", p, None) in keyspace
    for reason in SHED_REASONS:
        assert ("shed", None, reason) in keyspace
        assert ("wouldShed", None, reason) in keyspace
    for reason in THROTTLE_REASONS:
        assert ("throttled", None, reason) in keyspace
        assert ("wouldThrottled", None, reason) in keyspace
    assert types["pilosa_qos"] == "gauge"
    gkeys = {l.get("key") for n, l, _ in samples if n == "pilosa_qos"}
    assert {"estimatedWaitMs", "queuePressure", "mode"} <= gkeys
    # mode off on this server -> gauge 0; real traffic admitted counts
    # only under observe/enforce, so the zeros themselves are the assert
    mode = next(v for n, l, v in samples
                if n == "pilosa_qos" and l.get("key") == "mode")
    assert mode == 0.0


def test_live_metrics_handoff_drain_and_fence_families(pair):
    """Zero-downtime-operations PR satellite: the hinted-handoff
    counters (writeHandoffs/{queued,replayed,dropped} — the previously
    SILENT skipped-replica writes), the drain lifecycle gauges and the
    rejoin read-fence counters are scrapeable, emitted unconditionally
    (zeros included — this cluster never drained) so a hint-log-growth
    alert can never race the first skipped write. The drain shed reason
    also joins the QoS shed family keyspace."""
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_writeHandoffs_total"] == "counter"
    hkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_writeHandoffs_total"}
    assert {"queued", "replayed", "dropped", "replayFailures"} <= hkeys
    assert types["pilosa_writeHandoffs"] == "gauge"
    gkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_writeHandoffs"}
    assert {"pendingBytes", "pendingTargets"} <= gkeys
    # drain lifecycle: gauge 0 on a healthy node + the shed counter
    assert types["pilosa_drain"] == "gauge"
    dkeys = {l.get("key") for n, l, _ in samples if n == "pilosa_drain"}
    assert {"draining", "activeQueries"} <= dkeys
    draining = next(v for n, l, v in samples
                    if n == "pilosa_drain" and l.get("key") == "draining")
    assert draining == 0.0
    assert types["pilosa_drain_total"] == "counter"
    assert ("drain/shedQueries".split("/")[1] in
            {l.get("key") for n, l, _ in samples
             if n == "pilosa_drain_total"})
    # rejoin read fence
    assert types["pilosa_readFence_total"] == "counter"
    fkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_readFence_total"}
    assert {"rerouted", "refusedRemote", "servedStale"} <= fkeys
    fenced = next(v for n, l, v in samples
                  if n == "pilosa_readFence"
                  and l.get("key") == "fencedShards")
    assert fenced == 0.0
    # "draining" is a first-class shed reason in the QoS glossary
    assert ("shed", "draining") in {
        (l.get("key"), l.get("reason")) for n, l, _ in samples
        if n == "pilosa_qos_total"}


def test_live_metrics_heat_families(pair):
    """Heat PR satellite: the fragment-temperature families — aggregate
    heat counters (reads/writes/deviceMs/h2dBytes/uploads/evictions),
    the tracked/spilled/hot/skew gauges, the score-distribution snapshot
    (cumulative le labels, bounded regardless of fragment count), and
    the residency heat-eviction counter — are scrapeable, emitted
    unconditionally while a tracker exists (zeros included) so a
    "fleet went cold" alert never races the first access, and conform
    like everything else. Per-fragment cardinality deliberately stays
    behind /debug/heat: the scrape's label space is bounded."""
    from pilosa_tpu.utils.heat import DISTRIBUTION_BOUNDS
    servers, uris = pair
    # the fixture's queries already heated fragments on this node
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_heat_total"] == "counter"
    hkeys = {l.get("key") for n, l, _ in samples
             if n == "pilosa_heat_total"}
    assert {"reads", "writes", "deviceMs", "h2dBytes", "uploads",
            "evictions"} <= hkeys
    reads = next(v for n, l, v in samples
                 if n == "pilosa_heat_total" and l.get("key") == "reads")
    assert reads >= 1  # real traffic heated real fragments
    assert types["pilosa_heat"] == "gauge"
    gkeys = {l.get("key") for n, l, _ in samples if n == "pilosa_heat"}
    assert {"trackedFragments", "spilledFragments", "hotFragments",
            "skew"} <= gkeys
    tracked = next(v for n, l, v in samples
                   if n == "pilosa_heat"
                   and l.get("key") == "trackedFragments")
    assert tracked >= 1
    # the distribution snapshot: one series per bound plus +Inf,
    # cumulative (a histogram SNAPSHOT of decaying scores, typed gauge)
    assert types["pilosa_heatDistribution"] == "gauge"
    dist = sorted(
        ((l.get("le"), v) for n, l, v in samples
         if n == "pilosa_heatDistribution"),
        key=lambda t: float("inf") if t[0] == "+Inf" else float(t[0]))
    assert len(dist) == len(DISTRIBUTION_BOUNDS) + 1
    vals = [v for _, v in dist]
    assert vals == sorted(vals)  # cumulative
    assert dist[-1] == ("+Inf", tracked)
    # residency heat-eviction counter joins the residency family
    assert "heatEvictions" in {
        l.get("key") for n, l, _ in samples
        if n == "pilosa_residency_total"}


def test_live_metrics_events_families(pair):
    """Flight-recorder PR satellite: the pilosa_events_total{type=...}
    family is emitted unconditionally for EVERY registered event type
    (zeros included — an "event rate spiked" alert can never race the
    first emitted event), plus the eviction counters per lane and the
    retained/spool gauges, all conforming like everything else."""
    from pilosa_tpu.utils.events import EVENT_TYPES, LANES
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    assert types["pilosa_events_total"] == "counter"
    emitted = {l.get("type"): v for n, l, v in samples
               if n == "pilosa_events_total" and "type" in l}
    for t in EVENT_TYPES:
        assert t in emitted, f"event family missing type={t}"
    # the live server booted, so its node.start is a real nonzero count
    assert emitted["node.start"] >= 1
    lanes = {l.get("lane") for n, l, _ in samples
             if n == "pilosa_events_total" and l.get("key") == "evicted"}
    assert set(LANES) <= lanes
    gkeys = {l.get("key") for n, l, _ in samples if n == "pilosa_events"}
    assert {"retained", "spoolBytes"} <= gkeys


def test_event_type_inventory_drift_guard():
    """Companion to the env-gate/config-knob guards: every event type
    emitted anywhere under pilosa_tpu/ must be registered in
    utils/events.py EVENT_TYPES, and every registered type must appear
    in the docs/operations.md glossary — a future PR cannot add a
    timeline event operators can't decode."""
    import os

    from pilosa_tpu.analysis import event_type_findings
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = event_type_findings(root)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_stats_registry_drift_guard(pair):
    """Tier-1 drift guard: every counter/gauge/timing name registered in
    the live StatsClient reaches the /metrics exposition — so a future PR
    cannot add a stat that silently never becomes scrapeable."""
    from pilosa_tpu.utils.stats import _split_key
    servers, uris = pair
    snap = servers[0].stats.snapshot()
    assert snap.get("counts"), "live server should have counted something"
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    _, samples = check_conformance(text)
    names = {n for n, _, _ in samples}
    for key in snap.get("counts", {}):
        fam, _ = _split_key(key)
        assert f"pilosa_{fam}_total" in names, \
            f"registered counter {key!r} missing from /metrics"
    for key in snap.get("gauges", {}):
        fam, _ = _split_key(key)
        assert f"pilosa_{fam}" in names, \
            f"registered gauge {key!r} missing from /metrics"
    for key in snap.get("timings", {}):
        fam, _ = _split_key(key)
        assert f"pilosa_{fam}_count" in names, \
            f"registered timing {key!r} missing from /metrics"
    for key in snap.get("sets", {}):
        fam, _ = _split_key(key)
        assert f"pilosa_{fam}_cardinality" in names, \
            f"registered set {key!r} missing from /metrics"


def test_env_gate_inventory_drift_guard():
    """Companion to the stats-registry guard: every PILOSA_TPU_* env
    gate referenced anywhere under pilosa_tpu/ must appear in
    docs/operations.md — a future PR cannot add a gate operators can't
    discover."""
    import os

    from pilosa_tpu.analysis import env_gate_findings
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = env_gate_findings(root)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_config_knob_inventory_drift_guard():
    """Every [section] knob in cli/config.py must appear in
    docs/operations.md AND round-trip through Config.to_toml() — the
    wiring a knob needs to be settable cli→config→Server."""
    import os

    from pilosa_tpu.analysis import config_knob_findings
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = config_knob_findings(root)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_metrics_endpoint_without_stats_client(pair):
    """A handler with no stats wired still answers 200 with an empty
    (legal) exposition."""
    from pilosa_tpu.net.http_server import Handler
    servers, _ = pair
    h = Handler(servers[0].api, stats=None)
    status, ctype, body = h.dispatch("GET", "/metrics", {}, b"")
    assert status == 200 and ctype.startswith("text/plain")
    parse_exposition(body.decode())


def test_live_metrics_kernel_families_full_keyspace(pair):
    """pilosa_kernels* families are emitted UNCONDITIONALLY across the
    full kernel-family × rep keyspace (zeros included) — "sparse kernels
    stalled" alerts must never race the first sparse dispatch."""
    from pilosa_tpu.constants import KERNEL_FAMILY_REPS
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    for fam in ("pilosa_kernelsDispatches_total",
                "pilosa_kernelsWaitMs_total", "pilosa_kernelsWaited_total",
                "pilosa_kernelsH2dBytes_total",
                "pilosa_kernelsD2hBytes_total"):
        assert types.get(fam) == "counter", f"{fam} missing"
        series = {(ls.get("key"), ls.get("rep"))
                  for n, ls, _ in samples if n == fam}
        for family, rep in KERNEL_FAMILY_REPS.items():
            assert (family, rep) in series, \
                f"{fam}: no series for family {family!r} rep {rep!r}"
    # real traffic dispatched real kernels: at least one non-zero series
    assert any(v > 0 for n, _, v in samples
               if n == "pilosa_kernelsDispatches_total")
    # and the dispatch-latency histogram rendered for a live family
    assert types.get("pilosa_kernelDispatchMs") == "histogram"


def test_live_metrics_hbm_families(pair):
    """pilosa_hbm* gauges: unconditional across the rep keyspace, and
    the resident-bytes series byte-exact against /debug/hbm."""
    servers, uris = pair
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    types, samples = check_conformance(text)
    for fam in ("pilosa_hbmResidentBytes", "pilosa_hbmResidentEntries"):
        assert types.get(fam) == "gauge", f"{fam} missing"
        reps = {ls.get("rep") for n, ls, _ in samples if n == fam}
        assert {"dense", "sparse", "run", "other"} <= reps
    for fam in ("pilosa_hbmPlanCacheBytes", "pilosa_hbmBudgetBytes",
                "pilosa_hbmHeadroomBytes", "pilosa_hbmDriftBytes"):
        assert types.get(fam) == "gauge", f"{fam} missing"
    with urllib.request.urlopen(uris[0] + "/debug/hbm?top=0",
                                timeout=10) as r:
        hbm = json.loads(r.read())
    total = sum(v for n, ls, v in samples if n == "pilosa_hbmResidentBytes")
    assert total == hbm["residentBytes"]


def test_kernel_family_inventory_drift_guard():
    """Every kernel family named at a counted_jit / telemetry
    record_dispatch site or KERNEL_FAMILY attribute anywhere under
    pilosa_tpu/ is registered in constants.KERNEL_FAMILY_REPS — a future
    PR cannot dispatch under a family the attribution plane, the
    /metrics zero-fill and the dashboards have never heard of."""
    import os

    from pilosa_tpu.analysis import run_all
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = [f for f in run_all(root) if f.rule == "kernel-family"]
    assert findings == [], "\n".join(f.render() for f in findings)
