"""/debug/pprof handlers (net/http_server.py get_debug_pprof): the index
listing, the `goroutine` thread-stack dump, the `profile` sampling
profiler (?seconds=), and the unknown-profile 404 — previously untested
beyond a smoke check. Driven at the Handler level (no network flakiness)
plus one live-server pass."""

import json
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.server import Server


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    s = Server(str(tmp_path_factory.mktemp("pprof") / "node"), port=0).open()
    yield s
    s.close()


def dispatch(server, path, query=None):
    return server.handler.dispatch("GET", path, query or {}, b"")


def test_index_listing_default_and_explicit(server):
    for path in ("/debug/pprof", "/debug/pprof/", "/debug/pprof/index"):
        status, ctype, body = dispatch(server, path)
        assert status == 200, path
        out = json.loads(body)
        assert out["profiles"] == ["goroutine", "profile"], path


def test_goroutine_dumps_every_thread_stack(server):
    marker = threading.Event()
    release = threading.Event()

    def parked_thread_for_pprof_test():
        marker.set()
        release.wait(10)

    t = threading.Thread(target=parked_thread_for_pprof_test, daemon=True)
    t.start()
    marker.wait(5)
    try:
        status, _, body = dispatch(server, "/debug/pprof/goroutine")
        assert status == 200
        out = json.loads(body)
        assert out["threads"] >= 2  # at least us + the parked thread
        assert len(out["stacks"]) == out["threads"]
        # stacks are real formatted frames: the parked thread's function
        # name appears in exactly the dump, with file:line context
        flat = "".join(f for frames in out["stacks"].values()
                       for f in frames)
        assert "parked_thread_for_pprof_test" in flat
        assert ".py" in flat and "line" in flat
    finally:
        release.set()
        t.join(5)


def test_profile_samples_busy_thread(server):
    stop = threading.Event()

    def busy_loop_for_pprof_test():
        x = 0
        while not stop.is_set():
            x += 1
        return x

    t = threading.Thread(target=busy_loop_for_pprof_test, daemon=True)
    t.start()
    try:
        t0 = time.monotonic()
        status, _, body = dispatch(server, "/debug/pprof/profile",
                                   {"seconds": ["0.3"]})
        elapsed = time.monotonic() - t0
        assert status == 200
        out = json.loads(body)
        assert out["samples"] >= 1
        assert elapsed >= 0.3  # honored the requested window
        assert elapsed < 5.0
        # the top-sites table attributes samples to the busy loop
        assert out["top"], out
        sites = " ".join(e["site"] for e in out["top"])
        assert "busy_loop_for_pprof_test" in sites, out["top"][:5]
        for entry in out["top"]:
            assert entry["samples"] >= 1
            assert ":" in entry["site"]  # file:line function shape
    finally:
        stop.set()
        t.join(5)


def test_profile_seconds_is_capped(server):
    """?seconds= is clamped to 30 — a scrape typo must not pin a handler
    thread for an hour (the sampler loop holds no locks, but still)."""
    t0 = time.monotonic()
    status, _, body = dispatch(server, "/debug/pprof/profile",
                               {"seconds": ["0.05"]})
    assert status == 200
    assert time.monotonic() - t0 < 5.0
    assert json.loads(body)["samples"] >= 0


def test_unknown_profile_404(server):
    for name in ("heapz", "mutex", "block", "cmdline"):
        status, _, body = dispatch(server, f"/debug/pprof/{name}")
        assert status == 404, name
        assert "unknown profile" in json.loads(body)["error"]


def test_pprof_over_live_http(server):
    """One end-to-end pass over the real socket (the Handler-level tests
    above cover the matrix)."""
    with urllib.request.urlopen(server.uri + "/debug/pprof/goroutine",
                                timeout=10) as r:
        assert r.status == 200
        assert json.loads(r.read())["threads"] >= 1
    # unknown query args on a spec'd endpoint still 400 (typo guard)
    try:
        urllib.request.urlopen(
            server.uri + "/debug/pprof/profile?second=1", timeout=10)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
