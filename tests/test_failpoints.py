"""Failpoint framework + crash/corruption recovery units.

Covers utils/failpoints.py (registry, actions, chaos schedule, counters),
the CRC-framed WAL (torn-tail truncation on reopen, legacy/mixed logs), the
blake2b snapshot trailer (quarantine on digest failure), and the hardened
snapshot/open error paths.
"""

import io
import os
import struct

import numpy as np
import pytest

from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.roaring import (
    OP_ADD,
    OP_MAGIC,
    OP_REMOVE,
    SNAP_TRAILER_MAGIC,
    Bitmap,
    CorruptionError,
    fnv1a32,
    frame_op,
)
from pilosa_tpu.utils import failpoints


def legacy_op(typ, value):
    body = struct.pack("<BQ", typ, value)
    return body + struct.pack("<I", fnv1a32(body))


# -- framework --------------------------------------------------------------


def test_unknown_failpoint_name_rejected():
    with pytest.raises(KeyError):
        failpoints.configure("storage.wal.appendd", "raise")


def test_kind_must_be_allowed_for_point():
    with pytest.raises(ValueError, match="does not support"):
        failpoints.configure("net.client.send", "truncate-write")


def test_raise_delay_times_and_counters():
    fired = 0
    with failpoints.failpoint("storage.fragment.open", "raise", times=2):
        for _ in range(4):
            try:
                failpoints.hit("storage.fragment.open")
            except failpoints.FailpointError:
                fired += 1
    assert fired == 2  # times=2 bounds total firings
    c = failpoints.counters()["storage.fragment.open"]
    assert c["evaluations"] == 4 and c["fired"] == 2
    # inactive after the context manager — and with nothing armed, hit()
    # is a no-op that doesn't even count (the zero-overhead fast path)
    failpoints.hit("storage.fragment.open")
    snap = failpoints.snapshot()
    assert snap["points"]["storage.fragment.open"]["evaluations"] == 4
    assert len(snap["logTail"]) == 2
    assert snap["logTail"][0]["kind"] == "raise"


def test_custom_exception_type():
    class Boom(Exception):
        pass

    with failpoints.failpoint("executor.fanout", "raise"):
        with pytest.raises(Boom):
            failpoints.hit("executor.fanout", exc=Boom)


def test_corrupt_write_and_read_helpers():
    with failpoints.failpoint("storage.wal.append", "truncate-write",
                              arg=0.5):
        data, exc = failpoints.corrupt_write("storage.wal.append",
                                             b"0123456789")
        assert data == b"01234" and isinstance(exc, failpoints.FailpointError)
    with failpoints.failpoint("net.client.read", "partial-read", arg=0.3):
        assert failpoints.corrupt_read("net.client.read", b"0123456789") \
            == b"012"
    # inactive: pass-through
    data, exc = failpoints.corrupt_write("storage.wal.append", b"xy")
    assert data == b"xy" and exc is None


def test_chaos_schedule_is_deterministic_per_seed():
    def run():
        failpoints.reset()
        failpoints.arm_chaos(1234, rate=0.5,
                             points={"executor.fanout", "net.client.send"})
        outcomes = []
        for i in range(40):
            name = ("executor.fanout", "net.client.send")[i % 2]
            try:
                act = failpoints.hit(name)
                outcomes.append(("ok", None if act is None else act.kind))
            except failpoints.FailpointError:
                outcomes.append(("raise", None))
        log = failpoints.schedule_log()
        failpoints.reset()
        return outcomes, log

    a, la = run()
    b, lb = run()
    assert a == b and la == lb
    assert any(kind == "raise" for kind, _ in a)  # rate=0.5 actually fires
    assert la and la[0]["seq"] == 1


def test_chaos_env_arming(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_CHAOS_SEED", "77")
    monkeypatch.setenv("PILOSA_TPU_CHAOS_RATE", "1.0")
    monkeypatch.setenv("PILOSA_TPU_CHAOS_POINTS", "net.client.send")
    failpoints.reset()
    failpoints._maybe_arm_from_env()
    snap = failpoints.snapshot()
    assert snap["armed"] and snap["chaos"]["seed"] == 77
    assert snap["chaos"]["points"] == ["net.client.send"]
    # only the listed point draws (rate=1.0: every evaluation fires some
    # allowed kind — raise or delay)
    for _ in range(5):
        try:
            failpoints.hit("net.client.send")
        except failpoints.FailpointError:
            pass
        failpoints.hit("executor.fanout")  # not in points: never fires
    c = failpoints.counters()
    assert c["net.client.send"]["fired"] == 5
    assert c.get("executor.fanout", {"fired": 0})["fired"] == 0


# -- CRC-framed WAL ---------------------------------------------------------


def test_framed_record_roundtrip_and_crc():
    rec = frame_op(OP_ADD, 12345)
    assert len(rec) == 15 and rec[0] == OP_MAGIC
    b = Bitmap(np.array([1], dtype=np.uint64))
    data = b.to_bytes() + rec
    back = Bitmap.from_bytes(data)
    assert back.contains(12345) and back.op_n == 1
    # flip a byte in the value: CRC catches it
    bad = bytearray(rec)
    bad[5] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        Bitmap.from_bytes(b.to_bytes() + bytes(bad))


def test_legacy_and_mixed_oplog_replay():
    b = Bitmap(np.array([7], dtype=np.uint64))
    snap = b.to_bytes()
    # legacy-only (pre-framing files), then legacy + framed (a log that
    # gained framed appends after an upgrade)
    legacy = legacy_op(OP_ADD, 100) + legacy_op(OP_REMOVE, 7)
    back = Bitmap.from_bytes(snap + legacy)
    assert back.contains(100) and not back.contains(7) and back.op_n == 2
    mixed = legacy + frame_op(OP_ADD, 200) + frame_op(OP_REMOVE, 100)
    back = Bitmap.from_bytes(snap + mixed)
    assert back.contains(200) and not back.contains(100)
    assert back.op_n == 4


def test_network_parse_still_rejects_torn_tail():
    b = Bitmap(np.array([1], dtype=np.uint64))
    torn = b.to_bytes() + frame_op(OP_ADD, 5)[:9]
    with pytest.raises(ValueError, match="out of bounds"):
        Bitmap.from_bytes(torn)  # recover_wal=False: refuse, as before
    back = Bitmap.from_bytes(torn, recover_wal=True)
    assert back.wal_error is not None
    assert back.wal_valid_end == len(b.to_bytes())
    assert not back.contains(5)


def test_torn_write_in_surviving_process_is_rewound(tmp_path):
    """A torn append in a process that KEEPS RUNNING must rewind the file
    to the record boundary: otherwise a later acked record lands after
    the garbage, and the next open's truncate-at-first-tear would silently
    discard it (acked-write loss)."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    for col in range(10):
        f.set_bit(1, col)  # acked, WAL-framed
    with failpoints.failpoint("storage.wal.append", "truncate-write",
                              arg=0.4, times=1):
        with pytest.raises(failpoints.FailpointError):
            f.set_bit(2, 999)
    # the partial record was rewound off the log: later acked writes are
    # safe even though the process never restarted
    f.set_bit(3, 5)  # acked AFTER the tear
    f.close()
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.wal_truncated_bytes == 0  # nothing torn on disk
    assert g.row_columns(1).tolist() == list(range(10))
    assert g.row_count(2) == 0  # the torn op was never acked
    assert g.contains(3, 5)  # the post-tear acked write survived
    g.close()


def test_fragment_reopen_truncates_torn_wal_tail(tmp_path):
    """A crash mid-append (no chance to rewind) leaves a partial record at
    EOF: reopen replays everything acked and truncates the tear."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    for col in range(10):
        f.set_bit(1, col)
    f.close()
    with open(path, "ab") as fh:  # the crash's torn half-record
        fh.write(frame_op(OP_ADD, 12345)[:7])
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.row_columns(1).tolist() == list(range(10))
    assert not g.contains(0, 12345 % (1 << 20))
    assert g.wal_truncated_bytes == 7 and g.wal_truncate_error
    # the file is clean again: appends + reopen work
    g.set_bit(3, 5)
    g.close()
    h = Fragment(path, "i", "f", "standard", 0).open()
    assert h.wal_truncated_bytes == 0
    assert h.contains(3, 5) and h.row_count(1) == 10
    h.close()


def test_fragment_reopen_truncates_garbage_tail(tmp_path):
    """Arbitrary appended garbage (bit-rot past the last record) is
    truncated, not fatal — the pre-framing behavior was a refused open."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.set_bit(0, 1)
    f.close()
    with open(path, "ab") as fh:
        fh.write(b"\x7fgarbage-not-an-op-record")
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.contains(0, 1)
    assert g.wal_truncated_bytes == 25
    g.close()
    # idempotent: second reopen is clean
    h = Fragment(path, "i", "f", "standard", 0).open()
    assert h.wal_truncated_bytes == 0
    h.close()


# -- snapshot integrity trailer --------------------------------------------


def test_snapshot_carries_verified_trailer(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.bulk_import([1, 2, 3], [10, 20, 30])  # bulk path snapshots
    f.close()
    raw = open(path, "rb").read()
    assert SNAP_TRAILER_MAGIC in raw
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.quarantine_path is None
    assert g.row_columns(1).tolist() == [10]
    # WAL appends land AFTER the trailer and replay across it
    g.set_bit(5, 50)
    g.close()
    h = Fragment(path, "i", "f", "standard", 0).open()
    assert h.contains(5, 50) and h.row_columns(2).tolist() == [20]
    h.close()


def test_corrupt_snapshot_quarantined_not_fatal(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.bulk_import([1] * 64, list(range(64)))
    f.set_bit(2, 7)  # one WAL record after the snapshot
    f.close()
    # bit-rot INSIDE the container payload (the section is 30 bytes:
    # 8 header + 12 desc + 4 offset + [nruns u16 | start u16 | last u16]).
    # Byte 27 flips the run's start value: STRUCTURALLY valid — only the
    # digest can catch it (flipping a size-bearing byte instead trips the
    # bounds checks first, which also quarantines)
    with open(path, "r+b") as fh:
        fh.seek(27)
        byte = fh.read(1)
        fh.seek(27)
        fh.write(bytes([byte[0] ^ 0xFF]))
    g = Fragment(path, "i", "f", "standard", 0).open()
    # quarantined + reopened empty: the node came up, data awaits rebuild
    assert g.quarantine_path and os.path.exists(g.quarantine_path)
    assert "blake2b" in g.corruption_error
    assert g.needs_rebuild and g.bit_count() == 0
    # fully writable (fresh file, trailer included)
    g.set_bit(0, 0)
    g.close()
    # reopen of the FRESH file is clean, and the sidecar lock was managed
    # correctly throughout (no leak: this open would fail "locked")
    h = Fragment(path, "i", "f", "standard", 0).open()
    assert h.quarantine_path is None and h.contains(0, 0)
    h.close()


def test_trailer_length_mismatch_quarantines(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.bulk_import([1], [5])
    f.close()
    raw = open(path, "rb").read()
    idx = raw.rindex(SNAP_TRAILER_MAGIC)
    mangled = raw[:idx + 4] + struct.pack("<Q", 12) + raw[idx + 12:]
    with open(path, "wb") as fh:
        fh.write(mangled)
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.quarantine_path and "length mismatch" in g.corruption_error
    g.close()


def test_legacy_snapshot_without_trailer_still_opens(tmp_path):
    """Pre-trailer fragment files (write_to output + legacy WAL) parse
    unverified — upgrades must not quarantine every existing file."""
    path = str(tmp_path / "frag")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    b = Bitmap(np.array([3, 70000], dtype=np.uint64))
    with open(path, "wb") as fh:
        b.write_to(fh)
        fh.write(legacy_op(OP_ADD, 9))
    f = Fragment(path, "i", "f", "standard", 0).open()
    assert f.quarantine_path is None
    assert f.contains(0, 3) and f.contains(0, 9) and f.contains(1, 70000 % (1 << 20)) is not None
    # first snapshot upgrades the file to the trailered format
    f.snapshot()
    f.close()
    assert SNAP_TRAILER_MAGIC in open(path, "rb").read()
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.contains(0, 9)
    g.close()


def test_failed_snapshot_keeps_old_file_serving(tmp_path):
    """A snapshot that dies mid-write (torn tmp file) must leave the old
    snapshot + WAL intact, re-attach the WAL, and not strand a partial
    .snapshotting file."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    for col in range(8):
        f.set_bit(1, col)
    with failpoints.failpoint("storage.snapshot.write", "truncate-write",
                              arg=0.5, times=1):
        with pytest.raises(failpoints.FailpointError):
            f.snapshot()
    assert not os.path.exists(path + ".snapshotting")
    # still serving, still WAL-attached: later writes are durable
    f.set_bit(1, 100)
    assert f.storage.op_writer is not None
    f.close()
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.row_columns(1).tolist() == list(range(8)) + [100]
    # and a clean snapshot works afterwards
    g.snapshot()
    g.close()


def test_append_ops_torn_buffer_rewound(tmp_path):
    """append_ops (anti-entropy small-adoption durability) torn mid-buffer
    in a surviving process: the WHOLE unacked delta is rewound — none of
    it may survive as a partial adoption, and later appends stay safe."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.set_bit(0, 0)
    with failpoints.failpoint("storage.wal.append", "truncate-write",
                              arg=0.55, times=1):
        with pytest.raises(failpoints.FailpointError):
            f.storage.append_ops(
                np.arange(10, 20, dtype=np.uint64),
                np.empty(0, dtype=np.uint64))
    f.set_bit(0, 3)  # acked after the tear: must survive
    f.close()
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.contains(0, 0) and g.contains(0, 3)
    assert g.wal_truncated_bytes == 0
    assert not any(g.contains(0, c) for c in range(10, 20))
    g.close()


def test_midlog_wal_bitrot_quarantines_not_truncates(tmp_path):
    """Bit-rot in a MIDDLE record with valid acked records after it must
    NOT truncate (that would silently discard the acked suffix): it
    quarantines for replica rebuild, like snapshot corruption."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    for col in range(5):
        f.set_bit(1, col)  # 5 framed, fsyncable, ACKED records
    f.close()
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 5 * 15 + 5)  # a value byte of the FIRST record
        b = fh.read(1)
        fh.seek(size - 5 * 15 + 5)
        fh.write(bytes([b[0] ^ 0xFF]))
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.quarantine_path and "mid-stream" in g.corruption_error
    assert g.wal_truncated_bytes == 0 and g.needs_rebuild
    g.close()


def test_unregistered_hit_raises_when_armed():
    with failpoints.failpoint("executor.fanout", "raise", times=0):
        with pytest.raises(KeyError, match="unregistered"):
            failpoints.hit("storage.wal.appendd")


def test_crash_torn_append_ops_buffer_recovers(tmp_path):
    """The crash shape of the same tear (process died before any rewind):
    whole records before the tear replay, the torn one truncates."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.set_bit(0, 0)
    f.close()
    buf = b"".join(frame_op(OP_ADD, c) for c in range(10, 20))
    with open(path, "ab") as fh:
        fh.write(buf[:82])  # 5 whole records + 7 torn bytes of the 6th
    g = Fragment(path, "i", "f", "standard", 0).open()
    assert g.contains(0, 0)
    assert g.wal_truncated_bytes == 7
    survivors = [c for c in range(10, 20) if g.contains(0, c)]
    assert survivors == list(range(10, 15))
    g.close()


def test_wal_fsync_env_overrides_config(tmp_path, monkeypatch):
    # config says always -> fragment fsyncs
    f = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0,
                 wal_fsync=True)
    assert f.wal_fsync is True
    # env override wins over config in BOTH directions
    monkeypatch.setenv("PILOSA_TPU_WAL_FSYNC", "off")
    f = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0,
                 wal_fsync=True)
    assert f.wal_fsync is False
    monkeypatch.setenv("PILOSA_TPU_WAL_FSYNC", "always")
    f = Fragment(str(tmp_path / "c"), "i", "f", "standard", 0,
                 wal_fsync=False)
    assert f.wal_fsync is True
    monkeypatch.delenv("PILOSA_TPU_WAL_FSYNC")
    f = Fragment(str(tmp_path / "d"), "i", "f", "standard", 0)
    assert f.wal_fsync is False


def test_wal_fsync_config_plumbs_to_fragment(tmp_path):
    from pilosa_tpu.models import Holder

    h = Holder(str(tmp_path), wal_fsync=True)
    h.open()
    idx = h.create_index("i")
    fld = idx.create_field("f")
    v = fld.create_view_if_not_exists("standard")
    frag = v.create_fragment_if_not_exists(0)
    assert frag.wal_fsync is True and frag.storage.op_sync is True
    h.close()
