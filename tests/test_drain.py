"""Zero-downtime operations: graceful drain, durable hinted handoff,
read-fenced rejoin.

The acceptance contract (ISSUE 10): a rolling restart under load loses
zero acked writes and fails zero well-formed requests. Concretely:

* a drain broadcast moves the node to DRAINING and peers route around it
  IMMEDIATELY (no probe-timeout wait); new external queries shed with
  503 + X-Pilosa-Shed-Reason: draining; in-flight work finishes and a
  final snapshot lands
* a write acked while a replica is down/draining is appended to a
  durable, CRC32-framed per-target hint log and is readable from that
  replica after hint replay — WITHOUT waiting for an anti-entropy pass
* hint logs survive SIGKILL and torn tails (valid prefix replays; the
  damage forces the anti-entropy fallback, never silent loss)
* a rejoining node read-fences possibly-stale shards until block
  checksums confirm parity

Tests marked `chaos` ride the PR-4 conftest hook (seed + fired-schedule
printed on failure).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server import Server
from pilosa_tpu.storage import hints as hints_mod
from pilosa_tpu.storage.hints import HintStore, parse_hint_log, verify_hint_log
from pilosa_tpu.utils import failpoints


def http(method, uri, path, body=None, timeout=20):
    req = urllib.request.Request(uri + path, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else (
        json.dumps(payload).encode() if payload is not None else b"")
    status, headers, out = http("POST", uri, path, body)
    return status, headers, json.loads(out) if out else {}


def jget(uri, path):
    status, _h, out = http("GET", uri, path)
    return status, json.loads(out) if out else {}


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except Exception:  # noqa: BLE001 — condition not ready yet
            pass
        time.sleep(interval)
    return False


# -- hint log unit behavior --------------------------------------------------


def test_hint_log_roundtrip_and_framing(tmp_path):
    hs = HintStore(str(tmp_path / "h"))
    assert hs.append("n1", "i", "Set(5, f=1)")
    assert hs.append("n1", "i", "ClearRow(f=2)", shards=[0, 3])
    assert hs.pending("n1") > 0
    # the on-disk form is CRC-framed with the 0xFB magic (disjoint from
    # the WAL's 0xFA) so `pilosa-tpu check` can classify by lead byte
    with open(hs._path("n1"), "rb") as f:
        data = f.read()
    assert data[0] == hints_mod.HINT_MAGIC
    records, valid_end, err = parse_hint_log(data)
    assert err == "" and valid_end == len(data)
    assert [d["pql"] for _, d in records] == ["Set(5, f=1)", "ClearRow(f=2)"]
    assert records[1][1]["shards"] == [0, 3]
    applied = []
    replayed, dropped, complete = hs.replay("n1", applied.append)
    assert (replayed, dropped, complete) == (2, 0, True)
    assert applied[0] == {"index": "i", "pql": "Set(5, f=1)"}
    assert hs.pending("n1") == 0  # retired after a clean replay
    # replaying an empty / absent log is complete (nothing was skipped)
    assert hs.replay("n1", applied.append) == (0, 0, True)


def test_hint_log_torn_tail_truncation(tmp_path):
    """Damage after valid records: the valid prefix replays; the tear
    counts as a drop, so replay reports INCOMPLETE and the return-heal
    falls back to anti-entropy instead of trusting the hints."""
    hs = HintStore(str(tmp_path / "h"))
    hs.append("n1", "i", "Set(1, f=1)")
    hs.append("n1", "i", "Set(2, f=1)")
    path = hs._path("n1")
    with open(path, "ab") as f:
        f.write(b"\xfb\x01torn-mid-record")
    rep = verify_hint_log(path)
    assert rep["records"] == 2 and rep["error"]
    applied = []
    replayed, dropped, complete = hs.replay("n1", applied.append)
    assert replayed == 2 and not complete
    assert [d["pql"] for d in applied] == ["Set(1, f=1)", "Set(2, f=1)"]


def test_hint_log_corrupt_record_checksum(tmp_path):
    hs = HintStore(str(tmp_path / "h"))
    hs.append("n1", "i", "Set(1, f=1)")
    hs.append("n1", "i", "Set(2, f=1)")
    path = hs._path("n1")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # rot a byte in the SECOND record
        f.seek(size - 3)
        b = f.read(1)
        f.seek(size - 3)
        f.write(bytes([b[0] ^ 0xFF]))
    applied = []
    replayed, dropped, complete = hs.replay("n1", applied.append)
    assert replayed == 1 and not complete  # prefix replays, damage drops


def test_hint_log_byte_cap_writes_durable_drop_marker(tmp_path):
    """Overflow must be remembered ACROSS restarts: the dropped write is
    replaced by an in-band marker, so a fresh HintStore over the same
    directory still reports the replay incomplete."""
    hs = HintStore(str(tmp_path / "h"), max_bytes=200)
    assert hs.append("n1", "i", "Set(1, f=1)")
    while hs.append("n1", "i", "Set(2, f=1)"):
        pass  # fill to the cap; the final call dropped + marked
    assert hs.dropped == 1
    # a RESTARTED store (no in-memory state) still knows
    hs2 = HintStore(str(tmp_path / "h"))
    replayed, dropped, complete = hs2.replay("n1", lambda d: None)
    assert replayed >= 1 and dropped == 1 and not complete


def test_hint_log_age_cap_drops_stale_hints(tmp_path):
    hs = HintStore(str(tmp_path / "h"), max_age=3600.0)
    hs.append("n1", "i", "Set(1, f=1)")
    # age the record by rewriting its timestamp 2 hours into the past
    path = hs._path("n1")
    with open(path, "rb") as f:
        records, _, _ = parse_hint_log(f.read())
    old = hints_mod._frame(
        json.dumps(records[0][1], separators=(",", ":")).encode(),
        time.time() - 7200)
    with open(path, "wb") as f:
        f.write(old)
    hs.append("n1", "i", "Set(2, f=1)")  # fresh one after it
    applied = []
    replayed, dropped, complete = hs.replay("n1", applied.append)
    assert replayed == 1 and dropped == 1 and not complete
    assert applied[0]["pql"] == "Set(2, f=1)"


def test_hint_failpoints_registered_and_fire(tmp_path):
    """The chaos surface: storage.hints.append drops the hint (write
    stays acked by live replicas; anti-entropy covers); a replay fault
    keeps the log for the next return."""
    hs = HintStore(str(tmp_path / "h"))
    with failpoints.failpoint("storage.hints.append", "raise", times=1):
        assert hs.append("n1", "i", "Set(1, f=1)") is False
    assert hs.dropped == 1 and hs.pending("n1") == 0
    hs.append("n1", "i", "Set(2, f=1)")
    with failpoints.failpoint("storage.hints.replay", "raise", times=1):
        replayed, dropped, complete = hs.replay("n1", lambda d: None)
    assert (replayed, complete) == (0, False)
    assert hs.pending("n1") > 0  # kept for the retry
    replayed, dropped, complete = hs.replay("n1", lambda d: None)
    assert (replayed, dropped, complete) == (1, 0, True)


# -- SIGKILL durability ------------------------------------------------------

HINT_WRITER = r"""
import sys
from pilosa_tpu.storage.hints import HintStore

# fsync per hint: the acked line prints only after the frame is durable
hs = HintStore(sys.argv[1], fsync=True)
i = 0
while True:  # parent SIGKILLs us mid-stream (a crash mid-drain)
    hs.append("target-node", "i", f"Set({i}, f=1)")
    print(f"ACK {i}", flush=True)
    i += 1
"""


@pytest.mark.chaos
def test_sigkill_mid_drain_hints_survive_and_replay(tmp_path):
    """A coordinator crashing mid-drain (SIGKILL: no flush, no goodbye)
    must not lose queued handoff promises: every hint acked before the
    kill replays after restart; at most the torn tail record is lost —
    and a tear marks the replay incomplete, forcing the anti-entropy
    fallback rather than silent loss."""
    script = tmp_path / "writer.py"
    script.write_text(HINT_WRITER)
    hints_dir = str(tmp_path / "data" / ".hints")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen([sys.executable, str(script), hints_dir],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=env)
    acked = []
    try:
        for line in proc.stdout:
            parts = line.split()
            assert parts[0] == b"ACK", line
            acked.append(int(parts[1]))
            if len(acked) >= 60:
                os.kill(proc.pid, signal.SIGKILL)
                break
        rest, err = proc.communicate(timeout=30)
        for line in rest.splitlines():
            parts = line.split()
            if len(parts) == 2 and parts[0] == b"ACK":
                acked.append(int(parts[1]))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert len(acked) >= 60

    hs = HintStore(hints_dir)  # the restarted process
    applied = []
    replayed, dropped, complete = hs.replay("target-node", applied.append)
    got = {int(d["pql"].split("(")[1].split(",")[0]) for d in applied}
    missing = [i for i in acked if i not in got]
    assert not missing, f"{len(missing)} acked hints lost: {missing[:5]}"
    # a torn tail (the record being written at kill time) is allowed —
    # but then the replay must say so
    assert complete or dropped >= 1


# -- live cluster: drain lifecycle ------------------------------------------


@pytest.fixture
def cluster3(tmp_path):
    servers = []
    for i in range(3):
        s = Server(str(tmp_path / f"n{i}"), port=0, replica_n=2).open()
        servers.append(s)
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    yield servers
    failpoints.reset()
    for s in servers:
        try:
            s.close()
        except Exception:  # noqa: BLE001 — some were restarted/closed
            pass


def _seed(s0, rows=(1, 2, 3), shards=4, per_row=8):
    jpost(s0.uri, "/index/i", {})
    jpost(s0.uri, "/index/i/field/f", {})
    for shard in range(shards):
        for row in rows:
            for k in range(per_row):
                col = shard * SHARD_WIDTH + row * 100 + k
                st, _h, out = jpost(s0.uri, "/index/i/query",
                                    raw=f"Set({col}, f={row})".encode())
                assert st == 200 and out["results"] == [True], (st, out)
    return shards * per_row


def _restart(tmp_path, idx, port, uris):
    s = Server(str(tmp_path / f"n{idx}"), port=port, replica_n=2)
    s.cluster_hosts = uris
    s.open()
    return s


def test_drain_sheds_and_peers_route_around_immediately(cluster3):
    s0, s1, s2 = cluster3
    expected = _seed(s0)
    st, _h, out = jpost(s2.uri, "/cluster/drain")
    assert st == 200 and out["draining"] is True
    # peers marked it DRAINING from the broadcast — no probe wait
    # (membership_interval is 5s and liveness_threshold 3, so probe-based
    # detection could not have happened yet)
    assert wait_until(lambda: s0.cluster.is_draining(s2.node_id)
                      and s1.cluster.is_draining(s2.node_id), timeout=10)
    assert wait_until(lambda: s2.drained, timeout=15)
    # new external queries on the draining node: 503 + headers
    st, headers, out = jpost(s2.uri, "/index/i/query",
                             raw=b"Count(Row(f=1))")
    assert st == 503
    assert headers.get("X-Pilosa-Shed-Reason") == "draining"
    assert "Retry-After" in headers
    assert out.get("code") == "shed"
    # /status reports the lifecycle state; health is yellow, NOT red
    st, doc = jget(s2.uri, "/status")
    assert doc["nodeState"] == "DRAINING"
    assert doc["health"]["score"] == "yellow"
    # queries through live nodes keep answering correctly (routed around)
    for uri in (s0.uri, s1.uri):
        st, _h, out = jpost(uri, "/index/i/query", raw=b"Count(Row(f=1))")
        assert st == 200 and out["results"] == [expected], out
    # the federation renders the draining node yellow with state DRAINING
    st, fleet = jget(s0.uri, "/cluster/stats")
    entry = next(n for n in fleet["fleet"]["nodes"]
                 if n["id"] == s2.node_id)
    assert entry["state"] == "DRAINING"
    assert entry["health"]["score"] == "yellow"
    assert fleet["fleet"]["health"] == "yellow"
    # drain observability: /debug/vars blocks + shed counters
    st, vars_ = jget(s2.uri, "/debug/vars")
    assert vars_["drain"]["draining"] is True
    assert vars_["drain"]["shedQueries"] >= 1
    assert vars_["qos"]["shed"]["draining"] >= 1
    # abort restores service and re-announces READY
    st, _h, out = jpost(s2.uri, "/cluster/drain", {"abort": True})
    assert st == 200 and out["draining"] is False
    assert wait_until(lambda: not s0.cluster.is_draining(s2.node_id),
                      timeout=10)
    st, _h, out = jpost(s2.uri, "/index/i/query", raw=b"Count(Row(f=1))")
    assert st == 200 and out["results"] == [expected]


def test_drain_waits_for_inflight_and_snapshots(cluster3):
    s0, s1, s2 = cluster3
    _seed(s0, shards=2, per_row=4)
    # dirty WAL state on s2 (writes routed to whatever it owns)
    ops_before = sum(int(getattr(frag.storage, "op_n", 0) or 0)
                     for *_x, frag in s2.holder.walk_fragments())
    s2.drain(timeout=10.0)
    assert s2.drained
    info = s2.drain_status()
    assert info["inflightDrained"] and info["queuesFlushed"]
    if ops_before:
        assert info["snapshotted"] >= 1
    # every fragment's WAL is now empty: the restart replays nothing
    for *_x, frag in s2.holder.walk_fragments():
        assert int(getattr(frag.storage, "op_n", 0) or 0) == 0


def test_write_acked_while_replica_down_replays_without_anti_entropy(
        cluster3, tmp_path):
    """THE acceptance criterion: a write acked while a replica was down
    is readable from that replica after hint replay, with zero
    anti-entropy passes involved."""
    s0, s1, s2 = cluster3
    _seed(s0, shards=3, per_row=4)
    uris = [s.uri for s in cluster3]
    port = s2.http.port

    # graceful drain, then the process goes away
    jpost(s2.uri, "/cluster/drain")
    assert wait_until(lambda: s2.drained, timeout=15)
    s2.close()

    # writes acked while the replica is gone -> hinted, not silently
    # skipped (and they must ack with 200 despite the down replica)
    acked = []
    for k in range(10):
        col = (k % 3) * SHARD_WIDTH + 900 + k
        st, _h, out = jpost(cluster3[k % 2].uri, "/index/i/query",
                            raw=f"Set({col}, f=9)".encode())
        assert st == 200 and out["results"] == [True], (st, out)
        acked.append(col)
    hinted = (s0.hints.snapshot()["queued"] + s1.hints.snapshot()["queued"])
    assert hinted >= 1, "skipped replica writes must be hinted"

    # restart on the same port/data: the rejoin broadcast triggers hint
    # replay from peers; fenced shards verify and unfence
    s2b = _restart(tmp_path, 2, port, uris)
    try:
        def replica_has_all():
            idx = s2b.holder.index("i")
            if idx is None:
                return False
            for col in acked:
                shard = col // SHARD_WIDTH
                if not s2b.cluster.owns_shard(s2b.node_id, "i", shard):
                    continue
                v = idx.field("f").view("standard")
                frag = v.fragment(shard) if v else None
                if frag is None or not frag.contains(9, col % SHARD_WIDTH):
                    return False
            return True

        assert wait_until(replica_has_all, timeout=30), \
            "acked writes did not reach the returned replica via hints"
        # ZERO anti-entropy involvement: no scrub pass ran anywhere, and
        # the hints all replayed cleanly
        assert s0._scrub_passes == 0 and s1._scrub_passes == 0 \
            and s2b._scrub_passes == 0
        assert wait_until(
            lambda: (s0.hints.snapshot()["replayed"]
                     + s1.hints.snapshot()["replayed"]) == hinted
            and s0.hints.snapshot()["pendingBytes"] == 0
            and s1.hints.snapshot()["pendingBytes"] == 0, timeout=20), \
            (s0.hints.snapshot(), s1.hints.snapshot(), hinted)
        # the read fence lifted after parity verification
        assert wait_until(
            lambda: s2b.executor.fence_snapshot()["fencedShards"] == 0,
            timeout=20)
        # and the returned replica answers reads correctly itself
        st, _h, out = jpost(s2b.uri, "/index/i/query", raw=b"Row(f=9)")
        assert st == 200
        assert set(out["results"][0]["columns"]) == set(acked)
    finally:
        s2b.close()


def test_rejoining_node_read_fences_until_verified(cluster3, tmp_path):
    """A restarted node arms the read fence for its local shards and
    lifts it only after checksum parity with a replica — /debug/vars
    surfaces the fence while it lasts."""
    s0, s1, s2 = cluster3
    _seed(s0, shards=3, per_row=4)
    uris = [s.uri for s in cluster3]
    port = s2.http.port
    s2.drain(timeout=5.0)
    s2.close()
    s2b = _restart(tmp_path, 2, port, uris)
    try:
        # fence armed at open for every local fragment's shard
        assert s2b.executor.fence_snapshot()["fencedShards"] >= 1 or \
            wait_until(
                lambda: s2b.executor.fence_snapshot()["fencedShards"] == 0,
                timeout=1)
        # data unchanged while away -> checksums match -> fence lifts
        assert wait_until(
            lambda: s2b.executor.fence_snapshot()["fencedShards"] == 0,
            timeout=20)
    finally:
        s2b.close()


@pytest.mark.chaos
def test_rolling_restart_storm_loses_no_acked_writes(cluster3, tmp_path):
    """3-node seeded storm with a rolling restart: every node drains,
    dies and rejoins in sequence while writes and reads continue under
    injected RPC faults. Afterward every acked write is present on every
    replica that owns its shard."""
    servers = list(cluster3)
    _seed(servers[0], shards=3, per_row=4)
    uris = [s.uri for s in servers]
    ports = [s.http.port for s in servers]

    failpoints.arm_chaos(20260804, rate=0.03, points={
        "net.client.send", "net.client.read", "executor.fanout",
        "storage.hints.append", "storage.hints.replay",
    })
    acked = []
    bad = []
    wi = 0

    def churn(n, via):
        nonlocal wi
        for _ in range(n):
            live = [s for s in via if s is not None]
            src = live[wi % len(live)]
            col = (wi % 3) * SHARD_WIDTH + 500 + wi
            wi += 1
            st, _h, out = jpost(src.uri, "/index/i/query",
                                raw=f"Set({col}, f=7)".encode())
            if st == 200 and out.get("results") == [True]:
                acked.append(col)
            elif st == 200:
                bad.append(("write-200-nottrue", out))
            elif "error" not in out:
                bad.append(("write-error-shape", st, out))

    churn(6, servers)
    for i in range(3):
        others = [s for j, s in enumerate(servers) if j != i]
        jpost(servers[i].uri, "/cluster/drain")
        assert wait_until(lambda: servers[i].drained, timeout=20)
        servers[i].close()
        churn(6, others)  # acked while the replica is away -> hints
        servers[i] = _restart(tmp_path, i, ports[i], uris)
        cluster3[i] = servers[i]  # fixture teardown closes the new one
        # wait for the rejoin to settle: peers cleared the mark and the
        # fence lifted (hints replayed or scrub-verified)
        assert wait_until(
            lambda: all(not o.cluster.is_draining(servers[i].node_id)
                        and not o.cluster.is_down(servers[i].node_id)
                        for o in others), timeout=30)
        assert wait_until(
            lambda: servers[i].executor.fence_snapshot()[
                "fencedShards"] == 0, timeout=40)
        churn(4, servers)
    assert not bad, bad
    failpoints.reset()

    # chaos may have dropped hints (storage.hints.append faults) or
    # failed replays mid-stream: drive the membership tick's pending-hint
    # retry directly (the fixture's servers run no timers), then the
    # documented anti-entropy fallback for whatever was dropped
    def settled():
        for s in servers:
            s._retry_pending_hints()
        return all(not s.hints.snapshot()["pendingBytes"] for s in servers)

    wait_until(settled, timeout=20)
    for s in servers:
        s.anti_entropy_pace = 0.0
        s.scrub_pass()

    missing = []
    for s in servers:
        idx = s.holder.index("i")
        for col in acked:
            shard = col // SHARD_WIDTH
            if not s.cluster.owns_shard(s.node_id, "i", shard):
                continue
            v = idx.field("f").view("standard")
            frag = v.fragment(shard) if v else None
            if frag is None or not frag.contains(7, col % SHARD_WIDTH):
                missing.append((s.node_id[:8], col))
    assert not missing, \
        f"{len(missing)} acked writes missing from replicas: {missing[:6]}"
