"""Run-container kernel family + three-way hybrid manager (ISSUE 17).

Kernel half: every run op against a set-algebra oracle built from the
same column sets — intersection (run∩run, run∩dense, sparse∩run), the
fused run_intersect_count, counts, densify, and the host-side builders.
Manager half: the three-way sparse/run/dense transition rule — both
thresholds, both hysteresis bands, the run_stats=None advisory-missing
case, and the transition counters the fuzz asserts on.
"""

import numpy as np
import pytest

import pilosa_tpu.ops.bitvector as bv
from pilosa_tpu.parallel.residency import HybridManager

W = 64  # words per test row (2048 bits — full shard width not needed)
WIDTH = W * 32


def runs_of(cols, slots=16):
    return bv.runs_from_columns(np.asarray(sorted(cols), dtype=np.int64),
                                slots)


def sparse_of(cols, slots=64):
    return bv.sparse_from_columns(np.asarray(sorted(cols), dtype=np.int64),
                                  slots)


def dense_of(cols):
    return bv.dense_from_columns(np.asarray(sorted(cols), dtype=np.int64),
                                 width=WIDTH)


SETS = [
    set(),
    set(range(5, 40)),
    set(range(0, 200)) | set(range(900, 1000)),
    set(range(30, 35)) | set(range(37, 60)) | {100, 101, 102, 2047},
    set(range(0, WIDTH, 7)) & set(range(0, 512)),  # many 1-bit runs
]


def test_runs_from_columns_roundtrip():
    for s in SETS:
        runs = runs_of(s, slots=256)
        back = np.asarray(bv.run_to_dense(runs, W))
        np.testing.assert_array_equal(back, dense_of(s))
        assert int(bv.run_count(runs)) == len(s)


def test_intervals_from_sorted():
    iv = bv.intervals_from_sorted(np.array([1, 2, 3, 7, 9, 10]))
    np.testing.assert_array_equal(iv, [[1, 3], [7, 7], [9, 10]])
    assert bv.intervals_from_sorted(np.array([], dtype=np.int64)).shape == \
        (0, 2)


def test_runs_from_intervals_overflow_drops():
    """Intervals past `slots` drop (stale-stat case): lossy but sized by
    the caller from fragment stats, so the build stays bounded."""
    iv = np.array([[0, 1], [4, 5], [8, 9]])
    runs = bv.runs_from_intervals(iv, 2)
    assert runs.shape == (2, 2)
    assert int(bv.run_count(runs)) == 4


@pytest.mark.parametrize("ai", range(len(SETS)))
@pytest.mark.parametrize("bi", range(len(SETS)))
def test_run_ops_match_set_algebra(ai, bi):
    a, b = SETS[ai], SETS[bi]
    ra, rb = runs_of(a, 128), runs_of(b, 128)
    inter = a & b

    got = np.asarray(bv.run_to_dense(bv.run_intersect(ra, rb), W))
    np.testing.assert_array_equal(got, dense_of(inter))
    # the fused count never sorts or materializes the overlap list
    assert int(bv.run_intersect_count(ra, rb)) == len(inter)

    dm = np.asarray(bv.run_intersect_dense(ra, dense_of(b), W))
    np.testing.assert_array_equal(dm, dense_of(inter))
    assert int(bv.run_dense_count(ra, dense_of(b), W)) == len(inter)

    sa = sparse_of(a, 4096)
    got_sp = np.asarray(bv.sparse_intersect_run(sa, rb))
    live = got_sp[got_sp < bv.SPARSE_SENTINEL]
    assert set(live.tolist()) == inter
    diff = np.asarray(bv.sparse_difference_run(sa, rb))
    assert set(diff[diff < bv.SPARSE_SENTINEL].tolist()) == a - b


def test_run_intersect_keeps_sorted_sentinel_contract():
    """Output runs are sorted with sentinel padding at the tail — the
    contract every downstream kernel assumes."""
    out = np.asarray(bv.run_intersect(runs_of(SETS[2], 16),
                                      runs_of(SETS[3], 16)))
    starts = out[0]
    assert np.all(np.diff(starts.astype(np.int64)) >= 0)
    valid = starts < bv.RUN_SENTINEL
    assert valid.any()
    last_valid = int(np.max(np.flatnonzero(valid)))
    # sentinels only after the last valid slot — no interleaved holes
    assert np.all(valid[:last_valid + 1])
    assert np.all(starts[last_valid + 1:] == bv.RUN_SENTINEL)


def test_run_ops_batch_over_shards():
    """Shard-batched layout [S, 2, R]: per-shard results independent."""
    ra = np.stack([runs_of(SETS[1], 32), runs_of(SETS[2], 32)])
    rb = np.stack([runs_of(SETS[3], 32), runs_of(SETS[1], 32)])
    counts = np.asarray(bv.run_intersect_count(ra, rb))
    assert counts.tolist() == [len(SETS[1] & SETS[3]),
                               len(SETS[2] & SETS[1])]
    cnt = np.asarray(bv.run_count(ra))
    assert cnt.tolist() == [len(SETS[1]), len(SETS[2])]


def test_eval_hybrid_mixed_tree_with_runs():
    a, b, c = SETS[1], SETS[2], SETS[3]
    leaves = [runs_of(a, 64), dense_of(b), sparse_of(c, 64)]
    kinds = ["run", "dense", "sparse"]
    prog = ("and", ("or", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
    kind, arr = bv.eval_hybrid(prog, leaves, kinds, n_words=W)
    expect = (a | b) & c
    if kind == "sparse":
        got = set(np.asarray(arr)[np.asarray(arr) < bv.SPARSE_SENTINEL]
                  .tolist())
    elif kind == "run":
        got = set(bv.columns_from_dense(
            np.asarray(bv.run_to_dense(arr, W))).tolist())
    else:
        got = set(bv.columns_from_dense(np.asarray(arr)).tolist())
    assert got == expect
    assert bv.hybrid_count(prog, leaves, kinds, n_words=W) == len(expect)


def test_hybrid_count_fused_all_run_and():
    """The all-run AND pushdown takes the fused no-argsort path; parity
    with the generic evaluator on 2- and 3-operand programs."""
    leaves = [runs_of(SETS[1], 64), runs_of(SETS[2], 64),
              runs_of(SETS[3], 64)]
    kinds = ["run", "run", "run"]
    p2 = ("and", ("leaf", 0), ("leaf", 1))
    p3 = ("and", ("leaf", 0), ("leaf", 1), ("leaf", 2))
    assert bv.hybrid_count(p2, leaves, kinds) == len(SETS[1] & SETS[2])
    assert bv.hybrid_count(p3, leaves, kinds) == \
        len(SETS[1] & SETS[2] & SETS[3])


# ------------------------------------------------ three-way manager rule


def mgr(threshold=1000, run_threshold=100, hysteresis=0.25):
    return HybridManager(threshold=threshold, hysteresis=hysteresis,
                         run_threshold=run_threshold)


def test_choose_three_way_by_regime():
    m = mgr()
    assert m.choose(("r", 1), 500)[0] == "sparse"
    # above the cardinality threshold, few intervals -> run
    rep, slots = m.choose(("r", 2), 5000, run_stats=(8, 2048))
    assert rep == "run" and slots >= 8
    # above both thresholds -> dense
    assert m.choose(("r", 3), 5000, run_stats=(500, 4))[0] == "dense"
    # run stats missing entirely (no container walk) -> dense
    assert m.choose(("r", 4), 5000)[0] == "dense"


def test_run_stats_missing_keeps_run_resident_row():
    """run_stats=None means the signal is MISSING, not changed: a row
    already run-resident stays run instead of flapping dense."""
    m = mgr()
    assert m.choose(("r", 1), 5000, run_stats=(8, 2048))[0] == "run"
    assert m.choose(("r", 1), 5000)[0] == "run"
    assert m.run_transitions == 0


def test_run_hysteresis_band():
    m = mgr()  # run_threshold 100, band floor 75
    # dense row whose interval count falls into the band stays dense
    assert m.choose(("r", 1), 5000, run_stats=(500, 4))[0] == "dense"
    assert m.choose(("r", 1), 5000, run_stats=(90, 50))[0] == "dense"
    # below the band floor it demotes to run
    assert m.choose(("r", 1), 5000, run_stats=(40, 200))[0] == "run"
    assert m.demoted == 1 and m.run_transitions == 1
    # interval count crossing the threshold promotes immediately
    assert m.choose(("r", 1), 5000, run_stats=(101, 30))[0] == "dense"
    assert m.promoted == 1 and m.run_transitions == 2


def test_sparse_band_keeps_run_rep():
    """A run row whose cardinality falls into the sparse band keeps its
    rep (hot or no heat tracker); below the floor it demotes sparse."""
    m = mgr()  # threshold 1000, band floor 750
    assert m.choose(("r", 1), 5000, run_stats=(8, 700))[0] == "run"
    assert m.choose(("r", 1), 900, run_stats=(8, 120))[0] == "run"
    assert m.choose(("r", 1), 500)[0] == "sparse"
    # first choose has no history (not a transition); leaving run is one
    assert m.run_transitions == 1 and m.demoted == 1


def test_run_threshold_zero_disables_runs():
    m = mgr(run_threshold=0)
    assert m.choose(("r", 1), 5000, run_stats=(2, 2500))[0] == "dense"
    snap = m.snapshot()
    assert snap["runThreshold"] == 0 and snap["runUploads"] == 0


def test_record_upload_run_counters():
    m = mgr()
    m.record_upload("run", 4096)
    snap = m.snapshot()
    assert snap["runUploads"] == 1
    assert snap["runBytesUploaded"] == 4096
