"""Network-layer fan-out coalescing (net/coalesce.py NodeCoalescer), the
/internal/query-batch envelope, mixed-version 404 fallback, the
single-retry rule under coalesced senders, and hedged replica reads.

Unit tests drive the coalescer against a scripted fake client; the
integration tests run REAL multi-node clusters over HTTP and assert the
coalesced path answers byte-identically to the per-query path."""

import json
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.encoding.protobuf import Serializer
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.coalesce import NodeCoalescer

SW = SHARD_WIDTH


# --------------------------------------------------------------- unit level


class FakeClient:
    """Scripted InternalClient stand-in: query_batch_raw answers every
    entry with RESULT_UINT64 = len(entry pql) (distinct pqls -> distinct
    results, so misrouted batch slots are detectable); query_proto records
    per-query fallback traffic."""

    def __init__(self, batch_status: int = 0, err_for: str = ""):
        self.batch_calls: list[list] = []
        self.proto_calls: list[tuple] = []
        self.batch_status = batch_status
        self.err_for = err_for  # pql whose entry answers with Err
        self.ser = Serializer()
        self.lock = threading.Lock()

    def query_batch_raw(self, uri, entries):
        with self.lock:
            self.batch_calls.append(list(entries))
        if self.batch_status:
            raise ClientError("scripted", status=self.batch_status)
        out = []
        for e in entries:
            if self.err_for and e["query"] == self.err_for:
                out.append(self.ser.encode_query_response([], err="boom"))
            else:
                out.append(self.ser.encode_query_response([len(e["query"])]))
        return out

    def query_proto(self, uri, index, pql, shards=None, remote=False):
        with self.lock:
            self.proto_calls.append((uri, index, pql))
        return [len(pql)]


def _run_threads(n, fn):
    errs = []

    def wrap(i):
        try:
            fn(i)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]


def test_concurrent_queries_coalesce_and_route_correctly():
    fc = FakeClient()
    co = NodeCoalescer(fc, window_s=0.05)
    results = {}

    def go(i):
        pql = "Count(Row(f=%d))" % i + "x" * i  # distinct lengths
        results[i] = (co.query("http://n1:1", "idx", pql), len(pql))

    _run_threads(12, go)
    for i, (got, want) in results.items():
        assert got == [want], (i, got, want)
    n_entries = sum(len(b) for b in fc.batch_calls)
    assert n_entries == 12
    # concurrency + the admission window must actually coalesce
    assert len(fc.batch_calls) < 12
    snap = co.snapshot()
    assert snap["batched_queries"] == 12
    assert snap["mean_coalesce_factor"] > 1.0


def test_singleflight_dedup_one_wire_entry_per_unique_query():
    fc = FakeClient()
    co = NodeCoalescer(fc, window_s=0.05)
    out = []
    lock = threading.Lock()

    def go(i):
        r = co.query("http://n1:1", "idx", "Count(Row(f=7))")
        with lock:
            out.append(r)

    _run_threads(10, go)
    assert all(r == [len("Count(Row(f=7))")] for r in out)
    # identical entries dedup on the wire...
    assert sum(len(b) for b in fc.batch_calls) < 10
    assert co.snapshot()["deduped_queries"] > 0
    # ...but every waiter decodes its OWN result object (downstream code
    # mutates result graphs; deduped waiters must never share one)
    ids = {id(r) for r in out}
    assert len(ids) == len(out)


def test_404_fallback_marks_legacy_and_serves_per_query():
    fc = FakeClient(batch_status=404)
    co = NodeCoalescer(fc, window_s=0.02)

    def go(i):
        assert co.query("http://old:1", "idx", "Count(Row(f=%d))" % i) \
            == [len("Count(Row(f=%d))" % i)]

    _run_threads(8, go)
    # every query was answered per-query; at least one envelope was tried
    assert len(fc.proto_calls) == 8
    assert len(fc.batch_calls) >= 1
    assert co.snapshot()["legacy_nodes"] == 1
    # legacy destination now bypasses the coalescer entirely
    before = len(fc.batch_calls)
    assert co.query("http://old:1", "idx", "Count(Row(f=1))") \
        == [len("Count(Row(f=1))")]
    assert len(fc.batch_calls) == before


def test_legacy_ttl_reprobes_the_destination():
    fc = FakeClient(batch_status=404)
    co = NodeCoalescer(fc, window_s=0.0, legacy_ttl=0.05)
    out = co._compute(("http://old:1",),
                      [("idx", "q", None, None, None, False, None, None)])
    assert len(out) == 1  # fallback sentinel per waiter
    assert co._is_legacy("http://old:1")
    time.sleep(0.06)
    assert not co._is_legacy("http://old:1")  # TTL expired: re-probe


def test_per_entry_error_raises_only_that_waiter():
    fc = FakeClient(err_for="Count(Row(f=13))")
    co = NodeCoalescer(fc, window_s=0.05)
    oks, errors = [], []

    def go(i):
        pql = "Count(Row(f=%d))" % i
        try:
            oks.append(co.query("http://n1:1", "idx", pql))
        except ClientError as e:
            errors.append((i, str(e)))

    _run_threads(16, go)
    assert len(errors) == 1 and errors[0][0] == 13
    assert "boom" in errors[0][1]
    assert len(oks) == 15


def test_disabled_coalescer_goes_direct():
    fc = FakeClient()
    co = NodeCoalescer(fc)
    co.enabled = False
    assert co.query("http://n1:1", "idx", "Count(Row(f=1))") \
        == [len("Count(Row(f=1))")]
    assert fc.batch_calls == [] and len(fc.proto_calls) == 1


# ------------------------------------ single-retry rule, coalesced senders


class BatchEchoServer:
    """Raw-socket HTTP server speaking just enough /internal/query-batch:
    parses the envelope, answers every entry with RESULT_UINT64 =
    len(entry pql). Per-REQUEST scripted actions: "ok" (respond, keep
    alive), "close-after" (respond then close — the stale-keep-alive
    shape), "truncate" (headers + partial body then close — the
    mid-response failure that must NOT be retried)."""

    def __init__(self, script):
        self.script = list(script)
        self.sock = socket.create_server(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.connections = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._ser = Serializer()
        threading.Thread(target=self._serve, daemon=True).start()

    @property
    def uri(self):
        return f"http://127.0.0.1:{self.port}"

    def _serve(self):
        self.sock.settimeout(10)
        while True:
            try:
                conn, _ = self.sock.accept()
            except (OSError, socket.timeout):
                return
            with self._lock:
                self.connections += 1
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _read_request(self, conn):
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                return None
            data += chunk
        head, body = data.split(b"\r\n\r\n", 1)
        clen = 0
        for line in head.decode().split("\r\n"):
            if line.lower().startswith("content-length:"):
                clen = int(line.split(":", 1)[1])
        while len(body) < clen:
            chunk = conn.recv(65536)
            if not chunk:
                return None
            body += chunk
        return body

    def _handle(self, conn):
        try:
            while True:
                body = self._read_request(conn)
                if body is None:
                    return
                with self._lock:
                    self.requests += 1
                    action = self.script.pop(0) if self.script else "ok"
                entries = json.loads(body)["queries"]
                resp = self._ser.encode_query_batch_response(
                    [([len(e["query"])], "") for e in entries])
                payload = (b"HTTP/1.1 200 OK\r\n"
                           b"Content-Type: application/json\r\n"
                           b"Content-Length: " + str(len(resp)).encode()
                           + b"\r\n\r\n" + resp)
                if action == "truncate":
                    conn.sendall(payload[:len(payload) - len(resp) // 2])
                    conn.close()
                    return
                conn.sendall(payload)
                if action == "close-after":
                    conn.close()
                    return
        except OSError:
            pass

    def close(self):
        self.sock.close()


def test_stale_keepalive_retry_is_transparent_for_coalesced_envelopes():
    # the server closes its connection after the first envelope WITHOUT a
    # Connection: close header; the same (persistent, pooled-connection)
    # sender thread's next envelope hits the stale socket and must
    # transparently reconnect — the envelope is all-reads, so the one
    # re-send is safe under the single-retry rule
    srv = BatchEchoServer(["close-after"] + ["ok"] * 50)
    try:
        client = InternalClient(timeout=5)
        co = NodeCoalescer(client, window_s=0.01)
        # deterministic stale path: this thread leads both envelopes, so
        # envelope 2 rides the conn the server closed after envelope 1
        assert co.query(srv.uri, "idx", "Count(Row(f=1))") \
            == [len("Count(Row(f=1))")]
        assert co.query(srv.uri, "idx", "Count(Row(f=2))") \
            == [len("Count(Row(f=2))")]
        assert srv.connections == 2  # exactly one transparent reconnect
        assert srv.requests == 2

        # and under concurrent coalesced senders mid-close: no error ever
        # surfaces to a waiter
        def go(i):
            pql = "Count(Row(f=%d))" % i
            assert co.query(srv.uri, "idx", pql) == [len(pql)]

        _run_threads(4, go)
    finally:
        srv.close()


def test_mid_response_failure_is_terminal_not_resent():
    # headers arrived, body truncated: the peer processed the request, so
    # the client must surface the error WITHOUT re-sending (a re-send
    # could double-execute side effects on a non-idempotent route)
    srv = BatchEchoServer(["truncate"])
    try:
        client = InternalClient(timeout=5)
        with pytest.raises(ClientError):
            client.query_batch_raw(srv.uri, [
                {"index": "idx", "query": "Count(Row(f=1))"}])
        time.sleep(0.05)
        assert srv.requests == 1  # exactly one send: no retry after headers
    finally:
        srv.close()


# --------------------------------------------------------- cluster fixtures


def jpost(uri, path, payload=None, raw=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _build_cluster(tmp, n_nodes, replica_n, n_shards=6):
    """Cluster with PINNED node ids ("a", "b", "c") and index "i": the
    jump-hash placement is deterministic, and this (ids, index) choice
    splits primary ownership across every node — so fan-out (and with it
    the coalescer and the fan-out pool) is exercised from node 0 on every
    run, not only when random uuids happen to land shards remotely."""
    from pilosa_tpu.server import Server
    servers = [Server(str(tmp / f"n{i}"), port=0, replica_n=replica_n,
                      node_id=chr(ord("a") + i)).open()
               for i in range(n_nodes)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    rng = np.random.default_rng(61)
    sets = {}
    u = uris[0]
    jpost(u, "/index/i", {})
    jpost(u, "/index/i/field/f", {})
    # rows drawn from a shared universe so intersections/differences are
    # substantial (independent sparse draws over n_shards*2^20 columns
    # would make every corpus model trivially ~0)
    universe = rng.choice(n_shards * SW, 2400, replace=False)
    row_ids, col_ids = [], []
    for row in range(3):
        cols = np.unique(rng.choice(universe, 1200))
        sets[row] = set(int(c) for c in cols)
        row_ids += [row] * cols.size
        col_ids += cols.tolist()
    jpost(u, "/index/i/field/f/import",
          {"rowIDs": row_ids, "columnIDs": col_ids})
    jpost(u, "/recalculate-caches")
    # wait until every node answers the cross-shard count correctly
    # (create-shard announcements are async)
    expect = len(sets[0] & sets[1])
    assert expect > 100  # the corpus models must be non-trivial
    q = b"Count(Intersect(Row(f=0), Row(f=1)))"
    deadline = time.monotonic() + 30
    for uri in uris:
        while True:
            out = jpost(uri, "/index/i/query", raw=q)
            if out["results"][0] == expect:
                break
            assert time.monotonic() < deadline, (uri, out, expect)
            time.sleep(0.2)
    return servers, uris, sets


def _topn3_model(sets):
    # n = the full row count: per-node phase-1 truncation (distributed
    # TopN is approximate when n < rows, like the reference) cannot bite,
    # so the assertion is deterministic on every topology
    best = sorted(((len(cs), -r) for r, cs in sets.items()),
                  reverse=True)[:3]
    return [{"id": -nr, "count": c} for c, nr in best]


CORPUS = [
    ("Count(Intersect(Row(f=0), Row(f=1)))", lambda s: len(s[0] & s[1])),
    ("Count(Union(Row(f=0), Row(f=2)))", lambda s: len(s[0] | s[2])),
    ("Count(Difference(Row(f=1), Row(f=2)))", lambda s: len(s[1] - s[2])),
    ("Count(Xor(Row(f=0), Row(f=2)))", lambda s: len(s[0] ^ s[2])),
    ("TopN(f, n=3)", _topn3_model),
]


def _check_corpus(uri, sets, threads=8, rounds=2):
    """Concurrent corpus queries — concurrency forces envelope traffic."""
    def go(i):
        for _ in range(rounds):
            for pql, model in CORPUS:
                out = jpost(uri, "/index/i/query", raw=pql.encode())
                assert out["results"][0] == model(sets), pql
    _run_threads(threads, go)


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    """2-node replica_n=2 cluster: every shard lives on both nodes, so
    every node batch has a hedge candidate (the local slice)."""
    tmp = tmp_path_factory.mktemp("coalpair")
    servers, uris, sets = _build_cluster(tmp, 2, 2)
    yield servers, uris, sets
    for s in servers:
        s.close()


# ------------------------------------------------- integration: coalescing


def test_coalesced_cluster_answers_match_models(pair):
    servers, uris, sets = pair
    coal = servers[0].executor.coalescer
    assert coal is not None and coal.enabled
    b0 = coal.snapshot()
    _check_corpus(uris[0], sets)
    b1 = coal.snapshot()
    # fan-out traffic actually rode the envelope route
    assert b1["batches"] > b0["batches"]
    assert b1["batched_queries"] > b0["batched_queries"]


def test_persistent_fanout_pool_is_reused_across_queries(pair):
    servers, uris, sets = pair
    ex = servers[0].executor
    jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=0))")
    pool = ex._fanout_pool
    assert pool is not None  # created lazily by the first distributed query
    jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=1))")
    assert ex._fanout_pool is pool  # no per-query executor churn


def test_trace_id_propagates_through_coalesced_fanout(pair):
    servers, uris, sets = pair
    seen = []
    orig = servers[1].handler.dispatch

    def spy(method, path, query, body, headers=None, **kw):
        if path == "/internal/query-batch":
            seen.append((headers or {}).get("X-Pilosa-Trace-Id"))
        return orig(method, path, query, body, headers=headers, **kw)

    servers[1].handler.dispatch = spy
    try:
        req = urllib.request.Request(
            uris[0] + "/index/i/query", data=b"Count(Row(f=0))",
            method="POST", headers={"X-Pilosa-Trace-Id": "trace-xyz"})
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read())
        deadline = time.monotonic() + 5
        while not seen and time.monotonic() < deadline:
            time.sleep(0.02)
        # the fan-out pool thread ran in a copied context: the envelope
        # carried the caller's trace id, not a fresh one
        assert "trace-xyz" in seen, seen
    finally:
        servers[1].handler.dispatch = orig


# ------------------------------------------- integration: mixed-version 404


def test_mixed_version_cluster_falls_back_per_query(tmp_path):
    servers, uris, sets = _build_cluster(tmp_path, 3, 1)
    try:
        # node 1 "predates" the batch route: 404 like an old binary
        servers[1].handler.post_query_batch = \
            lambda params, query, body: (404, "application/json",
                                         b'{"error": "not found"}')
        coal0 = servers[0].executor.coalescer
        _check_corpus(uris[0], sets, threads=6, rounds=2)
        # every corpus query answered correctly from every node
        for uri in uris:
            for pql, model in CORPUS:
                out = jpost(uri, "/index/i/query", raw=pql.encode())
                assert out["results"][0] == model(sets), (uri, pql)
        snap = coal0.snapshot()
        # the 404 node was detected and is now served per-query
        assert snap["legacy_nodes"] >= 1 or snap["fallback_queries"] > 0
    finally:
        for s in servers:
            s.close()


# ------------------------------------------- integration: mid-batch death


def test_mid_batch_node_death_fails_over_per_shard(tmp_path):
    servers, uris, sets = _build_cluster(tmp_path, 3, 2)
    try:
        # kill node 2's HTTP surface abruptly (the process "dies"); the
        # cluster still routes to it, so in-flight envelopes fail with
        # ClientError and every waiter re-maps its shards onto surviving
        # replicas — exactly the per-query failover contract
        servers[2].http.close()
        _check_corpus(uris[0], sets, threads=6, rounds=1)
        for pql, model in CORPUS:
            out = jpost(uris[0], "/index/i/query", raw=pql.encode())
            assert out["results"][0] == model(sets), pql
    finally:
        for s in servers:
            s.close()


# ------------------------------------------------ integration: hedged reads


def _slow_node(server, delay):
    """Make a node's query surfaces slow (both the per-query route and the
    batch envelope); returns a restore function."""
    h = server.handler
    orig_q, orig_b = h.post_query, h.post_query_batch

    def slow_q(params, query, body):
        time.sleep(delay)
        return orig_q(params, query, body)

    def slow_b(params, query, body):
        time.sleep(delay)
        return orig_b(params, query, body)

    h.post_query, h.post_query_batch = slow_q, slow_b

    def restore():
        h.post_query, h.post_query_batch = orig_q, orig_b

    return restore


def test_hedge_wins_over_slow_replica_without_double_counting(pair):
    servers, uris, sets = pair
    ex = servers[0].executor
    restore = _slow_node(servers[1], 0.6)
    ex.hedge_delay = 0.05
    fired0, won0 = ex.hedges_fired, ex.hedges_won
    try:
        expect = len(sets[0] & sets[1])
        t0 = time.perf_counter()
        out = jpost(uris[0], "/index/i/query",
                    raw=b"Count(Intersect(Row(f=0), Row(f=1)))")
        elapsed = time.perf_counter() - t0
        # the hedge (local replica) won, the count is exact — the slow
        # primary's eventual response was discarded, not added
        assert out["results"][0] == expect, out
        assert elapsed < 0.55, elapsed
        assert ex.hedges_fired > fired0
        assert ex.hedges_won > won0
    finally:
        ex.hedge_delay = 0.0
        restore()


def test_writes_are_never_hedged_or_coalesced(pair):
    """Fuzz-style sweep over every write call: with hedging enabled and a
    coalescer installed, no write ever rides the batch envelope and no
    write is ever hedged (a hedge IS a re-send; net/client.py:70-95)."""
    servers, uris, sets = pair
    ex = servers[0].executor
    coal = ex.coalescer
    rng = np.random.default_rng(7)
    coalesced_pqls = []
    orig_query = coal.query

    def spy(uri, index, pql, shards=None):
        coalesced_pqls.append(pql)
        return orig_query(uri, index, pql, shards=shards)

    coal.query = spy
    ex.hedge_delay = 0.001  # aggressively hedge-eligible, were writes reads
    fired0 = ex.hedges_fired
    try:
        writes = []
        for _ in range(3):
            col = int(rng.integers(0, 2 * SW))
            row = int(rng.integers(0, 3))
            writes += [
                f"Set({col}, f={row})",
                f"Clear({col}, f={row})",
                f"Store(Row(f={row}), f=9)",
                "ClearRow(f=9)",
                f"SetRowAttrs(f, {row}, hot=true)",
                f"SetColumnAttrs({col}, note=\"x\")",
            ]
        for pql in writes:
            jpost(uris[0], "/index/i/query", raw=pql.encode())
        assert ex.hedges_fired == fired0  # no write ever hedged
        for pql in coalesced_pqls:  # no write ever rode an envelope
            for w in ex.WRITE_CALLS:
                assert not pql.startswith(w), pql
        # and the writes landed exactly once: a fresh Set is visible with
        # count +1 from every node (no duplicate side effects). The column
        # lives past the imported shard range, so it cannot collide with
        # fixture data
        col = 7 * SW + 4242
        base = jpost(uris[0], "/index/i/query",
                     raw=b"Count(Row(f=0))")["results"][0]
        jpost(uris[0], "/index/i/query", raw=f"Set({col}, f=0)".encode())
        for uri in uris:
            got = jpost(uri, "/index/i/query",
                        raw=b"Count(Row(f=0))")["results"][0]
            assert got == base + 1, (uri, got, base)
    finally:
        ex.hedge_delay = 0.0
        coal.query = orig_query


# --------------------------------------------------------- observability


def test_debug_vars_expose_coalesce_and_hedge_metrics(pair):
    servers, uris, sets = pair
    jpost(uris[0], "/index/i/query", raw=b"Count(Row(f=0))")
    with urllib.request.urlopen(uris[0] + "/debug/vars", timeout=10) as r:
        dv = json.loads(r.read())
    assert "netCoalesce" in dv
    for k in ("batches", "batched_queries", "netCoalesceBatchSize",
              "mean_coalesce_factor", "deduped_queries",
              "fallback_queries"):
        assert k in dv["netCoalesce"], k
    assert set(dv["hedges"]) == {"hedgesFired", "hedgesWon",
                                 "hedgesCancelled"}
    # per-node fan-out latency histogram: a timing entry per remote node
    # with log2 buckets
    fanout = {k: v for k, v in dv.get("timings", {}).items()
              if k.startswith("fanoutLatency/")}
    assert fanout, dv.get("timings", {}).keys()
    for entry in fanout.values():
        assert entry["count"] >= 1
        assert entry["buckets"], entry
