"""Golden-fixture compatibility: parse real files written by the reference
implementation (gated on the read-only reference checkout being present).

`testdata/sample_view/0` is a Pilosa-format fragment storage file;
`roaring/testdata/bitmapcontainer.roaringbitmap` is official RoaringFormatSpec
(cookie 12346) — the reference reads both (roaring/roaring.go:3887).
"""

import os

import pytest

from pilosa_tpu.storage.roaring import Bitmap

REF = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not available"
)


def test_parse_reference_fragment_file():
    data = open(f"{REF}/testdata/sample_view/0", "rb").read()
    b = Bitmap.from_bytes(data)
    assert len(b.containers) == 14207
    assert b.count() == 35001
    # re-serialize -> re-parse is lossless
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert b2.count() == b.count()
    assert b2.min() == b.min() and b2.max() == b.max()


def test_parse_official_format_file():
    data = open(f"{REF}/roaring/testdata/bitmapcontainer.roaringbitmap", "rb").read()
    b = Bitmap.from_bytes(data)
    assert b.count() == 10000
    assert b.min() == 1 and b.max() == 65537
