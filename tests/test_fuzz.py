"""Randomized PQL tree fuzzing against a pure-Python set model.

Reference: internal/test/querygenerator.go builds randomized nested
Row/Union/Intersect/Difference/Xor call trees for executor stress. Here
every generated tree is evaluated both by the Executor (device path) and by
a trivial column-set model; results must match exactly. Seeded for
reproducibility.
"""

import random

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import Holder
from pilosa_tpu.parallel.mesh import DeviceRunner, make_mesh

N_FIELDS = 3
ROWS_PER_FIELD = 4
N_SHARDS = 3
BITS_PER_ROW = 12


@pytest.fixture(scope="module", params=["single", "mesh"])
def world(tmp_path_factory, request):
    """(executor, model): model[field][row] = set of columns."""
    rng = random.Random(0xF0CC)
    tmp = tmp_path_factory.mktemp(f"fuzz_{request.param}")
    h = Holder(str(tmp / "data")).open()
    idx = h.create_index("i")
    model: dict[str, dict[int, set[int]]] = {}
    exists: set[int] = set()
    for fi in range(N_FIELDS):
        fname = f"f{fi}"
        f = idx.create_field(fname)
        model[fname] = {}
        for row in range(ROWS_PER_FIELD):
            cols = {rng.randrange(N_SHARDS * SHARD_WIDTH)
                    for _ in range(BITS_PER_ROW)}
            model[fname][row] = cols
            f.import_bits([row] * len(cols), sorted(cols))
            exists |= cols
    for c in sorted(exists):
        idx.mark_exists(c)
    runner = DeviceRunner(make_mesh() if request.param == "mesh" else None)
    ex = Executor(h, runner=runner)
    yield ex, model, exists
    h.close()


def gen_tree(rng: random.Random, depth: int) -> tuple[str, object]:
    """Returns (pql, evaluator) where evaluator is a closure over a model."""
    if depth <= 0 or rng.random() < 0.3:
        f = f"f{rng.randrange(N_FIELDS)}"
        r = rng.randrange(ROWS_PER_FIELD + 1)  # may reference an empty row
        return f"Row({f}={r})", ("row", f, r)
    op = rng.choice(["Union", "Intersect", "Difference", "Xor", "Not"])
    if op == "Not":
        pql, ev = gen_tree(rng, depth - 1)
        return f"Not({pql})", ("not", ev)
    n = rng.randrange(2, 4)
    subs = [gen_tree(rng, depth - 1) for _ in range(n)]
    pql = f"{op}({', '.join(p for p, _ in subs)})"
    return pql, (op.lower(), [e for _, e in subs])


def eval_model(node, model, exists: set[int]) -> set[int]:
    kind = node[0]
    if kind == "row":
        return set(model[node[1]].get(node[2], set()))
    if kind == "not":
        return exists - eval_model(node[1], model, exists)
    subs = [eval_model(s, model, exists) for s in node[1]]
    if kind == "union":
        out = set()
        for s in subs:
            out |= s
        return out
    if kind == "intersect":
        out = subs[0]
        for s in subs[1:]:
            out &= s
        return out
    if kind == "difference":
        out = subs[0]
        for s in subs[1:]:
            out -= s
        return out
    # xor is strictly pairwise-folded left to right
    out = subs[0]
    for s in subs[1:]:
        out ^= s
    return out


def test_fuzz_bitmap_trees(world):
    ex, model, exists = world
    rng = random.Random(0xBEEF)
    for i in range(60):
        pql, tree = gen_tree(rng, depth=3)
        expected = sorted(eval_model(tree, model, exists))
        got = ex.execute("i", pql)[0].columns().tolist()
        assert got == expected, f"iteration {i}: {pql}"


def test_fuzz_counts_match_rows(world):
    ex, model, exists = world
    rng = random.Random(0xC0DE)
    for i in range(30):
        pql, tree = gen_tree(rng, depth=2)
        expected = len(eval_model(tree, model, exists))
        got = ex.execute("i", f"Count({pql})")[0]
        assert got == expected, f"iteration {i}: Count({pql})"


# --------------------------------------------------------------- BSI fuzz


@pytest.fixture(scope="module")
def bsi_world(tmp_path_factory):
    """(executor, values, row_model): an int field over random columns plus
    one set field for Intersect composition."""
    from pilosa_tpu.models import FieldOptions, FieldType

    rng = random.Random(0xB51)
    tmp = tmp_path_factory.mktemp("fuzz_bsi")
    h = Holder(str(tmp / "data")).open()
    idx = h.create_index("b", track_existence=False)
    v = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-50, max=200))
    f = idx.create_field("f")
    n_cols = 2 * SHARD_WIDTH
    values: dict[int, int] = {}
    cols = rng.sample(range(n_cols), 600)
    vals = [rng.randrange(-50, 201) for _ in cols]
    for c, val in zip(cols, vals):
        values[c] = val
    v.import_values(cols, vals)
    rows: dict[int, set[int]] = {}
    for r in range(3):
        rc = set(rng.sample(range(n_cols), 300)) | \
            set(rng.sample(cols, 50))  # overlap with valued columns
        rows[r] = rc
        f.import_bits([r] * len(rc), sorted(rc))
    ex = Executor(h)
    yield ex, values, rows
    h.close()


def _bsi_model(values, op, x, y=None):
    if op == "><":
        return {c for c, val in values.items() if x <= val <= y}
    import operator

    f = {"<": operator.lt, "<=": operator.le, ">": operator.gt,
         ">=": operator.ge, "==": operator.eq, "!=": operator.ne}[op]
    return {c for c, val in values.items() if f(val, x)}


def test_fuzz_bsi_conditions(bsi_world):
    """Random comparison sweeps (incl. values at/past the field bounds and
    the a < v < b form) vs a dict model — the borrow/carry compare kernels
    and base-offset clamps (fragment.go:808-985, field.go:1385-1430)."""
    ex, values, rows = bsi_world
    rng = random.Random(0x5EED)
    ops = ["<", "<=", ">", ">=", "==", "!="]
    for i in range(50):
        if rng.random() < 0.25:
            a = rng.randrange(-60, 211)
            b = a + rng.randrange(0, 80)
            pql = f"Range({a} < v < {b})"
            expected = _bsi_model(values, "><", a + 1, b - 1)
        else:
            op = rng.choice(ops)
            x = rng.randrange(-60, 211)  # may exceed [min, max]
            pql = f"Range(v {op} {x})"
            expected = _bsi_model(values, op, x)
        got = set(ex.execute("b", pql)[0].columns().tolist())
        assert got == expected, f"iteration {i}: {pql}"
        # Count() takes the 1-leaf batcher path
        got_n = ex.execute("b", f"Count({pql})")[0]
        assert got_n == len(expected), f"iteration {i}: Count({pql})"


def test_fuzz_bsi_intersect_and_sum(bsi_world):
    """Range composed under Intersect, and Sum over a filtered Range."""
    ex, values, rows = bsi_world
    rng = random.Random(0xFACE)
    for i in range(25):
        r = rng.randrange(3)
        x = rng.randrange(-50, 201)
        pql = f"Intersect(Row(f={r}), Range(v >= {x}))"
        expected = rows[r] & _bsi_model(values, ">=", x)
        got = set(ex.execute("b", pql)[0].columns().tolist())
        assert got == expected, f"iteration {i}: {pql}"
        vc = ex.execute("b", f"Sum(Range(v >= {x}), field=v)")[0]
        keep = _bsi_model(values, ">=", x)
        assert vc.count == len(keep) and \
            vc.val == sum(values[c] for c in keep), f"iteration {i}: Sum"


def test_parser_depth_limit_and_adversarial_inputs():
    """Every adversarial input parses or raises ValueError — never an
    internal error type. 500-deep nesting used to escape as
    RecursionError (a remote crash/500 vector)."""
    import random
    import string

    from pilosa_tpu.pql.parser import parse_string

    rng = random.Random(4)
    cases = ["Union(" * 200 + "Row(f=1)" + ")" * 200,
             "Not(" * 500 + "Row(f=1)" + ")" * 500,
             "Row(f=99999999999999999999999999)",
             "Set(18446744073709551615, f=1)",
             'Row(f="héllo wörld")', 'Set("☃", f="☃")', 'Row(f="")']
    q = 'TopN(f, Row(g=3), n=5, attrName=cat, attrValues=["a", "b"])'
    cases += [q[:i] for i in range(1, len(q))]
    alphabet = string.printable
    cases += ["".join(rng.choice(alphabet)
                      for _ in range(rng.randrange(1, 60)))
              for _ in range(800)]
    for c in cases:
        try:
            parse_string(c)
        except ValueError:
            pass  # the one acceptable failure type
    # depth just under the bound still parses
    ok = "Not(" * 100 + "Row(f=1)" + ")" * 100
    parse_string(ok)
