"""Streaming-ingest parity fuzz (ISSUE 16 satellite).

The coalesced write path (executor._execute_ingest -> IngestBatcher ->
Fragment.apply_batch) must be BIT-IDENTICAL to the per-bit path it
replaces: same per-call changed flags, same final bitmap content, same
reads interleaved mid-stream, same existence tracking — under any
interleaving of Set/Clear, including the PILOSA_TPU_INGEST=0 kill switch
flipping at runtime. A twin executor pinned to the legacy path is the
oracle.
"""

import random
import threading

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import Holder


@pytest.fixture
def twins(tmp_path, monkeypatch):
    """Two independent holder+executor stacks fed identical inputs: `ex`
    runs the coalesced ingest path, `legacy` is pinned per-bit via the
    kill switch (read per call, so pinning is just env scoping)."""
    monkeypatch.delenv("PILOSA_TPU_INGEST", raising=False)
    ha = Holder(str(tmp_path / "a")).open()
    hb = Holder(str(tmp_path / "b")).open()
    for h in (ha, hb):
        h.create_index("i").create_field("f")
        h.index("i").create_field("g")
    ea, eb = Executor(ha), Executor(hb)
    yield ea, eb, monkeypatch
    ha.close()
    hb.close()


def _legacy(monkeypatch, ex, pql):
    monkeypatch.setenv("PILOSA_TPU_INGEST", "0")
    try:
        return ex.execute("i", pql)
    finally:
        monkeypatch.delenv("PILOSA_TPU_INGEST")


def _row_columns(ex, field, row):
    return list(ex.execute("i", f"Row({field}={row})")[0].columns())


def test_ingest_parity_fuzz(twins):
    """~600 seeded random mutations (two fields, few rows, columns
    straddling a shard boundary, Set/Clear heavily colliding), applied
    one call at a time to both stacks, with reads interleaved. Every
    changed flag and every read must match the per-bit oracle."""
    ex, legacy, monkey = twins
    rng = random.Random(0xB17)
    rows = [0, 1, 7]
    cols = ([rng.randrange(0, 2000) for _ in range(25)]
            + [SHARD_WIDTH - 3, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 11])
    for step in range(600):
        field = rng.choice(["f", "g"])
        row = rng.choice(rows)
        col = rng.choice(cols)
        op = "Set" if rng.random() < 0.6 else "Clear"
        pql = f"{op}({col}, {field}={row})"
        got = ex.execute("i", pql)
        want = _legacy(monkey, legacy, pql)
        assert got == want, f"step {step}: {pql}: {got} != {want}"
        if step % 40 == 17:
            f2, r2 = rng.choice(["f", "g"]), rng.choice(rows)
            assert (_row_columns(ex, f2, r2)
                    == _row_columns(legacy, f2, r2)), f"read @ {step}"
            q = f"Count(Union(Row(f={r2}), Not(Row(g={r2}))))"
            assert ex.execute("i", q) == legacy.execute("i", q)
    for field in ("f", "g"):
        for row in rows:
            assert _row_columns(ex, field, row) == _row_columns(
                legacy, field, row)
    # existence tracking batched through the same group commit
    assert (ex.execute("i", "Count(Not(Row(f=999)))")
            == legacy.execute("i", "Count(Not(Row(f=999)))"))


def test_ingest_kill_switch_flip_parity(twins):
    """PILOSA_TPU_INGEST flips every 25 mutations on the primary stack
    (batched <-> per-bit mid-stream) while the oracle stays per-bit
    throughout: results and final state still match — the two paths
    compose at any boundary."""
    ex, legacy, monkey = twins
    rng = random.Random(0xFA)
    for step in range(300):
        pql = (f"{'Set' if rng.random() < 0.55 else 'Clear'}"
               f"({rng.randrange(0, 300)}, f={rng.randrange(0, 3)})")
        if (step // 25) % 2:
            got = _legacy(monkey, ex, pql)
        else:
            got = ex.execute("i", pql)
        assert got == _legacy(monkey, legacy, pql), f"step {step}: {pql}"
    for row in range(3):
        assert _row_columns(ex, "f", row) == _row_columns(
            legacy, "f", row)


def test_ingest_multi_call_and_concurrent_writers(twins):
    """A multi-call envelope coalesces into ONE group commit per touched
    fragment (the >=10x fsyncs-per-acked-mutation reduction), and
    concurrent writer threads through execute() all get their acks with
    the union visible afterwards."""
    ex, _legacy_ex, _monkey = twins
    base = ex.ingest_snapshot()
    pql = "".join(f"Set({c}, f=5)" for c in range(100))
    assert ex.execute("i", pql) == [True] * 100
    snap = ex.ingest_snapshot()
    d_mut = snap["mutations"] - base["mutations"]
    d_wal = snap["walAppends"] - base["walAppends"]
    assert d_mut == 100
    # one append for the f=5 fragment + one for the existence row,
    # where the per-bit path pays one WAL write per Set plus one per
    # mark_exists: >= 10x fewer fsync-able appends
    assert 0 < d_wal <= d_mut // 10
    errs: list = []
    acks: dict = {}

    def writer(tid: int):
        try:
            got = []
            for c in range(tid * 50, tid * 50 + 50):
                got.extend(ex.execute("i", f"Set({c}, g=9)"))
            acks[tid] = got
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(t,), daemon=True)
          for t in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs
    assert all(acks[t] == [True] * 50 for t in range(8))
    assert _row_columns(ex, "g", 9) == list(range(400))
