"""TopN kernel tests vs. python sort ground truth (reference:
fragment_internal_test.go top/TopN cases)."""

import numpy as np

from pilosa_tpu.ops import bitvector as bv
from pilosa_tpu.ops import topn

WIDTH = 1 << 16
RNG = np.random.default_rng(11)


def make_slab(row_sizes):
    rows, cols = [], []
    for n in row_sizes:
        c = np.unique(RNG.integers(0, WIDTH, size=n))
        cols.append(set(c.tolist()))
        rows.append(bv.dense_from_columns(c, WIDTH))
    return np.stack(rows), cols


def test_top_rows():
    sizes = [10, 5000, 300, 4999, 1, 2500, 0, 800]
    slab, cols = make_slab(sizes)
    counts, idx = topn.top_rows(slab, 3)
    real = sorted(range(len(cols)), key=lambda i: -len(cols[i]))[:3]
    assert [len(cols[i]) for i in real] == np.asarray(counts).tolist()
    # top_k breaks count ties by index; compare counts not indices
    assert sorted(np.asarray(idx).tolist(), key=lambda i: -len(cols[i]))[0] == real[0]


def test_top_rows_k_clamped():
    slab, _ = make_slab([5, 10])
    counts, idx = topn.top_rows(slab, 100)
    assert counts.shape == (2,)


def test_top_rows_intersect():
    slab, cols = make_slab([1000, 2000, 3000, 4000])
    src_cols = np.unique(RNG.integers(0, WIDTH, size=2048))
    src = bv.dense_from_columns(src_cols, WIDTH)
    ssrc = set(src_cols.tolist())
    counts, idx = topn.top_rows_intersect(slab, src, 4)
    expect = sorted((len(c & ssrc) for c in cols), reverse=True)
    assert np.asarray(counts).tolist() == expect


def test_tanimoto():
    slab, cols = make_slab([100, 1000, 3000])
    src_cols = np.unique(RNG.integers(0, WIDTH, size=1000))
    src = bv.dense_from_columns(src_cols, WIDTH)
    ssrc = set(src_cols.tolist())
    inter, rcounts, scount = topn.tanimoto_counts(slab, src)
    assert int(scount) == len(ssrc)
    for i, c in enumerate(cols):
        assert int(inter[i]) == len(c & ssrc)
        assert int(rcounts[i]) == len(c)
    thr = 5
    mask = np.asarray(topn.tanimoto_mask(inter, rcounts, scount, np.int32(thr)))
    for i, c in enumerate(cols):
        # STRICT (reference fragment.go:1096-1100): equality at the
        # threshold is dropped
        t = 100 * len(c & ssrc) > thr * (len(c) + len(ssrc) - len(c & ssrc))
        assert bool(mask[i]) == t


# ---------------------------------------------------------------------------
# executor integration: pruning walk + no-full-scan guarantees (VERDICT r1
# items 3-4; reference threshold walk fragment.go:1121-1136)
# ---------------------------------------------------------------------------


def _make_executor(tmp_path):
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import Holder
    from pilosa_tpu.parallel.mesh import DeviceRunner

    h = Holder(str(tmp_path / "data")).open()
    return Executor(h, runner=DeviceRunner())


def test_topn_recount_bounded(tmp_path):
    """TopN(n) over a wide fragment recounts only ~n winners, not every row
    (round-1 weakness: every row id became a candidate and got a device
    recount)."""
    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    n_rows = 5000
    rows = np.repeat(np.arange(n_rows), 2)
    cols = RNG.integers(0, 1 << 16, size=2 * n_rows)
    f.import_bits(rows.tolist(), cols.tolist())

    ex.topn_recount_rows = 0
    top = ex.execute("i", "TopN(f, n=10)")[0]
    assert len(list(top)) == 10
    assert ex.topn_recount_rows <= 20, ex.topn_recount_rows
    ex.holder.close()


def test_topn_no_cache_rebuilds_not_scans(tmp_path):
    """A ranked field whose rank cache was dropped rebuilds it instead of
    falling back to a full row-id scan; a cache-type=none field yields no
    TopN candidates (nopCache semantics, cache.go:461-481)."""
    from pilosa_tpu.models.field import FieldOptions

    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1, 1, 1, 2, 2, 3], [1, 2, 3, 1, 2, 1])
    view = f.view("standard")
    view.rank_caches.clear()  # simulate lost caches
    ex.topn_recount_rows = 0
    top = ex.execute("i", "TopN(f, n=2)")[0]
    assert list(top) == [(1, 3), (2, 2)]
    assert view.rank_caches  # rebuilt in place

    g = idx.create_field("g", FieldOptions(cache_type="none"))
    g.import_bits([1, 1, 2], [1, 2, 1])
    top = ex.execute("i", "TopN(g, n=2)")[0]
    assert list(top) == []  # nopCache: no candidates, no full scan
    ex.holder.close()


def test_topn_src_walk_prunes_and_matches_naive(tmp_path):
    """TopN(src, f, n): the threshold walk early-exits once cached upper
    bounds can't beat the n-th best, and the surviving pairs match a naive
    full intersection recount."""
    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(3)
    truth = {}
    src_cols = set(range(0, 1 << 14))
    g.import_bits([7] * len(src_cols), sorted(src_cols))
    n_rows = 800
    all_rows, all_cols = [], []
    for rid in range(n_rows):
        # row size scales with id so cached counts have a strong order
        size = 20 + rid * 4
        c = np.unique(rng.integers(0, 1 << 16, size=size))
        truth[rid] = len(set(c.tolist()) & src_cols)
        all_rows.extend([rid] * len(c))
        all_cols.extend(c.tolist())
    f.import_bits(all_rows, all_cols)

    ex.topn_recount_rows = 0
    top = ex.execute("i", "TopN(f, Row(g=7), n=5)")[0]
    expect = sorted(truth.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    assert list(top) == [(rid, c) for rid, c in expect]
    # pruning: the walk must stop well before materializing all 800 rows
    assert ex.topn_recount_rows < n_rows, ex.topn_recount_rows
    ex.holder.close()


def test_pallas_count_flag_parity(tmp_path):
    """PILOSA_TPU_PALLAS routes Count() through the Pallas program_count
    kernel; results must match the XLA path exactly."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.parallel.mesh import DeviceRunner

    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    rng = np.random.default_rng(5)
    for rid in (1, 2):
        cols = np.unique(rng.integers(0, 1 << 16, size=3000))
        f.import_bits([rid] * len(cols), cols.tolist())

    plain = ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")[0]
    ex_pallas = Executor(ex.holder, runner=DeviceRunner(use_pallas=True))
    assert ex_pallas.runner.use_pallas
    fused = ex_pallas.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")[0]
    assert fused == plain > 0
    # union+andnot program shape too
    q = "Count(Difference(Union(Row(f=1), Row(f=2)), Row(f=1)))"
    assert ex_pallas.execute("i", q)[0] == ex.execute("i", q)[0]
    ex.holder.close()


def test_topn_ids_respects_attr_filter(tmp_path):
    """The explicit-ids path applies the attrName/attrValues filter too
    (fragment.go:1056-1076 filters the RowIDs path as well)."""
    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1, 1, 1, 2, 2], [1, 2, 3, 1, 2])
    ex.execute("i", 'SetRowAttrs(f, 1, color="red")')
    ex.execute("i", 'SetRowAttrs(f, 2, color="blue")')
    top = ex.execute(
        "i", 'TopN(f, ids=[1,2], attrName="color", attrValues=["red"])')[0]
    assert list(top) == [(1, 3)]
    ex.holder.close()


def test_topn_src_tie_breaks_by_id(tmp_path):
    """Intersection-count ties resolve to the smaller row id (Pairs order),
    even when the larger id ranks earlier in the cached-count walk."""
    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    g.import_bits([7, 7, 7], [1, 2, 3])
    # row 5: 10 bits, 3 in src; row 2: 8 bits, 3 in src -> tie on
    # intersection, row 5 walks first (bigger cached count)
    f.import_bits([5] * 10, [1, 2, 3, 10, 11, 12, 13, 14, 15, 16])
    f.import_bits([2] * 8, [1, 2, 3, 20, 21, 22, 23, 24])
    top = ex.execute("i", "TopN(f, Row(g=7), n=1)")[0]
    assert list(top) == [(2, 3)]
    ex.holder.close()


def test_topn_n_zero_means_all(tmp_path):
    """Explicit n=0 is the reference's zero value: unlimited results, with
    and without a Src bitmap (executor.go:694)."""
    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    f.import_bits([1, 1, 2], [1, 2, 1])
    g.import_bits([7, 7], [1, 2])
    assert list(ex.execute("i", "TopN(f, n=0)")[0]) == [(1, 2), (2, 1)]
    assert list(ex.execute("i", "TopN(f, Row(g=7), n=0)")[0]) == \
        [(1, 2), (2, 1)]
    ex.holder.close()


def test_topn_n_zero_distributed(tmp_path):
    """n=0 = unlimited must hold on the distributed reduce path too."""
    from pilosa_tpu.models.cache import merge_pairs  # noqa: F401
    from pilosa_tpu.pql import parse_string

    ex = _make_executor(tmp_path)
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1, 1, 2], [1, 2, 1])
    call = parse_string('TopN(f, n=0)').calls[0]
    partials = [[(1, 2), (2, 1)]]
    out = ex._reduce(call, partials, idx, [0])
    assert list(out) == [(1, 2), (2, 1)]
    ex.holder.close()


def test_topn_src_sparse_matches_dense(tmp_path):
    """The sparse host walk (frozen stores) and the dense device walk
    agree on TopN-with-Src results, with and without tanimotoThreshold;
    mutated candidate rows force the dense fallback and still agree."""
    from pilosa_tpu.constants import SHARD_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models import FieldOptions, Holder

    rng = np.random.default_rng(67)
    h = Holder(str(tmp_path / "d")).open()
    try:
        idx = h.create_index("sp", track_existence=False)
        n_rows = 3000
        rows_l, cols_l = [], []
        sets = {}
        for r in range(n_rows):
            c = np.unique(rng.integers(0, 2 * SHARD_WIDTH, 40))
            sets[r] = set(c.tolist())
            rows_l.append(np.full(c.size, r, dtype=np.uint64))
            cols_l.append(c.astype(np.uint64))
        fz = idx.create_field("fz", FieldOptions(cache_size=5000))
        fz.import_rows_frozen(np.concatenate(rows_l), np.concatenate(cols_l))
        mu = idx.create_field("mu", FieldOptions(cache_size=5000))
        mu.import_bits(np.concatenate(rows_l).tolist(),
                       np.concatenate(cols_l).tolist())
        ex = Executor(h)
        for q in ("TopN(%s, Row(%s=7), n=15)",
                  "TopN(%s, Row(%s=7), n=15, tanimotoThreshold=30)"):
            (a,) = ex.execute("sp", q % ("fz", "fz"))
            (b,) = ex.execute("sp", q % ("mu", "mu"))  # dense walk (dict)
            assert [tuple(p) for p in a] == [tuple(p) for p in b], q
        # brute-force check of the non-tanimoto result
        (a,) = ex.execute("sp", "TopN(fz, Row(fz=7), n=15)")
        brute = sorted(((len(sets[r] & sets[7]), -r) for r in range(n_rows)
                        if sets[r] & sets[7]), reverse=True)[:15]
        assert [tuple(p) for p in a] == [(-nr, c) for c, nr in brute]
        # mutate a candidate row on the frozen field -> overlay forces the
        # dense fallback for that walk; result still exact
        ex.execute("sp", f"Set({2 * SHARD_WIDTH - 1}, fz=7)")
        (a2,) = ex.execute("sp", "TopN(fz, Row(fz=7), n=15)")
        sets[7].add(2 * SHARD_WIDTH - 1)
        brute2 = sorted(((len(sets[r] & sets[7]), -r) for r in range(n_rows)
                         if sets[r] & sets[7]), reverse=True)[:15]
        assert [tuple(p) for p in a2] == [(-nr, c) for c, nr in brute2]
    finally:
        h.close()


def test_tanimoto_boundary_strict_parity():
    """A row whose tanimoto equals EXACTLY threshold/100 is dropped by
    both the dense mask and the sparse host walk (reference keeps only
    ceil(100·count/union) > T, fragment.go:1096-1100)."""
    import numpy as np
    # inter=1, row=2, src=2 -> union=3, tanimoto=1/3; T=33: 100*1 > 33*3
    # (100 > 99, kept); T=34: 100 < 102 (dropped). Exact equality case:
    # inter=1, union=4, T=25 -> 100*1 == 25*4 -> DROPPED (strict).
    inter = np.array([1], dtype=np.int32)
    rcounts = np.array([3], dtype=np.int32)  # union = 3+2-1 = 4
    scount = np.int32(2)
    keep_25 = np.asarray(topn.tanimoto_mask(inter, rcounts, scount,
                                            np.int32(25)))
    assert not bool(keep_25[0])  # equality at threshold -> dropped
    keep_24 = np.asarray(topn.tanimoto_mask(inter, rcounts, scount,
                                            np.int32(24)))
    assert bool(keep_24[0])
