"""TopN kernel tests vs. python sort ground truth (reference:
fragment_internal_test.go top/TopN cases)."""

import numpy as np

from pilosa_tpu.ops import bitvector as bv
from pilosa_tpu.ops import topn

WIDTH = 1 << 16
RNG = np.random.default_rng(11)


def make_slab(row_sizes):
    rows, cols = [], []
    for n in row_sizes:
        c = np.unique(RNG.integers(0, WIDTH, size=n))
        cols.append(set(c.tolist()))
        rows.append(bv.dense_from_columns(c, WIDTH))
    return np.stack(rows), cols


def test_top_rows():
    sizes = [10, 5000, 300, 4999, 1, 2500, 0, 800]
    slab, cols = make_slab(sizes)
    counts, idx = topn.top_rows(slab, 3)
    real = sorted(range(len(cols)), key=lambda i: -len(cols[i]))[:3]
    assert [len(cols[i]) for i in real] == np.asarray(counts).tolist()
    # top_k breaks count ties by index; compare counts not indices
    assert sorted(np.asarray(idx).tolist(), key=lambda i: -len(cols[i]))[0] == real[0]


def test_top_rows_k_clamped():
    slab, _ = make_slab([5, 10])
    counts, idx = topn.top_rows(slab, 100)
    assert counts.shape == (2,)


def test_top_rows_intersect():
    slab, cols = make_slab([1000, 2000, 3000, 4000])
    src_cols = np.unique(RNG.integers(0, WIDTH, size=2048))
    src = bv.dense_from_columns(src_cols, WIDTH)
    ssrc = set(src_cols.tolist())
    counts, idx = topn.top_rows_intersect(slab, src, 4)
    expect = sorted((len(c & ssrc) for c in cols), reverse=True)
    assert np.asarray(counts).tolist() == expect


def test_tanimoto():
    slab, cols = make_slab([100, 1000, 3000])
    src_cols = np.unique(RNG.integers(0, WIDTH, size=1000))
    src = bv.dense_from_columns(src_cols, WIDTH)
    ssrc = set(src_cols.tolist())
    inter, rcounts, scount = topn.tanimoto_counts(slab, src)
    assert int(scount) == len(ssrc)
    for i, c in enumerate(cols):
        assert int(inter[i]) == len(c & ssrc)
        assert int(rcounts[i]) == len(c)
    thr = 5
    mask = np.asarray(topn.tanimoto_mask(inter, rcounts, scount, np.int32(thr)))
    for i, c in enumerate(cols):
        t = 100 * len(c & ssrc) >= thr * (len(c) + len(ssrc) - len(c & ssrc))
        assert bool(mask[i]) == t
