"""Cost-based planner + generation-keyed plan cache (pilosa_tpu/planner.py).

Covers: cardinality-ordered reordering (and its canonicalization effect —
permuted operand orders share one plan-cache key), exact-zero
short-circuits (and that they never swallow validation errors), cache
invalidation by write generation, the profiler's `plan` node (chosen
order, estimated vs actual, cache events, pushdown with zero host row
bitmap bytes), the env kill switches, the clean zero-operand Intersect()
error end-to-end through the HTTP API, and the /debug/vars + /metrics
counter surfaces."""

import json
import urllib.request

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.executor import ExecutionError, Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.planner import is_empty_call, subtree_cache_key


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path / "data")).open()
    e = Executor(h)
    yield e
    h.close()


@pytest.fixture
def populated(ex):
    idx = ex.holder.create_index("i")
    f = idx.create_field("f")
    # skewed cardinalities over 2 shards: row0 big, row1 medium, row2 tiny
    f.import_bits([0] * 3000, list(range(2000))
                  + [SHARD_WIDTH + c for c in range(1000)])
    f.import_bits([1] * 50, list(range(50)))
    f.import_bits([2] * 3, [5, 7, SHARD_WIDTH + 9])
    for c in list(range(2000)) + [SHARD_WIDTH + c for c in range(1000)]:
        idx.mark_exists(c)
    return ex


# ------------------------------------------------------------- reordering


def test_reorder_cheapest_first(populated):
    ex = populated
    idx = ex.holder.index("i")
    q = "Count(Intersect(Row(f=0), Row(f=2), Row(f=1)))"
    from pilosa_tpu.pql import parse_string
    call = parse_string(q).calls[0]
    shards = idx.available_shards_list()
    planned, info = ex.planner.plan_call(idx, call, shards)
    # child of Count reordered ascending by exact cardinality: 2 (3 bits),
    # 1 (50 bits), 0 (3000 bits)
    rows = [c.args["f"] for c in planned.children[0].children]
    assert rows == [2, 1, 0]
    assert info["reorders"] == 1
    assert info["order"][0].startswith("Row(f=2)")
    # estimates are exact for plain rows
    by_expr = {e["expr"]: e for e in info["estimates"]}
    assert by_expr["Row(f=2)"]["est"] == 3 and by_expr["Row(f=2)"]["exact"]
    assert by_expr["Row(f=0)"]["est"] == 3000
    # the original parsed AST was not mutated (shared via parse cache)
    assert [c.args["f"] for c in call.children[0].children] == [0, 2, 1]


def test_reorder_does_not_change_results(populated):
    ex = populated
    for q in ("Count(Intersect(Row(f=0), Row(f=1)))",
              "Count(Union(Row(f=2), Row(f=0), Row(f=1)))",
              "Count(Xor(Row(f=1), Row(f=2)))",
              "Intersect(Row(f=0), Row(f=1))"):
        (planned,) = ex.execute("i", q)
        ex2 = Executor(ex.holder)
        ex2.planner = None
        ex2.plan_cache = None
        (unplanned,) = ex2.execute("i", q)
        if hasattr(planned, "segments"):
            assert {s: list(c) for s, c in planned.segments.items()} == \
                   {s: list(c) for s, c in unplanned.segments.items()}
        else:
            assert planned == unplanned


def test_permuted_operands_share_cache_entry(populated):
    ex = populated
    assert ex.execute("i", "Count(Intersect(Row(f=0), Row(f=1)))")[0] == 50
    s0 = ex.plan_cache.snapshot()
    assert ex.execute("i", "Count(Intersect(Row(f=1), Row(f=0)))")[0] == 50
    s1 = ex.plan_cache.snapshot()
    assert s1["hits"] == s0["hits"] + 1  # canonical order shared the key
    assert s1["entries"] == s0["entries"]


# ---------------------------------------------------------- short-circuit


def test_short_circuit_empty_intersect(populated):
    ex = populated
    idx = ex.holder.index("i")
    res0 = ex.residency.snapshot()
    # row 9 holds no bits: Intersect is provably empty — no leaves
    # uploaded, no dispatch
    assert ex.execute("i", "Count(Intersect(Row(f=0), Row(f=9)))")[0] == 0
    assert ex.planner.snapshot()["shortCircuits"] >= 1
    assert ex.residency.snapshot() == res0  # nothing materialized
    row = ex.execute("i", "Intersect(Row(f=0), Row(f=9))")[0]
    assert not row.segments


def test_union_drops_empty_children(populated):
    ex = populated
    idx = ex.holder.index("i")
    from pilosa_tpu.pql import parse_string
    call = parse_string("Union(Row(f=9), Row(f=2), Row(f=9))").calls[0]
    planned, info = ex.planner.plan_call(
        idx, call, idx.available_shards_list())
    assert info["shortCircuits"] == 2
    assert len(planned.children) == 1
    assert planned.children[0].args["f"] == 2
    # all-empty union collapses to the canonical empty call
    call2 = parse_string("Union(Row(f=9), Row(f=8))").calls[0]
    planned2, _ = ex.planner.plan_call(
        idx, call2, idx.available_shards_list())
    assert is_empty_call(planned2)


def test_difference_first_empty_short_circuits(populated):
    ex = populated
    assert ex.execute("i", "Count(Difference(Row(f=9), Row(f=0)))")[0] == 0
    # a &~ empty = a: the empty subtrahend drops out
    assert ex.execute("i", "Count(Difference(Row(f=1), Row(f=9)))")[0] == 50


def test_short_circuit_never_swallows_validation_errors(populated):
    ex = populated
    # nofield does not exist: the planned query must still raise, even
    # though Row(f=9) is provably empty
    with pytest.raises(ExecutionError, match="field not found"):
        ex.execute("i", "Count(Intersect(Row(f=9), Row(nofield=1)))")


def test_empty_intersect_clean_error(populated):
    ex = populated
    with pytest.raises(ExecutionError) as ei:
        ex.execute("i", "Count(Intersect())")
    msg = str(ei.value)
    assert "Intersect()" in msg
    assert "offset 6" in msg  # position of Intersect inside Count(...)
    with pytest.raises(ExecutionError, match="Difference"):
        ex.execute("i", "Count(Difference())")


# ----------------------------------------------------------- plan cache


def test_cache_hit_and_generation_invalidation(populated):
    ex = populated
    q = "Count(Intersect(Row(f=0), Row(f=1)))"
    assert ex.execute("i", q)[0] == 50
    s0 = ex.plan_cache.snapshot()
    assert ex.execute("i", q)[0] == 50
    s1 = ex.plan_cache.snapshot()
    assert s1["hits"] == s0["hits"] + 1
    # a write bumps the row generation -> new key -> recompute, fresh data
    f = ex.holder.index("i").field("f")
    f.set_bit(1, 100)  # row 1 gains a column inside row 0's range
    assert ex.execute("i", q)[0] == 51
    s2 = ex.plan_cache.snapshot()
    assert s2["misses"] > s1["misses"]


def test_cached_row_results_are_dense_device_arrays(populated):
    ex = populated
    q = "Intersect(Row(f=0), Row(f=2))"
    r1 = ex.execute("i", q)[0]
    s0 = ex.plan_cache.snapshot()
    r2 = ex.execute("i", q)[0]
    assert ex.plan_cache.snapshot()["hits"] == s0["hits"] + 1
    assert {s: list(c) for s, c in r1.segments.items()} == \
           {s: list(c) for s, c in r2.segments.items()}
    assert s0["bytes"] > 0


def test_cache_budget_evicts_lru(populated):
    ex = populated
    ex.plan_cache.budget = 2 * (SHARD_WIDTH // 8) * 2  # ~2 dense rows
    for rid in (0, 1, 2):
        ex.execute("i", f"Intersect(Row(f={rid}), Row(f={rid}))")
    snap = ex.plan_cache.snapshot()
    assert snap["evictions"] >= 1
    assert snap["bytes"] <= ex.plan_cache.budget


def test_subtree_cache_key_stable_and_generation_sensitive(populated):
    ex = populated
    idx = ex.holder.index("i")
    from pilosa_tpu.pql import parse_string
    call = parse_string("Intersect(Row(f=1), Row(f=2))").calls[0]
    shards = idx.available_shards_list()
    k1 = subtree_cache_key(ex, idx, call, shards)
    k2 = subtree_cache_key(ex, idx, call, shards)
    assert k1 == k2
    idx.field("f").set_bit(1, 500)  # a NEW bit (col 500 not in row 1)
    assert subtree_cache_key(ex, idx, call, shards) != k1
    # setting an already-set bit is a no-op: no generation bump, same key
    k3 = subtree_cache_key(ex, idx, call, shards)
    idx.field("f").set_bit(1, 500)
    assert subtree_cache_key(ex, idx, call, shards) == k3


def test_clear_caches_drops_plan_cache(populated):
    ex = populated
    ex.execute("i", "Count(Row(f=0))")
    assert ex.plan_cache.snapshot()["entries"] >= 1
    ex.clear_caches()
    assert ex.plan_cache.snapshot()["entries"] == 0


# ---------------------------------------------------------- kill switches


def test_env_kill_switches(tmp_path, monkeypatch):
    h = Holder(str(tmp_path / "kd")).open()
    try:
        monkeypatch.setenv("PILOSA_TPU_PLANNER", "0")
        monkeypatch.setenv("PILOSA_TPU_PLAN_CACHE", "0")
        off = Executor(h)
        assert off.planner is None and off.plan_cache is None
        idx = h.create_index("k")
        f = idx.create_field("f")
        f.import_bits([0, 0, 1], [1, 2, 2])
        # written-order execution still correct, nothing cached
        assert off.execute("k", "Count(Intersect(Row(f=0), Row(f=1)))")[0] \
            == 1
        monkeypatch.setenv("PILOSA_TPU_PLANNER", "1")
        monkeypatch.setenv("PILOSA_TPU_PLAN_CACHE", "1")
        on = Executor(h)
        assert on.planner is not None and on.plan_cache is not None
        assert on.execute("k", "Count(Intersect(Row(f=0), Row(f=1)))")[0] \
            == 1
    finally:
        h.close()


def test_server_config_knobs(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "s"), port=0, plan="off",
                 plan_cache_bytes=0).open()
    try:
        assert srv.executor.planner is None
        assert srv.executor.plan_cache is None
    finally:
        srv.close()
    with pytest.raises(ValueError, match="plan"):
        Server(str(tmp_path / "s2"), port=0, plan="maybe")


# ------------------------------------------------------- profiler surface


def test_profile_plan_node_pushdown_and_cache_events(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "p"), port=0).open()
    try:
        uri = srv.uri

        def jpost(path, payload=None, raw=None):
            body = raw if raw is not None else json.dumps(
                payload or {}).encode()
            req = urllib.request.Request(uri + path, data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        jpost("/index/p", {})
        jpost("/index/p/field/f", {})
        jpost("/index/p/field/f/import",
              {"rowIDs": [0] * 100 + [1] * 10,
               "columnIDs": list(range(100)) + list(range(10))})
        q = b"Count(Intersect(Row(f=1), Row(f=0)))"
        out = jpost("/index/p/query?profile=true", raw=q)
        plan = out["profile"]["plan"]
        assert plan, out["profile"]
        node = plan[0]
        assert node["call"] == "Count"
        assert node["pushdown"] is True
        assert node["hostRowBitmapBytes"] == 0  # no host materialization
        assert node["order"][0].startswith("Row(f=1)")  # cheapest first
        assert node["actualCardinality"] == 10
        ests = {e["expr"]: e["est"] for e in node["estimates"]}
        assert ests["Row(f=1)"] == 10 and ests["Row(f=0)"] == 100
        assert node["cache"] and node["cache"][0]["hit"] is False
        # repeat: the cache event records a hit this time
        out2 = jpost("/index/p/query?profile=true", raw=q)
        node2 = out2["profile"]["plan"][0]
        assert node2["cache"][0]["hit"] is True
        assert node2["actualCardinality"] == 10
        # slow-query history carries the plan node (long_query_time=0
        # records nothing, so arm it and re-run)
        srv.api.long_query_time = 1e-9
        jpost("/index/p/query?profile=true", raw=q)
        with urllib.request.urlopen(uri + "/debug/query-history",
                                    timeout=10) as r:
            hist = json.loads(r.read())["queries"]
        assert hist and hist[0]["profile"]["plan"][0]["call"] == "Count"
    finally:
        srv.close()


def test_zero_arg_intersect_http_e2e(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "z"), port=0).open()
    try:
        uri = srv.uri
        req = urllib.request.Request(uri + "/index/z", data=b"{}",
                                     method="POST")
        urllib.request.urlopen(req, timeout=10).read()
        req = urllib.request.Request(uri + "/index/z/query",
                                     data=b"Count(Intersect())",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        body = ei.value.read().decode()
        assert "Intersect()" in body
        assert "offset 6" in body  # position inside Count(Intersect())
        assert "not supported" not in body  # the old bare error is gone
    finally:
        srv.close()


# ----------------------------------------------------- counter surfaces


def test_debug_vars_and_metrics_counters(tmp_path):
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "m"), port=0).open()
    try:
        uri = srv.uri

        def jpost(path, payload=None, raw=None):
            body = raw if raw is not None else json.dumps(
                payload or {}).encode()
            req = urllib.request.Request(uri + path, data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        jpost("/index/m", {})
        jpost("/index/m/field/f", {})
        jpost("/index/m/field/f/import",
              {"rowIDs": [0, 0, 1], "columnIDs": [1, 2, 2]})
        q = b"Count(Intersect(Row(f=0), Row(f=1)))"  # written order is
        # NOT cheapest-first (row 0 has 2 bits, row 1 has 1): reorders
        jpost("/index/m/query", raw=q)
        jpost("/index/m/query", raw=q)
        with urllib.request.urlopen(uri + "/debug/vars", timeout=10) as r:
            d = json.loads(r.read())
        assert d["planner"]["plans"] >= 2
        assert d["planner"]["reorders"] >= 1
        assert d["planner"]["pushdowns"] >= 2
        assert d["planCache"]["hits"] >= 1
        assert d["planCache"]["entries"] >= 1
        with urllib.request.urlopen(uri + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for needle in ('pilosa_planner_total{key="reorders"}',
                       'pilosa_planner_total{key="pushdowns"}',
                       'pilosa_planner_total{key="shortCircuits"}',
                       'pilosa_planCache_total{key="hits"}',
                       'pilosa_planCache_total{key="misses"}',
                       'pilosa_planCache_total{key="evictions"}',
                       'pilosa_planCache{key="bytes"}',
                       'pilosa_planCache{key="entries"}'):
            assert needle in text, needle
        # telemetry ring series (sample_gauges): planner/plancache gauges
        g = srv.sample_gauges()
        assert "plancache.bytes" in g and "plancache.hit_rate" in g
        g2 = srv.sample_gauges()  # second tick: windowed rates computed
        assert "planner.reorders_per_s" in g2
    finally:
        srv.close()


def test_planner_defensive_on_estimation_surprise(populated):
    """A planner that trips over an exotic call shape degrades to written
    order, never a new error."""
    ex = populated
    idx = ex.holder.index("i")
    from pilosa_tpu.pql import Call
    weird = Call("Count", {}, [Call("Intersect", {}, [
        Call("Row", {"f": 0}), Call("Bogus", {})])])
    # planner leaves it alone (Bogus is unknown): the executor raises its
    # own error, same as unplanned
    with pytest.raises(ExecutionError, match="expected bitmap call"):
        ex._execute_call(idx, weird, None)
