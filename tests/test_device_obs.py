"""Device observability plane (ISSUE 18).

Four surfaces under test:

* kernel latency attribution (utils/telemetry.py KernelStats +
  counted_jit timing): per-(family, rep, arity) histograms, the
  dispatch-vs-wait split, byte attribution, the tracer-nesting
  no-double-book contract, recompile-storm signature diffs;
* the HBM residency map (executor.hbm_snapshot + /debug/hbm +
  /cluster/hbm federation): byte-exact accounting against the residency
  LRU, per-rep padding waste, legacy-peer degradation;
* on-demand device profile capture (DeviceProfiler): kill switch,
  single-flight busy contract, spool byte cap;
* PQL EXPLAIN (executor.explain_call + api.explain): the parity fuzz —
  explain-then-execute makes the representation choices EXPLAIN
  predicted, with zero device dispatches counter-asserted — plus the
  planner calibration ring and the kernel-family lint rule.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.analysis.lint import lint_source
from pilosa_tpu.constants import KERNEL_FAMILY_REPS, SHARD_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.pql.parser import parse_string
from pilosa_tpu.utils import telemetry as T

W = SHARD_WIDTH // 32


def _dispatch_counts():
    """(device programs entered, kernel-stats dispatches) — the two
    counters EXPLAIN must leave untouched."""
    x = T.xla.snapshot()
    return (x["cachedDispatches"] + x["compiles"],
            T.kernels.totals()["dispatches"])


# ------------------------------------------------------- KernelStats unit


def test_kernel_stats_records_and_snapshots():
    ks = T.KernelStats()
    ks.record_call("bitwise", "dense", 3, ms=1.5, h2d_bytes=256)
    ks.record_call("bitwise", "dense", 3, ms=0.5)
    ks.record_call("sparse", "sparse", 2)          # counted, untimed
    ks.record_wait("batcher", 12.0, n=4)
    ks.record_bytes("sparse", h2d=1024, d2h=64)
    t = ks.totals()
    assert t["dispatches"] == 3
    assert t["dispatch_ms_total"] == pytest.approx(2.0)
    assert t["wait_ms_total"] == pytest.approx(12.0) and t["waited"] == 4
    assert t["h2d_bytes"] == 256 + 1024 and t["d2h_bytes"] == 64
    snap = ks.snapshot()
    assert snap["dispatches"] == 3
    by_key = {(c["family"], c["rep"], c["arity"]): c
              for c in snap["calls"]}
    c = by_key[("bitwise", "dense", 3)]
    assert c["dispatches"] == 2 and c["timed"] == 2
    assert c["minMs"] == 0.5 and c["maxMs"] == 1.5
    assert sum(c["buckets"].values()) == 2
    c = by_key[("sparse", "sparse", 2)]
    assert c["dispatches"] == 1 and c["timed"] == 0 and c["minMs"] is None
    assert snap["wait"]["batcher"]["avgMs"] == pytest.approx(3.0)
    ks.reset()
    assert ks.totals()["dispatches"] == 0
    assert ks.snapshot()["calls"] == []


def test_kernel_stats_metrics_view_key_syntax():
    """metrics_view emits StatsClient-syntax keys with the rep tag —
    the exact series /metrics zero-fills, so the syntax IS the contract."""
    ks = T.KernelStats()
    ks.record_call("bitwise", "dense", 2, ms=1.0)
    ks.record_call("sparse", "sparse", 2, ms=4.0)
    ks.record_wait("batcher", 6.0, n=2)
    ks.record_bytes("run", h2d=128)
    counts, timings = ks.metrics_view()
    assert counts["kernelsDispatches/bitwise,rep:dense"] == 1
    assert counts["kernelsWaited/batcher,rep:dense"] == 2
    assert counts["kernelsH2dBytes/run,rep:run"] == 128
    tk = timings["kernelDispatchMs/sparse,rep:sparse"]
    assert tk["count"] == 1 and tk["sum"] == pytest.approx(4.0)
    assert tk["buckets"]  # log2 buckets render as a histogram


def test_kernel_rep_follows_inventory():
    assert T.kernel_rep("sparse") == "sparse"
    assert T.kernel_rep("run") == "run"
    assert T.kernel_rep("bitwise") == "dense"
    assert T.kernel_rep("never-registered") == "dense"
    # every registered family maps to a rep the metrics zero-fill knows
    assert set(KERNEL_FAMILY_REPS.values()) <= {"dense", "sparse", "run"}


# --------------------------------------------- counted_jit timing contract


def test_counted_jit_times_direct_calls():
    import jax.numpy as jnp
    before = T.kernels.snapshot()
    prior = {(c["family"], c["rep"], c["arity"]): c["dispatches"]
             for c in before["calls"]}

    @T.counted_jit("bitwise")
    def k(a, b):
        return a & b

    x = np.full((1, 4), 7, dtype=np.uint32)
    k(jnp.asarray(x), jnp.asarray(x))
    k(jnp.asarray(x), jnp.asarray(x))
    after = T.kernels.snapshot()
    cur = {(c["family"], c["rep"], c["arity"]): c
           for c in after["calls"]}
    c = cur[("bitwise", "dense", 2)]
    assert c["dispatches"] - prior.get(("bitwise", "dense", 2), 0) == 2
    assert c["timed"] >= 2 and c["msTotal"] > 0


def test_counted_jit_host_array_books_h2d_bytes():
    before = T.kernels.snapshot()["bytes"].get("bitwise", {}).get("h2d", 0)

    @T.counted_jit("bitwise")
    def k(a):
        return a | a

    host = np.zeros((2, W), dtype=np.uint32)
    k(host)  # a host ndarray crosses the h2d link at dispatch
    after = T.kernels.snapshot()["bytes"]["bitwise"]["h2d"]
    assert after - before >= host.nbytes


def test_counted_jit_no_double_booking_under_tracer_nesting():
    """A counted_jit kernel called from inside another jit sees tracer
    arguments and must record NOTHING — the outer dispatch is the one
    real device program."""
    import jax
    import jax.numpy as jnp

    @T.counted_jit("bitwise")
    def inner(a):
        return a ^ a

    @jax.jit
    def outer(a):
        return inner(inner(a))

    arr = jnp.zeros((1, 4), dtype=jnp.uint32)
    outer(arr)  # compile: inner traces twice, must not book
    d0, k0 = _dispatch_counts()
    outer(arr)
    outer(arr)
    d1, k1 = _dispatch_counts()
    assert k1 - k0 == 0  # zero kernel-stats entries from nested calls
    assert d1 - d0 == 0  # and no per-family xla bookings either


def test_kernel_stats_kill_switch(monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv("PILOSA_TPU_KERNEL_STATS", "0")
    assert not T.kernel_stats_enabled()

    @T.counted_jit("bitwise")
    def k(a):
        return ~a

    k0 = T.kernels.totals()["dispatches"]
    k(jnp.zeros((1, 4), dtype=jnp.uint32))
    assert T.kernels.totals()["dispatches"] == k0
    monkeypatch.delenv("PILOSA_TPU_KERNEL_STATS")
    k(jnp.zeros((1, 4), dtype=jnp.uint32))
    assert T.kernels.totals()["dispatches"] == k0 + 1


# -------------------------------------------------- storm signature diff


def test_recompile_storm_carries_signature_diff():
    x = T.XLACounters()
    sig = lambda shape: ("tree", (("arr", shape, "uint32"),))  # noqa: E731
    x.record("bitwise", sig((8, 4096)))
    with pytest.warns(RuntimeWarning, match="recompile storm"):
        for i in range(1, 12):
            x.record("bitwise", sig((8 + i, 4096)))
    snap = x.snapshot()
    fam = snap["families"]["bitwise"]
    assert snap["storms"] >= 1
    diff = fam["lastSignatureDiff"]
    assert diff is not None
    # the diff names the churning leaf: old shape -> new shape
    assert any("4096" in str(d) for d in diff["changed"])


# --------------------------------------------------------- DeviceProfiler


def test_device_profiler_kill_switch(tmp_path, monkeypatch):
    p = T.DeviceProfiler(spool_dir=str(tmp_path / "spool"))
    monkeypatch.setenv("PILOSA_TPU_DEVICE_PROFILE", "0")
    doc = p.capture(0.1)
    assert doc["status"] == "disabled" and p.captures == 0


def test_device_profiler_busy_single_flight(tmp_path):
    p = T.DeviceProfiler(spool_dir=str(tmp_path / "spool"))
    assert p._busy.acquire(blocking=False)
    try:
        assert p.capture(0.05)["status"] == "busy"
    finally:
        p._busy.release()


def test_device_profiler_capture_and_cap(tmp_path):
    spool = tmp_path / "spool"
    p = T.DeviceProfiler(spool_dir=str(spool), cap_bytes=1)
    doc = p.capture(0.05)
    assert doc["status"] == "ok", doc
    assert doc["spoolDir"] == str(spool)
    assert os.path.isdir(doc["dir"])
    first = doc["dir"]
    doc2 = p.capture(0.05)
    assert doc2["status"] == "ok"
    # 1-byte cap: the older capture is evicted, the newest survives
    assert os.path.isdir(doc2["dir"]) and not os.path.isdir(first)
    snap = p.snapshot()
    assert snap["captures"] == 2 and not snap["busy"]


# --------------------------------------------------------- CalibrationRing


def test_calibration_ring_stats_and_limit_zero():
    from pilosa_tpu.planner import CalibrationRing
    r = CalibrationRing(size=8)
    r.record({"call": "Count", "est": 150, "actual": 100})   # +50%
    r.record({"call": "Count", "est": 50, "actual": 100})    # -50%
    r.record({"call": "TopN", "est": 10, "actual": None})    # uncompared
    snap = r.snapshot()
    assert snap["recorded"] == 3 and snap["compared"] == 2
    assert snap["meanAbsRelErr"] == pytest.approx(0.5)
    assert snap["maxAbsRelErr"] == pytest.approx(0.5)
    assert len(snap["entries"]) == 3
    assert snap["entries"][0]["call"] == "TopN"  # newest first
    # limit=0 is summary-only: the EXPLAIN response must not drag the
    # whole ring across the wire
    s0 = r.snapshot(limit=0)
    assert s0["entries"] == [] and s0["compared"] == 2
    r.reset()
    assert r.snapshot()["recorded"] == 0


# ----------------------------------------------------- EXPLAIN + HBM map


@pytest.fixture()
def obs_ex(tmp_path):
    """Holder with one row per representation band: sparse (150 bits),
    dense (high-cardinality scattered), run (contiguous intervals)."""
    h = Holder(str(tmp_path / "data")).open()
    idx = h.create_index("obs", track_existence=True)
    f = idx.create_field("f")
    rng = np.random.default_rng(11)
    sets = {}
    cols = rng.choice(2 * SHARD_WIDTH, size=150, replace=False)
    f.import_bits([0] * cols.size, cols.tolist())
    sets[0] = set(cols.tolist())
    cols = rng.choice(2 * SHARD_WIDTH, size=50_000, replace=False)
    f.import_bits([1] * cols.size, cols.tolist())
    sets[1] = set(cols.tolist())
    runs = [c for s in range(0, 8000, 2000)
            for c in range(s * 3, s * 3 + 2000)]
    f.import_bits([2] * len(runs), runs)
    sets[2] = set(runs)
    ex = Executor(h)
    yield h, ex, idx, sets
    h.close()


def _leaf_reps(node, out=None):
    """DFS leaf (field, rowId, rep) triples from an EXPLAIN tree."""
    if out is None:
        out = []
    if node.get("kind") == "op":
        for ch in node.get("children", ()):
            _leaf_reps(ch, out)
    elif node.get("kind") == "row":
        out.append((node.get("field"), node.get("rowId"), node["rep"]))
    return out


def test_explain_zero_dispatch_and_rep_prediction(obs_ex):
    h, ex, idx, sets = obs_ex
    call = parse_string(
        "Count(Union(Row(f=0), Intersect(Row(f=1), Row(f=2))))").calls[0]
    d0, k0 = _dispatch_counts()
    doc = ex.explain_call(idx, call, None)
    d1, k1 = _dispatch_counts()
    assert (d1 - d0, k1 - k0) == (0, 0), "EXPLAIN dispatched a program"
    reps = {rid: rep for _, rid, rep in _leaf_reps(doc["tree"])}
    assert reps == {0: "sparse", 1: "dense", 2: "run"}
    # nothing resident yet: every leaf pays its upload estimate
    assert doc["estimatedH2dBytes"] > 0
    for _, _, rep in _leaf_reps(doc["tree"]):
        assert rep in ("dense", "sparse", "run")
    # a hybrid tree routes per-rep kernel families (not the fused path)
    fams = {n["kernelFamily"] for n in _explain_leaves(doc["tree"])}
    assert fams == {"bitwise", "sparse", "run"}


def _explain_leaves(node):
    if node.get("kind") == "op":
        for ch in node.get("children", ()):
            yield from _explain_leaves(ch)
    else:
        yield node


def test_explain_all_dense_predicts_fused_program(obs_ex):
    h, ex, idx, sets = obs_ex
    call = parse_string("Count(Row(f=1))").calls[0]
    doc = ex.explain_call(idx, call, None)
    (leaf,) = list(_explain_leaves(doc["tree"]))
    assert leaf["rep"] == "dense" and leaf["kernelFamily"] == "program"


def test_explain_vacant_row_plans_without_dispatch(obs_ex):
    """A row id with no bits set still plans (cardinality 0, cheapest
    band) — and EXPLAIN still dispatches nothing for it."""
    h, ex, idx, sets = obs_ex
    call = parse_string("Count(Row(f=999))").calls[0]
    d0, k0 = _dispatch_counts()
    doc = ex.explain_call(idx, call, None)
    assert _dispatch_counts() == (d0, k0)
    (leaf,) = list(_explain_leaves(doc["tree"]))
    assert leaf["maxShardCardinality"] == 0
    assert leaf["rep"] == "sparse"  # 0 bits sits below the sparse band
    assert not leaf["residency"]["resident"]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_explain_execute_parity_fuzz(obs_ex, seed):
    """The acceptance fuzz: EXPLAIN's representation choices equal the
    choices a subsequent execution actually makes (peek mode never
    advances hysteresis), estimates drop to zero once leaves are
    resident, and the count matches the set oracle."""
    rng = np.random.default_rng(seed)
    h, ex, idx, sets = obs_ex
    ops = ["Union", "Intersect", "Difference", "Xor"]

    def tree(depth):
        if depth == 0 or rng.random() < 0.3:
            rid = int(rng.integers(0, 3))
            return f"Row(f={rid})", sets[rid]
        op = ops[int(rng.integers(0, len(ops)))]
        (lp, ls), (rp, rs) = tree(depth - 1), tree(depth - 1)
        pql = f"{op}({lp}, {rp})"
        val = {"Union": ls | rs, "Intersect": ls & rs,
               "Difference": ls - rs, "Xor": ls ^ rs}[op]
        return pql, val

    pql, oracle = tree(2)
    call = parse_string(f"Count({pql})").calls[0]
    d0, k0 = _dispatch_counts()
    doc = ex.explain_call(idx, call, None)
    assert _dispatch_counts() == (d0, k0)
    predicted = _leaf_reps(doc["tree"])
    (n,) = ex.execute("obs", f"Count({pql})")
    assert n == len(oracle)
    # every predicted leaf is now resident under the predicted rep
    kinds = {"dense": "row", "sparse": "sparse", "run": "run"}
    entries = ex.residency.entries_snapshot()
    for field, rid, rep in predicted:
        assert any(k[0] == kinds[rep] and k[2] == field and k[4] == rid
                   for k, _ in entries), (field, rid, rep)
    # a second EXPLAIN sees resident generation-matched leaves: zero
    # upload estimate, same reps (execution didn't flip the choice)
    doc2 = ex.explain_call(idx, call, None)
    assert _leaf_reps(doc2["tree"]) == predicted
    assert doc2["estimatedH2dBytes"] == 0
    for leaf in _explain_leaves(doc2["tree"]):
        assert leaf["residency"]["resident"]
        assert leaf["residency"]["generationMatch"]


def test_explain_not_includes_existence_leaf(obs_ex):
    h, ex, idx, sets = obs_ex
    call = parse_string("Count(Not(Row(f=0)))").calls[0]
    doc = ex.explain_call(idx, call, None)
    node = doc["tree"]
    assert node["op"] == "Not" and len(node["children"]) == 2


def test_explain_stale_generation_detected(obs_ex):
    """Executor-path writes patch the resident leaf in place (EXPLAIN
    keeps seeing a generation match); a write that bypasses the executor
    bumps storage generations underneath it, and EXPLAIN reports the
    entry resident-but-stale and charges the re-upload."""
    h, ex, idx, sets = obs_ex
    ex.execute("obs", "Count(Row(f=0))")
    call = parse_string("Count(Row(f=0))").calls[0]
    doc = ex.explain_call(idx, call, None)
    (leaf,) = list(_explain_leaves(doc["tree"]))
    assert leaf["residency"]["generationMatch"]
    # a direct import bypasses the executor's device-leaf patching —
    # column inside the existing shard set (a new shard would change the
    # query's shard tuple, which is a different leaf key entirely)
    col = next(c for c in range(100) if c not in sets[0])
    idx.field("f").import_bits([0], [col])
    doc = ex.explain_call(idx, call, None)
    (leaf,) = list(_explain_leaves(doc["tree"]))
    assert leaf["residency"]["resident"]
    assert not leaf["residency"]["generationMatch"]
    assert leaf["estimatedH2dBytes"] > 0


def _api_for(h, ex):
    from pilosa_tpu.api import API
    from pilosa_tpu.parallel.cluster import Cluster, Node
    cluster = Cluster("n1")
    cluster.set_static([Node(id="n1", uri="http://localhost:0")])
    return API(h, cluster, executor=ex)


def test_api_explain_notes_and_calibration(obs_ex, tmp_path):
    h, ex, idx, sets = obs_ex
    api = _api_for(h, ex)
    doc = api.explain("obs", "Set(1, f=0)\nCount(Row(f=0))")
    assert doc["explain"][0]["planned"] is False
    assert "write call" in doc["explain"][0]["note"]
    assert doc["explain"][1]["call"] == "Count"
    assert "calibration" in doc
    assert doc["calibration"]["entries"] == []  # summary-only on the wire


def test_executed_profiled_query_feeds_calibration(obs_ex):
    from pilosa_tpu import planner as _planner
    h, ex, idx, sets = obs_ex
    api = _api_for(h, ex)
    before = _planner.calibration.snapshot()["recorded"]
    api.query_results("obs", "Count(Row(f=0))", profile=True)
    snap = _planner.calibration.snapshot()
    assert snap["recorded"] > before
    e = snap["entries"][0]
    assert e["call"] == "Count" and e["actual"] == len(sets[0])


# ----------------------------------------------------------- HBM snapshot


def test_hbm_snapshot_byte_exact_accounting(obs_ex):
    h, ex, idx, sets = obs_ex
    ex.execute("obs", "Count(Union(Row(f=0), Row(f=1), Row(f=2)))")
    doc = ex.hbm_snapshot(top=0)
    res = ex.residency.snapshot()
    assert doc["residentBytes"] == res["bytes"]
    assert doc["entries"] == res["entries"]
    # every resident byte is attributed: field groups + other kinds
    grouped = sum(g["bytes"] for g in doc["byField"]) \
        + sum(k["bytes"] for k in doc["otherKinds"])
    assert grouped == doc["residentBytes"]
    assert doc["accountedBytes"] == \
        doc["residentBytes"] + doc["planCacheBytes"]
    assert doc["headroomBytes"] == \
        doc["budgetBytes"] - doc["residentBytes"]
    # the three rep bands are present with real padded bytes
    reps = {g["rep"] for g in doc["byField"]}
    assert {"dense", "sparse", "run"} <= reps
    for g in doc["byField"]:
        assert g["bytes"] > 0 and g["wasteBytes"] >= 0
        assert g["wasteBytes"] <= g["bytes"]
    # sparse/run pay power-of-two slot padding; the waste map sees it
    assert doc["wasteByRep"]["sparse"] >= 0


def test_hbm_snapshot_top_truncates(obs_ex):
    h, ex, idx, sets = obs_ex
    ex.execute("obs", "Count(Union(Row(f=0), Row(f=1), Row(f=2)))")
    doc = ex.hbm_snapshot(top=1)
    assert len(doc["byField"]) == 1
    assert doc["byFieldTruncated"] >= 1
    # truncation never loses bytes from the headline numbers
    full = ex.hbm_snapshot(top=0)
    assert doc["residentBytes"] == full["residentBytes"]


# ------------------------------------------------------------ lint rule


def test_lint_kernel_family_counted_jit_literal():
    bad = ("from pilosa_tpu.utils.telemetry import counted_jit\n"
           "@counted_jit('nosuchfamily')\n"
           "def k(a):\n    return a\n")
    assert [f.rule for f in lint_source("pilosa_tpu/ops/x.py", bad)] \
        == ["kernel-family"]
    good = bad.replace("nosuchfamily", "bitwise")
    assert lint_source("pilosa_tpu/ops/x.py", good) == []


def test_lint_kernel_family_rejects_non_literal():
    src = ("from pilosa_tpu.utils.telemetry import counted_jit\n"
           "fam = 'bitwise'\n"
           "@counted_jit(fam)\n"
           "def k(a):\n    return a\n")
    assert "kernel-family" in [f.rule
                               for f in lint_source("pilosa_tpu/x.py", src)]


def test_lint_kernel_family_class_attr():
    bad = "class B:\n    KERNEL_FAMILY = 'unregistered'\n"
    assert [f.rule for f in lint_source("pilosa_tpu/x.py", bad)] \
        == ["kernel-family"]
    assert lint_source("pilosa_tpu/x.py",
                       "class B:\n    KERNEL_FAMILY = 'batcher'\n") == []
    # None opts a host-side batcher out of attribution — legal
    assert lint_source("pilosa_tpu/x.py",
                       "class B:\n    KERNEL_FAMILY = None\n") == []


def test_lint_kernel_family_ignores_unrelated_record_dispatch():
    """QueryProfile.record_dispatch takes a dispatch KIND, not a kernel
    family — only telemetry's record_dispatch is in scope."""
    src = "r.profile.record_dispatch('fanout', 3)\n"
    assert lint_source("pilosa_tpu/x.py", src) == []
    flagged = "telemetry.record_dispatch('nosuchfamily')\n"
    assert [f.rule for f in lint_source("pilosa_tpu/x.py", flagged)] \
        == ["kernel-family"]


def test_every_registered_family_has_known_rep():
    from pilosa_tpu.constants import KERNEL_FAMILIES
    assert KERNEL_FAMILIES == frozenset(KERNEL_FAMILY_REPS)
    assert "batcher" in KERNEL_FAMILIES and "ingest" in KERNEL_FAMILIES


# ------------------------------------------------------------ live HTTP


@pytest.fixture(scope="module")
def trio(tmp_path_factory):
    """3-node cluster with resident device leaves on every node — the
    /cluster/hbm acceptance topology."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("devobs")
    servers = [Server(str(tmp / f"n{i}"), port=0,
                      node_id=chr(ord("a") + i)).open() for i in range(3)]
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()

    def jpost(path, payload=None, raw=None, node=0, query=""):
        body = raw if raw is not None else json.dumps(payload or {}).encode()
        req = urllib.request.Request(uris[node] + path + query, data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def jget(path, node=0):
        with urllib.request.urlopen(uris[node] + path, timeout=30) as r:
            return json.loads(r.read())

    jpost("/index/m", {})
    jpost("/index/m/field/f", {})
    cols = list(range(0, 6 * SHARD_WIDTH, 997))
    jpost("/index/m/field/f/import",
          {"rowIDs": [0] * len(cols), "columnIDs": cols})
    for _ in range(2):
        jpost("/index/m/query", raw=b"Count(Row(f=0))")
    yield servers, uris, jpost, jget
    for s in servers:
        s.close()


def test_http_explain_zero_dispatch(trio):
    servers, uris, jpost, jget = trio
    d0, k0 = _dispatch_counts()
    doc = jpost("/index/m/query", raw=b"Count(Row(f=0))",
                query="?explain=true")
    # remote fan-out planning happens on peers; this node's own device
    # counters must not move (acceptance: zero dispatches)
    assert _dispatch_counts() == (d0, k0)
    assert doc["index"] == "m"
    (entry,) = doc["explain"]
    assert entry["call"] == "Count"
    assert "estimatedH2dBytes" in entry
    assert "calibration" in doc


def test_http_debug_hbm_and_vars(trio):
    servers, uris, jpost, jget = trio
    doc = jget("/debug/hbm")
    assert doc["residentBytes"] >= 0
    assert doc["accountedBytes"] == \
        doc["residentBytes"] + doc["planCacheBytes"]
    v = jget("/debug/vars")
    assert "kernels" in v and "deviceProfiler" in v
    assert v["kernels"]["enabled"] in (True, False)
    assert v["hbm"] is None or "residentBytes" in v["hbm"]
    assert "calibration" in v.get("planner", {})


def test_http_cluster_hbm_federation_byte_exact(trio):
    servers, uris, jpost, jget = trio
    doc = jget("/cluster/hbm")
    assert {n["status"] for n in doc["nodes"]} == {"ok"}
    assert len(doc["byNode"]) == 3
    # fleet totals equal the sum of every node's own map, byte-exact
    want = sum(jget("/debug/hbm", node=i)["residentBytes"]
               for i in range(3))
    assert doc["totals"]["residentBytes"] == want
    # and every node's bytes are fully attributed inside its doc
    for node_doc in doc["byNode"].values():
        grouped = sum(g["bytes"] for g in node_doc["byField"]) \
            + sum(k["bytes"] for k in node_doc["otherKinds"])
        assert grouped == node_doc["residentBytes"]


def test_http_cluster_hbm_legacy_degrade(trio, monkeypatch):
    from pilosa_tpu.net.client import ClientError
    servers, uris, jpost, jget = trio

    def legacy(uri, timeout=None):
        raise ClientError("not found", status=404)

    monkeypatch.setattr(servers[0].client, "debug_hbm", legacy)
    doc = servers[0].cluster_hbm()
    statuses = {n["id"]: n["status"] for n in doc["nodes"]}
    assert statuses["a"] == "ok"
    assert set(statuses.values()) == {"ok", "legacy"}
    # the merge stays partial-but-honest: local bytes still counted
    assert doc["totals"]["residentBytes"] == \
        jget("/debug/hbm")["residentBytes"]


def test_http_device_profile_disabled(trio, monkeypatch):
    servers, uris, jpost, jget = trio
    monkeypatch.setenv("PILOSA_TPU_DEVICE_PROFILE", "0")
    doc = jpost("/debug/device-profile", raw=b"", query="?seconds=0.1")
    assert doc["status"] == "disabled"
