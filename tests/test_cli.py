"""CLI + config tests (cmd/root_test.go table pattern: flag/env/TOML
precedence; ctl import/export/inspect/check)."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from pilosa_tpu.cli.config import Config, load_config
from pilosa_tpu.cli.main import main


def test_config_defaults():
    cfg = Config()
    assert cfg.bind == "localhost:10101"
    assert cfg.port == 10101
    assert cfg.cluster.disabled is True


def test_config_toml_env_precedence(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        'data-dir = "/tmp/x"\nbind = "localhost:9999"\n'
        "[cluster]\nreplicas = 2\nhosts = [\"http://a:1\", \"http://b:2\"]\n"
        "[anti-entropy]\ninterval = 5.0\n")
    cfg = load_config(str(toml), environ={})
    assert cfg.data_dir == "/tmp/x"
    assert cfg.port == 9999
    assert cfg.cluster.replicas == 2
    assert cfg.cluster.hosts == ["http://a:1", "http://b:2"]
    assert cfg.anti_entropy.interval == 5.0
    # env overrides TOML
    cfg = load_config(str(toml), environ={
        "PILOSA_TPU_BIND": "localhost:8888",
        "PILOSA_TPU_CLUSTER_REPLICAS": "3",
        "PILOSA_TPU_VERBOSE": "true",
    })
    assert cfg.port == 8888
    assert cfg.cluster.replicas == 3
    assert cfg.verbose is True


def test_generate_config_roundtrip(tmp_path, capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    toml = tmp_path / "gen.toml"
    toml.write_text(out)
    cfg = load_config(str(toml), environ={})
    assert cfg.bind == Config().bind


def test_inspect_and_check(tmp_path, capsys):
    import numpy as np
    from pilosa_tpu.storage.roaring import Bitmap
    path = tmp_path / "frag"
    with open(path, "wb") as f:
        Bitmap(np.arange(100, dtype=np.uint64)).write_to(f)
    assert main(["inspect", str(path)]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["bits"] == 100
    assert main(["check", str(path)]) == 0
    assert "OK" in capsys.readouterr().out
    bad = tmp_path / "bad"
    bad.write_bytes(b"\x99\x99 garbage")
    assert main(["check", str(bad)]) == 1
    assert "FAILED" in capsys.readouterr().out


@pytest.fixture
def live_server(tmp_path):
    """Spawn `pilosa-tpu server` as a real subprocess on a random port.

    [mesh] platform=cpu via env: the server initializes the backend at
    startup (mesh auto-detect), and subprocesses can't reach the CPU
    platform through JAX_PLATFORMS alone (the TPU plugin overrides it)."""
    proc, uri = _spawn_server(
        tmp_path, env_extra={"PILOSA_TPU_MESH_PLATFORM": "cpu"})
    yield uri
    proc.terminate()
    proc.wait(timeout=10)


def test_server_import_export_cli(live_server, tmp_path, capsys):
    csv_in = tmp_path / "in.csv"
    csv_in.write_text("1,10\n1,20\n2,30\n")
    assert main(["import", "--host", live_server, "--index", "i",
                 "--field", "f", "--create", str(csv_in)]) == 0
    assert "imported 3 records" in capsys.readouterr().out
    out_file = tmp_path / "out.csv"
    assert main(["export", "--host", live_server, "--index", "i",
                 "--field", "f", "-o", str(out_file)]) == 0
    assert sorted(out_file.read_text().strip().splitlines()) == [
        "1,10", "1,20", "2,30"]


def _spawn_server(tmp_path, extra_args=(), env_extra=None, wait=True):
    import os
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "--data-dir", str(tmp_path / f"data{port}"),
         "--bind", f"localhost:{port}", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
    uri = f"http://localhost:{port}"
    if wait:
        _wait_up(proc, uri)
    return proc, uri


def _wait_up(proc, uri):
    for _ in range(150):
        try:
            urllib.request.urlopen(uri + "/version", timeout=1)
            return
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server died: {proc.stderr.read().decode()}")
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("server did not come up")


def _post_query(uri, index, pql):
    req = urllib.request.Request(f"{uri}/index/{index}/query",
                                 data=pql.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_server_mesh_e2e(tmp_path):
    """The stock binary shards slabs over a GSPMD mesh (VERDICT round-1 #3:
    cmd_server previously always ran DeviceRunner(mesh=None)). Drives the
    real HTTP server over the 8-device virtual CPU mesh and asserts sharded
    execution + result parity against a meshless server."""
    from pilosa_tpu.constants import SHARD_WIDTH

    env = {"PILOSA_TPU_MESH_HOST_DEVICES": "8"}  # implies platform=cpu
    # launch both, then poll both: overlaps the two backend cold-starts
    proc, uri = _spawn_server(tmp_path, ["--mesh-devices", "auto"], env,
                              wait=False)
    proc2, uri2 = _spawn_server(tmp_path, ["--mesh-devices", "none"], env,
                                wait=False)
    try:
        _wait_up(proc, uri)
        _wait_up(proc2, uri2)
        with urllib.request.urlopen(uri + "/info", timeout=5) as resp:
            info = json.loads(resp.read())
        assert info["meshDevices"] == 8, info
        with urllib.request.urlopen(uri2 + "/info", timeout=5) as resp:
            assert json.loads(resp.read())["meshDevices"] == 1

        for u in (uri, uri2):
            for path in ("/index/i", "/index/i/field/f"):
                req = urllib.request.Request(u + path, data=b"{}",
                                             method="POST")
                urllib.request.urlopen(req, timeout=10)
            # bits across 10 shards so the slab genuinely partitions
            # (8-device mesh pads 10 -> 16 shard slots)
            for s in range(10):
                _post_query(u, "i", f"Set({s * SHARD_WIDTH + s}, f=1)")
                _post_query(u, "i", f"Set({s * SHARD_WIDTH + 7}, f=2)")
        for q in ("Count(Row(f=1))",
                  "Count(Intersect(Row(f=1), Row(f=2)))",
                  "Count(Union(Row(f=1), Row(f=2)))",
                  "TopN(f, n=3)"):
            meshed = _post_query(uri, "i", q)
            single = _post_query(uri2, "i", q)
            assert meshed == single, (q, meshed, single)
        assert meshed["results"]  # sanity: last query returned data
    finally:
        proc.terminate()
        proc2.terminate()
        proc.wait(timeout=10)
        proc2.wait(timeout=10)


def test_mesh_config_sources(tmp_path, monkeypatch):
    cfg = Config()
    assert cfg.mesh.devices == "auto" and cfg.mesh.host_devices == 0
    toml = tmp_path / "c.toml"
    toml.write_text('[mesh]\ndevices = "4"\nplatform = "cpu"\n'
                    "host-devices = 8\n")
    cfg = load_config(str(toml), environ={})
    assert (cfg.mesh.devices, cfg.mesh.platform, cfg.mesh.host_devices) == \
        ("4", "cpu", 8)
    cfg = load_config(str(toml),
                      environ={"PILOSA_TPU_MESH_DEVICES": "none",
                               "PILOSA_TPU_MESH_HOST_DEVICES": "2"})
    assert cfg.mesh.devices == "none" and cfg.mesh.host_devices == 2
    # round-trips through generate-config
    assert "[mesh]" in cfg.to_toml()


def test_mesh_from_config_variants():
    from pilosa_tpu.parallel.mesh import mesh_from_config

    assert mesh_from_config(devices="none") is None
    m = mesh_from_config(devices="auto")  # conftest: 8 virtual cpu devices
    assert m is not None and m.size == 8
    m = mesh_from_config(devices="4")
    assert m is not None and m.size == 4
    with pytest.raises(ValueError, match="integer"):
        mesh_from_config(devices="bogus")
    with pytest.raises(ValueError, match="available"):
        mesh_from_config(devices="999")


def test_profile_capture_cli(live_server, capsys, monkeypatch):
    """pilosa-tpu profile-capture drives POST /debug/device-profile:
    a capture round-trips (CPU backends trace too), --json emits the raw
    doc, and the kill switch surfaces as a non-zero exit."""
    assert main(["profile-capture", "--host", live_server,
                 "--seconds", "0.05"]) == 0
    out = capsys.readouterr().out
    assert "captured" in out and "tensorboard --logdir" in out
    assert main(["profile-capture", "--host", live_server,
                 "--seconds", "0.05", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "ok" and doc["captures"] >= 2
