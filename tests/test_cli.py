"""CLI + config tests (cmd/root_test.go table pattern: flag/env/TOML
precedence; ctl import/export/inspect/check)."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from pilosa_tpu.cli.config import Config, load_config
from pilosa_tpu.cli.main import main


def test_config_defaults():
    cfg = Config()
    assert cfg.bind == "localhost:10101"
    assert cfg.port == 10101
    assert cfg.cluster.disabled is True


def test_config_toml_env_precedence(tmp_path):
    toml = tmp_path / "c.toml"
    toml.write_text(
        'data-dir = "/tmp/x"\nbind = "localhost:9999"\n'
        "[cluster]\nreplicas = 2\nhosts = [\"http://a:1\", \"http://b:2\"]\n"
        "[anti-entropy]\ninterval = 5.0\n")
    cfg = load_config(str(toml), environ={})
    assert cfg.data_dir == "/tmp/x"
    assert cfg.port == 9999
    assert cfg.cluster.replicas == 2
    assert cfg.cluster.hosts == ["http://a:1", "http://b:2"]
    assert cfg.anti_entropy.interval == 5.0
    # env overrides TOML
    cfg = load_config(str(toml), environ={
        "PILOSA_TPU_BIND": "localhost:8888",
        "PILOSA_TPU_CLUSTER_REPLICAS": "3",
        "PILOSA_TPU_VERBOSE": "true",
    })
    assert cfg.port == 8888
    assert cfg.cluster.replicas == 3
    assert cfg.verbose is True


def test_generate_config_roundtrip(tmp_path, capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    toml = tmp_path / "gen.toml"
    toml.write_text(out)
    cfg = load_config(str(toml), environ={})
    assert cfg.bind == Config().bind


def test_inspect_and_check(tmp_path, capsys):
    import numpy as np
    from pilosa_tpu.storage.roaring import Bitmap
    path = tmp_path / "frag"
    with open(path, "wb") as f:
        Bitmap(np.arange(100, dtype=np.uint64)).write_to(f)
    assert main(["inspect", str(path)]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["bits"] == 100
    assert main(["check", str(path)]) == 0
    assert "OK" in capsys.readouterr().out
    bad = tmp_path / "bad"
    bad.write_bytes(b"\x99\x99 garbage")
    assert main(["check", str(bad)]) == 1
    assert "FAILED" in capsys.readouterr().out


@pytest.fixture
def live_server(tmp_path):
    """Spawn `pilosa-tpu server` as a real subprocess on a random port."""
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "--data-dir", str(tmp_path / "data"), "--bind", f"localhost:{port}"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    uri = f"http://localhost:{port}"
    for _ in range(100):
        try:
            urllib.request.urlopen(uri + "/version", timeout=1)
            break
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server died: {proc.stderr.read().decode()}")
            time.sleep(0.2)
    else:
        proc.kill()
        raise RuntimeError("server did not come up")
    yield uri
    proc.terminate()
    proc.wait(timeout=10)


def test_server_import_export_cli(live_server, tmp_path, capsys):
    csv_in = tmp_path / "in.csv"
    csv_in.write_text("1,10\n1,20\n2,30\n")
    assert main(["import", "--host", live_server, "--index", "i",
                 "--field", "f", "--create", str(csv_in)]) == 0
    assert "imported 3 records" in capsys.readouterr().out
    out_file = tmp_path / "out.csv"
    assert main(["export", "--host", live_server, "--index", "i",
                 "--field", "f", "-o", str(out_file)]) == 0
    assert sorted(out_file.read_text().strip().splitlines()) == [
        "1,10", "1,20", "2,30"]
