"""Per-principal resource accounting, SLO burn rates, trace export
(utils/accounting.py + utils/tracing.py TraceExporter + the HTTP
surfaces): ledger bounds and spill, principal extraction and cross-node
inheritance, multi-window burn math, /debug/usage and the federated
/cluster/usage, the accounting kill switch, and the Jaeger/OTLP-JSON
golden round-trip of a live 2-node profiled query's span tree."""

import json
import time
import urllib.request

import pytest

from pilosa_tpu.utils import accounting as A
from pilosa_tpu.utils import tracing as T


# ------------------------------------------------------------------- ledger


def test_ledger_charges_and_totals():
    led = A.UsageLedger()
    led.charge("alice", device_ms=2.5, hbm_bytes=100, queries=1)
    led.charge("alice", rpc_bytes=50, queue_ms=1.0, queries=1, errors=1)
    led.charge("bob", device_ms=1.0, queries=1, plan_cache_hits=3)
    snap = led.snapshot()
    a = snap["principals"]["alice"]
    assert a["deviceMs"] == 2.5 and a["hbmBytes"] == 100
    assert a["rpcBytes"] == 50 and a["queueMs"] == 1.0
    assert a["queries"] == 2 and a["errors"] == 1
    assert snap["principals"]["bob"]["planCacheHits"] == 3
    # totals are exact sums over every principal
    assert snap["totals"]["queries"] == 3
    assert snap["totals"]["deviceMs"] == 3.5
    # sorted by deviceMs desc; top bounds the list but not the totals
    assert list(snap["principals"]) == ["alice", "bob"]
    topped = led.snapshot(top=1)
    assert list(topped["principals"]) == ["alice"]
    assert topped["totals"]["queries"] == 3


def test_ledger_bounded_with_lowest_spender_spill():
    led = A.UsageLedger(max_principals=4)
    for i in range(10):
        led.charge(f"p{i}", device_ms=float(i), queries=1)
    snap = led.snapshot()
    assert snap["trackedPrincipals"] <= 4
    assert A.SPILL in snap["principals"]
    assert snap["spilledPrincipals"] > 0
    # NOTHING is lost: totals still count all ten queries, and the top
    # spenders survive as named entries (top-K semantics)
    assert snap["totals"]["queries"] == 10
    assert "p9" in snap["principals"]
    assert "p8" in snap["principals"]
    # the spill bucket absorbed the evicted principals' charges
    spilled_q = snap["principals"][A.SPILL]["queries"]
    named_q = sum(e["queries"] for p, e in snap["principals"].items()
                  if p != A.SPILL)
    assert spilled_q + named_q == 10


def test_ledger_delta_ring_since_cursor():
    led = A.UsageLedger(ring_size=8)
    led.charge("alice", queries=2)
    led.sample_tick()
    out = led.since(0)
    assert out["samples"][-1]["gauges"]["alice"]["queries"] == 2
    cur = out["seq"]
    # a quiet tick still advances the cursor (cheap polling)
    led.sample_tick()
    out2 = led.since(cur)
    assert out2["seq"] == cur + 1
    assert out2["samples"][-1]["gauges"] == {}
    # deltas, not totals: the next tick reports only NEW charges
    led.charge("alice", queries=5)
    led.sample_tick()
    got = led.since(out2["seq"])["samples"][-1]["gauges"]
    assert got["alice"]["queries"] == 5


# -------------------------------------------------------------- principals


def test_principal_extraction_precedence():
    # inherited internal-RPC header wins (cross-node inheritance)
    assert A.principal_from_headers(
        {A.PRINCIPAL_HEADER: "key:alice", "X-API-Key": "bob"}) == "key:alice"
    # API key used verbatim under the key: prefix
    assert A.principal_from_headers({"X-API-Key": "alice"}) == "key:alice"
    # Authorization is digested, never stored raw
    p = A.principal_from_headers({"Authorization": "Bearer s3cret"})
    assert p.startswith("auth:") and "s3cret" not in p
    assert p == A.principal_from_headers({"Authorization": "Bearer s3cret"})
    # remote-addr fallback, then anonymous
    assert A.principal_from_headers({}, "10.0.0.7") == "addr:10.0.0.7"
    assert A.principal_from_headers({}) == "anonymous"
    # hostile header bytes cannot ride into labels / stats keys
    weird = A.principal_from_headers({"X-API-Key": 'a,b:"c\nd' + "x" * 100})
    assert "," not in weird and "\n" not in weird and len(weird) <= 68


def test_account_contextvar_nop_fast_path():
    assert A.current() is None  # nothing installed: charge sites nop
    led = A.UsageLedger()
    tok = A.current_account.set(A.Account(led, "key:x"))
    try:
        A.current().charge(queries=1)
    finally:
        A.current_account.reset(tok)
    assert led.totals()["queries"] == 1
    assert A.current() is None


# --------------------------------------------------------------------- SLO


def test_classify_query():
    from pilosa_tpu.pql import parse_string_cached
    assert A.classify_query(parse_string_cached("Count(Row(f=1))")) == "count"
    assert A.classify_query(parse_string_cached("Row(f=1)")) == "read"
    assert A.classify_query(
        parse_string_cached("Intersect(Row(f=1), Row(f=2))")) == "read"
    assert A.classify_query(
        parse_string_cached('TopN(f, n=3)')) == "topn"
    assert A.classify_query(
        parse_string_cached("GroupBy(Rows(field=f))")) == "groupby"
    assert A.classify_query("not parsed") == "other"


def test_slo_burn_math_and_multiwindow_status():
    tr = A.SLOTracker(
        [A.Objective("count-latency", "count", 10.0, 0.9),
         A.Objective("availability", None, None, 0.9)],
        burn_yellow=1.0, burn_red=5.0)
    # 20 good count queries: zero burn, green
    for _ in range(20):
        tr.observe("count", 0.001, True)
    ev = tr.evaluate()
    assert ev["count-latency"]["burnShort"] == 0.0
    assert ev["count-latency"]["status"] == "green"
    # other classes never touch the count objective
    tr.observe("topn", 99.0, True)
    assert tr.evaluate()["count-latency"]["windowShortTotal"] == 20
    # every count query now blows the 10 ms bound: bad ratio 0.5 over the
    # window, budget 0.1 -> burn 5x in BOTH windows -> red
    for _ in range(20):
        tr.observe("count", 0.05, True)
    ev = tr.evaluate()
    assert ev["count-latency"]["burnShort"] == pytest.approx(5.0)
    assert ev["count-latency"]["status"] == "red"
    # latency badness does NOT count against availability (no errors)
    assert ev["availability"]["status"] == "green"
    status, reason = tr.worst()
    assert status == "red" and "count-latency" in reason


def test_slo_idle_objective_is_green_and_bad_target_rejected():
    tr = A.SLOTracker([A.Objective("availability", None, None, 0.999)])
    assert tr.evaluate()["availability"]["status"] == "green"
    with pytest.raises(ValueError):
        A.Objective("x", None, None, 1.5)
    with pytest.raises(ValueError):
        A.SLOTracker([], short_window=10, long_window=5)


def test_health_score_slo_input():
    from pilosa_tpu.utils.telemetry import health_score
    assert health_score({})["score"] == "green"
    out = health_score({"sloStatus": "red", "sloReason": "SLO x burning"})
    assert out["score"] == "red" and "SLO x burning" in out["reasons"]
    assert health_score({"sloStatus": "yellow"})["score"] == "yellow"


# ----------------------------------------------------------- profile spans


def _sample_profile():
    return {
        "traceId": "feedc0de00000001", "node": "coord", "index": "i",
        "pql": "Count(Row(f=1))", "startWall": 1000.0, "elapsedMs": 12.0,
        "calls": [{"call": "Count", "ms": 11.0}],
        "fanout": [{"node": "remote-1", "shards": 4, "ms": 6.0,
                    "transport": "coalesced"}],
        "dispatches": [{"batcher": "CountBatcher", "dispatch": 7,
                        "batchSize": 4, "wallMs": 2.0, "shareMs": 0.5}],
        "residency": {"hits": 1, "misses": 0, "hostToDeviceBytes": 0},
        "plan": [],
        "remoteProfiles": [{"node": "remote-1", "profile": {
            "traceId": "feedc0de00000001", "node": "remote-1",
            "startWall": 1000.002, "elapsedMs": 5.0,
            "calls": [{"call": "Count", "ms": 4.0}],
            "fanout": [], "dispatches": [], "remoteProfiles": []}}],
    }


def test_profile_to_spans_links_remote_under_fanout():
    spans = T.profile_to_spans(_sample_profile())
    assert len({s["traceID"] for s in spans}) == 1  # ONE trace id
    by_id = {s["spanID"]: s for s in spans}
    roots = [s for s in spans if not s["parentSpanID"]]
    assert len(roots) == 1 and roots[0]["operationName"] == "pilosa.query"
    # every parent link resolves inside the batch
    for s in spans:
        assert s["parentSpanID"] == "" or s["parentSpanID"] in by_id
    # the remote node's query span hangs under the coordinator's fan-out
    # span for that node — the cross-node parent/child link
    remote_root = next(s for s in spans
                       if s["operationName"] == "pilosa.query"
                       and s["tags"].get("node") == "remote-1")
    parent = by_id[remote_root["parentSpanID"]]
    assert parent["operationName"] == "fanout.remote-1"
    # remote's own call span chains up to the coordinator root
    remote_call = next(s for s in spans
                       if s["operationName"] == "call.Count"
                       and s["parentSpanID"] == remote_root["spanID"])
    hops = 0
    cur = remote_call
    while cur["parentSpanID"]:
        cur = by_id[cur["parentSpanID"]]
        hops += 1
    assert cur is roots[0] and hops == 3


def test_jaeger_and_otlp_batches_round_trip(tmp_path):
    spans = T.profile_to_spans(_sample_profile())
    jb = T.spans_to_jaeger(spans)
    assert jb["process"]["serviceName"] == "pilosa-tpu"
    # Jaeger: CHILD_OF references reproduce the exact parent links
    child_of = {s["spanID"]: (s["references"][0]["spanID"]
                              if s["references"] else "")
                for s in jb["spans"]}
    assert child_of == {s["spanID"]: s["parentSpanID"] for s in spans}
    ob = T.spans_to_otlp(spans)
    ospans = ob["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert {s["spanId"]: s["parentSpanId"] for s in ospans} \
        == {s["spanID"]: s["parentSpanID"] for s in spans}
    # OTLP trace ids are the zero-padded 128-bit form of the same trace
    assert {s["traceId"] for s in ospans} \
        == {spans[0]["traceID"].rjust(32, "0")}
    assert all(int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
               for s in ospans)
    # file-mode exporter: one parseable JSON batch per spool line
    spool = tmp_path / "spool.jsonl"
    exp = T.TraceExporter(mode="file", path=str(spool), fmt="otlp",
                          flush_interval=0)
    exp.export_profile(_sample_profile())
    exp.flush()
    lines = spool.read_text().strip().splitlines()
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert exp.exported == len(spans)
    exp.close()


def test_trace_exporter_kill_switch_and_sampling(tmp_path, monkeypatch):
    spool = tmp_path / "spool.jsonl"
    exp = T.TraceExporter(mode="file", path=str(spool), flush_interval=0)
    monkeypatch.setenv("PILOSA_TPU_TRACE_EXPORT", "0")
    exp.export_profile(_sample_profile())
    exp.flush()
    assert not spool.exists() and exp.exported == 0
    monkeypatch.delenv("PILOSA_TPU_TRACE_EXPORT")
    # sample=0 drops deterministically; sample=1 ships
    exp0 = T.TraceExporter(mode="file", path=str(spool), sample=0.0,
                           flush_interval=0)
    exp0.export_profile(_sample_profile())
    exp0.flush()
    assert not spool.exists()
    exp.export_profile(_sample_profile())
    exp.flush()
    assert spool.exists()
    exp.close()
    exp0.close()
    with pytest.raises(ValueError):
        T.TraceExporter(mode="carrier-pigeon", path="x")
    with pytest.raises(ValueError):
        T.TraceExporter(mode="file", path="")


# ------------------------------------------------------------ live cluster


def _post(uri, path, payload=None, raw=None, headers=None):
    body = raw if raw is not None else json.dumps(payload or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get(uri, path):
    with urllib.request.urlopen(uri + path, timeout=15) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def acct_cluster(tmp_path_factory):
    """3-node cluster with a file trace exporter on the coordinator and a
    deliberately-unmeetable count-latency SLO, serving two API keys."""
    from pilosa_tpu.server import Server

    tmp = tmp_path_factory.mktemp("acct")
    spool = tmp / "spool.jsonl"
    servers = []
    for i in range(3):
        kw = {}
        if i == 0:
            kw = {"trace_export": "file",
                  "trace_export_path": str(spool),
                  "slo_count_latency_ms": 0.0001,
                  "slo_latency_target": 0.9,
                  "slo_burn_yellow": 1.0, "slo_burn_red": 5.0}
        servers.append(Server(str(tmp / f"n{i}"), port=0,
                              node_id=chr(ord("a") + i),
                              telemetry_interval=0.05, **kw).open())
    uris = [s.uri for s in servers]
    for s in servers:
        s.cluster_hosts = uris
        s.refresh_membership()
    _post(uris[0], "/index/u", {})
    _post(uris[0], "/index/u/field/f", {})
    cols = list(range(0, 3 * 2 ** 20, 4099))
    _post(uris[0], "/index/u/field/f/import",
          {"rowIDs": [0] * len(cols), "columnIDs": cols,
           })
    _post(uris[0], "/index/u/field/f/import",
          {"rowIDs": [1] * (len(cols) // 2), "columnIDs": cols[::2]})
    yield servers, uris, spool
    for s in servers:
        s.close()


def test_per_principal_usage_on_live_cluster(acct_cluster):
    servers, uris, _ = acct_cluster
    # distinct PQL per request so the plan cache cannot zero the device
    # charges; alice issues twice bob's traffic
    for i, (key, n) in enumerate((("alice", 6), ("bob", 3))):
        for j in range(n):
            _post(uris[0], "/index/u/query",
                  raw=f"Count(Intersect(Row(f={i}), Row(f={j % 2})))"
                  .encode(), headers={"X-API-Key": key})
    doc = _get(uris[0], "/debug/usage")
    assert doc["enabled"]
    pa = doc["principals"]["key:alice"]
    pb = doc["principals"]["key:bob"]
    assert pa["queries"] == 6 and pb["queries"] == 3
    assert pa["deviceMs"] > 0, pa
    assert pa["rpcBytes"] > 0, pa  # fan-out to the other nodes
    # per-principal rows sum to the ledger totals (the /debug/vars
    # cross-check the acceptance criterion audits)
    for f in ("deviceMs", "rpcBytes", "queries"):
        total = sum(e[f] for e in doc["principals"].values())
        assert total == pytest.approx(doc["totals"][f], rel=1e-6), f
    # /debug/vars mirrors the same ledger
    dv = _get(uris[0], "/debug/vars")
    assert dv["usage"]["totals"]["queries"] == doc["totals"]["queries"]
    assert "slo" in dv


def test_principal_inherited_by_remote_nodes(acct_cluster):
    """Internal fan-out RPCs charge the REMOTE node's ledger under the
    coordinator's principal (header + envelope-entry inheritance)."""
    servers, uris, _ = acct_cluster
    _post(uris[0], "/index/u/query", raw=b"Count(Row(f=0))",
          headers={"X-API-Key": "carol"})
    found = False
    for s in servers[1:]:
        snap = s.usage.snapshot()
        if "key:carol" in snap["principals"]:
            p = snap["principals"]["key:carol"]
            assert p["queries"] >= 1
            found = True
    assert found, [s.usage.snapshot()["principals"].keys()
                   for s in servers]


def test_cluster_usage_federates_and_sums(acct_cluster):
    servers, uris, _ = acct_cluster
    doc = _get(uris[1], "/cluster/usage")
    assert {n["status"] for n in doc["nodes"]} == {"ok"}
    assert len(doc["nodes"]) == 3
    # the fleet totals are the sum of every node's ledger totals
    expect = sum(s.usage.totals()["queries"] for s in servers)
    assert doc["totals"]["queries"] == pytest.approx(expect)
    merged_alice = doc["principals"]["key:alice"]
    per_node = sum(
        s.usage.snapshot()["principals"].get("key:alice",
                                             {"queries": 0})["queries"]
        for s in servers)
    assert merged_alice["queries"] == pytest.approx(per_node)
    assert merged_alice["nodes"] >= 1


def test_cluster_usage_legacy_peer_degrades(acct_cluster):
    servers, uris, _ = acct_cluster
    orig = servers[2].handler.get_debug_usage

    def _legacy_404(params, query, body):
        return 404, "application/json", b'{"error": "not found"}'

    servers[2].handler.get_debug_usage = _legacy_404
    try:
        doc = _get(uris[0], "/cluster/usage")
        by_id = {n["id"]: n["status"] for n in doc["nodes"]}
        assert by_id["c"] == "legacy"
        assert by_id["a"] == "ok" and by_id["b"] == "ok"
    finally:
        servers[2].handler.get_debug_usage = orig


def test_slo_red_trips_gauges_and_health(acct_cluster):
    """The deliberately-unmeetable count-latency objective (0.0001 ms)
    goes red once count traffic flows, and the red lands on /metrics,
    /debug/usage and the node's health score."""
    servers, uris, _ = acct_cluster
    for j in range(4):
        _post(uris[0], "/index/u/query",
              raw=f"Count(Row(f={j % 2}))".encode(),
              headers={"X-API-Key": "slo-prober"})
    doc = _get(uris[0], "/debug/usage")
    ob = doc["slo"]["count-latency"]
    assert ob["status"] == "red", ob
    assert ob["burnShort"] >= 5.0 and ob["burnLong"] >= 5.0
    with urllib.request.urlopen(uris[0] + "/metrics", timeout=10) as r:
        text = r.read().decode()
    line = next(l for l in text.splitlines()
                if l.startswith("pilosa_slo")
                and 'key="status"' in l and "count-latency" in l)
    assert line.rstrip().endswith("2")  # red = 2.0
    health = servers[0].node_health()
    assert health["score"] == "red"
    assert any("count-latency" in r for r in health["reasons"])
    # the availability objective is untouched by latency badness
    assert doc["slo"]["availability"]["status"] == "green"


def test_exported_trace_spans_cross_nodes(acct_cluster):
    """Acceptance: a profiled cross-node query's exported batch contains
    the coordinator AND remote spans under one trace id, with the remote
    subtree parented into the coordinator's fan-out span."""
    servers, uris, spool = acct_cluster
    out = _post(uris[0], "/index/u/query?profile=true",
                raw=b"Count(Row(f=0))", headers={"X-API-Key": "tracer"})
    trace_id = out["profile"]["traceId"]
    assert any(r.get("profile") for r in out["profile"]["remoteProfiles"]), \
        "expected a remote profile fragment (cross-node query)"
    servers[0].trace_exporter.flush()
    batches = [json.loads(l) for l in
               spool.read_text().strip().splitlines()]
    spans = [s for b in batches for s in b["spans"]
             if s["traceID"] == trace_id]
    assert spans, "no exported spans for the profiled trace id"

    def tags(s):  # Jaeger-JSON tags are [{key, type, value}] lists
        return {t["key"]: t["value"] for t in s.get("tags", [])}

    nodes = {tags(s).get("node") for s in spans
             if s["operationName"] == "pilosa.query"}
    assert "a" in nodes and len(nodes) >= 2, nodes  # coordinator + remote
    by_id = {s["spanID"]: s for s in spans}
    remote_roots = [s for s in spans if s["operationName"] == "pilosa.query"
                    and tags(s).get("node") != "a"]
    for rr in remote_roots:
        parent = rr["references"][0]["spanID"]
        assert parent in by_id
        assert by_id[parent]["operationName"].startswith("fanout.")


def test_accounting_kill_switch(tmp_path, monkeypatch):
    from pilosa_tpu.server import Server
    monkeypatch.setenv("PILOSA_TPU_ACCOUNTING", "0")
    srv = Server(str(tmp_path / "ks"), port=0).open()
    try:
        _post(srv.uri, "/index/k", {})
        _post(srv.uri, "/index/k/field/f", {})
        _post(srv.uri, "/index/k/query", raw=b"Set(1, f=1)")
        _post(srv.uri, "/index/k/query", raw=b"Count(Row(f=1))",
              headers={"X-API-Key": "ghost"})
        doc = _get(srv.uri, "/debug/usage")
        assert not doc["enabled"]
        assert doc["principals"] == {} and doc["totals"]["queries"] == 0
    finally:
        srv.close()


def test_usage_ledger_runtime_toggle(tmp_path):
    """ledger.enabled flips accounting at runtime (the bench A/B path)."""
    from pilosa_tpu.server import Server
    srv = Server(str(tmp_path / "tog"), port=0).open()
    try:
        _post(srv.uri, "/index/t", {})
        _post(srv.uri, "/index/t/field/f", {})
        _post(srv.uri, "/index/t/query", raw=b"Set(1, f=1)")
        srv.usage.enabled = False
        _post(srv.uri, "/index/t/query", raw=b"Count(Row(f=1))",
              headers={"X-API-Key": "off"})
        assert "key:off" not in srv.usage.snapshot()["principals"]
        srv.usage.enabled = True
        _post(srv.uri, "/index/t/query", raw=b"Count(Row(f=1))",
              headers={"X-API-Key": "on"})
        assert srv.usage.snapshot()["principals"]["key:on"]["queries"] == 1
    finally:
        srv.close()
