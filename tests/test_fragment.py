"""Fragment storage tests: WAL durability, snapshot compaction, row
materialization, BSI values, bulk import, block checksums.

Mirrors fragment_internal_test.go coverage (setBit/clearBit, setValue,
snapshot, import paths, Blocks) on temp dirs.
"""

import io
import os

import numpy as np
import pytest

from pilosa_tpu.constants import MAX_OP_N, SHARD_WIDTH
from pilosa_tpu.storage.fragment import Fragment

RNG = np.random.default_rng(3)


@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "i/f/views/standard/fragments/0"), "i", "f", "standard", 0)
    f.open()
    yield f
    f.close()


def reopen(f: Fragment) -> Fragment:
    f.close()
    g = Fragment(f.path, f.index, f.field, f.view, f.shard)
    return g.open()


def test_set_clear_bit_and_durability(frag):
    assert frag.set_bit(10, 100)
    assert not frag.set_bit(10, 100)
    assert frag.set_bit(10, 200)
    assert frag.set_bit(500, SHARD_WIDTH - 1)
    assert frag.clear_bit(10, 200)
    assert frag.contains(10, 100)
    assert not frag.contains(10, 200)

    # ops were WAL'd, not snapshotted: reopen replays them
    g = reopen(frag)
    assert g.contains(10, 100)
    assert not g.contains(10, 200)
    assert g.contains(500, SHARD_WIDTH - 1)
    assert g.row_columns(10).tolist() == [100]
    g.close()


def test_snapshot_at_max_opn(frag):
    for i in range(MAX_OP_N + 2):
        frag.set_bit(0, i % SHARD_WIDTH)
    assert frag.op_n <= MAX_OP_N  # snapshot reset the op counter
    g = reopen(frag)
    assert g.bit_count() == MAX_OP_N + 2
    assert g.op_n <= MAX_OP_N
    g.close()


def test_row_dense_matches_columns(frag):
    cols = np.unique(RNG.integers(0, SHARD_WIDTH, 500))
    for c in cols:
        frag.set_bit(7, int(c))
    dense = frag.row_dense(7)
    from pilosa_tpu.ops.bitvector import columns_from_dense
    np.testing.assert_array_equal(columns_from_dense(dense), cols)
    assert frag.row_count(7) == cols.size
    assert frag.row_ids() == [7]
    assert frag.max_row_id() == 7


def test_generations_track_mutations(frag):
    g0 = frag.row_generation(3)
    frag.set_bit(3, 1)
    g1 = frag.row_generation(3)
    assert g1 > g0
    frag.set_bit(4, 1)
    assert frag.row_generation(3) == g1  # other row untouched
    frag.clear_bit(3, 1)
    assert frag.row_generation(3) > g1


def test_set_row_and_clear_row(frag):
    frag.set_bit(2, 5)
    frag.set_row(2, np.array([7, 8, 9]))
    assert frag.row_columns(2).tolist() == [7, 8, 9]
    assert frag.clear_row(2) == 3
    assert frag.row_columns(2).size == 0


def test_bsi_value_roundtrip(frag):
    assert frag.set_value(42, 16, 12345)
    v, ok = frag.value(42, 16)
    assert ok and v == 12345
    # overwrite with a smaller value must clear high bits
    frag.set_value(42, 16, 3)
    v, ok = frag.value(42, 16)
    assert ok and v == 3
    # unset column
    v, ok = frag.value(43, 16)
    assert not ok
    frag.clear_value(42, 16)
    assert frag.value(42, 16) == (0, False)


def test_bulk_import(frag):
    rows = [1, 1, 2, 3, 3, 3]
    cols = [10, 20, 10, 1, 2, 3]
    frag.bulk_import(rows, cols)
    assert frag.row_columns(1).tolist() == [10, 20]
    assert frag.row_columns(3).tolist() == [1, 2, 3]
    # bulk import snapshots: no ops pending
    assert frag.op_n == 0
    g = reopen(frag)
    assert g.bit_count() == 6
    g.close()


def test_bulk_import_values(frag):
    cols = [5, 6, 7]
    vals = [100, 0, 65535]
    frag.bulk_import_values(cols, vals, 16)
    for c, v in zip(cols, vals):
        got, ok = frag.value(c, 16)
        assert ok and got == v


def test_import_roaring(frag, tmp_path):
    other = Fragment(str(tmp_path / "o"), "i", "f", "standard", 0).open()
    other.bulk_import([0, 1], [100, 200])
    data = other.storage.to_bytes()
    other.close()
    frag.set_bit(0, 50)
    frag.import_roaring(data)
    assert frag.row_columns(0).tolist() == [50, 100]
    assert frag.row_columns(1).tolist() == [200]
    frag.import_roaring(data, clear=True)
    assert frag.row_columns(0).tolist() == [50]
    assert frag.row_columns(1).size == 0


def test_blocks_and_merge(frag, tmp_path):
    frag.set_bit(0, 1)
    frag.set_bit(150, 2)     # block 1
    frag.set_bit(250, 3)     # block 2
    blocks = dict(frag.blocks())
    assert set(blocks) == {0, 1, 2}

    peer = Fragment(str(tmp_path / "p"), "i", "f", "standard", 0).open()
    peer.set_bit(0, 1)
    peer.set_bit(0, 9)       # peer has extra bit in block 0
    frag.set_bit(50, 4)      # local extra in block 0
    pr, pc = peer.block_data(0)
    sets_r, sets_c, n_adopted = frag.merge_block(0, pr, pc)
    # local adopted the peer's bit
    assert frag.contains(0, 9)
    assert n_adopted >= 1
    # delta for the peer: the local-only pairs
    assert list(zip(sets_r.tolist(), sets_c.tolist())) == [(50, 4)]
    # checksums equal after peer applies delta
    for r, c in zip(sets_r.tolist(), sets_c.tolist()):
        peer.set_bit(r, c)
    assert dict(peer.blocks())[0] == dict(frag.blocks())[0]
    peer.close()


def test_merge_block_majority(frag):
    """3-replica consensus: pairs on >= 2 of 3 replicas win; local applies
    sets AND clears; per-peer deltas carry both directions
    (fragment.go:1366, 1407-1417)."""
    import numpy as np

    def pos(r, c):
        from pilosa_tpu.constants import SHARD_WIDTH
        return np.uint64(r) * np.uint64(SHARD_WIDTH) + np.uint64(c)

    # local: {A, B};  peer1: {B, C};  peer2: {C}
    # counts: A=1 (clear), B=2 (keep), C=2 (local must adopt)
    frag.set_bit(0, 1)   # A, local-only stray
    frag.set_bit(0, 2)   # B
    peer1 = np.array([pos(0, 2), pos(0, 3)], dtype=np.uint64)  # B, C
    peer2 = np.array([pos(0, 3)], dtype=np.uint64)             # C
    n_sets, n_clears, deltas, durable = frag.merge_block_majority(
        0, [peer1, peer2])
    assert durable  # small adoption rode the WAL
    assert n_sets == 1 and n_clears == 1
    assert not frag.contains(0, 1)   # minority stray cleared locally
    assert frag.contains(0, 2)
    assert frag.contains(0, 3)       # majority pair adopted
    # peer1 already matches the target {B, C}: no deltas
    p1_sets, p1_clears = deltas[0]
    assert p1_sets.size == 0 and p1_clears.size == 0
    # peer2 is missing B: one set delta, no clears
    p2_sets, p2_clears = deltas[1]
    assert p2_sets.tolist() == [int(pos(0, 2))]
    assert p2_clears.size == 0


def test_merge_block_majority_two_replicas_is_union(frag):
    """With a single peer the majority threshold is 1 — union, no clears."""
    import numpy as np
    from pilosa_tpu.constants import SHARD_WIDTH
    frag.set_bit(0, 1)
    peer = np.array([np.uint64(7)], dtype=np.uint64)  # row 0, col 7
    n_sets, n_clears, deltas, durable = frag.merge_block_majority(0, [peer])
    assert durable
    assert n_sets == 1 and n_clears == 0
    assert frag.contains(0, 1) and frag.contains(0, 7)
    sets, clears = deltas[0]
    assert sets.tolist() == [int(np.uint64(0) * np.uint64(SHARD_WIDTH) + np.uint64(1))]
    assert clears.size == 0


def test_merge_block_majority_wal_durability(frag):
    """Small adoptions are redo-logged, not snapshotted: reopen WITHOUT a
    snapshot must replay the adopted sets AND clears (writeOp contract,
    roaring/roaring.go:977)."""
    frag.set_bit(0, 1)
    frag.set_bit(0, 2)
    frag.snapshot()  # baseline persisted; WAL empty from here
    peer1 = np.array([2, 3], dtype=np.uint64)  # row 0 cols 2,3
    peer2 = np.array([3], dtype=np.uint64)
    _, _, _, durable = frag.merge_block_majority(0, [peer1, peer2])
    assert durable
    g = reopen(frag)
    assert not g.contains(0, 1)  # clear replayed
    assert g.contains(0, 2) and g.contains(0, 3)  # adoption replayed
    g.close()


def test_merge_block_majority_volatile_no_snapshot(frag, tmp_path, monkeypatch):
    """Adopting a few bits into a VOLATILE frozen fragment must not trigger
    a corpus-wide snapshot (VERDICT r4 weak #4: one adopted pair cost a
    measured ~76s rewrite of a 125M-row shard)."""
    rows = np.repeat(np.arange(50, dtype=np.uint64), 2000)
    cols = np.tile(np.arange(2000, dtype=np.uint64), 50)
    pos = np.sort(rows * np.uint64(SHARD_WIDTH) + cols)
    frag.import_frozen(pos)
    calls = {"n": 0}
    orig = Fragment.snapshot

    def counting(self):
        calls["n"] += 1
        return orig(self)

    monkeypatch.setattr(Fragment, "snapshot", counting)
    peer = np.concatenate([pos, [np.uint64(7 * SHARD_WIDTH + 5000)]])
    n_sets, _, _, durable = frag.merge_block_majority(0, [peer])
    assert n_sets == 1 and frag.contains(7, 5000)
    assert durable  # volatile contract: no snapshot owed by the caller
    assert calls["n"] == 0


def test_tar_roundtrip(frag, tmp_path):
    frag.bulk_import([0, 1, 2], [1, 2, 3])
    buf = io.BytesIO()
    frag.write_to_tar(buf)
    buf.seek(0)
    other = Fragment(str(tmp_path / "t"), "i", "f", "standard", 1).open()
    other.read_from_tar(buf)
    assert other.bit_count() == 3
    assert other.row_columns(1).tolist() == [2]
    other.close()


def test_snapshot_atomic_file(frag):
    frag.set_bit(0, 1)
    frag.snapshot()
    assert os.path.exists(frag.path)
    assert not os.path.exists(frag.path + ".snapshotting")
    g = reopen(frag)
    assert g.contains(0, 1) and g.op_n == 0
    g.close()


# ---------------------------------------------------------------------------
# mmap + flock storage lifecycle (fragment.go:190-247; VERDICT r1 item 5)
# ---------------------------------------------------------------------------


def _lazy_stats(frag):
    from pilosa_tpu.storage.roaring import LazyContainer
    lazy = mat = eager = 0
    for c in frag.storage.containers.values():
        if isinstance(c, LazyContainer):
            if c.materialized:
                mat += 1
            else:
                lazy += 1
        else:
            eager += 1
    return lazy, mat, eager


def test_open_is_lazy_and_rank_build_stays_lazy(tmp_path):
    """Holder-open cost is O(container metadata): after open + rank-cache
    style row counting, no container payload has been parsed."""
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    rows = np.repeat(np.arange(50), 3000)
    cols = np.tile(np.arange(3000) * 17 % SHARD_WIDTH, 50)
    frag.bulk_import(rows.tolist(), cols.tolist())
    frag.close()

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    lazy, mat, eager = _lazy_stats(frag)
    assert lazy > 0 and mat == 0 and eager == 0, (lazy, mat, eager)
    # rank-cache build pattern: row_ids + row_count — container-aligned
    # count_range uses descriptor cardinality, no payload parse
    for rid in frag.row_ids():
        assert frag.row_count(rid) == 3000
    lazy2, mat2, _ = _lazy_stats(frag)
    assert mat2 == 0, "row counting materialized containers"
    # a real read materializes only that row's containers
    got = np.flatnonzero(
        np.unpackbits(frag.row_dense(7).view(np.uint8), bitorder="little"))
    assert got.size == 3000
    _, mat3, _ = _lazy_stats(frag)
    assert 0 < mat3 <= SHARD_WIDTH // (1 << 16)
    frag.close()


def test_flock_second_opener_refused(tmp_path):
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    frag.set_bit(1, 5)
    with pytest.raises(RuntimeError, match="locked"):
        Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    frag.close()
    # released on close
    frag2 = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    assert frag2.contains(1, 5)
    frag2.close()


def test_flock_second_process_refused(tmp_path):
    import subprocess
    import sys

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    frag.set_bit(1, 5)
    code = (
        "from pilosa_tpu.storage.fragment import Fragment\n"
        "try:\n"
        f"    Fragment({str(tmp_path / 'f')!r}, 'i', 'f', 'standard', 0).open()\n"
        "    print('OPENED')\n"
        "except RuntimeError as e:\n"
        "    print('REFUSED:', e)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd=repo_root)
    assert "REFUSED" in out.stdout, (out.stdout, out.stderr)
    frag.close()


def test_crash_recovery_acked_writes_survive(tmp_path):
    """A process killed after acking set_bit()s must not lose them: the WAL
    is unbuffered (each op is a write(2) before the ack, the reference's
    os.File semantics — roaring.go:977 writeOp). The child exits via
    os._exit, which skips every userspace flush; a buffered WAL fails this.
    """
    import subprocess
    import sys

    n = 500
    code = (
        "import os\n"
        "from pilosa_tpu.storage.fragment import Fragment\n"
        f"f = Fragment({str(tmp_path / 'f')!r}, 'i', 'f', 'standard', 0).open()\n"
        f"for i in range({n}):\n"
        "    assert f.set_bit(i % 7, i)\n"
        "os._exit(0)\n"  # simulated crash: no close(), no flush, no atexit
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd=repo_root)
    assert out.returncode == 0, (out.stdout, out.stderr)

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    try:
        for i in range(n):
            assert frag.contains(i % 7, i), f"lost acked write {i}"
        assert frag.op_n == n
    finally:
        frag.close()


def test_wal_fsync_mode(tmp_path):
    """PILOSA_TPU_WAL_FSYNC=always fsyncs per acked op (power-loss
    durability beyond the reference's process-crash guarantee)."""
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0,
                    wal_fsync=True).open()
    try:
        assert frag.storage.op_sync
        assert frag.set_bit(3, 9)
        assert frag.clear_bit(3, 9)
        frag.snapshot()
        assert frag.storage.op_sync  # plumbed through snapshot re-open
        assert frag.set_bit(4, 1)
    finally:
        frag.close()
    g = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    try:
        assert g.contains(4, 1) and not g.contains(3, 9)
    finally:
        g.close()


def test_snapshot_remaps_and_preserves_laziness(tmp_path):
    """After a WAL-compaction snapshot, unread containers re-point at the
    new mapping without ever being parsed; data stays correct."""
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    rows = np.repeat(np.arange(20), 5000)
    cols = np.tile((np.arange(5000) * 13) % SHARD_WIDTH, 20)
    frag.bulk_import(rows.tolist(), cols.tolist())
    frag.close()

    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    frag.set_bit(0, 1)  # touch row 0 only
    frag.snapshot()
    lazy, mat, eager = _lazy_stats(frag)
    # row 0's containers were materialized by the write and carried over;
    # everything else re-lazied onto the new mmap
    assert lazy > 0 and (mat + eager) <= SHARD_WIDTH // (1 << 16) + 1
    assert frag.contains(0, 1)
    assert frag.row_count(5) == 5000
    frag.close()
    frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
    assert frag.contains(0, 1) and frag.row_count(5) == 5000
    frag.close()


def test_row_counts_overlay_after_single_bit_writes(tmp_path):
    """row_counts must absorb single-bit writes via the per-row overlay —
    and stay exact — without a bulk-generation rebuild."""
    from pilosa_tpu.storage.fragment import Fragment

    f = Fragment(str(tmp_path / "rc"), "i", "f", "standard", 0).open()
    f.bulk_import([0, 0, 1, 2], [5, 9, 9, 70000])
    assert f.row_counts([0, 1, 2, 3]).tolist() == [2, 1, 1, 0]
    bulk_gen_before = f._row_counts_cache[0]
    f.set_bit(1, 100)       # single-bit write: overlay, not rebuild
    f.set_bit(3, 8)
    f.clear_bit(0, 5)
    assert f.row_counts([0, 1, 2, 3]).tolist() == [1, 2, 1, 1]
    assert f._row_counts_cache[0] == bulk_gen_before  # base map reused
    # repeated query hits the overlay (same generations)
    assert f.row_counts([1, 3]).tolist() == [2, 1]
    # a bulk mutation rebuilds the base map
    f.bulk_import([5], [123])
    assert f.row_counts([0, 1, 2, 3, 5]).tolist() == [1, 2, 1, 1, 1]
    f.close()


def test_close_with_live_mmap_views_holds_flock(tmp_path):
    """ADVICE r4: close() must not release the flock while zero-copy views
    over the snapshot mmap are still exported — another process could
    rewrite/truncate the file under them. Without external views the lock
    releases normally (same-process reopen works); with a live external
    view the lock is held until the process exits."""
    path = str(tmp_path / "fz")
    # >= FROZEN_PARSE_MIN containers so reopen takes the zero-copy frozen
    # parse (one bit in each of 4096 rows x 16 container subs)
    rows = np.repeat(np.arange(4096, dtype=np.uint64), 16)
    cols = np.tile(np.arange(16, dtype=np.uint64) * np.uint64(65536), 4096)
    pos = np.sort(rows * np.uint64(SHARD_WIDTH) + cols)
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.import_frozen(pos)
    f.snapshot()
    f.close()

    # reopen parses the snapshot into a frozen store backed by the mmap
    g = Fragment(path, "i", "f", "standard", 0).open()
    from pilosa_tpu.storage.frozen import FrozenContainers
    assert isinstance(g.storage.containers, FrozenContainers)
    # case 1: no external views -> close releases the lock, reopen works
    g.close()
    h = Fragment(path, "i", "f", "standard", 0).open()
    # case 2: an external zero-copy view outlives close -> flock held
    view = h.storage.containers._lows[:10]  # mmap-backed slice
    h.close()
    with pytest.raises(RuntimeError, match="locked"):
        Fragment(path, "i", "f", "standard", 0).open()
    del view  # last view dies -> mapping reclaimed
    k = Fragment(path, "i", "f", "standard", 0).open()
    assert k.bit_count() == pos.size
    k.close()
