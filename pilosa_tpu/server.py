"""Server: process lifecycle wiring holder + cluster + executor + transport.

Reference: server.go — functional options (server.go:84-246), Open() sequence
(§3.1 of SURVEY.md), cluster message dispatch (server.go:485-580), anti-
entropy ticker (server.go:430-483). One Server is one "node": a host process
that owns a data dir and drives the local device mesh slice.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from pilosa_tpu.api import API
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import FieldOptions, Holder
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.http_server import Handler, HTTPServer
from pilosa_tpu.parallel.cluster import (
    Cluster,
    EVENT_LEAVE,
    Node,
    ResizeJob,
    STATE_NORMAL,
    STATE_RESIZING,
    STATE_STARTING,
)
from pilosa_tpu.parallel.mesh import DeviceRunner
from pilosa_tpu.utils import threads as _threads
from pilosa_tpu.utils.translate import TranslateStore

import os


class Server:
    """One node of the index. With `cluster_hosts` empty: single-node static
    cluster (the reference's `cluster.disabled` mode, server/config.go:65)."""

    def __init__(self, data_dir: str, host: str = "localhost", port: int = 0,
                 node_id: Optional[str] = None,
                 cluster_hosts: Optional[list[str]] = None,
                 replica_n: int = 1,
                 anti_entropy_interval: float = 0.0,
                 anti_entropy_jitter: float = 0.25,
                 anti_entropy_pace: float = 0.0,
                 anti_entropy_max_blocks: int = 0,
                 wal_fsync: str = "off",
                 cache_flush_interval: float = 60.0,
                 membership_interval: float = 5.0,
                 liveness_threshold: int = 3,
                 probe_timeout: float = 2.0,
                 join: bool = False,
                 resize_timeout: float = 120.0,
                 mesh=None,
                 long_query_time: float = 0.0,
                 query_timeout: float = 0.0,
                 max_writes_per_request: int = 5000,
                 metric_service: str = "expvar",
                 metric_host: str = "127.0.0.1:8125",
                 metric_poll_interval: float = 0.0,
                 diagnostics_url: str = "",
                 diagnostics_interval: float = 0.0,
                 tls_certificate: str = "",
                 tls_key: str = "",
                 tls_skip_verify: bool = False,
                 tracing_sampler_type: str = "off",
                 tracing_sampler_param: float = 0.0,
                 tracing_endpoint: str = "",
                 gossip_port: Optional[int] = None,
                 gossip_seeds: Optional[list[str]] = None,
                 gossip_config=None,
                 fanout_pool_size: int = 32,
                 fanout_coalesce_window: float = 0.002,
                 fanout_coalesce_max_batch: int = 64,
                 hedge_delay: float = 0.0,
                 ici_serving: str = "auto",
                 profile_mode: str = "auto",
                 query_history_size: int = 100,
                 telemetry_interval: float = 5.0,
                 telemetry_ring: int = 720,
                 log_format: str = "plain",
                 plan: str = "on",
                 plan_cache_bytes: int = 256 << 20,
                 sparse_threshold: int = 4096,
                 run_threshold: int = 2048,
                 usage_max_principals: int = 256,
                 usage_ring: int = 360,
                 slo_read_latency_ms: float = 0.0,
                 slo_count_latency_ms: float = 0.0,
                 slo_topn_latency_ms: float = 0.0,
                 slo_groupby_latency_ms: float = 0.0,
                 slo_latency_target: float = 0.99,
                 slo_availability_target: float = 0.999,
                 slo_burn_yellow: float = 6.0,
                 slo_burn_red: float = 14.4,
                 slo_window_short: float = 300.0,
                 slo_window_long: float = 3600.0,
                 trace_export: str = "off",
                 trace_export_path: str = "",
                 trace_export_endpoint: str = "",
                 trace_export_format: str = "jaeger",
                 trace_export_sample: float = 1.0,
                 qos_mode: str = "off",
                 qos_default_priority: str = "interactive",
                 qos_default_deadline: float = 0.0,
                 qos_queries_per_s: float = 0.0,
                 qos_device_ms_per_s: float = 0.0,
                 qos_bytes_per_s: float = 0.0,
                 qos_burst: float = 2.0,
                 qos_max_principals: int = 256,
                 qos_principals: Optional[dict] = None,
                 gossip_secret: str = "",
                 hint_max_bytes: int = 64 << 20,
                 hint_max_age: float = 3600.0,
                 drain_timeout: float = 30.0,
                 eviction: str = "lru",
                 events_ring: int = 2048,
                 events_spool: int = 0,
                 ingest_batch_window: float = 0.0,
                 ingest_max_batch: int = 4096):
        self.data_dir = data_dir
        # [storage] wal-fsync, plumbed down the model tree to every
        # Fragment (PILOSA_TPU_WAL_FSYNC env overrides per fragment —
        # precedence documented in docs/operations.md)
        if wal_fsync not in ("off", "always"):
            raise ValueError(
                f"invalid [storage] wal-fsync {wal_fsync!r} "
                "(expected off | always)")
        if plan not in ("on", "off"):
            # a typo'd mode must fail the boot, not silently act as "on"
            raise ValueError(
                f"invalid [query] plan {plan!r} (expected on | off)")
        if eviction not in ("lru", "heat"):
            raise ValueError(
                f"invalid [storage] eviction {eviction!r} "
                "(expected lru | heat)")
        if ici_serving not in ("off", "auto", "on"):
            # a typo'd mode must fail the boot, not silently act as "auto"
            raise ValueError(
                f"invalid [cluster] ici-serving {ici_serving!r} "
                "(expected off | auto | on)")
        self.wal_fsync = wal_fsync
        self.holder = Holder(data_dir, wal_fsync=(wal_fsync == "always"))
        self.node_id = node_id or self._load_or_create_id()
        self.cluster = Cluster(
            self.node_id, replica_n=replica_n,
            schema_fn=self._schema_shards,
            topology_path=os.path.join(data_dir, ".topology"))
        self.translate = TranslateStore(os.path.join(data_dir, ".keys"))
        self.runner = DeviceRunner(mesh)
        self.client = InternalClient(tls_skip_verify=tls_skip_verify)
        from pilosa_tpu.utils.logger import Logger
        from pilosa_tpu.utils.stats import new_stats_client
        from pilosa_tpu.utils.tracing import SpanExporter, Tracer
        self.stats = new_stats_client(metric_service, metric_host)
        # [tracing] config (server/config.go:96-104): an endpoint enables
        # batched span export; sampler gates which traces ship. Accepts a
        # full URL or the reference's bare agent "host:port" form.
        if tracing_endpoint and "://" not in tracing_endpoint:
            tracing_endpoint = f"http://{tracing_endpoint}/api/traces"
        exporter = (SpanExporter(tracing_endpoint)
                    if tracing_endpoint else None)
        self.tracer = Tracer(exporter=exporter,
                             sampler_type=tracing_sampler_type,
                             sampler_param=tracing_sampler_param)
        # --log-format=json emits structured lines carrying trace=<id> as
        # a proper field (utils/logger.py); Logger validates the mode
        self.logger = Logger(fmt=log_format)
        # cluster flight recorder (utils/events.py; docs/operations.md
        # "Flight recorder and incident timelines"): a typed, HLC-stamped
        # event journal every state-transition choke point emits into.
        # The HLC piggybacks on internal RPCs and gossip so the merged
        # /cluster/events timeline is causal, not wall-clock. Knobs:
        # [metric] events-ring (per-lane bound) / events-spool (durable
        # JSONL byte cap, 0 = off); PILOSA_TPU_EVENTS=0 kills recording.
        from pilosa_tpu.utils.events import (
            EventJournal,
            HybridLogicalClock,
            register_crash_dump,
        )
        if events_ring < 1:
            raise ValueError(
                f"invalid [metric] events-ring {events_ring!r} "
                "(expected >= 1)")
        if events_spool < 0:
            raise ValueError("[metric] events-spool must be >= 0")
        self.clock = HybridLogicalClock()
        self.events = EventJournal(
            node_id=self.node_id, ring_size=events_ring, clock=self.clock,
            spool_path=(os.path.join(data_dir, "events.spool.jsonl")
                        if events_spool > 0 else ""),
            spool_max_bytes=events_spool, stats=self.stats)
        # warn/error log lines land on the merged timeline too (bounded
        # LOG lane: a log storm can't evict lifecycle events)
        self.logger.journal = self.events
        # every outbound RPC piggybacks this node's HLC; responses merge
        self.client.hlc = self.clock
        # crash forensics: SIGQUIT (and any fatal path calling
        # spill_all_crash_dumps) spills the ring next to the data dir
        register_crash_dump(self.events, data_dir)
        from pilosa_tpu.utils.diagnostics import (
            DiagnosticsCollector,
            RuntimeMonitor,
        )
        from pilosa_tpu import __version__
        self.runtime_monitor = RuntimeMonitor(self.stats,
                                              metric_poll_interval)
        self.diagnostics = DiagnosticsCollector(
            __version__, url=diagnostics_url, interval=diagnostics_interval,
            holder=self.holder, cluster=self.cluster, logger=self.logger)
        from pilosa_tpu.utils.cluster_translate import ClusterTranslator
        self.cluster_translate = ClusterTranslator(self.translate, self.cluster,
                                                   self.client)
        self.executor = Executor(self.holder, runner=self.runner,
                                 translator=self.cluster_translate,
                                 cluster=self.cluster, client=self.client)
        self.executor.stats = self.stats
        self.executor.tracer = self.tracer
        # distributed fan-out knobs (net/coalesce.py; docs/operations.md
        # "Fan-out and hedging"): persistent pool size, coalesce window /
        # envelope cap, hedged-read delay (0 disables hedging)
        self.executor.fanout_pool_size = fanout_pool_size
        self.executor.hedge_delay = hedge_delay
        # [cluster] ici-serving: slice-local routing mode (docs
        # "ICI-native serving"). The PILOSA_TPU_ICI=0 env kill switch
        # (read at Executor/DeviceRunner construction) wins over config —
        # the emergency toggle needs no rollout. ici-serving=off also
        # keeps the runner on the GSPMD jit kernels (no shard_map
        # serving-mode programs), so off truly is the pre-ICI engine.
        self.executor.ici_mode = ici_serving
        if ici_serving == "off":
            self.runner.ici_serving = False
        # [query] planner + plan-cache knobs (docs/operations.md "Query
        # planning"). The env kill switches (PILOSA_TPU_PLANNER=0 /
        # PILOSA_TPU_PLAN_CACHE=0, read at Executor construction) win over
        # config — the emergency toggles need no config rollout.
        if plan == "off":
            self.executor.planner = None
        if plan_cache_bytes <= 0:
            self.executor.plan_cache = None
        elif self.executor.plan_cache is not None:
            self.executor.plan_cache.budget = plan_cache_bytes
        # [query] sparse-threshold: hybrid sparse/dense device containers
        # (docs/operations.md "Hybrid containers"); 0 = pure dense. The
        # PILOSA_TPU_HYBRID=0 env kill switch is read per decision and
        # wins over any threshold — no rollout needed.
        if sparse_threshold < 0:
            raise ValueError(
                f"invalid [query] sparse-threshold {sparse_threshold!r} "
                "(expected >= 0)")
        self.executor.hybrid.threshold = sparse_threshold
        # [query] run-threshold: run (interval-pair) device containers
        # for long-run rows above the sparse threshold; 0 = never run.
        if run_threshold < 0:
            raise ValueError(
                f"invalid [query] run-threshold {run_threshold!r} "
                "(expected >= 0)")
        self.executor.hybrid.run_threshold = run_threshold
        if self.executor.coalescer is not None:
            self.executor.coalescer.admission_s = fanout_coalesce_window
            self.executor.coalescer.max_batch = max(
                1, fanout_coalesce_max_batch)
        # [ingest] — write-side continuous batching (docs/operations.md
        # "Streaming ingest"); window 0 = self-clocked group commit. The
        # PILOSA_TPU_INGEST=0 kill switch is read per call and wins.
        if ingest_batch_window < 0:
            raise ValueError(
                f"invalid [ingest] batch-window {ingest_batch_window!r} "
                "(expected >= 0)")
        self.executor.ingest.admission_s = float(ingest_batch_window)
        self.executor.ingest.max_batch = max(1, ingest_max_batch)
        # [storage] eviction = lru|heat: heat steers DeviceResidency to
        # evict coldest-by-fragment-heat instead of LRU (utils/heat.py).
        # The PILOSA_TPU_HEAT=0 kill switch wins structurally: with it
        # set the Executor built no tracker and the residency manager
        # falls back to lru regardless of this knob.
        self.executor.residency.eviction = eviction
        # durable hinted handoff (storage/hints.py): replica writes
        # skipped because the target is down/draining append here and
        # replay in order when the target returns ([cluster]
        # hint-max-bytes / hint-max-age knobs; fsync follows wal-fsync —
        # a hint guards an acked write, so it gets the WAL's durability)
        from pilosa_tpu.storage.hints import HintStore
        if hint_max_age < 0 or drain_timeout < 0:
            raise ValueError(
                "[cluster] hint-max-age and drain-timeout must be >= 0")
        self.hints = HintStore(os.path.join(data_dir, ".hints"),
                               max_bytes=hint_max_bytes,
                               max_age=hint_max_age,
                               fsync=(wal_fsync == "always"),
                               stats=self.stats, logger=self.logger,
                               journal=self.events)
        self.executor.hints = self.hints
        # flight-recorder hook for topology-fingerprint flips and
        # slice-local route flips (executor._ici_co_resident)
        self.executor.journal = self.events
        # graceful-drain lifecycle (docs/operations.md "Rolling restarts
        # and drains"): SIGTERM / POST /cluster/drain moves this node to
        # a broadcast DRAINING state, sheds new external queries with
        # 503 + X-Pilosa-Shed-Reason: draining, waits out in-flight work
        # and queue flushes, then lands a final snapshot per dirty
        # fragment so the restart replays no WAL.
        self.drain_timeout = drain_timeout
        self.draining = False
        self.drained = False
        self._drain_lock = threading.Lock()
        self._drain_thread: Optional[threading.Thread] = None
        self._drain_abort = threading.Event()
        self._drain_info: dict = {}
        # rejoin read fence: how long a fenced shard may wait for parity
        # verification before availability wins and the fence lifts loudly
        self.rejoin_fence_timeout = 120.0
        self._fence_thread: Optional[threading.Thread] = None
        self._fence_wake = threading.Event()
        self.api = API(self.holder, self.cluster, executor=self.executor,
                       translate_store=self.cluster_translate)
        # distributed query profiler knobs ([cluster] profile /
        # query-history-size; PILOSA_TPU_PROFILE=0 kill switch is read by
        # the API itself): mode gates when a QueryProfile is recorded, the
        # ring holds the /debug/query-history entries
        if profile_mode not in ("off", "auto", "on"):
            # a typo'd mode must fail the boot, not silently act as "auto"
            raise ValueError(
                f"invalid [cluster] profile mode {profile_mode!r} "
                "(expected off | auto | on)")
        self.api.profile_mode = profile_mode
        from pilosa_tpu.utils.profile import QueryHistory
        self.api.query_history = QueryHistory(query_history_size)
        # per-principal resource accounting (utils/accounting.py): the
        # bounded usage ledger every charge site in the stack attributes
        # into ([metric] usage-max-principals / usage-ring knobs;
        # PILOSA_TPU_ACCOUNTING=0 kill switch read per request), plus the
        # [slo] objectives evaluated with multi-window burn-rate math.
        from pilosa_tpu.utils import accounting as _accounting
        self.usage = _accounting.UsageLedger(
            max_principals=usage_max_principals, ring_size=usage_ring)
        self.api.usage_ledger = self.usage
        objectives = []
        if slo_availability_target > 0:
            objectives.append(_accounting.Objective(
                "availability", None, None, slo_availability_target))
        for cls, ms in (("read", slo_read_latency_ms),
                        ("count", slo_count_latency_ms),
                        ("topn", slo_topn_latency_ms),
                        ("groupby", slo_groupby_latency_ms)):
            if ms > 0:
                objectives.append(_accounting.Objective(
                    f"{cls}-latency", cls, ms, slo_latency_target))
        self.slo = _accounting.SLOTracker(
            objectives, short_window=slo_window_short,
            long_window=slo_window_long, burn_yellow=slo_burn_yellow,
            burn_red=slo_burn_red)
        self.api.slo = self.slo
        # external trace export ([metric] trace-export = off|file|http):
        # finished cross-node profile trees — and, when no [tracing]
        # endpoint claimed the recording tracer, its finished spans too —
        # ship as Jaeger/OTLP-JSON batches to a spool file or collector.
        # PILOSA_TPU_TRACE_EXPORT=0 is the kill switch (read per batch).
        if trace_export not in ("off", "file", "http"):
            raise ValueError(
                f"invalid [metric] trace-export {trace_export!r} "
                "(expected off | file | http)")
        self.trace_exporter = None
        if trace_export != "off":
            from pilosa_tpu.utils.tracing import TraceExporter
            spool = trace_export_path or os.path.join(
                data_dir, "trace-spool.jsonl")
            self.trace_exporter = TraceExporter(
                mode=trace_export, path=spool,
                endpoint=trace_export_endpoint, fmt=trace_export_format,
                sample=trace_export_sample)
            self.api.trace_exporter = self.trace_exporter
            if exporter is None:
                # the recording tracer ships its spans through the same
                # egress; sampling follows trace-export-sample unless the
                # operator configured an explicit [tracing] sampler
                self.tracer.exporter = self.trace_exporter
                if tracing_sampler_type == "off":
                    self.tracer.sampler_type = "probabilistic"
                    self.tracer.sampler_param = trace_export_sample
        # fleet telemetry (utils/telemetry.py): background sampler ->
        # bounded ring served at GET /debug/timeseries; [metric]
        # telemetry-interval / telemetry-ring knobs, PILOSA_TPU_TELEMETRY=0
        # kill switch. The federation + /status share node_health().
        from pilosa_tpu.utils.telemetry import TelemetrySampler
        if telemetry_ring < 1:
            raise ValueError(
                f"invalid [metric] telemetry-ring {telemetry_ring!r} "
                "(expected >= 1)")
        self.telemetry = TelemetrySampler(interval=telemetry_interval,
                                          ring_size=telemetry_ring,
                                          source=self.sample_gauges,
                                          logger=self.logger)
        self._telemetry_prev: tuple = (None, 0.0)
        self._last_hit_rate = 1.0  # carried through zero-lookup windows
        self._last_plan_hit_rate = 0.0  # plan cache starts cold
        self._last_ici_share = 0.0  # slice-local share of routed reads
        self._last_hybrid_share = 0.0  # sparse share of row-leaf uploads
        self._last_hybrid_run_share = 0.0  # run share of row-leaf uploads
        self.api.health_fn = self.node_health
        self.api.node_stats_fn = self.node_stats
        self.api.cluster_stats_fn = self.cluster_stats
        self.api.cluster_usage_fn = self.cluster_usage
        self.api.cluster_heat_fn = self.cluster_heat
        self.api.cluster_events_fn = self.cluster_events
        self.api.cluster_hbm_fn = self.cluster_hbm
        # last health score seen by the sampler: a change emits a
        # health.transition event onto the timeline
        self._last_health: Optional[str] = None
        # multi-tenant QoS plane (pilosa_tpu/qos.py): per-principal quota
        # buckets refilled against the usage ledger, priority classes the
        # batchers/pools order by, deadline-aware admission + shedding.
        # Built unconditionally (mode="off" = zero behavior change) so
        # the qos/* observability families always exist; QosPlane
        # validates mode/priority/overrides and fails the boot on typos.
        # PILOSA_TPU_QOS=0 is the env kill switch over any mode.
        from pilosa_tpu.qos import QosPlane
        self.qos = QosPlane(
            mode=qos_mode, default_priority=qos_default_priority,
            default_deadline=qos_default_deadline,
            queries_per_s=qos_queries_per_s,
            device_ms_per_s=qos_device_ms_per_s,
            bytes_per_s=qos_bytes_per_s, burst_s=qos_burst,
            max_principals=qos_max_principals, principals=qos_principals,
            executor=self.executor, ledger=self.usage,
            health_fn=self.node_health, logger=self.logger)
        # shed-storm onset/end + quota-debt events ride the journal
        self.qos.journal = self.events
        self.api.qos_plane = self.qos
        self.api.drain_fn = self.request_drain
        self.api.drain_status_fn = self.drain_status
        self.api.node_state_fn = (
            lambda: "DRAINING" if self.draining else "READY")
        self.handler = Handler(self.api, cluster_message_fn=self.receive_message,
                               stats=self.stats, query_timeout=query_timeout,
                               telemetry=self.telemetry, qos_plane=self.qos,
                               events=self.events)
        self.http = HTTPServer(self.handler, host=host, port=port,
                               tls_certificate=tls_certificate, tls_key=tls_key)
        self._bind_host = host
        self.cluster_hosts = cluster_hosts or []
        self.long_query_time = long_query_time
        self.max_writes_per_request = max_writes_per_request
        self.anti_entropy_interval = anti_entropy_interval
        # scrubber tuning (docs/operations.md "Failure modes and
        # recovery"): jitter de-synchronizes the nodes' scrub passes (a
        # cluster whose replicas all scrub at the same instant doubles its
        # own fan-out load spike); pace sleeps between per-fragment scrubs
        # so a pass never starves the query fan-out pool; max_blocks
        # bounds the blocks merged per fragment per pass (0 = unbounded)
        if not 0.0 <= anti_entropy_jitter < 1.0:
            # a FRACTION of the interval, not seconds — jitter >= 1 would
            # sample negative intervals, i.e. a continuous scrub storm
            raise ValueError(
                f"invalid [anti-entropy] jitter {anti_entropy_jitter!r} "
                "(a fraction: expected 0 <= jitter < 1)")
        if anti_entropy_pace < 0 or anti_entropy_max_blocks < 0:
            raise ValueError("[anti-entropy] pace and max-blocks must be >= 0")
        self.anti_entropy_jitter = anti_entropy_jitter
        self.anti_entropy_pace = anti_entropy_pace
        self.anti_entropy_max_blocks = anti_entropy_max_blocks
        self._scrub_passes = 0
        self.cache_flush_interval = cache_flush_interval
        self._cache_flush_timer: Optional[threading.Timer] = None
        self.membership_interval = membership_interval
        # liveness probing (the memberlist probe/suspicion analog,
        # gossip/gossip.go:488-519): after `liveness_threshold` consecutive
        # failed /status probes a peer is marked down and routed around
        self.liveness_threshold = liveness_threshold
        self.probe_timeout = probe_timeout
        self._probe_failures: dict[str, int] = {}
        # consecutive successful probes of a DOWN node (anti-flap: one
        # lucky probe must not flip a struggling peer back into placement
        # only to flap out again next tick)
        self._probe_successes: dict[str, int] = {}
        # successes required to revive a down node (memberlist-style
        # hysteresis; 1 = the old instant-revive behavior)
        self.revive_threshold = 2
        # peers asked to confirm a suspected-dead node before we mark it
        # down (memberlist indirect ping fan-out)
        self.indirect_probes = 2
        # node ids with an in-flight return-heal (single-flight per node)
        self._return_sync_running: set[str] = set()
        # optional SWIM gossip failure detector (gossip/gossip.go:42-541):
        # gossip_port switches liveness from the HTTP probe loop to UDP
        # probe/ack + suspicion + refutation; both drive the same
        # mark_down/mark_up hooks. 0 = bind an ephemeral port.
        self.gossip = None
        self._gossip_port = gossip_port
        self._gossip_seeds = gossip_seeds or []
        self._gossip_config = gossip_config
        # [gossip] secret: non-empty -> every gossip datagram is AES-GCM
        # encrypted under a key derived from the shared passphrase
        # (parallel/gossip.py; utils/aesgcm.py)
        self._gossip_secret = gossip_secret
        # join=True: this node is being added to an existing cluster —
        # cluster_hosts are seed URIs (the gossip-seeds analog). It announces
        # itself and stays STARTING until the coordinator's resize completes
        # and a topology broadcast admits it (nodeJoin, cluster.go:1715).
        self.join = join
        self._ae_timer: Optional[threading.Timer] = None
        self._member_timer: Optional[threading.Timer] = None
        # coordinator-side queue of membership events that arrived while a
        # resize was already running (listenForJoins, cluster.go:1095-1148)
        self._pending_resizes: list[tuple[str, Node]] = []
        self._resize_lock = threading.Lock()
        # tombstones: ids removed by resize. Without these the additive
        # membership merge would resurrect a removed-but-still-running node
        # (the memberlist leave-event analog for static clusters).
        self._removed_ids: set[str] = set()
        self._left = False  # this node itself was removed from the cluster
        # a lost resize-complete ack must not wedge the cluster in RESIZING
        # forever: the coordinator aborts the job after resize_timeout
        self.resize_timeout = resize_timeout
        self._resize_watchdog: Optional[threading.Timer] = None
        # async broadcast plane (SendAsync, broadcast.go:30-36): writes
        # announce shards through a queue drained off the request thread,
        # so a slow/hung peer never adds latency to Set()/imports
        import queue as _queue
        self._bcast_queue: "_queue.Queue" = _queue.Queue()
        self._bcast_thread: Optional[threading.Thread] = None
        self._bcast_dropped = 0  # per-peer queue overflow drops (AE heals)
        self.closed = False

    # -- lifecycle (server.go Open, §3.1) -----------------------------------

    def _load_or_create_id(self) -> str:
        """Persistent node id (.id file, holder.go:576)."""
        os.makedirs(self.data_dir, exist_ok=True)
        path = os.path.join(self.data_dir, ".id")
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        node_id = str(uuid.uuid4())
        with open(path, "w") as f:
            f.write(node_id)
        return node_id

    def _schema_shards(self) -> dict:
        """{index: {field: [shards]}} from the cluster-wide available-shards
        bitmaps (broadcast-synced), NOT local fragments — a shard that was
        migrated away must still be planned over on the next resize."""
        out: dict = {}
        for iname, idx in self.holder.indexes.items():
            for fname, field in idx.fields.items():
                out.setdefault(iname, {})[fname] = [
                    int(s) for s in field.available_shards.slice()]
        return out

    def open(self) -> "Server":
        self.translate.open()
        self.holder.open()
        for d in self.holder.damaged_fragments():
            # recovery happened inside Fragment.open; make it LOUD for the
            # operator (also surfaced in /debug/vars damagedFragments and
            # on the flight-recorder timeline)
            frag_key = (f"{d['index']}/{d['field']}/{d['view']}"
                        f"/{d['shard']}")
            if d["quarantinePath"]:
                self.logger.errorf(
                    "storage: fragment %s failed its integrity "
                    "check (%s): quarantined to %s, reopened empty — the "
                    "scrubber will rebuild it from a replica",
                    frag_key, d["corruptionError"], d["quarantinePath"])
                self.events.emit("snapshot.quarantined", fragment=frag_key,
                                 error=str(d["corruptionError"])[:200],
                                 quarantinePath=d["quarantinePath"])
            if d["walTruncatedBytes"]:
                self.logger.warnf(
                    "storage: fragment %s had a torn WAL tail "
                    "(%s): truncated %d un-acked bytes",
                    frag_key, d["walTruncateError"],
                    d["walTruncatedBytes"])
                self.events.emit("wal.truncated", fragment=frag_key,
                                 bytes=int(d["walTruncatedBytes"]))
        self.holder.set_shard_hook(self._on_shard_added)
        self.http.serve_background()
        me = Node(id=self.node_id, uri=self.http.uri,
                  is_coordinator=not self.cluster_hosts)
        if self.join and self.cluster_hosts:
            # dynamic member: knock on the seeds and wait in STARTING for the
            # coordinator's resize + topology broadcast to admit us
            self.cluster.nodes = [me]
            self.request_join()
            if self.membership_interval > 0:
                self._schedule_membership_refresh()
        elif not self.cluster_hosts:
            self.cluster.set_static([me])
            self.cluster.coordinator_id = self.node_id
        else:
            # static multi-node (all hosts known up front; nodes ordered by
            # id). Peers may not be up yet: start with self, converge via
            # refresh_membership once peers answer /internal/nodes.
            self.cluster.set_static([me])
            self.refresh_membership()
            # peers may come up later: keep refreshing until everyone answers
            # (the gossip-convergence analog for static clusters)
            if self.membership_interval > 0:
                self._schedule_membership_refresh()
        self.api.broadcast_fn = self.broadcast
        # shard-CREATING Set writes announce before the ack
        # (read-your-writes through any node; see executor.py) — bulk
        # imports keep the async _on_shard_added queue
        self.executor.announce_shard_fn = self._announce_shard_bounded
        self.api.resize_fn = self._resize_request
        self.api.abort_fn = self._abort_request
        self.api.forward_import_fn = self.client.import_bits
        self.api.forward_roaring_fn = (
            lambda uri, index, field, shard, views, clear:
            self.client.import_roaring(uri, index, field, shard, views,
                                       clear=clear, remote=True))
        self.api.long_query_time = self.long_query_time
        self.api.max_writes_per_request = self.max_writes_per_request
        self.api.logger = self.logger
        self.api.probe_peer_fn = (
            lambda target_uri: bool(
                self.client.status(target_uri, timeout=self.probe_timeout)))
        if self._gossip_port is not None:
            self._open_gossip()
        if self.anti_entropy_interval > 0:
            self._schedule_anti_entropy()
        if self.cache_flush_interval > 0:
            self._schedule_cache_flush()
        self._bcast_thread = _threads.spawn(self._bcast_worker,
                                            name="pilosa-bcast")
        self.runtime_monitor.start()
        self.diagnostics.start()
        # route recompile-storm warnings into the server log (process-
        # global counters: the first server's logger wins, later in-process
        # servers — a test pattern — keep it)
        from pilosa_tpu.utils import telemetry as _telemetry
        if _telemetry.xla.log_fn is None:
            _telemetry.xla.log_fn = self.logger.printf
        if _telemetry.xla.event_fn is None:
            # recompile storms land on the flight-recorder timeline too
            # (process-global counters: first server's journal wins,
            # exactly like log_fn)
            _telemetry.xla.event_fn = self._xla_storm_event
        self.telemetry.start()
        # rejoin protocol (docs/operations.md "Rolling restarts and
        # drains"): (1) read-fence local fragments that may have missed
        # writes while this process was away, until parity with a replica
        # is verified; (2) announce the return so peers clear our
        # DRAINING/down mark and replay queued hints immediately instead
        # of waiting a probe cycle.
        self._arm_read_fence()
        self.events.emit("node.start", uri=self.http.uri,
                         cluster=bool(self.cluster_hosts))
        if self.cluster_hosts and not self.join:
            self.broadcast({"type": "node-state", "id": self.node_id,
                            "state": "READY"})
        return self

    def _schedule_membership_refresh(self) -> None:
        if self.closed:
            return
        self._member_timer = _threads.ctx_timer(self.membership_interval,
                                                self._membership_tick)
        self._member_timer.start()

    def _membership_tick(self) -> None:
        try:
            if self.join and self.cluster.state == STATE_STARTING \
                    and not self.cluster.down_ids:
                # keep knocking until admitted — but only when STARTING
                # means "not yet joined"; liveness-induced STARTING (peers
                # down >= ReplicaN) must fall through so probing can detect
                # their return and mark them back up
                self.request_join()
            else:
                # fetch over the network WITHOUT the lock, then apply the
                # merge under it so it cannot interleave with a join/leave
                # job flipping state (set_static would un-gate writes
                # mid-resize and orphan the active job)
                reports = self._fetch_peer_nodes()
                if reports is not None:
                    with self._resize_lock:
                        if self.cluster.state != STATE_RESIZING \
                                and self.cluster.active_job is None:
                            self._apply_membership(reports)
                if self.gossip is None:
                    # otherwise gossip is the failure detector; the HTTP
                    # probe loop would fight its suspicion timing
                    self._probe_peers()
            # hinted-handoff retry: a replay that failed mid-stream (the
            # target flapped, an injected fault) keeps its log; if the
            # target is alive NOW, re-run the return-heal rather than
            # waiting for another down/up transition that may never come
            self._retry_pending_hints()
        finally:
            self._schedule_membership_refresh()

    # -- SWIM gossip failure detector (optional backend) --------------------

    def _open_gossip(self) -> None:
        """Start the UDP gossip endpoint and join the seeds. The node's
        HTTP URI rides the alive record's meta (the NodeMeta channel the
        reference uses for the same purpose, gossip/gossip.go:248-257), so
        peers discovered purely by gossip can be admitted to membership."""
        from pilosa_tpu.parallel.gossip import Gossip, parse_seed
        from pilosa_tpu.utils.aesgcm import derive_key
        self.gossip = Gossip(self.node_id, bind_host=self._bind_host,
                             bind_port=self._gossip_port,
                             meta={"uri": self.http.uri},
                             config=self._gossip_config,
                             on_alive=self._on_gossip_alive,
                             on_dead=self._on_gossip_dead,
                             secret_key=(derive_key(self._gossip_secret)
                                         if self._gossip_secret else None),
                             logger=self.logger)
        # gossip datagrams piggyback the flight-recorder HLC (the UDP
        # twin of the HTTP plane's X-Pilosa-HLC header)
        self.gossip.clock = self.clock
        self.gossip.open(seeds=[parse_seed(s) for s in self._gossip_seeds])
        self.logger.printf("gossip: listening on %s:%d (seeds: %s)",
                           self.gossip.host, self.gossip.port,
                           ",".join(self._gossip_seeds) or "none")

    def _on_gossip_dead(self, member) -> None:
        """Gossip declared a peer dead (suspicion expired un-refuted):
        the NodeLeave -> route-around path (cluster.go:1690-1703)."""
        if self.closed or member.id == self.node_id:
            return
        if any(n.id == member.id for n in self.cluster.nodes) \
                and not self.cluster.is_down(member.id):
            self.logger.printf("gossip: node %s dead (suspicion expired), "
                               "marking down", member.id)
            self.cluster.mark_down(member.id)
            self.stats.count("liveness/node_down")
            self.events.emit("peer.down", peer=member.id,
                             detector="gossip")

    def _on_gossip_alive(self, member) -> None:
        """A peer (re)entered alive state: revive it if it was down, or
        admit a gossip-discovered node to membership (NotifyJoin,
        gossip/gossip.go:335-342)."""
        if self.closed or member.id == self.node_id:
            return
        node = next((n for n in self.cluster.nodes if n.id == member.id),
                    None)
        if node is None:
            uri = member.meta.get("uri")
            if uri and member.id not in self._removed_ids:
                with self._resize_lock:
                    if self.cluster.state != STATE_RESIZING \
                            and self.cluster.active_job is None:
                        self._apply_membership([{"id": member.id,
                                                 "uri": uri}])
        elif self.cluster.is_down(member.id):
            self.logger.printf("gossip: node %s back up", member.id)
            self.cluster.mark_up(member.id)
            self.events.emit("peer.up", peer=member.id,
                             detector="gossip")
            self._on_node_return(node)

    def refresh_membership(self) -> None:
        """Merge peer node lists from all configured hosts (the static-mode
        analog of a gossip LocalState/MergeRemoteState sync,
        gossip/gossip.go:274-316)."""
        reports = self._fetch_peer_nodes()
        if reports is None:
            return
        self._apply_membership(reports)

    def _fetch_peer_nodes(self) -> Optional[list[dict]]:
        """Network half of refresh_membership: peer reports, no locks, no
        cluster mutation (safe to run outside _resize_lock)."""
        if not self.cluster_hosts or self._left:
            return None
        reports: list[dict] = []
        for huri in self.cluster_hosts:
            if huri == self.http.uri:
                continue
            try:
                # short timeout: a SIGSTOP'd/hung seed must not stall the
                # membership tick for the client's default 30s — liveness
                # probing downstream of this fetch depends on ticks firing
                reports.extend(
                    self.client.nodes(huri, timeout=self.probe_timeout) or [])
            except ClientError:
                pass
        return reports

    def _apply_membership(self, reports: list[dict]) -> None:
        me = Node(id=self.node_id, uri=self.http.uri)
        # seed with current membership: nodes admitted dynamically (topology
        # broadcasts) stay known even when a seed host is briefly down
        nodes = {n.id: n for n in self.cluster.nodes
                 if n.id not in self._removed_ids}
        nodes[self.node_id] = me
        for nd in reports:
            if nd["id"] not in nodes and nd["id"] not in self._removed_ids:
                nodes[nd["id"]] = Node.from_dict(nd)
        self.cluster.set_static(list(nodes.values()))
        # sticky explicit coordinator; lowest node id otherwise
        self.cluster.elect_coordinator()

    def _probe_peers(self) -> None:
        """Liveness detection: probe every known peer's /status each
        membership tick. `liveness_threshold` consecutive failures mark the
        node down (memberlist probe -> suspicion -> NodeLeave,
        gossip/gossip.go:488-519); placement then routes around it and the
        cluster state recomputes (DEGRADED / STARTING, cluster.go:522-533).
        A later successful probe marks it back up — the reference treats
        this as 'temporarily unavailable... expect it to come back up'
        (cluster.go:1694-1696)."""
        if self._left or self.closed:
            return
        peers = [n for n in list(self.cluster.nodes)
                 if n.id != self.node_id and n.uri]
        # drop counters for nodes no longer in membership, so a node that
        # is removed and later re-added starts from a clean slate
        peer_ids = {n.id for n in peers}
        for stale in set(self._probe_failures) - peer_ids:
            del self._probe_failures[stale]
        for stale in set(self._probe_successes) - peer_ids:
            del self._probe_successes[stale]
        if not peers:
            return

        # probe concurrently: N down peers must cost one probe_timeout per
        # tick, not N of them (the membership timer is a single thread)
        claims: dict[str, str] = {}  # live peer -> its coordinator claim
        node_states: dict[str, str] = {}  # live peer -> its nodeState

        def probe(node):
            try:
                st = self.client.status(node.uri, timeout=self.probe_timeout)
                claim = st.get("coordinatorID")
                if claim:
                    claims[node.id] = claim
                node_states[node.id] = st.get("nodeState", "")
                return True
            except Exception:  # noqa: BLE001 — ANY probe failure means
                # not-alive (ClientError, socket teardown mid-close, ...);
                # an escaping exception would kill the probe thread and
                # count as dead anyway, minus the noise
                return False

        results: dict[str, bool] = {}
        threads = []
        for node in peers:
            threads.append(_threads.spawn(
                lambda n=node: results.__setitem__(n.id, probe(n))))
        for t in threads:
            t.join(self.probe_timeout + 1.0)
        suspects: list = []
        for node in peers:
            alive = results.get(node.id, False)
            if alive:
                self._probe_failures.pop(node.id, None)
                if self.cluster.is_down(node.id):
                    # anti-flap hysteresis: a down node needs
                    # revive_threshold CONSECUTIVE good probes before it
                    # re-enters placement (memberlist's suspicion decay —
                    # one lucky probe of a struggling peer must not flap
                    # it up only to fall out again next tick)
                    ok = self._probe_successes.get(node.id, 0) + 1
                    if ok < self.revive_threshold:
                        self._probe_successes[node.id] = ok
                        continue
                    self._probe_successes.pop(node.id, None)
                    self.logger.printf("liveness: node %s (%s) back up",
                                       node.id, node.uri)
                    self.cluster.mark_up(node.id)
                    self.events.emit("peer.up", peer=node.id,
                                     detector="probe")
                    self._on_node_return(node)
                elif self.cluster.is_draining(node.id) \
                        and node_states.get(node.id) == "READY":
                    # the drained peer restarted and we missed its rejoin
                    # broadcast: its own /status says READY — clear the
                    # mark and run the return-heal (hint replay first)
                    self.logger.printf(
                        "drain: peer %s back from drain (probe)", node.id)
                    self.cluster.clear_draining(node.id)
                    self._on_node_return(node)
            else:
                self._probe_successes.pop(node.id, None)
                n = self._probe_failures.get(node.id, 0) + 1
                self._probe_failures[node.id] = n
                if (n >= self.liveness_threshold
                        and not self.cluster.is_down(node.id)):
                    suspects.append(node)
        # coordinator convergence: adopt the claim of the lowest-id LIVE
        # node (the deterministic electoral authority — its own claim is
        # sticky via elect_coordinator), so an explicit set-coordinator
        # reaches nodes that missed the broadcast within one probe tick
        live_ids = {self.node_id} | {n.id for n in peers
                                     if results.get(n.id)}
        authority = min(live_ids)
        if authority != self.node_id:
            claim = claims.get(authority)
            if claim and self.cluster.node_by_id(claim) is not None:
                self.cluster.adopt_coordinator(claim)
        if not suspects:
            return
        # SUSPECT phase: before declaring a peer dead, ask other live
        # peers to probe it for us (memberlist indirect ping) — a broken
        # link between us and the peer must not evict a node the rest of
        # the cluster can reach. All suspects are checked concurrently
        # (same rule as the direct probes: N suspects must not serialize
        # N timeouts on the membership-tick thread).
        refuted: dict[str, bool] = {}
        checkers = []
        for node in suspects:
            checkers.append(_threads.spawn(
                lambda nd=node: refuted.__setitem__(
                    nd.id,
                    self._indirect_confirms_alive(nd, peers, results))))
        deadline = 3 * self.probe_timeout + 3.0
        for t in checkers:
            t.join(deadline)
        for node in suspects:
            if refuted.get(node.id):
                self.logger.printf(
                    "liveness: node %s (%s) suspected after %d failed "
                    "probes but refuted by indirect probe (link problem, "
                    "not node death)", node.id, node.uri,
                    self._probe_failures.get(node.id, 0))
                self._probe_failures.pop(node.id, None)
                self.stats.count("liveness/suspect_refuted")
                continue
            self.logger.printf(
                "liveness: node %s (%s) failed %d probes, marking "
                "down (cluster -> %s)", node.id, node.uri,
                self._probe_failures.get(node.id, 0),
                "DEGRADED" if len(self.cluster.down_ids) + 1
                < self.cluster.replica_n else "STARTING")
            self.cluster.mark_down(node.id)
            self.stats.count("liveness/node_down")
            self.events.emit("peer.down", peer=node.id, detector="probe",
                             failedProbes=self._probe_failures.get(
                                 node.id, 0))

    def _indirect_confirms_alive(self, target, peers, results) -> bool:
        """Ask up to `indirect_probes` live peers whether THEY can reach
        the suspected node (gossip/gossip.go probe path). True if any
        vouches for it. Helpers are asked CONCURRENTLY (same rule as the
        direct probes: N suspects must not serialize N timeouts on the
        membership-tick thread), and the outer RPC deadline leaves room
        for the helper's own nested probe_timeout — a genuine vouch for a
        slow-but-alive node must not be discarded by our socket closing
        first."""
        helpers = [p for p in peers
                   if p.id != target.id and results.get(p.id)
                   and not self.cluster.is_down(p.id)][:self.indirect_probes]
        if not helpers:
            return False
        outer_timeout = 2 * self.probe_timeout + 1.0
        vouched = threading.Event()  # set by the FIRST positive vote
        done = threading.Event()  # set when every helper has answered
        votes: dict[str, bool] = {}

        def ask(helper):
            try:
                votes[helper.id] = self.client.probe_indirect(
                    helper.uri, target.uri, timeout=outer_timeout)
            except Exception:  # noqa: BLE001 — helper unreachable: no vote
                votes[helper.id] = False
            if votes[helper.id]:
                vouched.set()
            if len(votes) == len(helpers):
                done.set()

        for h in helpers:
            _threads.spawn(ask, h)
        # one vouch settles it — don't hold the membership tick hostage to
        # the slowest helper's full timeout (a recurring-suspect peer would
        # stall liveness detection for every OTHER peer each round)
        deadline = time.monotonic() + outer_timeout + 1.0
        while time.monotonic() < deadline:
            if vouched.wait(0.05) or done.is_set():
                break
        return vouched.is_set() or any(votes.values())

    def _on_node_return(self, node) -> None:
        """Heal a peer that was probe-marked down and came back: broadcasts
        skipped it while down, so (a) the coordinator re-pushes schema DDL +
        available shards it may have missed, and (b) this node runs one
        anti-entropy pass — even when the periodic ticker is disabled — so
        writes acked during the outage reach the returning replica (the
        reference's returning memberlist node gets the cluster status on
        re-join, cluster.go:1755-1765, and heals via anti-entropy).

        Every observer pushes (not just the coordinator — the down node may
        BE the coordinator); the sync applies via create-if-not-exists, so
        duplicate pushes are idempotent. Missed delete-index/delete-field
        broadcasts are NOT replayed — the returning node keeps the deleted
        schema objects, matching the reference (a memberlist node that was
        partitioned through a DeleteIndex keeps it too; holder.go has no
        delete reconciliation) — but stale fragments are never pushed back
        to peers (the peer's 404 distinguishes missing-fragment from
        missing-field, _sync_fragment).

        The entire heal runs on a background thread (the probe tick must
        never block on the returning node), is single-flight PER RETURNING
        NODE (two nodes returning together each get their own heal), and
        syncs only the shards this node co-owns with the returner — not a
        full cluster-wide pass per observer."""
        if node.id in self._return_sync_running:
            return
        self._return_sync_running.add(node.id)

        def heal():
            try:
                try:
                    self.client.send_message(node.uri, {
                        "type": "schema-sync",
                        "schema": self.holder.schema(),
                        "availableShards": {
                            iname: {fname: [int(s)
                                            for s in f.available_shards.slice()]
                                    for fname, f in idx.fields.items()}
                            for iname, idx in self.holder.indexes.items()},
                    })
                except ClientError as e:
                    self.logger.printf(
                        "liveness: schema re-sync to %s failed: %s",
                        node.id, e)
                if self.cluster._explicit_claim:
                    # the returning node missed the set-coordinator
                    # broadcast while down — re-push the explicit CLAIM
                    # (heals the gossip backend too, where the probe-tick
                    # claim convergence does not run; the receiver keeps it
                    # pending until it knows the claimed node)
                    try:
                        self.client.send_message(node.uri, {
                            "type": "set-coordinator",
                            "id": self.cluster._explicit_claim})
                    except ClientError as e:
                        self.logger.printf(
                            "liveness: coordinator re-push to %s failed: %s",
                            node.id, e)
                # durable hinted handoff first: writes skipped while the
                # node was away stream back in order (idempotent apply).
                # The O(blocks) anti-entropy sync runs ONLY when hints
                # were dropped (byte/age caps, torn log) — a clean replay
                # IS the heal, no scrub pass required.
                complete = True
                try:
                    _r, _d, complete = self.replay_hints(node)
                except Exception as e:  # noqa: BLE001 — replay failure
                    # falls back to the full sync below
                    complete = False
                    self.logger.printf(
                        "hints: replay to %s failed: %s", node.id, e)
                try:
                    if not complete:
                        self._sync_with_node(node.id)
                except Exception as e:  # noqa: BLE001 — best-effort healing
                    self.logger.printf(
                        "liveness: post-return sync failed: %s", e)
                # tell the returning node its hints are in, so its rejoin
                # read fence verifies and lifts now, not at the next poll
                try:
                    self.client.send_message(node.uri, {
                        "type": "hints-replayed", "target": node.id,
                        "from": self.node_id, "complete": complete})
                except ClientError:
                    pass
            finally:
                self._return_sync_running.discard(node.id)

        _threads.spawn(heal)

    def _sync_with_node(self, node_id: str) -> int:
        """One anti-entropy pass scoped to fragments co-owned with one peer
        (the returning-node heal: full sync_holder per observer would be an
        O(N^2) RPC storm per return event)."""
        merged = 0
        for iname, idx in self.holder.indexes.items():
            for fname, field in idx.fields.items():
                for vname, view in field.views.items():
                    for shard in view.shards():
                        owners = {n.id for n in
                                  self.cluster.shard_nodes(iname, shard)}
                        if self.node_id in owners and node_id in owners:
                            merged += self._sync_fragment(
                                iname, fname, vname, shard)
        return merged

    # -- graceful drain + rejoin (docs/operations.md "Rolling restarts") ----

    def _handle_node_state(self, msg: dict) -> None:
        """A peer's lifecycle announcement: DRAINING routes around it
        immediately (no probe-timeout wait); READY is the rejoin — clear
        its marks and run the return-heal (hint replay first, anti-entropy
        only if hints were dropped)."""
        nid = msg.get("id")
        state = msg.get("state")
        if not nid or nid == self.node_id:
            return
        node = self.cluster.node_by_id(nid)
        if state == "DRAINING":
            if node is not None and not self.cluster.is_draining(nid):
                self.logger.printf(
                    "drain: peer %s is draining — routing around it", nid)
                self.cluster.mark_draining(nid)
                self.stats.count("drain/peerDraining")
                self.events.emit("peer.draining", peer=nid)
        elif state == "READY":
            was_away = (self.cluster.is_down(nid)
                        or self.cluster.is_draining(nid))
            self.cluster.mark_up(nid)
            self.cluster.clear_draining(nid)
            self._probe_failures.pop(nid, None)
            self._probe_successes.pop(nid, None)
            if was_away and node is not None:
                self.logger.printf(
                    "drain: peer %s rejoined — replaying hints", nid)
                self.events.emit("peer.rejoined", peer=nid)
                self._on_node_return(node)

    def request_drain(self, abort: bool = False,
                      timeout: Optional[float] = None) -> dict:
        """API hook for POST /cluster/drain (and the CLI's SIGTERM path):
        start the drain on a background thread — the endpoint answers
        immediately with the status document; operators poll /status
        (nodeState) for completion. abort=True cancels an in-progress
        drain and re-announces READY."""
        if abort:
            self.abort_drain()
            return self.drain_status()
        with self._drain_lock:
            if self._drain_thread is None or not self._drain_thread.is_alive():
                self._drain_abort.clear()
                self._drain_thread = _threads.spawn(
                    self.drain, timeout, name="pilosa-drain")
        return self.drain_status()

    def abort_drain(self) -> None:
        """Cancel a drain: stop shedding, re-announce READY so peers
        restore routing (an operator's change of heart must not leave the
        node half-out of the cluster)."""
        if not self.draining:
            return
        self._drain_abort.set()
        self.draining = False
        self.handler.draining = False
        me = self.cluster.local_node
        if me is not None and me.state == "DRAINING":
            me.state = "READY"
        self.logger.printf("drain: aborted — resuming service")
        self.events.emit("drain.abort")
        self.broadcast({"type": "node-state", "id": self.node_id,
                        "state": "READY"})

    def _drain_wait(self, cond, deadline: Optional[float]) -> bool:
        while not cond():
            if self._drain_abort.is_set() or self.closed:
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    def drain(self, timeout: Optional[float] = None) -> dict:
        """The graceful-drain sequence, run to completion (synchronous;
        request_drain wraps it in a thread):

          1. shed — new external queries get 503 + Retry-After +
             X-Pilosa-Shed-Reason: draining; internal RPCs (replica
             writes, fragment retrieval, stats, hint replay) keep working
          2. announce — peers mark this node DRAINING and route/hedge/
             coalesce around it immediately
          3. settle — in-flight external queries finish, then the device
             batchers and the network coalescer flush their queues
          4. persist — rank caches flush, every fragment with pending WAL
             ops (or volatile bulk loads) lands a final snapshot, so the
             restarted process replays nothing
        The node then reports nodeState=DRAINING until the process exits
        (the operator's signal to proceed with the restart)."""
        timeout = self.drain_timeout if timeout is None else timeout
        t0 = time.monotonic()
        deadline = t0 + timeout if timeout and timeout > 0 else None
        first = not self.draining
        if first:
            self.draining = True
            self.handler.draining = True
            me = self.cluster.local_node
            if me is not None:
                me.state = "DRAINING"
            self.stats.count("drain/started")
            self.logger.printf(
                "drain: shedding new external queries (timeout %.1fs)",
                timeout)
            self.events.emit("drain.start", timeoutSeconds=timeout)
            self.broadcast({"type": "node-state", "id": self.node_id,
                            "state": "DRAINING"})
        inflight_ok = self._drain_wait(
            lambda: self.handler.active_queries == 0, deadline)

        def queues_empty() -> bool:
            depth = 0
            for attr in ("batcher", "sum_batcher", "minmax_batcher",
                         "coalescer"):
                b = getattr(self.executor, attr, None)
                if b is not None:
                    depth += b.queue_depth()
            return depth == 0

        flushed_ok = self._drain_wait(queues_empty, deadline)
        if self._drain_abort.is_set():
            return self.drain_status()
        snapshotted = 0
        snapshot_errors = 0
        try:
            self.holder.flush_caches()
        except Exception as e:  # noqa: BLE001 — caches are rebuildable
            self.logger.printf("drain: cache flush failed: %s", e)
        for iname, fname, vname, shard, frag in \
                list(self.holder.walk_fragments()):
            dirty = (int(getattr(frag.storage, "op_n", 0) or 0) > 0
                     or getattr(frag, "_volatile", False))
            if not dirty:
                continue
            try:
                frag.snapshot()
                snapshotted += 1
            except (OSError, ValueError) as e:
                snapshot_errors += 1
                self.logger.printf(
                    "drain: final snapshot of %s/%s/%s/%d failed: %s",
                    iname, fname, vname, shard, e)
        self.drained = True
        self._drain_info = {
            "inflightDrained": inflight_ok,
            "queuesFlushed": flushed_ok,
            "snapshotted": snapshotted,
            "snapshotErrors": snapshot_errors,
            "durationSeconds": round(time.monotonic() - t0, 3),
        }
        self.stats.count("drain/completed")
        self.logger.printf(
            "drain: complete in %.2fs (inflight=%s queues=%s snapshots=%d)"
            " — safe to stop the process",
            self._drain_info["durationSeconds"], inflight_ok, flushed_ok,
            snapshotted)
        self.events.emit("drain.complete", snapshotted=snapshotted,
                         snapshotErrors=snapshot_errors,
                         durationSeconds=self._drain_info[
                             "durationSeconds"])
        return self.drain_status()

    def drain_status(self) -> dict:
        """The drain/* observability block (/debug/vars, /cluster/drain
        responses, unconditional /metrics gauges)."""
        out = {
            "draining": self.draining,
            "drained": self.drained,
            "shedQueries": self.handler.drain_sheds,
            "activeQueries": self.handler.active_queries,
            "timeoutSeconds": self.drain_timeout,
        }
        out.update(self._drain_info)
        return out

    # -- read-fenced rejoin --------------------------------------------------

    def _arm_read_fence(self) -> None:
        """Fence every local fragment's (index, shard) at startup when
        this node is (re)joining a multi-node cluster: the fragments may
        have missed writes while the process was away, and a fenced read
        routes to a peer replica until block checksums confirm parity
        (or a scrub heals the divergence). Single-node clusters and empty
        data dirs have nothing to fence."""
        if not self.cluster_hosts and not self.join:
            return
        keys = {(iname, shard) for iname, _f, _v, shard, _frag
                in self.holder.walk_fragments()}
        if not keys:
            return
        n = self.executor.fence_reads(keys)
        if not n:
            return
        self.stats.count("readFence/fenced", n)
        self.logger.printf(
            "rejoin: read-fenced %d shard(s) pending parity verification "
            "(reads route to replicas until hints replay or a checksum "
            "scrub confirms)", n)
        self.events.emit("fence.armed", shards=n)
        self._start_fence_worker()

    def _start_fence_worker(self) -> None:
        self._fence_wake.set()
        t = self._fence_thread
        if t is not None and t.is_alive():
            return
        self._fence_thread = _threads.spawn(self._fence_worker,
                                            name="pilosa-fence")

    def _fence_worker(self) -> None:
        deadline = time.monotonic() + self.rejoin_fence_timeout
        while not self.closed and self.executor.read_fence:
            try:
                self._verify_fence_pass()
            except Exception as e:  # noqa: BLE001 — a verify failure
                # (peer mid-restart, transient RPC) retries next tick
                self.logger.printf("rejoin: fence verify pass failed: %s", e)
            if not self.executor.read_fence:
                break
            if time.monotonic() >= deadline:
                # availability wins over an unverifiable fence (e.g. every
                # replica stayed down): lift it LOUDLY — the anti-entropy
                # scrubber remains the backstop for any real divergence
                with self.executor._fence_lock:
                    n = len(self.executor.read_fence)
                    self.executor.read_fence.clear()
                self.stats.count("readFence/expired", n)
                self.logger.warnf(
                    "rejoin: fence expired after %.0fs with %d shard(s) "
                    "unverified — serving local data; anti-entropy will "
                    "heal any divergence", self.rejoin_fence_timeout, n)
                self.events.emit("fence.expired", shards=n,
                                 timeoutSeconds=self.rejoin_fence_timeout)
                break
            self._fence_wake.wait(0.25)
            self._fence_wake.clear()

    def _verify_fence_pass(self) -> int:
        """One pass over fenced shards: compare every local fragment's
        block checksums with a live replica — parity lifts the fence;
        divergence runs the block-majority scrub for that fragment first
        (the 'block-checksum-verified scrub' of the rejoin contract).
        Shards with no reachable replica stay fenced for the next pass."""
        lifted = 0
        fence = sorted(self.executor.read_fence)
        for iname, shard in fence:
            idx = self.holder.index(iname)
            if idx is None:
                self.executor.unfence_reads((iname, shard))
                lifted += 1
                continue
            owners = self.cluster.shard_nodes(iname, shard)
            # a draining peer still serves verification reads; only
            # probe-dead peers are unusable
            peers = [n for n in owners
                     if n.id != self.node_id and n.uri
                     and not self.cluster.is_down(n.id)]
            if not peers:
                if len(owners) <= 1 or all(n.id == self.node_id
                                           for n in owners):
                    # no replica configured for this shard: nothing to
                    # verify against, and nobody else can serve it
                    self.executor.unfence_reads((iname, shard))
                    lifted += 1
                continue
            peer = peers[0]
            verified = True
            healed = False
            for fname, field in idx.fields.items():
                for vname, view in field.views.items():
                    frag = view.fragment(shard)
                    if frag is None:
                        continue
                    try:
                        remote = {b["id"]: b["checksum"]
                                  for b in self.client.fragment_blocks(
                                      peer.uri, iname, fname, vname, shard)}
                    except ClientError as e:
                        if e.code == "fragment-not-found":
                            remote = {}
                        else:
                            verified = False  # unreachable: retry later
                            break
                    local = {b: c.hex() for b, c in frag.blocks()}
                    if local != remote:
                        # diverged: heal NOW via the block-majority sync,
                        # then the fence lifts on the healed state
                        self._sync_fragment(iname, fname, vname, shard)
                        healed = True
                if not verified:
                    break
            if verified:
                self.executor.unfence_reads((iname, shard))
                lifted += 1
                self.stats.count("readFence/verified")
                self.events.emit("fence.lifted", index=iname, shard=shard,
                                 healed=healed)
                if healed:
                    self.stats.count("readFence/healed")
        return lifted

    # -- hint replay ---------------------------------------------------------

    def _retry_pending_hints(self) -> None:
        """Re-drive the return-heal for any LIVE member that still has a
        queued hint log (a previous replay failed mid-stream). Runs on
        the membership tick; single-flight per target via the
        _return_sync_running guard inside _on_node_return."""
        if not self.hints.pending_targets():
            return
        for n in list(self.cluster.nodes):
            if (n.id != self.node_id and n.uri
                    and not self.cluster.is_unavailable(n.id)
                    and self.hints.pending(n.id)):
                self._on_node_return(n)

    def replay_hints(self, node) -> tuple[int, int, bool]:
        """Stream queued hints to a returned peer in order, applying each
        as the idempotent remote write it originally was. Returns
        (replayed, dropped, complete) — see HintStore.replay."""
        def apply(doc: dict) -> None:
            self.client.query_proto(node.uri, doc["index"], doc["pql"],
                                    shards=doc.get("shards"), remote=True)

        replayed, dropped, complete = self.hints.replay(node.id, apply)
        if replayed or dropped:
            self.events.emit("hint.replay", target=node.id,
                             replayed=replayed, dropped=dropped,
                             complete=complete)
            self.logger.printf(
                "hints: replayed %d hint(s) to %s, %d dropped%s",
                replayed, node.id, dropped,
                "" if complete else " — anti-entropy will finish the heal")
        return replayed, dropped, complete

    def _xla_storm_event(self, family: str, new_keys: int,
                         sig_diff=None) -> None:
        """XLACounters storm hook: a recompile storm is a health incident
        the merged timeline must show (utils/telemetry.py). `sig_diff`
        is the old-vs-new dispatch signature diff — the leaf whose
        shape/dtype churned — so the timeline entry is actionable."""
        try:
            payload = {"family": family, "newShapes": int(new_keys)}
            if sig_diff:
                payload["signatureDiff"] = sig_diff
            self.events.emit("xla.recompile_storm", **payload)
        except Exception:  # noqa: BLE001 — recording must never break
            pass  # the dispatch path that tripped the storm

    def close(self) -> None:
        self.closed = True
        from pilosa_tpu.utils.events import unregister_crash_dump
        self.events.emit("node.stop")
        unregister_crash_dump(self.events)
        if self.gossip is not None:
            self.gossip.close()
        if self._bcast_thread is not None:
            self._bcast_queue.put(None)  # wake + stop the worker
            self._bcast_thread.join(timeout=2.0)
        if self._ae_timer is not None:
            self._ae_timer.cancel()
        if self._cache_flush_timer is not None:
            self._cache_flush_timer.cancel()
        if self._member_timer is not None:
            self._member_timer.cancel()
        if self._resize_watchdog is not None:
            self._resize_watchdog.cancel()
        self.telemetry.close()
        self.executor.shutdown()  # persistent fan-out / batch-exec pools
        self.runtime_monitor.close()
        self.diagnostics.close()
        if self.tracer.exporter is not None:
            self.tracer.exporter.close()  # final flush
        if self.trace_exporter is not None:
            self.trace_exporter.close()  # idempotent when it IS the
            # tracer's exporter (TraceExporter.close guards re-entry)
        self.http.close()
        self.holder.close()
        self.translate.close()

    @property
    def uri(self) -> str:
        return self.http.uri

    # -- cluster message dispatch (server.go:485-580) -----------------------

    def receive_message(self, msg: dict) -> None:
        mtype = msg.get("type")
        if mtype == "create-index":
            if self.holder.index(msg["index"]) is None:
                self.holder.create_index(msg["index"], keys=msg.get("keys", False),
                                         track_existence=msg.get("trackExistence", True))
        elif mtype == "delete-index":
            if self.holder.index(msg["index"]) is not None:
                self.holder.delete_index(msg["index"])
                self.executor.clear_caches()
        elif mtype == "create-field":
            idx = self.holder.index(msg["index"])
            if idx is not None and idx.field(msg["field"]) is None:
                idx.create_field(msg["field"], FieldOptions(**msg.get("options", {})))
        elif mtype == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None and idx.field(msg["field"]) is not None:
                idx.delete_field(msg["field"])
                self.executor.clear_caches()
        elif mtype == "create-shard":
            idx = self.holder.index(msg["index"])
            f = idx.field(msg["field"]) if idx else None
            if f is not None:
                f.add_available_shard(int(msg["shard"]), quiet=True)
        elif mtype == "node-join":
            node = Node.from_dict(msg["node"])
            self.cluster.add_node(node)
        elif mtype == "recalculate-caches":
            self.api.recalculate_caches()
        elif mtype == "set-coordinator":
            # SetCoordinatorMessage (broadcast.go; api.go SetCoordinator):
            # every node adopts the new coordinator or resize plans after a
            # failover would be driven by divergent coordinators. Adopt
            # unconditionally (the id may be a node we learn of next tick);
            # elect_coordinator reverts an id that never materializes, and
            # the probe loop's authority claim converges stragglers.
            if msg.get("id"):
                self.cluster.adopt_coordinator(msg["id"])
        elif mtype == "node-join-request":
            self._handle_join_request(Node.from_dict(msg["node"]))
        elif mtype == "node-leave-request":
            self._handle_leave_request(msg["id"])
        elif mtype == "resize-instruction":
            # async: fetching fragments over HTTP must not block the
            # coordinator's send (followResizeInstruction runs in a
            # goroutine, cluster.go:1251)
            _threads.spawn(self.follow_resize_instruction, msg)
        elif mtype == "resize-complete":
            self._handle_resize_complete(msg)
        elif mtype == "resize-abort":
            self._abort_request()
        elif mtype == "node-state":
            self._handle_node_state(msg)
        elif mtype == "hints-replayed":
            # a peer finished streaming its queued hints to us: wake the
            # rejoin verifier so the read fence lifts as soon as block
            # checksums confirm parity (instead of at the next poll tick)
            if msg.get("target") == self.node_id:
                self._fence_wake.set()
                if self.executor.read_fence:
                    self._start_fence_worker()
        elif mtype == "topology":
            self._apply_topology(msg["nodes"], msg.get("removed"))
        elif mtype == "cluster-state":
            self.cluster._set_state(msg["state"])
        elif mtype == "schema-sync":
            # coordinator push to a node returning from down: DDL broadcasts
            # it missed while broadcasts skipped it (_on_node_return)
            self._apply_schema(msg.get("schema", []))
            for iname, fields in msg.get("availableShards", {}).items():
                idx = self.holder.index(iname)
                if idx is None:
                    continue
                for fname, shards in fields.items():
                    f = idx.field(fname)
                    if f is not None:
                        for s in shards:
                            f.add_available_shard(int(s), quiet=True)
        else:
            raise ValueError(f"unknown cluster message type: {mtype}")

    def _on_shard_added(self, index_name: str, field_name: str, shard: int) -> None:
        """Announce newly-available shards so every node's shard set stays
        complete for query fan-out (CreateShardMessage, view.go:208-263).

        Async: this hook fires from inside the FIRST write to a new shard,
        so the announcement must not ride the write path — the reference
        sends it over gossip (SendAsync, broadcast.go:30); here it goes
        through the broadcast queue and the write returns immediately."""
        self.broadcast_async({"type": "create-shard", "index": index_name,
                              "field": field_name, "shard": shard})

    def _peer_uris(self) -> list[str]:
        return [n.uri for n in self.cluster.nodes
                if n.id != self.node_id and n.uri
                and not self.cluster.is_down(n.id)]

    def broadcast(self, msg: dict) -> None:
        """SendSync: POST to every peer CONCURRENTLY and wait for all
        (server.go:582-604) — total latency is the slowest peer, not the
        sum. Failed peers are skipped; they converge via anti-entropy or
        the return-heal schema sync."""
        uris = self._peer_uris()
        if not uris:
            return
        if len(uris) == 1:  # no thread overhead for the 2-node case
            try:
                self.client.send_message(uris[0], msg)
            except ClientError:
                pass
            return
        threads = [_threads.spawn(self._send_quiet, u, msg)
                   for u in uris]
        for t in threads:
            t.join()

    def _send_quiet(self, uri: str, msg: dict) -> None:
        try:
            self.client.send_message(uri, msg)
        except ClientError:
            pass  # peers converge via anti-entropy

    # budget for the pre-ack create-shard announcement of a shard-CREATING
    # Set: healthy peers answer within ~1 RTT; a hung peer costs at most
    # this (once per new shard — its daemon sender keeps trying after the
    # ack, so delivery is attempted either way)
    ANNOUNCE_SHARD_BUDGET_S = 0.5

    def _announce_shard_bounded(self, iname: str, fname: str,
                                shard: int) -> None:
        """Concurrent create-shard broadcast with a bounded wait, run
        BEFORE a shard-creating Set() acks: an immediately-following read
        through any live node must not race the async announcement queue
        (PR-1 made the per-write announcement async precisely so a hung
        peer adds no write latency — that holds for the common case; only
        the once-per-shard-lifetime CREATING write pays a bounded wait)."""
        msg = {"type": "create-shard", "index": iname, "field": fname,
               "shard": shard}
        uris = self._peer_uris()
        if not uris:
            return
        threads = [_threads.spawn(self._send_quiet, u, msg)
                   for u in uris]
        deadline = time.monotonic() + self.ANNOUNCE_SHARD_BUDGET_S
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    # per-peer async queue bound: a long-hung peer must not grow its queue
    # without limit — dropped messages converge via anti-entropy / the
    # return-heal schema sync
    BCAST_PEER_QUEUE_MAX = 1024

    def broadcast_async(self, msg: dict) -> None:
        """SendAsync (broadcast.go:30-36): enqueue and return — delivery
        happens on per-peer sender workers with bounded retry; after that,
        anti-entropy converges. The caller (a write path) never blocks on
        a peer, and a hung peer head-of-line-blocks ONLY its own queue —
        announcements keep flowing to healthy peers."""
        if self.closed:
            return
        self._bcast_queue.put(msg)

    def _bcast_worker(self) -> None:
        """Fans the async broadcast queue out to one sender thread + queue
        per peer URI (created lazily; torn down on close and when the peer
        leaves the cluster — a departed peer must not keep a retrying
        sender alive for the rest of the server's life)."""
        import queue as _queue

        peer_queues: dict[str, "_queue.Queue"] = {}

        def peer_sender(uri: str, q: "_queue.Queue") -> None:
            while True:
                m = q.get()
                if m is None:
                    return
                try:
                    self.client.send_message(uri, m)
                except ClientError:
                    if self.closed:
                        continue
                    time.sleep(0.2)  # one retry, then let AE converge
                    self._send_quiet(uri, m)

        while True:
            msg = self._bcast_queue.get()
            if msg is None:  # close() sentinel: stop the peer senders too
                for q in peer_queues.values():
                    q.put(None)
                return
            # retire senders only for peers that LEFT the cluster; a
            # temporarily-down peer keeps its queue (it is just skipped
            # by _peer_uris until liveness marks it back up)
            member = {n.uri for n in self.cluster.nodes
                      if n.id != self.node_id and n.uri}
            for uri in [u for u in peer_queues if u not in member]:
                peer_queues.pop(uri).put(None)
            for uri in self._peer_uris():
                q = peer_queues.get(uri)
                if q is None:
                    q = peer_queues[uri] = _queue.Queue()
                    _threads.spawn(peer_sender, uri, q)
                if q.qsize() < self.BCAST_PEER_QUEUE_MAX:
                    q.put(msg)
                else:
                    self._bcast_dropped += 1

    # -- resize engine (cluster.go:1150-1515) -------------------------------

    def request_join(self) -> None:
        """Announce this node to the first answering seed; the request is
        forwarded to the coordinator which runs a resize job for us."""
        me = {"id": self.node_id, "uri": self.http.uri}
        for huri in self.cluster_hosts:
            if huri == self.http.uri:
                continue
            try:
                self.client.send_message(huri, {"type": "node-join-request",
                                                "node": me})
                return
            except ClientError:
                continue

    def _resize_request(self, event: str, node: Node):
        """API hook: route a membership change through the coordinator
        (api.RemoveNode → coordinator resize, api.go:1092). Raises
        ValueError so a refusal (e.g. too few replicas) surfaces to the
        operator's HTTP request instead of vanishing in forwarding."""
        if event != "leave":
            raise ValueError(f"unsupported resize event: {event}")
        if not self.cluster.is_coordinator():
            coord = self.cluster.node_by_id(self.cluster.coordinator_id)
            if coord is None or not coord.uri:
                raise ValueError("no coordinator available")
            try:
                self.client.send_message(coord.uri, {
                    "type": "node-leave-request", "id": node.id})
            except ClientError as e:
                raise ValueError(f"remove-node refused by coordinator: {e}")
            return None
        self._handle_leave_request(node.id)
        return self.cluster.active_job

    def _abort_request(self) -> None:
        """API hook for /cluster/resize/abort: cancel the coordinator's
        active job, then un-gate peers."""
        if not self.cluster.is_coordinator():
            coord = self.cluster.node_by_id(self.cluster.coordinator_id)
            if coord is None or not coord.uri:
                raise ValueError("no coordinator available")
            try:
                self.client.send_message(coord.uri, {"type": "resize-abort"})
            except ClientError as e:
                raise ValueError(f"abort refused by coordinator: {e}")
            return
        with self._resize_lock:
            self.cluster.abort_resize()
        if self._resize_watchdog is not None:
            self._resize_watchdog.cancel()
        self._resize_aborted()

    def _forward_to_coordinator(self, msg: dict) -> bool:
        coord = self.cluster.node_by_id(self.cluster.coordinator_id)
        if coord is None or coord.id == self.node_id or not coord.uri:
            return False
        try:
            self.client.send_message(coord.uri, msg)
            return True
        except ClientError:
            return False

    def _handle_join_request(self, node: Node) -> None:
        if node.id == self.node_id:
            return
        # a previously-removed node may rejoin: clear its tombstone
        self._removed_ids.discard(node.id)
        if self.cluster.node_by_id(node.id) is not None:
            # already a member (e.g. re-knock after a lost topology message):
            # resend the topology directly so the requester converges
            try:
                self.client.send_message(node.uri, {
                    "type": "topology",
                    "nodes": [n.to_dict() for n in self.cluster.nodes],
                    "removed": sorted(self._removed_ids)})
            except ClientError:
                pass
            return
        if not self.cluster.is_coordinator():
            self._forward_to_coordinator({"type": "node-join-request",
                                          "node": node.to_dict()})
            return
        with self._resize_lock:
            if self.cluster.state == STATE_RESIZING \
                    or self.cluster.active_job is not None:
                if all(n.id != node.id for _, n in self._pending_resizes):
                    self._pending_resizes.append(("join", node))
                return
            job = self.cluster.node_join(node)
        if job is not None:
            self._broadcast_state(STATE_RESIZING)
            self._distribute_resize(job)

    def _handle_leave_request(self, node_id: str) -> None:
        if not self.cluster.is_coordinator():
            self._forward_to_coordinator({"type": "node-leave-request",
                                          "id": node_id})
            return
        with self._resize_lock:
            victim = self.cluster.node_by_id(node_id)
            if self.cluster.state == STATE_RESIZING \
                    or self.cluster.active_job is not None:
                if victim is not None:
                    self._pending_resizes.append(("leave", victim))
                return
            job = self.cluster.node_leave(node_id)
        if job is not None:
            self._broadcast_state(STATE_RESIZING)
            self._distribute_resize(job)
        else:
            # degraded removal (too few nodes to rebuild replicas) — the
            # membership already changed; converge peers now
            self._removed_ids.add(node_id)
            self.hints.drop_target(node_id)  # never deliverable again
            self._broadcast_topology()
            # tell the victim it is out so it stops acting as a member
            if victim is not None and victim.uri:
                try:
                    self.client.send_message(victim.uri, {
                        "type": "topology",
                        "nodes": [n.to_dict() for n in self.cluster.nodes],
                        "removed": sorted(self._removed_ids)})
                except ClientError:
                    pass
            self.clean_holder()

    def _distribute_resize(self, job: ResizeJob) -> None:
        """Send each node its fetch instructions (distributeResizeInstructions,
        cluster.go:1499). Includes the schema so a joining node can apply DDL
        before loading fragments (followResizeInstruction applies schema
        first, cluster.go:1251-1340)."""
        uri_by_id = {n.id: n.uri for n in self.cluster.nodes}
        if job.node is not None:
            uri_by_id.setdefault(job.node.id, job.node.uri)
        self.events.emit("resize.start", job=job.id, event=job.event,
                         node=job.node_id)
        self._arm_watchdog(job.id)
        schema = self.holder.schema()
        # cluster-wide available-shards state rides along so a joining node
        # fans queries out over ALL shards, not just the ones it received
        # (the reference ships this in NodeStatus on join, server.go:485-580
        # → holder merge of remote available shards)
        avail = {
            iname: {fname: [int(s) for s in f.available_shards.slice()]
                    for fname, f in idx.fields.items()}
            for iname, idx in self.holder.indexes.items()
        }
        for target, sources in job.instructions.items():
            msg = {
                "type": "resize-instruction",
                "job": job.id,
                "coordinator": self.node_id,
                "coordinatorURI": self.http.uri,
                "schema": schema,
                "availableShards": avail,
                "sources": [dict(s.to_dict(),
                                 fromURI=uri_by_id.get(s.from_node, ""))
                            for s in sources],
            }
            if target == self.node_id:
                _threads.spawn(self.follow_resize_instruction, msg)
            else:
                try:
                    self.client.send_message(uri_by_id[target], msg)
                except ClientError as e:
                    self.logger.printf("resize: instruction undeliverable to "
                                       "%s: %s — aborting job", target, e)
                    with self._resize_lock:
                        self.cluster.abort_resize()
                    self._resize_aborted()
                    return

    def follow_resize_instruction(self, msg: dict) -> None:
        """Apply schema, stream each source fragment from its donor, ack the
        coordinator (followResizeInstruction, cluster.go:1251-1393)."""
        done = {"type": "resize-complete", "job": msg["job"],
                "node": self.node_id}
        try:
            self._apply_schema(msg.get("schema", []))
            for iname, fields in msg.get("availableShards", {}).items():
                idx = self.holder.index(iname)
                if idx is None:
                    continue
                for fname, shards in fields.items():
                    f = idx.field(fname)
                    if f is not None:
                        for s in shards:
                            f.add_available_shard(int(s), quiet=True)
            for src in msg.get("sources", []):
                idx = self.holder.index(src["index"])
                f = idx.field(src["field"]) if idx is not None else None
                if f is None:
                    raise ClientError(
                        f"schema missing for {src['index']}/{src['field']}")
                # the donor enumerates which views hold this shard; stream
                # each (fragment tar-walk analog, fragment.go:1823-1998)
                views = self.client.fragment_views(
                    src["fromURI"], src["index"], src["field"], src["shard"])
                for vname in views:
                    try:
                        data = self.client.retrieve_shard(
                            src["fromURI"], src["index"], src["field"],
                            vname, src["shard"])
                    except ClientError as e:
                        if e.status == 404:
                            continue  # raced away; anti-entropy will heal
                        raise
                    view = f.create_view_if_not_exists(vname)
                    frag = view.create_fragment_if_not_exists(src["shard"])
                    frag.import_roaring(data)
                    view.refresh_rank_cache(src["shard"])
                f.add_available_shard(src["shard"], quiet=True)
        except (ClientError, ValueError, OSError) as e:
            done["error"] = str(e)
        if msg.get("coordinator") == self.node_id:
            self._handle_resize_complete(done)
        else:
            # the ack must arrive or the cluster wedges in RESIZING until
            # the watchdog aborts — retry transient failures
            import time as _time
            for attempt in range(5):
                try:
                    self.client.send_message(msg["coordinatorURI"], done)
                    break
                except ClientError:
                    _time.sleep(0.5 * (attempt + 1))

    def _apply_schema(self, schema: list[dict]) -> None:
        """Create any indexes/fields we don't have yet from schema dicts
        (the resize instruction's Schema payload)."""
        for idx_d in schema:
            opts = idx_d.get("options", {})
            idx = self.holder.create_index_if_not_exists(
                idx_d["name"], keys=opts.get("keys", False),
                track_existence=opts.get("trackExistence", True))
            for fd in idx_d.get("fields", []):
                o = fd.get("options", {})
                idx.create_field_if_not_exists(fd["name"], FieldOptions(
                    type=o.get("type", "set"),
                    cache_type=o.get("cacheType", "ranked"),
                    cache_size=o.get("cacheSize", 50000),
                    min=o.get("min", 0),
                    max=o.get("max", 0),
                    time_quantum=o.get("timeQuantum", ""),
                    keys=o.get("keys", False)))

    def _handle_resize_complete(self, msg: dict) -> None:
        with self._resize_lock:
            job = self.cluster.active_job
            if job is None or job.id != msg.get("job"):
                return
            if msg.get("error"):
                self.logger.printf("resize: job %s failed on %s: %s",
                                   job.id, msg.get("node"), msg["error"])
                self.cluster.abort_resize()
                aborted, finished = True, False
            else:
                aborted = False
                self.cluster.complete_resize(job, msg["node"])
                # done when the job cleared — the post-resize state may be
                # DEGRADED if an unrelated node is probe-marked down;
                # completion steps (topology broadcast, watchdog cancel,
                # pending-resize drain) must still run
                finished = self.cluster.active_job is None
                if finished and job.event == EVENT_LEAVE:
                    self._removed_ids.add(job.node_id)
        if aborted:
            if self._resize_watchdog is not None:
                self._resize_watchdog.cancel()
            self._resize_aborted()
            return
        if not finished:
            return
        if self._resize_watchdog is not None:
            self._resize_watchdog.cancel()
        self.events.emit("resize.complete", job=job.id, event=job.event,
                         node=job.node_id)
        if job.event == EVENT_LEAVE:
            # the departed node's queued hints are never deliverable
            self.hints.drop_target(job.node_id)
        self._broadcast_topology()
        # tell the departed node it is out so it stops acting as a member
        if job.event == EVENT_LEAVE and job.node is not None and job.node.uri:
            try:
                self.client.send_message(job.node.uri, {
                    "type": "topology",
                    "nodes": [n.to_dict() for n in self.cluster.nodes],
                    "removed": sorted(self._removed_ids)})
            except ClientError:
                pass
        self.clean_holder()
        self._drain_pending_resizes()

    def _arm_watchdog(self, job_id: str) -> None:
        if self._resize_watchdog is not None:
            self._resize_watchdog.cancel()
        if self.resize_timeout <= 0:
            return
        t = _threads.ctx_timer(self.resize_timeout, self._watchdog_fire,
                               args=(job_id,))
        t.start()
        self._resize_watchdog = t

    def _watchdog_fire(self, job_id: str) -> None:
        with self._resize_lock:
            job = self.cluster.active_job
            if job is None or job.id != job_id:
                return
            self.logger.printf("resize: job %s timed out after %.0fs — "
                               "aborting", job_id, self.resize_timeout)
            self.cluster.abort_resize()
        self._resize_aborted()

    def _broadcast_state(self, state: str) -> None:
        """Propagate the cluster state to every member so e.g. RESIZING
        blocks writes cluster-wide, not just on the coordinator (the
        reference's ClusterStatus broadcast, server.go:485-580)."""
        self.broadcast({"type": "cluster-state", "state": state})

    def _resize_aborted(self) -> None:
        """Un-wedge peers stuck in RESIZING, then try the next queued
        membership event (an aborted join self-heals by re-knocking)."""
        self.events.emit("resize.abort")
        self._broadcast_state(self.cluster.state)
        self._drain_pending_resizes()

    def _drain_pending_resizes(self) -> None:
        """Dispatch queued membership events one at a time (listenForJoins,
        cluster.go:1095-1148). A queued event that became invalid (e.g. a
        leave now refused for lack of replicas) is logged and skipped so it
        cannot wedge the rest of the queue."""
        while True:
            with self._resize_lock:
                if not self._pending_resizes:
                    return
                event, node = self._pending_resizes.pop(0)
            try:
                if event == "join":
                    self._handle_join_request(node)
                else:
                    self._handle_leave_request(node.id)
                with self._resize_lock:
                    started = self.cluster.active_job is not None
                if started:
                    return  # a job is running; its completion drains next
                # event completed synchronously (degraded removal,
                # already-member join) — keep draining
            except ValueError as e:
                self.logger.printf("resize: dropping queued %s(%s): %s",
                                   event, node.id, e)

    def _broadcast_topology(self) -> None:
        """Push the final membership to every node (the coordinator's
        cluster-status broadcast after a resize completes)."""
        nodes_d = [n.to_dict() for n in self.cluster.nodes]
        self.cluster.elect_coordinator()
        msg = {"type": "topology", "nodes": nodes_d,
               "removed": sorted(self._removed_ids)}
        for n in self.cluster.nodes:
            if n.id == self.node_id or not n.uri:
                continue
            try:
                self.client.send_message(n.uri, msg)
            except ClientError:
                pass

    def _apply_topology(self, nodes_d: list[dict],
                        removed: Optional[list[str]] = None) -> None:
        # the coordinator's removed-set is authoritative: REPLACE (a union
        # would tombstone a removed-then-rejoined node on peers forever,
        # silently diverging membership)
        if removed is not None:
            self._removed_ids = set(removed)
        if self.node_id in self._removed_ids:
            # we were removed: become a standalone node and stop merging
            # ourselves back into the cluster (operator shuts us down)
            self._left = True
            me = Node(id=self.node_id, uri=self.http.uri)
            self.cluster.set_static([me])
            self.cluster.coordinator_id = self.node_id
            return
        nodes = [Node.from_dict(d) for d in nodes_d
                 if d["id"] not in self._removed_ids]
        before = {n.id for n in self.cluster.nodes}
        self.cluster.set_static(nodes)
        self.cluster.elect_coordinator()
        after = {n.id for n in self.cluster.nodes}
        if after != before:
            self.events.emit("topology.change",
                             nodes=sorted(after),
                             added=sorted(after - before),
                             removed=sorted(before - after))
        self.clean_holder()

    def clean_holder(self) -> int:
        """Drop fragments this node no longer owns after a resize
        (holderCleaner, holder.go:855-906). Returns fragments dropped."""
        dropped = 0
        for iname, idx in self.holder.indexes.items():
            for f in idx.fields.values():
                for view in f.views.values():
                    for shard in view.shards():
                        if not self.cluster.owns_shard(self.node_id, iname,
                                                       shard):
                            view.delete_fragment(shard)
                            dropped += 1
        return dropped

    # -- fleet telemetry (utils/telemetry.py; docs/operations.md) -----------

    # time-series tail shipped inside the node stats document — enough for
    # the dashboard's fleet sparklines without re-fetching every ring
    STATS_TAIL_SAMPLES = 60

    def sample_gauges(self) -> dict:
        """One telemetry tick (the sampler's source): instantaneous gauges
        plus window rates derived from cumulative counters since the
        previous tick. Keys are dotted series names; the ring stores the
        returned dict verbatim."""
        from pilosa_tpu.utils import telemetry as _telemetry
        from pilosa_tpu.utils.diagnostics import process_rss

        now = time.monotonic()
        g: dict = {}
        raw: dict = {}
        ex = self.executor
        res = getattr(ex, "residency", None)
        if res is not None:
            snap = res.snapshot()
            g["residency.bytes"] = float(snap["bytes"])
            g["residency.budget"] = float(res.budget)
            g["residency.entries"] = float(snap["entries"])
            raw["residency.hits"] = snap["hits"]
            raw["residency.lookups"] = snap["hits"] + snap["misses"]
            raw["residency.evictions"] = snap["evictions"]
        pc = getattr(ex, "plan_cache", None)
        if pc is not None:
            cs = pc.snapshot()
            g["plancache.bytes"] = float(cs["bytes"])
            g["plancache.entries"] = float(cs["entries"])
            raw["plancache.hits"] = cs["hits"]
            raw["plancache.lookups"] = cs["hits"] + cs["misses"]
            raw["plancache.evictions"] = cs["evictions"]
        pl = getattr(ex, "planner", None)
        if pl is not None:
            ps = pl.snapshot()
            raw["planner.reorders"] = ps["reorders"]
            raw["planner.pushdowns"] = ps["pushdowns"]
            raw["planner.short_circuits"] = ps["shortCircuits"]
        # fragment heat map: tick the tracker's summary ring (the
        # /debug/heat since-cursor feed rides the sampler's clock) and
        # publish the aggregate temperature gauges the dashboard's
        # skew sparkline reads
        tracker = getattr(ex, "heat", None)
        if tracker is not None:
            hsum = tracker.sample_tick()
            g["heat.hot_fragments"] = float(hsum["hotFragments"])
            g["heat.skew"] = float(hsum["skew"])
            g["heat.tracker_entries"] = float(hsum["trackerEntries"])
        # per-principal usage ledger: tick its delta ring (the
        # /debug/usage since-cursor feed rides the sampler's clock) and
        # sample fleet-level gauges; SLO burn rates per objective
        usage = getattr(self.api, "usage_ledger", None)
        if usage is not None:
            usage.sample_tick()
            ut = usage.totals()
            g["usage.tracked_principals"] = float(
                usage.snapshot(top=1)["trackedPrincipals"])
            raw["usage.queries"] = ut["queries"]
            raw["usage.device_ms"] = ut["deviceMs"]
            raw["usage.rpc_bytes"] = ut["rpcBytes"]
        slo = getattr(self.api, "slo", None)
        if slo is not None:
            worst = 0.0
            for name, ob in slo.evaluate().items():
                g[f"slo.{name}.burn_short"] = ob["burnShort"]
                g[f"slo.{name}.burn_long"] = ob["burnLong"]
                worst = max(worst, {"green": 0.0, "yellow": 1.0,
                                    "red": 2.0}[ob["status"]])
            g["slo.worst"] = worst
        # QoS plane: admission/shed/throttle totals (windowed to rates
        # below) + the live wait estimate admission decides against
        qp = getattr(self, "qos", None)
        if qp is not None:
            qt = qp.totals()
            raw["qos.admitted"] = qt["admitted"]
            raw["qos.shed"] = qt["shed"] + qt["wouldShed"]
            raw["qos.throttled"] = qt["throttled"]
            g["qos.estimated_wait_ms"] = round(qp.estimated_wait_ms(), 3)
        depth = 0
        for attr in ("batcher", "sum_batcher", "minmax_batcher"):
            b = getattr(ex, attr, None)
            if b is None:
                continue
            bs = b.snapshot()
            depth += bs["queue_depth"]
            raw["batcher.wait_ms_total"] = raw.get(
                "batcher.wait_ms_total", 0.0) + bs["wait_ms_total"]
            raw["batcher.waited"] = raw.get(
                "batcher.waited", 0) + bs["waited"]
            raw["batcher.batches"] = raw.get(
                "batcher.batches", 0) + bs["batches"]
        g["batcher.queue_depth"] = float(depth)
        ps = ex.fanout_pool_stats()
        g["fanout.pool_size"] = float(ps["size"])
        g["fanout.threads"] = float(ps["threads"])
        g["fanout.queued"] = float(ps["queued"])
        # occupancy approximation: threads are created on demand and
        # queued work means every thread is busy
        g["fanout.utilization"] = min(
            1.0, ps["threads"] / max(1, ps["size"])) if not ps["queued"] \
            else 1.0
        raw["hedges.fired"] = getattr(ex, "hedges_fired", 0)
        raw["hedges.won"] = getattr(ex, "hedges_won", 0)
        # ICI slice-local serving: route decision rates + the windowed
        # slice-local share (the dashboard's sparkline of how much of the
        # distributed read mix is escaping the HTTP plane)
        isnap = ex.ici_snapshot()
        raw["ici.slice_local"] = isnap["sliceLocal"]
        raw["ici.cross_slice"] = isnap["crossSlice"]
        raw["ici.fallback"] = isnap["fallback"]
        raw["ici.routed"] = (isnap["sliceLocal"] + isnap["crossSlice"]
                             + isnap["fallback"])
        # hybrid sparse/dense containers: live sparse occupancy gauges
        # plus the windowed sparse share of row-leaf uploads (the
        # dashboard's sparkline of how much of the leaf traffic escapes
        # the dense-plane cost)
        hy = ex.hybrid_snapshot()
        g["hybrid.sparse_bytes"] = float(hy["residentSparseBytes"])
        g["hybrid.sparse_leaves"] = float(hy["residentSparseLeaves"])
        g["hybrid.run_bytes"] = float(hy["residentRunBytes"])
        g["hybrid.run_leaves"] = float(hy["residentRunLeaves"])
        raw["hybrid.sparse_uploads"] = hy["sparseUploads"]
        raw["hybrid.run_uploads"] = hy["runUploads"]
        raw["hybrid.row_uploads"] = (hy["sparseUploads"]
                                     + hy["runUploads"]
                                     + hy["denseUploads"])
        # streaming ingest: coalesced write plane — mutation throughput
        # plus the WAL group-commit ratio (mutations per fsync-able WAL
        # append, the headline fsync-reduction evidence)
        ing = ex.ingest_snapshot()
        raw["ingest.mutations"] = ing["mutations"]
        raw["ingest.batches"] = ing["appliedBatches"]
        raw["ingest.wal_appends"] = ing["walAppends"]
        g["ingest.queue_depth"] = float(ing["queue_depth"])
        # hinted handoff + drain lifecycle + rejoin read fence
        hsnap = self.hints.snapshot()
        g["hints.pending_bytes"] = float(hsnap["pendingBytes"])
        g["hints.pending_targets"] = float(len(hsnap["pendingTargets"]))
        raw["hints.queued"] = hsnap["queued"]
        raw["hints.replayed"] = hsnap["replayed"]
        raw["hints.dropped"] = hsnap["dropped"]
        g["drain.draining"] = 1.0 if self.draining else 0.0
        raw["drain.shed"] = self.handler.drain_sheds
        esnap = self.events.snapshot()
        raw["events.emitted"] = esnap["emitted"]
        g["events.retained"] = float(sum(esnap["retained"].values()))
        g["fence.fenced_shards"] = float(
            ex.fence_snapshot()["fencedShards"])
        wal_bytes = 0
        wal_ops = 0
        poisoned = 0
        for _i, _f, _v, _s, frag in self.holder.walk_fragments():
            try:
                wal_bytes += os.path.getsize(frag.path)
            except (OSError, TypeError):
                pass
            wal_ops += int(getattr(frag.storage, "op_n", 0) or 0)
            if getattr(frag.storage, "wal_poisoned", False):
                poisoned += 1
        damaged = self.holder.damaged_fragments()
        g["wal.bytes"] = float(wal_bytes)
        g["wal.ops"] = float(wal_ops)
        g["wal.poisoned_fragments"] = float(poisoned)
        g["wal.damaged_fragments"] = float(len(damaged))
        g["wal.needs_rebuild"] = float(
            sum(1 for d in damaged if d["needsRebuild"]))
        g["process.rss_bytes"] = float(process_rss())
        g["process.threads"] = float(threading.active_count())
        raw["http.errors"] = float(self.handler.errors_5xx)
        xs = _telemetry.xla.snapshot()
        g["xla.compiles"] = float(xs["compiles"])
        g["xla.cached_dispatches"] = float(xs["cachedDispatches"])
        g["xla.storms"] = float(xs["storms"])
        raw["xla.compiles"] = xs["compiles"]
        for dev in _telemetry.device_memory_stats():
            ms = dev["memoryStats"]
            if ms and "bytes_in_use" in ms:
                # first device with a reporting backend (TPU HBM);
                # CPU backends return null stats and are skipped —
                # the dashboard's HBM sparkline degrades to absent
                g["device.bytes_in_use"] = float(ms["bytes_in_use"])
                g["device.hbm_bytes_in_use"] = float(ms["bytes_in_use"])
                g["device.hbm_limit"] = float(ms.get("bytes_limit", 0))
                break
        # device kernel attribution (telemetry.KernelStats): dispatch and
        # h2d throughput plus windowed per-dispatch wall / queue-wait
        ks = _telemetry.kernels.totals()
        raw["kernels.dispatches"] = ks["dispatches"]
        raw["kernels.dispatch_ms"] = ks["dispatch_ms_total"]
        raw["kernels.wait_ms"] = ks["wait_ms_total"]
        raw["kernels.waited"] = ks["waited"]
        raw["kernels.h2d_bytes"] = ks["h2d_bytes"]

        prev, prev_t = self._telemetry_prev
        dt = max(1e-9, now - prev_t)

        def rate(name: str) -> float:
            if prev is None or name not in prev or name not in raw:
                return 0.0
            return max(0.0, (raw[name] - prev[name]) / dt)

        if res is not None:
            if prev is not None:
                dlook = raw["residency.lookups"] - prev.get(
                    "residency.lookups", 0)
                dhits = raw["residency.hits"] - prev.get("residency.hits", 0)
                if dlook > 0:
                    self._last_hit_rate = max(0.0, dhits) / dlook
            g["residency.hit_rate"] = self._last_hit_rate
            g["residency.evictions_per_s"] = rate("residency.evictions")
        if pc is not None:
            # WINDOWED plan-cache hit rate, same rationale as residency's:
            # a lifetime ratio hides a cache that just started thrashing
            if prev is not None:
                dlook = raw["plancache.lookups"] - prev.get(
                    "plancache.lookups", 0)
                dhits = raw["plancache.hits"] - prev.get(
                    "plancache.hits", 0)
                if dlook > 0:
                    self._last_plan_hit_rate = max(0.0, dhits) / dlook
            g["plancache.hit_rate"] = self._last_plan_hit_rate
            g["plancache.evictions_per_s"] = rate("plancache.evictions")
        if pl is not None:
            g["planner.reorders_per_s"] = rate("planner.reorders")
            g["planner.pushdowns_per_s"] = rate("planner.pushdowns")
            g["planner.short_circuits_per_s"] = rate(
                "planner.short_circuits")
        if prev is not None:
            dwaited = raw.get("batcher.waited", 0) - prev.get(
                "batcher.waited", 0)
            dwait = raw.get("batcher.wait_ms_total", 0.0) - prev.get(
                "batcher.wait_ms_total", 0.0)
            g["batcher.avg_wait_ms"] = (max(0.0, dwait) / dwaited
                                        if dwaited > 0 else 0.0)
        g["batcher.batches_per_s"] = rate("batcher.batches")
        g["qos.admitted_per_s"] = rate("qos.admitted")
        g["qos.shed_per_s"] = rate("qos.shed")
        g["qos.throttled_per_s"] = rate("qos.throttled")
        g["ingest.sets_per_s"] = rate("ingest.mutations")
        g["ingest.batches_per_s"] = rate("ingest.batches")
        g["ingest.wal_appends_per_s"] = rate("ingest.wal_appends")
        g["hints.queued_per_s"] = rate("hints.queued")
        g["hints.replayed_per_s"] = rate("hints.replayed")
        g["hints.dropped_per_s"] = rate("hints.dropped")
        g["drain.shed_per_s"] = rate("drain.shed")
        g["events.emitted_per_s"] = rate("events.emitted")
        g["hedges.fired_per_s"] = rate("hedges.fired")
        g["ici.slice_local_per_s"] = rate("ici.slice_local")
        g["ici.cross_slice_per_s"] = rate("ici.cross_slice")
        if prev is not None:
            drouted = raw["ici.routed"] - prev.get("ici.routed", 0)
            dlocal = raw["ici.slice_local"] - prev.get(
                "ici.slice_local", 0)
            if drouted > 0:
                self._last_ici_share = max(0.0, dlocal) / drouted
        g["ici.slice_local_share"] = self._last_ici_share
        if prev is not None:
            dups = raw["hybrid.row_uploads"] - prev.get(
                "hybrid.row_uploads", 0)
            dsp = raw["hybrid.sparse_uploads"] - prev.get(
                "hybrid.sparse_uploads", 0)
            drn = raw["hybrid.run_uploads"] - prev.get(
                "hybrid.run_uploads", 0)
            if dups > 0:
                self._last_hybrid_share = max(0.0, dsp) / dups
                self._last_hybrid_run_share = max(0.0, drn) / dups
        g["hybrid.sparse_share"] = self._last_hybrid_share
        g["hybrid.run_share"] = self._last_hybrid_run_share
        g["http.errors_per_s"] = rate("http.errors")
        g["xla.compiles_per_s"] = rate("xla.compiles")
        g["kernels.dispatches_per_s"] = rate("kernels.dispatches")
        g["kernels.h2d_bytes_per_s"] = rate("kernels.h2d_bytes")
        # windowed per-dispatch dispatch wall and per-request queue wait
        # (dashboard sparklines): delta-over-delta, same discipline as
        # batcher.avg_wait_ms above
        g["kernels.avg_dispatch_ms"] = 0.0
        g["kernels.avg_wait_ms"] = 0.0
        if prev is not None:
            dd = raw["kernels.dispatches"] - prev.get(
                "kernels.dispatches", 0)
            dms = raw["kernels.dispatch_ms"] - prev.get(
                "kernels.dispatch_ms", 0.0)
            if dd > 0:
                g["kernels.avg_dispatch_ms"] = max(0.0, dms) / dd
            dw = raw["kernels.waited"] - prev.get("kernels.waited", 0)
            dwm = raw["kernels.wait_ms"] - prev.get("kernels.wait_ms", 0.0)
            if dw > 0:
                g["kernels.avg_wait_ms"] = max(0.0, dwm) / dw
        g["usage.queries_per_s"] = rate("usage.queries")
        g["usage.device_ms_per_s"] = rate("usage.device_ms")
        g["usage.rpc_bytes_per_s"] = rate("usage.rpc_bytes")
        self._telemetry_prev = (raw, now)
        # health-transition events: the sampler is the one periodic
        # observer of the shared health score, so a green->yellow->red
        # (or recovery) edge lands on the flight-recorder timeline with
        # its reasons exactly once per transition
        health = self.node_health()
        if self._last_health is not None \
                and health["score"] != self._last_health:
            self.events.emit("health.transition",
                             fromScore=self._last_health,
                             toScore=health["score"],
                             reasons=health["reasons"][:5])
        self._last_health = health["score"]
        return g

    def _health_inputs(self) -> dict:
        """Cheap live reads feeding telemetry.health_score — shared by
        /status (via api.health_fn) and the node stats document. /status
        is the load-balancer AND peer-probe hot path, so the O(fragments)
        storage walk is read from the sampler's last tick when one exists
        (staleness <= telemetry-interval); the direct walk is only the
        sampler-disabled fallback."""
        from pilosa_tpu.utils import telemetry as _telemetry

        latest = self.telemetry.ring.latest()
        if latest:
            poisoned = latest.get("wal.poisoned_fragments", 0.0) > 0
            needs_rebuild = int(latest.get("wal.needs_rebuild", 0.0))
            n_damaged = int(latest.get("wal.damaged_fragments", 0.0))
        else:
            damaged = self.holder.damaged_fragments()
            poisoned = any(
                getattr(frag.storage, "wal_poisoned", False)
                for _i, _f, _v, _s, frag in self.holder.walk_fragments())
            needs_rebuild = sum(1 for d in damaged if d["needsRebuild"])
            n_damaged = len(damaged)
        ps = self.executor.fanout_pool_stats()
        out = {
            "walPoisoned": poisoned,
            "needsRebuild": needs_rebuild,
            "damagedFragments": n_damaged,
            "errorRate": latest.get("http.errors_per_s", 0.0),
            "queueSaturation": ps["queued"] / max(1, ps["size"]),
            "recompileStormActive": _telemetry.xla.storm_active(),
            # lifecycle: a draining node is deliberately yellow (the
            # federation renders the restart as in-progress, not broken),
            # and unverified fenced shards keep the rejoin visible
            "draining": self.draining,
            "fencedShards": self.executor.fence_snapshot()["fencedShards"],
        }
        slo = getattr(self.api, "slo", None)
        if slo is not None:
            # an SLO burning its error budget makes the node yellow/red
            # on /status and in the federation — the same single health
            # definition load balancers act on
            status, reason = slo.worst()
            if status != "green":
                out["sloStatus"] = status
                out["sloReason"] = reason
        return out

    def node_health(self) -> dict:
        from pilosa_tpu.utils.telemetry import health_score
        return health_score(self._health_inputs())

    def node_stats(self) -> dict:
        """This node's fleet-telemetry document (GET /internal/stats):
        identity, health + its inputs, the latest sampled gauges, XLA
        counters, device memory, and a bounded time-series tail for the
        fleet dashboard's sparklines."""
        from pilosa_tpu import __version__
        from pilosa_tpu.utils import telemetry as _telemetry

        inputs = self._health_inputs()
        ring = self.telemetry.ring
        tail = ring.since(0, limit=self.STATS_TAIL_SAMPLES)
        return {
            "id": self.node_id,
            "uri": self.http.uri,
            # a draining node reports DRAINING (the federation renders it
            # yellow via the health inputs); otherwise the cluster state
            "state": "DRAINING" if self.draining else self.cluster.state,
            "version": __version__,
            "uptimeSeconds": int(time.monotonic() - self.api.start_time),
            "health": _telemetry.health_score(inputs),
            "healthInputs": inputs,
            "damagedFragments": inputs["damagedFragments"],
            "gauges": ring.latest(),
            "counters": {
                "http5xx": self.handler.errors_5xx,
                "hedgesFired": getattr(self.executor, "hedges_fired", 0),
                "sampleErrors": self.telemetry.sample_errors,
            },
            "xla": _telemetry.xla.snapshot(),
            "deviceMemory": _telemetry.device_memory_stats(),
            "timeseries": tail,
        }

    def cluster_stats(self) -> dict:
        """The merged fleet document (GET /cluster/stats): every live
        peer's node stats collected CONCURRENTLY over the persistent
        fan-out pool, scored per node. Peers that 404 the route degrade
        to "legacy" (mixed-version clusters stay green); down peers are
        red without burning an RPC on them; a transient fetch failure of
        a live peer is yellow, never an error."""
        local = self.node_stats()
        entries: dict[str, dict] = {self.node_id: local}
        order: list[str] = []
        timeout = max(2.0, self.probe_timeout)
        # dedicated short-lived threads, NOT the query fan-out pool: under
        # heavy query load that pool's queue is deep (the very condition
        # queueSaturation flags), and stats fetches queued behind query
        # RPCs would time out and paint healthy peers yellow exactly when
        # the operator looks at the fleet (same pattern as _probe_peers)
        fetchers: list[tuple] = []
        for n in list(self.cluster.nodes):
            order.append(n.id)
            if n.id == self.node_id:
                continue
            if self.cluster.is_down(n.id):
                if self.cluster.is_draining(n.id):
                    # a drained node that went away is mid-restart, not
                    # failed: yellow until it rejoins (or the drain mark
                    # ages into a plain down if it never comes back —
                    # probes clear the draining mark only via mark_up)
                    entries[n.id] = {
                        "id": n.id, "uri": n.uri, "state": "DRAINING",
                        "health": {"score": "yellow", "reasons": [
                            "node draining (rolling restart in "
                            "progress)"]}}
                else:
                    entries[n.id] = {
                        "id": n.id, "uri": n.uri, "state": "down",
                        "health": {"score": "red", "reasons": [
                            "node marked down (liveness)"]}}
                continue
            if not n.uri:
                entries[n.id] = {
                    "id": n.id, "uri": "", "state": "unknown",
                    "health": {"score": "yellow",
                               "reasons": ["no known URI"]}}
                continue

            def fetch(node=n):
                draining = self.cluster.is_draining(node.id)
                try:
                    doc = self.client.node_stats(node.uri, timeout)
                    doc.setdefault("id", node.id)
                    doc.setdefault("uri", node.uri)
                    entries[node.id] = doc
                except ClientError as e:
                    if draining:
                        # mid-restart: the drained process has stopped
                        # answering but has NOT failed — yellow, not red
                        entries[node.id] = {
                            "id": node.id, "uri": node.uri,
                            "state": "DRAINING",
                            "health": {"score": "yellow", "reasons": [
                                "node draining (rolling restart in "
                                "progress)"]}}
                    elif e.status == 404:
                        entries[node.id] = {
                            "id": node.id, "uri": node.uri, "state": "up",
                            "health": {"score": "legacy", "reasons": [
                                "peer predates /internal/stats "
                                "(legacy protocol)"]}}
                    else:
                        entries[node.id] = {
                            "id": node.id, "uri": node.uri, "state": "up",
                            "health": {"score": "yellow", "reasons": [
                                f"stats fetch failed: {e}"]}}
                except Exception as e:  # noqa: BLE001 — never fail whole
                    entries[node.id] = {
                        "id": node.id, "uri": node.uri, "state": "up",
                        "health": {"score": "yellow", "reasons": [
                            f"stats fetch failed: "
                            f"{type(e).__name__}: {e}"]}}

            fetchers.append((n, _threads.spawn(fetch)))
        for n, t in fetchers:
            t.join(timeout + 1.0)
            if n.id not in entries:
                entries[n.id] = {
                    "id": n.id, "uri": n.uri, "state": "up",
                    "health": {"score": "yellow", "reasons": [
                        f"stats fetch timed out after {timeout:.1f}s"]}}
        nodes = [entries[i] for i in order]
        counts: dict[str, int] = {}
        worst = "green"
        sev = {"green": 0, "yellow": 1, "red": 2}
        for nd in nodes:
            score = (nd.get("health") or {}).get("score", "unknown")
            counts[score] = counts.get(score, 0) + 1
            # legacy/unknown never degrade the fleet: a peer speaking the
            # old protocol is healthy by every signal it CAN emit
            if score in sev and sev[score] > sev[worst]:
                worst = score
        return {
            "fleet": {"health": worst, "counts": counts, "nodes": nodes},
            "generatedBy": self.node_id,
            "asOf": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }

    def cluster_usage(self) -> dict:
        """The fleet's merged per-principal usage (GET /cluster/usage):
        every live peer's /debug/usage ledger collected concurrently and
        summed per principal, so "who is spending the fleet" is one
        request from any node. Same degradation contract as
        cluster_stats: peers that 404 the route are "legacy" (never an
        error), down peers are skipped without an RPC, transient fetch
        failures mark the node and leave the merge partial-but-honest."""
        from pilosa_tpu.utils import accounting as _accounting

        docs: dict[str, dict] = {}
        nodes: list[dict] = []
        timeout = max(2.0, self.probe_timeout)
        fetchers: list[tuple] = []
        for n in list(self.cluster.nodes):
            if n.id == self.node_id:
                docs[n.id] = self.usage.snapshot()
                nodes.append({"id": n.id, "uri": self.uri, "status": "ok"})
                continue
            if self.cluster.is_down(n.id) or not n.uri:
                nodes.append({"id": n.id, "uri": n.uri or "",
                              "status": "down"})
                continue
            entry = {"id": n.id, "uri": n.uri, "status": "pending"}
            nodes.append(entry)

            def fetch(node=n, entry=entry):
                try:
                    docs[node.id] = self.client.debug_usage(node.uri,
                                                            timeout)
                    entry["status"] = "ok"
                except ClientError as e:
                    entry["status"] = ("legacy" if e.status == 404
                                       else "error")
                except Exception:  # noqa: BLE001 — never fail the merge
                    entry["status"] = "error"

            fetchers.append((entry, _threads.spawn(fetch)))
        for entry, t in fetchers:
            t.join(timeout + 1.0)
            if entry["status"] == "pending":
                entry["status"] = "error"
        merged: dict[str, dict] = {}
        totals = dict.fromkeys(_accounting.FIELDS, 0.0)
        spilled = 0
        for doc in docs.values():
            for p, e in (doc.get("principals") or {}).items():
                acc = merged.setdefault(
                    p, dict.fromkeys(_accounting.FIELDS, 0.0))
                for f in _accounting.FIELDS:
                    acc[f] += float(e.get(f, 0.0))
                acc["nodes"] = acc.get("nodes", 0) + 1
            for f in _accounting.FIELDS:
                totals[f] += float((doc.get("totals") or {}).get(f, 0.0))
            spilled += int(doc.get("spilledPrincipals", 0))
        ordered = dict(sorted(merged.items(),
                              key=lambda kv: (-kv[1]["deviceMs"],
                                              -kv[1]["queries"], kv[0])))
        return {
            "principals": ordered,
            "totals": totals,
            "spilledPrincipals": spilled,
            "nodes": nodes,
            "generatedBy": self.node_id,
            "asOf": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }

    def cluster_hbm(self) -> dict:
        """The fleet's HBM residency maps (GET /cluster/hbm): every live
        peer's /debug/hbm document collected concurrently, with fleet
        byte totals summed across nodes — "what is resident where, and
        how much headroom is left" from any node. Same degradation
        contract as cluster_stats: peers that 404 the route are "legacy"
        (never an error), down peers are skipped without an RPC,
        transient fetch failures leave the merge partial-but-honest."""
        docs: dict[str, dict] = {}
        nodes: list[dict] = []
        timeout = max(2.0, self.probe_timeout)
        fetchers: list[tuple] = []
        for n in list(self.cluster.nodes):
            if n.id == self.node_id:
                docs[n.id] = self.executor.hbm_snapshot()
                nodes.append({"id": n.id, "uri": self.uri, "status": "ok"})
                continue
            if self.cluster.is_down(n.id) or not n.uri:
                nodes.append({"id": n.id, "uri": n.uri or "",
                              "status": "down"})
                continue
            entry = {"id": n.id, "uri": n.uri, "status": "pending"}
            nodes.append(entry)

            def fetch(node=n, entry=entry):
                try:
                    docs[node.id] = self.client.debug_hbm(node.uri, timeout)
                    entry["status"] = "ok"
                except ClientError as e:
                    entry["status"] = ("legacy" if e.status == 404
                                       else "error")
                except Exception:  # noqa: BLE001 — never fail the merge
                    entry["status"] = "error"

            fetchers.append((entry, _threads.spawn(fetch)))
        for entry, t in fetchers:
            t.join(timeout + 1.0)
            if entry["status"] == "pending":
                entry["status"] = "error"
        totals = {"residentBytes": 0, "budgetBytes": 0, "headroomBytes": 0,
                  "planCacheBytes": 0, "entries": 0}
        drift = None
        for doc in docs.values():
            for f in totals:
                totals[f] += int(doc.get(f, 0) or 0)
            d = doc.get("hbmDriftBytes")
            if d is not None:
                drift = (drift or 0) + int(d)
        return {
            "byNode": docs,
            "totals": totals,
            "hbmDriftBytes": drift,
            "nodes": nodes,
            "generatedBy": self.node_id,
            "asOf": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }

    def cluster_events(self, limit: int = 0) -> dict:
        """The merged cluster timeline (GET /cluster/events): every live
        peer's /debug/events feed collected CONCURRENTLY and HLC-sorted
        into one causal event stream (utils/events.py merge_events) —
        "what happened, in order, across the fleet" from any node. Same
        degradation contract as cluster_stats: peers that 404 the route
        are "legacy" (never an error), down peers are skipped without an
        RPC, transient failures leave the merge partial-but-honest. The
        RPCs themselves piggyback HLC stamps, so the collecting node's
        clock catches up to every peer before it sorts."""
        from pilosa_tpu.utils import events as _events

        docs: dict[str, list[dict]] = {}
        nodes: list[dict] = []
        timeout = max(2.0, self.probe_timeout)
        fetchers: list[tuple] = []
        for n in list(self.cluster.nodes):
            if n.id == self.node_id:
                docs[n.id] = self.events.events(0)
                nodes.append({"id": n.id, "uri": self.uri,
                              "status": "ok"})
                continue
            if self.cluster.is_down(n.id) or not n.uri:
                nodes.append({"id": n.id, "uri": n.uri or "",
                              "status": "down"})
                continue
            entry = {"id": n.id, "uri": n.uri, "status": "pending"}
            nodes.append(entry)

            def fetch(node=n, entry=entry):
                try:
                    doc = self.client.debug_events(node.uri, timeout)
                    docs[node.id] = doc.get("events", [])
                    entry["status"] = "ok"
                except ClientError as e:
                    entry["status"] = ("legacy" if e.status == 404
                                       else "error")
                except Exception:  # noqa: BLE001 — never fail the merge
                    entry["status"] = "error"

            fetchers.append((entry, _threads.spawn(fetch)))
        for entry, t in fetchers:
            t.join(timeout + 1.0)
            if entry["status"] == "pending":
                entry["status"] = "error"
        merged = _events.merge_events(docs)
        if limit > 0:
            merged = merged[-limit:]
        return {
            "events": merged,
            "nodes": nodes,
            "generatedBy": self.node_id,
            "asOf": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }

    def cluster_heat(self) -> dict:
        """The fleet's merged fragment heat map (GET /cluster/heat):
        every live peer's /debug/heat document collected concurrently
        and merged per fragment coordinate (utils/heat.py
        merge_heat_docs — replica heat SUMS: two nodes serving a
        fragment's reads make it twice as hot fleet-wide, the signal
        rebalancing ranks by). Same degradation contract as
        cluster_stats/cluster_usage: peers that 404 the route are
        "legacy" (never an error), down peers are skipped without an
        RPC, transient failures leave the merge partial-but-honest.
        Per-node skew/health summaries ride along — the placement
        advisor's node-level input."""
        from pilosa_tpu.utils import heat as _heat

        docs: dict[str, dict] = {}
        nodes: list[dict] = []
        timeout = max(2.0, self.probe_timeout)
        fetchers: list[tuple] = []
        for n in list(self.cluster.nodes):
            if n.id == self.node_id:
                tracker = getattr(self.executor, "heat", None)
                docs[n.id] = (tracker.snapshot(top=0)
                              if tracker is not None else {})
                nodes.append({"id": n.id, "uri": self.uri,
                              "status": "ok"})
                continue
            if self.cluster.is_down(n.id) or not n.uri:
                nodes.append({"id": n.id, "uri": n.uri or "",
                              "status": "down"})
                continue
            entry = {"id": n.id, "uri": n.uri, "status": "pending"}
            nodes.append(entry)

            def fetch(node=n, entry=entry):
                try:
                    docs[node.id] = self.client.debug_heat(node.uri,
                                                           timeout)
                    entry["status"] = "ok"
                except ClientError as e:
                    entry["status"] = ("legacy" if e.status == 404
                                       else "error")
                except Exception:  # noqa: BLE001 — never fail the merge
                    entry["status"] = "error"

            fetchers.append((entry, _threads.spawn(fetch)))
        for entry, t in fetchers:
            t.join(timeout + 1.0)
            if entry["status"] == "pending":
                entry["status"] = "error"
        out = _heat.merge_heat_docs(docs)
        for entry in nodes:
            doc = docs.get(entry["id"])
            if doc:
                # node-level temperature summary: the advisor's
                # per-node hot-shard skew vs health input
                entry["skew"] = doc.get("skew", 1.0)
                entry["hotFragments"] = doc.get("hotFragments", 0)
                entry["trackedFragments"] = doc.get(
                    "trackedFragments", 0)
        out["nodes"] = nodes
        out["generatedBy"] = self.node_id
        out["asOf"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        return out

    # -- anti-entropy scrubber (server.go:430-483; fragment.go:2170) --------

    def _schedule_anti_entropy(self) -> None:
        if self.closed:
            return
        import random as _random
        interval = self.anti_entropy_interval
        if self.anti_entropy_jitter > 0:
            # de-synchronize replicas: every node scrubbing at the same
            # instant turns anti-entropy into a cluster-wide load spike
            interval *= 1.0 + _random.uniform(-self.anti_entropy_jitter,
                                              self.anti_entropy_jitter)
        self._ae_timer = _threads.ctx_timer(max(interval, 0.01),
                                            self._anti_entropy_tick)
        self._ae_timer.start()

    def _anti_entropy_tick(self) -> None:
        try:
            self.scrub_pass()
        except Exception as e:  # noqa: BLE001 — a failed pass (dead peer,
            # injected fault) must never kill the ticker: the next pass
            # retries everything from scratch
            self.logger.printf("anti-entropy: pass failed: %s", e)
        finally:
            self._schedule_anti_entropy()

    def _resize_active(self) -> bool:
        with self._resize_lock:
            return (self.cluster.state == STATE_RESIZING
                    or self.cluster.active_job is not None)

    def scrub_pass(self) -> int:
        """One full scrubber pass: rebuild quarantined fragments from live
        replicas, then walk owned fragments diffing block checksums against
        replicas and repairing divergence via merge_block_majority
        (sync_holder). Skipped while a resize is migrating fragments — the
        two would fight over the same shards (and sync_holder re-checks
        per fragment, since a paced pass can span minutes and a resize can
        start mid-pass). Returns blocks merged."""
        import time as _time
        if self._resize_active():
            return 0
        t0 = _time.monotonic()
        rebuilt = self.repair_quarantined()
        merged = self.sync_holder()
        self._scrub_passes += 1
        self.events.emit("scrub.pass", blocksMerged=merged,
                         fragmentsRebuilt=rebuilt,
                         seconds=round(_time.monotonic() - t0, 3))
        self.stats.count("antiEntropy/passes")
        if merged:
            self.stats.count("antiEntropy/blocksMerged", merged)
        if rebuilt:
            self.stats.count("antiEntropy/fragmentsRebuilt", rebuilt)
        self.stats.gauge("antiEntropy/lastPassSeconds",
                         _time.monotonic() - t0)
        return merged

    def repair_quarantined(self) -> int:
        """Rebuild fragments that open() quarantined (corrupt snapshot →
        emptied) by streaming a replica's full snapshot over the resize
        copy path (RetrieveShardFromURI analog). Block-level anti-entropy
        would converge them too, but a whole-fragment fetch is one RPC
        instead of a block-by-block vote, and it marks the fragment healthy
        immediately. No live replica → left empty; the next pass retries.
        Returns fragments rebuilt."""
        rebuilt = 0
        for iname, fname, vname, shard, frag in \
                list(self.holder.walk_fragments()):
            if not frag.needs_rebuild:
                continue
            for node in self.cluster.shard_nodes(iname, shard):
                if node.id == self.node_id or not node.uri \
                        or self.cluster.is_down(node.id):
                    continue
                try:
                    data = self.client.retrieve_shard(
                        node.uri, iname, fname, vname, shard)
                except ClientError:
                    continue  # replica has no copy / unreachable: next one
                try:
                    # bulk union into the emptied fragment; import_roaring
                    # auto-snapshots, so the rebuild is durable (fresh
                    # integrity trailer included) before we mark it healthy
                    frag.import_roaring(data)
                except (ValueError, OSError) as e:
                    self.logger.printf(
                        "scrubber: rebuild of %s/%s/%s/%d from %s failed: %s",
                        iname, fname, vname, shard, node.id, e)
                    continue
                frag.rebuilt_from = node.id
                rebuilt += 1
                self.logger.printf(
                    "scrubber: rebuilt quarantined fragment %s/%s/%s/%d "
                    "from replica %s (%d bits; corrupt file kept at %s)",
                    iname, fname, vname, shard, node.id, frag.bit_count(),
                    frag.quarantine_path)
                break
        return rebuilt

    def _schedule_cache_flush(self) -> None:
        if self.closed:
            return
        self._cache_flush_timer = _threads.ctx_timer(
            self.cache_flush_interval, self._cache_flush_tick)
        self._cache_flush_timer.start()

    def _cache_flush_tick(self) -> None:
        """Periodic rank-cache persistence (holder.monitorCacheFlush,
        holder.go:483-526)."""
        try:
            self.holder.flush_caches()
        except Exception as e:  # noqa: BLE001 — a failed flush must not kill the ticker
            self.logger.printf("cache flush: %s", e)
        finally:
            self._schedule_cache_flush()

    def sync_holder(self) -> int:
        """One full anti-entropy pass: index column attrs, field row attrs,
        then owned fragments; returns blocks merged (holderSyncer.SyncHolder,
        holder.go:633-853 — syncIndex :726, syncField :772, fragments :821)."""
        merged = 0
        for iname, idx in self.holder.indexes.items():
            merged += self._sync_attrs(
                idx.column_attrs,
                lambda uri, blocks, rng: self.client.column_attr_diff(
                    uri, iname, blocks, rng))
            for fname, field in idx.fields.items():
                merged += self._sync_attrs(
                    field.row_attrs,
                    lambda uri, blocks, rng, fn=fname:
                    self.client.row_attr_diff(uri, iname, fn, blocks, rng))
                for vname, view in field.views.items():
                    for shard in view.shards():
                        if self._resize_active():
                            # a resize started mid-pass (paced passes can
                            # span minutes): stop — merging blocks against
                            # a topology that is migrating under us would
                            # race the fragment copies. The next pass
                            # finishes the walk.
                            return merged
                        if not self.cluster.owns_shard(self.node_id, iname, shard):
                            continue
                        merged += self._sync_fragment(iname, fname, vname, shard)
                        if self.anti_entropy_pace > 0:
                            # paced: a scrub pass shares the node with live
                            # queries — it must trickle, not starve the
                            # fan-out pool / HTTP threads of CPU and peers
                            time.sleep(self.anti_entropy_pace)
        return merged

    # attr blocks per diff request: bounds both the request body and the
    # peer's response working set so one anti-entropy pass streams a large
    # attr store in pages instead of shipping the whole block list at once
    # (the reference pages via attr blocks, attr.go / holder.go:726-820)
    ATTR_SYNC_PAGE = 512

    def _sync_attrs(self, store, diff_fn) -> int:
        """Pull attr blocks that differ from each peer and merge them in
        (attrs replicate to every node; each node pulls on its own pass).

        Paged: local blocks are sent in ATTR_SYNC_PAGE chunks, each with a
        [lo, hi) block range that tiles the whole id space — so peer-only
        blocks between or beyond my chunks are still pulled exactly once."""
        merged = 0

        def make_pages():
            # rebuilt per peer: attrs merged from one peer change the
            # local checksums, and stale pages would make every later
            # peer resend data already merged
            all_blocks = [{"id": b, "checksum": chk.hex()}
                          for b, chk in store.blocks()]
            pages = []
            lo = 0
            for i in range(0, len(all_blocks), self.ATTR_SYNC_PAGE):
                chunk = all_blocks[i:i + self.ATTR_SYNC_PAGE]
                last = i + self.ATTR_SYNC_PAGE >= len(all_blocks)
                hi = None if last else int(chunk[-1]["id"]) + 1
                pages.append((chunk, [lo, hi]))
                lo = hi
            # no local blocks: one full unbounded pull
            return pages or [([], [0, None])]

        for node in self.cluster.nodes:
            if node.id == self.node_id or not node.uri \
                    or self.cluster.is_down(node.id):
                continue
            got = False
            try:
                for chunk, rng in make_pages():
                    attrs = diff_fn(node.uri, chunk, rng)
                    if attrs:
                        store.set_bulk_attrs(attrs.items())
                        got = True
            except ClientError:
                pass  # later pages lost; earlier merges still count
            if got:
                merged += 1
        return merged

    def _sync_fragment(self, iname: str, fname: str, vname: str, shard: int) -> int:
        """Majority-consensus fragment sync (syncBlock, fragment.go:2271-2356):
        fetch each out-of-sync block's pairset from EVERY reachable replica,
        run ONE merge with majorityN = (configured replicas + 1)//2, apply
        local sets AND clears, and push both delta directions to each peer
        (clears ride import_roaring(clear=True)). The threshold comes from
        the CONFIGURED replica count, and whenever any configured replica
        didn't vote (unreachable, marked down, deleted schema) the merge
        falls back to union — so clears only ever happen on the full
        replica set's evidence, and a dropped voter can never let a
        minority outvote the true majority."""
        import numpy as np
        from pilosa_tpu.storage.roaring import Bitmap
        from pilosa_tpu.constants import SHARD_WIDTH
        from pilosa_tpu.utils import failpoints

        failpoints.hit("server.scrub.fragment")
        frag = self.holder.index(iname).field(fname).view(vname).fragment(shard)
        if frag is None:
            return 0
        # collect every reachable replica's block-checksum map up front
        peers = []  # (node, {blk: checksum-hex}, has_fragment)
        for node in self.cluster.shard_nodes(iname, shard):
            if node.id == self.node_id or not node.uri \
                    or self.cluster.is_down(node.id):
                continue
            try:
                remote = {b["id"]: b["checksum"]
                          for b in self.client.fragment_blocks(
                              node.uri, iname, fname, vname, shard)}
                has_fragment = True
            except ClientError as e:
                if e.code != "fragment-not-found":
                    # a missing *index/field* on the peer means it was
                    # deleted there (we missed the broadcast while down):
                    # do NOT push — that would churn RPCs against the
                    # deleted schema every pass. An unreachable peer is
                    # likewise excluded: it can't vote or receive deltas.
                    continue
                # peer owns the shard but has no fragment at all (e.g. it
                # was down for the write that created it): it votes with
                # empty blocks, and the set-deltas we push create the
                # fragment remotely via the import
                remote, has_fragment = {}, False
            peers.append((node, remote, has_fragment))
        if not peers:
            return 0
        # clears need the FULL replica set's evidence: if any configured
        # replica isn't voting (down, unreachable, schema gone), fall back
        # to union (majority_n=1) instead of letting the remaining voters
        # clear bits the absent replica may hold the majority with
        configured = min(self.cluster.replica_n, len(self.cluster.nodes))
        if len(peers) + 1 == configured:
            majority_n = (configured + 1) // 2
        else:
            majority_n = 1
        local_blocks = dict(frag.blocks())
        all_blocks = set(local_blocks)
        for _, remote, _ in peers:
            all_blocks |= set(remote)
        merged = 0
        adopted = False  # any local change -> snapshot for the WAL
        sw = np.uint64(SHARD_WIDTH)
        for blk in sorted(all_blocks):
            if self.anti_entropy_max_blocks > 0 \
                    and merged >= self.anti_entropy_max_blocks:
                break  # bounded pass; the next pass picks up where diffs remain
            lc = local_blocks.get(blk)
            if lc is not None and all(remote.get(blk) == lc.hex()
                                      for _, remote, _ in peers):
                continue
            # every peer votes: absent block (or absent fragment) = empty
            # set; identical checksums mean identical pairsets, so each
            # DISTINCT checksum is fetched once — a peer matching local
            # votes the local copy, peers matching each other share one
            # fetch (each still votes individually)
            by_checksum: dict = {}
            if lc is not None:
                lr, lcols = frag.block_data(blk)
                by_checksum[lc.hex()] = (lr.astype(np.uint64) * sw
                                         + lcols.astype(np.uint64))
            voters, positions = [], []
            fetch_failed = False
            for node, remote, has_fragment in peers:
                if not has_fragment or blk not in remote:
                    pos = np.empty(0, dtype=np.uint64)
                elif remote[blk] in by_checksum:
                    pos = by_checksum[remote[blk]]
                else:
                    try:
                        data = self.client.block_data(node.uri, iname, fname,
                                                      vname, shard, blk)
                    except ClientError as e:
                        if e.status != 404:
                            if majority_n > 1:
                                # a correct majority needs this replica's
                                # vote; skip the block this pass rather
                                # than clear on partial evidence
                                fetch_failed = True
                                break
                            # union mode can't clear, so a flaky peer just
                            # drops out of this block: the remaining peers
                            # still heal (and it gets no delta push)
                            continue
                        data = None  # block raced away: empty vote
                    if data is None:
                        pos = np.empty(0, dtype=np.uint64)
                    else:
                        pos = (np.array(data.get("rowIDs", []),
                                        dtype=np.uint64) * sw
                               + np.array(data.get("columnIDs", []),
                                          dtype=np.uint64))
                        by_checksum[remote[blk]] = pos
                voters.append(node)
                positions.append(pos)
            if fetch_failed:
                continue
            n_sets, n_clears, deltas, durable = frag.merge_block_majority(
                blk, positions, majority_n=majority_n)
            # small adoptions WAL-append inside the merge; only a large
            # adoption asks for the one-snapshot-per-pass fallback
            adopted |= not durable
            merged += 1
            for node, (peer_sets, peer_clears) in zip(voters, deltas):
                for delta, clear in ((peer_sets, False), (peer_clears, True)):
                    if not delta.size:
                        continue
                    payload = Bitmap(delta).to_bytes()
                    try:
                        self.client.import_roaring(
                            node.uri, iname, fname, shard, {vname: payload},
                            remote=True, clear=clear)
                    except ClientError:
                        pass
        if adopted:
            # only LARGE adoptions on WAL-attached fragments land here
            # (durable=False): small ones WAL-appended inside
            # merge_block_majority, volatile fragments owe nothing by
            # contract — one snapshot per sync pass covers the rest
            frag.snapshot()
        return merged
