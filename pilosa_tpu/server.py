"""Server: process lifecycle wiring holder + cluster + executor + transport.

Reference: server.go — functional options (server.go:84-246), Open() sequence
(§3.1 of SURVEY.md), cluster message dispatch (server.go:485-580), anti-
entropy ticker (server.go:430-483). One Server is one "node": a host process
that owns a data dir and drives the local device mesh slice.
"""

from __future__ import annotations

import threading
import uuid
from typing import Optional

from pilosa_tpu.api import API
from pilosa_tpu.executor import Executor
from pilosa_tpu.models import FieldOptions, Holder
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.http_server import Handler, HTTPServer
from pilosa_tpu.parallel.cluster import Cluster, Node, STATE_NORMAL
from pilosa_tpu.parallel.mesh import DeviceRunner
from pilosa_tpu.utils.translate import TranslateStore

import os


class Server:
    """One node of the index. With `cluster_hosts` empty: single-node static
    cluster (the reference's `cluster.disabled` mode, server/config.go:65)."""

    def __init__(self, data_dir: str, host: str = "localhost", port: int = 0,
                 node_id: Optional[str] = None,
                 cluster_hosts: Optional[list[str]] = None,
                 replica_n: int = 1,
                 anti_entropy_interval: float = 0.0,
                 membership_interval: float = 5.0,
                 mesh=None):
        self.data_dir = data_dir
        self.holder = Holder(data_dir)
        self.node_id = node_id or self._load_or_create_id()
        self.cluster = Cluster(
            self.node_id, replica_n=replica_n,
            schema_fn=self._schema_shards,
            topology_path=os.path.join(data_dir, ".topology"))
        self.translate = TranslateStore(os.path.join(data_dir, ".keys"))
        self.runner = DeviceRunner(mesh)
        self.client = InternalClient()
        from pilosa_tpu.utils.logger import Logger
        from pilosa_tpu.utils.stats import new_stats_client
        from pilosa_tpu.utils.tracing import Tracer
        self.stats = new_stats_client()
        self.tracer = Tracer()
        self.logger = Logger()
        from pilosa_tpu.utils.cluster_translate import ClusterTranslator
        self.cluster_translate = ClusterTranslator(self.translate, self.cluster,
                                                   self.client)
        self.executor = Executor(self.holder, runner=self.runner,
                                 translator=self.cluster_translate,
                                 cluster=self.cluster, client=self.client)
        self.executor.stats = self.stats
        self.executor.tracer = self.tracer
        self.api = API(self.holder, self.cluster, executor=self.executor,
                       translate_store=self.cluster_translate)
        self.handler = Handler(self.api, cluster_message_fn=self.receive_message,
                               stats=self.stats)
        self.http = HTTPServer(self.handler, host=host, port=port)
        self.cluster_hosts = cluster_hosts or []
        self.anti_entropy_interval = anti_entropy_interval
        self.membership_interval = membership_interval
        self._ae_timer: Optional[threading.Timer] = None
        self._member_timer: Optional[threading.Timer] = None
        self.closed = False

    # -- lifecycle (server.go Open, §3.1) -----------------------------------

    def _load_or_create_id(self) -> str:
        """Persistent node id (.id file, holder.go:576)."""
        os.makedirs(self.data_dir, exist_ok=True)
        path = os.path.join(self.data_dir, ".id")
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        node_id = str(uuid.uuid4())
        with open(path, "w") as f:
            f.write(node_id)
        return node_id

    def _schema_shards(self) -> dict:
        out: dict = {}
        for iname, idx in self.holder.indexes.items():
            for fname, field in idx.fields.items():
                for vname, view in field.views.items():
                    out.setdefault(iname, {}).setdefault(fname, {})[vname] = view.shards()
        return out

    def open(self) -> "Server":
        self.translate.open()
        self.holder.open()
        self.holder.set_shard_hook(self._on_shard_added)
        self.http.serve_background()
        me = Node(id=self.node_id, uri=self.http.uri,
                  is_coordinator=not self.cluster_hosts)
        if not self.cluster_hosts:
            self.cluster.set_static([me])
            self.cluster.coordinator_id = self.node_id
        else:
            # static multi-node (all hosts known up front; nodes ordered by
            # id). Peers may not be up yet: start with self, converge via
            # refresh_membership once peers answer /internal/nodes.
            self.cluster.set_static([me])
            self.refresh_membership()
            # peers may come up later: keep refreshing until everyone answers
            # (the gossip-convergence analog for static clusters)
            if self.membership_interval > 0:
                self._schedule_membership_refresh()
        self.api.broadcast_fn = self.broadcast
        if self.anti_entropy_interval > 0:
            self._schedule_anti_entropy()
        return self

    def _schedule_membership_refresh(self) -> None:
        if self.closed:
            return
        self._member_timer = threading.Timer(self.membership_interval,
                                             self._membership_tick)
        self._member_timer.daemon = True
        self._member_timer.start()

    def _membership_tick(self) -> None:
        from pilosa_tpu.parallel.cluster import STATE_RESIZING
        try:
            if self.cluster.state != STATE_RESIZING:
                self.refresh_membership()
        finally:
            self._schedule_membership_refresh()

    def refresh_membership(self) -> None:
        """Merge peer node lists from all configured hosts (the static-mode
        analog of a gossip LocalState/MergeRemoteState sync,
        gossip/gossip.go:274-316)."""
        if not self.cluster_hosts:
            return
        me = Node(id=self.node_id, uri=self.http.uri)
        nodes = {self.node_id: me}
        for huri in self.cluster_hosts:
            if huri == self.http.uri:
                continue
            try:
                for nd in self.client.nodes(huri) or []:
                    if nd["id"] not in nodes:
                        nodes[nd["id"]] = Node.from_dict(nd)
            except ClientError:
                pass
        self.cluster.set_static(list(nodes.values()))
        # lowest node id coordinates (deterministic across peers)
        self.cluster.coordinator_id = min(nodes)

    def close(self) -> None:
        self.closed = True
        if self._ae_timer is not None:
            self._ae_timer.cancel()
        if self._member_timer is not None:
            self._member_timer.cancel()
        self.http.close()
        self.holder.close()
        self.translate.close()

    @property
    def uri(self) -> str:
        return self.http.uri

    # -- cluster message dispatch (server.go:485-580) -----------------------

    def receive_message(self, msg: dict) -> None:
        mtype = msg.get("type")
        if mtype == "create-index":
            if self.holder.index(msg["index"]) is None:
                self.holder.create_index(msg["index"], keys=msg.get("keys", False),
                                         track_existence=msg.get("trackExistence", True))
        elif mtype == "delete-index":
            if self.holder.index(msg["index"]) is not None:
                self.holder.delete_index(msg["index"])
        elif mtype == "create-field":
            idx = self.holder.index(msg["index"])
            if idx is not None and idx.field(msg["field"]) is None:
                idx.create_field(msg["field"], FieldOptions(**msg.get("options", {})))
        elif mtype == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None and idx.field(msg["field"]) is not None:
                idx.delete_field(msg["field"])
        elif mtype == "create-shard":
            idx = self.holder.index(msg["index"])
            f = idx.field(msg["field"]) if idx else None
            if f is not None:
                f.add_available_shard(int(msg["shard"]), quiet=True)
        elif mtype == "node-join":
            node = Node.from_dict(msg["node"])
            self.cluster.add_node(node)
        elif mtype == "recalculate-caches":
            self.api.recalculate_caches()
        else:
            raise ValueError(f"unknown cluster message type: {mtype}")

    def _on_shard_added(self, index_name: str, field_name: str, shard: int) -> None:
        """Broadcast newly-available shards so every node's shard set stays
        complete for query fan-out (CreateShardMessage, view.go:208-263)."""
        self.broadcast({"type": "create-shard", "index": index_name,
                        "field": field_name, "shard": shard})

    def broadcast(self, msg: dict) -> None:
        """SendSync: POST to every peer (server.go:582-604)."""
        for node in self.cluster.nodes:
            if node.id == self.node_id or not node.uri:
                continue
            try:
                self.client.send_message(node.uri, msg)
            except ClientError:
                pass  # peers converge via anti-entropy

    # -- anti-entropy (server.go:430-483; fragmentSyncer fragment.go:2170) --

    def _schedule_anti_entropy(self) -> None:
        if self.closed:
            return
        self._ae_timer = threading.Timer(self.anti_entropy_interval,
                                         self._anti_entropy_tick)
        self._ae_timer.daemon = True
        self._ae_timer.start()

    def _anti_entropy_tick(self) -> None:
        try:
            self.sync_holder()
        finally:
            self._schedule_anti_entropy()

    def sync_holder(self) -> int:
        """One full anti-entropy pass over owned fragments; returns number of
        blocks merged (holderSyncer.SyncHolder, holder.go:633-853)."""
        merged = 0
        for iname, idx in self.holder.indexes.items():
            for fname, field in idx.fields.items():
                for vname, view in field.views.items():
                    for shard in view.shards():
                        if not self.cluster.owns_shard(self.node_id, iname, shard):
                            continue
                        merged += self._sync_fragment(iname, fname, vname, shard)
        return merged

    def _sync_fragment(self, iname: str, fname: str, vname: str, shard: int) -> int:
        frag = self.holder.index(iname).field(fname).view(vname).fragment(shard)
        if frag is None:
            return 0
        local_blocks = dict(frag.blocks())
        merged = 0
        for node in self.cluster.shard_nodes(iname, shard):
            if node.id == self.node_id or not node.uri:
                continue
            try:
                remote = {b["id"]: b["checksum"]
                          for b in self.client.fragment_blocks(
                              node.uri, iname, fname, vname, shard)}
            except ClientError:
                continue
            for blk in set(local_blocks) | set(remote):
                lc = local_blocks.get(blk)
                if lc is not None and remote.get(blk) == lc.hex():
                    continue
                try:
                    data = self.client.block_data(node.uri, iname, fname, vname,
                                                  shard, blk)
                except ClientError:
                    continue
                import numpy as np
                sets_r, sets_c = frag.merge_block(
                    blk, np.array(data.get("rowIDs", []), dtype=np.int64),
                    np.array(data.get("columnIDs", []), dtype=np.int64))
                merged += 1
                # push local-only pairs back to the peer
                if sets_r.size:
                    from pilosa_tpu.storage.roaring import Bitmap
                    from pilosa_tpu.constants import SHARD_WIDTH
                    positions = sets_r.astype(np.uint64) * np.uint64(SHARD_WIDTH) \
                        + sets_c.astype(np.uint64)
                    payload = Bitmap(positions).to_bytes()
                    try:
                        self.client.import_roaring(node.uri, iname, fname, shard,
                                                   {vname: payload})
                    except ClientError:
                        pass
        return merged
