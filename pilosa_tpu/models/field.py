"""Field: a typed sub-matrix of an index.

Reference: field.go — five types (set / int / time / mutex / bool,
field.go:53-59), functional options (field.go:90-174), views map, BSI groups
(field.go:1356-1437), time quantum (field.go:637-665), and the
available-shards bitmap persisted to `.available.shards` (field.go:255-317).

BSI encoding: an int field's values are stored in view "bsig_<field>" with
base = min; stored value = value - min; bit depth covers (max - min)
(bsiGroup, field.go:1364). Mutex/bool enforce one-row-per-column by
clear-then-set (mutexVector, fragment.go:2426-2485).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass, field as dc_field
from datetime import datetime
from typing import Iterable, Optional

import numpy as np

from pilosa_tpu.constants import DEFAULT_CACHE_SIZE, SHARD_WIDTH
from pilosa_tpu.models import timequantum
from pilosa_tpu.models.row import Row
from pilosa_tpu.models.view import VIEW_BSI_PREFIX, VIEW_STANDARD, View, view_path
from pilosa_tpu.storage.roaring import Bitmap


class FieldType:
    SET = "set"
    INT = "int"
    TIME = "time"
    MUTEX = "mutex"
    BOOL = "bool"

    ALL = (SET, INT, TIME, MUTEX, BOOL)


@dataclass
class FieldOptions:
    type: str = FieldType.SET
    cache_type: str = "ranked"
    cache_size: int = DEFAULT_CACHE_SIZE
    min: int = 0
    max: int = 0
    time_quantum: str = ""
    keys: bool = False

    def validate(self) -> None:
        from pilosa_tpu.models.cache import _CACHE_TYPES
        if self.type not in FieldType.ALL:
            raise ValueError(f"invalid field type: {self.type}")
        if self.cache_type not in _CACHE_TYPES:
            raise ValueError(f"invalid cache type: {self.cache_type}")
        if self.type == FieldType.INT and self.max < self.min:
            raise ValueError("int field max must be >= min")
        if self.type == FieldType.TIME:
            timequantum.validate_quantum(self.time_quantum)
            if not self.time_quantum:
                raise ValueError("time field requires a time quantum")


class Field:
    def __init__(self, path: str, index: str, name: str,
                 options: Optional[FieldOptions] = None,
                 wal_fsync: Optional[bool] = None):
        self.path = path
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        # [storage] wal-fsync, plumbed down to every fragment of every view
        self.wal_fsync = wal_fsync
        self.views: dict[str, View] = {}
        # two concurrent first-writes must not both construct a View for
        # the same name: each would open (flock) the same fragment files
        self._view_mu = threading.Lock()
        self.available_shards = Bitmap()
        # bumped on every available-shards change: Index.available_shards
        # memoizes its union on the tuple of field versions (the query
        # path calls it per query; re-slicing the union each time was a
        # measurable share of serving CPU on 1-core hosts)
        self.shards_version = 0
        # row attr store (reference: field.go rowAttrStore, boltdb-backed)
        from pilosa_tpu.utils.attrstore import AttrStore
        self.row_attrs = AttrStore(os.path.join(self.path, ".row_attrs.db"))
        # fired on newly-available shards so the server can broadcast a
        # CreateShardMessage (view.go:208-263); (index, field, shard) ->
        self.on_shard_added = None

    # -- derived ------------------------------------------------------------

    @property
    def bsi_view_name(self) -> str:
        return VIEW_BSI_PREFIX + self.name

    @property
    def base(self) -> int:
        """BSI offset: stored value = actual - base (field.go:1364)."""
        return self.options.min

    @property
    def bit_depth(self) -> int:
        span = self.options.max - self.options.min
        return max(span.bit_length(), 1)

    def _track_rank(self) -> bool:
        return (self.options.type in (FieldType.SET, FieldType.MUTEX, FieldType.BOOL, FieldType.TIME)
                and self.options.cache_type != "none")

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Field":
        os.makedirs(self.path, exist_ok=True)
        self.row_attrs.open()
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                self.options = FieldOptions(**json.load(f))
        else:
            self.save_meta()
        avail = os.path.join(self.path, ".available.shards")
        if os.path.exists(avail):
            with open(avail, "rb") as f:
                data = f.read()
            if data:
                self.available_shards = Bitmap.from_bytes(data)
                self.shards_version += 1
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for vname in os.listdir(views_dir):
                self._open_view(vname)
        return self

    def close(self) -> None:
        for v in self.views.values():
            v.close()
        self.views.clear()
        self.row_attrs.close()

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, ".meta"), "w") as f:
            json.dump(asdict(self.options), f)

    def _save_available_shards(self) -> None:
        with open(os.path.join(self.path, ".available.shards"), "wb") as f:
            self.available_shards.write_to(f)

    def _open_view(self, name: str) -> View:
        v = self.views.get(name)
        if v is None:
            with self._view_mu:  # double-checked: creation is rare
                v = self.views.get(name)
                if v is None:
                    v = View(view_path(self.path, name), self.index,
                             self.name, name,
                             track_rank=self._track_rank()
                             and not name.startswith(VIEW_BSI_PREFIX),
                             cache_size=self.options.cache_size,
                             cache_type=self.options.cache_type,
                             wal_fsync=self.wal_fsync).open()
                    self.views[name] = v
        return v

    def view(self, name: str = VIEW_STANDARD) -> Optional[View]:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        return self._open_view(name)

    # -- shard tracking -----------------------------------------------------

    def add_available_shard(self, shard: int, quiet: bool = False) -> None:
        if not self.available_shards.contains(shard):
            self.available_shards.add(shard)
            self.shards_version += 1
            self._save_available_shards()
            if self.on_shard_added is not None and not quiet:
                self.on_shard_added(self.index, self.name, shard)

    def remove_available_shard(self, shard: int) -> None:
        if self.available_shards.contains(shard):
            self.available_shards.remove(shard)
            self.shards_version += 1
            self._save_available_shards()

    def shards(self) -> list[int]:
        return [int(s) for s in self.available_shards.slice()]

    # -- write paths (field.go:803-1214) ------------------------------------

    def _views_for_write(self, timestamp: Optional[datetime]) -> list[str]:
        if self.options.type == FieldType.TIME:
            views = [] if timestamp is None else timequantum.views_by_time(
                VIEW_STANDARD, timestamp, self.options.time_quantum)
            return [VIEW_STANDARD] + views
        if timestamp is not None:
            if self.options.type == FieldType.SET:
                raise ValueError("timestamp given on non-time field")
            raise ValueError("timestamp given on non-time field")
        return [VIEW_STANDARD]

    def set_bit(self, row_id: int, column: int,
                timestamp: Optional[datetime] = None) -> bool:
        """SetBit (field.go:803): writes the standard view plus one time view
        per quantum unit; mutex/bool clear other rows first."""
        if self.options.type == FieldType.INT:
            raise ValueError(f"field {self.name} is an int field; use set_value")
        if self.options.type == FieldType.BOOL and row_id not in (0, 1):
            raise ValueError("bool field rows must be 0 (false) or 1 (true)")
        if self.options.type in (FieldType.MUTEX, FieldType.BOOL):
            self._clear_other_rows(row_id, column)
        changed = False
        for vname in self._views_for_write(timestamp):
            changed |= self.create_view_if_not_exists(vname).set_bit(row_id, column)
        self.add_available_shard(column // SHARD_WIDTH)
        return changed

    def clear_bit(self, row_id: int, column: int) -> bool:
        changed = False
        for v in self.views.values():
            if not v.name.startswith(VIEW_BSI_PREFIX):
                changed |= v.clear_bit(row_id, column)
        return changed

    def _clear_other_rows(self, row_id: int, column: int) -> None:
        """Mutex semantics: at most one row set per column (mutexVector
        clear-then-set, fragment.go:387-407). Uses the column probe
        (rows_for_column — fragment.go:2446-2455 rowsVector.Get) so cost is
        independent of how many rows the fragment holds."""
        shard = column // SHARD_WIDTH
        for v in self.views.values():
            if v.name.startswith(VIEW_BSI_PREFIX):
                continue
            frag = v.fragment(shard)
            if frag is None:
                continue
            for rid in frag.rows_for_column(column):
                if rid != row_id:
                    v.clear_bit(rid, column)

    def set_value(self, column: int, value: int) -> bool:
        """SetValue (field.go:951): store value - base in the BSI view,
        auto-expanding max like the reference does on import."""
        if self.options.type != FieldType.INT:
            raise ValueError(f"field {self.name} is not an int field")
        if value < self.options.min or value > self.options.max:
            raise ValueError(
                f"value {value} out of range [{self.options.min}, {self.options.max}]")
        v = self.create_view_if_not_exists(self.bsi_view_name)
        shard = column // SHARD_WIDTH
        frag = v.create_fragment_if_not_exists(shard)
        changed = frag.set_value(column % SHARD_WIDTH, self.bit_depth, value - self.base)
        self.add_available_shard(shard)
        return changed

    def value(self, column: int) -> tuple[int, bool]:
        v = self.views.get(self.bsi_view_name)
        if v is None:
            return 0, False
        frag = v.fragment(column // SHARD_WIDTH)
        if frag is None:
            return 0, False
        raw, ok = frag.value(column % SHARD_WIDTH, self.bit_depth)
        return (raw + self.base, True) if ok else (0, False)

    def clear_value(self, column: int) -> bool:
        v = self.views.get(self.bsi_view_name)
        if v is None:
            return False
        frag = v.fragment(column // SHARD_WIDTH)
        if frag is None:
            return False
        return frag.clear_value(column % SHARD_WIDTH, self.bit_depth)

    # -- bulk import (field.go:1058-1214) -----------------------------------

    def import_bits(self, row_ids: Iterable[int], columns: Iterable[int],
                    timestamps: Optional[Iterable[Optional[datetime]]] = None,
                    clear: bool = False) -> None:
        """Bulk import; clear=True removes the bits instead (the import
        endpoint's clear mode, http/handler.go:1002-1004)."""
        rows = list(row_ids)
        cols = list(columns)
        tss = list(timestamps) if timestamps is not None else [None] * len(rows)
        if not (len(rows) == len(cols) == len(tss)):
            raise ValueError("row/column/timestamp length mismatch")
        # group (view, shard) -> (rows, cols)
        groups: dict[tuple[str, int], tuple[list[int], list[int]]] = {}
        for r, c, ts in zip(rows, cols, tss):
            for vname in self._views_for_write(ts if self.options.type == FieldType.TIME else None):
                key = (vname, c // SHARD_WIDTH)
                g = groups.setdefault(key, ([], []))
                g[0].append(r)
                g[1].append(c % SHARD_WIDTH)
        mutex = self.options.type in (FieldType.MUTEX, FieldType.BOOL)
        for (vname, shard), (grows, gcols) in groups.items():
            view = self.create_view_if_not_exists(vname)
            frag = view.create_fragment_if_not_exists(shard)
            if clear:
                frag.bulk_clear(grows, gcols)
            elif mutex:
                frag.bulk_import_mutex(grows, gcols)
            else:
                frag.bulk_import(grows, gcols)
            view.refresh_rank_cache(shard)
            self.add_available_shard(shard)

    def import_rows_frozen(self, row_ids: np.ndarray,
                           columns: np.ndarray) -> None:
        """BASELINE-scale set-field bulk load through the frozen store:
        shard split and bit positions are pure numpy, each shard's
        fragment freezes in one shot, and rank caches build from the flat
        key layout instead of a per-row Python walk (see
        fragment.import_frozen / view.load_frozen_fragment). Standard
        view only — time/mutex/bool semantics need the mutating paths."""
        if self.options.type != FieldType.SET or self.options.time_quantum:
            raise ValueError(
                "import_rows_frozen supports plain set fields only")
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(columns, dtype=np.uint64)
        if rows.size != cols.size:
            raise ValueError("row/column length mismatch")
        w = np.uint64(SHARD_WIDTH)
        shards = (cols // w).astype(np.int64)
        positions = rows * w + cols % w
        order = np.lexsort((positions, shards))
        shards, positions = shards[order], positions[order]
        boundaries = np.flatnonzero(np.diff(shards)) + 1
        view = self.create_view_if_not_exists(VIEW_STANDARD)
        for g_shards, g_pos in zip(np.split(shards, boundaries),
                                   np.split(positions, boundaries)):
            shard = int(g_shards[0])
            view.load_frozen_fragment(shard, g_pos)
            self.add_available_shard(shard)

    def _values_shard_groups(self, columns: Iterable[int],
                             values: Iterable[int]):
        """Validate, dedup (LAST write per column wins — importValue
        semantics, fragment.go:1624: applying both duplicates would leave
        the bitwise OR of the values, a value never written) and split a
        BSI import into per-shard (shard, cols, vals) groups. Fully
        vectorized: a Python-loop grouping walks every (col, val) pair —
        at the BASELINE 1B-column scale that alone is hours."""
        from pilosa_tpu.storage.fragment import as_array

        cols = as_array(columns, np.uint64)
        vals = as_array(values, np.int64)
        if cols.size != vals.size:
            raise ValueError("column/value length mismatch")
        if vals.size and (int(vals.min()) < self.options.min
                          or int(vals.max()) > self.options.max):
            bad = vals[(vals < self.options.min) | (vals > self.options.max)]
            raise ValueError(f"value {int(bad[0])} out of range")
        if cols.size == 0:
            return []
        order = np.argsort(cols, kind="stable")
        cols, vals = cols[order], vals[order]
        if cols.size > 1:
            # after a stable sort the last duplicate is last in input order
            last = np.concatenate([cols[1:] != cols[:-1], [True]])
            cols, vals = cols[last], vals[last]
        shards = (cols // np.uint64(SHARD_WIDTH)).astype(np.int64)
        boundaries = np.flatnonzero(np.diff(shards)) + 1
        # eager list, not a generator: callers create the BSI view AFTER
        # this validates, so a rejected import leaves no ghost empty view
        return [(int(gcols[0] // np.uint64(SHARD_WIDTH)),
                 gcols % np.uint64(SHARD_WIDTH), gvals - self.base)
                for gcols, gvals in zip(np.split(cols, boundaries),
                                        np.split(vals, boundaries))]

    def import_values(self, columns: Iterable[int], values: Iterable[int]) -> None:
        """BSI bulk import through the mutating path (WAL-detached bulk
        merge + snapshot per touched fragment)."""
        groups = self._values_shard_groups(columns, values)
        view = self.create_view_if_not_exists(self.bsi_view_name)
        for shard, scols, svals in groups:
            frag = view.create_fragment_if_not_exists(shard)
            frag.bulk_import_values(scols, svals, self.bit_depth)
            self.add_available_shard(shard)

    def import_values_frozen(self, columns: Iterable[int],
                             values: Iterable[int]) -> None:
        """BASELINE-scale BSI bulk load through the frozen store — the
        deferred-durability analog of import_rows_frozen for INT fields:
        plane masks become one sorted position array per shard and each
        (empty) fragment freezes in one shot, skipping the per-container
        merge loops and per-batch snapshots of the mutating path
        (importValue, fragment.go:1624-1658 at 1B-column scale). Volatile
        like import_frozen: durability is opt-in via snapshot()."""
        if self.options.type != FieldType.INT:
            raise ValueError("import_values_frozen supports int fields only")
        groups = self._values_shard_groups(columns, values)
        view = self.create_view_if_not_exists(self.bsi_view_name)
        depth = self.bit_depth
        sw = np.uint64(SHARD_WIDTH)
        for shard, scols, svals in groups:
            # plane ranges are disjoint and scols is sorted-unique, so each
            # plane slice is already sorted — concatenation in plane order
            # IS the sorted position array (presorted skips a re-sort of
            # depth x |cols| positions per shard)
            planes = [scols[((svals >> i) & 1).astype(bool)]
                      + np.uint64(i) * sw for i in range(depth)]
            planes.append(scols + np.uint64(depth) * sw)  # not-null row
            view.load_frozen_fragment(shard, np.concatenate(planes),
                                      presorted=True)
            self.add_available_shard(shard)

    # -- reads --------------------------------------------------------------

    def row(self, row_id: int, view: str = VIEW_STANDARD) -> Row:
        """Whole-field row across shards (Field.Row, field.go:791)."""
        v = self.views.get(view)
        out = Row()
        if v is None:
            return out
        for shard in v.shards():
            frag = v.fragment(shard)
            cols = frag.row_columns(row_id)
            if cols.size:
                out.segments[shard] = cols.astype(np.uint64) + np.uint64(shard * SHARD_WIDTH)
        return out

    def row_time(self, row_id: int, start: datetime, end: datetime) -> Row:
        """Union of time views covering [start, end) (RowTime field.go:666)."""
        if self.options.type != FieldType.TIME:
            raise ValueError("row_time on non-time field")
        out = Row()
        for vname in timequantum.views_by_time_range(
                VIEW_STANDARD, start, end, self.options.time_quantum):
            out = out.union(self.row(row_id, view=vname))
        return out
