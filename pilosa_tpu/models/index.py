"""Index: a namespace of fields sharing one column space.

Reference: index.go — owns fields, the existence field `_exists`
(index.go:167-175; used by Not() and existence-aware Count), meta persistence
(index.go:177-218) and AvailableShards = union of field shard bitmaps
(index.go:238).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Optional

from pilosa_tpu.constants import EXISTENCE_FIELD_NAME
from pilosa_tpu.models.field import Field, FieldOptions, FieldType
from pilosa_tpu.storage.roaring import Bitmap

_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")


def validate_name(name: str) -> None:
    """Index/field naming rule (pilosa.go validateName)."""
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid name: {name!r}")


class Index:
    def __init__(self, path: str, name: str, keys: bool = False,
                 track_existence: bool = True,
                 wal_fsync: Optional[bool] = None):
        validate_name(name)
        self.path = path
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        # [storage] wal-fsync, plumbed down to every field/view/fragment
        self.wal_fsync = wal_fsync
        self.fields: dict[str, Field] = {}
        # guards concurrent field creation (two racing first-imports must
        # not both construct a Field: duplicate stores + fragment flocks)
        import threading
        self._field_mu = threading.Lock()
        # (per-field shard versions, union bitmap) — see available_shards
        self._avail_shards_cache = None
        # bumped on field create/delete: keys the memo across schema
        # changes (a recreated field's shards_version restarts at 0)
        self._schema_epoch = 0
        self.shard_hook = None
        # column attr store (reference: index.go ColumnAttrStore)
        from pilosa_tpu.utils.attrstore import AttrStore
        self.column_attrs = AttrStore(os.path.join(self.path, ".col_attrs.db"))

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Index":
        os.makedirs(self.path, exist_ok=True)
        self.column_attrs.open()
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                data = json.load(f)
            self.keys = data.get("keys", False)
            self.track_existence = data.get("trackExistence", True)
        else:
            self.save_meta()
        for fname in sorted(os.listdir(self.path)):
            fpath = os.path.join(self.path, fname)
            if os.path.isdir(fpath):
                self.fields[fname] = Field(fpath, self.name, fname,
                                           wal_fsync=self.wal_fsync).open()
        if self.track_existence and EXISTENCE_FIELD_NAME not in self.fields:
            self._create_existence_field()
        return self

    def close(self) -> None:
        for f in self.fields.values():
            f.close()
        self.fields.clear()
        self.column_attrs.close()

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, ".meta"), "w") as f:
            json.dump({"keys": self.keys, "trackExistence": self.track_existence}, f)

    def _create_existence_field(self) -> Field:
        opts = FieldOptions(type=FieldType.SET, cache_type="none")
        f = Field(os.path.join(self.path, EXISTENCE_FIELD_NAME),
                  self.name, EXISTENCE_FIELD_NAME, opts,
                  wal_fsync=self.wal_fsync)
        f.open()
        self.fields[EXISTENCE_FIELD_NAME] = f
        return f

    # -- fields -------------------------------------------------------------

    def field(self, name: str) -> Optional[Field]:
        return self.fields.get(name)

    def existence_field(self) -> Optional[Field]:
        return self.fields.get(EXISTENCE_FIELD_NAME)

    def create_field(self, name: str, options: Optional[FieldOptions] = None) -> Field:
        validate_name(name)
        options = options or FieldOptions()
        options.validate()
        with self._field_mu:
            if name in self.fields:
                raise ValueError(f"field already exists: {name}")
            f = Field(os.path.join(self.path, name), self.name, name, options,
                      wal_fsync=self.wal_fsync)
            f.save_meta()
            f.open()
            f.on_shard_added = self.shard_hook
            self.fields[name] = f
            self._schema_epoch += 1
            return f

    def set_shard_hook(self, fn) -> None:
        self.shard_hook = fn
        for f in self.fields.values():
            f.on_shard_added = fn

    def create_field_if_not_exists(self, name: str,
                                   options: Optional[FieldOptions] = None) -> Field:
        existing = self.fields.get(name)
        if existing is not None:
            return existing
        try:
            return self.create_field(name, options)
        except ValueError:
            # lost a creation race: the winner's field is the field
            existing = self.fields.get(name)
            if existing is not None:
                return existing
            raise

    def delete_field(self, name: str) -> None:
        f = self.fields.pop(name, None)
        if f is None:
            raise KeyError(f"field not found: {name}")
        self._schema_epoch += 1
        f.close()
        shutil.rmtree(f.path, ignore_errors=True)

    # -- shards -------------------------------------------------------------

    def available_shards(self) -> Bitmap:
        """Union of per-field shard bitmaps (index.go:238), memoized on
        the per-field shard versions — the query fan-out calls this per
        query, and rebuilding the union per call was a measurable share
        of serving CPU on small hosts. Callers must not mutate it."""
        # list(): atomic snapshot under the GIL — generator iteration
        # would race a concurrent create_field resizing the dict. The
        # schema epoch guards delete+recreate: a fresh Field restarts
        # shards_version at 0, which would otherwise collide with the
        # old field's first version and serve a stale shard list.
        fields = list(self.fields.items())
        key = (self._schema_epoch,
               tuple((name, f.shards_version) for name, f in fields))
        cached = self._avail_shards_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        out = Bitmap()
        for _, f in fields:
            out = out.union(f.available_shards)
        if not out.any():
            out.add(0)  # queries always cover at least shard 0
        self._avail_shards_cache = (key, out, sorted(
            int(s) for s in out.slice()))
        return out

    def available_shards_list(self) -> list[int]:
        """Sorted shard ids, memoized with available_shards — what the
        executor's per-query fan-out actually consumes."""
        self.available_shards()
        return self._avail_shards_cache[2]

    # -- existence tracking (writes mark columns live; Not()/existence
    #    queries read it — index.go:167, executor.go:1478) ------------------

    def mark_exists(self, column: int) -> None:
        if not self.track_existence:
            return
        ef = self.existence_field()
        if ef is not None:
            ef.set_bit(0, column)

    def schema_dict(self) -> dict:
        return {
            "name": self.name,
            "options": {"keys": self.keys, "trackExistence": self.track_existence},
            "fields": [
                {"name": f.name, "options": {
                    "type": f.options.type,
                    "cacheType": f.options.cache_type,
                    "cacheSize": f.options.cache_size,
                    "min": f.options.min,
                    "max": f.options.max,
                    "timeQuantum": f.options.time_quantum,
                    "keys": f.options.keys,
                }}
                for name, f in sorted(self.fields.items())
                if name != EXISTENCE_FIELD_NAME
            ],
        }
