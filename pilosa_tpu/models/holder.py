"""Holder: the root container of all indexes on a node.

Reference: holder.go — open/close directory walk (holder.go:132-192), schema
(holder.go:267), create/delete index. The TPU build keeps the same on-disk
tree: <data_dir>/<index>/<field>/views/<view>/fragments/<shard>.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

from pilosa_tpu.models.index import Index, validate_name


class Holder:
    def __init__(self, path: str, wal_fsync=None):
        self.path = path
        self.indexes: dict[str, Index] = {}
        self.opened = False
        self.shard_hook = None
        # [storage] wal-fsync (None = default off; PILOSA_TPU_WAL_FSYNC env
        # overrides at the fragment): plumbed down the whole tree
        self.wal_fsync = wal_fsync

    def open(self) -> "Holder":
        os.makedirs(self.path, exist_ok=True)
        for name in sorted(os.listdir(self.path)):
            ipath = os.path.join(self.path, name)
            if os.path.isdir(ipath) and not name.startswith("."):
                self.indexes[name] = Index(ipath, name,
                                           wal_fsync=self.wal_fsync).open()
        self.opened = True
        return self

    def close(self) -> None:
        for idx in self.indexes.values():
            idx.close()
        self.indexes.clear()
        self.opened = False

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True) -> Index:
        validate_name(name)
        if name in self.indexes:
            raise ValueError(f"index already exists: {name}")
        idx = Index(os.path.join(self.path, name), name, keys=keys,
                    track_existence=track_existence,
                    wal_fsync=self.wal_fsync)
        idx.save_meta()
        idx.open()
        if self.shard_hook is not None:
            idx.set_shard_hook(self.shard_hook)
        self.indexes[name] = idx
        return idx

    def set_shard_hook(self, fn) -> None:
        """Install the shard-creation broadcast hook on the whole tree."""
        self.shard_hook = fn
        for idx in self.indexes.values():
            idx.set_shard_hook(fn)

    def create_index_if_not_exists(self, name: str, **kw) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        return self.create_index(name, **kw)

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index not found: {name}")
        idx.close()
        shutil.rmtree(idx.path, ignore_errors=True)

    def flush_caches(self) -> int:
        """Persist all TopN rank caches in place — the cache-flush ticker's
        work (holder.monitorCacheFlush, holder.go:483-526). Returns caches
        written."""
        n = 0
        # snapshot the tree: this runs on the flush ticker thread while HTTP
        # threads may be creating indexes/fields/views concurrently
        for idx in list(self.indexes.values()):
            for f in list(idx.fields.values()):
                for view in list(f.views.values()):
                    n += view.flush_caches()
        return n

    def schema(self) -> list[dict]:
        return [idx.schema_dict() for _, idx in sorted(self.indexes.items())]

    def walk_fragments(self):
        """Yield every (index_name, field_name, view_name, shard, fragment)
        under a point-in-time snapshot of the tree (list() copies: handler
        threads create schema objects concurrently)."""
        for iname, idx in list(self.indexes.items()):
            for fname, fld in list(idx.fields.items()):
                for vname, view in list(fld.views.items()):
                    for shard, frag in list(view.fragments.items()):
                        yield iname, fname, vname, shard, frag

    def damaged_fragments(self) -> list[dict]:
        """Corruption-recovery report for /debug/vars and the scrubber:
        fragments that were quarantined at open (awaiting or done with a
        replica rebuild) or had a torn WAL tail truncated."""
        out = []
        for iname, fname, vname, shard, frag in self.walk_fragments():
            if frag.quarantine_path is None \
                    and not frag.wal_truncated_bytes:
                continue
            out.append({
                "index": iname, "field": fname, "view": vname,
                "shard": shard,
                "quarantinePath": frag.quarantine_path,
                "corruptionError": frag.corruption_error,
                "rebuiltFrom": frag.rebuilt_from,
                "needsRebuild": frag.needs_rebuild,
                "walTruncatedBytes": frag.wal_truncated_bytes,
                "walTruncateError": frag.wal_truncate_error,
            })
        return out
