"""Holder: the root container of all indexes on a node.

Reference: holder.go — open/close directory walk (holder.go:132-192), schema
(holder.go:267), create/delete index. The TPU build keeps the same on-disk
tree: <data_dir>/<index>/<field>/views/<view>/fragments/<shard>.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

from pilosa_tpu.models.index import Index, validate_name


class Holder:
    def __init__(self, path: str):
        self.path = path
        self.indexes: dict[str, Index] = {}
        self.opened = False
        self.shard_hook = None

    def open(self) -> "Holder":
        os.makedirs(self.path, exist_ok=True)
        for name in sorted(os.listdir(self.path)):
            ipath = os.path.join(self.path, name)
            if os.path.isdir(ipath) and not name.startswith("."):
                self.indexes[name] = Index(ipath, name).open()
        self.opened = True
        return self

    def close(self) -> None:
        for idx in self.indexes.values():
            idx.close()
        self.indexes.clear()
        self.opened = False

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True) -> Index:
        validate_name(name)
        if name in self.indexes:
            raise ValueError(f"index already exists: {name}")
        idx = Index(os.path.join(self.path, name), name, keys=keys,
                    track_existence=track_existence)
        idx.save_meta()
        idx.open()
        if self.shard_hook is not None:
            idx.set_shard_hook(self.shard_hook)
        self.indexes[name] = idx
        return idx

    def set_shard_hook(self, fn) -> None:
        """Install the shard-creation broadcast hook on the whole tree."""
        self.shard_hook = fn
        for idx in self.indexes.values():
            idx.set_shard_hook(fn)

    def create_index_if_not_exists(self, name: str, **kw) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        return self.create_index(name, **kw)

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index not found: {name}")
        idx.close()
        shutil.rmtree(idx.path, ignore_errors=True)

    def flush_caches(self) -> int:
        """Persist all TopN rank caches in place — the cache-flush ticker's
        work (holder.monitorCacheFlush, holder.go:483-526). Returns caches
        written."""
        n = 0
        # snapshot the tree: this runs on the flush ticker thread while HTTP
        # threads may be creating indexes/fields/views concurrently
        for idx in list(self.indexes.values()):
            for f in list(idx.fields.values()):
                for view in list(f.views.values()):
                    n += view.flush_caches()
        return n

    def schema(self) -> list[dict]:
        return [idx.schema_dict() for _, idx in sorted(self.indexes.items())]
