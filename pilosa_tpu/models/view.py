"""View: a named sub-bitmap of a field.

Reference: view.go — "standard" (view.go:34), time views "standard_YYYYMMDDHH"
(time.go:63-215) and BSI views "bsig_<field>" (view.go:36); a view owns
fragments by shard and creates them on demand (view.go:208-263).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.models.cache import (
    CACHE_TYPE_NONE,
    CACHE_TYPE_RANKED,
    RankCache,
    load_cache,
    make_cache,
)
from pilosa_tpu.storage.fragment import Fragment

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


def view_path(field_path: str, name: str) -> str:
    return os.path.join(field_path, "views", name)


class View:
    def __init__(self, path: str, index: str, field: str, name: str,
                 track_rank: bool = False, cache_size: int = 50000,
                 cache_type: str = CACHE_TYPE_RANKED,
                 wal_fsync: Optional[bool] = None):
        self.path = path
        self.index = index
        self.field = field
        self.name = name
        # [storage] wal-fsync, plumbed holder->index->field->view->fragment
        # (None = fragment default; PILOSA_TPU_WAL_FSYNC env overrides)
        self.wal_fsync = wal_fsync
        self.fragments: dict[int, Fragment] = {}
        # serializes fragment creation: two HTTP threads racing
        # create_fragment_if_not_exists would both construct + open() the
        # same file, and the loser trips its sibling's flock
        self._frag_mu = threading.Lock()
        self.track_rank = track_rank and cache_type != CACHE_TYPE_NONE
        self.cache_size = cache_size
        self.cache_type = cache_type
        self.rank_caches: dict[int, RankCache] = {}

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "View":
        frag_dir = os.path.join(self.path, "fragments")
        if os.path.isdir(frag_dir):
            for fname in os.listdir(frag_dir):
                if fname.endswith((".cache", ".snapshotting", ".tmp",
                                   ".lock")):
                    continue
                try:
                    shard = int(fname)
                except ValueError:
                    continue
                self._open_fragment(shard)
        return self

    def flush_caches(self) -> int:
        """Persist rank caches without closing (fragment.FlushCache,
        fragment.go:1796-1821, driven by holder.monitorCacheFlush). Returns
        caches written."""
        n = 0
        for shard, frag in list(self.fragments.items()):
            cache = self.rank_caches.get(shard)
            if cache is not None:
                cache.save(frag.path + ".cache")
                n += 1
        return n

    def close(self) -> None:
        self.flush_caches()
        for frag in self.fragments.values():
            frag.close()
        self.fragments.clear()
        self.rank_caches.clear()

    def _open_fragment(self, shard: int) -> Fragment:
        frag = Fragment(
            os.path.join(self.path, "fragments", str(shard)),
            self.index, self.field, self.name, shard,
            wal_fsync=self.wal_fsync,
        ).open()
        self.fragments[shard] = frag
        if self.track_rank:
            cache_path = frag.path + ".cache"
            if os.path.exists(cache_path):
                self.rank_caches[shard] = load_cache(cache_path)
            else:
                cache = make_cache(self.cache_type, self.cache_size)
                cache.bulk_add((rid, frag.row_count(rid)) for rid in frag.row_ids())
                self.rank_caches[shard] = cache
        return frag

    # -- fragment routing ---------------------------------------------------

    def fragment(self, shard: int) -> Optional[Fragment]:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        frag = self.fragments.get(shard)
        if frag is None:
            with self._frag_mu:  # double-checked: creation is rare
                frag = self.fragments.get(shard)
                if frag is None:
                    frag = self._open_fragment(shard)
        return frag

    def shards(self) -> list[int]:
        return sorted(self.fragments)

    def delete_fragment(self, shard: int) -> None:
        """Drop a fragment and its files — post-resize GC of shards this node
        no longer owns (holderCleaner, holder.go:855-906)."""
        frag = self.fragments.pop(shard, None)
        if frag is None:
            return
        frag.close()
        for p in (frag.path, frag.path + ".cache", frag.path + ".snapshotting",
                  frag.path + ".lock"):
            if os.path.exists(p):
                os.remove(p)
        self.rank_caches.pop(shard, None)

    # -- writes (global column space; view.setBit view.go:309) --------------

    def set_bit(self, row_id: int, column: int) -> bool:
        shard = column // SHARD_WIDTH
        frag = self.create_fragment_if_not_exists(shard)
        changed = frag.set_bit(row_id, column % SHARD_WIDTH)
        if changed:
            self._update_rank(shard, frag, row_id)
        return changed

    def clear_bit(self, row_id: int, column: int) -> bool:
        shard = column // SHARD_WIDTH
        frag = self.fragments.get(shard)
        if frag is None:
            return False
        changed = frag.clear_bit(row_id, column % SHARD_WIDTH)
        if changed:
            self._update_rank(shard, frag, row_id)
        return changed

    def _update_rank(self, shard: int, frag: Fragment, row_id: int) -> None:
        cache = self.rank_caches.get(shard)
        if cache is not None:
            # row_count walks at most 16 container keys — cheap enough to
            # keep cached counts exact (the reference recounts via rowCache,
            # fragment.go:435-440). The count-read + cache-store pair runs
            # under the fragment write lock: two racing writers could
            # otherwise store their reads out of order and pin a stale
            # count until the row's next write.
            with frag.mu:
                cache.add(row_id, frag.row_count(row_id))

    def refresh_rank_cache(self, shard: int) -> None:
        if not self.track_rank:
            return
        frag = self.fragments.get(shard)
        if frag is None:
            return
        cache = make_cache(self.cache_type, self.cache_size)
        cache.bulk_add((rid, frag.row_count(rid)) for rid in frag.row_ids())
        self.rank_caches[shard] = cache

    def load_frozen_fragment(self, shard: int, positions: np.ndarray,
                             presorted: bool = False) -> Fragment:
        """Bulk-load one shard's fragment from shard-local bit positions
        via the frozen store (fragment.import_frozen), building the rank
        cache VECTORIZED: per-row counts come from the frozen key layout
        and only the top cache_size rows enter the cache — equivalent to
        the reference's add-then-prune (cache.go Invalidate keeps the top
        cache_size by rank), but without iterating a billion rows in
        Python."""
        frag = self.create_fragment_if_not_exists(shard)
        frag.import_frozen(positions, presorted=presorted)
        if self.track_rank:
            from pilosa_tpu.constants import CONTAINERS_PER_SHARD

            cache = make_cache(self.cache_type, self.cache_size)
            uids, sums = frag._frozen_row_arrays(frag.storage.containers,
                                                 CONTAINERS_PER_SHARD)
            k = getattr(cache, "cache_size", self.cache_size)
            if uids.size > k:
                top = np.argpartition(-sums, k - 1)[:k]
                uids, sums = uids[top], sums[top]
            cache.bulk_add(zip(uids.tolist(), sums.tolist()))
            self.rank_caches[shard] = cache
        return frag
