"""Data model: the schema tree Holder -> Index -> Field -> View -> Fragment.

Mirrors the reference's domain objects (holder.go, index.go, field.go,
view.go, row.go) with the TPU split: this layer is host-side metadata +
storage routing; all query compute flows through the executor's device
kernels over dense row materializations.
"""

from pilosa_tpu.models.field import Field, FieldOptions, FieldType  # noqa: F401
from pilosa_tpu.models.holder import Holder  # noqa: F401
from pilosa_tpu.models.index import Index  # noqa: F401
from pilosa_tpu.models.row import Row  # noqa: F401
