"""Time quantum: YMDH time-view generation and minimal range covers.

Reference: time.go. A time field with quantum e.g. "YMD" writes each bit into
one view per unit (standard_2018, standard_201801, standard_20180102,
time.go:63-85 viewsByTime/viewByTimeUnit), and a Range query decomposes
[start, end) into the minimal set of views that exactly covers it
(viewsByTimeRange, time.go:86-130).
"""

from __future__ import annotations

from datetime import datetime, timedelta

VALID_UNITS = "YMDH"

# view-name timestamp layouts per unit (viewByTimeUnit time.go:176-215)
_FORMATS = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def validate_quantum(q: str) -> None:
    """A quantum is an ordered subset of "YMDH" (TimeQuantum.Valid,
    time.go:36-60)."""
    if q and (not all(c in VALID_UNITS for c in q)
              or [c for c in VALID_UNITS if c in q] != list(q)):
        raise ValueError(f"invalid time quantum: {q!r}")


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    return f"{name}_{t.strftime(_FORMATS[unit])}"


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """All views a timestamped write lands in — one per quantum unit."""
    return [view_by_time_unit(name, t, u) for u in quantum]


def _floor(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "M":
        return t.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "D":
        return t.replace(hour=0, minute=0, second=0, microsecond=0)
    return t.replace(minute=0, second=0, microsecond=0)


def _next(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(year=t.year + 1)
    if unit == "M":
        return t.replace(year=t.year + (t.month == 12), month=t.month % 12 + 1)
    if unit == "D":
        return t + timedelta(days=1)
    return t + timedelta(hours=1)


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal set of views exactly covering [start, end): greedily use the
    largest quantum unit on aligned interior spans and recurse into smaller
    units at the ragged boundaries (viewsByTimeRange, time.go:86-130)."""
    validate_quantum(quantum)
    if not quantum or start >= end:
        return []

    def cover(lo: datetime, hi: datetime, units: str) -> list[str]:
        if lo >= hi or not units:
            return []
        u, rest = units[0], units[1:]
        first = _floor(lo, u)
        if first < lo:
            first = _next(first, u)
        last = _floor(hi, u)
        if first >= last:
            # no full u-aligned span inside; fall through to smaller units
            return cover(lo, hi, rest)
        out = cover(lo, first, rest)
        t = first
        while t < last:
            out.append(view_by_time_unit(name, t, u))
            t = _next(t, u)
        out.extend(cover(last, hi, rest))
        return out

    return cover(start, end, quantum)
